"""Pass 2d: collective-shape contracts — static mesh/operand math.

The sharded step programs move data through three collectives whose
operand shapes are fully determined by the config: the ``ppermute`` halo
exchange sends ``halo`` boundary rows per shard (:mod:`stmgcn_tpu.
parallel.halo`), the data-parallel loss ``psum``/gather sees per-device
batch slices, and branch model parallelism ``psum``s over equal branch
shards. A config whose extents don't divide its operands fails only at
runtime — on the mesh, possibly hours into a run (``strip_decompose``
raises at decomposition time; GSPMD raggedness surfaces as a sharding
error inside jit). This pass re-derives the shapes from the config alone
— no data build, no trace — and flags the mismatches up front for every
preset whose mesh spans more than one device.

For the halo plan the check estimates the grid (neighborhood) branch's
support bandwidth a priori: a rows x cols rook grid in row-major order
has adjacency bandwidth ``cols``, and a K-hop kernel (``chebyshev`` /
``random_walk_diffusion`` order K) reaches ``K * cols``; ``localpool``
is one hop. The transport/similarity branches' *exact* bandwidths are
data-dependent, but their nonzero **counts** are config math
(:func:`expected_branch_nnz`: the synthetic transport graph draws
Bernoulli edges at rate ``min(1, 10/n)``, the similarity graph keeps the
top decile of correlations), and a matrix with bandwidth ``b`` has at
most ``n * (2b + 1)`` nonzeros under *any* node ordering — so
:func:`branch_bandwidth_floor` is a sound worst-case lower bound on the
bandwidth any reordering can achieve. When ``region_strategy="banded"``
is *forced* (``"auto"`` routes dense branches away at decomposition
time), a floor above the halo budget means strip decomposition must
drop neighbors regardless of how the decomposer orders nodes — flagged
up front instead of surfacing as accuracy loss on the mesh.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = [
    "branch_bandwidth_floor",
    "check_collective_contracts",
    "expected_branch_nnz",
    "grid_bandwidth_estimate",
]

_K_HOP_KERNELS = ("chebyshev", "random_walk_diffusion")


def grid_bandwidth_estimate(kernel_type: str, K: int, cols: int) -> int:
    """A-priori support bandwidth of the rook-grid branch.

    Row-major rook adjacency has bandwidth ``cols`` (the vertical
    neighbor); a K-hop kernel's highest-order support reaches K such
    steps. ``localpool`` is the one-hop Kipf support.
    """
    hops = K if kernel_type in _K_HOP_KERNELS else 1
    return hops * cols


def expected_branch_nnz(kind: str, n: int) -> int:
    """Worst-case nonzero count of a data-dependent branch support.

    ``transport``: the synthetic builder draws directed Bernoulli edges
    at rate ``p = min(1, 10/n)`` and symmetrizes, so an (i, j) entry is
    present with probability ``<= 2p`` — worst case ``min(n*n, 20*n)``
    nonzeros. ``similarity``: the builder thresholds at the top decile
    of pairwise correlations, exactly ``ceil(0.1 * n*n)`` entries.
    """
    if kind == "transport":
        return min(n * n, 20 * n)
    if kind == "similarity":
        return -(-(n * n) // 10)
    raise ValueError(f"unknown data-dependent branch kind: {kind!r}")


def branch_bandwidth_floor(n: int, nnz: int) -> int:
    """Lower bound on achievable bandwidth for any ordering of an
    ``n x n`` support with ``nnz`` nonzeros.

    A matrix with bandwidth ``b`` has at most ``n * (2b + 1)`` nonzeros,
    so ``b >= (nnz/n - 1) / 2`` no matter how the decomposer permutes
    nodes — the a-priori bound the grid branch gets from geometry, the
    dense branches get from counting.
    """
    per_row = -(-nnz // n)  # ceil: the densest row is at least the mean
    return max(0, -(-(per_row - 1) // 2))


def _city_grids(cfg) -> List[Tuple[int, int]]:
    """Every city's (rows, cols) synthetic grid shape."""
    d = cfg.data
    if d.city_rows is not None:
        return [(r, r) for r in d.city_rows]
    cols = d.cols if d.cols is not None else d.rows
    return [(d.rows, cols)] * max(1, d.n_cities)


def check_collective_contracts(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate collective operand shapes against mesh extents.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset. Pure config math — safe without a JAX backend.
    """
    from stmgcn_tpu.config import PRESETS

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="collective-shape",
                path=f"<contract:collective:{name}>",
                line=0,
                message=message,
                severity=RULES["collective-shape"].severity,
            )
        )

    for name, cfg in configs:
        mesh = cfg.mesh
        if mesh.n_devices <= 1:
            continue

        if mesh.dp > 1 and cfg.train.batch_size % mesh.dp:
            emit(
                name,
                f"{name}: batch_size {cfg.train.batch_size} is not "
                f"divisible by dp={mesh.dp} — the data-parallel loss "
                "psum/gather would see ragged per-device batch shards",
            )

        if mesh.branch > 1 and cfg.model.m_graphs % mesh.branch:
            emit(
                name,
                f"{name}: m_graphs {cfg.model.m_graphs} is not divisible "
                f"by branch={mesh.branch} — the branch-sum psum needs "
                "equal branch shards on every device",
            )

        halo_active = (
            mesh.region > 1
            and mesh.region_strategy in ("banded", "auto")
            and not cfg.model.sparse
        )
        if not halo_active:
            continue
        for rows, cols in _city_grids(cfg):
            n = rows * cols
            padded = -(-n // mesh.region) * mesh.region
            n_local = padded // mesh.region
            budget = min(
                mesh.halo if mesh.halo is not None else n_local // 2, n_local
            )
            if mesh.halo is not None and mesh.halo > n_local:
                emit(
                    name,
                    f"{name}: mesh.halo {mesh.halo} exceeds the shard size "
                    f"{n_local} ({padded} padded nodes / region="
                    f"{mesh.region}) — the ppermute exchange operand "
                    "cannot hold more rows than the shard",
                )
            bw = grid_bandwidth_estimate(
                cfg.model.kernel_type, cfg.model.K, cols
            )
            if bw > n_local:
                emit(
                    name,
                    f"{name}: grid-branch support bandwidth ~{bw} "
                    f"({cfg.model.kernel_type} K={cfg.model.K} on a "
                    f"{rows}x{cols} grid) exceeds the shard size {n_local} "
                    "— no halo fits; shrink mesh.region or reorder nodes",
                )
            elif bw > budget and mesh.region_strategy == "banded":
                emit(
                    name,
                    f"{name}: region_strategy='banded' but the grid "
                    f"branch's support bandwidth ~{bw} exceeds the halo "
                    f"budget {budget} (shard size {n_local}) — "
                    "strip_decompose would drop boundary neighbors; use "
                    "'auto' or raise mesh.halo",
                )
            if mesh.region_strategy != "banded":
                continue
            # forced banded routes the data-dependent branches through
            # strip decomposition too — gate on their counting floor
            # (branch order: 0 grid, 1 transport, 2 similarity)
            present = []
            if cfg.model.m_graphs >= 2:
                present.append("transport")
            if cfg.model.m_graphs >= 3:
                present.append("similarity")
            for kind in present:
                floor = branch_bandwidth_floor(
                    n, expected_branch_nnz(kind, n)
                )
                if floor > budget:
                    emit(
                        name,
                        f"{name}: region_strategy='banded' but the {kind} "
                        f"branch's bandwidth floor {floor} (worst-case "
                        f"{expected_branch_nnz(kind, n)} nnz over {n} "
                        f"nodes; no ordering can do better) exceeds the "
                        f"halo budget {budget} — strip_decompose must "
                        "drop neighbors; use 'auto' or raise mesh.halo",
                    )
    return findings
