"""Pass 2g: SPMD contracts — compiled collectives vs declared manifests.

Every other contract pass reasons *a priori* (config math, abstract
traces). This one closes the loop the way ``pallas_check`` did for
Mosaic VMEM: it lowers the **real sharded train/serve step programs**
for each multi-device preset on the virtual CPU mesh (the same
``--xla_force_host_platform_device_count`` substrate ``dryrun_multichip``
and the 8-virtual-device tests use — no accelerator, no execution),
walks the post-partitioning HLO for collectives (:mod:`.hlo`), and diffs
what GSPMD actually emitted against the plan's declared
:class:`~stmgcn_tpu.parallel.manifest.CollectiveManifest`. Three rules:

- ``spmd-collective-manifest``: an observed collective with no matching
  declaration is implicit GSPMD resharding the plan never asked for
  (e.g. a full node-axis all-gather silently erasing the banded plan's
  N/(2·halo)x wire reduction); a *required* declaration with no observed
  op means the plan never engaged (e.g. banded routing fell back to
  dense without anyone noticing).
- ``spmd-wire-budget``: observed bytes-on-wire per program vs the
  rebaselined :data:`WIRE_BUDGETS` ceiling, plus two analytic models —
  every region halo ``collective-permute`` must fit the boundary-rows
  bound ``halo x B_local x M_local x F_cap x itemsize``, and the dp
  gradient-sync all-reduce total must fit ``2 x param_bytes`` slack.
  Budgets are maintained by ``stmgcn lint --rebaseline`` exactly like
  jaxpr primitive budgets.
- ``spmd-shard-footprint``: the ``resident-memory`` math extended from
  whole-array to **per-device** operand footprints (supports strip/shard
  + batch shard per device vs the per-core budget) for every
  multi-device preset — the rule extension ROADMAP item 3 asks for.

The probe programs shrink data/model dims (dryrun-style) so lowering
stays in CPU-compile seconds, but keep each preset's mesh axes and
routing decisions — the manifest's vocabulary (collective kind x mesh
axes) is shrink-invariant. Lowerings are cached per program: all three
rules and the lint-gate summary read one compile.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.hlo import CollectiveOp, collect_collectives
from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = [
    "PROGRAM_SPECS",
    "WIRE_BUDGETS",
    "analyze_program",
    "check_shard_footprints",
    "check_spmd_contracts",
    "declared_manifests",
    "estimate_shard_footprint",
    "rebaseline_wire",
    "spmd_summary",
]

#: static per-program wire ceilings (total collective output bytes in the
#: compiled module), measured x ~2 headroom, rounded up to the next KiB.
#: Single-line literal: ``stmgcn lint --rebaseline`` rewrites it in place
#: from fresh measurements (:func:`rebaseline_wire`).
WIRE_BUDGETS = {"multicity/train": 8192, "multicity/serve": 1024, "scaled/train": 60416, "scaled/serve": 27648, "branchpar/train": 6144, "branchpar/serve": 2048, "bandedbranch/train": 15360, "bandedbranch/serve": 4096}

#: probe program registry: name -> (preset, "train"|"serve", banded?).
#: Every preset whose mesh spans >1 device must appear here (coverage is
#: itself checked); ``banded`` marks programs whose routing must engage
#: the explicit halo plan, which flips the manifest's required ops.
PROGRAM_SPECS = {
    "multicity/train": ("multicity", "train", False),
    "multicity/serve": ("multicity", "serve", False),
    "scaled/train": ("scaled", "train", True),
    "scaled/serve": ("scaled", "serve", True),
    "branchpar/train": ("branchpar", "train", False),
    "branchpar/serve": ("branchpar", "serve", False),
    "bandedbranch/train": ("bandedbranch", "train", True),
    "bandedbranch/serve": ("bandedbranch", "serve", True),
}

_ITEMSIZE = 4  # probe programs run float32 (dryrun parity)
_PSUM_SLACK_BYTES = 4096  # loss/count scalars riding the dp sync


@dataclasses.dataclass
class ProgramReport:
    """One lowered probe program: compiled collectives + wire meta."""

    name: str
    ops: List[CollectiveOp]
    while_count: int
    mesh_shape: Tuple[int, ...]
    axis_names: Tuple[str, ...]
    #: analytic-model inputs: ``param_bytes``, and for banded programs
    #: ``halo``/``b_local``/``m_local``/``f_cap``
    meta: dict

    @property
    def total_bytes(self) -> int:
        return sum(op.out_bytes for op in self.ops)


def declared_manifests() -> Dict[str, "object"]:
    """Every probe program's declared manifest — pure config, no JAX.

    This is what ``dryrun_multichip`` persists into the ``MULTICHIP_r*``
    record so future on-chip runs can diff compiled reality against the
    same declarations this pass checks statically.
    """
    from stmgcn_tpu.config import preset
    from stmgcn_tpu.parallel.manifest import manifest_for_config

    return {
        name: manifest_for_config(preset(p), program=kind, banded=banded)
        for name, (p, kind, banded) in PROGRAM_SPECS.items()
    }


# ---------------------------------------------------------------------------
# probe program construction (cached; one lowering per program, shared by
# every rule and by the lint-gate summary)
# ---------------------------------------------------------------------------

_REPORT_CACHE: Optional[Dict[str, ProgramReport]] = None


def _band_adj(n: int, w: int, seed: int):
    """Symmetric adjacency with every edge within index distance ``w``."""
    import numpy as np

    rng = np.random.default_rng(seed)
    a = np.zeros((n, n), np.float32)
    for d in range(1, w + 1):
        band = (rng.random(n - d) < 0.7).astype(np.float32)
        a += np.diag(band, d) + np.diag(band, -d)
    return a


def _abstract_state(tree, mesh):
    """ShapeDtypeStructs with the state placement's shardings attached.

    Mirrors :meth:`MeshPlacement.put(kind="state")` — replicated except
    the vmapped ``branches`` subtree's leading axis over ``branch`` —
    without materializing a single parameter: the probe only lowers.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.tree_util import DictKey, tree_map_with_path

    has_branch = "branch" in mesh.shape

    def conv(path, leaf):
        in_branches = has_branch and any(
            isinstance(k, DictKey) and k.key == "branches" for k in path
        )
        spec = (
            P("branch", *([None] * (len(leaf.shape) - 1)))
            if in_branches and len(leaf.shape)
            else P()
        )
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, spec)
        )

    return tree_map_with_path(conv, tree)


def _tree_bytes(tree) -> int:
    import jax

    return sum(
        math.prod(l.shape) * l.dtype.itemsize for l in jax.tree.leaves(tree)
    )


def _lower_pair(
    base: str, mesh, placement, model, supports, x, y, mask, meta: dict
) -> Dict[str, ProgramReport]:
    """Lower ``{base}/train`` and ``{base}/serve`` from abstract params."""
    import jax
    import numpy as np

    from stmgcn_tpu.serving.engine import serve_bucket_fn
    from stmgcn_tpu.train import make_optimizer, make_step_fns

    sup_p = placement.put(supports, "supports")
    x_p = placement.put(np.asarray(x), "x")
    y_p = placement.put(np.asarray(y), "y")
    mask_p = placement.put(np.asarray(mask), "mask")
    fns = make_step_fns(model, make_optimizer(2e-3, 1e-4), "mse")
    params_s, opt_s = jax.eval_shape(fns.init, jax.random.key(0), sup_p, x_p)
    params_a = _abstract_state(params_s, mesh)
    opt_a = _abstract_state(opt_s, mesh)
    meta = dict(meta, param_bytes=_tree_bytes(params_s))

    shape = tuple(mesh.devices.shape)
    names = tuple(mesh.axis_names)
    out: Dict[str, ProgramReport] = {}

    txt = (
        fns.train_step.lower(params_a, opt_a, sup_p, x_p, y_p, mask_p)
        .compile()
        .as_text()
    )
    ops, loops = collect_collectives(txt, shape, names)
    out[f"{base}/train"] = ProgramReport(
        f"{base}/train", ops, loops, shape, names, meta
    )

    # bind the factory result first: serve_bucket_fn itself is never the
    # jitted callable, so it must not become a program-db jit root here
    serve_fwd = serve_bucket_fn(model)
    serve = jax.jit(serve_fwd)
    txt = serve.lower(params_a, sup_p, x_p).compile().as_text()
    ops, loops = collect_collectives(txt, shape, names)
    out[f"{base}/serve"] = ProgramReport(
        f"{base}/serve", ops, loops, shape, names, meta
    )
    return out


def _probe_dense(base: str, dp: int, branch: int, M: int) -> Dict[str, ProgramReport]:
    """Dense-GSPMD probe (dp and dp x branch plans): no region sharding,
    tiny synthetic operands — support values are irrelevant to the
    lowered communication structure."""
    import numpy as np

    from stmgcn_tpu.models import STMGCN
    from stmgcn_tpu.parallel import MeshPlacement, build_mesh

    rng = np.random.default_rng(0)
    N, B, T = 16, 2 * dp, 3
    mesh = build_mesh(dp=dp, region=1, branch=branch)
    placement = MeshPlacement(mesh)
    model = STMGCN(
        m_graphs=M, n_supports=2, seq_len=T, input_dim=1,
        lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8,
    )
    sup = rng.normal(size=(M, 2, N, N)).astype(np.float32) * 0.1
    x = rng.standard_normal((B, T, N, 1)).astype(np.float32)
    y = (rng.standard_normal((B, N, 1)) * 0.1).astype(np.float32)
    mask = np.ones(B, np.float32)
    return _lower_pair(base, mesh, placement, model, sup, x, y, mask, {})


def _probe_routed(base: str) -> Dict[str, ProgramReport]:
    """Banded probes through the *real* routing path: ``build_dataset``
    + ``route_supports`` + ``build_model``, dryrun-style shrinks.

    ``scaled``: 32x2 grid so the cheb-K2 grid branch fits the halo
    budget (bandwidth 4 <= n_local // 2 = 4) while the random transport/
    similarity branches rightly stay dense — the preset's mixed plan.
    ``bandedbranch``: banded city adjacencies stand in for the synthetic
    transport graph (which no ordering bands — see the preset docstring);
    with every branch within budget, routing produces the branch-stacked
    strips whose engaged composition the manifest declares.
    """
    import numpy as np

    from stmgcn_tpu.config import preset
    from stmgcn_tpu.experiment import build_dataset, build_model, route_supports
    from stmgcn_tpu.parallel import MeshPlacement, ShardSpec, build_mesh

    cfg = preset(base)
    cfg.model.lstm_hidden_dim = 8
    cfg.model.lstm_num_layers = 1
    cfg.model.gcn_hidden_dim = 8
    cfg.model.dtype = "float32"
    if base == "scaled":
        # 32x2 grid, cheb-K2: grid bandwidth K*cols = 4 <= n_local//2 = 4
        # (the 50x50/K=3 original routes the same way at preset scale)
        cfg.data.rows, cfg.data.cols = 32, 2
        cfg.data.n_timesteps = 24 * 7 + 64
        cfg.model.K = 2
        cfg.train.batch_size = 2
    else:  # bandedbranch
        cfg.data.rows = 4
        cfg.data.n_timesteps = 24 * 7 + 64
        cfg.train.batch_size = 4
        cfg.mesh.halo = 4
    mesh = build_mesh(
        dp=cfg.mesh.dp, region=cfg.mesh.region, branch=cfg.mesh.branch
    )
    placement = MeshPlacement(mesh)
    dataset = build_dataset(cfg)
    if base == "bandedbranch":
        n = dataset.n_nodes
        dataset.adjs = {"g0": _band_adj(n, 1, 1), "g1": _band_adj(n, 2, 2)}
    supports, modes = route_supports(cfg, dataset)
    if modes is None or "banded" not in modes:
        raise RuntimeError(
            f"spmd probe {base!r}: routing did not engage the banded plan "
            f"(modes={modes}) — the probe shrink no longer matches the "
            "router's bandwidth budget"
        )
    model = build_model(cfg, dataset.n_feats, modes, ShardSpec(mesh=mesh))
    batch = next(
        dataset.batches("train", cfg.train.batch_size, pad_last=True)
    )
    mask = (np.arange(len(batch)) < batch.n_real).astype(np.float32)
    banded = [s for s in (supports if isinstance(supports, tuple) else (supports,))
              if hasattr(s, "halo")]
    halo = max(s.halo for s in banded)
    m_local = max(1, cfg.model.m_graphs // cfg.mesh.branch)
    f_cap = (
        cfg.data.serial_len + cfg.data.daily_len + cfg.data.weekly_len
        + 2 * cfg.model.lstm_hidden_dim + cfg.model.gcn_hidden_dim
    )
    meta = {
        "halo": halo,
        "b_local": cfg.train.batch_size // cfg.mesh.dp,
        "m_local": m_local,
        "f_cap": f_cap,
    }
    return _lower_pair(
        base, mesh, placement, model, supports, batch.x, batch.y, mask, meta
    )


def _lower_programs() -> Dict[str, ProgramReport]:
    """All probe programs, lowered once per process and cached."""
    global _REPORT_CACHE
    if _REPORT_CACHE is not None:
        return _REPORT_CACHE
    import jax

    need = max(
        math.prod(_preset_mesh(p)) for p, _, _ in PROGRAM_SPECS.values()
    )
    if len(jax.devices()) < need:
        raise RuntimeError(
            f"spmd contract pass needs {need} devices to lower the probe "
            f"programs, found {len(jax.devices())} — call "
            "force_host_platform('cpu', n_devices=8) before any JAX use "
            "(stmgcn lint and tests/conftest.py do)"
        )
    reports: Dict[str, ProgramReport] = {}
    reports.update(_probe_dense("multicity", dp=8, branch=1, M=2))
    reports.update(_probe_routed("scaled"))
    reports.update(_probe_dense("branchpar", dp=2, branch=3, M=3))
    reports.update(_probe_routed("bandedbranch"))
    missing = set(PROGRAM_SPECS) - set(reports)
    if missing:
        raise RuntimeError(f"spmd probes built no program for {sorted(missing)}")
    _REPORT_CACHE = reports
    return reports


def _preset_mesh(preset_name: str) -> Tuple[int, ...]:
    from stmgcn_tpu.config import preset

    m = preset(preset_name).mesh
    return (m.dp, m.region, m.branch)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _emit(findings: List[Finding], rule: str, name: str, message: str) -> None:
    findings.append(
        Finding(
            rule=rule,
            path=f"<contract:spmd:{name}>",
            line=0,
            message=message,
            severity=RULES[rule].severity,
        )
    )


def analyze_program(
    name: str,
    hlo_text: str,
    manifest,
    mesh_shape: Iterable[int],
    axis_names: Iterable[str],
    meta: Optional[dict] = None,
    budget: Optional[int] = None,
) -> List[Finding]:
    """Manifest + wire findings for one compiled module (testable core).

    ``meta`` carries the analytic-model inputs (``halo``/``b_local``/
    ``m_local``/``f_cap`` for the halo bound, ``param_bytes`` for the dp
    psum bound); ``budget`` is the program's total-bytes ceiling. Either
    may be omitted to check manifest structure alone.
    """
    ops, while_count = collect_collectives(
        hlo_text, tuple(mesh_shape), tuple(axis_names)
    )
    rep = ProgramReport(
        name, ops, while_count, tuple(mesh_shape), tuple(axis_names),
        dict(meta or {}),
    )
    return _manifest_findings(rep, manifest) + _wire_findings(rep, budget)


def _manifest_findings(rep: ProgramReport, manifest) -> List[Finding]:
    findings: List[Finding] = []
    by_sig: Dict[Tuple[str, str], List[CollectiveOp]] = {}
    for op in rep.ops:
        by_sig.setdefault((op.kind, op.axes), []).append(op)
    for (kind, axes), ops in sorted(by_sig.items()):
        decl = manifest.lookup(kind, axes)
        if decl is None:
            names = ", ".join(f"%{o.name}" for o in ops[:3])
            findings_msg = (
                f"{rep.name}: compiled program contains {len(ops)} "
                f"undeclared {kind} over mesh axes '{axes}' ({names}"
                f"{', ...' if len(ops) > 3 else ''}, "
                f"{sum(o.out_bytes for o in ops):,} bytes) — implicit "
                "GSPMD resharding the plan never declared; fix the "
                "operand shardings, or declare it in the plan's "
                "CollectiveManifest fragment (parallel/manifest.py) if "
                "the movement is intended"
            )
            _emit(findings, "spmd-collective-manifest", rep.name, findings_msg)
            continue
        if decl.max_count is not None and len(ops) > decl.max_count:
            _emit(
                findings, "spmd-collective-manifest", rep.name,
                f"{rep.name}: {len(ops)} {kind} ops over '{axes}' exceed "
                f"the declared max_count {decl.max_count} — the program's "
                "communication structure drifted; re-derive the manifest "
                "or fix the regression",
            )
    for decl in manifest.decls:
        if decl.required and (decl.kind, decl.axes) not in by_sig:
            _emit(
                findings, "spmd-collective-manifest", rep.name,
                f"{rep.name}: declared {decl.kind} over '{decl.axes}' "
                f"({decl.reason or 'required by the plan'}) never appears "
                "in the compiled program — the plan did not engage "
                "(routing fell back, or the sharded operands were "
                "replicated before the op)",
            )
    return findings


def _wire_findings(
    rep: ProgramReport, budget: Optional[int]
) -> List[Finding]:
    findings: List[Finding] = []
    meta = rep.meta
    if budget is not None and rep.total_bytes > budget:
        _emit(
            findings, "spmd-wire-budget", rep.name,
            f"{rep.name}: compiled program moves {rep.total_bytes:,} "
            f"collective output bytes, over the budget {budget:,} "
            "(measured x ~2 headroom) — a real wire regression needs "
            "`stmgcn lint --rebaseline` to re-baseline deliberately",
        )
    if "halo" in meta:
        cap = (
            meta["halo"] * meta["b_local"] * meta["m_local"]
            * meta["f_cap"] * _ITEMSIZE
        )
        for op in rep.ops:
            if op.kind == "collective-permute" and op.out_bytes > cap:
                _emit(
                    findings, "spmd-wire-budget", rep.name,
                    f"{rep.name}: halo permute %{op.name} moves "
                    f"{op.out_bytes:,} bytes, over the boundary-rows bound "
                    f"{cap:,} (halo {meta['halo']} x B_local "
                    f"{meta['b_local']} x M_local {meta['m_local']} x "
                    f"F_cap {meta['f_cap']} x {_ITEMSIZE}) — the exchange "
                    "is moving more than boundary rows, which erases the "
                    "banded plan's N/(2·halo)x wire reduction",
                )
    if "param_bytes" in meta and any(
        op.kind == "all-reduce" and op.axes == "dp" for op in rep.ops
    ):
        dp_bytes = sum(
            op.out_bytes
            for op in rep.ops
            if op.kind == "all-reduce" and op.axes == "dp"
        )
        cap = 2 * meta["param_bytes"] + _PSUM_SLACK_BYTES
        if dp_bytes > cap:
            _emit(
                findings, "spmd-wire-budget", rep.name,
                f"{rep.name}: dp all-reduce traffic {dp_bytes:,} bytes "
                f"exceeds the gradient-psum model 2 x param_bytes "
                f"({meta['param_bytes']:,}) + {_PSUM_SLACK_BYTES} — "
                "something beyond gradients/loss is syncing over dp "
                "(likely an activation replicated the wrong way)",
            )
    return findings


# ---------------------------------------------------------------------------
# per-device footprint math (pure config, preset-scale — no lowering)
# ---------------------------------------------------------------------------


def estimate_shard_footprint(cfg) -> dict:
    """Per-device operand bytes for a config's sharded training step.

    The ``resident-memory`` arithmetic extended to mesh shards: supports
    (dense row-shards over ``region`` and graph-shards over ``branch``,
    or banded strips ``n_local x (n_local + 2·halo)`` when the halo plan
    is forced) plus one streamed batch's ``x``/``y`` shard. Data arrays
    are float32 regardless of compute dtype, as in ``resident_check``.
    Pure config math — nothing is built.
    """
    from stmgcn_tpu.data.windowing import WindowSpec

    d, mesh = cfg.data, cfg.mesh
    spec = WindowSpec(
        d.serial_len, d.daily_len, d.weekly_len, d.day_timesteps,
        horizon=d.horizon,
    )
    cols = d.cols if d.cols is not None else d.rows
    if d.city_rows is not None:
        city_nodes = [r * r for r in d.city_rows]
    else:
        city_nodes = [d.rows * cols] * max(1, d.n_cities)
    ksup = cfg.model.n_supports
    m_local = max(1, cfg.model.m_graphs // mesh.branch)
    region = mesh.region
    supports_bytes = 0
    for n in city_nodes:
        n_pad = -(-n // region) * region
        n_local = n_pad // region
        if mesh.region_strategy == "banded" and region > 1:
            halo = min(
                mesh.halo if mesh.halo is not None else n_local // 2, n_local
            )
            supports_bytes += (
                m_local * ksup * n_local * (n_local + 2 * halo) * _ITEMSIZE
            )
        else:
            # dense row shard (GSPMD / auto's worst case: auto may route
            # every branch dense)
            supports_bytes += m_local * ksup * n_local * n_pad * _ITEMSIZE
    n_max = max(city_nodes)
    n_pad = -(-n_max // region) * region
    b_local = -(-cfg.train.batch_size // mesh.dp)
    x_bytes = b_local * spec.seq_len * (n_pad // region) * _ITEMSIZE
    y_bytes = b_local * max(1, d.horizon) * (n_pad // region) * _ITEMSIZE
    total = supports_bytes + x_bytes + y_bytes
    return {
        "supports_bytes": supports_bytes,
        "batch_bytes": x_bytes + y_bytes,
        "total_bytes": total,
    }


def check_shard_footprints(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
    budget_bytes: Optional[int] = None,
) -> List[Finding]:
    """Per-device operand footprint vs the per-core budget, every
    multi-device preset. Single-device residency is ``resident-memory``'s
    domain; this rule owns the sharded extension."""
    from stmgcn_tpu.config import PRESETS
    from stmgcn_tpu.train.trainer import Trainer

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]
    if budget_bytes is None:
        budget_bytes = Trainer.RESIDENT_CAP_BYTES

    findings: List[Finding] = []
    for name, cfg in configs:
        if cfg.mesh.n_devices <= 1:
            continue
        est = estimate_shard_footprint(cfg)
        if est["total_bytes"] > budget_bytes:
            _emit(
                findings, "spmd-shard-footprint", name,
                f"{name}: per-device sharded operands need "
                f"{est['total_bytes']:,} bytes (supports "
                f"{est['supports_bytes']:,} + batch {est['batch_bytes']:,}) "
                f"but the per-core budget is {budget_bytes:,} — the step "
                "OOMs on every device at once; raise region/branch "
                "extents, shrink the batch, or band the supports",
            )
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def check_spmd_contracts(
    budgets: Optional[Dict[str, int]] = None,
) -> List[Finding]:
    """The full pass: coverage + manifest + wire for every probe program,
    then preset-scale footprints. One (cached) lowering per program."""
    from stmgcn_tpu.config import PRESETS

    budgets = WIRE_BUDGETS if budgets is None else budgets
    findings: List[Finding] = []
    covered = {p for p, _, _ in PROGRAM_SPECS.values()}
    for name, build in PRESETS.items():
        if build().mesh.n_devices > 1 and name not in covered:
            _emit(
                findings, "spmd-collective-manifest", name,
                f"{name}: multi-device preset has no spmd probe program — "
                "add it to analysis/spmd_check.PROGRAM_SPECS so its "
                "compiled collectives are checked against a manifest",
            )
    manifests = declared_manifests()
    for name, rep in _lower_programs().items():
        findings.extend(_manifest_findings(rep, manifests[name]))
        budget = budgets.get(name)
        if budget is None:
            _emit(
                findings, "spmd-wire-budget", name,
                f"{name}: no wire budget recorded — run "
                "`stmgcn lint --rebaseline` to measure and pin it",
            )
        findings.extend(_wire_findings(rep, budget))
    findings.extend(check_shard_footprints())
    return findings


def spmd_summary() -> dict:
    """The lint-gate section: programs checked / collectives observed /
    unsuppressed findings (0 programs or any finding fails the gate)."""
    reports = _lower_programs()
    findings = check_spmd_contracts()
    return {
        "programs": len(reports),
        "collectives": sum(len(r.ops) for r in reports.values()),
        "findings": sum(1 for f in findings if not f.suppressed),
    }


def measured_wire_totals() -> Dict[str, int]:
    return {n: r.total_bytes for n, r in _lower_programs().items()}


def rebaseline_wire(
    path: Optional[str] = None, headroom: float = 2.0
) -> dict:
    """Measure per-program wire totals and rewrite :data:`WIRE_BUDGETS`.

    Same contract as the jaxpr primitive rebaseline: measured x
    ``headroom`` (the standing ~2x policy), rounded up to the next KiB,
    rewritten into this module's single-line literal (``path`` overrides
    for tests) and updated in-process.
    """
    if headroom < 1.0:
        raise ValueError(f"headroom must be >= 1.0, got {headroom}")
    totals = measured_wire_totals()
    budgets = {
        name: max(1024, int(math.ceil(t * headroom / 1024.0) * 1024))
        for name, t in totals.items()
    }
    path = path or __file__
    with open(path) as f:
        src = f.read()
    literal = "{" + ", ".join(f'"{k}": {v}' for k, v in budgets.items()) + "}"
    new_src, n_subs = re.subn(
        r"WIRE_BUDGETS = \{[^}]*\}",
        "WIRE_BUDGETS = " + literal,
        src,
        count=1,
    )
    if n_subs != 1:
        raise RuntimeError(f"could not find WIRE_BUDGETS literal in {path}")
    with open(path, "w") as f:
        f.write(new_src)
    WIRE_BUDGETS.clear()
    WIRE_BUDGETS.update(budgets)
    return {"totals": totals, "budgets": budgets, "path": path}
