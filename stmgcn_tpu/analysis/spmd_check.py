"""Pass 2g: SPMD contracts — compiled collectives vs declared manifests.

Every other contract pass reasons *a priori* (config math, abstract
traces). This one closes the loop the way ``pallas_check`` did for
Mosaic VMEM: it lowers the **real composed train/serve programs** —
the fused superstep each preset's trainer actually dispatches
(:meth:`~stmgcn_tpu.train.trainer.Trainer.composed_program`, built by
:mod:`stmgcn_tpu.parallel.compose`) and the serving engines'
``serve_bucket_fn`` over the same model/operands — on the virtual CPU
mesh (the same ``--xla_force_host_platform_device_count`` substrate
``dryrun_multichip`` and the 8-virtual-device tests use — no
accelerator, no execution), walks the post-partitioning HLO for
collectives (:mod:`.hlo`), and diffs what GSPMD actually emitted against
the plan's declared
:class:`~stmgcn_tpu.parallel.manifest.CollectiveManifest`. Three rules:

- ``spmd-collective-manifest``: an observed collective with no matching
  declaration is implicit GSPMD resharding the plan never asked for
  (e.g. a full node-axis all-gather silently erasing the banded plan's
  N/(2·halo)x wire reduction); a *required* declaration with no observed
  op means the plan never engaged (e.g. banded routing fell back to
  dense without anyone noticing).
- ``spmd-wire-budget``: observed bytes-on-wire per program vs the
  rebaselined :data:`WIRE_BUDGETS` ceiling, plus two analytic models —
  every region halo ``collective-permute`` must fit the boundary-rows
  bound ``halo x B_local x M_local x F_cap x itemsize``, and the dp
  gradient-sync all-reduce total must fit ``2 x param_bytes`` slack.
  Budgets are maintained by ``stmgcn lint --rebaseline`` exactly like
  jaxpr primitive budgets.
- ``spmd-shard-footprint``: the ``resident-memory`` math extended from
  whole-array to **per-device** operand footprints (supports strip/shard
  + batch shard per device vs the per-core budget) for every
  multi-device preset — the rule extension ROADMAP item 3 asks for.

The composed trainers shrink data/model dims (dryrun-style,
:func:`stmgcn_tpu.parallel.compose.composed_config`) so lowering stays
in CPU-compile seconds, but keep each preset's mesh axes and routing
decisions — the manifest's vocabulary (collective kind x mesh axes) is
shrink-invariant. Crucially these are NOT standalone probe programs:
``scripts/lint_gate.sh`` executes one smoke superstep of the same
composed program and ``tests/test_multichip_exec.py`` pins its parity
against the single-device/per-step twin, so the certified program and
the executed program are one object by construction. Lowerings are
cached per program: all three rules and the lint-gate summary read one
compile.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.hlo import CollectiveOp, collect_collectives
from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = [
    "PROGRAM_SPECS",
    "WIRE_BUDGETS",
    "analyze_program",
    "check_shard_footprints",
    "check_spmd_contracts",
    "declared_manifests",
    "estimate_shard_footprint",
    "rebaseline_wire",
    "spmd_summary",
]

#: static per-program wire ceilings (total collective output bytes in the
#: compiled module), measured x ~2 headroom, rounded up to the next KiB.
#: Single-line literal: ``stmgcn lint --rebaseline`` rewrites it in place
#: from fresh measurements (:func:`rebaseline_wire`).
WIRE_BUDGETS = {"multicity/train": 16384, "multicity/serve": 1024, "scaled/train": 113664, "scaled/serve": 55296, "branchpar/train": 8192, "branchpar/serve": 2048, "bandedbranch/train": 15360, "bandedbranch/serve": 4096}

#: composed program registry: name -> (preset, "train"|"serve", banded?).
#: Every preset whose mesh spans >1 device must appear here (coverage is
#: itself checked); ``banded`` marks programs whose routing must engage
#: the explicit halo plan, which flips the manifest's required ops. The
#: preset names index :data:`stmgcn_tpu.parallel.compose.COMPOSED_PRESETS`.
PROGRAM_SPECS = {
    "multicity/train": ("multicity", "train", False),
    "multicity/serve": ("multicity", "serve", False),
    "scaled/train": ("scaled", "train", True),
    "scaled/serve": ("scaled", "serve", True),
    "branchpar/train": ("branchpar", "train", False),
    "branchpar/serve": ("branchpar", "serve", False),
    "bandedbranch/train": ("bandedbranch", "train", True),
    "bandedbranch/serve": ("bandedbranch", "serve", True),
}

_ITEMSIZE = 4  # composed programs run float32 (dryrun parity)
_PSUM_SLACK_BYTES = 4096  # loss/count scalars riding the dp sync


@dataclasses.dataclass
class ProgramReport:
    """One lowered composed program: compiled collectives + wire meta."""

    name: str
    ops: List[CollectiveOp]
    while_count: int
    mesh_shape: Tuple[int, ...]
    axis_names: Tuple[str, ...]
    #: analytic-model inputs: ``param_bytes``, and for banded programs
    #: ``halo``/``b_local``/``m_local``/``f_cap``
    meta: dict

    @property
    def total_bytes(self) -> int:
        return sum(op.out_bytes for op in self.ops)


def declared_manifests() -> Dict[str, "object"]:
    """Every composed program's declared manifest — pure config, no JAX.

    This is what ``dryrun_multichip`` persists into the ``MULTICHIP_r*``
    record so future on-chip runs can diff compiled reality against the
    same declarations this pass checks statically.
    """
    from stmgcn_tpu.config import preset
    from stmgcn_tpu.parallel.manifest import manifest_for_config

    return {
        name: manifest_for_config(preset(p), program=kind, banded=banded)
        for name, (p, kind, banded) in PROGRAM_SPECS.items()
    }


# ---------------------------------------------------------------------------
# composed program lowering (cached; one lowering per program, shared by
# every rule and by the lint-gate summary)
# ---------------------------------------------------------------------------

_REPORT_CACHE: Optional[Dict[str, ProgramReport]] = None


def _tree_bytes(tree) -> int:
    import jax

    return sum(
        math.prod(l.shape) * l.dtype.itemsize for l in jax.tree.leaves(tree)
    )


def _composed_pair(base: str) -> Dict[str, ProgramReport]:
    """Lower ``{base}/train`` and ``{base}/serve`` from the preset's
    composed trainer (:mod:`stmgcn_tpu.parallel.compose`).

    The train program is the fused superstep
    :meth:`~stmgcn_tpu.train.trainer.Trainer.composed_program` returns —
    the very jitted callable the trainer's epochs dispatch, with its real
    placed operand tuple. The serve program is the serving engines'
    ``serve_bucket_fn`` over the same model/params/supports, fed a window
    gathered from the resident series (so its batch/node shardings are
    the trainer's, not a probe's).
    """
    import jax

    from stmgcn_tpu.parallel.compose import (
        banded_meta, composed_config, composed_trainer,
    )
    from stmgcn_tpu.serving.engine import serve_bucket_fn

    cfg = composed_config(base)
    trainer = composed_trainer(base)
    pname, fn, args = trainer.composed_program()
    meta = dict(
        banded_meta(trainer, cfg),
        param_bytes=_tree_bytes(trainer.params),
        program=pname,
    )
    mesh = trainer.placement.mesh
    shape = tuple(mesh.devices.shape)
    names = tuple(mesh.axis_names)
    out: Dict[str, ProgramReport] = {}

    txt = fn.lower(*args).compile().as_text()
    ops, loops = collect_collectives(txt, shape, names)
    out[f"{base}/train"] = ProgramReport(
        f"{base}/train", ops, loops, shape, names, meta
    )

    batch = next(trainer.dataset.batches(
        "train", trainer.batch_size, pad_last=True, with_arrays=False,
    ))
    x, _, _ = trainer._place_batch(batch, "train")
    # bind the factory result first: serve_bucket_fn itself is never the
    # jitted callable, so it must not become a program-db jit root here
    serve_fwd = serve_bucket_fn(trainer.model)
    serve = jax.jit(serve_fwd)
    txt = (
        serve.lower(trainer.params, trainer._supports_for(batch), x)
        .compile()
        .as_text()
    )
    ops, loops = collect_collectives(txt, shape, names)
    out[f"{base}/serve"] = ProgramReport(
        f"{base}/serve", ops, loops, shape, names, dict(meta, program="serve_bucket")
    )
    return out


def _lower_programs() -> Dict[str, ProgramReport]:
    """All composed programs, lowered once per process and cached."""
    global _REPORT_CACHE
    if _REPORT_CACHE is not None:
        return _REPORT_CACHE
    import jax

    need = max(
        math.prod(_preset_mesh(p)) for p, _, _ in PROGRAM_SPECS.values()
    )
    if len(jax.devices()) < need:
        raise RuntimeError(
            f"spmd contract pass needs {need} devices to lower the "
            f"composed programs, found {len(jax.devices())} — call "
            "force_host_platform('cpu', n_devices=8) before any JAX use "
            "(stmgcn lint and tests/conftest.py do)"
        )
    reports: Dict[str, ProgramReport] = {}
    for preset_name in dict.fromkeys(p for p, _, _ in PROGRAM_SPECS.values()):
        reports.update(_composed_pair(preset_name))
    missing = set(PROGRAM_SPECS) - set(reports)
    if missing:
        raise RuntimeError(
            f"composed lowering built no program for {sorted(missing)}"
        )
    _REPORT_CACHE = reports
    return reports


def _preset_mesh(preset_name: str) -> Tuple[int, ...]:
    from stmgcn_tpu.config import preset

    m = preset(preset_name).mesh
    return (m.dp, m.region, m.branch)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _emit(findings: List[Finding], rule: str, name: str, message: str) -> None:
    findings.append(
        Finding(
            rule=rule,
            path=f"<contract:spmd:{name}>",
            line=0,
            message=message,
            severity=RULES[rule].severity,
        )
    )


def analyze_program(
    name: str,
    hlo_text: str,
    manifest,
    mesh_shape: Iterable[int],
    axis_names: Iterable[str],
    meta: Optional[dict] = None,
    budget: Optional[int] = None,
) -> List[Finding]:
    """Manifest + wire findings for one compiled module (testable core).

    ``meta`` carries the analytic-model inputs (``halo``/``b_local``/
    ``m_local``/``f_cap`` for the halo bound, ``param_bytes`` for the dp
    psum bound); ``budget`` is the program's total-bytes ceiling. Either
    may be omitted to check manifest structure alone.
    """
    ops, while_count = collect_collectives(
        hlo_text, tuple(mesh_shape), tuple(axis_names)
    )
    rep = ProgramReport(
        name, ops, while_count, tuple(mesh_shape), tuple(axis_names),
        dict(meta or {}),
    )
    return _manifest_findings(rep, manifest) + _wire_findings(rep, budget)


def _manifest_findings(rep: ProgramReport, manifest) -> List[Finding]:
    findings: List[Finding] = []
    by_sig: Dict[Tuple[str, str], List[CollectiveOp]] = {}
    for op in rep.ops:
        by_sig.setdefault((op.kind, op.axes), []).append(op)
    for (kind, axes), ops in sorted(by_sig.items()):
        decl = manifest.lookup(kind, axes)
        if decl is None:
            names = ", ".join(f"%{o.name}" for o in ops[:3])
            findings_msg = (
                f"{rep.name}: compiled program contains {len(ops)} "
                f"undeclared {kind} over mesh axes '{axes}' ({names}"
                f"{', ...' if len(ops) > 3 else ''}, "
                f"{sum(o.out_bytes for o in ops):,} bytes) — implicit "
                "GSPMD resharding the plan never declared; fix the "
                "operand shardings, or declare it in the plan's "
                "CollectiveManifest fragment (parallel/manifest.py) if "
                "the movement is intended"
            )
            _emit(findings, "spmd-collective-manifest", rep.name, findings_msg)
            continue
        if decl.max_count is not None and len(ops) > decl.max_count:
            _emit(
                findings, "spmd-collective-manifest", rep.name,
                f"{rep.name}: {len(ops)} {kind} ops over '{axes}' exceed "
                f"the declared max_count {decl.max_count} — the program's "
                "communication structure drifted; re-derive the manifest "
                "or fix the regression",
            )
    for decl in manifest.decls:
        if decl.required and (decl.kind, decl.axes) not in by_sig:
            _emit(
                findings, "spmd-collective-manifest", rep.name,
                f"{rep.name}: declared {decl.kind} over '{decl.axes}' "
                f"({decl.reason or 'required by the plan'}) never appears "
                "in the compiled program — the plan did not engage "
                "(routing fell back, or the sharded operands were "
                "replicated before the op)",
            )
    return findings


def _wire_findings(
    rep: ProgramReport, budget: Optional[int]
) -> List[Finding]:
    findings: List[Finding] = []
    meta = rep.meta
    if budget is not None and rep.total_bytes > budget:
        _emit(
            findings, "spmd-wire-budget", rep.name,
            f"{rep.name}: compiled program moves {rep.total_bytes:,} "
            f"collective output bytes, over the budget {budget:,} "
            "(measured x ~2 headroom) — a real wire regression needs "
            "`stmgcn lint --rebaseline` to re-baseline deliberately",
        )
    if "halo" in meta:
        cap = (
            meta["halo"] * meta["b_local"] * meta["m_local"]
            * meta["f_cap"] * _ITEMSIZE
        )
        for op in rep.ops:
            if op.kind == "collective-permute" and op.out_bytes > cap:
                _emit(
                    findings, "spmd-wire-budget", rep.name,
                    f"{rep.name}: halo permute %{op.name} moves "
                    f"{op.out_bytes:,} bytes, over the boundary-rows bound "
                    f"{cap:,} (halo {meta['halo']} x B_local "
                    f"{meta['b_local']} x M_local {meta['m_local']} x "
                    f"F_cap {meta['f_cap']} x {_ITEMSIZE}) — the exchange "
                    "is moving more than boundary rows, which erases the "
                    "banded plan's N/(2·halo)x wire reduction",
                )
    if "param_bytes" in meta and any(
        op.kind == "all-reduce" and op.axes == "dp" for op in rep.ops
    ):
        dp_bytes = sum(
            op.out_bytes
            for op in rep.ops
            if op.kind == "all-reduce" and op.axes == "dp"
        )
        cap = 2 * meta["param_bytes"] + _PSUM_SLACK_BYTES
        if dp_bytes > cap:
            _emit(
                findings, "spmd-wire-budget", rep.name,
                f"{rep.name}: dp all-reduce traffic {dp_bytes:,} bytes "
                f"exceeds the gradient-psum model 2 x param_bytes "
                f"({meta['param_bytes']:,}) + {_PSUM_SLACK_BYTES} — "
                "something beyond gradients/loss is syncing over dp "
                "(likely an activation replicated the wrong way)",
            )
    return findings


# ---------------------------------------------------------------------------
# per-device footprint math (pure config, preset-scale — no lowering)
# ---------------------------------------------------------------------------


def estimate_shard_footprint(cfg) -> dict:
    """Per-device operand bytes for a config's sharded training step.

    The ``resident-memory`` arithmetic extended to mesh shards: supports
    (dense row-shards over ``region`` and graph-shards over ``branch``,
    or banded strips ``n_local x (n_local + 2·halo)`` when the halo plan
    is forced) plus one streamed batch's ``x``/``y`` shard. Data arrays
    are float32 regardless of compute dtype, as in ``resident_check``.
    Pure config math — nothing is built.
    """
    from stmgcn_tpu.data.windowing import WindowSpec

    d, mesh = cfg.data, cfg.mesh
    spec = WindowSpec(
        d.serial_len, d.daily_len, d.weekly_len, d.day_timesteps,
        horizon=d.horizon,
    )
    cols = d.cols if d.cols is not None else d.rows
    if d.city_rows is not None:
        city_nodes = [r * r for r in d.city_rows]
    else:
        city_nodes = [d.rows * cols] * max(1, d.n_cities)
    ksup = cfg.model.n_supports
    m_local = max(1, cfg.model.m_graphs // mesh.branch)
    region = mesh.region
    supports_bytes = 0
    for n in city_nodes:
        n_pad = -(-n // region) * region
        n_local = n_pad // region
        if mesh.region_strategy == "banded" and region > 1:
            halo = min(
                mesh.halo if mesh.halo is not None else n_local // 2, n_local
            )
            supports_bytes += (
                m_local * ksup * n_local * (n_local + 2 * halo) * _ITEMSIZE
            )
        else:
            # dense row shard (GSPMD / auto's worst case: auto may route
            # every branch dense)
            supports_bytes += m_local * ksup * n_local * n_pad * _ITEMSIZE
    n_max = max(city_nodes)
    n_pad = -(-n_max // region) * region
    b_local = -(-cfg.train.batch_size // mesh.dp)
    x_bytes = b_local * spec.seq_len * (n_pad // region) * _ITEMSIZE
    y_bytes = b_local * max(1, d.horizon) * (n_pad // region) * _ITEMSIZE
    total = supports_bytes + x_bytes + y_bytes
    return {
        "supports_bytes": supports_bytes,
        "batch_bytes": x_bytes + y_bytes,
        "total_bytes": total,
    }


def check_shard_footprints(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
    budget_bytes: Optional[int] = None,
) -> List[Finding]:
    """Per-device operand footprint vs the per-core budget, every
    multi-device preset. Single-device residency is ``resident-memory``'s
    domain; this rule owns the sharded extension."""
    from stmgcn_tpu.config import PRESETS
    from stmgcn_tpu.train.trainer import Trainer

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]
    if budget_bytes is None:
        budget_bytes = Trainer.RESIDENT_CAP_BYTES

    findings: List[Finding] = []
    for name, cfg in configs:
        if cfg.mesh.n_devices <= 1:
            continue
        est = estimate_shard_footprint(cfg)
        if est["total_bytes"] > budget_bytes:
            _emit(
                findings, "spmd-shard-footprint", name,
                f"{name}: per-device sharded operands need "
                f"{est['total_bytes']:,} bytes (supports "
                f"{est['supports_bytes']:,} + batch {est['batch_bytes']:,}) "
                f"but the per-core budget is {budget_bytes:,} — the step "
                "OOMs on every device at once; raise region/branch "
                "extents, shrink the batch, or band the supports",
            )
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def check_spmd_contracts(
    budgets: Optional[Dict[str, int]] = None,
) -> List[Finding]:
    """The full pass: coverage + manifest + wire for every composed
    program, then preset-scale footprints. One (cached) lowering per
    program."""
    from stmgcn_tpu.config import PRESETS

    budgets = WIRE_BUDGETS if budgets is None else budgets
    findings: List[Finding] = []
    covered = {p for p, _, _ in PROGRAM_SPECS.values()}
    for name, build in PRESETS.items():
        if build().mesh.n_devices > 1 and name not in covered:
            _emit(
                findings, "spmd-collective-manifest", name,
                f"{name}: multi-device preset has no composed spmd "
                "program — add it to analysis/spmd_check.PROGRAM_SPECS "
                "(and parallel/compose.py) so its compiled collectives "
                "are checked against a manifest",
            )
    manifests = declared_manifests()
    for name, rep in _lower_programs().items():
        findings.extend(_manifest_findings(rep, manifests[name]))
        budget = budgets.get(name)
        if budget is None:
            _emit(
                findings, "spmd-wire-budget", name,
                f"{name}: no wire budget recorded — run "
                "`stmgcn lint --rebaseline` to measure and pin it",
            )
        findings.extend(_wire_findings(rep, budget))
    findings.extend(check_shard_footprints())
    return findings


def spmd_summary() -> dict:
    """The lint-gate section: programs checked / collectives observed /
    unsuppressed findings (0 programs or any finding fails the gate)."""
    reports = _lower_programs()
    findings = check_spmd_contracts()
    return {
        "programs": len(reports),
        "collectives": sum(len(r.ops) for r in reports.values()),
        "findings": sum(1 for f in findings if not f.suppressed),
    }


def measured_wire_totals() -> Dict[str, int]:
    return {n: r.total_bytes for n, r in _lower_programs().items()}


def rebaseline_wire(
    path: Optional[str] = None, headroom: float = 2.0
) -> dict:
    """Measure per-program wire totals and rewrite :data:`WIRE_BUDGETS`.

    Same contract as the jaxpr primitive rebaseline: measured x
    ``headroom`` (the standing ~2x policy), rounded up to the next KiB,
    rewritten into this module's single-line literal (``path`` overrides
    for tests) and updated in-process.
    """
    if headroom < 1.0:
        raise ValueError(f"headroom must be >= 1.0, got {headroom}")
    totals = measured_wire_totals()
    budgets = {
        name: max(1024, int(math.ceil(t * headroom / 1024.0) * 1024))
        for name, t in totals.items()
    }
    path = path or __file__
    with open(path) as f:
        src = f.read()
    literal = "{" + ", ".join(f'"{k}": {v}' for k, v in budgets.items()) + "}"
    new_src, n_subs = re.subn(
        r"WIRE_BUDGETS = \{[^}]*\}",
        "WIRE_BUDGETS = " + literal,
        src,
        count=1,
    )
    if n_subs != 1:
        raise RuntimeError(f"could not find WIRE_BUDGETS literal in {path}")
    with open(path, "w") as f:
        f.write(new_src)
    WIRE_BUDGETS.clear()
    WIRE_BUDGETS.update(budgets)
    return {"totals": totals, "budgets": budgets, "path": path}
