"""Pass 2k: serving-federation contracts — tier topology config math.

A federation misconfiguration does not fail a request, it degrades a
tier: more replicas than cities leaves paid-for engines permanently
idle behind the hash ring, too few virtual nodes makes the ring's
imbalance exceed the bound the capacity plan assumed, a global overload
budget below a single replica's local bound turns the *tier* limiter
into the binding constraint (every replica sheds on the shared budget
before its own queue fills — the local SLO math goes dead), and a
handover window longer than the drain window means a re-shard can
out-wait the drain that triggered it. The per-config arithmetic is
``FederationConfig.violations()``; this pass evaluates it per preset
with the cross-cutting inputs wired in: the sibling
:class:`~stmgcn_tpu.config.ServingConfig` for the budget cross-check
and the data config's city count for the topology check. Pure config
math — no JAX, no engines.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_federation_config"]


def check_federation_config(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate every preset's federation topology knobs.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset. One finding per violation string.
    """
    from stmgcn_tpu.config import PRESETS

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="federation-config",
                path=f"<contract:federation:{name}>",
                line=0,
                message=message,
                severity=RULES["federation-config"].severity,
            )
        )

    for name, cfg in configs:
        fed = getattr(cfg, "federation", None)
        if fed is None:
            continue
        data = getattr(cfg, "data", None)
        n_cities = None if data is None else getattr(data, "n_cities", None)
        for violation in fed.violations(
            serving=getattr(cfg, "serving", None),
            n_cities=n_cities,
        ):
            emit(name, f"{name}: {violation}")
    return findings
