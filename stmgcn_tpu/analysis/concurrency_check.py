"""Pass 1b: static concurrency analysis over the program database.

The serving/observability stack is genuinely threaded — a two-condvar
micro-batcher, a checkpoint-watcher thread, an async checkpoint writer,
and a process-wide metrics registry all rely on hand-written lock
discipline that only dynamic tests exercise. This pass makes that
discipline statically checkable, using :class:`~.program_db.ProgramDB`'s
class model (lock/condvar/event/thread/queue fields recognized from
their constructors, plus type-informed dispatch) as ground truth. Four
rules, all repo-wide:

- ``unguarded-attr`` — guarded-by inference. An attribute written under
  ``with self._lock`` in at least one method and read or written
  lock-free elsewhere in the same class is a data race; the finding
  carries the cross-method chain (guarding writer -> lock-free access).
  Lock context propagates through private (``_``-prefixed) helper
  methods that are *only* called with the lock held (fixpoint
  intersection over intra-class call sites), so ``self._helper()``
  under the lock doesn't produce false positives inside the helper.
- ``lock-order-cycle`` — a global lock-acquisition-order graph across
  modules: an edge ``A -> B`` whenever ``B`` can be acquired while
  ``A`` is held, including through resolved cross-class calls
  (``self._stats.record(...)`` under the batcher lock reaching the
  registry lock). Any cycle is a potential deadlock and an error.
- ``condvar-discipline`` — ``Condition.wait()`` outside a ``while``
  predicate loop (spurious wakeup / missed-notify hazard),
  ``wait``/``notify`` without the condvar's owning lock held.
- ``thread-lifecycle`` — a non-daemon ``Thread`` started without a
  reachable ``join()``/``cancel()`` path (class fields and function
  locals both), and any blocking call (``queue.get/put``,
  ``time.sleep``, ``Thread.join``, ``Event.wait``, device sync) made
  while holding a lock. ``Condition.wait()`` is exempt for its *owning*
  lock — which it releases — but flagged when any other lock is held
  across it.

Zero-false-positive contract: everything above fires only on evidence
the class model can prove — unknown receiver types, non-constant
``daemon=`` flags, and threads that escape their function are skipped,
never guessed. Suppression is the standard ``# stmgcn: ignore[rule-id]``
on the *reported* line (for cross-method findings: the offending access,
not the guarding writer).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from stmgcn_tpu.analysis.lint import _suppressions
from stmgcn_tpu.analysis.program_db import (
    ClassInfo,
    ModuleEntry,
    ProgramDB,
    _dotted_expr,
    _self_attr,
)
from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_concurrency"]

#: absolute dotted calls that block the calling thread
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep()",
    "jax.block_until_ready": "jax.block_until_ready() device sync",
    "jax.device_get": "jax.device_get() device readback",
}

#: method calls that mutate their receiver in place — a write for
#: guarded-by purposes (``self._pending.append(...)``)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "remove", "discard", "add", "clear", "update",
    "setdefault", "sort", "reverse",
}


@dataclasses.dataclass
class _Access:
    attr: str
    write: bool
    line: int
    col: int
    held: Tuple[str, ...]
    method: str


@dataclasses.dataclass
class _Acquire:
    lock: str
    held: Tuple[str, ...]
    line: int
    col: int
    method: str


@dataclasses.dataclass
class _CallSite:
    owner: str  # "module:Class" of the callee
    method: str
    held: Tuple[str, ...]
    line: int
    col: int
    from_method: str


@dataclasses.dataclass
class _CondOp:
    field: str
    op: str  # "wait" | "notify"
    in_while: bool
    held: Tuple[str, ...]
    line: int
    col: int
    method: str


@dataclasses.dataclass
class _Blocking:
    what: str
    held: Tuple[str, ...]
    line: int
    col: int
    method: str


@dataclasses.dataclass
class _ClassFacts:
    ci: ClassInfo
    entry: ModuleEntry
    accesses: List[_Access] = dataclasses.field(default_factory=list)
    acquires: List[_Acquire] = dataclasses.field(default_factory=list)
    calls: List[_CallSite] = dataclasses.field(default_factory=list)
    cond_ops: List[_CondOp] = dataclasses.field(default_factory=list)
    blocking: List[_Blocking] = dataclasses.field(default_factory=list)
    #: thread field -> (line, col, method) of its .start()
    starts: Dict[str, Tuple[int, int, str]] = dataclasses.field(
        default_factory=dict
    )
    joins: Set[str] = dataclasses.field(default_factory=set)
    #: method -> locks guaranteed held on entry (call-site fixpoint)
    inherited: Dict[str, frozenset] = dataclasses.field(default_factory=dict)

    def lock_id(self, field: str) -> str:
        """Normalized lock identity: condvars map to their owning lock."""
        owner = field
        if field in self.ci.condvars:
            owner = self.ci.condvars[field] or field
        return f"{self.ci.qualname}.{owner}"

    def held_for(self, method: str, held: Tuple[str, ...]) -> frozenset:
        return frozenset(held) | self.inherited.get(method, frozenset())


class _MethodWalker:
    """One method's sweep: attribute accesses, lock acquisitions, calls,
    condvar ops, and blocking calls — each tagged with the syntactic
    with-lock context it happens under. Nested defs/lambdas run later,
    so their bodies are walked with an *empty* held set."""

    def __init__(
        self, db: ProgramDB, facts: _ClassFacts, method: str, fn_node
    ):
        self.db = db
        self.facts = facts
        self.method = method
        self.fn_node = fn_node
        self.held: List[str] = []
        self.while_depth = 0

    # -- recording helpers -------------------------------------------------
    def _tagged(self) -> Tuple[str, ...]:
        return tuple(self.held)

    def _access(self, attr: str, write: bool, node: ast.AST) -> None:
        ci = self.facts.ci
        if attr in ci.sync_fields or attr not in ci.attrs:
            return
        self.facts.accesses.append(
            _Access(
                attr=attr, write=write, line=node.lineno,
                col=node.col_offset + 1, held=self._tagged(),
                method=self.method,
            )
        )

    # -- the walk ----------------------------------------------------------
    def walk(self, node: ast.AST) -> None:
        handler = getattr(self, f"_walk_{type(node).__name__}", None)
        if handler is not None:
            handler(node)
        else:
            for child in ast.iter_child_nodes(node):
                self.walk(child)

    def walk_body(self) -> None:
        for stmt in self.fn_node.body:
            self.walk(stmt)

    def _walk_With(self, node) -> None:
        acquired = 0
        for item in node.items:
            field = _self_attr(item.context_expr)
            ci = self.facts.ci
            if field is not None and (
                field in ci.locks or field in ci.condvars
            ):
                lid = self.facts.lock_id(field)
                self.facts.acquires.append(
                    _Acquire(
                        lock=lid, held=self._tagged(),
                        line=item.context_expr.lineno,
                        col=item.context_expr.col_offset + 1,
                        method=self.method,
                    )
                )
                self.held.append(lid)
                acquired += 1
            else:
                self.walk(item.context_expr)
        for stmt in node.body:
            self.walk(stmt)
        for _ in range(acquired):
            self.held.pop()

    _walk_AsyncWith = _walk_With

    def _walk_While(self, node: ast.While) -> None:
        self.walk(node.test)
        self.while_depth += 1
        for stmt in node.body:
            self.walk(stmt)
        self.while_depth -= 1
        for stmt in node.orelse:
            self.walk(stmt)

    def _nested_def(self, node) -> None:
        # runs later, on some other stack: no lock is held at entry
        saved_held, saved_while = self.held, self.while_depth
        self.held, self.while_depth = [], 0
        for child in ast.iter_child_nodes(node):
            self.walk(child)
        self.held, self.while_depth = saved_held, saved_while

    _walk_FunctionDef = _nested_def
    _walk_AsyncFunctionDef = _nested_def
    _walk_Lambda = _nested_def

    def _write_target(self, target: ast.AST) -> None:
        attr = _self_attr(target)
        if attr is not None:
            self._access(attr, True, target)
            return
        if isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
            if attr is not None:
                self._access(attr, True, target)
            else:
                self.walk(target.value)
            self.walk(target.slice)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._write_target(elt)
        elif isinstance(target, ast.Starred):
            self._write_target(target.value)
        elif isinstance(target, ast.Attribute):
            self.walk(target.value)

    def _walk_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._write_target(t)
        self.walk(node.value)

    def _walk_AugAssign(self, node: ast.AugAssign) -> None:
        self._write_target(node.target)
        self.walk(node.value)

    def _walk_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._write_target(node.target)
            self.walk(node.value)

    def _walk_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._write_target(t)

    def _walk_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is None:
            self.walk(node.value)
            return
        ci = self.facts.ci
        if attr in ci.methods:
            # property read / bound-method reference: executes the method
            self.facts.calls.append(
                _CallSite(
                    owner=ci.qualname, method=attr, held=self._tagged(),
                    line=node.lineno, col=node.col_offset + 1,
                    from_method=self.method,
                )
            )
            return
        self._access(attr, False, node)

    def _walk_Call(self, node: ast.Call) -> None:
        self._handle_call(node)
        for arg in node.args:
            self.walk(arg)
        for kw in node.keywords:
            self.walk(kw.value)

    def _handle_call(self, node: ast.Call) -> None:
        func = node.func
        facts, ci, entry = self.facts, self.facts.ci, self.facts.entry
        held = self._tagged()
        line, col = func.lineno, func.col_offset + 1
        if not isinstance(func, ast.Attribute):
            # plain name call: blocking only via an imported binding
            if isinstance(func, ast.Name):
                what = _BLOCKING_CALLS.get(entry.imports.get(func.id, ""))
                if what is not None:
                    facts.blocking.append(
                        _Blocking(what, held, line, col, self.method)
                    )
            else:
                self.walk(func)
            return

        m = func.attr
        recv_field = _self_attr(func.value)
        if recv_field is not None:
            if recv_field in ci.condvars:
                if m in ("wait", "wait_for"):
                    facts.cond_ops.append(
                        _CondOp(
                            field=recv_field, op="wait",
                            in_while=self.while_depth > 0 or m == "wait_for",
                            held=held, line=line, col=col,
                            method=self.method,
                        )
                    )
                elif m in ("notify", "notify_all"):
                    facts.cond_ops.append(
                        _CondOp(
                            field=recv_field, op="notify", in_while=False,
                            held=held, line=line, col=col,
                            method=self.method,
                        )
                    )
            elif recv_field in ci.locks:
                if m == "acquire":
                    facts.acquires.append(
                        _Acquire(
                            lock=facts.lock_id(recv_field), held=held,
                            line=line, col=col, method=self.method,
                        )
                    )
            elif recv_field in ci.threads:
                if m == "start":
                    facts.starts.setdefault(
                        recv_field, (line, col, self.method)
                    )
                elif m in ("join", "cancel"):
                    facts.joins.add(recv_field)
                    if m == "join":
                        facts.blocking.append(
                            _Blocking(
                                "Thread.join()", held, line, col, self.method
                            )
                        )
            elif recv_field in ci.events:
                if m == "wait":
                    facts.blocking.append(
                        _Blocking(
                            "Event.wait()", held, line, col, self.method
                        )
                    )
            elif recv_field in ci.queues:
                if m in ("get", "put", "join"):
                    facts.blocking.append(
                        _Blocking(
                            f"queue .{m}()", held, line, col, self.method
                        )
                    )
            else:
                # a plain attribute receiver: a read — or a write when
                # the call mutates the receiver in place — plus a
                # resolved cross-class call when the attr's class is known
                self._access(recv_field, m in _MUTATORS, func.value)
                t = ci.attr_types.get(recv_field)
                if t is not None:
                    target_ci = self.db.classes.get(t)
                    if target_ci is not None and m in target_ci.methods:
                        facts.calls.append(
                            _CallSite(
                                owner=t, method=m, held=held, line=line,
                                col=col, from_method=self.method,
                            )
                        )
            return

        if isinstance(func.value, ast.Name) and func.value.id == "self":
            if m in ci.methods:
                facts.calls.append(
                    _CallSite(
                        owner=ci.qualname, method=m, held=held, line=line,
                        col=col, from_method=self.method,
                    )
                )
            return

        # non-self receiver: device sync by method name, module-level
        # blocking calls by dotted path, typed resolution for the rest
        if m == "block_until_ready":
            facts.blocking.append(
                _Blocking(
                    ".block_until_ready() device sync", held, line, col,
                    self.method,
                )
            )
        dotted = _dotted_expr(func)
        if dotted is not None:
            root, _, rest = dotted.partition(".")
            absd = entry.imports.get(root, root) + (f".{rest}" if rest else "")
            what = _BLOCKING_CALLS.get(absd)
            if what is not None:
                facts.blocking.append(
                    _Blocking(what, held, line, col, self.method)
                )
        tm = self.db.typed_method_target(
            entry, ci.qualname, self.fn_node, node
        )
        if tm is not None:
            facts.calls.append(
                _CallSite(
                    owner=tm[0], method=tm[1], held=held, line=line,
                    col=col, from_method=self.method,
                )
            )
        self.walk(func.value)


def _collect_class_facts(db: ProgramDB) -> Dict[str, _ClassFacts]:
    out: Dict[str, _ClassFacts] = {}
    for qual, ci in db.classes.items():
        entry = db.modules[ci.module]
        facts = _ClassFacts(ci=ci, entry=entry)
        for mname, mnode in ci.methods.items():
            _MethodWalker(db, facts, mname, mnode).walk_body()
        _propagate_held(facts)
        out[qual] = facts
    return out


def _propagate_held(facts: _ClassFacts) -> None:
    """Fixpoint: a private method called *only* with lock L held inherits
    L. Public methods never inherit (external callers are unknown)."""
    sites: Dict[str, List[Tuple[str, frozenset]]] = {}
    for c in facts.calls:
        if c.owner == facts.ci.qualname:
            sites.setdefault(c.method, []).append(
                (c.from_method, frozenset(c.held))
            )
    inherited = {m: frozenset() for m in facts.ci.methods}
    for _ in range(len(facts.ci.methods) + 2):
        changed = False
        for m in facts.ci.methods:
            if not m.startswith("_") or m.startswith("__"):
                continue
            m_sites = sites.get(m)
            if not m_sites:
                continue
            eff: Optional[frozenset] = None
            for caller, held in m_sites:
                s = held | inherited.get(caller, frozenset())
                eff = s if eff is None else (eff & s)
            eff = eff or frozenset()
            if eff != inherited[m]:
                inherited[m] = eff
                changed = True
        if not changed:
            break
    facts.inherited = inherited


def _emit(
    findings: List[Finding],
    rule: str,
    entry: ModuleEntry,
    line: int,
    col: int,
    message: str,
    chain: tuple = (),
) -> None:
    findings.append(
        Finding(
            rule=rule, path=entry.path, line=line, col=col, message=message,
            severity=RULES[rule].severity, chain=chain,
        )
    )


def _check_unguarded(
    facts: _ClassFacts, findings: List[Finding]
) -> None:
    ci = facts.ci
    if not ci.locks and not ci.condvars:
        return
    guards: Dict[str, Set[str]] = {}
    guard_writer: Dict[str, Tuple[str, int]] = {}
    for a in facts.accesses:
        if a.method == "__init__" or not a.write:
            continue
        eff = facts.held_for(a.method, a.held)
        if eff:
            guards.setdefault(a.attr, set()).update(eff)
            guard_writer.setdefault(a.attr, (a.method, a.line))
    for a in facts.accesses:
        if a.method == "__init__":
            continue
        locks = guards.get(a.attr)
        if not locks:
            continue
        if facts.held_for(a.method, a.held) & locks:
            continue
        writer, wline = guard_writer[a.attr]
        lock_names = ", ".join(
            sorted(lock.rsplit(".", 1)[-1] for lock in locks)
        )
        kind = "written" if a.write else "read"
        _emit(
            findings, "unguarded-attr", facts.entry, a.line, a.col,
            f"attribute `self.{a.attr}` of `{ci.name}` is written under "
            f"`self.{lock_names}` (in `{writer}`, line {wline}) but {kind} "
            f"lock-free in `{a.method}` — a data race; guard the access or "
            "document + suppress the lock-free protocol",
            chain=(
                f"{ci.qualname}.{writer}",
                f"{ci.qualname}.{a.method}",
            ),
        )


def _check_condvars(facts: _ClassFacts, findings: List[Finding]) -> None:
    ci = facts.ci
    for op in facts.cond_ops:
        owner = facts.lock_id(op.field)
        eff = facts.held_for(op.method, op.held)
        if op.op == "wait":
            if not op.in_while:
                _emit(
                    findings, "condvar-discipline", facts.entry, op.line,
                    op.col,
                    f"`self.{op.field}.wait()` in `{ci.name}.{op.method}` "
                    "is not inside a `while` predicate loop — spurious "
                    "wakeups and missed notifies silently break the "
                    "protocol; re-test the predicate in a while loop",
                    chain=(f"{ci.qualname}.{op.method}",),
                )
            if owner not in eff:
                _emit(
                    findings, "condvar-discipline", facts.entry, op.line,
                    op.col,
                    f"`self.{op.field}.wait()` in `{ci.name}.{op.method}` "
                    f"without holding its owning lock "
                    f"`{owner.rsplit('.', 1)[-1]}` — raises RuntimeError "
                    "at runtime",
                    chain=(f"{ci.qualname}.{op.method}",),
                )
            extra = eff - {owner}
            if extra:
                names = ", ".join(sorted(x.rsplit(".", 1)[-1] for x in extra))
                _emit(
                    findings, "thread-lifecycle", facts.entry, op.line,
                    op.col,
                    f"`self.{op.field}.wait()` in `{ci.name}.{op.method}` "
                    f"blocks while still holding `{names}` — wait() only "
                    "releases its owning lock; any other lock held across "
                    "it starves every contender",
                    chain=(f"{ci.qualname}.{op.method}",),
                )
        else:  # notify
            if owner not in eff:
                _emit(
                    findings, "condvar-discipline", facts.entry, op.line,
                    op.col,
                    f"`self.{op.field}.{'notify'}()` in "
                    f"`{ci.name}.{op.method}` outside the owning lock "
                    f"`{owner.rsplit('.', 1)[-1]}` — raises RuntimeError "
                    "at runtime (and the woken waiter races the predicate)",
                    chain=(f"{ci.qualname}.{op.method}",),
                )


def _check_thread_fields(
    facts: _ClassFacts, findings: List[Finding]
) -> None:
    ci = facts.ci
    for field, (line, col, method) in facts.starts.items():
        daemon = ci.threads.get(field)
        if daemon is not False:  # daemon or not statically knowable
            continue
        if field in facts.joins:
            continue
        _emit(
            findings, "thread-lifecycle", facts.entry, line, col,
            f"non-daemon thread `self.{field}` of `{ci.name}` is started "
            f"in `{method}` but no method ever joins or cancels it — "
            "process shutdown hangs on it; join it, make it daemon, or "
            "add a stop path",
            chain=(f"{ci.qualname}.{method}",),
        )
    for b in facts.blocking:
        eff = facts.held_for(b.method, b.held)
        if not eff:
            continue
        names = ", ".join(sorted(x.rsplit(".", 1)[-1] for x in eff))
        _emit(
            findings, "thread-lifecycle", facts.entry, b.line, b.col,
            f"blocking call {b.what} in `{ci.name}.{b.method}` while "
            f"holding `{names}` — every contender stalls for the full "
            "blocking duration; move the call outside the critical "
            "section",
            chain=(f"{ci.qualname}.{b.method}",),
        )


def _check_lock_order(
    db: ProgramDB,
    all_facts: Dict[str, _ClassFacts],
    findings: List[Finding],
) -> None:
    # transitive closure of locks each method can acquire
    direct: Dict[str, Set[str]] = {}
    calls: Dict[str, List[_CallSite]] = {}
    for qual, facts in all_facts.items():
        for a in facts.acquires:
            direct.setdefault(f"{qual}.{a.method}", set()).add(a.lock)
        for c in facts.calls:
            calls.setdefault(f"{qual}.{c.from_method}", []).append(c)

    closure_memo: Dict[str, Set[str]] = {}

    def closure(mk: str, seen: frozenset) -> Set[str]:
        if mk in closure_memo:
            return closure_memo[mk]
        if mk in seen:
            return set()
        out = set(direct.get(mk, ()))
        for c in calls.get(mk, ()):
            out |= closure(f"{c.owner}.{c.method}", seen | {mk})
        if not seen:  # memo only complete (non-cycle-truncated) results
            closure_memo[mk] = out
        return out

    # edges: lock A held while lock B is acquired (directly or via calls)
    edges: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int, int, str]] = {}

    def add_edge(
        src: str, dst: str, entry: ModuleEntry, line: int, col: int,
        method_qual: str,
    ) -> None:
        if src == dst:
            return
        edges.setdefault(src, set()).add(dst)
        edges.setdefault(dst, set())
        sites.setdefault((src, dst), (entry.path, line, col, method_qual))

    for qual, facts in all_facts.items():
        for a in facts.acquires:
            eff = facts.held_for(a.method, a.held)
            for l in eff:
                add_edge(
                    l, a.lock, facts.entry, a.line, a.col,
                    f"{qual}.{a.method}",
                )
        for c in facts.calls:
            eff = facts.held_for(c.from_method, c.held)
            if not eff:
                continue
            for l2 in closure(f"{c.owner}.{c.method}", frozenset()):
                for l in eff:
                    add_edge(
                        l, l2, facts.entry, c.line, c.col,
                        f"{qual}.{c.from_method}",
                    )

    # cycle extraction: DFS with a gray stack; canonicalize by rotation
    color: Dict[str, int] = {n: 0 for n in edges}
    stack: List[str] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(n: str) -> None:
        color[n] = 1
        stack.append(n)
        for m in sorted(edges.get(n, ())):
            if color.get(m, 0) == 0:
                dfs(m)
            elif color.get(m) == 1:
                cyc = stack[stack.index(m):]
                k = min(range(len(cyc)), key=lambda j: cyc[j])
                canon = tuple(cyc[k:] + cyc[:k])
                if canon in seen_cycles:
                    continue
                seen_cycles.add(canon)
                edge_pairs = [
                    (canon[i], canon[(i + 1) % len(canon)])
                    for i in range(len(canon))
                ]
                path, line, col, _ = sites[edge_pairs[0]]
                entry = next(
                    e for e in (
                        f.entry for f in all_facts.values()
                    ) if e.path == path
                )
                order = " -> ".join(canon + (canon[0],))
                legs = "; ".join(
                    f"`{dst}` acquired under `{src}` at "
                    f"{sites[(src, dst)][0]}:{sites[(src, dst)][1]}"
                    for src, dst in edge_pairs
                )
                _emit(
                    findings, "lock-order-cycle", entry, line, col,
                    f"lock acquisition order cycle {order} — two threads "
                    f"taking the locks in opposite orders deadlock ({legs})",
                    chain=tuple(sites[p][3] for p in edge_pairs),
                )
        stack.pop()
        color[n] = 2

    for n in sorted(edges):
        if color.get(n, 0) == 0:
            dfs(n)


class _LocalThreads(ast.NodeVisitor):
    """Function-local thread lifecycle: a non-daemon Thread/Timer bound
    to a local name (directly or inside a list) and started must have a
    ``join()``/``cancel()`` somewhere in the function — through the name
    itself or a for-loop alias over the list. Threads that escape (are
    returned, yielded, or passed to another call) are skipped."""

    def __init__(self, db: ProgramDB, entry: ModuleEntry):
        self.db = db
        self.entry = entry
        #: var -> (daemon, line, col)
        self.threads: Dict[str, Tuple[Optional[bool], int, int]] = {}
        self.aliases: Dict[str, str] = {}  # for-target -> collection var
        self.started: Set[str] = set()
        self.joined: Set[str] = set()
        self.escaped: Set[str] = set()

    def _ctor_daemon(self, call: ast.Call) -> Optional[Tuple[Optional[bool]]]:
        """(daemon,) when ``call`` constructs a Thread/Timer, else None."""
        d = _dotted_expr(call.func)
        if d is None:
            return None
        root, _, rest = d.partition(".")
        absd = self.entry.imports.get(root, root) + (
            f".{rest}" if rest else ""
        )
        if absd not in ("threading.Thread", "threading.Timer"):
            return None
        daemon: Optional[bool] = False
        for kw in call.keywords:
            if kw.arg == "daemon":
                daemon = (
                    kw.value.value
                    if isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, bool)
                    else None
                )
        return (daemon,)

    def _thread_ctor_in(self, value: ast.AST) -> Optional[Tuple[Optional[bool]]]:
        """A thread constructor directly, in a list literal, or as a
        list-comprehension element."""
        if isinstance(value, ast.Call):
            return self._ctor_daemon(value)
        if isinstance(value, (ast.List, ast.Tuple)):
            for elt in value.elts:
                if isinstance(elt, ast.Call):
                    got = self._ctor_daemon(elt)
                    if got is not None:
                        return got
        if isinstance(value, ast.ListComp) and isinstance(
            value.elt, ast.Call
        ):
            return self._ctor_daemon(value.elt)
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        got = self._thread_ctor_in(node.value)
        if got is not None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.threads[t.id] = (
                        got[0], node.value.lineno, node.value.col_offset + 1
                    )
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if isinstance(node.target, ast.Name) and isinstance(
            node.iter, ast.Name
        ):
            base = self.aliases.get(node.iter.id, node.iter.id)
            if base in self.threads:
                self.aliases[node.target.id] = base
        self.generic_visit(node)

    def _base(self, name: str) -> Optional[str]:
        base = self.aliases.get(name, name)
        return base if base in self.threads else None

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            base = self._base(func.value.id)
            if base is not None:
                if func.attr == "start":
                    self.started.add(base)
                elif func.attr in ("join", "cancel"):
                    self.joined.add(base)
                elif func.attr == "append":
                    # collection.append(Thread(...)) — stays tracked
                    pass
        # a thread handed to another call escapes local analysis
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                base = self._base(arg.id)
                if base is not None:
                    self.escaped.add(base)
        self.generic_visit(node)

    def _escape(self, node) -> None:
        if node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    base = self._base(sub.id)
                    if base is not None:
                        self.escaped.add(base)
        self.generic_visit(node)

    visit_Return = _escape
    visit_Yield = _escape

    def findings(self, findings: List[Finding]) -> None:
        for var in sorted(self.started - self.joined - self.escaped):
            daemon, line, col = self.threads[var]
            if daemon is not False:
                continue
            _emit(
                findings, "thread-lifecycle", self.entry, line, col,
                f"non-daemon thread `{var}` is started but never joined "
                "or cancelled in this function — the process cannot exit "
                "while it runs; join it or pass daemon=True",
            )


def _check_local_threads(
    db: ProgramDB, entry: ModuleEntry, findings: List[Finding]
) -> None:
    for node in entry.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lt = _LocalThreads(db, entry)
            lt.visit(node)
            lt.findings(findings)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    lt = _LocalThreads(db, entry)
                    lt.visit(item)
                    lt.findings(findings)


def check_concurrency(
    db: Optional[ProgramDB] = None, *, include_suppressed: bool = False
) -> List[Finding]:
    """Run the four concurrency rules repo-wide over ``db`` (built from
    the installed package when omitted). Suppressions apply at each
    finding's reported line, exactly like the AST lint."""
    if db is None:
        import os

        import stmgcn_tpu

        root = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
        db = ProgramDB.from_root(root, type_informed=True)

    findings: List[Finding] = []
    all_facts = _collect_class_facts(db)
    for facts in all_facts.values():
        _check_unguarded(facts, findings)
        _check_condvars(facts, findings)
        _check_thread_fields(facts, findings)
    _check_lock_order(db, all_facts, findings)
    for entry in db.modules.values():
        _check_local_threads(db, entry, findings)

    # suppression: the reported line governs, mirroring lint_source
    suppress_by_path = {
        e.path: _suppressions(e.source) for e in db.modules.values()
    }
    out: List[Finding] = []
    for f in findings:
        rules = suppress_by_path.get(f.path, {}).get(f.line, ...)
        live = rules is ... or (rules is not None and f.rule not in rules)
        if live:
            out.append(f)
        elif include_suppressed:
            out.append(dataclasses.replace(f, suppressed=True))
    return out
