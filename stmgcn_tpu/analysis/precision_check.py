"""Pass 2h: precision dataflow contracts over every traced program.

Judges the role-classified dtype sites :mod:`.dtype_flow` extracts from
every registered contract program against the declarative
:class:`stmgcn_tpu.config.PrecisionPolicy` — three error rules on the
standard lint machinery:

- **precision-policy** — a site's compute dtype outside its role's
  allowance, a self-contradictory policy, a registered program the walk
  missed (coverage is checked, not assumed), a master-param or loss
  boundary leaf off the declared dtype, or a census drift from
  :data:`PRECISION_BASELINES`.
- **accum-dtype** — any mandatory-f32 reduction role (sum reductions,
  scan/while carries, psum operands, dot-general accumulators) holding
  a floating dtype narrower than f32; the finding names the exact eqn
  and carry leaf with its full provenance chain.
- **implicit-cast** — a float->float dtype-changing cast the policy's
  whitelist never declared (casts to f64 stay with fp64-promotion).

The per-program **dtype census** (bytes and FLOPs by dtype, count of
dtype-changing casts) is persisted as the single-line
:data:`PRECISION_BASELINES` literal by ``stmgcn lint --rebaseline``
(:func:`rebaseline_precision`) — the future bf16 migration lands as a
measured census diff plus a deliberate rebaseline, never silent drift.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from stmgcn_tpu.analysis.dtype_flow import (
    FLOAT_DTYPES,
    DtypeSite,
    ProgramFlow,
    program_flows,
)
from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = [
    "PRECISION_BASELINES",
    "check_flow",
    "check_precision",
    "measured_census",
    "precision_summary",
    "rebaseline_precision",
]

#: measured per-program dtype census (bytes/FLOPs by dtype, count of
#: dtype-changing casts) — the precision twin of PRIMITIVE_BUDGETS. The
#: float-dtype *set* is gated exactly (a new floating dtype in any
#: program is drift) and the cast count at ~2x headroom; the byte/FLOP
#: values are provenance for census diffs, not gates. Keep this a
#: single-line literal: ``stmgcn lint --rebaseline`` rewrites it in
#: place from the measured census (:func:`rebaseline_precision`).
PRECISION_BASELINES = {'eval_step': {'bytes': {'bool': 3, 'float32': 56788692, 'int32': 48}, 'flops': {'float32': 121699200}, 'casts': 0, 'eqns': 94}, 'serve_bucket': {'bytes': {'bool': 3, 'float32': 28369024, 'int32': 48}, 'flops': {'float32': 60849600}, 'casts': 0, 'eqns': 85}, 'serve_fleet_bucket': {'bytes': {'bool': 1731, 'float32': 41197376, 'int32': 1552}, 'flops': {'float32': 60849600}, 'casts': 2, 'eqns': 133}, 'train_fleet_superstep': {'bytes': {'bool': 118890, 'float32': 146578200, 'int32': 5116}, 'flops': {'float32': 283977600}, 'casts': 4, 'eqns': 483}, 'train_fleet_superstep_bf16': {'bytes': {'bfloat16': 5636640, 'bool': 118890, 'float32': 145407412, 'int32': 5116}, 'flops': {'float32': 283977600}, 'casts': 86, 'eqns': 565}, 'train_series_superstep': {'bytes': {'bool': 118788, 'float32': 146061284, 'int32': 4700}, 'flops': {'float32': 283977600}, 'casts': 2, 'eqns': 455}, 'train_series_superstep_bf16': {'bytes': {'bfloat16': 5636640, 'bool': 118788, 'float32': 144890496, 'int32': 4700}, 'flops': {'float32': 283977600}, 'casts': 84, 'eqns': 537}, 'train_series_superstep_health': {'bytes': {'bool': 133988, 'float32': 146183392, 'int32': 35252}, 'flops': {'float32': 283977600}, 'casts': 14, 'eqns': 655}, 'train_step': {'bytes': {'bool': 118564, 'float32': 145816468, 'int32': 68}, 'flops': {'float32': 283977600}, 'casts': 2, 'eqns': 430}, 'train_step_bf16': {'bytes': {'bfloat16': 5636640, 'bool': 118564, 'float32': 144645680, 'int32': 68}, 'flops': {'float32': 283977600}, 'casts': 84, 'eqns': 512}, 'train_step_checked': {'bytes': {'bool': 11302964, 'float32': 145725276, 'int32': 1296}, 'flops': {'float32': 283977600}, 'casts': 2, 'eqns': 1641}, 'train_superstep': {'bytes': {'bool': 118628, 'float32': 146061284, 'int32': 1096}, 'flops': {'float32': 283977600}, 'casts': 2, 'eqns': 445}, 'train_superstep_bf16': {'bytes': {'bfloat16': 5636640, 'bool': 118628, 'float32': 144890496, 'int32': 1096}, 'flops': {'float32': 283977600}, 'casts': 84, 'eqns': 527}}

_ITEMSIZE = {"float16": 2, "bfloat16": 2, "float32": 4, "float64": 8}
_CAST_HEADROOM = 2.0


def _emit(
    findings: List[Finding], rule: str, name: str, message: str
) -> None:
    findings.append(
        Finding(
            rule=rule,
            path=f"<contract:precision:{name}>",
            line=0,
            message=message,
            severity=RULES[rule].severity,
        )
    )


def _site_findings(
    flow: ProgramFlow, site: DtypeSite, policy
) -> List[Finding]:
    findings: List[Finding] = []
    if site.role == "cast":
        src = site.operand_dtypes[0] if site.operand_dtypes else "?"
        dst = site.dtype
        if (
            src in FLOAT_DTYPES
            and dst in FLOAT_DTYPES
            and src != dst
            and dst != "float64"  # fp64-promotion owns promotions to f64
            and (src, dst) not in policy.cast_whitelist
        ):
            _emit(
                findings, "implicit-cast", flow.name,
                f"{site.describe()}: cast {src}->{dst} is not in "
                f"PrecisionPolicy.cast_whitelist "
                f"{tuple(policy.cast_whitelist)} — a silent "
                f"{'up' if _ITEMSIZE[dst] > _ITEMSIZE[src] else 'down'}cast "
                "the migration plan never audited",
            )
        return findings
    if site.role in policy.reduction_f32_roles:
        # accumulation roles are owned by accum-dtype (one finding per
        # hazard, not one per rule)
        if site.dtype in FLOAT_DTYPES and _ITEMSIZE[site.dtype] < 4:
            _emit(
                findings, "accum-dtype", flow.name,
                f"{site.describe()}: reduction accumulator narrower than "
                f"float32 — role {site.role!r} is in "
                "PrecisionPolicy.reduction_f32_roles (mandatory f32); "
                "low-order bits are lost on every add",
            )
        return findings
    allowed = policy.allowed(site.role)
    if allowed is None:
        return findings
    checked = (
        [d for d in site.operand_dtypes if d in FLOAT_DTYPES]
        if site.role == "dot_general"
        else ([site.dtype] if site.dtype in FLOAT_DTYPES else [])
    )
    bad = sorted({d for d in checked if d not in allowed})
    if bad:
        _emit(
            findings, "precision-policy", flow.name,
            f"{site.describe()}: dtype(s) {bad} outside "
            f"PrecisionPolicy.role_dtypes[{site.role!r}] = {allowed}",
        )
    return findings


def _boundary_findings(flow: ProgramFlow, policy) -> List[Finding]:
    """Master-param / optimizer-state / loss dtype at program edges."""
    findings: List[Finding] = []
    master = policy.master_param_dtype
    loss_allowed = policy.allowed("loss") or (master,)
    for end, labels, dtypes in (
        ("input", flow.in_labels, flow.in_dtypes),
        ("output", flow.out_labels, flow.out_dtypes),
    ):
        seen: Dict[str, int] = {}
        for label, dt in zip(labels, dtypes):
            i = seen.get(label, 0)
            seen[label] = i + 1
            if dt not in FLOAT_DTYPES:
                continue
            if label in ("param", "opt_state") and dt != master:
                _emit(
                    findings, "precision-policy", flow.name,
                    f"{flow.name}: {end} leaf {label}[{i}] has dtype "
                    f"{dt}, but PrecisionPolicy.master_param_dtype is "
                    f"{master!r} — master state must stay wide; cast for "
                    "compute instead",
                )
            elif label == "loss" and dt not in loss_allowed:
                _emit(
                    findings, "precision-policy", flow.name,
                    f"{flow.name}: {end} leaf loss[{i}] has dtype {dt} "
                    f"outside PrecisionPolicy.role_dtypes['loss'] = "
                    f"{loss_allowed}",
                )
    return findings


def check_flow(flow: ProgramFlow, policy) -> List[Finding]:
    """All three precision rules over one walked program."""
    findings: List[Finding] = []
    for site in flow.sites:
        findings.extend(_site_findings(flow, site, policy))
    findings.extend(_boundary_findings(flow, policy))
    return findings


def _census_findings(
    name: str, census: dict, baseline: Optional[dict]
) -> List[Finding]:
    findings: List[Finding] = []
    if baseline is None:
        _emit(
            findings, "precision-policy", name,
            f"{name}: no PRECISION_BASELINES entry — a new contract "
            "program needs a deliberate census baseline; run "
            "`stmgcn lint --rebaseline`",
        )
        return findings
    measured_f = {d for d in census["bytes"] if d in FLOAT_DTYPES}
    baseline_f = {d for d in baseline.get("bytes", {}) if d in FLOAT_DTYPES}
    if measured_f != baseline_f:
        _emit(
            findings, "precision-policy", name,
            f"{name}: floating dtype census drifted — measured "
            f"{sorted(measured_f)} vs baseline {sorted(baseline_f)}; a "
            "precision migration must land as `stmgcn lint "
            "--rebaseline`, never as silent drift",
        )
    cast_budget = int(baseline.get("casts", 0) * _CAST_HEADROOM)
    if census["casts"] > max(cast_budget, baseline.get("casts", 0)):
        _emit(
            findings, "precision-policy", name,
            f"{name}: {census['casts']} dtype-changing casts > budget "
            f"{cast_budget} (baseline {baseline.get('casts', 0)} x "
            f"{_CAST_HEADROOM} headroom) — cast-boundary growth; "
            "rebaseline deliberately if intended",
        )
    return findings


def check_precision(
    preset_name: str = "smoke",
    policy=None,
    flows: Optional[Dict[str, ProgramFlow]] = None,
) -> List[Finding]:
    """Walk every registered contract program and apply the policy.

    ``policy``/``flows`` overrides exist for fixtures; the default is
    the preset's declared :class:`~stmgcn_tpu.config.PrecisionPolicy`
    over the cached :func:`~.dtype_flow.program_flows` registry.
    """
    from stmgcn_tpu.analysis.jaxpr_check import PRIMITIVE_BUDGETS
    from stmgcn_tpu.config import preset

    if policy is None:
        policy = preset(preset_name).precision
    findings: List[Finding] = []
    for v in policy.violations():
        _emit(findings, "precision-policy", "policy", f"PrecisionPolicy: {v}")
    if flows is None:
        flows = program_flows(preset_name)
    # coverage is itself a contract: a registered program the dtype walk
    # never saw is a hole in the certification, not a pass
    for name in sorted(set(PRIMITIVE_BUDGETS) - set(flows)):
        _emit(
            findings, "precision-policy", name,
            f"{name}: registered contract program was not walked by the "
            "dtype-flow pass — precision coverage hole",
        )
    for name in sorted(flows):
        flow = flows[name]
        findings.extend(check_flow(flow, policy))
        findings.extend(
            _census_findings(name, flow.census, PRECISION_BASELINES.get(name))
        )
    return findings


def measured_census(preset_name: str = "smoke") -> Dict[str, dict]:
    """The current per-program dtype census (the rebaseline payload)."""
    return {
        name: flow.census
        for name, flow in sorted(program_flows(preset_name).items())
    }


def precision_summary(preset_name: str = "smoke") -> dict:
    """The lint-gate section: programs walked / sites classified /
    unsuppressed findings (0 programs or any finding fails the gate).
    ``bf16_programs`` counts the mixed-precision twin programs the walk
    covered — the gate requires it > 0, so the bf16 certification can
    never silently drop out of the registry."""
    flows = program_flows(preset_name)
    findings = check_precision(preset_name, flows=flows)
    return {
        "programs": len(flows),
        "bf16_programs": sum(1 for name in flows if name.endswith("_bf16")),
        "sites": sum(len(f.sites) for f in flows.values()),
        "findings": sum(1 for f in findings if not f.suppressed),
    }


def rebaseline_precision(
    path: Optional[str] = None, preset_name: str = "smoke"
) -> dict:
    """Measure the dtype census and rewrite :data:`PRECISION_BASELINES`.

    Same contract as the primitive/wire rebaselines: the measured
    census is written verbatim into this module's single-line literal
    (``path`` overrides the target for tests) and updated in-process so
    subsequent checks see the new baseline. Cast headroom (~2x) is
    applied at check time, not stored.

    Returns ``{"census": ..., "path": ...}``.
    """
    census = measured_census(preset_name)
    path = path or __file__
    with open(path) as f:
        src = f.read()
    new_src, n_subs = re.subn(
        r"PRECISION_BASELINES = \{.*\}",
        "PRECISION_BASELINES = " + repr(census),
        src,
        count=1,
    )
    if n_subs != 1:
        raise RuntimeError(
            f"could not find the PRECISION_BASELINES literal in {path}"
        )
    with open(path, "w") as f:
        f.write(new_src)
    PRECISION_BASELINES.clear()
    PRECISION_BASELINES.update(census)
    return {"census": census, "path": path}
