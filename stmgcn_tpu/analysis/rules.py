"""Rule registry and the JAX symbol-compatibility table.

Every finding carries one of these rule ids; the tier-1 test and the
``stmgcn lint`` CLI treat ``error``-severity rules as gating. The compat
table is the machine-readable form of the supported-version contract
(``jax>=0.4.30,<0.6`` in pyproject.toml): symbols that moved, appeared,
or disappeared inside that range must be routed through
:mod:`stmgcn_tpu.utils.platform` so one shim owns the version split —
``from jax import shard_map`` at module scope is precisely the mistake
that killed six test modules at collection on this image's jax 0.4.37.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

__all__ = ["JAX_COMPAT_ATTRS", "JAX_COMPAT_IMPORTS", "RULES", "Rule"]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str  # "error" | "warning"
    summary: str
    #: long-form text for SARIF ``fullDescription`` (code-scanning UIs
    #: show it on the rule page); empty falls back to ``summary`` so the
    #: SARIF rules array never carries an empty description
    description: str = ""


_ALL_RULES = [
    # -- pass 1: AST lint ------------------------------------------------
    Rule(
        "jax-compat-import",
        "error",
        "import of a JAX symbol that moved/appeared/disappeared within the "
        "supported version range (jax>=0.4.30,<0.6); route it through "
        "stmgcn_tpu.utils.platform",
    ),
    Rule(
        "host-sync-in-jit",
        "error",
        "host-synchronizing call (.item()/float()/np.asarray/jax.device_get/"
        "block_until_ready) inside a function reachable from jitted code — "
        "a hidden device->host readback in the hot path",
    ),
    Rule(
        "traced-control-flow",
        "error",
        "Python if/while on a traced value inside a jit-reachable function "
        "— fails to trace, or silently specializes on one branch",
    ),
    Rule(
        "unfenced-timing",
        "warning",
        "time.time()/perf_counter() span around device dispatch with no "
        "readback fence — on the tunneled axon backend this times dispatch, "
        "not compute (see stmgcn_tpu.utils.profiling)",
    ),
    Rule(
        "missing-donate",
        "warning",
        "jax.jit of a train-step-like function without donate_argnums — "
        "params/opt-state buffers are copied instead of reused every step",
    ),
    Rule(
        "recompile-hazard",
        "warning",
        "a fresh object reaches jax.jit's trace cache every call — "
        "jit(...) invoked in place (new wrapper, empty cache) or a "
        "lambda/list/dict literal at a static_argnums/static_argnames "
        "position (new identity/unhashable value -> retrace or TypeError)",
    ),
    Rule(
        "closure-identity",
        "warning",
        "a per-call-fresh callable identity reaches jax.jit's trace cache "
        "— functools.partial / a bound method / a nested def at a "
        "static_argnums/static_argnames position, or jax.jit bound inside "
        "a loop body — each call (or iteration) presents a new identity "
        "and silently retraces",
    ),
    # -- pass 1b: static concurrency analysis ----------------------------
    Rule(
        "unguarded-attr",
        "error",
        "an attribute written under `with self._lock` in one method is "
        "read/written lock-free in another method of the same class — a "
        "data race; the finding carries the guarding-writer -> lock-free-"
        "access chain",
    ),
    Rule(
        "lock-order-cycle",
        "error",
        "the global lock-acquisition graph (built across modules through "
        "the type-informed call graph) contains a cycle — two threads "
        "taking the locks in opposite orders deadlock",
    ),
    Rule(
        "condvar-discipline",
        "error",
        "Condition.wait() outside a while-predicate loop (spurious "
        "wakeup / missed notify), or wait/notify without the condvar's "
        "owning lock held (RuntimeError at runtime)",
    ),
    Rule(
        "thread-lifecycle",
        "error",
        "a non-daemon Thread started with no reachable join()/cancel() "
        "path (shutdown hangs on it), or a blocking call (queue.get/put, "
        "sleep, join, Event.wait, device sync) made while holding a lock",
    ),
    # -- pass 2: jaxpr / sharding contracts ------------------------------
    Rule(
        "fp64-promotion",
        "error",
        "step jaxpr contains a convert_element_type to float64 — a silent "
        "2x memory/bandwidth promotion (TPUs have no fp64 MXU path)",
    ),
    Rule(
        "weak-type-output",
        "error",
        "step output aval is weak-typed where its input was not — the "
        "second call recompiles against the strengthened type",
    ),
    Rule(
        "primitive-budget",
        "error",
        "step jaxpr primitive count exceeds the recorded budget — a fusion "
        "or op-count regression (rebaseline deliberately if intended)",
    ),
    Rule(
        "collective-shape",
        "error",
        "a preset's mesh extents and collective operand shapes disagree "
        "(ppermute halo rows vs shard size, batch vs dp, m_graphs vs "
        "branch) — the collective fails or drops data at runtime",
    ),
    Rule(
        "resident-memory",
        "error",
        "a preset requests resident data placement its device cannot hold "
        "(window-free series vs materialized windows vs the per-core "
        "budget, or resident on a multi-device mesh) — the run OOMs or is "
        "rejected at the first epoch",
    ),
    Rule(
        "fleet-shape-class",
        "error",
        "a preset's fleet shape-class plan is unviable (invalid planner "
        "knobs, fleet=True on a homogeneous dataset or streamed data, "
        "cities uncovered within the class/waste budget, or a class's "
        "resident footprint over the per-core budget) — the fleet fast "
        "path is rejected, OOMs, or silently degrades per city",
    ),
    Rule(
        "serving-bucket-shape",
        "error",
        "a preset's serving bucket ladder is unservable (not strictly "
        "increasing, tops out below max_batch, or a rung's worst-case pad "
        "waste exceeds max_pad_waste) — engine construction would reject it "
        "at deploy time",
    ),
    Rule(
        "serving-slo",
        "error",
        "a preset's SLO/admission knobs are self-contradictory (deadline_ms "
        "at or below the max_delay_ms coalescing floor sheds every "
        "coalesced request, queue_bound_rows below the top rung can never "
        "fill a saturated dispatch, degrade_rung outside the ladder has no "
        "compiled program) — a deploy-time outage detectable from config "
        "math",
    ),
    Rule(
        "obs-overhead",
        "error",
        "a preset enables tracing with an unbounded span ring or "
        "configures a histogram reservoir past the documented budget "
        "(config.OBS_RING_BUDGET / OBS_RESERVOIR_BUDGET) — observability "
        "itself becomes the memory leak / perf regression in a "
        "long-lived process",
    ),
    Rule(
        "health-overhead",
        "error",
        "a preset's numeric-health knobs are self-defeating (drift "
        "comparison without a training-time baseline, sketch/reservoir "
        "sizes outside the documented OBS_RESERVOIR_BUDGET, or a "
        "non-positive sampling cadence) — HealthConfig.violations() "
        "config math, detectable before any step runs",
    ),
    Rule(
        "continual-config",
        "error",
        "a preset's continual-loop knobs cannot run unattended (ring "
        "sized past the per-core resident budget or too small for one "
        "training window, retrain cadence the measured superstep time "
        "cannot sustain without starving serving, promotion-gate "
        "thresholds missing or unordered, or a drift-only trigger with "
        "no health baseline to fire against) — "
        "ContinualConfig.violations() config math, detectable before "
        "any step runs",
    ),
    Rule(
        "federation-config",
        "error",
        "a preset's serving-federation topology cannot hold its own "
        "contracts (more replicas than cities — engines permanently "
        "idle behind the hash ring, too few virtual nodes for the "
        "configured imbalance bound, a tier-wide overload budget below "
        "a single replica's local queue bound or top dispatch rung — "
        "the global limiter binds before any local SLO math applies, "
        "or a handover window that out-waits the drain window) — "
        "FederationConfig.violations() config math, detectable before "
        "any replica is built",
    ),
    Rule(
        "pallas-blockspec",
        "error",
        "a pl.pallas_call BlockSpec/grid disagrees with its operand "
        "shapes (non-divisible block dims, grid not covering the padded "
        "rows, spec/operand arity mismatch, or the static checker out of "
        "sync with the kernel source) — Mosaic rejects the program or "
        "the kernel addresses rows it was never given",
    ),
    Rule(
        "pallas-vmem",
        "error",
        "a pallas_call's estimated VMEM footprint (double-buffered "
        "streamed blocks + resident blocks, calibrated against the real "
        "Mosaic AOT 18.04 MB fp32-forward OOM) exceeds the ~16 MiB/core "
        "scoped budget — Mosaic aborts compilation on a real chip",
    ),
    Rule(
        "tile-plan",
        "error",
        "a preset's tiled-support plan cannot hold: tile_size/"
        "tile_waste_budget outside their ranges, tiled combined with "
        "sparse or a >1-device mesh, node padding on the tile grid "
        "already past the waste budget (build_supports guaranteed to "
        "raise), or the tiled SpMM's calibrated VMEM estimate at the "
        "configured tile size past the ~16 MiB/core budget — pure "
        "config math, detectable before any adjacency is built",
    ),
    # -- pass 2h: precision dataflow (dtype_flow + precision_check) -------
    Rule(
        "precision-policy",
        "error",
        "a dtype site's compute dtype is outside its role's PrecisionPolicy "
        "allowance, the policy itself is self-contradictory, a registered "
        "contract program escaped the dtype-flow walk, or the measured "
        "dtype census drifted from PRECISION_BASELINES (rebaseline "
        "deliberately with the feature that moved it)",
        description=(
            "The dtype-flow pass walks the jaxpr of every registered "
            "contract program, classifies each eqn into the precision "
            "role taxonomy (dot-general operand/accumulator, accumulating "
            "reduction, order statistic, scan carry, psum, normalization "
            "stat, cast, loss, optimizer update, master param), and "
            "checks each site's dtype against the declarative "
            "PrecisionPolicy in config.py. This rule fires when a site's "
            "dtype falls outside its role's allowance (e.g. a bf16 "
            "dot-general under a policy whose role_dtypes pins "
            "dot_general to float32), when PrecisionPolicy.violations() "
            "reports the policy self-contradictory, when a program in "
            "the contract registry was not walked (a coverage hole is a "
            "finding, not silence), or when the per-program dtype census "
            "(float dtype set, cast count) drifts from the "
            "PRECISION_BASELINES literal — the bf16 migration lands as a "
            "deliberate `stmgcn lint --rebaseline`, never as silent "
            "drift. Each finding names the eqn, role, provenance chain, "
            "and the policy knob that bans it."
        ),
    ),
    Rule(
        "accum-dtype",
        "error",
        "a reduction accumulator — reduce_sum-family output, scan/while "
        "carry leaf, psum operand, or dot-general accumulator — has a "
        "floating dtype narrower than float32 (the classic bf16 "
        "accumulation hazard: low-order bits lost on every add)",
        description=(
            "Accumulation sites sum many addends, so precision loss "
            "compounds: a bf16 scan carry or reduce_sum silently diverges "
            "training long after compilation succeeds. For every role in "
            "PrecisionPolicy.reduction_f32_roles (by default reduce_sum, "
            "scan_carry, psum, dot_general_accum) this rule fires on any "
            "floating dtype with itemsize < 4 bytes, naming the exact "
            "eqn (walk index and primitive), the carry leaf or operand "
            "position, and the full dtype provenance chain back to the "
            "program input, constant, or cast site that introduced the "
            "narrow dtype. bf16 *compute* with f32 accumulation passes; "
            "bf16 accumulation never does."
        ),
    ),
    Rule(
        "implicit-cast",
        "error",
        "a float->float dtype-changing convert_element_type the "
        "PrecisionPolicy.cast_whitelist did not declare — a silent up- or "
        "downcast the migration plan never audited",
        description=(
            "Every dtype-changing float cast in a traced program must "
            "appear in PrecisionPolicy.cast_whitelist as a (src, dst) "
            "pair (by default exactly the f32<->bf16 boundary). An "
            "unwhitelisted cast is either an accidental promotion "
            "(memory/bandwidth doubled behind the optimizer's back) or "
            "an accidental truncation (precision lost where the policy "
            "promised full width). Casts to float64 are excluded here — "
            "the fp64-promotion rule owns those unconditionally. Each "
            "finding names the eqn, the src->dst pair, and the "
            "provenance chain of the value being cast."
        ),
    ),
    # -- pass 2g: SPMD collective contracts (spmd_check) ------------------
    Rule(
        "spmd-collective-manifest",
        "error",
        "a multi-device preset's compiled step program contains a "
        "collective (kind x mesh axes) its plan never declared — implicit "
        "GSPMD resharding, e.g. a full node-axis all-gather erasing the "
        "banded plan's wire savings — or a declared required collective "
        "never appears, meaning the plan did not engage",
    ),
    Rule(
        "spmd-wire-budget",
        "error",
        "a compiled program's collective bytes-on-wire exceed the "
        "rebaselined per-program budget, a halo permute moves more than "
        "the boundary-rows bound, or dp all-reduce traffic exceeds the "
        "gradient-psum model (2 x param_bytes + slack) — a communication "
        "regression; rebaseline deliberately if intended",
    ),
    Rule(
        "spmd-shard-footprint",
        "error",
        "a multi-device preset's per-device sharded operand footprint "
        "(support strips/shards + batch shard) exceeds the per-core "
        "budget — the resident-memory math extended to mesh shards; the "
        "step OOMs on every device at once",
    ),
    Rule(
        "partition-axis-name",
        "error",
        "PartitionSpec names a mesh axis that no mesh in this repo defines "
        "(known axes: dp, region, branch)",
    ),
    Rule(
        "partition-rank",
        "error",
        "PartitionSpec rank exceeds the documented operand rank for its "
        "array kind (placement table)",
    ),
]

RULES: Dict[str, Rule] = {r.id: r for r in _ALL_RULES}

#: ``(module, symbol) -> why`` — ``from module import symbol`` is flagged.
#: ``symbol`` of ``"*"`` flags any import from that module.
JAX_COMPAT_IMPORTS: Dict[Tuple[str, str], str] = {
    ("jax", "shard_map"): (
        "jax.shard_map only exists from 0.5.x; use "
        "stmgcn_tpu.utils.platform.shard_map (handles check_vma/check_rep)"
    ),
    ("jax.experimental.shard_map", "*"): (
        "moves to jax.shard_map in 0.5.x; use "
        "stmgcn_tpu.utils.platform.shard_map"
    ),
    ("jax", "linear_util"): "moved to jax.extend.linear_util in 0.4.x",
    ("jax.experimental", "maps"): "removed in 0.4.x (xmap retired)",
    ("jax.experimental.maps", "*"): "removed in 0.4.x (xmap retired)",
    ("jax.experimental", "host_callback"): (
        "removed; use jax.experimental.io_callback / jax.debug.callback"
    ),
    ("jax.experimental.host_callback", "*"): (
        "removed; use jax.experimental.io_callback / jax.debug.callback"
    ),
    ("jax", "abstract_arrays"): "removed in 0.4.x; use jax.core avals",
    ("jax.experimental", "global_device_array"): "removed; use jax.Array",
    ("jax.experimental.global_device_array", "*"): "removed; use jax.Array",
    ("jax.interpreters", "xla"): (
        "gutted across 0.4.x; use jax.extend / public APIs"
    ),
}

#: dotted attribute chains (rooted at the ``jax`` module) that are
#: version-fragile when *called*, with the portable replacement.
JAX_COMPAT_ATTRS: Dict[str, str] = {
    "jax.lax.axis_size": (
        "only exists from 0.5.x; use stmgcn_tpu.utils.platform.axis_size"
    ),
    "jax.shard_map": (
        "only exists from 0.5.x; use stmgcn_tpu.utils.platform.shard_map"
    ),
    "jax.tree_map": "removed in 0.6; use jax.tree.map",
    "jax.tree_multimap": "removed long ago; use jax.tree.map",
    "jax.treedef_is_leaf": "moved to jax.tree_util",
    "jax.experimental.shard_map.shard_map": (
        "moves to jax.shard_map in 0.5.x; use "
        "stmgcn_tpu.utils.platform.shard_map"
    ),
}
