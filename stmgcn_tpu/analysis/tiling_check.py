"""Pass 2i: tiled-support plan contracts — pure config math.

The tiled-sparse path (``ops/tiling.py`` + ``model.tiled``) commits at
config time to a tile size, a condensation waste budget, and the claim
that the fused SpMM kernels fit in VMEM at that tile. All three are
checkable before any adjacency is built, the same way ``fleet-shape-
class`` re-runs the planner host-side:

- **knob ranges** — ``tile_size >= 1`` and ``tile_waste_budget`` in
  ``(0, 1]`` (``build_supports`` raises on violation at plan time, but
  a preset should not ship a config that cannot plan);
- **mode conflicts** — ``model.tiled`` with ``model.sparse`` (the two
  non-dense layouts are mutually exclusive) or with a >1-device mesh
  (tiled plans are single-device; ``route_supports`` rejects both);
- **node-padding waste** — each city's node count rounds up to the tile
  grid (``ceil(N / tile) * tile``); when the padding rows alone exceed
  ``tile_waste_budget``, the realized condensation waste *must* exceed
  the budget too and ``build_supports`` is guaranteed to raise. A
  config-time certainty, flagged before any data is generated;
- **kernel VMEM at the configured tile** — the calibrated footprint
  model from :mod:`.pallas_check` (same ``CALIBRATION`` constant, same
  double-buffered streamed blocks) at the tiled SpMM's worst-case
  column tile (``tm = 256``): one ``(tile, tile)`` support block plus
  the gathered signal block and the output block. Past ~16 MiB/core
  Mosaic aborts compilation — the exact boundary the ``pallas-vmem``
  rule pins for the shipped kernels, here evaluated at a *configured*
  tile instead of the shipped one (tile=512 clears it, tile=1024 does
  not).

No data build, no trace.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple, Union

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = [
    "check_tile_plan",
    "tile_plan_violations",
    "tiled_spmm_vmem_estimate",
]

#: the kernels' column-tile ceiling (ops/spmm.py: ``tm = min(256, ...)``)
_TM_WORST = 256


def _ceil_to(n: int, t: int) -> int:
    return -(-n // t) * t


def tiled_spmm_vmem_estimate(tile: int, itemsize: int = 4) -> float:
    """Calibrated VMEM bytes of one tiled SpMM launch at ``tile``.

    Worst-case operand set per grid step: the ``(tile, tile)`` support
    block, the gathered ``(tile, tm)`` signal block, and the ``(tile,
    tm)`` output block — all streamed, so double-buffered, under the
    same fitted calibration as :func:`.pallas_check.vmem_estimate`.
    """
    from stmgcn_tpu.analysis.pallas_check import CALIBRATION, PIPELINE_FACTOR

    streamed = (tile * tile + 2 * tile * _TM_WORST) * itemsize
    return CALIBRATION * PIPELINE_FACTOR * streamed


def tile_plan_violations(
    model_cfg, n_nodes: Union[int, Sequence[int]]
) -> List[str]:
    """Config-arithmetic violations of one model config's tiled plan.

    ``n_nodes`` is the city node count, or one count per city for a
    heterogeneous preset. Returns human-readable messages; empty when
    the config is not tiled or the plan is viable.
    """
    m = model_cfg
    msgs: List[str] = []
    if not getattr(m, "tiled", False):
        return msgs
    if m.sparse:
        msgs.append(
            "model.tiled and model.sparse are mutually exclusive — the "
            "offline tile plan replaces the banded/sparse layout"
        )
    if m.tile_size < 1:
        msgs.append(
            f"model.tile_size must be >= 1, got {m.tile_size} — "
            "plan_tiling rejects it"
        )
        return msgs
    if not 0.0 < m.tile_waste_budget <= 1.0:
        msgs.append(
            f"model.tile_waste_budget must be in (0, 1], got "
            f"{m.tile_waste_budget} — build_supports can never accept a "
            "plan under it"
        )
        return msgs
    sizes = (
        list(n_nodes) if isinstance(n_nodes, (list, tuple)) else [n_nodes]
    )
    for city, n in enumerate(sizes):
        padded = _ceil_to(max(int(n), 1), m.tile_size)
        waste = 1.0 - n / padded
        if waste > m.tile_waste_budget:
            msgs.append(
                f"city {city}: N={n} pads to {padded} on the "
                f"tile_size={m.tile_size} grid — {waste:.3f} of every "
                "stored block row is padding, already past "
                f"tile_waste_budget={m.tile_waste_budget}; build_supports "
                "is guaranteed to raise (shrink the tile or raise the "
                "budget)"
            )
    est = tiled_spmm_vmem_estimate(m.tile_size)
    from stmgcn_tpu.analysis.pallas_check import VMEM_BUDGET_BYTES

    if est > VMEM_BUDGET_BYTES:
        msgs.append(
            f"tile_size={m.tile_size}: the tiled SpMM's streamed blocks "
            f"estimate {est / (1 << 20):.2f} MiB of VMEM "
            f"(calibrated model, tm={_TM_WORST} worst case) against the "
            f"{VMEM_BUDGET_BYTES >> 20} MiB/core budget — Mosaic aborts "
            "at this tile; 512 is the largest viable power of two"
        )
    return msgs


def _city_nodes(cfg) -> List[int]:
    d = cfg.data
    cols = d.cols
    if d.city_rows is not None:
        return [r * (cols if cols is not None else r) for r in d.city_rows]
    return [d.rows * (cols if cols is not None else d.rows)]


def check_tile_plan(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate every preset's tiled-support plan (no-op for untiled
    presets). ``configs`` is ``(name, ExperimentConfig)`` pairs; default
    is every registered preset. Pure config math — safe without a JAX
    backend."""
    from stmgcn_tpu.config import PRESETS

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="tile-plan",
                path=f"<contract:tile-plan:{name}>",
                line=0,
                message=f"{name}: {message}",
                severity=RULES["tile-plan"].severity,
            )
        )

    for name, cfg in configs:
        if not getattr(cfg.model, "tiled", False):
            continue
        if cfg.mesh.n_devices > 1:
            emit(
                name,
                f"model.tiled on a {cfg.mesh.n_devices}-device mesh — "
                "tiled plans are single-device artifacts and "
                "route_supports rejects the combination",
            )
        for msg in tile_plan_violations(cfg.model, _city_nodes(cfg)):
            emit(name, msg)
    return findings
