"""Pass 2f: resident-memory contracts — static data-residency math.

The trainer's resident data placement keeps training data in device HBM
for the whole run; whether a preset *fits* is pure config arithmetic,
the same way the collective-shape pass re-derives ppermute operands.
Two representations exist (``train/trainer.py``):

- **window-free** (default): the raw normalized ``(T, N, C)`` series per
  city plus int32 target vectors — one copy of every timestep;
- **materialized** windows: ``(S, seq_len, N, C)`` sample arrays — a
  ~``seq_len``x copy, since consecutive windows overlap almost entirely.

This pass estimates both footprints per preset from the config alone
(synthetic demand is float32 with one channel; data arrays stay float32
regardless of the model's compute dtype) and flags configurations whose
*requested* residency cannot hold: ``data_placement="resident"`` with a
multi-device mesh (the trainer raises at construction) or with a
footprint beyond the per-core budget (the conservative
``Trainer.RESIDENT_CAP_BYTES`` floor — devices that report more memory
only relax this at runtime). ``"auto"`` placement never errors here: it
degrades to streaming by design. No data build, no trace.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_resident_memory", "estimate_resident_bytes"]

#: synthetic demand channels (stmgcn_tpu/data/synthetic.py emits one) and
#: the pipeline's storage dtype (normalization casts to float32)
_CHANNELS = 1
_ITEMSIZE = 4


def estimate_resident_bytes(cfg) -> dict:
    """Both resident footprints for a config, in bytes.

    Returns ``{"series_bytes", "materialized_bytes", "ratio"}`` summed
    over cities: the window-free payload (series + int32 targets +
    offset table) vs the materialized ``(x, y)`` window arrays — exactly
    the arithmetic behind ``DemandDataset.resident_nbytes`` / ``nbytes``,
    re-derived from config fields so no dataset is built.
    """
    from stmgcn_tpu.data.windowing import WindowSpec

    d = cfg.data
    spec = WindowSpec(
        d.serial_len, d.daily_len, d.weekly_len, d.day_timesteps,
        horizon=d.horizon,
    )
    n_cities = max(1, d.n_cities)
    cols = d.cols if d.cols is not None else d.rows
    if d.city_rows is not None:
        nodes = [r * r for r in d.city_rows]
    else:
        nodes = [d.rows * cols] * n_cities
    if d.city_timesteps is not None:
        steps = list(d.city_timesteps)
    else:
        steps = [d.n_timesteps] * n_cities

    series = materialized = targets = 0
    for n, t in zip(nodes, steps):
        s = max(0, spec.n_samples(t))
        series += t * n * _CHANNELS * _ITEMSIZE
        targets += 4 * s
        materialized += (
            s * (spec.seq_len + spec.horizon) * n * _CHANNELS * _ITEMSIZE
        )
    series_total = series + targets + 4 * spec.seq_len
    return {
        "series_bytes": series_total,
        "materialized_bytes": materialized,
        "ratio": materialized / series_total if series_total else 0.0,
    }


def check_resident_memory(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
    budget_bytes: Optional[int] = None,
) -> List[Finding]:
    """Validate requested data residency against the per-core budget.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset. Pure config math — safe without a JAX backend.
    """
    from stmgcn_tpu.config import PRESETS
    from stmgcn_tpu.train.trainer import Trainer

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]
    if budget_bytes is None:
        budget_bytes = Trainer.RESIDENT_CAP_BYTES

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="resident-memory",
                path=f"<contract:resident:{name}>",
                line=0,
                message=message,
                severity=RULES["resident-memory"].severity,
            )
        )

    for name, cfg in configs:
        if cfg.train.data_placement != "resident":
            # "auto" degrades to streaming when oversized; "stream" never
            # holds data resident — nothing can fail at runtime
            continue
        if cfg.mesh.n_devices > 1 and cfg.train.window_free is False:
            # mesh residency composes ONLY through the window-free gather
            # (series region-sharded, index blocks dp-sharded — the
            # composed multi-chip fast path); materialized windows on a
            # mesh are rejected by the trainer
            emit(
                name,
                f"{name}: data_placement='resident' with a "
                f"{cfg.mesh.n_devices}-device mesh and window_free=False "
                "— the trainer rejects mesh-resident materialized windows "
                "(residency composes only through the window-free "
                "gather); drop window_free=False or stream batches",
            )
            continue
        est = estimate_resident_bytes(cfg)
        window_free = (
            cfg.train.window_free is not False and not cfg.data.hetero
        )
        resident = (
            est["series_bytes"] if window_free else est["materialized_bytes"]
        )
        kind = "window-free series" if window_free else "materialized windows"
        if resident > budget_bytes:
            hint = (
                " (the materialized fallback is forced: window_free=False/"
                "hetero — the window-free series would be "
                f"{est['series_bytes']:,} bytes)"
                if not window_free and est["series_bytes"] <= budget_bytes
                else ""
            )
            emit(
                name,
                f"{name}: resident data ({kind}) needs {resident:,} bytes "
                f"but the per-core budget is {budget_bytes:,} — the run "
                f"OOMs at the first epoch{hint}; use data_placement="
                "'auto'/'stream' or shrink the series",
            )
    return findings
