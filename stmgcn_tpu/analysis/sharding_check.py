"""Pass 2b: static PartitionSpec validation.

Two checks over the sharding layer:

- **partition-axis-name** — every *string-literal* axis name inside a
  ``PartitionSpec(...)`` / ``P(...)`` call in the package must be one of
  the mesh axes this repo ever constructs (``dp``, ``region``, ``branch``
  — :func:`stmgcn_tpu.parallel.mesh.build_mesh`). A typo'd axis name
  (``"regoin"``) passes Python, passes single-device tests (specs are
  inert off-mesh), and only explodes at ``device_put`` on real hardware.
  Names held in variables are out of static reach and are skipped — the
  placement runtime raises on those.
- **partition-rank** — the :class:`~stmgcn_tpu.parallel.placement
  .MeshPlacement` table's specs must fit the documented operand ranks
  (a spec longer than its operand's ndim raises at placement time, on
  device, at full scale).
"""

from __future__ import annotations

import ast
import os
from pathlib import Path
from typing import List, Optional

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["MESH_AXES", "check_partition_specs"]

#: the only axis names any mesh in this repo constructs
#: (stmgcn_tpu/parallel/mesh.py: build_mesh)
MESH_AXES = frozenset({"dp", "region", "branch"})

#: array-kind -> max operand rank for the MeshPlacement.SPECS table
#: (module docstring of stmgcn_tpu/parallel/placement.py)
_KIND_RANKS = {"supports": 4, "x": 4, "y": 4, "mask": 2, "state": 0}


def _spec_aliases(tree: ast.Module) -> set:
    """Local names bound to jax.sharding.PartitionSpec in this module."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
            "jax.sharding", "jax.experimental.pjit", "jax.interpreters.pxla"
        ):
            for a in node.names:
                if a.name == "PartitionSpec":
                    names.add(a.asname or a.name)
    return names


def _literal_axes(arg: ast.AST):
    """String-literal axis names in one P() argument (handles tuples)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        yield arg.value, arg
    elif isinstance(arg, (ast.Tuple, ast.List)):
        for elt in arg.elts:
            yield from _literal_axes(elt)


def _check_file(path: Path, rel: str) -> List[Finding]:
    findings: List[Finding] = []
    tree = ast.parse(path.read_text())
    aliases = _spec_aliases(tree)
    if not aliases:
        return findings
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in aliases
        ):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for name, src in _literal_axes(arg):
                if name not in MESH_AXES:
                    findings.append(
                        Finding(
                            rule="partition-axis-name",
                            path=rel,
                            line=src.lineno,
                            col=src.col_offset + 1,
                            message=(
                                f"PartitionSpec axis {name!r} is not a mesh "
                                f"axis this repo builds ({sorted(MESH_AXES)})"
                            ),
                            severity=RULES["partition-axis-name"].severity,
                        )
                    )
    return findings


def check_partition_specs(root: Optional[str] = None) -> List[Finding]:
    """Run both sharding checks; ``root`` defaults to the package dir."""
    if root is None:
        import stmgcn_tpu

        root = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
    findings: List[Finding] = []
    cwd = os.getcwd()
    for f in sorted(Path(root).rglob("*.py")):
        rel = os.path.relpath(f, cwd)
        rel = f.as_posix() if rel.startswith("..") else Path(rel).as_posix()
        findings.extend(_check_file(f, rel))

    # runtime rank validation of the placement table (no mesh needed:
    # PartitionSpec length is static)
    from stmgcn_tpu.parallel.placement import MeshPlacement

    for kind, spec in MeshPlacement.SPECS.items():
        max_rank = _KIND_RANKS.get(kind)
        if max_rank is not None and len(spec) > max_rank:
            findings.append(
                Finding(
                    rule="partition-rank",
                    path="stmgcn_tpu/parallel/placement.py",
                    line=0,
                    message=(
                        f"SPECS[{kind!r}] has rank {len(spec)} > documented "
                        f"operand rank {max_rank}"
                    ),
                    severity=RULES["partition-rank"].severity,
                )
            )
        for ax in spec:
            for name in (ax if isinstance(ax, tuple) else (ax,)):
                if name is not None and name not in MESH_AXES:
                    findings.append(
                        Finding(
                            rule="partition-axis-name",
                            path="stmgcn_tpu/parallel/placement.py",
                            line=0,
                            message=(
                                f"SPECS[{kind!r}] names unknown mesh axis "
                                f"{name!r}"
                            ),
                            severity=RULES["partition-axis-name"].severity,
                        )
                    )
    return findings
