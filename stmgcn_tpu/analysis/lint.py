"""Pass 1: the AST linter.

Per-module analysis in two sweeps. Sweep one builds a module index —
import aliases (so ``jnp.any`` resolves to ``jax.numpy.any`` whatever the
alias), every function/method definition, and the *jit-reachability* seed
set: functions wrapped by a tracing transform (``jax.jit`` / ``shard_map``
/ ``vmap`` / ``grad`` / ``lax.scan`` bodies, as decorators or call
arguments) plus methods of ``flax`` ``nn.Module`` subclasses (flax applies
run under trace). Reachability then propagates through same-module calls
by name. Sweep two walks each function and emits findings; rules that
only make sense under trace (``host-sync-in-jit``, ``traced-control-flow``)
fire only inside reachable functions, which is what keeps host-side
pre-processing (support building, metrics, checkpointing) out of scope.

Reachability *propagation* is per-module here; whole-program mode
(:func:`lint_package` with ``whole_program=True``, the default) injects
extra reachable functions computed by :mod:`.program_db`'s global call
graph — but only through statically resolved imports, never dynamic
dispatch, so the promotion adds reachability without adding the false
positives that make a linter get turned off. Findings in functions that
are only *globally* reachable carry the root→function call chain. The
contract pass (:mod:`.jaxpr_check`) still covers the hot path by
tracing it for real.

Suppression: ``# stmgcn: ignore[rule-id]`` (or bare ``# stmgcn: ignore``)
on the finding's line — the *reported* line, which for a cross-module
finding is where the offending call sits, not where the jit root lives.
``include_suppressed=True`` keeps suppressed findings in the output
(marked, never counted) for audit via ``--format json
--include-suppressed``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import JAX_COMPAT_ATTRS, JAX_COMPAT_IMPORTS, RULES

__all__ = ["lint_package", "lint_paths", "lint_source"]

#: transforms whose function argument executes under a JAX trace
_TRACER_WRAPPERS = {
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad", "shard_map",
    "checkify", "remat", "checkpoint", "scan", "while_loop", "cond",
    "fori_loop", "switch", "associative_scan", "custom_vjp", "custom_jvp",
}

_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic"}

_SUPPRESS_RE = re.compile(r"#\s*stmgcn:\s*ignore(?:\[([\w\-, ]+)\])?")


def _suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """``line -> suppressed rule ids`` (``None`` = every rule)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = (
                {r.strip() for r in m.group(1).split(",")} if m.group(1) else None
            )
    return out


class _ModuleIndex(ast.NodeVisitor):
    """Sweep one: aliases, function defs, jit-root seeds, call edges."""

    def __init__(self):
        self.aliases: Dict[str, str] = {}  # local name -> dotted module
        self.funcs: Dict[str, ast.AST] = {}  # simple name -> def node
        self.calls: Dict[str, Set[str]] = {}  # caller name -> callee names
        self.roots: Set[str] = set()
        self._stack: List[str] = []
        self._class_is_flax: List[bool] = []

    # -- imports ---------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for a in node.names:
            if node.module:
                self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        self.generic_visit(node)

    # -- resolution helpers ----------------------------------------------
    def dotted(self, node: ast.AST) -> Optional[str]:
        """Resolve an attribute chain to a dotted path through the alias
        map (``jnp.any`` -> ``jax.numpy.any``); None for non-name roots."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        return ".".join([root] + list(reversed(parts)))

    # -- defs --------------------------------------------------------------
    def _handle_func(self, node) -> None:
        name = node.name
        self.funcs.setdefault(name, node)
        self.calls.setdefault(name, set())
        if self._class_is_flax and self._class_is_flax[-1]:
            self.roots.add(name)
        for dec in node.decorator_list:
            for cand in self._wrapper_names(dec):
                self.roots.add(name)
                break
        self._stack.append(name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _handle_func
    visit_AsyncFunctionDef = _handle_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        is_flax = any(
            (self.dotted(b) or "").split(".")[-1] == "Module"
            for b in node.bases
        )
        self._class_is_flax.append(is_flax)
        self.generic_visit(node)
        self._class_is_flax.pop()

    def _wrapper_names(self, node: ast.AST) -> List[str]:
        """Tracer-wrapper hits inside a decorator / call-func expression."""
        hits: List[str] = []
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                d = self.dotted(sub)
                if d and d.split(".")[-1] in _TRACER_WRAPPERS:
                    hits.append(d)
        return hits

    # -- call edges + root seeding ----------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        callee = None
        if isinstance(node.func, ast.Name):
            callee = node.func.id
        elif isinstance(node.func, ast.Attribute):
            callee = node.func.attr  # self.foo() / mod.foo(): match by name
        if self._stack and callee:
            self.calls[self._stack[-1]].add(callee)
        # a local function handed to a tracing transform becomes a root
        d = self.dotted(node.func)
        if d and d.split(".")[-1] in _TRACER_WRAPPERS:
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in self.funcs:
                        self.roots.add(sub.id)
        self.generic_visit(node)

    def reachable(self) -> Set[str]:
        seen = set(self.roots & set(self.funcs))
        frontier = list(seen)
        while frontier:
            fn = frontier.pop()
            for callee in self.calls.get(fn, ()):
                if callee in self.funcs and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen


class _Linter:
    def __init__(
        self,
        tree: ast.Module,
        path: str,
        extra_reachable: Optional[Dict[str, tuple]] = None,
    ):
        self.path = path
        self.findings: List[Finding] = []
        self.index = _ModuleIndex()
        self.index.visit(tree)
        # late seeding: functions defined after the call that jits them
        self.reachable = self.index.reachable()
        # whole-program promotion: functions reachable only through the
        # global call graph, each carrying its root->function chain
        self.chains: Dict[str, tuple] = dict(extra_reachable or {})
        self.reachable |= set(self.chains) & set(self.index.funcs)
        self.tree = tree

    def _emit(
        self, rule: str, node: ast.AST, message: str, chain: tuple = ()
    ) -> None:
        self.findings.append(
            Finding(
                rule=rule,
                path=self.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", -1) + 1,
                message=message,
                severity=RULES[rule].severity,
                chain=chain,
            )
        )

    def run(self) -> List[Finding]:
        self._check_imports()
        for name, fn in self.index.funcs.items():
            self._check_timing_span(fn)
            if name in self.reachable:
                self._check_traced_body(fn)
        self._check_compat_attrs()
        self._check_donate()
        self._check_recompile_hazard()
        self._check_closure_identity()
        return self.findings

    # -- jax-compat-import -------------------------------------------------
    def _check_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    why = JAX_COMPAT_IMPORTS.get(
                        (node.module, a.name)
                    ) or JAX_COMPAT_IMPORTS.get((node.module, "*"))
                    if why:
                        self._emit(
                            "jax-compat-import", node,
                            f"`from {node.module} import {a.name}`: {why}",
                        )
            elif isinstance(node, ast.Import):
                for a in node.names:
                    mod = a.name
                    why = JAX_COMPAT_IMPORTS.get((mod, "*"))
                    if why is None and "." in mod:
                        parent, _, leaf = mod.rpartition(".")
                        why = JAX_COMPAT_IMPORTS.get((parent, leaf))
                    if why:
                        self._emit(
                            "jax-compat-import", node, f"`import {mod}`: {why}"
                        )

    def _check_compat_attrs(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                d = self.index.dotted(node.func)
                if d in JAX_COMPAT_ATTRS:
                    self._emit(
                        "jax-compat-import", node,
                        f"`{d}(...)`: {JAX_COMPAT_ATTRS[d]}",
                    )

    # -- host-sync-in-jit / traced-control-flow ---------------------------
    def _is_host_sync(self, node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args:
                return ".item() readback"
            if f.attr == "block_until_ready":
                return ".block_until_ready() sync"
            d = self.index.dotted(f)
            if d in ("jax.device_get", "jax.block_until_ready"):
                return f"{d} sync"
            if d is not None and d.startswith("numpy.") and f.attr == "asarray":
                return "np.asarray device->host copy"
        elif isinstance(f, ast.Name) and f.id == "float":
            if len(node.args) == 1 and not isinstance(node.args[0], ast.Constant):
                return "float() readback of a computed value"
        return None

    def _check_traced_body(self, fn) -> None:
        chain = self.chains.get(fn.name, ())
        via = " (cross-module)" if chain else ""
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                why = self._is_host_sync(node)
                if why:
                    self._emit(
                        "host-sync-in-jit", node,
                        f"{why} inside jit-reachable `{fn.name}`{via}",
                        chain=chain,
                    )
            elif isinstance(node, (ast.If, ast.While)):
                traced = self._traced_test(node.test)
                if traced:
                    kw = "if" if isinstance(node, ast.If) else "while"
                    self._emit(
                        "traced-control-flow", node,
                        f"Python `{kw}` on traced value ({traced}) in "
                        f"jit-reachable `{fn.name}`{via} — use jnp.where / "
                        "lax.cond / lax.while_loop",
                        chain=chain,
                    )

    def _traced_test(self, test: ast.AST) -> Optional[str]:
        """A test expression that evaluates a traced array to a bool."""
        for sub in ast.walk(test):
            if not isinstance(sub, ast.Call):
                continue
            d = self.index.dotted(sub.func)
            if d and (d.startswith("jax.numpy.") or d.startswith("jax.lax.")):
                return d
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("any", "all")
                and not (d and d.startswith("numpy."))
            ):
                return f".{sub.func.attr}()"
        return None

    # -- unfenced-timing ---------------------------------------------------
    def _check_timing_span(self, fn) -> None:
        starts: Set[str] = set()
        closing: List[ast.AST] = []
        dispatch = fence = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                d = self.index.dotted(node.value.func)
                if d in _TIME_CALLS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            starts.add(t.id)
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if (
                    isinstance(node.left, ast.Call)
                    and self.index.dotted(node.left.func) in _TIME_CALLS
                    and isinstance(node.right, ast.Name)
                    and node.right.id in starts
                ):
                    closing.append(node)
            if isinstance(node, ast.Call):
                if self._is_host_sync(node) is not None:
                    fence = True
                f = node.func
                name = (
                    f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute) else ""
                )
                if name == "fence":
                    fence = True
                if (
                    name in ("apply", "step")
                    or name.endswith("_step")
                    or name in self.reachable
                ):
                    dispatch = True
        if closing and dispatch and not fence:
            self._emit(
                "unfenced-timing", closing[0],
                f"timing span in `{fn.name}` brackets device dispatch with "
                "no readback fence — times dispatch, not compute; fence the "
                "result (stmgcn_tpu.utils.profiling.fence) or use "
                "time_chained",
            )

    # -- missing-donate ----------------------------------------------------
    _DONATE_MSG = (
        "jax.jit of a train step without donate_argnums — params/opt-state "
        "buffers are copied, not reused, every step"
    )

    def _is_jit(self, node: ast.AST) -> bool:
        d = self.index.dotted(node)
        return bool(d) and d.split(".")[-1] in ("jit", "pjit")

    def _check_donate(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and self._is_jit(node.func):
                if not node.args:
                    continue
                names: List[str] = [
                    sub.id
                    for sub in ast.walk(node.args[0])
                    if isinstance(sub, ast.Name)
                ]
                if not any("train_step" in n for n in names):
                    continue
                kwargs = {kw.arg for kw in node.keywords}
                if not kwargs & {"donate_argnums", "donate_argnames"}:
                    self._emit("missing-donate", node, self._DONATE_MSG)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if "train_step" not in node.name:
                    continue
                for dec in node.decorator_list:
                    if self._is_jit(dec):
                        # bare @jax.jit cannot carry donate_argnums at all
                        self._emit("missing-donate", dec, self._DONATE_MSG)
                    elif isinstance(dec, ast.Call) and any(
                        self._is_jit(a) for a in [dec.func] + list(dec.args)
                    ):
                        kwargs = {kw.arg for kw in dec.keywords}
                        if not kwargs & {"donate_argnums", "donate_argnames"}:
                            self._emit("missing-donate", dec, self._DONATE_MSG)

    # -- recompile-hazard --------------------------------------------------
    #: AST nodes that build a brand-new object on every evaluation — as a
    #: static arg they miss (lambda: fresh identity) or break (list/dict/
    #: set: unhashable) the jit cache on every call
    _FRESH_NODES = (
        ast.Lambda, ast.List, ast.Dict, ast.Set,
        ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
    )

    @staticmethod
    def _const_values(node: ast.AST, typ) -> Set:
        return {
            sub.value
            for sub in ast.walk(node)
            if isinstance(sub, ast.Constant) and isinstance(sub.value, typ)
        }

    def _static_jit_map(self) -> Dict[str, tuple]:
        """``wrapper name -> (static argnums, static argnames)`` for every
        ``g = jax.jit(f, static_argnums=.../static_argnames=...)``."""
        static: Dict[str, tuple] = {}
        for node in ast.walk(self.tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and self._is_jit(node.value.func)
            ):
                continue
            nums: Set[int] = set()
            names: Set[str] = set()
            for kw in node.value.keywords:
                if kw.arg == "static_argnums":
                    nums |= self._const_values(kw.value, int)
                elif kw.arg == "static_argnames":
                    names |= self._const_values(kw.value, str)
            if not (nums or names):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    static[t.id] = (nums, names)
        return static

    def _check_recompile_hazard(self) -> None:
        # sweep A: ``jax.jit(f)(...)`` invoked in place — a fresh wrapper
        # (with an empty trace cache) every evaluation. Binding the wrapper
        # (``g = jax.jit(f)``, the factory pattern) is the fix and is not
        # flagged.
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Call)
                and self._is_jit(node.func.func)
            ):
                self._emit(
                    "recompile-hazard", node,
                    "jax.jit(...) invoked in place — every evaluation builds "
                    "a fresh wrapper with an empty trace cache; bind the "
                    "jitted function once and reuse it",
                )
        # sweep B: fresh/unhashable literals handed to a jitted wrapper's
        # static positions — flag calls of ``g = jax.jit(f, static_*=...)``
        # that pass a per-call-fresh object there.
        static = self._static_jit_map()
        if not static:
            return

        def flag(call: ast.Call, value: ast.AST, where: str) -> None:
            kind = type(value).__name__.lower()
            self._emit(
                "recompile-hazard", value,
                f"fresh {kind} passed at static {where} of jitted "
                f"`{call.func.id}` — static args are cached by value/"
                "identity, so a per-call object retraces (lambda) or raises "
                "TypeError: unhashable (list/dict/set) every call; hoist it "
                "to a stable binding",
            )

        for node in ast.walk(self.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in static
            ):
                continue
            nums, names = static[node.func.id]
            for i, arg in enumerate(node.args):
                if i in nums and isinstance(arg, self._FRESH_NODES):
                    flag(node, arg, f"position {i}")
            for kw in node.keywords:
                if kw.arg in names and isinstance(kw.value, self._FRESH_NODES):
                    flag(node, kw.value, f"argname `{kw.arg}`")

    # -- closure-identity --------------------------------------------------
    def _fresh_callable(self, arg: ast.AST, nested_defs: Set[str]):
        """Why ``arg`` is a per-call-fresh callable identity, or None.

        The literal cases (lambda/list/dict) belong to recompile-hazard;
        this rule covers the identities the literal sweep can't see:
        ``functools.partial(...)`` builds a new object per evaluation,
        ``obj.method`` binds a fresh method object per attribute access
        (only flagged when the attribute names a def in this module —
        plain value attributes stay out of scope), and a def nested in
        the calling function is a fresh closure per outer call.
        """
        if isinstance(arg, ast.Call):
            d = self.index.dotted(arg.func)
            if d and d.split(".")[-1] == "partial" and (
                d.startswith("functools.") or d == "partial"
            ):
                return "functools.partial(...) — a new partial object"
        if isinstance(arg, ast.Attribute) and arg.attr in self.index.funcs:
            return (
                f"bound method `.{arg.attr}` — a fresh method object per "
                "attribute access"
            )
        if isinstance(arg, ast.Name) and arg.id in nested_defs:
            return (
                f"nested def `{arg.id}` — a fresh closure per call of the "
                "enclosing function"
            )
        return None

    def _check_closure_identity(self) -> None:
        # sweep A: fresh callable identities at static positions of mapped
        # jitted wrappers (the identities recompile-hazard's literal-only
        # sweep misses)
        static = self._static_jit_map()

        def check_call(call: ast.Call, nested: Set[str]) -> None:
            if not (
                isinstance(call.func, ast.Name) and call.func.id in static
            ):
                return
            nums, names = static[call.func.id]

            def flag(value: ast.AST, why: str, where: str) -> None:
                self._emit(
                    "closure-identity", value,
                    f"{why} at static {where} of jitted `{call.func.id}` — "
                    "every call presents a new identity to the trace cache "
                    "and silently retraces; hoist it to a stable binding",
                )

            for i, arg in enumerate(call.args):
                if i in nums:
                    why = self._fresh_callable(arg, nested)
                    if why:
                        flag(arg, why, f"position {i}")
            for kw in call.keywords:
                if kw.arg in names:
                    why = self._fresh_callable(kw.value, nested)
                    if why:
                        flag(kw.value, why, f"argname `{kw.arg}`")

        if static:
            seen_calls: Set[int] = set()
            for outer in ast.walk(self.tree):
                if not isinstance(
                    outer, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                nested = {
                    d.name
                    for d in ast.walk(outer)
                    if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and d is not outer
                }
                for call in ast.walk(outer):
                    if isinstance(call, ast.Call):
                        seen_calls.add(id(call))
                        check_call(call, nested)
            for call in ast.walk(self.tree):
                if isinstance(call, ast.Call) and id(call) not in seen_calls:
                    check_call(call, set())  # module scope: no nested defs

        # sweep B: ``g = jax.jit(f)`` bound inside a loop body — a fresh
        # wrapper (empty trace cache) every iteration. The AOT idiom
        # ``jax.jit(f).lower(...).compile()`` in a loop is deliberately
        # exempt: the value assigned there is the *compiled* program, and
        # tracing per shape bucket is the point (serving/engine.py).
        flagged: Set[int] = set()
        for loop in ast.walk(self.tree):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            for node in ast.walk(loop):
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and self._is_jit(node.value.func)
                    and id(node) not in flagged
                ):
                    flagged.add(id(node))
                    self._emit(
                        "closure-identity", node,
                        "jax.jit bound inside a loop body — every iteration "
                        "builds a fresh wrapper with an empty trace cache; "
                        "bind once outside the loop (AOT per-shape "
                        "compilation via .lower().compile() is the "
                        "loop-safe form)",
                    )


def lint_source(
    source: str,
    path: str = "<string>",
    *,
    extra_reachable: Optional[Dict[str, tuple]] = None,
    include_suppressed: bool = False,
) -> List[Finding]:
    """Lint one module's source text.

    ``extra_reachable`` maps function names to cross-module call chains
    (whole-program promotion); ``include_suppressed`` keeps suppressed
    findings, marked, instead of dropping them.
    """
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [
            Finding(
                rule="jax-compat-import", path=path, line=e.lineno or 0,
                message=f"unparseable module: {e.msg}", severity="error",
            )
        ]
    findings = _Linter(tree, path, extra_reachable=extra_reachable).run()
    suppress = _suppressions(source)
    out = []
    for f in findings:
        rules = suppress.get(f.line, ...)
        live = rules is ... or (rules is not None and f.rule not in rules)
        if live:
            out.append(f)
        elif include_suppressed:
            out.append(dataclasses.replace(f, suppressed=True))
    return out


def lint_paths(
    paths: Iterable, *, include_suppressed: bool = False
) -> List[Finding]:
    """Lint ``.py`` files / directory trees; paths become repo-relative."""
    findings: List[Finding] = []
    cwd = os.getcwd()
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    for f in files:
        rel = os.path.relpath(f, cwd)
        rel = f.as_posix() if rel.startswith("..") else Path(rel).as_posix()
        findings.extend(
            lint_source(f.read_text(), rel,
                        include_suppressed=include_suppressed)
        )
    return findings


def lint_package(
    root: Optional[str] = None,
    *,
    whole_program: bool = True,
    include_suppressed: bool = False,
) -> List[Finding]:
    """Lint the shipped ``stmgcn_tpu`` package (the tier-1 contract).

    ``whole_program=True`` (the default) first builds the repo-wide
    program database (:mod:`.program_db`, with type-informed dispatch
    resolution on) and promotes functions that are jit-reachable only
    through resolved cross-module calls; their findings carry the
    root→function chain. The same database then drives the four
    concurrency rules (:mod:`.concurrency_check`) repo-wide.
    ``whole_program=False`` is the per-module escape hatch
    (``stmgcn lint --no-whole-program``) — no program db, no
    concurrency pass.
    """
    if root is None:
        import stmgcn_tpu

        root = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
    if not whole_program:
        return lint_paths([root], include_suppressed=include_suppressed)

    from stmgcn_tpu.analysis.concurrency_check import check_concurrency
    from stmgcn_tpu.analysis.program_db import ProgramDB

    db = ProgramDB.from_root(root, type_informed=True)
    findings: List[Finding] = []
    for name, entry in sorted(db.modules.items()):
        findings.extend(
            lint_source(
                entry.source,
                entry.path,
                extra_reachable=db.module_extras(name),
                include_suppressed=include_suppressed,
            )
        )
    # the concurrency rules run off the same typed program database
    findings.extend(
        check_concurrency(db, include_suppressed=include_suppressed)
    )
    # files the parser rejected never made it into the DB — lint them
    # per-module so the unparseable-module finding still surfaces
    indexed = {e.path for e in db.modules.values()}
    cwd = os.getcwd()
    for f in sorted(Path(root).rglob("*.py")):
        rel = os.path.relpath(f, cwd)
        rel = f.as_posix() if rel.startswith("..") else Path(rel).as_posix()
        if rel not in indexed:
            findings.extend(
                lint_source(f.read_text(), rel,
                            include_suppressed=include_suppressed)
            )
    return findings
