"""Pass 2h: obs-overhead contracts — observability config budget math.

The observability layer must never become the thing it measures: a
preset that turns span tracing on with an unbounded ring, or sizes a
histogram reservoir past the documented budget, regresses a long-lived
process in exactly the way the old unbounded ``EngineStats`` lists did.
The budgets (``config.OBS_RING_BUDGET`` / ``OBS_RESERVOIR_BUDGET``) and
the per-config arithmetic (``ObsConfig.violations()``) live next to the
other config contracts; this pass evaluates them per preset. Pure
config math — no tracer, no JAX.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_obs_overhead"]


def check_obs_overhead(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate every preset's observability knobs against the budgets.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset. One finding per violation string.
    """
    from stmgcn_tpu.config import PRESETS

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="obs-overhead",
                path=f"<contract:obs:{name}>",
                line=0,
                message=message,
                severity=RULES["obs-overhead"].severity,
            )
        )

    for name, cfg in configs:
        obs = getattr(cfg, "obs", None)
        if obs is None:
            continue
        for violation in obs.violations():
            emit(name, f"{name}: {violation}")
    return findings
