"""Pass 2j: continual-loop contracts — closed-loop config math.

The continual loop (:mod:`stmgcn_tpu.train.continual`) is the one
subsystem designed to run *unattended*: a config mistake does not fail
a job, it degrades a service — a ring sized past the per-core resident
budget OOMs serving, a retrain cadence the measured superstep cannot
sustain starves the dispatch path, a drift-only trigger with no
baseline never retrains at all, and a malformed promotion gate either
rejects every candidate or (worse) promotes anything. The per-config
arithmetic is ``ContinualConfig.violations()``; this pass evaluates it
per preset with the cross-cutting inputs wired in: row bytes from the
preset's data shape, the budget from ``Trainer.RESIDENT_CAP_BYTES``
(imported lazily, same as the ``resident-memory`` pass), and the
sibling health/data configs for the cross-field checks. Pure config
math — no JAX, no trainer.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_continual_config"]

#: demand channels and storage dtype — lockstep with resident_check.py
_CHANNELS = 1
_ITEMSIZE = 4


def check_continual_config(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
    budget_bytes: Optional[int] = None,
) -> List[Finding]:
    """Validate every preset's continual-loop knobs.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset. One finding per violation string.
    """
    from stmgcn_tpu.config import PRESETS

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]
    if budget_bytes is None:
        # lazy: the check must not pull the trainer (and jax) at import
        from stmgcn_tpu.train.trainer import Trainer

        budget_bytes = Trainer.RESIDENT_CAP_BYTES

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="continual-config",
                path=f"<contract:continual:{name}>",
                line=0,
                message=message,
                severity=RULES["continual-config"].severity,
            )
        )

    for name, cfg in configs:
        cont = getattr(cfg, "continual", None)
        if cont is None:
            continue
        data = getattr(cfg, "data", None)
        row_bytes = None
        if data is not None:
            cols = data.cols if data.cols is not None else data.rows
            row_bytes = data.rows * cols * _CHANNELS * _ITEMSIZE
        for violation in cont.violations(
            row_bytes=row_bytes,
            budget_bytes=budget_bytes,
            health=getattr(cfg, "health", None),
            data=data,
        ):
            emit(name, f"{name}: {violation}")
    return findings
