"""Pass 2e: serving-bucket-shape contracts — static ladder math.

The serving engine compiles one AOT program per ``ServingConfig.buckets``
rung and pads every request batch up to its covering rung. A bad ladder
fails only at engine construction — i.e. at deploy time, on the serving
host. This pass re-derives the ladder contract from the config alone
(the same :meth:`~stmgcn_tpu.config.ServingConfig.violations` math the
engine enforces) and flags it at lint time instead: rungs must be
strictly increasing, the top rung must cover ``max_batch`` (batches
above it have no program), and no rung's worst-case padded waste — a
batch one row past the previous rung — may exceed ``max_pad_waste``.
Pure config math, safe without a JAX backend.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_serving_buckets"]


def check_serving_buckets(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate every preset's serving bucket ladder.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset.
    """
    from stmgcn_tpu.config import PRESETS

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]

    findings: List[Finding] = []
    for name, cfg in configs:
        serving = getattr(cfg, "serving", None)
        if serving is None:
            continue
        for message in serving.violations():
            findings.append(
                Finding(
                    rule="serving-bucket-shape",
                    path=f"<contract:serving:{name}>",
                    line=0,
                    message=f"{name}: {message}",
                    severity=RULES["serving-bucket-shape"].severity,
                )
            )
    return findings
