"""Pass 2e: serving config contracts — static ladder + SLO math.

The serving engine compiles one AOT program per ``ServingConfig.buckets``
rung and pads every request batch up to its covering rung; with the SLO
knobs set it also builds an admission controller in front of the queue.
A bad ladder or a self-contradictory SLO fails only at engine
construction — i.e. at deploy time, on the serving host. These passes
re-derive both contracts from the config alone (the same
:meth:`~stmgcn_tpu.config.ServingConfig.violations` math the engine
enforces) and flag them at lint time instead:

- ``serving-bucket-shape`` (:func:`check_serving_buckets`): rungs must
  be strictly increasing, the top rung must cover ``max_batch`` (batches
  above it have no program), and no rung's worst-case padded waste — a
  batch one row past the previous rung — may exceed ``max_pad_waste``.
- ``serving-slo`` (:func:`check_serving_slo`): ``deadline_ms`` must
  exceed the coalescing delay floor ``max_delay_ms`` (below it every
  coalesced request is shed by construction), ``queue_bound_rows`` must
  cover the top rung (a tighter bound can never fill a saturated
  dispatch), and ``degrade_rung`` must be a ladder rung under the
  "degrade" policy (no compiled program exists for anything else).

Pure config math, safe without a JAX backend.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_serving_buckets", "check_serving_slo"]


def _preset_configs():
    from stmgcn_tpu.config import PRESETS

    return [(name, build()) for name, build in PRESETS.items()]


def _check_configs(configs, rule: str, method: str) -> List[Finding]:
    findings: List[Finding] = []
    for name, cfg in configs:
        serving = getattr(cfg, "serving", None)
        if serving is None:
            continue
        for message in getattr(serving, method)():
            findings.append(
                Finding(
                    rule=rule,
                    path=f"<contract:serving:{name}>",
                    line=0,
                    message=f"{name}: {message}",
                    severity=RULES[rule].severity,
                )
            )
    return findings


def check_serving_buckets(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate every preset's serving bucket ladder.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset.
    """
    if configs is None:
        configs = _preset_configs()
    return _check_configs(configs, "serving-bucket-shape", "ladder_violations")


def check_serving_slo(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate every preset's SLO / admission-control knobs.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset.
    """
    if configs is None:
        configs = _preset_configs()
    return _check_configs(configs, "serving-slo", "slo_violations")
