"""Findings and report rendering for the analysis passes.

A :class:`Finding` is one rule violation at one source location; both
passes produce lists of them so the CLI, the tier-1 test, and any CI
gate consume one shape. ``render_json`` is the machine-readable contract
(``stmgcn lint --format json``): a stable top-level object with the rule
table version, counts, and per-finding records.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List

__all__ = ["Finding", "render_json", "render_sarif", "render_text"]

#: bumped when the JSON report shape or rule ids change incompatibly
#: (v2: whole-program lint — findings carry ``chain``/``suppressed``,
#: counts exclude suppressed findings; v3: concurrency rules —
#: unguarded-attr / lock-order-cycle / condvar-discipline /
#: thread-lifecycle run in lint_package's default whole-program mode,
#: chains may now be cross-method, not only jit-reachability)
REPORT_VERSION = 3


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``path`` is repo-relative where possible; ``line``/``col`` are
    1-based (col 0 for whole-file findings such as contract failures).
    ``chain`` is the cross-module jit-reachability call chain
    (``module:function`` qualnames, root first) when whole-program mode
    promoted the enclosing function — empty for per-module findings.
    ``suppressed`` findings survive only under ``--include-suppressed``
    and never gate (excluded from the error/warning counts).
    """

    rule: str
    path: str
    line: int
    message: str
    col: int = 0
    severity: str = "error"  # "error" gates; "warning" reports only
    chain: tuple = ()
    suppressed: bool = False

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["chain"] = list(self.chain)
        return d

    def __str__(self) -> str:
        mark = " (suppressed)" if self.suppressed else ""
        via = (
            f" [via {' -> '.join(self.chain)}]" if len(self.chain) > 1 else ""
        )
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule}: "
            f"{self.message}{via}{mark}"
        )


def render_text(findings: Iterable[Finding]) -> str:
    """Human-readable one-line-per-finding report, sorted by location."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    if not ordered:
        return "stmgcn lint: clean"
    lines: List[str] = [str(f) for f in ordered]
    live = [f for f in ordered if not f.suppressed]
    n_err = sum(1 for f in live if f.severity == "error")
    n_warn = len(live) - n_err
    tail = f"stmgcn lint: {n_err} error(s), {n_warn} warning(s)"
    n_sup = len(ordered) - len(live)
    if n_sup:
        tail += f", {n_sup} suppressed"
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings: Iterable[Finding]) -> str:
    """Machine-readable report (the CI contract). Suppressed findings
    (present only under ``--include-suppressed``) are listed but never
    counted — the counts are what gates."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    live = [f for f in ordered if not f.suppressed]
    payload = {
        "version": REPORT_VERSION,
        "errors": sum(1 for f in live if f.severity == "error"),
        "warnings": sum(1 for f in live if f.severity != "error"),
        "findings": [f.to_dict() for f in ordered],
    }
    return json.dumps(payload, indent=2)


def render_sarif(findings: Iterable[Finding]) -> str:
    """One SARIF 2.1.0 document (``stmgcn lint --format sarif``).

    The stdout contract is a *single* JSON document — one ``runs`` entry
    for the whole invocation, every rule that produced a finding listed
    in ``tool.driver.rules``, one ``result`` per finding. Contract-pass
    findings use their virtual ``<contract:...>`` paths verbatim as
    artifact URIs (they have no file), with the 1-based SARIF minimum
    ``startLine`` of 1 standing in for line 0. Suppressed findings carry
    a ``suppressions`` entry (``kind: inSource``) so uploaders hide them
    without losing the record — mirroring ``render_json``, where they
    are listed but never counted.
    """
    from stmgcn_tpu.analysis.rules import RULES

    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    rule_ids = sorted({f.rule for f in ordered})
    # both descriptions must be non-empty for every rule: shortDescription
    # is the registry summary (or the id for unregistered rules), and
    # fullDescription falls back to the summary when a rule carries no
    # long-form text — code-scanning uploads reject/blank-render empty
    # description objects
    rules = [
        {
            "id": rid,
            "shortDescription": {
                "text": (RULES[rid].summary if rid in RULES else rid) or rid
            },
            "fullDescription": {
                "text": (
                    (RULES[rid].description or RULES[rid].summary)
                    if rid in RULES else rid
                ) or rid
            },
            "defaultConfiguration": {
                "level": "error"
                if rid in RULES and RULES[rid].severity == "error"
                else "warning"
            },
        }
        for rid in rule_ids
    ]
    index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in ordered:
        res = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {
                            "startLine": max(1, f.line),
                            "startColumn": max(1, f.col),
                        },
                    }
                }
            ],
        }
        if f.chain:
            res["properties"] = {"chain": list(f.chain)}
        if f.suppressed:
            res["suppressions"] = [{"kind": "inSource"}]
        results.append(res)
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "stmgcn-lint",
                        "informationUri": (
                            "https://github.com/stmgcn-tpu/stmgcn-tpu"
                        ),
                        "version": str(REPORT_VERSION),
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)
