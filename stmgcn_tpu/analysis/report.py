"""Findings and report rendering for the analysis passes.

A :class:`Finding` is one rule violation at one source location; both
passes produce lists of them so the CLI, the tier-1 test, and any CI
gate consume one shape. ``render_json`` is the machine-readable contract
(``stmgcn lint --format json``): a stable top-level object with the rule
table version, counts, and per-finding records.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List

__all__ = ["Finding", "render_json", "render_text"]

#: bumped when the JSON report shape or rule ids change incompatibly
REPORT_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``path`` is repo-relative where possible; ``line``/``col`` are
    1-based (col 0 for whole-file findings such as contract failures).
    """

    rule: str
    path: str
    line: int
    message: str
    col: int = 0
    severity: str = "error"  # "error" gates; "warning" reports only

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


def render_text(findings: Iterable[Finding]) -> str:
    """Human-readable one-line-per-finding report, sorted by location."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    if not ordered:
        return "stmgcn lint: clean"
    lines: List[str] = [str(f) for f in ordered]
    n_err = sum(1 for f in ordered if f.severity == "error")
    n_warn = len(ordered) - n_err
    lines.append(f"stmgcn lint: {n_err} error(s), {n_warn} warning(s)")
    return "\n".join(lines)


def render_json(findings: Iterable[Finding]) -> str:
    """Machine-readable report (the CI contract)."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    payload = {
        "version": REPORT_VERSION,
        "errors": sum(1 for f in ordered if f.severity == "error"),
        "warnings": sum(1 for f in ordered if f.severity != "error"),
        "findings": [f.to_dict() for f in ordered],
    }
    return json.dumps(payload, indent=2)
