"""Findings and report rendering for the analysis passes.

A :class:`Finding` is one rule violation at one source location; both
passes produce lists of them so the CLI, the tier-1 test, and any CI
gate consume one shape. ``render_json`` is the machine-readable contract
(``stmgcn lint --format json``): a stable top-level object with the rule
table version, counts, and per-finding records.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List

__all__ = ["Finding", "render_json", "render_text"]

#: bumped when the JSON report shape or rule ids change incompatibly
#: (v2: whole-program lint — findings carry ``chain``/``suppressed``,
#: counts exclude suppressed findings; v3: concurrency rules —
#: unguarded-attr / lock-order-cycle / condvar-discipline /
#: thread-lifecycle run in lint_package's default whole-program mode,
#: chains may now be cross-method, not only jit-reachability)
REPORT_VERSION = 3


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``path`` is repo-relative where possible; ``line``/``col`` are
    1-based (col 0 for whole-file findings such as contract failures).
    ``chain`` is the cross-module jit-reachability call chain
    (``module:function`` qualnames, root first) when whole-program mode
    promoted the enclosing function — empty for per-module findings.
    ``suppressed`` findings survive only under ``--include-suppressed``
    and never gate (excluded from the error/warning counts).
    """

    rule: str
    path: str
    line: int
    message: str
    col: int = 0
    severity: str = "error"  # "error" gates; "warning" reports only
    chain: tuple = ()
    suppressed: bool = False

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["chain"] = list(self.chain)
        return d

    def __str__(self) -> str:
        mark = " (suppressed)" if self.suppressed else ""
        via = (
            f" [via {' -> '.join(self.chain)}]" if len(self.chain) > 1 else ""
        )
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule}: "
            f"{self.message}{via}{mark}"
        )


def render_text(findings: Iterable[Finding]) -> str:
    """Human-readable one-line-per-finding report, sorted by location."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    if not ordered:
        return "stmgcn lint: clean"
    lines: List[str] = [str(f) for f in ordered]
    live = [f for f in ordered if not f.suppressed]
    n_err = sum(1 for f in live if f.severity == "error")
    n_warn = len(live) - n_err
    tail = f"stmgcn lint: {n_err} error(s), {n_warn} warning(s)"
    n_sup = len(ordered) - len(live)
    if n_sup:
        tail += f", {n_sup} suppressed"
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings: Iterable[Finding]) -> str:
    """Machine-readable report (the CI contract). Suppressed findings
    (present only under ``--include-suppressed``) are listed but never
    counted — the counts are what gates."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    live = [f for f in ordered if not f.suppressed]
    payload = {
        "version": REPORT_VERSION,
        "errors": sum(1 for f in live if f.severity == "error"),
        "warnings": sum(1 for f in live if f.severity != "error"),
        "findings": [f.to_dict() for f in ordered],
    }
    return json.dumps(payload, indent=2)
