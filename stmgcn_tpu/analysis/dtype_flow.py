"""Abstract dtype-dataflow interpretation over traced step jaxprs.

The contract pass has walked every registered step program since PR 1,
but dtype-blind: the only precision rule was a pointwise float64 scan.
This module gives the walk dtype eyes — ONE recursive pass per program
that tags every eqn with

- a **dtype lattice value** (operand dtypes in, output dtypes out),
- a **provenance chain** (which program input / constant / cast site the
  value's dtype descends from, cast and promotion steps appended), and
- a **site role** from the precision taxonomy (dot-general operand,
  dot-general accumulator, accumulating reduction, order statistic,
  scan/while carry, cross-device psum, normalization stat, cast),

plus a per-program **dtype census** (bytes and FLOPs by dtype, count of
dtype-changing casts) and the structured float64 events
:mod:`.jaxpr_check`'s ``fp64-promotion`` rule formats — so the fp64 scan
and the precision pass share this one walk instead of walking twice.

:mod:`.precision_check` judges the resulting :class:`ProgramFlow`
objects against the declarative :class:`stmgcn_tpu.config
.PrecisionPolicy`; this module only observes, it never emits findings.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DtypeSite",
    "ProgramFlow",
    "flow_program",
    "program_flows",
    "sub_jaxprs",
    "walk_eqns",
]

#: float dtype names the policy layer reasons about (np.dtype(...).name
#: for every floating dtype JAX can put in a step program; bfloat16's
#: numpy kind is 'V', so kind-based detection would miss it)
FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")

#: accumulating reductions: the output is a sum of many addends, so a
#: sub-f32 dtype loses low-order bits on every add (the classic bf16
#: accumulation hazard)
_ACCUM_PRIMS = frozenset(
    {"reduce_sum", "reduce_prod", "cumsum", "cumprod", "add_any",
     "reduce_window_sum", "cumlogsumexp"}
)

#: order statistics: max/min select, they never accumulate — safe at the
#: compute dtype
_ORDER_PRIMS = frozenset(
    {"reduce_max", "reduce_min", "reduce_and", "reduce_or", "cummax",
     "cummin", "argmax", "argmin", "reduce_window_max",
     "reduce_window_min"}
)

#: cross-device sum reductions (gradient syncs): the SPMD twin of
#: reduce_sum, same accumulation hazard over the wire
_PSUM_PRIMS = frozenset({"psum", "psum2"})

#: normalization stats (variance -> sqrt / rsqrt chains: global_norm,
#: Welford moments, layer-norm denominators) — stat precision gates the
#: whole normalized tensor
_NORM_PRIMS = frozenset({"sqrt", "rsqrt"})


def sub_jaxprs(params: dict):
    """Yield every ClosedJaxpr/Jaxpr value inside an eqn's params."""
    try:  # the forward-portable home (jax >= 0.4.33; jax.core goes in 0.6)
        from jax.extend.core import ClosedJaxpr, Jaxpr
    except ImportError:
        from jax.core import ClosedJaxpr, Jaxpr

    for v in params.values():
        if isinstance(v, (ClosedJaxpr, Jaxpr)):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                if isinstance(item, (ClosedJaxpr, Jaxpr)):
                    yield item


def walk_eqns(jaxpr):
    """Yield every eqn, recursing into call/control-flow sub-jaxprs."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    for eqn in inner.eqns:
        yield eqn
        for sub in sub_jaxprs(eqn.params):
            yield from walk_eqns(sub)


@dataclasses.dataclass(frozen=True)
class DtypeSite:
    """One role-classified dtype site in a walked program.

    ``eqn_index`` is the eqn's position in the recursive walk order
    (:func:`walk_eqns` — stable for a given trace, so a finding can name
    the exact eqn). ``provenance`` is the dtype's descent chain, seed
    first: ``input:<label>[i]`` / ``const:<dtype>`` / ``lit:<dtype>``,
    with ``cast:<src>-><dst>`` and ``promote:<prim>-><dtype>`` steps
    appended as the value flows.
    """

    program: str
    eqn_index: int
    primitive: str
    role: str
    dtype: str
    operand_dtypes: Tuple[str, ...]
    out_dtypes: Tuple[str, ...]
    provenance: Tuple[str, ...]
    detail: str = ""

    def describe(self) -> str:
        """The finding-message fragment naming this site exactly."""
        d = f" {self.detail}" if self.detail else ""
        return (
            f"{self.program}: eqn #{self.eqn_index} ({self.primitive}){d} "
            f"[{self.role}] dtype {self.dtype}, provenance "
            f"{' -> '.join(self.provenance) or '?'}"
        )


@dataclasses.dataclass
class ProgramFlow:
    """Everything one dtype walk learned about one traced program."""

    name: str
    sites: List[DtypeSite]
    #: {"bytes": {dtype: n}, "flops": {dtype: n}, "casts": n, "eqns": n}
    census: dict
    #: ordered float64 events for jaxpr_check's fp64-promotion messages:
    #: {"kind": "convert", "source": str} / {"kind": "out", "primitive": str}
    fp64_events: List[dict]
    eqn_count: int
    in_labels: Tuple[str, ...]
    out_labels: Tuple[str, ...]
    in_dtypes: Tuple[Optional[str], ...]
    out_dtypes: Tuple[Optional[str], ...]


def _dtype_name(aval) -> Optional[str]:
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return None
    try:
        return np.dtype(dt).name
    except TypeError:
        return str(dt)


def _var_dtype(var) -> Optional[str]:
    return _dtype_name(getattr(var, "aval", None))


def _is_float(name: Optional[str]) -> bool:
    return name in FLOAT_DTYPES


def _nbytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dt = getattr(aval, "dtype", None)
    if shape is None or dt is None:
        return 0
    try:
        return int(math.prod(shape)) * np.dtype(dt).itemsize
    except (TypeError, ValueError):
        return 0


def _dot_general_flops(eqn) -> int:
    """2 x output-size x contracted extent for one dot_general eqn."""
    try:
        (lhs_c, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        k = int(math.prod(lhs_shape[d] for d in lhs_c)) or 1
        out_size = int(math.prod(eqn.outvars[0].aval.shape)) or 1
        return 2 * out_size * k
    except (AttributeError, KeyError, TypeError, IndexError):
        return 0


def flow_program(
    name: str,
    closed,
    in_labels: Optional[Sequence[str]] = None,
    out_labels: Optional[Sequence[str]] = None,
) -> ProgramFlow:
    """One recursive dtype walk over a ClosedJaxpr.

    ``in_labels`` (one per flattened invar, e.g. from
    :data:`stmgcn_tpu.train.step.PRECISION_ROLES` expanded by the trace
    registry) seed the provenance chains; without them invars are
    labeled ``arg``. ``out_labels`` are recorded for the boundary checks
    (master-param / loss dtype) but do not affect the walk.
    """
    inner = closed.jaxpr
    n_in = len(inner.invars)
    labels = list(in_labels) if in_labels is not None else ["arg"] * n_in
    if len(labels) != n_in:
        raise ValueError(
            f"{name}: {len(labels)} in_labels for {n_in} invars"
        )

    sites: List[DtypeSite] = []
    fp64_events: List[dict] = []
    bytes_by: Dict[str, int] = {}
    flops_by: Dict[str, int] = {}
    counters = {"eqn": 0, "casts": 0}
    f64 = np.dtype(np.float64)

    env: Dict[object, Tuple[str, ...]] = {}
    group_counts: Dict[str, int] = {}
    for var, label in zip(inner.invars, labels):
        i = group_counts.get(label, 0)
        group_counts[label] = i + 1
        env[var] = (f"input:{label}[{i}]",)

    def prov(var, local_env) -> Tuple[str, ...]:
        try:
            got = local_env.get(var)
        except TypeError:  # Literals are unhashable — they ARE their value
            got = None
        if got is not None:
            return got
        return (f"lit:{_var_dtype(var) or '?'}",)

    def seed_consts(jaxpr, local_env) -> None:
        for cv in jaxpr.constvars:
            local_env[cv] = (f"const:{_var_dtype(cv) or '?'}",)

    def visit(jaxpr, local_env) -> None:
        seed_consts(jaxpr, local_env)
        for eqn in jaxpr.eqns:
            idx = counters["eqn"]
            counters["eqn"] += 1
            prim = eqn.primitive.name
            in_dts = tuple(_var_dtype(v) for v in eqn.invars)
            out_dts = tuple(_var_dtype(v) for v in eqn.outvars)

            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                dn = _dtype_name(aval)
                if dn is not None:
                    bytes_by[dn] = bytes_by.get(dn, 0) + _nbytes(aval)
            if prim == "dot_general":
                flops = _dot_general_flops(eqn)
                dn = out_dts[0] if out_dts else None
                if flops and dn:
                    flops_by[dn] = flops_by.get(dn, 0) + flops

            # the fp64 events, in the exact (convert-then-outvar) order
            # jaxpr_check's original two-branch scan emitted them
            if (
                prim == "convert_element_type"
                and np.dtype(eqn.params.get("new_dtype", np.float32)) == f64
            ):
                fp64_events.append({
                    "kind": "convert",
                    "source": str(eqn.source_info.traceback),
                })
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                if aval is not None and getattr(aval, "dtype", None) == f64:
                    fp64_events.append({"kind": "out", "primitive": prim})

            # -- provenance + role sites ------------------------------
            if prim == "convert_element_type":
                src, dst = in_dts[0], out_dts[0]
                chain = prov(eqn.invars[0], local_env)
                if src != dst:
                    chain = chain + (f"cast:{src}->{dst}",)
                    counters["casts"] += 1
                    sites.append(DtypeSite(
                        program=name, eqn_index=idx, primitive=prim,
                        role="cast", dtype=dst or "?",
                        operand_dtypes=(src or "?",), out_dtypes=out_dts,
                        provenance=chain,
                    ))
                for var in eqn.outvars:
                    local_env[var] = chain
            else:
                in_chains = [prov(v, local_env) for v in eqn.invars]
                for var in eqn.outvars:
                    dn = _var_dtype(var)
                    chain: Tuple[str, ...] = ()
                    for v, c in zip(eqn.invars, in_chains):
                        if _var_dtype(v) == dn:
                            chain = c
                            break
                    if not chain:
                        chain = in_chains[0] if in_chains else ()
                        if _is_float(dn):
                            chain = chain + (f"promote:{prim}->{dn}",)
                    local_env[var] = chain

                role_dt = out_dts[0] if out_dts else None
                if prim == "dot_general":
                    if any(_is_float(d) for d in in_dts):
                        sites.append(DtypeSite(
                            program=name, eqn_index=idx, primitive=prim,
                            role="dot_general", dtype=in_dts[0] or "?",
                            operand_dtypes=in_dts, out_dtypes=out_dts,
                            provenance=prov(eqn.invars[0], local_env),
                        ))
                        pref = eqn.params.get("preferred_element_type")
                        acc = (
                            np.dtype(pref).name if pref is not None
                            else role_dt
                        )
                        sites.append(DtypeSite(
                            program=name, eqn_index=idx, primitive=prim,
                            role="dot_general_accum", dtype=acc or "?",
                            operand_dtypes=in_dts, out_dtypes=out_dts,
                            provenance=prov(eqn.invars[0], local_env),
                            detail="accumulator",
                        ))
                elif prim in _ACCUM_PRIMS and _is_float(role_dt):
                    sites.append(DtypeSite(
                        program=name, eqn_index=idx, primitive=prim,
                        role="reduce_sum", dtype=role_dt,
                        operand_dtypes=in_dts, out_dtypes=out_dts,
                        provenance=prov(eqn.invars[0], local_env),
                    ))
                elif prim in _ORDER_PRIMS and _is_float(role_dt):
                    sites.append(DtypeSite(
                        program=name, eqn_index=idx, primitive=prim,
                        role="reduce_order", dtype=role_dt,
                        operand_dtypes=in_dts, out_dtypes=out_dts,
                        provenance=prov(eqn.invars[0], local_env),
                    ))
                elif prim in _PSUM_PRIMS:
                    for j, (v, d) in enumerate(zip(eqn.invars, in_dts)):
                        if _is_float(d):
                            sites.append(DtypeSite(
                                program=name, eqn_index=idx, primitive=prim,
                                role="psum", dtype=d,
                                operand_dtypes=in_dts, out_dtypes=out_dts,
                                provenance=prov(v, local_env),
                                detail=f"operand[{j}]",
                            ))
                elif prim in _NORM_PRIMS and _is_float(role_dt):
                    sites.append(DtypeSite(
                        program=name, eqn_index=idx, primitive=prim,
                        role="normalization", dtype=role_dt,
                        operand_dtypes=in_dts, out_dtypes=out_dts,
                        provenance=prov(eqn.invars[0], local_env),
                    ))
                elif prim == "scan":
                    nc = eqn.params.get("num_consts", 0)
                    nk = eqn.params.get("num_carry", 0)
                    carries = eqn.invars[nc:nc + nk]
                    for j, v in enumerate(carries):
                        d = _var_dtype(v)
                        if _is_float(d):
                            sites.append(DtypeSite(
                                program=name, eqn_index=idx, primitive=prim,
                                role="scan_carry", dtype=d,
                                operand_dtypes=in_dts, out_dtypes=out_dts,
                                provenance=prov(v, local_env),
                                detail=f"carry[{j}]",
                            ))
                elif prim == "while":
                    nc = (eqn.params.get("cond_nconsts", 0)
                          + eqn.params.get("body_nconsts", 0))
                    for j, v in enumerate(eqn.invars[nc:]):
                        d = _var_dtype(v)
                        if _is_float(d):
                            sites.append(DtypeSite(
                                program=name, eqn_index=idx, primitive=prim,
                                role="scan_carry", dtype=d,
                                operand_dtypes=in_dts, out_dtypes=out_dts,
                                provenance=prov(v, local_env),
                                detail=f"while_carry[{j}]",
                            ))

            for sub in sub_jaxprs(eqn.params):
                sub_inner = getattr(sub, "jaxpr", sub)
                sub_env: Dict[object, Tuple[str, ...]] = {}
                n_sub = len(sub_inner.invars)
                if n_sub <= len(eqn.invars):
                    # positional suffix alignment: scan/pjit bind all
                    # their operands, cond drops the leading predicate,
                    # while's body consts+carry trail the cond consts
                    src_vars = list(eqn.invars)[len(eqn.invars) - n_sub:]
                    for sv, ov in zip(sub_inner.invars, src_vars):
                        sub_env[sv] = prov(ov, local_env)
                else:
                    for sv in sub_inner.invars:
                        sub_env[sv] = (f"opaque:{prim}",)
                visit(sub_inner, sub_env)

    visit(inner, env)

    n_out = len(inner.outvars)
    outs = list(out_labels) if out_labels is not None else ["out"] * n_out
    if len(outs) != n_out:
        raise ValueError(f"{name}: {len(outs)} out_labels for {n_out} outvars")
    return ProgramFlow(
        name=name,
        sites=sites,
        census={
            "bytes": dict(sorted(bytes_by.items())),
            "flops": dict(sorted(flops_by.items())),
            "casts": counters["casts"],
            "eqns": counters["eqn"],
        },
        fp64_events=fp64_events,
        eqn_count=counters["eqn"],
        in_labels=tuple(labels),
        out_labels=tuple(outs),
        in_dtypes=tuple(_var_dtype(v) for v in inner.invars),
        out_dtypes=tuple(_var_dtype(v) for v in inner.outvars),
    )


_FLOW_CACHE: Dict[str, Dict[str, ProgramFlow]] = {}


def program_flows(preset_name: str = "smoke") -> Dict[str, ProgramFlow]:
    """One :class:`ProgramFlow` per registered contract program.

    Cached per preset and per process: the fp64-promotion scan
    (:mod:`.jaxpr_check`), the precision rules
    (:mod:`.precision_check`), and the lint-gate summary all consume
    this one walk — tracing and walking happen once.
    """
    cached = _FLOW_CACHE.get(preset_name)
    if cached is not None:
        return cached
    from stmgcn_tpu.analysis.jaxpr_check import _trace_step_programs

    flows = {
        name: flow_program(
            name, rec["jaxpr"],
            in_labels=rec["in_labels"], out_labels=rec["out_labels"],
        )
        for name, rec in _trace_step_programs(preset_name).items()
    }
    _FLOW_CACHE[preset_name] = flows
    return flows
