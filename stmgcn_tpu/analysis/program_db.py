"""Pass 0: the repo-wide program database behind whole-program lint.

The per-module AST lint (:mod:`.lint`) deliberately stops at module
boundaries: its jit-reachability seeds propagate through same-module
calls only, so a host-sync inside a helper that *another* module's
jitted code calls is invisible. This module builds the missing global
view — one parse of every ``.py`` file in the package, then:

- **resolved import aliases**: each module's ``import``/``from-import``
  bindings resolved to absolute dotted targets, including relative
  imports and re-export chains through package ``__init__`` modules
  (``from stmgcn_tpu.ops import make_conv`` follows
  ``ops/__init__.py``'s own ``from .chebconv import make_conv``);
- **a global call graph over qualnames** (``module:function``) whose
  cross-module edges exist *only* where a callee resolves statically
  through the alias map — a ``Name`` call bound by an import, or a
  dotted ``module.attr(...)`` call. Dynamic dispatch (``self.foo()``,
  attributes of unknown objects) stays what it was in the per-module
  pass: a same-module by-name edge, never a cross-module guess. That
  asymmetry is the precision contract — whole-program mode must add
  zero false positives on a tree the per-module pass reports clean
  (pinned in ``tests/test_analysis.py``);
- **global jit-reachability with call chains**: the union of every
  module's root seeds (tracer-wrapped defs, flax ``nn.Module`` methods,
  functions handed to ``jax.jit``/``lax.scan``/... — including *imported*
  functions handed to a tracer, which no per-module index can seed),
  BFS'd over the global graph with parent tracking so each newly
  reachable function carries the root→function chain findings report.

:func:`ProgramDB.module_extras` is the lint integration point: for one
module it returns the functions that are globally jit-reachable but
locally invisible, with their chains. :func:`ProgramDB.cross_module_gain`
is the acceptance-criteria view (functions only the global pass sees).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from stmgcn_tpu.analysis.lint import _TRACER_WRAPPERS, _ModuleIndex

__all__ = ["ModuleEntry", "ProgramDB"]

#: re-export chains longer than this are a cycle, not a design
_MAX_ALIAS_DEPTH = 8


@dataclasses.dataclass
class ModuleEntry:
    """One parsed module: source, tree, per-module index, import map."""

    name: str  # absolute dotted module name
    path: str  # repo-relative posix path (what findings report)
    source: str
    tree: ast.Module
    index: _ModuleIndex
    imports: Dict[str, str]  # local binding -> absolute dotted target
    is_package: bool  # an __init__.py


def _module_imports(
    tree: ast.Module, mod_name: str, is_package: bool
) -> Dict[str, str]:
    """Local name -> absolute dotted target, relative imports resolved."""
    out: Dict[str, str] = {}
    pkg_parts = mod_name.split(".") if is_package else mod_name.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    # `import a.b.c` binds only `a` — and `a` names the
                    # top-level package, which resolve_symbol then walks
                    out[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                if not base and node.level > 0:
                    continue  # relative import above the package root
                prefix = ".".join(base + ([node.module] if node.module else []))
            else:
                prefix = node.module or ""
            if not prefix:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{prefix}.{a.name}"
    return out


class ProgramDB:
    """Module graph + resolved aliases + global jit-reachability."""

    def __init__(self, entries: Dict[str, ModuleEntry]):
        self.modules = entries
        self.roots: Set[str] = set()
        self.edges: Dict[str, Set[str]] = {}
        self._build_graph()
        self._reach: Optional[Dict[str, Tuple[str, ...]]] = None

    # -- construction ------------------------------------------------------
    @classmethod
    def from_root(cls, root: str, package: Optional[str] = None) -> "ProgramDB":
        """Parse every ``.py`` under ``root`` (a package directory)."""
        root_path = Path(root)
        package = package or root_path.name
        cwd = os.getcwd()
        entries: Dict[str, ModuleEntry] = {}
        for f in sorted(root_path.rglob("*.py")):
            rel_mod = f.relative_to(root_path)
            parts = [package] + list(rel_mod.parts[:-1])
            is_package = f.name == "__init__.py"
            if not is_package:
                parts.append(f.stem)
            name = ".".join(parts)
            rel = os.path.relpath(f, cwd)
            rel = f.as_posix() if rel.startswith("..") else Path(rel).as_posix()
            source = f.read_text()
            entry = cls._entry(name, rel, source, is_package)
            if entry is not None:
                entries[name] = entry
        return cls(entries)

    @classmethod
    def from_sources(cls, sources: Dict[str, str]) -> "ProgramDB":
        """Build from ``{dotted module name: source}`` (test fixtures)."""
        entries: Dict[str, ModuleEntry] = {}
        for name, src in sources.items():
            path = name.replace(".", "/") + ".py"
            entry = cls._entry(name, path, src, is_package=False)
            if entry is not None:
                entries[name] = entry
        return cls(entries)

    @staticmethod
    def _entry(
        name: str, path: str, source: str, is_package: bool
    ) -> Optional[ModuleEntry]:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return None  # the per-module lint reports unparseable files
        index = _ModuleIndex()
        index.visit(tree)
        return ModuleEntry(
            name=name,
            path=path,
            source=source,
            tree=tree,
            index=index,
            imports=_module_imports(tree, name, is_package),
            is_package=is_package,
        )

    # -- symbol resolution -------------------------------------------------
    def resolve_symbol(self, dotted: str, _depth: int = 0) -> Optional[str]:
        """Absolute dotted path -> ``module:function`` qualname, following
        re-export chains; None when it doesn't land on a known def."""
        if _depth > _MAX_ALIAS_DEPTH:
            return None
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod not in self.modules:
                continue
            rest = parts[i:]
            if len(rest) != 1:
                return None  # attribute chain below a symbol: dynamic
            entry = self.modules[mod]
            sym = rest[0]
            if sym in entry.index.funcs:
                return f"{mod}:{sym}"
            if sym in entry.imports:
                return self.resolve_symbol(entry.imports[sym], _depth + 1)
            return None
        return None

    def _resolve_local(self, entry: ModuleEntry, dotted: str) -> Optional[str]:
        """Resolve a dotted expression rooted at one of ``entry``'s local
        bindings (``conv_mod.make_conv`` / imported ``make_conv``)."""
        root, _, rest = dotted.partition(".")
        target = entry.imports.get(root)
        if target is None:
            return None
        full = f"{target}.{rest}" if rest else target
        return self.resolve_symbol(full)

    # -- the global graph --------------------------------------------------
    def _build_graph(self) -> None:
        # register every def first — edge targets must exist before any
        # module's walker runs, whatever the module iteration order
        for name, entry in self.modules.items():
            for fn in entry.index.funcs:
                self.edges.setdefault(f"{name}:{fn}", set())
            for root_fn in entry.index.roots:
                if root_fn in entry.index.funcs:
                    self.roots.add(f"{name}:{root_fn}")
        for entry in self.modules.values():
            _GraphWalker(self, entry).visit(entry.tree)

    def global_reachability(self) -> Dict[str, Tuple[str, ...]]:
        """``qualname -> root→...→qualname chain`` for every globally
        jit-reachable function (roots map to one-element chains)."""
        if self._reach is not None:
            return self._reach
        parent: Dict[str, Optional[str]] = {}
        seen: Set[str] = set()
        frontier: List[str] = []
        for r in sorted(self.roots):
            if r in self.edges:  # root must be a known def
                seen.add(r)
                parent[r] = None
                frontier.append(r)
        while frontier:
            q = frontier.pop()
            for callee in sorted(self.edges.get(q, ())):
                if callee not in seen:
                    seen.add(callee)
                    parent[callee] = q
                    frontier.append(callee)
        out: Dict[str, Tuple[str, ...]] = {}
        for q in seen:
            chain: List[str] = []
            cur: Optional[str] = q
            while cur is not None:
                chain.append(cur)
                cur = parent[cur]
            out[q] = tuple(reversed(chain))
        self._reach = out
        return out

    # -- lint integration views --------------------------------------------
    def module_extras(
        self, mod_name: str
    ) -> Dict[str, Tuple[str, ...]]:
        """Functions of ``mod_name`` that are globally jit-reachable but
        invisible to the per-module pass, with their call chains."""
        entry = self.modules[mod_name]
        local = entry.index.reachable()
        out: Dict[str, Tuple[str, ...]] = {}
        for q, chain in self.global_reachability().items():
            mod, _, fn = q.partition(":")
            if mod == mod_name and fn not in local:
                out[fn] = chain
        return out

    def cross_module_gain(self) -> Dict[str, Tuple[str, ...]]:
        """Every globally-reachable qualname the per-module pass misses —
        the acceptance-criteria view (must be non-empty on this tree)."""
        out: Dict[str, Tuple[str, ...]] = {}
        for mod_name in self.modules:
            for fn, chain in self.module_extras(mod_name).items():
                out[f"{mod_name}:{fn}"] = chain
        return out


class _GraphWalker(ast.NodeVisitor):
    """Per-module sweep adding this module's edges to the global graph.

    Same attribution discipline as the local index (calls belong to the
    innermost enclosing def), but callees resolve through the import map
    first; only unresolved names fall back to same-module by-name edges.
    """

    def __init__(self, db: ProgramDB, entry: ModuleEntry):
        self.db = db
        self.entry = entry
        self._stack: List[str] = []

    def _handle_func(self, node) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _handle_func
    visit_AsyncFunctionDef = _handle_func

    def _add_edge(self, callee_q: str) -> None:
        if self._stack and callee_q in self.db.edges:
            caller_q = f"{self.entry.name}:{self._stack[-1]}"
            self.db.edges.setdefault(caller_q, set()).add(callee_q)

    def visit_Call(self, node: ast.Call) -> None:
        entry = self.entry
        target: Optional[str] = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
            if name in entry.imports:
                target = self.db.resolve_symbol(entry.imports[name])
            if target is None and name in entry.index.funcs:
                target = f"{entry.name}:{name}"
        elif isinstance(node.func, ast.Attribute):
            dotted = entry.index.dotted(node.func)
            if dotted:
                target = self._resolve_dotted(dotted)
            if target is None and node.func.attr in entry.index.funcs:
                # self.foo() / unknown-object attr: the per-module rule
                target = f"{entry.name}:{node.func.attr}"
        if target is not None:
            self._add_edge(target)

        # an *imported* function handed to a tracing transform becomes a
        # global root — the seed no per-module index can plant
        d = entry.index.dotted(node.func)
        if d and d.split(".")[-1] in _TRACER_WRAPPERS:
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in entry.imports:
                        q = self.db.resolve_symbol(entry.imports[sub.id])
                        if q is not None:
                            self.db.roots.add(q)
        self.generic_visit(node)

    def _resolve_dotted(self, dotted: str) -> Optional[str]:
        q = self.db.resolve_symbol(dotted)
        if q is not None:
            return q
        return self.db._resolve_local(self.entry, dotted)
