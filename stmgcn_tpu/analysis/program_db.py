"""Pass 0: the repo-wide program database behind whole-program lint.

The per-module AST lint (:mod:`.lint`) deliberately stops at module
boundaries: its jit-reachability seeds propagate through same-module
calls only, so a host-sync inside a helper that *another* module's
jitted code calls is invisible. This module builds the missing global
view — one parse of every ``.py`` file in the package, then:

- **resolved import aliases**: each module's ``import``/``from-import``
  bindings resolved to absolute dotted targets, including relative
  imports and re-export chains through package ``__init__`` modules
  (``from stmgcn_tpu.ops import make_conv`` follows
  ``ops/__init__.py``'s own ``from .chebconv import make_conv``);
- **a global call graph over qualnames** (``module:function``) whose
  cross-module edges exist *only* where a callee resolves statically
  through the alias map — a ``Name`` call bound by an import, or a
  dotted ``module.attr(...)`` call. Dynamic dispatch (``self.foo()``,
  attributes of unknown objects) stays what it was in the per-module
  pass: a same-module by-name edge, never a cross-module guess. That
  asymmetry is the precision contract — whole-program mode must add
  zero false positives on a tree the per-module pass reports clean
  (pinned in ``tests/test_analysis.py``);
- **global jit-reachability with call chains**: the union of every
  module's root seeds (tracer-wrapped defs, flax ``nn.Module`` methods,
  functions handed to ``jax.jit``/``lax.scan``/... — including *imported*
  functions handed to a tracer, which no per-module index can seed),
  BFS'd over the global graph with parent tracking so each newly
  reachable function carries the root→function chain findings report.

:func:`ProgramDB.module_extras` is the lint integration point: for one
module it returns the functions that are globally jit-reachable but
locally invisible, with their chains. :func:`ProgramDB.cross_module_gain`
is the acceptance-criteria view (functions only the global pass sees).

Class awareness (PR 10): every module's classes are modeled as
:class:`ClassInfo` — methods, attributes assigned in ``__init__``, and
synchronization fields recognized from their ``threading.Lock`` /
``RLock`` / ``Condition`` / ``Event`` / ``Thread`` / ``queue.Queue``
constructor calls. On top of that sits the **opt-in type-informed
resolution mode** (``type_informed=True``): ``self.method()``,
``self.attr.method()`` where the attribute's class is unambiguous from
``__init__``/annotation evidence, calls through a single-class-annotated
parameter, and calls on module-level singleton instances all resolve to
real ``module:method`` edges. The zero-false-positive contract is kept
the same way the import resolver keeps it: a target is only resolved
when exactly one class can be the receiver — conflicting assignments
poison the evidence and the call stays unresolved. Edges that exist
*only* because of typed resolution are recorded in
:attr:`ProgramDB.typed_edges` so tests can pin the gain and its
zero-new-findings property. The concurrency pass
(:mod:`.concurrency_check`) consumes the same class model.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from stmgcn_tpu.analysis.lint import _TRACER_WRAPPERS, _ModuleIndex

__all__ = ["ClassInfo", "ModuleEntry", "ProgramDB"]

#: re-export chains longer than this are a cycle, not a design
_MAX_ALIAS_DEPTH = 8

#: constructor dotted path -> synchronization-field kind
_SYNC_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Condition": "condvar",
    "threading.Event": "event",
    "threading.Thread": "thread",
    "threading.Timer": "thread",
    "queue.Queue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "queue.SimpleQueue": "queue",
}


def _dotted_expr(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name-rooted attribute chain; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` when ``node`` is exactly ``self.X``; None otherwise."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclasses.dataclass
class ClassInfo:
    """One class: methods, ``self`` attributes, typed synchronization
    fields, and the attribute types that are unambiguous from
    ``__init__``/annotation evidence (the dispatch-resolution basis)."""

    qualname: str  # "module:Class"
    module: str
    name: str
    node: ast.ClassDef
    methods: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    attrs: Set[str] = dataclasses.field(default_factory=set)
    locks: Set[str] = dataclasses.field(default_factory=set)
    #: condvar field -> owning lock field (None = owns its own lock)
    condvars: Dict[str, Optional[str]] = dataclasses.field(default_factory=dict)
    events: Set[str] = dataclasses.field(default_factory=set)
    queues: Set[str] = dataclasses.field(default_factory=set)
    #: thread field -> daemon flag (None = not statically knowable)
    threads: Dict[str, Optional[bool]] = dataclasses.field(default_factory=dict)
    #: attr -> "module:Class" — only when exactly one class is possible
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def sync_fields(self) -> Set[str]:
        return (
            self.locks
            | set(self.condvars)
            | self.events
            | self.queues
            | set(self.threads)
        )


@dataclasses.dataclass
class ModuleEntry:
    """One parsed module: source, tree, per-module index, import map."""

    name: str  # absolute dotted module name
    path: str  # repo-relative posix path (what findings report)
    source: str
    tree: ast.Module
    index: _ModuleIndex
    imports: Dict[str, str]  # local binding -> absolute dotted target
    is_package: bool  # an __init__.py


def _module_imports(
    tree: ast.Module, mod_name: str, is_package: bool
) -> Dict[str, str]:
    """Local name -> absolute dotted target, relative imports resolved."""
    out: Dict[str, str] = {}
    pkg_parts = mod_name.split(".") if is_package else mod_name.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    # `import a.b.c` binds only `a` — and `a` names the
                    # top-level package, which resolve_symbol then walks
                    out[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                if not base and node.level > 0:
                    continue  # relative import above the package root
                prefix = ".".join(base + ([node.module] if node.module else []))
            else:
                prefix = node.module or ""
            if not prefix:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{prefix}.{a.name}"
    return out


class ProgramDB:
    """Module graph + resolved aliases + global jit-reachability."""

    def __init__(
        self, entries: Dict[str, ModuleEntry], *, type_informed: bool = False
    ):
        self.modules = entries
        self.type_informed = type_informed
        self.roots: Set[str] = set()
        self.edges: Dict[str, Set[str]] = {}
        #: "module:Class" -> ClassInfo, for every class in every module
        self.classes: Dict[str, ClassInfo] = {}
        #: module -> {global name -> "module:Class"} singleton instances
        self._globals: Dict[str, Dict[str, str]] = {}
        #: (caller, callee) edges that exist only via typed resolution
        self.typed_edges: Set[Tuple[str, str]] = set()
        self._build_classes()
        self._build_graph()
        self._reach: Optional[Dict[str, Tuple[str, ...]]] = None

    # -- construction ------------------------------------------------------
    @classmethod
    def from_root(
        cls,
        root: str,
        package: Optional[str] = None,
        *,
        type_informed: bool = False,
    ) -> "ProgramDB":
        """Parse every ``.py`` under ``root`` (a package directory)."""
        root_path = Path(root)
        package = package or root_path.name
        cwd = os.getcwd()
        entries: Dict[str, ModuleEntry] = {}
        for f in sorted(root_path.rglob("*.py")):
            rel_mod = f.relative_to(root_path)
            parts = [package] + list(rel_mod.parts[:-1])
            is_package = f.name == "__init__.py"
            if not is_package:
                parts.append(f.stem)
            name = ".".join(parts)
            rel = os.path.relpath(f, cwd)
            rel = f.as_posix() if rel.startswith("..") else Path(rel).as_posix()
            source = f.read_text()
            entry = cls._entry(name, rel, source, is_package)
            if entry is not None:
                entries[name] = entry
        return cls(entries, type_informed=type_informed)

    @classmethod
    def from_sources(
        cls, sources: Dict[str, str], *, type_informed: bool = False
    ) -> "ProgramDB":
        """Build from ``{dotted module name: source}`` (test fixtures)."""
        entries: Dict[str, ModuleEntry] = {}
        for name, src in sources.items():
            path = name.replace(".", "/") + ".py"
            entry = cls._entry(name, path, src, is_package=False)
            if entry is not None:
                entries[name] = entry
        return cls(entries, type_informed=type_informed)

    @staticmethod
    def _entry(
        name: str, path: str, source: str, is_package: bool
    ) -> Optional[ModuleEntry]:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return None  # the per-module lint reports unparseable files
        index = _ModuleIndex()
        index.visit(tree)
        return ModuleEntry(
            name=name,
            path=path,
            source=source,
            tree=tree,
            index=index,
            imports=_module_imports(tree, name, is_package),
            is_package=is_package,
        )

    # -- symbol resolution -------------------------------------------------
    def resolve_symbol(self, dotted: str, _depth: int = 0) -> Optional[str]:
        """Absolute dotted path -> ``module:function`` qualname, following
        re-export chains; None when it doesn't land on a known def."""
        if _depth > _MAX_ALIAS_DEPTH:
            return None
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod not in self.modules:
                continue
            rest = parts[i:]
            if len(rest) != 1:
                return None  # attribute chain below a symbol: dynamic
            entry = self.modules[mod]
            sym = rest[0]
            if sym in entry.index.funcs:
                return f"{mod}:{sym}"
            if sym in entry.imports:
                return self.resolve_symbol(entry.imports[sym], _depth + 1)
            return None
        return None

    def _resolve_local(self, entry: ModuleEntry, dotted: str) -> Optional[str]:
        """Resolve a dotted expression rooted at one of ``entry``'s local
        bindings (``conv_mod.make_conv`` / imported ``make_conv``)."""
        root, _, rest = dotted.partition(".")
        target = entry.imports.get(root)
        if target is None:
            return None
        full = f"{target}.{rest}" if rest else target
        return self.resolve_symbol(full)

    # -- class modeling ----------------------------------------------------
    def _build_classes(self) -> None:
        # phase A: shells first, so cross-module class references resolve
        # whatever the module iteration order
        for name, entry in self.modules.items():
            for node in entry.tree.body:
                if isinstance(node, ast.ClassDef):
                    qual = f"{name}:{node.name}"
                    ci = ClassInfo(
                        qualname=qual, module=name, name=node.name, node=node
                    )
                    for item in node.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            ci.methods[item.name] = item
                    self.classes[qual] = ci
        # phase B: field analysis (needs resolve_class over the shells)
        for name, entry in self.modules.items():
            for qual, ci in list(self.classes.items()):
                if ci.module == name:
                    self._analyze_fields(entry, ci)
            self._globals[name] = self._module_globals(entry)

    def _abs_ctor(self, entry: ModuleEntry, func: ast.AST) -> Optional[str]:
        """Absolute dotted path of a call's constructor through the
        import map (``Condition`` -> ``threading.Condition``)."""
        d = _dotted_expr(func)
        if d is None:
            return None
        root, _, rest = d.partition(".")
        base = entry.imports.get(root, root)
        return f"{base}.{rest}" if rest else base

    def resolve_class(self, dotted: str, _depth: int = 0) -> Optional[str]:
        """Absolute dotted path -> ``module:Class`` qualname, following
        re-export chains; None when it doesn't land on a known class."""
        if _depth > _MAX_ALIAS_DEPTH:
            return None
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod not in self.modules:
                continue
            rest = parts[i:]
            if len(rest) != 1:
                return None
            sym = rest[0]
            if f"{mod}:{sym}" in self.classes:
                return f"{mod}:{sym}"
            imports = self.modules[mod].imports
            if sym in imports:
                return self.resolve_class(imports[sym], _depth + 1)
            return None
        return None

    def _annotation_class(
        self, entry: ModuleEntry, ann: Optional[ast.AST]
    ) -> Optional[str]:
        """``module:Class`` named by an annotation; ``Optional[X]``
        unwraps to ``X``; anything else ambiguous returns None."""
        if ann is None:
            return None
        if isinstance(ann, ast.Subscript):
            base = _dotted_expr(ann.value)
            if base and base.split(".")[-1] == "Optional":
                return self._annotation_class(entry, ann.slice)
            return None
        d = _dotted_expr(ann)
        if d is None:
            return None
        if "." not in d and f"{entry.name}:{d}" in self.classes:
            return f"{entry.name}:{d}"
        root, _, rest = d.partition(".")
        base = entry.imports.get(root)
        if base is None:
            return None
        return self.resolve_class(f"{base}.{rest}" if rest else base)

    def _called_class(
        self, entry: ModuleEntry, value: ast.AST
    ) -> Optional[str]:
        """``module:Class`` when ``value`` is a direct constructor call."""
        if not isinstance(value, ast.Call):
            return None
        d = self._abs_ctor(entry, value.func)
        if d is None or d in _SYNC_CTORS:
            return None
        if "." not in d and f"{entry.name}:{d}" in self.classes:
            return f"{entry.name}:{d}"
        return self.resolve_class(d)

    def _analyze_fields(self, entry: ModuleEntry, ci: ClassInfo) -> None:
        init = ci.methods.get("__init__")
        init_params: Dict[str, Optional[ast.AST]] = {}
        if init is not None:
            args = init.args
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                init_params[a.arg] = a.annotation
        evidence: Dict[str, Set[str]] = {}  # attr -> candidate class quals
        poisoned: Set[str] = set()  # attrs with a non-None untyped (re)assign
        for mname, method in ci.methods.items():
            for node in ast.walk(method):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets = [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    ci.attrs.add(attr)
                    if isinstance(node, ast.AnnAssign):
                        t_cls = self._annotation_class(entry, node.annotation)
                        if t_cls is not None:
                            evidence.setdefault(attr, set()).add(t_cls)
                        if value is None:
                            continue
                    kind = (
                        _SYNC_CTORS.get(self._abs_ctor(entry, value.func))
                        if isinstance(value, ast.Call)
                        else None
                    )
                    if kind == "lock":
                        ci.locks.add(attr)
                    elif kind == "condvar":
                        owner = None
                        if value.args:
                            owner = _self_attr(value.args[0])
                        ci.condvars[attr] = owner
                    elif kind == "event":
                        ci.events.add(attr)
                    elif kind == "queue":
                        ci.queues.add(attr)
                    elif kind == "thread":
                        daemon: Optional[bool] = False
                        for kw in value.keywords:
                            if kw.arg == "daemon":
                                daemon = (
                                    kw.value.value
                                    if isinstance(kw.value, ast.Constant)
                                    and isinstance(kw.value.value, bool)
                                    else None
                                )
                        ci.threads[attr] = daemon
                    else:
                        t_cls = self._called_class(entry, value)
                        if t_cls is None and (
                            mname == "__init__"
                            and isinstance(value, ast.Name)
                            and value.id in init_params
                        ):
                            t_cls = self._annotation_class(
                                entry, init_params[value.id]
                            )
                        if t_cls is not None:
                            evidence.setdefault(attr, set()).add(t_cls)
                        elif not (
                            isinstance(value, ast.Constant)
                            and value.value is None
                        ):
                            # a real untyped (re)assignment: the attribute's
                            # class is no longer unambiguous (None keeps the
                            # Optional[field] idiom typed)
                            poisoned.add(attr)
        for attr, cands in evidence.items():
            if len(cands) == 1 and attr not in poisoned:
                ci.attr_types[attr] = next(iter(cands))

    def _module_globals(self, entry: ModuleEntry) -> Dict[str, str]:
        """Module-level ``NAME = SomeClass()`` singleton instances."""
        out: Dict[str, str] = {}
        for node in entry.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            t_cls = self._called_class(entry, node.value)
            if t_cls is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = t_cls
        return out

    def instance_type(
        self, entry: ModuleEntry, name: str, _depth: int = 0
    ) -> Optional[str]:
        """``module:Class`` of a bare name that statically names a
        module-level singleton (local or imported); None otherwise."""
        if _depth > _MAX_ALIAS_DEPTH:
            return None
        local = self._globals.get(entry.name, {}).get(name)
        if local is not None:
            return local
        dotted = entry.imports.get(name)
        if dotted is None:
            return None
        mod, _, sym = dotted.rpartition(".")
        if mod in self.modules:
            target = self._globals.get(mod, {}).get(sym)
            if target is not None:
                return target
            if sym in self.modules[mod].imports:
                return self.instance_type(self.modules[mod], sym, _depth + 1)
        return None

    def receiver_type(
        self,
        entry: ModuleEntry,
        cls_qual: Optional[str],
        fn_node: Optional[ast.AST],
        recv: ast.AST,
    ) -> Optional[str]:
        """``module:Class`` of a call receiver expression, using only
        unambiguous evidence: ``self`` inside a known class, ``self.attr``
        with a single-class attr type, a single-class-annotated parameter
        of the enclosing function (unless locally reassigned), or a
        module-level singleton instance."""
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                return cls_qual
            if fn_node is not None:
                args = fn_node.args
                for a in args.posonlyargs + args.args + args.kwonlyargs:
                    if a.arg == recv.id:
                        if a.annotation is None or self._locally_bound(
                            fn_node, recv.id
                        ):
                            return None
                        return self._annotation_class(entry, a.annotation)
                if self._locally_bound(fn_node, recv.id):
                    return None
            return self.instance_type(entry, recv.id)
        attr = _self_attr(recv)
        if attr is not None and cls_qual is not None:
            ci = self.classes.get(cls_qual)
            if ci is not None:
                return ci.attr_types.get(attr)
        return None

    @staticmethod
    def _locally_bound(fn_node: ast.AST, name: str) -> bool:
        for sub in ast.walk(fn_node):
            if (
                isinstance(sub, ast.Name)
                and sub.id == name
                and isinstance(sub.ctx, (ast.Store, ast.Del))
            ):
                return True
        return False

    def typed_method_target(
        self,
        entry: ModuleEntry,
        cls_qual: Optional[str],
        fn_node: Optional[ast.AST],
        call: ast.Call,
    ) -> Optional[Tuple[str, str]]:
        """``("module:Class", method)`` for ``obj.m(...)`` when the
        receiver's class is unambiguous and defines ``m``; else None."""
        if not isinstance(call.func, ast.Attribute):
            return None
        t = self.receiver_type(entry, cls_qual, fn_node, call.func.value)
        if t is None:
            return None
        ci = self.classes.get(t)
        if ci is None or call.func.attr not in ci.methods:
            return None
        return t, call.func.attr

    # -- the global graph --------------------------------------------------
    def _build_graph(self) -> None:
        # register every def first — edge targets must exist before any
        # module's walker runs, whatever the module iteration order
        for name, entry in self.modules.items():
            for fn in entry.index.funcs:
                self.edges.setdefault(f"{name}:{fn}", set())
            for root_fn in entry.index.roots:
                if root_fn in entry.index.funcs:
                    self.roots.add(f"{name}:{root_fn}")
        for entry in self.modules.values():
            _GraphWalker(self, entry).visit(entry.tree)

    def global_reachability(self) -> Dict[str, Tuple[str, ...]]:
        """``qualname -> root→...→qualname chain`` for every globally
        jit-reachable function (roots map to one-element chains)."""
        if self._reach is not None:
            return self._reach
        parent: Dict[str, Optional[str]] = {}
        seen: Set[str] = set()
        frontier: List[str] = []
        for r in sorted(self.roots):
            if r in self.edges:  # root must be a known def
                seen.add(r)
                parent[r] = None
                frontier.append(r)
        while frontier:
            q = frontier.pop()
            for callee in sorted(self.edges.get(q, ())):
                if callee not in seen:
                    seen.add(callee)
                    parent[callee] = q
                    frontier.append(callee)
        out: Dict[str, Tuple[str, ...]] = {}
        for q in seen:
            chain: List[str] = []
            cur: Optional[str] = q
            while cur is not None:
                chain.append(cur)
                cur = parent[cur]
            out[q] = tuple(reversed(chain))
        self._reach = out
        return out

    # -- lint integration views --------------------------------------------
    def module_extras(
        self, mod_name: str
    ) -> Dict[str, Tuple[str, ...]]:
        """Functions of ``mod_name`` that are globally jit-reachable but
        invisible to the per-module pass, with their call chains."""
        entry = self.modules[mod_name]
        local = entry.index.reachable()
        out: Dict[str, Tuple[str, ...]] = {}
        for q, chain in self.global_reachability().items():
            mod, _, fn = q.partition(":")
            if mod == mod_name and fn not in local:
                out[fn] = chain
        return out

    def cross_module_gain(self) -> Dict[str, Tuple[str, ...]]:
        """Every globally-reachable qualname the per-module pass misses —
        the acceptance-criteria view (must be non-empty on this tree)."""
        out: Dict[str, Tuple[str, ...]] = {}
        for mod_name in self.modules:
            for fn, chain in self.module_extras(mod_name).items():
                out[f"{mod_name}:{fn}"] = chain
        return out


class _GraphWalker(ast.NodeVisitor):
    """Per-module sweep adding this module's edges to the global graph.

    Same attribution discipline as the local index (calls belong to the
    innermost enclosing def), but callees resolve through the import map
    first; only unresolved names fall back to same-module by-name edges.
    """

    def __init__(self, db: ProgramDB, entry: ModuleEntry):
        self.db = db
        self.entry = entry
        self._stack: List[str] = []
        self._fn_nodes: List[ast.AST] = []
        self._cls: List[str] = []

    def _handle_func(self, node) -> None:
        self._stack.append(node.name)
        self._fn_nodes.append(node)
        self.generic_visit(node)
        self._fn_nodes.pop()
        self._stack.pop()

    visit_FunctionDef = _handle_func
    visit_AsyncFunctionDef = _handle_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls.append(f"{self.entry.name}:{node.name}")
        self.generic_visit(node)
        self._cls.pop()

    def _add_edge(self, callee_q: str) -> None:
        if self._stack and callee_q in self.db.edges:
            caller_q = f"{self.entry.name}:{self._stack[-1]}"
            self.db.edges.setdefault(caller_q, set()).add(callee_q)

    def visit_Call(self, node: ast.Call) -> None:
        entry = self.entry
        target: Optional[str] = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
            if name in entry.imports:
                target = self.db.resolve_symbol(entry.imports[name])
            if target is None and name in entry.index.funcs:
                target = f"{entry.name}:{name}"
        elif isinstance(node.func, ast.Attribute):
            dotted = entry.index.dotted(node.func)
            if dotted:
                target = self._resolve_dotted(dotted)
            if target is None and node.func.attr in entry.index.funcs:
                # self.foo() / unknown-object attr: the per-module rule
                target = f"{entry.name}:{node.func.attr}"
        if target is not None:
            self._add_edge(target)

        # opt-in type-informed dispatch: obj.m() resolves through the
        # class model when the receiver class is unambiguous; edges that
        # only exist this way are recorded for the acceptance pin
        if self.db.type_informed and isinstance(node.func, ast.Attribute):
            tm = self.db.typed_method_target(
                entry,
                self._cls[-1] if self._cls else None,
                self._fn_nodes[-1] if self._fn_nodes else None,
                node,
            )
            if tm is not None:
                callee_q = f"{tm[0].split(':', 1)[0]}:{tm[1]}"
                if callee_q != target and self._stack:
                    caller_q = f"{entry.name}:{self._stack[-1]}"
                    if callee_q in self.db.edges and callee_q not in (
                        self.db.edges.get(caller_q, set())
                    ):
                        self.db.typed_edges.add((caller_q, callee_q))
                    self._add_edge(callee_q)

        # an *imported* function handed to a tracing transform becomes a
        # global root — the seed no per-module index can plant
        d = entry.index.dotted(node.func)
        if d and d.split(".")[-1] in _TRACER_WRAPPERS:
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in entry.imports:
                        q = self.db.resolve_symbol(entry.imports[sub.id])
                        if q is not None:
                            self.db.roots.add(q)
        self.generic_visit(node)

    def _resolve_dotted(self, dotted: str) -> Optional[str]:
        q = self.db.resolve_symbol(dotted)
        if q is not None:
            return q
        return self.db._resolve_local(self.entry, dotted)
