"""JAX-aware static analysis: AST lint + jaxpr contract checks.

Two complementary passes over the codebase, both runnable as
``stmgcn lint`` (see :mod:`stmgcn_tpu.analysis.cli`) and asserted clean
by tier-1 (``tests/test_analysis.py``):

- **Pass 1 — AST lint** (:mod:`.lint`): a visitor-based linter with
  repo-specific rules — version-fragile JAX imports (the compat table in
  :mod:`.rules`; the ``shard_map`` move that killed six test modules at
  collection is the canonical case), host-sync calls inside jit-reachable
  functions, Python control flow on traced values, ``time.time()`` spans
  around device dispatch without a readback fence (the
  :mod:`stmgcn_tpu.utils.profiling` lesson: on the tunneled axon backend
  an unfenced span times *dispatch*, not compute), train-step
  ``jax.jit`` calls missing ``donate_argnums``, and per-call-fresh
  callable identities (``functools.partial`` / bound methods / nested
  defs) at static argument positions. By default the pass is
  **whole-program**: :mod:`.program_db` parses every module once,
  resolves import aliases (including ``__init__`` re-export chains),
  and propagates jit-reachability across statically resolved
  inter-module calls, so a host-sync in a helper only *another*
  module's jitted code reaches is still flagged — with the cross-module
  call chain attached (``--no-whole-program`` restores the per-module
  view). The database also models classes (lock/condvar/thread fields,
  type-informed dispatch through unambiguous ``__init__``/annotation
  evidence), and :mod:`.concurrency_check` runs four concurrency rules
  off it repo-wide: ``unguarded-attr`` (guarded-by inference with
  cross-method chains), ``lock-order-cycle`` (global lock-acquisition
  graph), ``condvar-discipline``, and ``thread-lifecycle``.
- **Pass 2 — contract checks** (:mod:`.jaxpr_check`,
  :mod:`.sharding_check`, :mod:`.collective_check`,
  :mod:`.serving_check`): abstractly trace the smoke-preset step
  functions (and one serving bucket program) on CPU and assert jaxpr
  invariants (no silent fp64 promotions, no weak-type outputs that would
  recompile step 2, a primitive-count budget guarding against
  fusion-breaking regressions), static validation of every
  ``PartitionSpec`` literal against the mesh axis names and the
  placement rank table, collective-shape math for every multi-device
  preset (ppermute halo rows vs shard size, batch vs dp, m_graphs vs
  branch), resident-memory math for every preset (window-free series vs
  materialized-window footprint vs the per-core budget,
  :mod:`.resident_check`), fleet shape-class math for every preset that
  engages the fleet path (planner knobs, city coverage, per-class
  resident footprint, :mod:`.fleet_check`), serving bucket-ladder
  math for every preset (strictly increasing, covers max_batch, pad
  waste bounded), observability budget math for every preset (span-ring
  and histogram-reservoir bounds, :mod:`.obs_check`), numeric-health
  config math for every preset (drift-without-baseline, sketch and
  reservoir budgets, cadence, :mod:`.health_check`),
  serving-federation topology math for every preset (replica vs city
  counts, virtual-node count vs the imbalance bound, tier-wide
  overload budget vs per-replica local bounds, drain vs handover
  window ordering, :mod:`.federation_check`), and static Pallas
  kernel checks (:mod:`.pallas_check`):
  grid/BlockSpec divisibility plus a calibrated VMEM-footprint estimate
  for every ``pl.pallas_call`` site in :mod:`stmgcn_tpu.ops.pallas_lstm`
  and :mod:`stmgcn_tpu.ops.spmm`, reproducing the known 18.04 MB
  fp32-forward Mosaic OOM from source alone, and tiled-support plan
  math for every preset that turns on ``model.tiled`` (knob ranges,
  mode conflicts, tile-grid node-padding waste vs the budget, kernel
  VMEM at the configured tile — :mod:`.tiling_check`). The precision
  dataflow pass (:mod:`.dtype_flow` + :mod:`.precision_check`) rides
  the same traces: an abstract dtype interpreter tags every eqn of
  every registered contract program with its dtype and provenance
  chain, classifies sites by role (dot-general operands/accumulators,
  sum reductions, scan/while carries, psum, normalization stats,
  casts), and judges them against the preset's declarative
  ``PrecisionPolicy`` — three error rules (``precision-policy``,
  ``accum-dtype``, ``implicit-cast``) plus a per-program dtype census
  pinned by ``PRECISION_BASELINES`` (``--rebaseline``), so a bf16
  migration lands pre-certified by lint instead of discovered by loss
  curves.

Suppress a finding with ``# stmgcn: ignore[rule-id]`` (or a bare
``# stmgcn: ignore``) on the offending line.
"""

from stmgcn_tpu.analysis.collective_check import check_collective_contracts
from stmgcn_tpu.analysis.concurrency_check import check_concurrency
from stmgcn_tpu.analysis.continual_check import check_continual_config
from stmgcn_tpu.analysis.federation_check import check_federation_config
from stmgcn_tpu.analysis.fleet_check import check_fleet_shape_classes
from stmgcn_tpu.analysis.health_check import check_health_overhead
from stmgcn_tpu.analysis.jaxpr_check import check_step_contracts
from stmgcn_tpu.analysis.lint import lint_package, lint_paths, lint_source
from stmgcn_tpu.analysis.obs_check import check_obs_overhead
from stmgcn_tpu.analysis.dtype_flow import flow_program, program_flows
from stmgcn_tpu.analysis.pallas_check import check_pallas_kernels
from stmgcn_tpu.analysis.precision_check import (
    check_precision,
    precision_summary,
)
from stmgcn_tpu.analysis.program_db import ProgramDB
from stmgcn_tpu.analysis.report import (
    Finding,
    render_json,
    render_sarif,
    render_text,
)
from stmgcn_tpu.analysis.resident_check import check_resident_memory
from stmgcn_tpu.analysis.rules import RULES, Rule
from stmgcn_tpu.analysis.serving_check import (
    check_serving_buckets,
    check_serving_slo,
)
from stmgcn_tpu.analysis.sharding_check import check_partition_specs
from stmgcn_tpu.analysis.spmd_check import (
    check_spmd_contracts,
    declared_manifests,
    spmd_summary,
)
from stmgcn_tpu.analysis.tiling_check import check_tile_plan

__all__ = [
    "Finding",
    "ProgramDB",
    "RULES",
    "Rule",
    "check_collective_contracts",
    "check_concurrency",
    "check_continual_config",
    "check_federation_config",
    "check_fleet_shape_classes",
    "check_health_overhead",
    "check_obs_overhead",
    "check_pallas_kernels",
    "check_partition_specs",
    "check_precision",
    "check_resident_memory",
    "check_serving_buckets",
    "check_serving_slo",
    "check_spmd_contracts",
    "check_step_contracts",
    "check_tile_plan",
    "declared_manifests",
    "flow_program",
    "lint_package",
    "lint_paths",
    "lint_source",
    "precision_summary",
    "program_flows",
    "render_json",
    "render_sarif",
    "render_text",
    "spmd_summary",
]
