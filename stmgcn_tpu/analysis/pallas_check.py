"""Pass 2h: static Pallas kernel checks — BlockSpec/grid math + VMEM.

``benchmarks/mosaic_compile_check.py`` catches kernel sizing mistakes by
*really compiling* under Mosaic, which needs the axon tunnel's AOT path
to answer. This pass is the static approximation that gates earlier: it
parses ``ops/pallas_lstm.py``, extracts every ``pl.pallas_call`` site
(grid expression, per-operand ``BlockSpec`` shapes and index maps,
``out_shape`` structs), evaluates them against the shape arithmetic of
the enclosing function at a concrete kernel point, and checks

- **pallas-blockspec**: spec/operand arity, rank agreement, per-axis
  divisibility (every operand dim must be a multiple of its block dim),
  and grid coverage (``grid[0] * block_rows`` equals the padded rows on
  the streamed axis);
- **pallas-vmem**: a footprint estimate against the ~16 MiB/core scoped
  VMEM budget. Blocks whose index map uses the grid index are *streamed*
  and double-buffered by the pipeline (×2); constant-index blocks are
  resident once. The model is ``CALIBRATION × (2 × streamed_bytes +
  resident_bytes)``, with the calibration constant fitted to the one
  piece of real Mosaic AOT evidence this repo owns: the fp32 forward
  kernel at the pre-packing 128-row block allocating **18.04 MB vs the
  16 MB limit** (bench_stderr.log 2026-07-29, reproduced by
  ``mosaic_compile_check.py``). The constant absorbs what the block
  arithmetic can't see — kernel temporaries of the unrolled T×L
  recurrence and Mosaic's own stack — and the model is validated in both
  directions: it must flag that OOM point and pass every shipped
  ``_block_rows``-sized kernel (tests/test_analysis.py pins both).

The extraction is genuinely syntactic — edit a BlockSpec in
``ops/pallas_lstm.py`` and this pass re-derives the math from the new
source. If the source drifts past what the evaluator understands (new
variable names, a new pallas_call site), the check fails loudly with a
``pallas-blockspec`` out-of-sync finding rather than silently passing.
"""

from __future__ import annotations

import ast
import dataclasses
import math
import os
from typing import Dict, Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = [
    "KernelPoint",
    "PallasSite",
    "VMEM_BUDGET_BYTES",
    "check_pallas_kernels",
    "extract_pallas_sites",
    "vmem_estimate",
]

#: per-core scoped VMEM the Mosaic pipeline may allocate (v5e guide)
VMEM_BUDGET_BYTES = 16 * 1024 * 1024

#: streamed (grid-indexed) blocks are double-buffered by the pipeline
PIPELINE_FACTOR = 2

#: fitted so the fp32 forward kernel at the historical 128-row block
#: (T=12, L=3, H=64) estimates 18.04 MiB — the allocation real Mosaic
#: AOT reported for exactly that configuration. One real observation,
#: one free constant; everything else is block arithmetic.
CALIBRATION = 2.1064

_ITEMSIZE = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8}


@dataclasses.dataclass(frozen=True)
class KernelPoint:
    """One concrete kernel configuration to check the sites against.

    Defaults are the canonical bench point (``benchmarks/bench.py``:
    M=3 branches over R=16384 rows, T=12, L=3, H=64). ``fwd_rows`` /
    ``bwd_rows`` override the ``_block_rows`` derivation — that is how
    the known-OOM fixture reconstructs the pre-halving calibration.
    """

    dtype: str = "float32"
    seq_len: int = 12
    layers: int = 3
    hidden: int = 64
    rows: int = 16384
    fwd_rows: Optional[int] = None
    bwd_rows: Optional[int] = None

    @property
    def itemsize(self) -> int:
        return _ITEMSIZE[self.dtype]

    def block_rows(self) -> Tuple[int, int]:
        fwd, bwd = self.fwd_rows, self.bwd_rows
        if fwd is None or bwd is None:
            # the real derivation (env overrides included) — the checker
            # validates the configuration the kernel would actually run
            from stmgcn_tpu.ops.pallas_lstm import _block_rows

            dfwd, dbwd = _block_rows(self.itemsize, self.seq_len, self.layers)
            fwd = dfwd if fwd is None else fwd
            bwd = dbwd if bwd is None else bwd
        return fwd, bwd

    def describe(self) -> str:
        return (
            f"{self.dtype} T={self.seq_len} L={self.layers} H={self.hidden}"
        )


@dataclasses.dataclass(frozen=True)
class BlockUse:
    """One operand's block at one site: shape, full shape, streaming."""

    operand: str
    block: Tuple[int, ...]
    operand_shape: Tuple[int, ...]
    itemsize: int
    streamed: bool
    streamed_axis: Optional[int]

    @property
    def nbytes(self) -> int:
        return int(math.prod(self.block)) * self.itemsize


@dataclasses.dataclass(frozen=True)
class PallasSite:
    """One ``pl.pallas_call`` call site, still as AST."""

    fn: str  # enclosing function name
    path: str
    line: int
    grid: ast.expr
    in_specs: List[ast.expr]
    out_specs: List[ast.expr]
    out_shape: List[ast.expr]
    operands: List[str]  # names of the arrays the wrapped call receives


class _Unresolved(Exception):
    """The evaluator met a name/construct outside the site's env."""


def _ev(node: ast.AST, names: Dict[str, object]):
    """Tiny shape-arithmetic evaluator (ints, tuples, +,-,*,//,%, max)."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in names:
            return names[node.id]
        raise _Unresolved(node.id)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(_ev(e, names) for e in node.elts)
    if isinstance(node, ast.Attribute):
        parts = []
        cur: ast.AST = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            dotted = ".".join([cur.id] + list(reversed(parts)))
            if dotted in names:
                return names[dotted]
        raise _Unresolved(ast.dump(node))
    if isinstance(node, ast.BinOp):
        lhs, rhs = _ev(node.left, names), _ev(node.right, names)
        ops = {
            ast.Add: lambda a, b: a + b,
            ast.Sub: lambda a, b: a - b,
            ast.Mult: lambda a, b: a * b,
            ast.FloorDiv: lambda a, b: a // b,
            ast.Mod: lambda a, b: a % b,
        }
        fn = ops.get(type(node.op))
        if fn is None:
            raise _Unresolved(ast.dump(node.op))
        return fn(lhs, rhs)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_ev(node.operand, names)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id == "max":
            return max(_ev(a, names) for a in node.args)
        if node.func.id == "min":
            return min(_ev(a, names) for a in node.args)
    raise _Unresolved(ast.dump(node))


def _default_kernel_path() -> str:
    import stmgcn_tpu

    pkg = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
    return os.path.join(pkg, "ops", "pallas_lstm.py")


def extract_pallas_sites(path: Optional[str] = None) -> List[PallasSite]:
    """AST-extract every ``pl.pallas_call`` site in ``path`` (default:
    the shipped ``ops/pallas_lstm.py``). Pure syntax — no jax import."""
    from stmgcn_tpu.analysis.lint import _ModuleIndex

    path = path or _default_kernel_path()
    source = open(path).read()
    tree = ast.parse(source)
    index = _ModuleIndex()
    index.visit(tree)

    rel = os.path.relpath(path, os.getcwd())
    rel = path if rel.startswith("..") else rel.replace(os.sep, "/")

    sites: List[PallasSite] = []

    class _Finder(ast.NodeVisitor):
        def __init__(self):
            self._stack: List[str] = []

        def _handle_func(self, node):
            self._stack.append(node.name)
            self.generic_visit(node)
            self._stack.pop()

        visit_FunctionDef = _handle_func
        visit_AsyncFunctionDef = _handle_func

        def visit_Call(self, node: ast.Call) -> None:
            # shape: pl.pallas_call(kernel, grid=..., ...)(op0, op1, ...)
            if isinstance(node.func, ast.Call):
                d = index.dotted(node.func.func)
                if d and d.split(".")[-1] == "pallas_call":
                    inner = node.func
                    kw = {k.arg: k.value for k in inner.keywords}
                    operands = [
                        a.id if isinstance(a, ast.Name) else f"<arg{i}>"
                        for i, a in enumerate(node.args)
                    ]

                    def elts(name):
                        v = kw.get(name)
                        if isinstance(v, (ast.Tuple, ast.List)):
                            return list(v.elts)
                        return [] if v is None else [v]

                    sites.append(
                        PallasSite(
                            fn=self._stack[-1] if self._stack else "<module>",
                            path=rel,
                            line=node.lineno,
                            grid=kw.get("grid"),
                            in_specs=elts("in_specs"),
                            out_specs=elts("out_specs"),
                            out_shape=elts("out_shape"),
                            operands=operands,
                        )
                    )
            self.generic_visit(node)

    _Finder().visit(tree)
    return sites


def _round_up(n: int, block: int) -> int:
    return -(-n // block) * block


def _site_env(site: PallasSite, point: KernelPoint) -> Dict[str, object]:
    """The enclosing function's shape bindings at ``point`` — mirrors
    the arithmetic of ``_run_fwd`` / ``_fused_bwd`` in ops/pallas_lstm.py.
    Unknown sites raise :class:`_Unresolved` (checker out of sync)."""
    H, T, L = point.hidden, point.seq_len, point.layers
    four_h, h_dim = 4 * H, H
    fwd_block, bwd_block = point.block_rows()
    wxh_shape = (max(L - 1, 1), 2 * H, 4 * H)
    b_shape = (max(L - 1, 1), 4 * H)
    common = {
        "T": T, "L": L, "four_h": four_h, "h_dim": h_dim,
        "wxh.shape": wxh_shape, "b_stack.shape": b_shape,
        # out_shape dtypes: storage dtype or the kernel's f32 accumulators
        "dtype": point.itemsize, "f32": 4,
    }
    if site.fn == "_run_fwd":
        rp = _round_up(point.rows, fwd_block)
        shapes = {
            "xp": (T, rp, four_h),
            "wh0": (h_dim, four_h),
            "wxh": wxh_shape,
            "b_stack": b_shape,
        }
        return {**common, "block_fwd": fwd_block, "rp": rp,
                "grid": (rp // fwd_block,), "__shapes__": shapes}
    if site.fn == "_fused_bwd":
        rp = _round_up(point.rows, bwd_block)
        rp_fwd = _round_up(point.rows, fwd_block)  # residual padding
        shapes = {
            "xp": (T, rp, four_h),
            "wh0": (h_dim, four_h),
            "wxh": wxh_shape,
            "b_stack": b_shape,
            "hseq": (T, L, rp_fwd, h_dim),
            "cseq": (T, L, rp_fwd, h_dim),
            "gout": (T, rp, h_dim),
            "ghfin": (L, rp, h_dim),
            "gcfin": (L, rp, h_dim),
        }
        return {**common, "block_bwd": bwd_block, "rp": rp,
                "grid": (rp // bwd_block,), "__shapes__": shapes}
    raise _Unresolved(f"unknown pallas_call site `{site.fn}`")


def _spec_parts(spec: ast.expr) -> Tuple[ast.expr, Optional[ast.Lambda]]:
    """``pl.BlockSpec(shape, index_map)`` -> (shape expr, lambda|None)."""
    if not isinstance(spec, ast.Call):
        raise _Unresolved(ast.dump(spec))
    shape = spec.args[0] if spec.args else None
    imap = spec.args[1] if len(spec.args) > 1 else None
    for k in spec.keywords:
        if k.arg in ("block_shape",):
            shape = k.value
        elif k.arg in ("index_map",):
            imap = k.value
    if shape is None:
        raise _Unresolved("BlockSpec without a block shape")
    if imap is not None and not isinstance(imap, ast.Lambda):
        raise _Unresolved("non-lambda index_map")
    return shape, imap


def _streamed_axis(imap: Optional[ast.Lambda]) -> Optional[int]:
    """Index of the block axis driven by the grid index; None = constant.

    ``lambda i: (0, i, 0)`` streams axis 1; an index map that ignores its
    parameter revisits one block every grid step (resident/accumulator).
    """
    if imap is None or not imap.args.args:
        return None
    param = imap.args.args[0].arg
    body = imap.body
    elts = body.elts if isinstance(body, (ast.Tuple, ast.List)) else [body]
    for axis, e in enumerate(elts):
        if any(
            isinstance(s, ast.Name) and s.id == param for s in ast.walk(e)
        ):
            return axis
    return None


def _site_blocks(
    site: PallasSite, point: KernelPoint
) -> Tuple[Tuple[int, ...], List[BlockUse]]:
    """Evaluate the site at ``point`` -> (grid, every operand's block)."""
    env = _site_env(site, point)
    names = {k: v for k, v in env.items() if k != "__shapes__"}
    op_shapes: Dict[str, Tuple[int, ...]] = env["__shapes__"]

    grid_v = _ev(site.grid, names) if site.grid is not None else (1,)
    grid = tuple(grid_v) if isinstance(grid_v, tuple) else (int(grid_v),)

    uses: List[BlockUse] = []
    if len(site.in_specs) != len(site.operands):
        raise _Unresolved(
            f"{site.fn}: {len(site.in_specs)} in_specs for "
            f"{len(site.operands)} operands"
        )
    for spec, operand in zip(site.in_specs, site.operands):
        shape_e, imap = _spec_parts(spec)
        block = tuple(_ev(shape_e, names))
        if operand not in op_shapes:
            raise _Unresolved(f"{site.fn}: unknown operand `{operand}`")
        axis = _streamed_axis(imap)
        uses.append(
            BlockUse(operand, block, op_shapes[operand], point.itemsize,
                     axis is not None, axis)
        )
    if len(site.out_specs) != len(site.out_shape):
        raise _Unresolved(
            f"{site.fn}: {len(site.out_specs)} out_specs for "
            f"{len(site.out_shape)} out_shape structs"
        )
    for i, (spec, struct) in enumerate(zip(site.out_specs, site.out_shape)):
        shape_e, imap = _spec_parts(spec)
        block = tuple(_ev(shape_e, names))
        if not (isinstance(struct, ast.Call) and len(struct.args) >= 2):
            raise _Unresolved(f"{site.fn}: out_shape[{i}] not a struct")
        full = tuple(_ev(struct.args[0], names))
        itemsize = int(_ev(struct.args[1], names))
        axis = _streamed_axis(imap)
        uses.append(
            BlockUse(f"<out{i}>", block, full, itemsize,
                     axis is not None, axis)
        )
    return grid, uses


def vmem_estimate(site: PallasSite, point: KernelPoint) -> Dict[str, float]:
    """The calibrated footprint model at ``point`` (bytes + MiB views)."""
    _, uses = _site_blocks(site, point)
    streamed = sum(u.nbytes for u in uses if u.streamed)
    resident = sum(u.nbytes for u in uses if not u.streamed)
    est = CALIBRATION * (PIPELINE_FACTOR * streamed + resident)
    return {
        "site": site.fn,
        "streamed_bytes": streamed,
        "resident_bytes": resident,
        "estimate_bytes": est,
        "estimate_mib": est / (1 << 20),
        "budget_bytes": VMEM_BUDGET_BYTES,
    }


def _check_site(site: PallasSite, point: KernelPoint) -> List[Finding]:
    findings: List[Finding] = []

    def emit(rule: str, message: str) -> None:
        findings.append(
            Finding(rule=rule, path=site.path, line=site.line,
                    message=message, severity=RULES[rule].severity)
        )

    try:
        grid, uses = _site_blocks(site, point)
    except _Unresolved as e:
        emit(
            "pallas-blockspec",
            f"`{site.fn}` pallas_call: static checker out of sync with the "
            f"source ({e}) — update analysis/pallas_check.py alongside the "
            "kernel",
        )
        return findings

    for u in uses:
        if len(u.block) != len(u.operand_shape):
            emit(
                "pallas-blockspec",
                f"`{site.fn}` [{point.describe()}]: operand `{u.operand}` "
                f"block rank {len(u.block)} != operand rank "
                f"{len(u.operand_shape)}",
            )
            continue
        for axis, (b, full) in enumerate(zip(u.block, u.operand_shape)):
            if b <= 0 or full % b:
                emit(
                    "pallas-blockspec",
                    f"`{site.fn}` [{point.describe()}]: operand "
                    f"`{u.operand}` axis {axis} block {b} does not divide "
                    f"the operand dim {full} — Mosaic pads or rejects the "
                    "ragged final block",
                )
        if u.streamed and u.streamed_axis is not None:
            axis = u.streamed_axis
            if axis < len(u.block) and grid:
                covered = grid[0] * u.block[axis]
                if covered != u.operand_shape[axis]:
                    emit(
                        "pallas-blockspec",
                        f"`{site.fn}` [{point.describe()}]: grid {grid[0]} x "
                        f"block {u.block[axis]} covers {covered} of "
                        f"{u.operand_shape[axis]} rows of `{u.operand}` — "
                        "the kernel would read/write a row range it was "
                        "never given",
                    )

    est = vmem_estimate(site, point)
    if est["estimate_bytes"] > VMEM_BUDGET_BYTES:
        emit(
            "pallas-vmem",
            f"`{site.fn}` [{point.describe()}]: estimated VMEM footprint "
            f"{est['estimate_mib']:.2f} MiB exceeds the "
            f"{VMEM_BUDGET_BYTES >> 20} MiB/core scoped budget "
            f"(2x-buffered streamed blocks {est['streamed_bytes']} B + "
            f"resident blocks {est['resident_bytes']} B, calibration "
            f"x{CALIBRATION}) — shrink the block rows "
            "(STMGCN_PALLAS_FWD_ROWS/BWD_ROWS) or the block shapes",
        )
    return findings


def check_pallas_kernels(
    points: Optional[Iterable[KernelPoint]] = None,
    path: Optional[str] = None,
) -> List[Finding]:
    """Check every extracted pallas_call site at every ``point``.

    Default points: the bench configuration in both storage dtypes, with
    blocks derived by the kernel's own ``_block_rows`` (env overrides
    included, so an operator's ``STMGCN_PALLAS_FWD_ROWS`` experiment is
    checked as configured).
    """
    if points is None:
        points = [KernelPoint(dtype="float32"), KernelPoint(dtype="bfloat16")]
    sites = extract_pallas_sites(path)
    if not sites:
        return [
            Finding(
                rule="pallas-blockspec",
                path=path or _default_kernel_path(),
                line=0,
                message="no pl.pallas_call site found — the kernel moved "
                "and the static checker lost it; update "
                "analysis/pallas_check.py",
                severity=RULES["pallas-blockspec"].severity,
            )
        ]
    findings: List[Finding] = []
    for site in sites:
        for point in points:
            findings.extend(_check_site(site, point))
    return findings
