"""Pass 2h: static Pallas kernel checks — BlockSpec/grid math + VMEM.

``benchmarks/mosaic_compile_check.py`` catches kernel sizing mistakes by
*really compiling* under Mosaic, which needs the axon tunnel's AOT path
to answer. This pass is the static approximation that gates earlier: it
parses ``ops/pallas_lstm.py``, extracts every ``pl.pallas_call`` site
(grid expression, per-operand ``BlockSpec`` shapes and index maps,
``out_shape`` structs), evaluates them against the shape arithmetic of
the enclosing function at a concrete kernel point, and checks

- **pallas-blockspec**: spec/operand arity, rank agreement, per-axis
  divisibility (every operand dim must be a multiple of its block dim),
  and grid coverage (``grid[0] * block_rows`` equals the padded rows on
  the streamed axis);
- **pallas-vmem**: a footprint estimate against the ~16 MiB/core scoped
  VMEM budget. Blocks whose index map uses the grid index are *streamed*
  and double-buffered by the pipeline (×2); constant-index blocks are
  resident once. The model is ``CALIBRATION × (2 × streamed_bytes +
  resident_bytes)``, with the calibration constant fitted to the one
  piece of real Mosaic AOT evidence this repo owns: the fp32 forward
  kernel at the pre-packing 128-row block allocating **18.04 MB vs the
  16 MB limit** (bench_stderr.log 2026-07-29, reproduced by
  ``mosaic_compile_check.py``). The constant absorbs what the block
  arithmetic can't see — kernel temporaries of the unrolled T×L
  recurrence and Mosaic's own stack — and the model is validated in both
  directions: it must flag that OOM point and pass every shipped
  ``_block_rows``-sized kernel (tests/test_analysis.py pins both).

The extraction is genuinely syntactic — edit a BlockSpec in
``ops/pallas_lstm.py`` and this pass re-derives the math from the new
source. If the source drifts past what the evaluator understands (new
variable names, a new pallas_call site), the check fails loudly with a
``pallas-blockspec`` out-of-sync finding rather than silently passing.

The site model covers every ``pl.pallas_call`` in the repo — the LSTM
kernels above plus the three block-sparse SpMM launches in
``ops/spmm.py`` (``_spmm_call``, ``_stack_fwd_call``,
``_stack_bwd_call``). The SpMM sites wrap their geometry in
``pltpu.PrefetchScalarGridSpec`` (scalar-prefetched block-column index
lists), so the extractor also unwraps ``grid_spec=`` keywords, aligns
``in_specs`` against the operands *after* the prefetch arguments, and
classifies ``idx_ref[i, c]``-indexed axes as dynamically streamed
(gathered — double-buffered like any streamed block, but with no
statically checkable grid coverage). The prefetch index list itself
lives in SMEM and is excluded from the VMEM estimate.
"""

from __future__ import annotations

import ast
import dataclasses
import math
import os
from typing import Dict, Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = [
    "KERNEL_MODULES",
    "KernelPoint",
    "PallasSite",
    "SpmmKernelPoint",
    "VMEM_BUDGET_BYTES",
    "check_pallas_kernels",
    "extract_pallas_sites",
    "vmem_estimate",
]

#: per-core scoped VMEM the Mosaic pipeline may allocate (v5e guide)
VMEM_BUDGET_BYTES = 16 * 1024 * 1024

#: streamed (grid-indexed) blocks are double-buffered by the pipeline
PIPELINE_FACTOR = 2

#: fitted so the fp32 forward kernel at the historical 128-row block
#: (T=12, L=3, H=64) estimates 18.04 MiB — the allocation real Mosaic
#: AOT reported for exactly that configuration. One real observation,
#: one free constant; everything else is block arithmetic.
CALIBRATION = 2.1064

_ITEMSIZE = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8}

#: every module that owns a ``pl.pallas_call`` site, relative to the
#: package root; ``check_pallas_kernels`` covers them all by default and
#: tests/test_analysis.py asserts the repo grows no uncovered site
KERNEL_MODULES = ("ops/pallas_lstm.py", "ops/spmm.py")

_LSTM_FNS = frozenset({"_run_fwd", "_fused_bwd"})
_SPMM_FNS = frozenset({"_spmm_call", "_stack_fwd_call", "_stack_bwd_call"})


@dataclasses.dataclass(frozen=True)
class KernelPoint:
    """One concrete kernel configuration to check the sites against.

    Defaults are the canonical bench point (``benchmarks/bench.py``:
    M=3 branches over R=16384 rows, T=12, L=3, H=64). ``fwd_rows`` /
    ``bwd_rows`` override the ``_block_rows`` derivation — that is how
    the known-OOM fixture reconstructs the pre-halving calibration.
    """

    dtype: str = "float32"
    seq_len: int = 12
    layers: int = 3
    hidden: int = 64
    rows: int = 16384
    fwd_rows: Optional[int] = None
    bwd_rows: Optional[int] = None

    @property
    def itemsize(self) -> int:
        return _ITEMSIZE[self.dtype]

    def block_rows(self) -> Tuple[int, int]:
        fwd, bwd = self.fwd_rows, self.bwd_rows
        if fwd is None or bwd is None:
            # the real derivation (env overrides included) — the checker
            # validates the configuration the kernel would actually run
            from stmgcn_tpu.ops.pallas_lstm import _block_rows

            dfwd, dbwd = _block_rows(self.itemsize, self.seq_len, self.layers)
            fwd = dfwd if fwd is None else fwd
            bwd = dbwd if bwd is None else bwd
        return fwd, bwd

    def describe(self) -> str:
        return (
            f"{self.dtype} T={self.seq_len} L={self.layers} H={self.hidden}"
        )


@dataclasses.dataclass(frozen=True)
class SpmmKernelPoint:
    """One concrete block-sparse SpMM configuration (``ops/spmm.py``).

    Defaults are the largeN bench plan: N = R x 128 = 8192 permuted
    nodes, K = 3 Chebyshev supports per stacked launch, C stored block
    columns per row, and M dense signal columns (batch x features).
    ``r_t``/``c_max_t`` size the pre-transposed backward stacks.
    """

    dtype: str = "float32"
    tile: int = 128
    k: int = 3
    r: int = 64
    c_max: int = 8
    r_t: int = 64
    c_max_t: int = 8
    m: int = 256

    @property
    def itemsize(self) -> int:
        return _ITEMSIZE[self.dtype]

    def describe(self) -> str:
        return (
            f"{self.dtype} tile={self.tile} K={self.k} R={self.r} "
            f"C={self.c_max} M={self.m}"
        )


@dataclasses.dataclass(frozen=True)
class BlockUse:
    """One operand's block at one site: shape, full shape, streaming.

    ``roles`` is the per-axis index-map classification (None for a
    spec without an index map): ``("const", None)`` revisits one block,
    ``("param", p)`` is driven directly by grid parameter ``p`` (its
    coverage is statically checkable), ``("dynamic", None)`` is a
    computed index — the SpMM kernels' ``idx_ref[i, c]`` gathers.
    """

    operand: str
    block: Tuple[int, ...]
    operand_shape: Tuple[int, ...]
    itemsize: int
    streamed: bool
    roles: Optional[Tuple[Tuple[str, Optional[int]], ...]]

    @property
    def nbytes(self) -> int:
        return int(math.prod(self.block)) * self.itemsize


@dataclasses.dataclass(frozen=True)
class PallasSite:
    """One ``pl.pallas_call`` call site, still as AST."""

    fn: str  # enclosing function name
    path: str
    line: int
    grid: ast.expr
    in_specs: List[ast.expr]
    out_specs: List[ast.expr]
    out_shape: List[ast.expr]
    operands: List[str]  # names of the arrays the wrapped call receives
    #: leading operands consumed by PrefetchScalarGridSpec (SMEM scalars
    #: — no in_spec, no VMEM block)
    num_scalar_prefetch: int = 0


class _Unresolved(Exception):
    """The evaluator met a name/construct outside the site's env."""


def _ev(node: ast.AST, names: Dict[str, object]):
    """Tiny shape-arithmetic evaluator (ints, tuples, +,-,*,//,%, max)."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in names:
            return names[node.id]
        raise _Unresolved(node.id)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(_ev(e, names) for e in node.elts)
    if isinstance(node, ast.Attribute):
        parts = []
        cur: ast.AST = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            dotted = ".".join([cur.id] + list(reversed(parts)))
            if dotted in names:
                return names[dotted]
        raise _Unresolved(ast.dump(node))
    if isinstance(node, ast.BinOp):
        lhs, rhs = _ev(node.left, names), _ev(node.right, names)
        ops = {
            ast.Add: lambda a, b: a + b,
            ast.Sub: lambda a, b: a - b,
            ast.Mult: lambda a, b: a * b,
            ast.FloorDiv: lambda a, b: a // b,
            ast.Mod: lambda a, b: a % b,
        }
        fn = ops.get(type(node.op))
        if fn is None:
            raise _Unresolved(ast.dump(node.op))
        return fn(lhs, rhs)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_ev(node.operand, names)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id == "max":
            return max(_ev(a, names) for a in node.args)
        if node.func.id == "min":
            return min(_ev(a, names) for a in node.args)
    raise _Unresolved(ast.dump(node))


def _default_kernel_path(module: str = "ops/pallas_lstm.py") -> str:
    import stmgcn_tpu

    pkg = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
    return os.path.join(pkg, *module.split("/"))


def extract_pallas_sites(path: Optional[str] = None) -> List[PallasSite]:
    """AST-extract every ``pl.pallas_call`` site in ``path`` (default:
    the shipped ``ops/pallas_lstm.py``). Pure syntax — no jax import."""
    from stmgcn_tpu.analysis.lint import _ModuleIndex

    path = path or _default_kernel_path()
    source = open(path).read()
    tree = ast.parse(source)
    index = _ModuleIndex()
    index.visit(tree)

    rel = os.path.relpath(path, os.getcwd())
    rel = path if rel.startswith("..") else rel.replace(os.sep, "/")

    sites: List[PallasSite] = []

    class _Finder(ast.NodeVisitor):
        def __init__(self):
            self._stack: List[str] = []
            self._assigns: List[Dict[str, ast.expr]] = [{}]

        def _handle_func(self, node):
            self._stack.append(node.name)
            self._assigns.append({})
            self.generic_visit(node)
            self._assigns.pop()
            self._stack.pop()

        visit_FunctionDef = _handle_func
        visit_AsyncFunctionDef = _handle_func

        def visit_Assign(self, node: ast.Assign) -> None:
            # remember function-local `name = expr` so a
            # `grid_spec=pltpu.PrefetchScalarGridSpec(...)` bound to a
            # variable first still resolves to its construction
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                self._assigns[-1][node.targets[0].id] = node.value
            self.generic_visit(node)

        def visit_Call(self, node: ast.Call) -> None:
            # shape: pl.pallas_call(kernel, grid=..., ...)(op0, op1, ...)
            if isinstance(node.func, ast.Call):
                d = index.dotted(node.func.func)
                if d and d.split(".")[-1] == "pallas_call":
                    inner = node.func
                    kw = {k.arg: k.value for k in inner.keywords}
                    nsp = 0
                    gs = kw.get("grid_spec")
                    if isinstance(gs, ast.Name):
                        for scope in reversed(self._assigns):
                            if gs.id in scope:
                                gs = scope[gs.id]
                                break
                    if isinstance(gs, ast.Call):
                        gkw = {k.arg: k.value for k in gs.keywords}
                        n = gkw.get("num_scalar_prefetch")
                        if isinstance(n, ast.Constant):
                            nsp = int(n.value)
                        # grid/in_specs/out_specs live on the grid spec;
                        # out_shape stays on the pallas_call itself
                        kw = {**gkw, **kw}
                    operands = [
                        a.id if isinstance(a, ast.Name) else f"<arg{i}>"
                        for i, a in enumerate(node.args)
                    ]

                    def elts(name):
                        v = kw.get(name)
                        if isinstance(v, (ast.Tuple, ast.List)):
                            return list(v.elts)
                        return [] if v is None else [v]

                    sites.append(
                        PallasSite(
                            fn=self._stack[-1] if self._stack else "<module>",
                            path=rel,
                            line=node.lineno,
                            grid=kw.get("grid"),
                            in_specs=elts("in_specs"),
                            out_specs=elts("out_specs"),
                            out_shape=elts("out_shape"),
                            operands=operands,
                            num_scalar_prefetch=nsp,
                        )
                    )
            self.generic_visit(node)

    _Finder().visit(tree)
    return sites


def _round_up(n: int, block: int) -> int:
    return -(-n // block) * block


def _spmm_site_env(site: PallasSite, point: SpmmKernelPoint) -> Dict[str, object]:
    """Shape bindings of the ``ops/spmm.py`` launch wrappers at ``point``
    — mirrors ``_spmm_call`` / ``_stack_fwd_call`` / ``_stack_bwd_call``
    (``tm = min(256, ceil(M, TILE))`` column tiling, row padding to the
    block grid). The scalar-prefetched index list is SMEM-resident and
    carries no BlockSpec, so it appears in the shape table only."""
    t = point.tile
    tm = min(256, _round_up(point.m, 128))
    m_pad = _round_up(point.m, tm)
    common = {
        "tile": t, "tm": tm, "m_pad": m_pad, "mb": m_pad // tm,
        "jnp.float32": 4,
    }
    if site.fn == "_spmm_call":
        r, c = point.r, point.c_max
        shapes = {
            "idx": (r, c),
            "data": (r, c, t, t),
            "x_pad": (r * t, m_pad),
        }
        return {**common, "r": r, "c_max": c, "n_pad": r * t,
                "__shapes__": shapes}
    if site.fn == "_stack_fwd_call":
        k, r, c = point.k, point.r, point.c_max
        shapes = {
            "idx": (k, r, c),
            "data": (k, r, c, t, t),
            # the signal is passed as x_pad[None] — a subscript, so the
            # extractor names it positionally
            "<arg2>": (1, r * t, m_pad),
        }
        return {**common, "k": k, "r": r, "c_max": c, "__shapes__": shapes}
    if site.fn == "_stack_bwd_call":
        k, r_t, c_t = point.k, point.r_t, point.c_max_t
        shapes = {
            "idx_t": (k, r_t, c_t),
            "data_t": (k, r_t, c_t, t, t),
            "g_pad": (k, point.r * t, m_pad),
        }
        return {**common, "k": k, "r_t": r_t, "c_max_t": c_t,
                "__shapes__": shapes}
    raise _Unresolved(f"unknown spmm pallas_call site `{site.fn}`")


def _site_env(site: PallasSite, point) -> Dict[str, object]:
    """The enclosing function's shape bindings at ``point`` — mirrors
    the arithmetic of ``_run_fwd`` / ``_fused_bwd`` in ops/pallas_lstm.py
    and the SpMM launch wrappers in ops/spmm.py.
    Unknown sites raise :class:`_Unresolved` (checker out of sync)."""
    if site.fn in _SPMM_FNS or isinstance(point, SpmmKernelPoint):
        if not (site.fn in _SPMM_FNS and isinstance(point, SpmmKernelPoint)):
            raise _Unresolved(
                f"site `{site.fn}` checked against {type(point).__name__}"
            )
        return _spmm_site_env(site, point)
    H, T, L = point.hidden, point.seq_len, point.layers
    four_h, h_dim = 4 * H, H
    fwd_block, bwd_block = point.block_rows()
    wxh_shape = (max(L - 1, 1), 2 * H, 4 * H)
    b_shape = (max(L - 1, 1), 4 * H)
    common = {
        "T": T, "L": L, "four_h": four_h, "h_dim": h_dim,
        "wxh.shape": wxh_shape, "b_stack.shape": b_shape,
        # out_shape dtypes: storage dtype or the kernel's f32 accumulators
        "dtype": point.itemsize, "f32": 4,
    }
    if site.fn == "_run_fwd":
        rp = _round_up(point.rows, fwd_block)
        shapes = {
            "xp": (T, rp, four_h),
            "wh0": (h_dim, four_h),
            "wxh": wxh_shape,
            "b_stack": b_shape,
        }
        return {**common, "block_fwd": fwd_block, "rp": rp,
                "grid": (rp // fwd_block,), "__shapes__": shapes}
    if site.fn == "_fused_bwd":
        rp = _round_up(point.rows, bwd_block)
        rp_fwd = _round_up(point.rows, fwd_block)  # residual padding
        shapes = {
            "xp": (T, rp, four_h),
            "wh0": (h_dim, four_h),
            "wxh": wxh_shape,
            "b_stack": b_shape,
            "hseq": (T, L, rp_fwd, h_dim),
            "cseq": (T, L, rp_fwd, h_dim),
            "gout": (T, rp, h_dim),
            "ghfin": (L, rp, h_dim),
            "gcfin": (L, rp, h_dim),
        }
        return {**common, "block_bwd": bwd_block, "rp": rp,
                "grid": (rp // bwd_block,), "__shapes__": shapes}
    raise _Unresolved(f"unknown pallas_call site `{site.fn}`")


def _spec_parts(spec: ast.expr) -> Tuple[ast.expr, Optional[ast.Lambda]]:
    """``pl.BlockSpec(shape, index_map)`` -> (shape expr, lambda|None)."""
    if not isinstance(spec, ast.Call):
        raise _Unresolved(ast.dump(spec))
    shape = spec.args[0] if spec.args else None
    imap = spec.args[1] if len(spec.args) > 1 else None
    for k in spec.keywords:
        if k.arg in ("block_shape",):
            shape = k.value
        elif k.arg in ("index_map",):
            imap = k.value
    if shape is None:
        raise _Unresolved("BlockSpec without a block shape")
    if imap is not None and not isinstance(imap, ast.Lambda):
        raise _Unresolved("non-lambda index_map")
    return shape, imap


def _axis_roles(
    imap: Optional[ast.Lambda],
) -> Optional[Tuple[Tuple[str, Optional[int]], ...]]:
    """Classify each block axis of an index map (None for no map).

    ``lambda i: (0, i, 0)`` -> const/param-0/const; multi-parameter
    maps (``lambda ki, i, j, c, idx_ref: ...``) record which lambda
    parameter drives each axis; any computed index that references a
    parameter (``idx_ref[ki, i, c]``) is ``dynamic`` — streamed, but
    with no statically checkable coverage. A map that ignores every
    parameter revisits one block per grid step (resident/accumulator).
    """
    if imap is None or not imap.args.args:
        return None
    params = [a.arg for a in imap.args.args]
    body = imap.body
    elts = body.elts if isinstance(body, (ast.Tuple, ast.List)) else [body]
    roles: List[Tuple[str, Optional[int]]] = []
    for e in elts:
        if isinstance(e, ast.Name) and e.id in params:
            roles.append(("param", params.index(e.id)))
        elif any(
            isinstance(s, ast.Name) and s.id in params for s in ast.walk(e)
        ):
            roles.append(("dynamic", None))
        else:
            roles.append(("const", None))
    return tuple(roles)


def _site_blocks(
    site: PallasSite, point
) -> Tuple[Tuple[int, ...], List[BlockUse]]:
    """Evaluate the site at ``point`` -> (grid, every operand's block)."""
    env = _site_env(site, point)
    names = {k: v for k, v in env.items() if k != "__shapes__"}
    op_shapes: Dict[str, Tuple[int, ...]] = env["__shapes__"]

    grid_v = _ev(site.grid, names) if site.grid is not None else (1,)
    grid = tuple(grid_v) if isinstance(grid_v, tuple) else (int(grid_v),)

    def use_of(operand, spec, itemsize):
        shape_e, imap = _spec_parts(spec)
        block = tuple(_ev(shape_e, names))
        roles = _axis_roles(imap)
        streamed = roles is not None and any(
            kind != "const" for kind, _ in roles
        )
        return block, roles, streamed

    uses: List[BlockUse] = []
    # scalar-prefetched leading operands carry no BlockSpec (SMEM)
    specced = site.operands[site.num_scalar_prefetch:]
    if len(site.in_specs) != len(specced):
        raise _Unresolved(
            f"{site.fn}: {len(site.in_specs)} in_specs for "
            f"{len(specced)} post-prefetch operands"
        )
    for spec, operand in zip(site.in_specs, specced):
        if operand not in op_shapes:
            raise _Unresolved(f"{site.fn}: unknown operand `{operand}`")
        block, roles, streamed = use_of(operand, spec, point.itemsize)
        uses.append(
            BlockUse(operand, block, op_shapes[operand], point.itemsize,
                     streamed, roles)
        )
    if len(site.out_specs) != len(site.out_shape):
        raise _Unresolved(
            f"{site.fn}: {len(site.out_specs)} out_specs for "
            f"{len(site.out_shape)} out_shape structs"
        )
    for i, (spec, struct) in enumerate(zip(site.out_specs, site.out_shape)):
        if not (isinstance(struct, ast.Call) and len(struct.args) >= 2):
            raise _Unresolved(f"{site.fn}: out_shape[{i}] not a struct")
        full = tuple(_ev(struct.args[0], names))
        itemsize = int(_ev(struct.args[1], names))
        block, roles, streamed = use_of(f"<out{i}>", spec, itemsize)
        uses.append(
            BlockUse(f"<out{i}>", block, full, itemsize, streamed, roles)
        )
    return grid, uses


def vmem_estimate(site: PallasSite, point: KernelPoint) -> Dict[str, float]:
    """The calibrated footprint model at ``point`` (bytes + MiB views)."""
    _, uses = _site_blocks(site, point)
    streamed = sum(u.nbytes for u in uses if u.streamed)
    resident = sum(u.nbytes for u in uses if not u.streamed)
    est = CALIBRATION * (PIPELINE_FACTOR * streamed + resident)
    return {
        "site": site.fn,
        "streamed_bytes": streamed,
        "resident_bytes": resident,
        "estimate_bytes": est,
        "estimate_mib": est / (1 << 20),
        "budget_bytes": VMEM_BUDGET_BYTES,
    }


def _check_site(site: PallasSite, point: KernelPoint) -> List[Finding]:
    findings: List[Finding] = []

    def emit(rule: str, message: str) -> None:
        findings.append(
            Finding(rule=rule, path=site.path, line=site.line,
                    message=message, severity=RULES[rule].severity)
        )

    try:
        grid, uses = _site_blocks(site, point)
    except _Unresolved as e:
        emit(
            "pallas-blockspec",
            f"`{site.fn}` pallas_call: static checker out of sync with the "
            f"source ({e}) — update analysis/pallas_check.py alongside the "
            "kernel",
        )
        return findings

    for u in uses:
        if len(u.block) != len(u.operand_shape):
            emit(
                "pallas-blockspec",
                f"`{site.fn}` [{point.describe()}]: operand `{u.operand}` "
                f"block rank {len(u.block)} != operand rank "
                f"{len(u.operand_shape)}",
            )
            continue
        for axis, (b, full) in enumerate(zip(u.block, u.operand_shape)):
            if b <= 0 or full % b:
                emit(
                    "pallas-blockspec",
                    f"`{site.fn}` [{point.describe()}]: operand "
                    f"`{u.operand}` axis {axis} block {b} does not divide "
                    f"the operand dim {full} — Mosaic pads or rejects the "
                    "ragged final block",
                )
        for axis, (kind, pos) in enumerate(u.roles or ()):
            # only directly grid-driven axes have static coverage;
            # "dynamic" (idx_ref-gathered) axes are checked at runtime
            # by construction of the index lists
            if kind != "param" or axis >= len(u.block) or pos >= len(grid):
                continue
            covered = grid[pos] * u.block[axis]
            if covered != u.operand_shape[axis]:
                emit(
                    "pallas-blockspec",
                    f"`{site.fn}` [{point.describe()}]: grid {grid[pos]} x "
                    f"block {u.block[axis]} covers {covered} of "
                    f"{u.operand_shape[axis]} rows of `{u.operand}` axis "
                    f"{axis} — the kernel would read/write a range it was "
                    "never given",
                )

    est = vmem_estimate(site, point)
    if est["estimate_bytes"] > VMEM_BUDGET_BYTES:
        emit(
            "pallas-vmem",
            f"`{site.fn}` [{point.describe()}]: estimated VMEM footprint "
            f"{est['estimate_mib']:.2f} MiB exceeds the "
            f"{VMEM_BUDGET_BYTES >> 20} MiB/core scoped budget "
            f"(2x-buffered streamed blocks {est['streamed_bytes']} B + "
            f"resident blocks {est['resident_bytes']} B, calibration "
            f"x{CALIBRATION}) — shrink the block rows "
            "(STMGCN_PALLAS_FWD_ROWS/BWD_ROWS) or the block shapes",
        )
    return findings


def check_pallas_kernels(
    points: Optional[Iterable[KernelPoint]] = None,
    path: Optional[str] = None,
    spmm_points: Optional[Iterable[SpmmKernelPoint]] = None,
) -> List[Finding]:
    """Check every extracted pallas_call site at every matching point.

    Default LSTM points: the bench configuration in both storage dtypes,
    with blocks derived by the kernel's own ``_block_rows`` (env
    overrides included, so an operator's ``STMGCN_PALLAS_FWD_ROWS``
    experiment is checked as configured). Default SpMM point: the largeN
    bench plan at the shipped tile. With no explicit ``path`` every
    module in :data:`KERNEL_MODULES` is covered; a given ``path`` scopes
    the check to that file (fixtures), still dispatching each site to
    its point family by function name.
    """
    if points is None:
        points = [KernelPoint(dtype="float32"), KernelPoint(dtype="bfloat16")]
    if spmm_points is None:
        spmm_points = [SpmmKernelPoint()]
    if path is not None:
        sites = extract_pallas_sites(path)
    else:
        sites = [
            s
            for module in KERNEL_MODULES
            for s in extract_pallas_sites(_default_kernel_path(module))
        ]
    if not sites:
        return [
            Finding(
                rule="pallas-blockspec",
                path=path or _default_kernel_path(),
                line=0,
                message="no pl.pallas_call site found — the kernel moved "
                "and the static checker lost it; update "
                "analysis/pallas_check.py",
                severity=RULES["pallas-blockspec"].severity,
            )
        ]
    findings: List[Finding] = []
    for site in sites:
        for point in (spmm_points if site.fn in _SPMM_FNS else points):
            findings.extend(_check_site(site, point))
    return findings
