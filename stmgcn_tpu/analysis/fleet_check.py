"""Pass 2g: fleet shape-class contracts — static planner math.

The fleet fast path (``train/trainer.py``, ``serving/fleet.py``) groups
heterogeneous cities into shape classes so one compiled superstep per
class replaces the per-city materialized loop. Whether a preset's fleet
*plan* is viable is pure config arithmetic, the same way the
resident-memory pass re-derives footprints: the planner
(:func:`stmgcn_tpu.data.fleet.plan_shape_classes`) is deterministic in
the config's city sizes and knobs, so this pass re-runs it host-side
and flags configurations whose requested fleet path cannot hold:

- **invalid knobs** — ``fleet_max_classes < 1`` or ``fleet_max_pad_waste``
  outside ``[0, 1)`` (the planner raises at trainer construction);
- **fleet on a homogeneous dataset** — ``fleet=True`` with one shape
  (the trainer rejects it: there is nothing to bucket);
- **uncovered cities** — cities the class budget cannot cover within the
  pad-waste threshold silently keep the per-step fallback, so the
  requested speedup quietly evaporates for them;
- **per-class resident footprint** — the class's concatenated series +
  target vectors + stacked dense supports at the rung must fit the
  per-core budget (the conservative ``Trainer.RESIDENT_CAP_BYTES``
  floor), else the run OOMs (``resident``) or degrades to streaming and
  off the fleet path entirely (``auto``).

No data build, no trace.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_fleet_shape_classes", "estimate_fleet_plan"]

#: synthetic demand channels and the pipeline's storage dtype — keep in
#: lockstep with resident_check.py
_CHANNELS = 1
_ITEMSIZE = 4


def _fleet_engaged(cfg) -> bool:
    t = cfg.train
    return t.fleet is True or (t.fleet is None and t.steps_per_superstep > 1)


def _city_sizes(cfg) -> Optional[list]:
    """Per-city *padded* node counts (planner input), or ``None`` when the
    preset is homogeneous (no fleet to plan)."""
    from stmgcn_tpu.experiment import node_pad_target

    d = cfg.data
    if d.city_rows is None or max(1, d.n_cities) <= 1:
        return None
    nodes = [r * r for r in d.city_rows]
    if len(set(nodes)) <= 1 and not d.hetero:
        return None
    padded = []
    for n in nodes:
        target = node_pad_target(cfg, n)
        padded.append(target if target is not None else n)
    return padded


def estimate_fleet_plan(cfg):
    """Re-derive a preset's fleet plan and per-class resident bytes.

    Returns ``(plan, class_bytes)`` where ``class_bytes[i]`` is class
    ``i``'s device-resident payload — the time-concatenated member series
    at the rung, the int32 target vectors, and the ``(members, M, K,
    rung, rung)`` dense support stack — or ``(None, None)`` when the
    preset is homogeneous. Mirrors ``Trainer._fleet_series`` /
    ``_fleet_supports`` arithmetic without building a dataset.
    """
    from stmgcn_tpu.data.fleet import plan_shape_classes
    from stmgcn_tpu.data.windowing import WindowSpec

    sizes = _city_sizes(cfg)
    if sizes is None:
        return None, None
    t, d, m = cfg.train, cfg.data, cfg.model
    plan = plan_shape_classes(
        sizes,
        max_classes=t.fleet_max_classes,
        max_pad_waste=t.fleet_max_pad_waste,
    )
    spec = WindowSpec(
        d.serial_len, d.daily_len, d.weekly_len, d.day_timesteps,
        horizon=d.horizon,
    )
    if d.city_timesteps is not None:
        steps = list(d.city_timesteps)
    else:
        steps = [d.n_timesteps] * len(sizes)
    sup_entry = m.m_graphs * m.n_supports * _ITEMSIZE
    class_bytes = []
    for cls in plan.classes:
        rung = cls.n_nodes
        series = targets = 0
        for city in cls.cities:
            t_steps = steps[city]
            series += t_steps * rung * _CHANNELS * _ITEMSIZE
            targets += 4 * max(0, spec.n_samples(t_steps))
        stack = len(cls.cities) * sup_entry * rung * rung
        class_bytes.append(series + targets + stack)
    return plan, class_bytes


def check_fleet_shape_classes(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
    budget_bytes: Optional[int] = None,
) -> List[Finding]:
    """Validate every preset's fleet shape-class plan.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset. Pure config math — safe without a JAX backend.
    """
    from stmgcn_tpu.config import PRESETS
    from stmgcn_tpu.train.trainer import Trainer

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]
    if budget_bytes is None:
        budget_bytes = Trainer.RESIDENT_CAP_BYTES

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="fleet-shape-class",
                path=f"<contract:fleet:{name}>",
                line=0,
                message=message,
                severity=RULES["fleet-shape-class"].severity,
            )
        )

    for name, cfg in configs:
        t = cfg.train
        if not _fleet_engaged(cfg):
            continue
        explicit = t.fleet is True

        if t.fleet_max_classes < 1:
            emit(
                name,
                f"{name}: fleet_max_classes must be >= 1, got "
                f"{t.fleet_max_classes} — the planner rejects it at "
                "trainer construction",
            )
            continue
        if not 0.0 <= t.fleet_max_pad_waste < 1.0:
            emit(
                name,
                f"{name}: fleet_max_pad_waste must be in [0, 1), got "
                f"{t.fleet_max_pad_waste} — the planner rejects it at "
                "trainer construction",
            )
            continue

        sizes = _city_sizes(cfg)
        if sizes is None:
            if explicit:
                emit(
                    name,
                    f"{name}: fleet=True on a homogeneous dataset — there "
                    "is nothing to bucket and the trainer rejects the "
                    "config; drop fleet or use the plain superstep path",
                )
            continue
        if explicit and t.data_placement == "stream":
            emit(
                name,
                f"{name}: fleet=True with data_placement='stream' — the "
                "fleet path requires resident class series and the "
                "trainer rejects the combination",
            )
            continue

        plan, class_bytes = estimate_fleet_plan(cfg)
        if plan.unassigned:
            emit(
                name,
                f"{name}: {len(plan.unassigned)} of {len(sizes)} cities "
                f"(indices {list(plan.unassigned)}) fit no shape class "
                f"within fleet_max_classes={t.fleet_max_classes} / "
                f"fleet_max_pad_waste={t.fleet_max_pad_waste} — they "
                "silently keep the per-step fallback; raise the class "
                "budget or loosen the waste threshold",
            )
        for cls, nbytes in zip(plan.classes, class_bytes):
            if nbytes > budget_bytes:
                degrade = (
                    "the run OOMs at the first epoch"
                    if t.data_placement == "resident"
                    else "placement degrades to streaming and the fleet "
                    "path is silently lost"
                )
                emit(
                    name,
                    f"{name}: shape class N={cls.n_nodes} (cities "
                    f"{list(cls.cities)}) needs {nbytes:,} resident bytes "
                    f"but the per-core budget is {budget_bytes:,} — "
                    f"{degrade}; split the class or shrink the series",
                )
    return findings
