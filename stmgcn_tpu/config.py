"""Typed experiment configuration with presets for the baseline configs.

Replaces the reference's split-brain configuration — module-level constants
(``Main.py:9-16``), argparse flags (``Main.py:21-34``), and hard-coded model
widths at the construction site including ``n_nodes=58``
(``Main.py:62-63``) — with one dataclass tree. ``n_nodes`` is always derived
from data, never configured (SURVEY.md §5.f).

``PRESETS`` carries the five driver-defined benchmark configs
(``BASELINE.json``): smoke, default, scaled, multicity, longhorizon.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from stmgcn_tpu.ops.graph import SupportConfig, support_count

__all__ = [
    "ContinualConfig",
    "DataConfig",
    "ExperimentConfig",
    "FederationConfig",
    "HealthConfig",
    "MeshConfig",
    "ModelConfig",
    "OBS_RESERVOIR_BUDGET",
    "OBS_RING_BUDGET",
    "ObsConfig",
    "PRESETS",
    "ServingConfig",
    "TrainConfig",
    "preset",
]

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}

#: documented observability memory budgets (README "Observability"):
#: largest span ring / histogram reservoir a preset may configure. At
#: ~200 B per span record and ~8 B per sample these bound a fully-loaded
#: process to ~13 MB of trace ring and 64 KiB per histogram — the
#: ``obs-overhead`` lint rule fails any preset configured past them.
OBS_RING_BUDGET = 65536
OBS_RESERVOIR_BUDGET = 8192


@dataclasses.dataclass
class DataConfig:
    """Data source + windowing. ``path=None`` generates synthetic data."""

    path: Optional[str] = None
    rows: int = 10
    cols: Optional[int] = None
    n_timesteps: int = 24 * 7 * 8
    n_cities: int = 1  # >1: samples from several same-shape cities, concatenated
    #: synthetic multi-city: give every city the first city's graph stack.
    #: False (default) keeps each city's own graphs — real city pairs
    #: (BASELINE config 4, Chengdu+Beijing) never share adjacencies, so
    #: batches then carry a city index and train against per-city supports
    shared_graphs: bool = False
    #: treat cities as fully independent (per-city normalizer/split/shape
    #: — data.hetero.HeteroCityDataset) even when their shapes happen to
    #: match. Auto-enabled whenever city shapes differ.
    hetero: bool = False
    #: per-city synthetic grid rows (length n_cities); cities with
    #: different region counts imply the heterogeneous pipeline
    city_rows: Optional[tuple] = None
    #: per-city synthetic series lengths (length n_cities)
    city_timesteps: Optional[tuple] = None
    dt: int = 1  # hours per timestep (Main.py:10)
    serial_len: int = 3
    daily_len: int = 1
    weekly_len: int = 1
    horizon: int = 1  # forecast steps per sample (1 = reference parity)
    #: "minmax" (reference parity, Data_Container.py:21) | "std" | "none"
    normalize: str = "minmax"
    dates: Optional[tuple] = None  # (train_s, train_e, test_s, test_e) MMDD
    val_ratio: float = 0.2
    year: int = 2017
    train_frac: float = 0.7  # used when dates is None
    val_frac: float = 0.1
    seed: int = 0

    def override(self, **fields) -> "DataConfig":
        """Set fields keeping per-city companions consistent.

        Presets carry coupled fields (``n_cities`` with ``city_rows`` /
        ``city_timesteps``); overriding one in isolation leaves the config
        self-contradictory and fails validation only later, in
        ``build_dataset``. Overriding through this helper drops any
        per-city tuple whose length no longer matches ``n_cities`` (unless
        the same call replaces it). Returns ``self`` for chaining.
        """
        for k in fields:
            if not hasattr(self, k):
                raise AttributeError(f"DataConfig has no field {k!r}")
        for k, v in fields.items():
            setattr(self, k, v)
        if "n_cities" in fields:
            for name in ("city_rows", "city_timesteps"):
                if name in fields:
                    continue
                per_city = getattr(self, name)
                if per_city is not None and len(per_city) != self.n_cities:
                    setattr(self, name, None)
        return self

    @property
    def day_timesteps(self) -> int:
        return 24 // self.dt

    @property
    def seq_len(self) -> int:
        return self.serial_len + self.daily_len + self.weekly_len


@dataclasses.dataclass
class ModelConfig:
    """Architecture; widths default to the reference's (``Main.py:62-63``)."""

    m_graphs: int = 3
    kernel_type: str = "chebyshev"
    K: int = 2
    bidirectional: bool = True
    lstm_hidden_dim: int = 64
    lstm_num_layers: int = 3
    gcn_hidden_dim: int = 64
    use_bias: bool = True
    shared_gate_fc: bool = True
    #: route graph convolutions through the Pallas block-CSR SpMM (large
    #: sparse graphs); branches loop instead of vmapping
    sparse: bool = False
    #: route graph convolutions through the offline-reordered tiled-sparse
    #: path (ops/tiling.py): RCM-style node permutation + dense
    #: (tile, tile) block condensation covering all M x K supports in one
    #: plan, applied via gathered-tiles XLA or the fused Pallas SpMM.
    #: The large-N representation — mutually exclusive with ``sparse``
    #: and with multi-device meshes; branches loop instead of vmapping
    tiled: bool = False
    #: tiled-plan block edge; the ``tile-plan`` lint rule demands a
    #: positive multiple of 128 (the MXU's native tile) that fits the
    #: kernel's VMEM model
    tile_size: int = 128
    #: largest fraction of *stored* tile blocks the condensed plan may
    #: waste on all-zero padding (the uniform block-column imposition) —
    #: node-padding waste at config time (the ``tile-plan`` rule) and
    #: realized zero-block condensation waste at plan time
    #: (``build_supports`` raises past it). A graph whose nonzeros
    #: refuse to cluster should fall back to dense/sparse, not silently
    #: burn MXU cycles on zeros
    tile_waste_budget: float = 0.75
    remat: bool = False
    #: LSTM scan scheduling (numerically identical, XLA-level levers):
    #: unroll factor for the time scan, and single-scan-all-layers fusion
    lstm_unroll: int = 1
    lstm_fused_scan: bool = False
    #: "xla" | "pallas" — scan paths vs the hand-written fused TPU kernel
    lstm_backend: str = "xla"
    dtype: str = "float32"

    @property
    def n_supports(self) -> int:
        return support_count(self.kernel_type, self.K, self.bidirectional)

    @property
    def support_config(self) -> SupportConfig:
        return SupportConfig(self.kernel_type, self.K, self.bidirectional)

    @property
    def compute_dtype(self):
        return DTYPES[self.dtype]


@dataclasses.dataclass
class TrainConfig:
    """Optimization recipe; defaults are the reference's (``Main.py:9-16``)."""

    epochs: int = 100
    batch_size: int = 32
    lr: float = 2e-3
    weight_decay: float = 1e-4
    #: "none" (constant lr — reference parity, Main.py:13 has no
    #: scheduler) | "cosine" (linear warmup then cosine decay to
    #: lr * min_lr_fraction over the full run)
    lr_schedule: str = "none"
    warmup_epochs: float = 0.0
    min_lr_fraction: float = 0.0
    #: global-norm gradient clipping before the L2 term and Adam moments
    #: (None = off, reference parity)
    grad_clip_norm: Optional[float] = None
    loss: str = "mse"
    #: functional sanitizer (jax.experimental.checkify) on the train/eval
    #: steps: None | "nan" | "index" | "float" | "all" — fails at the step
    #: producing the bad value, with a device sync per step (debug tool)
    checks: Optional[str] = None
    patience: int = 10
    top_k: int = 1  # best improvement snapshots kept alongside best/latest
    shuffle: bool = False  # reference parity (Data_Container.py:122)
    #: batches placed on device ahead of the consuming step (0 disables);
    #: overlaps host->device copies with device compute
    prefetch: int = 1
    #: where batch data lives: "stream" uploads every batch (prefetch
    #: overlaps the copy), "resident" uploads each split once and gathers
    #: batches on device by index (the reference's whole-split residency,
    #: Data_Container.py:88-89, minus its eager-in-the-dataset placement),
    #: "auto" picks resident on a single device when the windowed arrays
    #: fit comfortably in HBM, else stream
    data_placement: str = "auto"
    #: resident data representation: None (default) keeps the raw
    #: normalized (T, N, C) series resident and reconstructs every batch
    #: on device from target indices + the window offset table —
    #: ~seq_len x fewer resident bytes, bit-identical results; False
    #: forces the materialized-window resident arrays (the parity
    #: oracle); True errors unless the window-free path is available
    #: (homogeneous dataset, resident placement)
    window_free: Optional[bool] = None
    #: fuse S train steps into one jitted lax.scan dispatch with on-device
    #: microbatch gather (train/step.py make_superstep_fns): one host
    #: dispatch + one loss readback per S optimizer steps. 1 (default) is
    #: the per-step loop; >1 requires resident data with one shared graph
    #: stack and otherwise silently falls back to per-step. Results are
    #: bit-identical either way — this is purely a dispatch-overhead knob
    steps_per_superstep: int = 1
    #: fleet shape-class training for heterogeneous cities
    #: (data/fleet.py): group cities by padded node count into a bounded
    #: rung ladder so ONE fused window-free superstep program per class
    #: covers every member (per-class support stacks + traced real-node
    #: counts). None (default) engages automatically when
    #: steps_per_superstep > 1 on a viable heterogeneous dataset
    #: (resident placement, dense per-city supports); True requires it
    #: (the Trainer raises naming the blocker otherwise); False never
    #: engages (the materialized per-city loop — the parity oracle)
    fleet: Optional[bool] = None
    #: most shape classes the fleet planner may open; cities that fit
    #: none run the per-step loop (surfaced via Trainer.fallback_reason)
    fleet_max_classes: int = 8
    #: max padded-node fraction of a rung a member city may waste
    #: (rung - n > waste * rung excludes the city from that rung)
    fleet_max_pad_waste: float = 0.5
    #: write checkpoint files from a background worker (serialization —
    #: the device->host snapshot — stays on the training thread; reads
    #: flush pending writes first)
    async_checkpoint: bool = True
    #: additionally rewrite latest.ckpt every K optimizer steps (0 = only
    #: at epoch boundaries); mid-epoch writes carry the exact resume
    #: cursor so --resume auto continues bit-exactly from step k
    checkpoint_every_steps: int = 0
    #: check each step's loss for non-finiteness; on a trip, roll
    #: params/opt_state back to the pre-step snapshot and skip/defer the
    #: batch (costs a device sync per step — off by default)
    divergence_guard: bool = False
    divergence_action: str = "skip"  # "skip" | "defer" (retry at epoch end)
    divergence_patience: int = 3  # consecutive trips before aborting
    #: multiply the learning rate by this factor on each trip (None = off)
    divergence_lr_cut: Optional[float] = None
    #: step-program compute precision: "fp32" (default — the exact
    #: pre-mixed-precision programs, bit for bit) | "bf16" (the lint-
    #: certified mixed-precision twins: bf16 operand casts at every
    #: matmul/conv use site contracting into f32 accumulation islands;
    #: the optimizer, its moments, every scan carry, and all checkpoint
    #: payloads stay f32 masters)
    precision: str = "fp32"
    #: seed for stochastically-rounded master->bf16 param casts (None =
    #: deterministic round-to-nearest-even; bf16 only). SR pre-casts the
    #: whole param tree at program entry, which moves the LSTM recurrent
    #: weight-grad scan accumulation to bf16 — a training knob, not a
    #: registered contract program
    sr_seed: Optional[int] = None
    seed: int = 0
    out_dir: str = "output"


@dataclasses.dataclass
class MeshConfig:
    """Device mesh extents: data-parallel x region(model)-parallel shards."""

    dp: int = 1
    region: int = 1
    #: graph-branch model parallelism: shard the M stacked branches (and
    #: their params/supports) over this axis; the sum fusion becomes one
    #: psum. Requires m_graphs % branch == 0. Composes with dense GSPMD,
    #: branch-stacked banded strips (every branch within the halo budget;
    #: 'auto' falls back to dense GSPMD otherwise), and branch-stacked
    #: block-CSR sparse supports.
    branch: int = 1
    #: how region-sharded graph convs communicate:
    #: - "gspmd": dense supports, XLA's automatic plan (all-gathers the
    #:   node axis of the signal per conv)
    #: - "banded": explicit halo-exchange plan for every branch; raises if
    #:   any support's bandwidth exceeds the shard size
    #: - "auto": per-branch — banded where the supports are banded enough
    #:   (bandwidth <= halo budget), GSPMD dense elsewhere
    region_strategy: str = "gspmd"
    #: halo budget for banded routing; None = tightest (max bandwidth),
    #: capped by the auto-routing threshold n_local // 2
    halo: Optional[int] = None

    def __post_init__(self):
        # extent 0 would silently zero n_devices and skip the mesh entirely
        # (a run the user asked to shard would train single-device)
        if min(self.dp, self.region, self.branch) < 1:
            raise ValueError(
                f"mesh extents must be >= 1, got dp={self.dp} "
                f"region={self.region} branch={self.branch}"
            )

    @property
    def n_devices(self) -> int:
        return self.dp * self.region * self.branch


@dataclasses.dataclass
class ServingConfig:
    """Inference-engine shape policy (:mod:`stmgcn_tpu.serving.engine`).

    The engine pre-compiles one AOT program per ``buckets`` rung and the
    micro-batcher coalesces concurrent requests into the smallest
    covering rung, waiting at most ``max_delay_ms`` for co-riders.
    ``violations()`` is the ladder's static contract — pure config math,
    shared by engine construction and the ``serving-bucket-shape`` /
    ``serving-slo`` analysis rules, so a bad ladder or a
    self-contradictory SLO fails ``stmgcn lint`` before it fails a
    deployment.
    """

    #: ascending batch-size ladder; one compiled program per rung. Keep 1
    #: in the ladder so lone interactive requests never wait or pad.
    buckets: tuple = (1, 4, 16, 64)
    #: micro-batcher coalescing deadline (ms a request may wait for
    #: co-riders when the pending rows don't exactly fill a rung)
    max_delay_ms: float = 2.0
    #: largest coalesced batch the ladder must cover (its top rung)
    max_batch: int = 64
    #: per-rung worst-case padded-waste bound: a batch one row past rung
    #: ``p`` pads to the next rung ``b`` wasting ``(b - p - 1) / b`` —
    #: ladders with bigger gaps than this fail validation
    max_pad_waste: float = 0.75
    #: per-request SLO deadline (ms from submit to response). None (the
    #: default) disables admission control entirely — unbounded queue,
    #: never shed, the pre-SLO engine behavior. When set, the admission
    #: controller rejects requests whose estimated wait (queue depth x
    #: measured per-rung device time) already exceeds the deadline, and
    #: the batcher sheds queued requests whose deadline expired before
    #: dispatch. Must exceed ``max_delay_ms``: a deadline below the
    #: coalescing delay rejects every coalesced request by construction.
    deadline_ms: Optional[float] = None
    #: bounded-queue admission limit (pending ROWS, not requests); 0 = no
    #: bound. Arrivals past the bound are rejected ``Overloaded``. Must
    #: cover the top rung — a bound below it could never fill a
    #: saturated dispatch.
    queue_bound_rows: int = 0
    #: what an over-SLO arrival gets: "reject" raises the typed
    #: Overloaded/DeadlineExceeded; "degrade" first tries to serve it
    #: inline through ``predict_direct`` at ``degrade_rung`` (bypassing
    #: the queue — bounded work, no coalescing), rejecting only requests
    #: too big for that rung
    shed_policy: str = "reject"
    #: ladder rung used by the "degrade" policy; None = the smallest
    #: rung. Must be a member of ``buckets``.
    degrade_rung: Optional[int] = None

    def __post_init__(self):
        # json round-trips hand lists back; the to_dict/from_dict identity
        # (and hashing-adjacent uses) need the canonical tuple form
        self.buckets = tuple(int(b) for b in self.buckets)

    def violations(self) -> list:
        """Every way this config is unservable (empty list = valid):
        the ladder contract plus the SLO contract. Engine construction
        rejects on any; lint splits them across ``serving-bucket-shape``
        and ``serving-slo``."""
        return self.ladder_violations() + self.slo_violations()

    def ladder_violations(self) -> list:
        """Bucket-ladder shape violations (the serving-bucket-shape rule)."""
        v = []
        b = self.buckets
        if not b:
            return ["bucket ladder is empty"]
        if any(x < 1 for x in b):
            v.append(f"buckets must be >= 1, got {b}")
        if any(y <= x for x, y in zip(b, b[1:])):
            v.append(f"bucket ladder must be strictly increasing, got {b}")
        if self.max_batch < 1:
            v.append(f"max_batch must be >= 1, got {self.max_batch}")
        elif b[-1] < self.max_batch:
            v.append(
                f"ladder tops out at {b[-1]} but max_batch is "
                f"{self.max_batch} — batches above the top rung have no "
                "program"
            )
        if not 0.0 <= self.max_pad_waste < 1.0:
            v.append(
                f"max_pad_waste must be in [0, 1), got {self.max_pad_waste}"
            )
        else:
            prev = 0
            for cur in b:
                if cur <= prev:
                    continue  # ordering already flagged above
                waste = (cur - (prev + 1)) / cur
                if waste > self.max_pad_waste:
                    v.append(
                        f"bucket {cur}: worst-case pad waste {waste:.3f} "
                        f"(one row past rung {prev} pads {cur - prev - 1} of "
                        f"{cur} rows) exceeds max_pad_waste "
                        f"{self.max_pad_waste} — add an intermediate rung"
                    )
                prev = cur
        if self.max_delay_ms < 0:
            v.append(f"max_delay_ms must be >= 0, got {self.max_delay_ms}")
        return v

    def slo_violations(self) -> list:
        """Self-contradictory SLO / admission knobs (the serving-slo rule).

        These configs *construct* an admission controller that can never
        behave as intended — every coalesced request shed, a queue that
        cannot fill a dispatch, a degrade rung with no program — so they
        are deploy-time outages detectable from pure config math.
        """
        v = []
        b = self.buckets
        if self.deadline_ms is not None and self.deadline_ms <= self.max_delay_ms:
            v.append(
                f"deadline_ms {self.deadline_ms} must exceed max_delay_ms "
                f"{self.max_delay_ms} — a request may legitimately wait the "
                "full coalescing delay, so a tighter deadline sheds every "
                "coalesced request by construction"
            )
        if self.queue_bound_rows < 0:
            v.append(
                f"queue_bound_rows must be >= 0, got {self.queue_bound_rows}"
            )
        elif self.queue_bound_rows and b and self.queue_bound_rows < b[-1]:
            v.append(
                f"queue_bound_rows {self.queue_bound_rows} is below the top "
                f"rung {b[-1]} — a saturated dispatch could never fill"
            )
        if self.shed_policy not in ("reject", "degrade"):
            v.append(
                f"shed_policy must be 'reject' or 'degrade', got "
                f"{self.shed_policy!r}"
            )
        if self.degrade_rung is not None:
            if self.shed_policy != "degrade":
                v.append(
                    f"degrade_rung {self.degrade_rung} is set but shed_policy "
                    f"is {self.shed_policy!r} — the rung would never be used"
                )
            if self.degrade_rung not in b:
                v.append(
                    f"degrade_rung {self.degrade_rung} is not a ladder rung "
                    f"{b} — no compiled program exists for it"
                )
        return v


@dataclasses.dataclass
class ObsConfig:
    """Runtime observability knobs (:mod:`stmgcn_tpu.obs`).

    Off by default — the disabled path must cost nothing on the hot
    loops. ``violations()`` is the pure-config contract behind the
    ``obs-overhead`` lint rule: a preset that turns tracing on with an
    unbounded ring or an over-budget reservoir is a silent memory/perf
    regression waiting for a long run, so it fails ``stmgcn lint``
    before it fails a soak.
    """

    #: record spans into the ring buffer (``--trace-out`` enables this)
    trace: bool = False
    #: JSONL export destination; None keeps the ring in-process only
    trace_path: Optional[str] = None
    #: span ring capacity; oldest spans evicted when full. Must be a
    #: positive bound within :data:`OBS_RING_BUDGET`
    ring_capacity: int = 4096
    #: bounded-histogram sample window (EngineStats percentiles etc.);
    #: must be positive and within :data:`OBS_RESERVOIR_BUDGET`
    reservoir: int = 1024

    def violations(self) -> list:
        """Every way this config breaks the documented overhead budget
        (empty list = valid; the ``obs-overhead`` rule). Reservoir
        bounds always apply — EngineStats histograms exist with tracing
        off; the ring bounds only matter once tracing allocates one.
        """
        v = []
        if self.reservoir < 1:
            v.append(
                f"reservoir must be >= 1, got {self.reservoir} — "
                "histograms need a positive sample bound"
            )
        elif self.reservoir > OBS_RESERVOIR_BUDGET:
            v.append(
                f"reservoir {self.reservoir} exceeds the documented "
                f"budget {OBS_RESERVOIR_BUDGET} — percentile windows "
                "past the budget buy no accuracy, only memory"
            )
        if not self.trace:
            return v
        if self.ring_capacity < 1:
            v.append(
                f"ring_capacity must be >= 1 when tracing, got "
                f"{self.ring_capacity} — an unbounded span buffer grows "
                "without limit in a long-lived process"
            )
        elif self.ring_capacity > OBS_RING_BUDGET:
            v.append(
                f"ring_capacity {self.ring_capacity} exceeds the "
                f"documented budget {OBS_RING_BUDGET} — export the "
                "trace and rotate instead of growing the ring"
            )
        return v


@dataclasses.dataclass
class HealthConfig:
    """Numeric health & drift telemetry knobs (:mod:`stmgcn_tpu.obs`).

    Off by default — the disabled path must compile the *same* step
    program as a build without the feature (the jaxpr budget for
    ``train_series_superstep`` pins this). ``violations()`` is the
    pure-config contract behind the ``health-overhead`` lint rule,
    mirroring :meth:`ObsConfig.violations`: a cadence below 1 silently
    disables the telemetry it claims to provide, sketches past the
    ``OBS_*`` budget family are unbounded per-city memory at fleet
    scale, and drift gauges without a baseline can never fire.
    """

    #: compute on-device training health stats (grad norms, update
    #: ratio, nonfinite counts) and stream them to ``health.jsonl``
    enabled: bool = False
    #: compute/download health stats every k-th superstep (1 = every
    #: superstep); must be >= 1
    every_k: int = 1
    #: per-channel histogram bins of the drift sketches (input moments
    #: + prediction distribution); bounded by OBS_RESERVOIR_BUDGET
    sketch_size: int = 64
    #: bounded sample window retained per drift sketch for debugging;
    #: bounded by OBS_RESERVOIR_BUDGET
    reservoir: int = 256
    #: compare live serving sketches against the training-time baseline
    #: and publish per-city z-score/PSI gauges
    drift: bool = False
    #: capture a training-time moment baseline into checkpoint meta
    #: (required for drift gauges — they have nothing to compare
    #: against without it)
    baseline: bool = True
    #: health.jsonl destination; None = ``<out_dir>/health.jsonl``
    out: Optional[str] = None

    def violations(self) -> list:
        """Every way this config breaks the documented overhead budget
        (empty list = valid; the ``health-overhead`` rule). Sketch and
        reservoir bounds always apply — the serving drift sketches
        exist whether or not training health is on; cadence only
        matters once the training side is enabled.
        """
        v = []
        if self.sketch_size < 1:
            v.append(
                f"sketch_size must be >= 1, got {self.sketch_size} — "
                "drift histograms need at least one bin"
            )
        elif self.sketch_size > OBS_RESERVOIR_BUDGET:
            v.append(
                f"sketch_size {self.sketch_size} exceeds the documented "
                f"budget {OBS_RESERVOIR_BUDGET} — finer drift bins past "
                "the budget buy no sensitivity, only per-city memory"
            )
        if self.reservoir < 0:
            v.append(
                f"reservoir must be >= 0, got {self.reservoir} — "
                "0 disables sample retention, negatives mean nothing"
            )
        elif self.reservoir > OBS_RESERVOIR_BUDGET:
            v.append(
                f"reservoir {self.reservoir} exceeds the documented "
                f"budget {OBS_RESERVOIR_BUDGET} — retained drift "
                "samples past the budget are unbounded per-city memory"
            )
        if self.drift and not self.baseline:
            v.append(
                "drift gauges are enabled but baseline capture is off — "
                "without a training-time baseline in checkpoint meta the "
                "z-score/PSI gauges can never fire"
            )
        if not self.enabled:
            return v
        if self.every_k < 1:
            v.append(
                f"every_k must be >= 1 when health is enabled, got "
                f"{self.every_k} — a non-positive cadence silently "
                "disables the telemetry this config claims to provide"
            )
        return v


@dataclasses.dataclass
class ContinualConfig:
    """Closed-loop continual learning knobs (ring ingest, retrain daemon,
    guarded promotion — :mod:`stmgcn_tpu.train.continual`).

    Off by default — with ``enabled=False`` the serving/training paths
    are exactly the loop-free build (parity pinned in
    tests/test_continual.py). ``violations()`` is the pure-config
    contract behind the ``continual-config`` lint rule: a ring bigger
    than the per-core resident budget, a retrain cadence the measured
    superstep time cannot sustain without starving serving, missing or
    unordered promotion-gate thresholds, and a drift trigger with no
    baseline to fire against are all deployment outages detectable
    before any step runs.
    """

    #: run the continual-training daemon (the ring itself can be used
    #: standalone — e.g. pre-filled for window-free serving)
    enabled: bool = False
    #: ring rows (timesteps) resident on device per city
    ring_capacity: int = 1024
    #: how many steps behind the head a late row may arrive and still be
    #: placed; older is a typed reject. Must be < ring_capacity
    reorder_window: int = 4
    #: wall-clock retrain cadence in seconds; 0 = drift-triggered only
    cadence_s: float = 0.0
    #: retrain when any city's drift z_max gauge crosses this
    drift_z_max: float = 8.0
    #: retrain when any city's drift PSI gauge crosses this
    drift_psi: float = 0.5
    #: fused supersteps per fine-tune round
    finetune_steps: int = 8
    #: microbatch size of the fine-tune superstep
    finetune_batch: int = 8
    #: train on only the freshest K targets; 0 = whole resident series
    finetune_window: int = 0
    #: consecutive daemon failures tolerated before it stays down
    max_restarts: int = 3
    #: initial retry backoff (doubles per failure, with jitter)
    backoff_s: float = 0.25
    #: backoff ceiling; must be >= backoff_s
    backoff_max_s: float = 4.0
    #: gate: reject a candidate whose fine-tune grad norm exceeded this
    promote_grad_norm_max: float = 1e3
    #: gate: reject a candidate whose update ratio exceeded this
    promote_update_ratio_max: float = 0.5
    #: gate: reject a candidate whose held-out eval loss exceeds the
    #: live generation's by more than this relative margin
    promote_eval_margin: float = 0.05
    #: measured fused-superstep wall time (ms) for the duty-cycle check;
    #: 0 = not yet measured (check skipped)
    superstep_ms: float = 0.0
    #: largest fraction of the cadence the fine-tune may occupy — above
    #: this the daemon starves serving on a shared core
    max_duty: float = 0.5

    def violations(self, *, row_bytes: Optional[int] = None,
                   budget_bytes: Optional[int] = None,
                   health=None, data=None) -> list:
        """Every way this config breaks the closed-loop deployment
        contract (empty list = valid; the ``continual-config`` rule).
        Ring bounds always apply — a pre-filled ring exists with the
        daemon off; trigger/retry/gate checks only matter once the loop
        is enabled. ``row_bytes``/``budget_bytes`` bring in the
        ``resident-memory`` per-core budget; ``health``/``data`` bring
        in the sibling configs for cross-field checks (drift trigger
        needs a baseline; the ring must cover one training window).
        """
        v = []
        if self.ring_capacity < 1:
            v.append(
                f"ring_capacity must be >= 1, got {self.ring_capacity} — "
                "an empty ring can never hold a series"
            )
        elif not 0 <= self.reorder_window < self.ring_capacity:
            v.append(
                f"reorder_window {self.reorder_window} must be in "
                f"[0, ring_capacity={self.ring_capacity}) — a late row "
                "can only overwrite a slot that is still resident"
            )
        if row_bytes is not None and budget_bytes is not None:
            need = self.ring_capacity * row_bytes
            if need > budget_bytes:
                v.append(
                    f"ring_capacity {self.ring_capacity} needs {need} "
                    f"resident bytes ({row_bytes} B/row) — over the "
                    f"per-core resident budget {budget_bytes}"
                )
        if data is not None and self.ring_capacity >= 1:
            from stmgcn_tpu.data.windowing import WindowSpec

            spec = WindowSpec(data.serial_len, data.daily_len,
                              data.weekly_len, data.day_timesteps,
                              horizon=data.horizon)
            need = spec.burn_in + spec.horizon
            if self.ring_capacity < need:
                v.append(
                    f"ring_capacity {self.ring_capacity} cannot hold one "
                    f"training window — burn_in+horizon is {need} for "
                    "this window spec, so the fine-tune would never have "
                    "a valid target"
                )
        if not self.enabled:
            return v
        if self.cadence_s < 0:
            v.append(f"cadence_s must be >= 0, got {self.cadence_s}")
        if self.cadence_s == 0 and health is not None and not (
            health.drift and health.baseline
        ):
            v.append(
                "cadence_s=0 makes drift gauges the only retrain trigger, "
                "but health.drift/health.baseline are not both on — the "
                "daemon would never fire"
            )
        if self.drift_z_max <= 0 or self.drift_psi <= 0:
            v.append(
                f"drift thresholds must be positive, got z_max="
                f"{self.drift_z_max}, psi={self.drift_psi} — a "
                "non-positive threshold retrains on every poll"
            )
        if self.finetune_steps < 1 or self.finetune_batch < 1:
            v.append(
                f"finetune_steps/finetune_batch must be >= 1, got "
                f"{self.finetune_steps}/{self.finetune_batch}"
            )
        if self.finetune_window < 0:
            v.append(
                f"finetune_window must be >= 0, got {self.finetune_window}"
            )
        if self.max_restarts < 0:
            v.append(f"max_restarts must be >= 0, got {self.max_restarts}")
        if self.backoff_s <= 0 or self.backoff_max_s < self.backoff_s:
            v.append(
                f"retry backoff must satisfy 0 < backoff_s <= "
                f"backoff_max_s, got {self.backoff_s}/{self.backoff_max_s}"
            )
        if self.promote_grad_norm_max <= 0 or self.promote_update_ratio_max <= 0:
            v.append(
                "promotion-gate bands must be positive, got grad_norm_max="
                f"{self.promote_grad_norm_max}, update_ratio_max="
                f"{self.promote_update_ratio_max} — a non-positive band "
                "rejects every candidate"
            )
        if self.promote_eval_margin < 0:
            v.append(
                f"promote_eval_margin must be >= 0, got "
                f"{self.promote_eval_margin} — a negative margin demands "
                "the candidate be strictly better than live to even tie"
            )
        if not 0 < self.max_duty <= 1:
            v.append(f"max_duty must be in (0, 1], got {self.max_duty}")
        elif self.cadence_s > 0 and self.superstep_ms > 0:
            duty = (self.finetune_steps * self.superstep_ms / 1e3) / self.cadence_s
            if duty > self.max_duty:
                v.append(
                    f"fine-tune duty cycle {duty:.2f} exceeds max_duty "
                    f"{self.max_duty} — {self.finetune_steps} supersteps "
                    f"x {self.superstep_ms} ms every {self.cadence_s} s "
                    "starves serving on a shared core"
                )
        return v


@dataclasses.dataclass
class FederationConfig:
    """Multi-replica serving-tier knobs (:mod:`stmgcn_tpu.serving
    .federation`).

    Off by default — with ``enabled=False`` the serving path is exactly
    the single-engine build. ``violations()`` is the pure-config
    contract behind the ``federation-config`` lint rule: a tier with
    more replicas than cities leaves replicas permanently idle, a hash
    ring with too few points cannot meet its imbalance bound, a global
    overload budget below a single replica's local bound sheds the tier
    before any replica could fill, and a handover window longer than a
    full drain inverts the lifecycle ordering — all deployment outages
    detectable before any request is served.
    """

    #: run the federation router (a single-replica deployment never
    #: builds a ring or a tier budget)
    enabled: bool = False
    #: active engine replicas the ring shards cities across
    replicas: int = 3
    #: warm spares kept built + checkpoint-watching but outside the ring
    spares: int = 0
    #: hash-ring points per replica (virtual nodes); more points =
    #: smoother city distribution and smaller re-shard movement
    vnodes: int = 64
    #: bound on relative per-replica load imbalance the ring may exhibit
    #: (max replica share vs the uniform share, as a fraction over 1.0)
    imbalance_max: float = 0.5
    #: tier-wide pending-row budget shared by every replica's admission
    #: controller; 0 = no global budget (local bounds only)
    global_queue_bound_rows: int = 0
    #: drain: max seconds to wait for a replica's in-flight work to
    #: flush before declaring it wedged and detaching anyway
    drain_timeout_s: float = 5.0
    #: re-shard: max seconds moved cities may wait for their old owner's
    #: in-flight work during the handover window
    handover_timeout_s: float = 2.0

    def violations(self, *, serving=None, n_cities=None) -> list:
        """Every way this config breaks the tier deployment contract
        (empty list = valid; the ``federation-config`` rule). Ring
        bounds always apply — a pre-built ring exists with the router
        off; replica-vs-city, budget, and lifecycle checks only matter
        once the tier is enabled. ``serving`` brings in the sibling
        :class:`ServingConfig` for the budget cross-check; ``n_cities``
        the data config's city count.
        """
        v = []
        if self.vnodes < 1:
            v.append(f"vnodes must be >= 1, got {self.vnodes}")
        if not 0.0 < self.imbalance_max <= 1.0:
            v.append(
                f"imbalance_max must be in (0, 1], got {self.imbalance_max}"
            )
        elif self.vnodes >= 1 and self.replicas >= 1:
            # ring imbalance shrinks ~ 1/sqrt(total points): demand
            # enough points that the configured bound is plausible
            need = int(4.0 / (self.imbalance_max * self.imbalance_max))
            if self.replicas * self.vnodes < need:
                v.append(
                    f"hash ring has {self.replicas * self.vnodes} points "
                    f"({self.replicas} replicas x {self.vnodes} vnodes) — "
                    f"fewer than the {need} needed to bound imbalance at "
                    f"{self.imbalance_max}; add vnodes or relax the bound"
                )
        if not self.enabled:
            return v
        if self.replicas < 1:
            v.append(f"replicas must be >= 1, got {self.replicas}")
        if self.spares < 0:
            v.append(f"spares must be >= 0, got {self.spares}")
        if n_cities is not None and self.replicas > n_cities:
            v.append(
                f"{self.replicas} replicas for {n_cities} cities — "
                "city->replica sharding leaves at least one replica "
                "permanently idle; shrink the tier or add cities"
            )
        if self.global_queue_bound_rows < 0:
            v.append(
                f"global_queue_bound_rows must be >= 0, got "
                f"{self.global_queue_bound_rows}"
            )
        elif self.global_queue_bound_rows and serving is not None:
            local = int(serving.queue_bound_rows)
            if local and self.global_queue_bound_rows < local:
                v.append(
                    f"global_queue_bound_rows {self.global_queue_bound_rows} "
                    f"is below the per-replica bound {local} — the tier "
                    "budget would shed before any single replica's queue "
                    "could legally fill"
                )
            top = serving.buckets[-1] if serving.buckets else 0
            if top and self.global_queue_bound_rows < top:
                v.append(
                    f"global_queue_bound_rows {self.global_queue_bound_rows} "
                    f"is below the top ladder rung {top} — no saturated "
                    "dispatch could ever be admitted tier-wide"
                )
        if self.drain_timeout_s <= 0 or self.handover_timeout_s <= 0:
            v.append(
                f"lifecycle timeouts must be positive, got drain="
                f"{self.drain_timeout_s}, handover={self.handover_timeout_s}"
            )
        elif self.handover_timeout_s > self.drain_timeout_s:
            v.append(
                f"handover_timeout_s {self.handover_timeout_s} exceeds "
                f"drain_timeout_s {self.drain_timeout_s} — a re-shard "
                "handover flushes a subset of one replica's in-flight "
                "work and can never be allowed longer than a full drain"
            )
        return v


#: float dtype names the precision policy can legislate over
PRECISION_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")

#: the site-role taxonomy the dtype-flow pass classifies eqns into
#: (:mod:`stmgcn_tpu.analysis.dtype_flow`); ``role_dtypes`` keys must
#: come from here
PRECISION_SITE_ROLES = (
    "dot_general",        # MXU operand — bf16 inputs are the point
    "dot_general_accum",  # MXU accumulator (preferred_element_type)
    "reduce_sum",         # accumulating reduction (sum/cumsum/add_any)
    "reduce_order",       # order statistic (max/min) — never accumulates
    "scan_carry",         # loop-carried state (params/opt-state/stats)
    "psum",               # cross-device gradient sync operand
    "normalization",      # variance/norm stat (sqrt/rsqrt chains)
    "cast",               # explicit convert_element_type boundary
    "loss",               # the loss output leaf
    "optimizer_update",   # opt-state output leaves
    "master_param",       # param input/output leaves
    "prediction",         # served prediction output leaves
)


@dataclasses.dataclass
class PrecisionPolicy:
    """Declarative mixed-precision contract (the bf16 migration's law).

    Pure config math in the established config-before-compute pattern:
    ``violations()`` is the self-consistency contract behind the
    ``precision-policy`` lint rule, and the dtype-flow pass
    (:mod:`stmgcn_tpu.analysis.precision_check`) judges every traced
    step program's role-classified sites against these knobs. The
    defaults encode the paper recipe this repo certifies against
    ("Fast Training of Sparse Graph Neural Networks on Dense
    Hardware"): bf16 allowed at MXU operands and order statistics, f32
    mandatory at every accumulation site (dot accumulators, sum
    reductions, scan carries, psums, normalization stats, loss,
    optimizer state), f32 master params, and only the f32<->bf16
    boundary casts whitelisted.
    """

    #: role -> allowed compute dtype names at sites of that role. Roles
    #: absent here are ungated by the precision-policy rule (the
    #: accumulation roles below are gated by accum-dtype instead).
    role_dtypes: dict = dataclasses.field(default_factory=lambda: {
        "dot_general": ("float32", "bfloat16"),
        "dot_general_accum": ("float32",),
        "reduce_sum": ("float32",),
        "reduce_order": ("float32", "bfloat16"),
        "scan_carry": ("float32",),
        "psum": ("float32",),
        "normalization": ("float32",),
        "loss": ("float32",),
        "optimizer_update": ("float32",),
        "prediction": ("float32", "bfloat16"),
    })
    #: roles where any floating dtype narrower than f32 is the
    #: ``accum-dtype`` error — the mandatory-f32 accumulation set
    reduction_f32_roles: tuple = (
        "reduce_sum", "scan_carry", "psum", "dot_general_accum",
    )
    #: dtype the trained parameters (and optimizer moments) live in at
    #: step boundaries — low-precision *compute* casts down from these,
    #: never the other way around
    master_param_dtype: str = "float32"
    #: ``(src, dst)`` float cast pairs the program may contain; any
    #: other float->float dtype-changing cast is the ``implicit-cast``
    #: error (casts *to* float64 are owned by fp64-promotion)
    cast_whitelist: tuple = (
        ("float32", "bfloat16"), ("bfloat16", "float32"),
    )

    def __post_init__(self):
        # json round-trips hand lists back; canonicalize to tuples
        self.role_dtypes = {
            k: tuple(v) for k, v in dict(self.role_dtypes).items()
        }
        self.reduction_f32_roles = tuple(self.reduction_f32_roles)
        self.cast_whitelist = tuple(tuple(p) for p in self.cast_whitelist)

    def allowed(self, role: str) -> Optional[tuple]:
        """Allowed dtype names for a role, None when the role is ungated."""
        if role == "master_param":
            return (self.master_param_dtype,)
        return self.role_dtypes.get(role)

    def violations(self) -> list:
        """Every way this policy is self-contradictory (empty = valid).

        A policy that *cannot* certify what it claims — a master dtype
        the optimizer loses bits in, an accumulation role whose own
        allowance permits sub-f32, a cast whitelist that legalizes the
        fp64 promotion another rule bans — is a config bug detectable
        before any program is walked.
        """
        v = []
        itemsize = {"float16": 2, "bfloat16": 2, "float32": 4, "float64": 8}
        if self.master_param_dtype not in PRECISION_FLOAT_DTYPES:
            v.append(
                f"master_param_dtype {self.master_param_dtype!r} is not a "
                f"float dtype name {PRECISION_FLOAT_DTYPES}"
            )
        elif itemsize[self.master_param_dtype] < 4:
            v.append(
                f"master_param_dtype {self.master_param_dtype!r} is "
                "narrower than float32 — optimizer updates underflow in "
                "sub-f32 master params; keep masters wide and cast for "
                "compute instead"
            )
        for role, allowed in self.role_dtypes.items():
            if role not in PRECISION_SITE_ROLES:
                v.append(
                    f"role_dtypes names unknown role {role!r} — the site "
                    f"taxonomy is {PRECISION_SITE_ROLES}"
                )
                continue
            if not allowed:
                v.append(f"role_dtypes[{role!r}] allows no dtype at all")
            for d in allowed:
                if d not in PRECISION_FLOAT_DTYPES:
                    v.append(
                        f"role_dtypes[{role!r}] names unknown float dtype "
                        f"{d!r}"
                    )
        if not self.reduction_f32_roles:
            v.append(
                "reduction_f32_roles is empty — with no mandatory-f32 "
                "accumulation roles a bf16 accumulator certifies clean, "
                "which defeats the policy's purpose"
            )
        for role in self.reduction_f32_roles:
            if role not in PRECISION_SITE_ROLES:
                v.append(
                    f"reduction_f32_roles names unknown role {role!r}"
                )
                continue
            narrow = [
                d for d in self.role_dtypes.get(role, ())
                if itemsize.get(d, 4) < 4
            ]
            if narrow:
                v.append(
                    f"role {role!r} is in reduction_f32_roles (mandatory "
                    f"f32) but role_dtypes allows {narrow} — the two "
                    "knobs contradict each other"
                )
        for pair in self.cast_whitelist:
            if len(pair) != 2:
                v.append(f"cast_whitelist entry {pair!r} is not a (src, dst) pair")
                continue
            src, dst = pair
            bad = [d for d in (src, dst) if d not in PRECISION_FLOAT_DTYPES]
            if bad:
                v.append(
                    f"cast_whitelist pair {pair!r} names unknown float "
                    f"dtype(s) {bad}"
                )
                continue
            if src == dst:
                v.append(
                    f"cast_whitelist pair {pair!r} casts a dtype to itself "
                    "— not a precision boundary"
                )
            if dst == "float64":
                v.append(
                    f"cast_whitelist pair {pair!r} whitelists a promotion "
                    "to float64, which the fp64-promotion rule bans "
                    "unconditionally (TPUs have no fp64 MXU path)"
                )
        return v


@dataclasses.dataclass
class ExperimentConfig:
    name: str = "default"
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    health: HealthConfig = dataclasses.field(default_factory=HealthConfig)
    continual: ContinualConfig = dataclasses.field(default_factory=ContinualConfig)
    federation: FederationConfig = dataclasses.field(default_factory=FederationConfig)
    precision: PrecisionPolicy = dataclasses.field(default_factory=PrecisionPolicy)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ExperimentConfig":
        return cls(
            name=d.get("name", "default"),
            data=DataConfig(**d.get("data", {})),
            model=ModelConfig(**d.get("model", {})),
            train=TrainConfig(**d.get("train", {})),
            mesh=MeshConfig(**d.get("mesh", {})),
            serving=ServingConfig(**d.get("serving", {})),
            obs=ObsConfig(**d.get("obs", {})),
            health=HealthConfig(**d.get("health", {})),
            continual=ContinualConfig(**d.get("continual", {})),
            federation=FederationConfig(**d.get("federation", {})),
            precision=PrecisionPolicy(**d.get("precision", {})),
        )


def _smoke() -> ExperimentConfig:
    """BASELINE config 1: single neighborhood-graph ChebGCN, 10x10 grid."""
    return ExperimentConfig(
        name="smoke",
        data=DataConfig(rows=10, n_timesteps=24 * 7 * 4),
        model=ModelConfig(m_graphs=1, lstm_hidden_dim=32, lstm_num_layers=1,
                          gcn_hidden_dim=32),
        train=TrainConfig(epochs=5, batch_size=32),
    )


def _default() -> ExperimentConfig:
    """BASELINE config 2: full ST-MGCN, 3 graphs + CGRNN."""
    return ExperimentConfig(name="default", data=DataConfig(rows=10))


def _scaled() -> ExperimentConfig:
    """BASELINE config 3: 50x50 grid, K=3, region axis sharded across 8.

    N=2500 does not divide region=8 — the node axis carries 4 zero-padded
    rows (2504 = 8 x 313; isolated nodes, masked out of gate/loss/metrics).
    ``region_strategy="auto"`` puts the banded grid branch on the explicit
    halo plan (cheb-K3 bandwidth 150 <= shard 313 // 2 = 156) and the
    non-banded transport/similarity branches on GSPMD.
    """
    return ExperimentConfig(
        name="scaled",
        data=DataConfig(rows=50, n_timesteps=24 * 7 * 4),
        model=ModelConfig(K=3, dtype="bfloat16"),
        train=TrainConfig(batch_size=16),
        mesh=MeshConfig(region=8, region_strategy="auto"),
    )


def _multicity() -> ExperimentConfig:
    """BASELINE config 4: heterogeneous city pair on a data-parallel mesh.

    Real city pairs (Chengdu + Beijing) differ in region count, series
    span, demand scale, and graphs — the cities here differ in all four
    (12x12 over 4 weeks vs 10x10 over 3 weeks; per-city normalizers and
    splits; per-city support stacks). One parameter set serves both (all
    parameters are region-count-agnostic); jit compiles one step per city
    shape.
    """
    return ExperimentConfig(
        name="multicity",
        data=DataConfig(
            rows=12,
            n_cities=2,
            n_timesteps=24 * 7 * 4,
            city_rows=(12, 10),
            city_timesteps=(24 * 7 * 4, 24 * 7 * 3),
        ),
        train=TrainConfig(batch_size=64),
        mesh=MeshConfig(dp=8),
    )


def _longhorizon() -> ExperimentConfig:
    """BASELINE config 5: 24-step history + 24-step seq2seq forecast,
    rematerialized scan."""
    return ExperimentConfig(
        name="longhorizon",
        data=DataConfig(rows=10, serial_len=24, horizon=24, n_timesteps=24 * 7 * 6),
        model=ModelConfig(remat=True),
    )


def _branchpar() -> ExperimentConfig:
    """Branch model parallelism: the flagship's M=3 vmapped branches (and
    their params/supports) sharded over a ``branch`` mesh axis, composed
    with data parallelism — the ``dp x branch`` plan ``dryrun_multichip``
    exercises. The branch-fusion sum lowers to one psum over ``branch``;
    the ``spmd-collective-manifest`` rule holds the compiled program to
    exactly that signature.
    """
    return ExperimentConfig(
        name="branchpar",
        data=DataConfig(rows=10, n_timesteps=24 * 7 * 4),
        train=TrainConfig(batch_size=16),
        mesh=MeshConfig(dp=2, branch=3),
    )


def _bandedbranch() -> ExperimentConfig:
    """Banded x branch composition on a 3-axis ``dp x region x branch``
    mesh: branch-stacked banded strips with each branch group running its
    own region halo ring (the loop-layout plan round 5 added).

    ``region_strategy="auto"`` routes each branch by its measured
    bandwidth: the 8x8 grid's cheb-K2 supports fit the halo budget
    (bandwidth 16 <= halo 16 <= n_local // 2 = 16); the synthetic
    transport branch is a symmetrized random graph that no node ordering
    bands, so on synthetic data the composition degrades to dense GSPMD
    by design. On banded city pairs (both branches within budget) the
    branch-stacked halo plan engages — that engaged composition is the
    program the spmd contract pass lowers and diffs against the
    manifest.
    """
    return ExperimentConfig(
        name="bandedbranch",
        data=DataConfig(rows=8, n_timesteps=24 * 7 * 4),
        model=ModelConfig(m_graphs=2),
        train=TrainConfig(batch_size=16),
        mesh=MeshConfig(
            dp=2, region=2, branch=2, region_strategy="auto", halo=16
        ),
    )


PRESETS = {
    "smoke": _smoke,
    "default": _default,
    "scaled": _scaled,
    "multicity": _multicity,
    "longhorizon": _longhorizon,
    "branchpar": _branchpar,
    "bandedbranch": _bandedbranch,
}


def preset(name: str) -> ExperimentConfig:
    if name not in PRESETS:
        raise ValueError(f"preset must be one of {sorted(PRESETS)}, got {name!r}")
    return PRESETS[name]()
