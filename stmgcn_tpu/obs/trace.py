"""Nested wall-clock span tracing into a bounded ring buffer.

Spans are plain host-side timers — ``with obs.span("superstep", step=i):``
— nested via a per-thread stack, recorded into a thread-safe ring
(oldest spans evicted, never unbounded growth) and exported as
schema-versioned JSONL. Device-side work needs one extra step on the
tunneled backend: a dispatch returns as soon as the program is enqueued,
so a span that closes at the Python ``return`` measures enqueue latency,
not device time. :meth:`Span.fence` closes the span through the readback
fence in :mod:`stmgcn_tpu.utils.profiling` (block + one-element
device_get), which is the only honest device-completion edge we have.

The tracer is process-global and off by default. The disabled path is
the whole point of the design: hot loops ask :func:`active_tracer` once
per batch and skip every obs call when it returns ``None``, so tracing
adds **zero per-step allocations** when disabled (context managers and
kwargs both allocate at the call site, which is why the hot paths use
the ``tracer.record_span(name, t0, t1)`` retroactive form instead).

Module scope is stdlib-only; jax is imported lazily inside
:meth:`Span.fence` so importing :mod:`stmgcn_tpu.obs` never pulls jax.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "SCHEMA_VERSION",
    "Span",
    "Tracer",
    "active_tracer",
    "configure",
    "enabled",
    "span",
]

#: bump when the JSONL span record shape changes; pinned by the slow-tier
#: trace-schema contract test
SCHEMA_VERSION = 1

#: default ring capacity; within the OBS_RING_BUDGET the obs-overhead
#: rule enforces for preset configs
DEFAULT_RING = 4096


class Span:
    """One open span. Close with :meth:`end` (host work) or
    :meth:`fence` (device work); both are idempotent-ish in the sense
    that only the first close records."""

    __slots__ = ("tracer", "name", "attrs", "id", "parent", "depth", "t0",
                 "_open")

    def __init__(self, tracer: "Tracer", name: str,
                 attrs: Optional[Dict[str, Any]],
                 span_id: int, parent: int, depth: int):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.id = span_id
        self.parent = parent
        self.depth = depth
        self.t0 = time.perf_counter()
        self._open = True

    def end(self) -> None:
        if not self._open:
            return
        self._open = False
        self.tracer._close(self, time.perf_counter())

    def fence(self, tree) -> None:
        """Block until ``tree``'s device work is done, then close.

        Tolerates trees with no array leaves (the fence raises
        ValueError there) by falling back to a plain :meth:`end` —
        an instrumentation span must never take down the run.
        """
        try:
            from stmgcn_tpu.utils.profiling import fence as _fence
            _fence(tree)
        except ValueError:
            pass
        self.end()

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end()


class _NoopSpan:
    """Stateless stand-in returned by :func:`span` when tracing is off.
    A single shared instance: no per-call allocation on the casual-use
    path (hot loops skip even this via :func:`active_tracer`)."""

    __slots__ = ()

    def end(self) -> None:
        pass

    def fence(self, tree) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class Tracer:
    """Bounded thread-safe span recorder.

    Closed spans land in a ring of at most ``capacity`` records; when
    full, the oldest are evicted and :attr:`dropped` counts them, so a
    long run degrades to "most recent window" instead of OOM. Span
    nesting (parent/depth) is tracked per thread.
    """

    def __init__(self, capacity: int = DEFAULT_RING):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.dropped = 0
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._next_id = 1
        self._t_origin = time.perf_counter()

    # -- span lifecycle ------------------------------------------------

    def _stack(self) -> List[int]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **attrs: Any) -> Span:
        stack = self._stack()
        parent = stack[-1] if stack else 0
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        sp = Span(self, name, attrs or None, span_id, parent, len(stack))
        stack.append(span_id)
        return sp

    def _close(self, sp: Span, t1: float) -> None:
        stack = self._stack()
        # unwind to this span; unbalanced closes (exception paths) drop
        # the abandoned children from the stack, not the ring
        while stack and stack[-1] != sp.id:
            stack.pop()
        if stack:
            stack.pop()
        self._record(sp.name, sp.t0, t1, sp.id, sp.parent, sp.depth, sp.attrs)

    def record_span(self, name: str, t0: float, t1: float,
                    attrs: Optional[Dict[str, Any]] = None) -> None:
        """Retroactive span from two ``perf_counter`` readings.

        The hot-loop form: the caller times with locals and reports
        after the fact, so the disabled path is a single ``is not None``
        check with no Span object, no kwargs dict, no context manager.
        Recorded at the current thread's nesting level.
        """
        stack = self._stack()
        parent = stack[-1] if stack else 0
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        self._record(name, t0, t1, span_id, parent, len(stack), attrs)

    def _record(self, name: str, t0: float, t1: float, span_id: int,
                parent: int, depth: int,
                attrs: Optional[Dict[str, Any]]) -> None:
        rec = {
            "schema_version": SCHEMA_VERSION,
            "id": span_id,
            "parent": parent,
            "depth": depth,
            "name": name,
            "ts": round((t0 - self._t_origin) * 1e3, 3),
            "dur_ms": round((t1 - t0) * 1e3, 3),
        }
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(rec)

    # -- export --------------------------------------------------------

    def spans(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def export_jsonl(self, path: str) -> int:
        """Write the ring as JSONL: a ``meta`` header line then one
        JSON object per span. Returns the number of spans written."""
        with self._lock:
            # one critical section for ring + dropped: the header's
            # dropped count stays consistent with the spans it describes
            spans = list(self._ring)
            dropped = self.dropped
        meta = {
            "schema_version": SCHEMA_VERSION,
            "kind": "meta",
            "capacity": self.capacity,
            "dropped": dropped,
            "spans": len(spans),
        }
        with open(path, "w") as f:
            f.write(json.dumps(meta, sort_keys=True) + "\n")
            for rec in spans:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        return len(spans)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0


# -- process-global switch ---------------------------------------------

_TRACER: Optional[Tracer] = None


def configure(enable: bool = True, capacity: int = DEFAULT_RING) -> Optional[Tracer]:
    """Turn tracing on (fresh :class:`Tracer`) or off (``None``)."""
    global _TRACER
    _TRACER = Tracer(capacity) if enable else None
    return _TRACER


def active_tracer() -> Optional[Tracer]:
    """The hot-loop gate: hoist ``trc = active_tracer()`` out of the
    loop and guard every obs call with ``if trc is not None``."""
    return _TRACER


def enabled() -> bool:
    return _TRACER is not None


def span(name: str, **attrs: Any):
    """Convenience for cool paths: a real span when tracing is on, the
    shared no-op otherwise. (Kwargs still allocate here — hot loops use
    :func:`active_tracer` + ``record_span`` instead.)"""
    trc = _TRACER
    if trc is None:
        return _NOOP_SPAN
    return trc.span(name, **attrs)
