"""Serving-side distribution drift: streaming moment sketches vs a
training-time baseline.

The retrain trigger for ROADMAP item 1: does live traffic still look
like the data the params were trained on? A :class:`MomentSketch` keeps
per-channel Welford moments (count/mean/M2 — inherently bounded, no
sample buffer) plus a fixed-bin histogram over *baseline-standardized*
values, so the PSI comparison needs no raw data retention. The baseline
is computed once at training time (:func:`baseline_from_samples`),
persisted inside checkpoint meta (``health_baseline``), and compared
live by a :class:`DriftMonitor` sitting at the serving normalize /
denormalize boundaries.

numpy + stdlib only — this rides inside ``serve_predict``, which is
deliberately JAX-free (the dispatch path never traces).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = [
    "DRIFT_SCHEMA_VERSION",
    "DriftMonitor",
    "MomentSketch",
    "baseline_from_samples",
    "drift_metrics",
    "psi",
]

DRIFT_SCHEMA_VERSION = 1

#: pooled standardized histograms span [-Z_EDGE, Z_EDGE]; the two outer
#: bins are open-ended so mass never falls off the support
Z_EDGE = 4.0

_EPS = 1e-6


def _as_channels(values, n_channels: int) -> np.ndarray:
    """Coerce an observation batch to ``(rows, C)`` float64."""
    a = np.asarray(values, dtype=np.float64)
    if a.ndim == 0:
        a = a.reshape(1, 1)
    if a.shape[-1] != n_channels:
        a = a.reshape(-1, 1) if n_channels == 1 else a.reshape(-1, n_channels)
    else:
        a = a.reshape(-1, n_channels)
    return a


def _hist_edges(bins: int) -> np.ndarray:
    """Internal edges of the pooled standardized histogram: ``bins``
    buckets over [-Z_EDGE, Z_EDGE] with open outer buckets."""
    if bins == 1:
        return np.empty(0)  # single catch-all bucket
    return np.linspace(-Z_EDGE, Z_EDGE, bins - 1)


class MomentSketch:
    """Streaming per-channel moments + pooled standardized histogram.

    ``norm=(mean, std)`` fixes the standardization the histogram uses —
    the *baseline's* moments for a live sketch, so live and baseline
    histograms share bins and PSI is well-defined. Without ``norm`` the
    sketch tracks moments only (histogram counts stay zero).
    """

    __slots__ = ("n_channels", "bins", "n", "mean", "m2", "counts", "_norm")

    def __init__(self, n_channels: int, bins: int = 64,
                 norm: Optional[Tuple[np.ndarray, np.ndarray]] = None):
        if n_channels < 1:
            raise ValueError(f"n_channels must be >= 1, got {n_channels}")
        if bins < 1:
            raise ValueError(f"bins must be >= 1, got {bins}")
        self.n_channels = n_channels
        self.bins = bins
        self.n = 0
        self.mean = np.zeros(n_channels)
        self.m2 = np.zeros(n_channels)
        self.counts = np.zeros(bins, dtype=np.int64)
        self._norm = None
        if norm is not None:
            mu, sd = norm
            self._norm = (
                np.asarray(mu, dtype=np.float64).reshape(n_channels),
                np.maximum(np.asarray(sd, dtype=np.float64)
                           .reshape(n_channels), _EPS),
            )

    def update(self, values) -> int:
        """Merge a batch of observations; returns rows consumed."""
        a = _as_channels(values, self.n_channels)
        nb = a.shape[0]
        if nb == 0:
            return 0
        # batched Welford merge: exact, no per-row loop
        mean_b = a.mean(axis=0)
        m2_b = ((a - mean_b) ** 2).sum(axis=0)
        tot = self.n + nb
        delta = mean_b - self.mean
        self.mean = self.mean + delta * (nb / tot)
        self.m2 = self.m2 + m2_b + delta**2 * (self.n * nb / tot)
        self.n = tot
        if self._norm is not None:
            mu, sd = self._norm
            z = ((a - mu) / sd).reshape(-1)
            idx = np.searchsorted(_hist_edges(self.bins), z)
            self.counts += np.bincount(idx, minlength=self.bins)
        return nb

    def var(self) -> np.ndarray:
        if self.n < 2:
            return np.zeros(self.n_channels)
        return self.m2 / (self.n - 1)

    def std(self) -> np.ndarray:
        return np.sqrt(self.var())

    def probs(self) -> np.ndarray:
        total = self.counts.sum()
        if total == 0:
            return np.full(self.bins, 1.0 / self.bins)
        return self.counts / total

    def to_dict(self) -> dict:
        return {
            "n": int(self.n),
            "mean": [float(v) for v in self.mean],
            "std": [float(v) for v in self.std()],
            "hist": [float(v) for v in self.probs()],
        }


def baseline_from_samples(samples, bins: int = 64,
                          n_channels: Optional[int] = None) -> dict:
    """Exact (two-pass) per-phase baseline from training-time data.

    Returns the JSON-able ``{"n", "mean", "std", "hist"}`` blob stored
    per city/phase inside checkpoint meta's ``health_baseline``; the
    histogram is over the samples standardized by their *own* moments,
    the same bins a live sketch standardized by this baseline uses.
    """
    a = np.asarray(samples, dtype=np.float64)
    c = n_channels if n_channels is not None else (
        a.shape[-1] if a.ndim >= 2 else 1)
    a = _as_channels(a, c)
    if a.shape[0] == 0:
        raise ValueError("baseline needs at least one sample row")
    mean = a.mean(axis=0)
    std = np.maximum(a.std(axis=0, ddof=1) if a.shape[0] > 1
                     else np.zeros(c), _EPS)
    z = ((a - mean) / std).reshape(-1)
    idx = np.searchsorted(_hist_edges(bins), z)
    counts = np.bincount(idx, minlength=bins).astype(np.float64)
    return {
        "n": int(a.shape[0]),
        "mean": [float(v) for v in mean],
        "std": [float(v) for v in std],
        "hist": [float(v) for v in counts / counts.sum()],
    }


def psi(expected, actual) -> float:
    """Population stability index between two probability vectors;
    epsilon-smoothed so empty bins don't blow up. Rule of thumb:
    < 0.1 stable, 0.1–0.25 moderate shift, > 0.25 major shift."""
    p = np.maximum(np.asarray(expected, dtype=np.float64), _EPS)
    q = np.maximum(np.asarray(actual, dtype=np.float64), _EPS)
    p = p / p.sum()
    q = q / q.sum()
    return float(np.sum((q - p) * np.log(q / p)))


def drift_metrics(baseline: dict, sketch: MomentSketch) -> dict:
    """Compare a live sketch against a per-phase baseline blob.

    ``z_max`` is the worst per-channel standardized mean shift
    ``(mu_live - mu_base) / (sigma_base / sqrt(n_live))`` — the classic
    large-sample z test for a drifted mean; ``psi`` compares the pooled
    standardized histograms.
    """
    if sketch.n == 0:
        return {"n": 0, "z_max": 0.0, "psi": 0.0}
    mu_b = np.asarray(baseline["mean"], dtype=np.float64)
    sd_b = np.maximum(np.asarray(baseline["std"], dtype=np.float64), _EPS)
    z = (sketch.mean - mu_b) / (sd_b / math.sqrt(sketch.n))
    return {
        "n": int(sketch.n),
        "z_max": float(np.max(np.abs(z))),
        "psi": psi(baseline["hist"], sketch.probs()),
    }


class DriftMonitor:
    """Generation-labeled live drift state for a serving engine.

    One monitor per engine; ``observe_*`` runs on the dispatch path so
    everything is lock-protected and numpy-cheap. ``reset(generation)``
    — called atomically with ``swap_params`` — drops every live sketch
    (and optionally swaps the baseline the new params were trained
    against), so gauges never mix traffic across param generations.
    """

    def __init__(self, baseline: dict, *, registry=None, generation: int = 0):
        self._lock = threading.Lock()
        self._registry = registry
        self.generation = generation
        self._baseline: Dict[str, Dict[str, dict]] = {}
        self._sketches: Dict[Tuple[str, str], MomentSketch] = {}
        with self._lock:  # same guard discipline as reset()
            self._set_baseline(baseline)

    def _set_baseline(self, baseline: dict) -> None:
        self.bins = int(baseline.get("bins", 64))
        self._baseline = {
            phase: {str(c): blob for c, blob in cities.items()}
            for phase, cities in (
                ("input", baseline.get("input", {})),
                ("prediction", baseline.get("prediction", {})),
            )
        }
        self._sketches = {}

    def _sketch_for(self, phase: str, city: str) -> Optional[MomentSketch]:
        blob = self._baseline.get(phase, {}).get(city)
        if blob is None:
            return None
        key = (phase, city)
        sk = self._sketches.get(key)
        if sk is None:
            sk = MomentSketch(
                len(blob["mean"]), bins=self.bins,
                norm=(np.asarray(blob["mean"]), np.asarray(blob["std"])),
            )
            self._sketches[key] = sk
        return sk

    def _observe(self, phase: str, city, values) -> None:
        city = str(city)
        with self._lock:
            sk = self._sketch_for(phase, city)
            if sk is None:
                return  # no baseline for this city/phase: nothing to compare
            sk.update(values)
            if self._registry is not None:
                m = drift_metrics(self._baseline[phase][city], sk)
                labels = {"city": city, "phase": phase,
                          "generation": str(self.generation)}
                self._registry.gauge("serving.drift.z_max", labels).set(
                    m["z_max"])
                self._registry.gauge("serving.drift.psi", labels).set(
                    m["psi"])
                self._registry.gauge("serving.drift.n", labels).set(m["n"])

    def observe_input(self, city, values) -> None:
        """Normalized model inputs for one city (the normalize boundary)."""
        self._observe("input", city, values)

    def observe_prediction(self, city, values) -> None:
        """Denormalized predictions for one city (the denormalize
        boundary)."""
        self._observe("prediction", city, values)

    def reset(self, generation: int, baseline: Optional[dict] = None) -> None:
        """Drop live sketches for a new param generation (hot-swap)."""
        with self._lock:
            self.generation = generation
            if baseline is not None:
                self._set_baseline(baseline)
            else:
                self._sketches = {}
            if self._registry is not None:
                self._registry.gauge(
                    "serving.drift.generation").set(generation)

    def snapshot(self) -> dict:
        """JSON-able drift state: per city/phase metrics + generation."""
        with self._lock:
            cities: Dict[str, dict] = {}
            for (phase, city), sk in self._sketches.items():
                m = drift_metrics(self._baseline[phase][city], sk)
                cities.setdefault(city, {})[phase] = m
            return {
                "schema_version": DRIFT_SCHEMA_VERSION,
                "generation": self.generation,
                "cities": cities,
            }
