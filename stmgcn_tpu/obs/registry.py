"""Process-wide metrics registry: counters, gauges, bounded reservoirs.

One registry per process (:data:`REGISTRY`), shared by every subsystem
that previously kept private dicts — :class:`~stmgcn_tpu.serving.metrics.
EngineStats` totals and sheds, hot-swap generations, checkpoint writes
and recoveries, fault injections, divergence-guard trips, and the
``jax.monitoring`` compile/transfer listeners (:mod:`.jaxmon`). The
exporters answer the two deployment questions the old private dicts
could not: "what is this process doing right now" (:meth:`MetricsRegistry
.to_json`) and "scrape me" (:meth:`MetricsRegistry.to_prometheus`,
text exposition format).

Everything here is stdlib-only and cheap: counters/gauges are one
``float`` behind the registry lock, histograms are a fixed-capacity
sample ring (:class:`Reservoir`) so a year-long serving process holds
the same memory as a one-minute test — the unbounded-list leak the old
``serving/metrics.py`` had is structurally impossible. The documented
budgets the ``obs-overhead`` lint rule enforces live in
:mod:`stmgcn_tpu.config` (``OBS_RING_BUDGET`` / ``OBS_RESERVOIR_BUDGET``).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "MetricsRegistry",
    "REGISTRY",
    "Reservoir",
    "registry",
]

#: default bounded-histogram capacity; within the documented budget the
#: ``obs-overhead`` rule enforces for preset configs
DEFAULT_RESERVOIR = 1024


class Counter:
    """Monotonic (within a reset) numeric counter."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge:
    """Last-write-wins numeric gauge."""

    __slots__ = ("name", "labels", "_value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def reset(self) -> None:
        self._value = 0.0


class Reservoir:
    """Bounded sample ring for percentile estimation.

    Keeps the most recent ``capacity`` samples (deterministic — no
    random eviction, so short runs and tests see *exactly* the samples
    they recorded) plus the all-time ``count``/``total``, so means over
    the full stream survive eviction. ``percentiles()`` matches the
    shape of the old ``serving.metrics.percentiles`` output.
    """

    __slots__ = ("name", "labels", "capacity", "_ring", "_count", "_total",
                 "_lock")

    def __init__(self, name: str = "", capacity: int = DEFAULT_RESERVOIR,
                 labels: Tuple[Tuple[str, str], ...] = ()):
        if capacity < 1:
            raise ValueError(f"reservoir capacity must be >= 1, got {capacity}")
        self.name = name
        self.labels = labels
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._count = 0
        self._total = 0.0
        self._lock = threading.Lock()

    def add(self, v: float) -> None:
        with self._lock:
            self._ring.append(v)
            self._count += 1
            self._total += v

    def extend(self, vs) -> None:
        with self._lock:
            for v in vs:
                self._ring.append(v)
                self._count += 1
                self._total += v

    @property
    def count(self) -> int:
        """All-time samples recorded (>= len(samples()) once evicting)."""
        with self._lock:
            return self._count

    @property
    def total(self) -> float:
        with self._lock:
            return self._total

    def samples(self) -> List[float]:
        with self._lock:
            return list(self._ring)

    def mean(self, default: float = 0.0) -> float:
        """Mean over the retained window (the wait-model estimator)."""
        with self._lock:
            if not self._ring:
                return default
            return sum(self._ring) / len(self._ring)

    def percentiles(self) -> dict:
        """p50/p95/p99/mean over the retained window (None when empty)."""
        samples = self.samples()
        if not samples:
            return {"p50": None, "p95": None, "p99": None, "mean": None}
        ordered = sorted(samples)

        def pct(q: float) -> float:
            # numpy's default linear interpolation, dependency-free
            pos = (len(ordered) - 1) * q
            lo = int(pos)
            hi = min(lo + 1, len(ordered) - 1)
            return ordered[lo] + (ordered[hi] - ordered[lo]) * (pos - lo)

        return {
            "p50": round(pct(0.50), 3),
            "p95": round(pct(0.95), 3),
            "p99": round(pct(0.99), 3),
            "mean": round(sum(ordered) / len(ordered), 3),
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._count = 0
            self._total = 0.0


def _label_key(labels: Optional[dict]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Thread-safe get-or-create metric store with JSON + Prometheus
    exporters. Metric identity is ``(name, sorted labels)`` — a second
    ``counter("x")`` call returns the same object, so call sites never
    hold registration state of their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}

    def _get_or_create(self, cls, name: str, labels: Optional[dict],
                       **kwargs):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name=name, labels=key[1], **kwargs)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, labels: Optional[dict] = None) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, labels: Optional[dict] = None) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str, labels: Optional[dict] = None,
                  capacity: int = DEFAULT_RESERVOIR) -> Reservoir:
        return self._get_or_create(Reservoir, name, labels, capacity=capacity)

    def metrics(self) -> list:
        with self._lock:
            return list(self._metrics.values())

    def to_json(self) -> dict:
        """``{name{labels}: value-or-percentiles}`` snapshot."""
        out: dict = {}
        for m in self.metrics():
            key = m.name
            if m.labels:
                rendered = ",".join(f"{k}={v}" for k, v in m.labels)
                key = f"{m.name}{{{rendered}}}"
            if isinstance(m, Reservoir):
                out[key] = {"count": m.count, **m.percentiles()}
            else:
                v = m.value
                out[key] = int(v) if float(v).is_integer() else v
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4): one sample per line;
        reservoirs expose ``_count`` plus quantile-labeled samples."""
        lines: List[str] = []
        for m in self.metrics():
            name = m.name.replace(".", "_").replace("-", "_")
            base = dict(m.labels)
            if isinstance(m, Reservoir):
                pct = m.percentiles()
                lines.append(f"# TYPE {name} summary")
                lines.append(
                    f"{name}_count{_prom_labels(base)} {m.count}"
                )
                for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                    if pct[key] is not None:
                        lines.append(
                            f"{name}{_prom_labels({**base, 'quantile': q})} "
                            f"{pct[key]}"
                        )
            else:
                kind = "counter" if isinstance(m, Counter) else "gauge"
                lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name}{_prom_labels(base)} {m.value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Zero every metric (tests / per-leg bench accounting); metric
        objects stay registered so held references keep working."""
        for m in self.metrics():
            m.reset()

    def dumps(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True)


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


#: the ONE process-wide registry every subsystem records into
REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return REGISTRY
