"""Unified runtime observability: span tracing, the process-wide
metrics registry, and JAX compile/transfer telemetry.

Stdlib-only at import time (jax loads lazily inside
:func:`jaxmon.install` and :meth:`trace.Span.fence`), off by default,
and free when off: hot loops hoist :func:`active_tracer` and skip every
obs call when it returns ``None``.
"""

from .registry import (
    Counter,
    Gauge,
    MetricsRegistry,
    REGISTRY,
    Reservoir,
    registry,
)
from .trace import (
    SCHEMA_VERSION,
    Span,
    Tracer,
    active_tracer,
    configure,
    enabled,
    span,
)
from .jaxmon import (
    install,
    installed,
    mark_warmup_complete,
    record_upload,
)
from . import jaxmon, report

__all__ = [
    "Counter",
    "Gauge",
    "MetricsRegistry",
    "REGISTRY",
    "Reservoir",
    "SCHEMA_VERSION",
    "Span",
    "Tracer",
    "active_tracer",
    "configure",
    "enabled",
    "install",
    "installed",
    "jaxmon",
    "mark_warmup_complete",
    "record_upload",
    "registry",
    "report",
    "span",
]
