"""Unified runtime observability: span tracing, the process-wide
metrics registry, JAX compile/transfer telemetry, and the numeric
health/drift layer (``health.jsonl`` + serving distribution drift).

jax-free at import time (it loads lazily inside :func:`jaxmon.install`
and :meth:`trace.Span.fence`; :mod:`.drift` needs only numpy), off by
default, and free when off: hot loops hoist :func:`active_tracer` and
skip every obs call when it returns ``None``.
"""

from .registry import (
    Counter,
    Gauge,
    MetricsRegistry,
    REGISTRY,
    Reservoir,
    registry,
)
from .trace import (
    SCHEMA_VERSION,
    Span,
    Tracer,
    active_tracer,
    configure,
    enabled,
    span,
)
from .jaxmon import (
    install,
    installed,
    mark_warmup_complete,
    record_upload,
)
from .health import (
    HEALTH_SCHEMA_VERSION,
    HealthWriter,
    load_health,
    publish_train_health,
    render_health_table,
    summarize_health,
)
from .drift import (
    DRIFT_SCHEMA_VERSION,
    DriftMonitor,
    MomentSketch,
    baseline_from_samples,
    drift_metrics,
    psi,
)
from . import drift, health, jaxmon, report

__all__ = [
    "Counter",
    "DRIFT_SCHEMA_VERSION",
    "DriftMonitor",
    "Gauge",
    "HEALTH_SCHEMA_VERSION",
    "HealthWriter",
    "MetricsRegistry",
    "MomentSketch",
    "REGISTRY",
    "Reservoir",
    "SCHEMA_VERSION",
    "Span",
    "Tracer",
    "active_tracer",
    "baseline_from_samples",
    "configure",
    "drift",
    "drift_metrics",
    "enabled",
    "health",
    "install",
    "installed",
    "jaxmon",
    "load_health",
    "mark_warmup_complete",
    "psi",
    "publish_train_health",
    "record_upload",
    "registry",
    "render_health_table",
    "span",
    "summarize_health",
]
