"""``stmgcn obs`` / ``stmgcn health`` — inspect exported telemetry files.

``stmgcn obs TRACE`` summarizes a JSONL span trace. Text mode renders
the per-phase table; ``--format json`` prints exactly one JSON line on
stdout (machine contract, same discipline as the bench CLIs) with the
summary, meta header, and — with ``--dump`` — the raw spans; ``--format
chrome`` prints the trace in Chrome trace-event JSON for
chrome://tracing / Perfetto ("open legacy trace"), threads rendered as
tracks and nested spans as duration events.

``stmgcn health PATH`` summarizes a ``health.jsonl`` file written by a
health-instrumented training run: loss/grad-norm/update-ratio rollups,
nonfinite counts, per-group gradient norms, per-city loss attribution,
and — when drift records are present — the worst-city drift z/PSI.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .health import load_health, render_health_table, summarize_health
from .report import chrome_trace, load_trace, render_table, summarize

__all__ = ["build_obs_parser", "build_health_parser", "health_main", "main"]


def _quiet_broken_pipe() -> None:
    # `stmgcn obs trace | head` closing the pipe early is fine; don't
    # let the teardown flush traceback either
    try:
        sys.stdout.close()
    except BrokenPipeError:
        pass


def build_obs_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stmgcn obs",
        description="Summarize a JSONL span trace (see README Observability).",
    )
    p.add_argument("trace", help="path to a --trace-out JSONL file")
    p.add_argument("--format", choices=("text", "json", "chrome"),
                   default="text",
                   help="text table, one JSON line, or a Chrome/Perfetto "
                        "trace-event JSON on stdout")
    p.add_argument("--dump", action="store_true",
                   help="include raw spans (json) / print them (text)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_obs_parser().parse_args(argv)
    try:
        meta, spans = load_trace(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"obs: cannot read trace: {e}", file=sys.stderr)
        return 2

    try:
        if args.format == "chrome":
            # redirect into a .json file and load it in chrome://tracing
            # or ui.perfetto.dev; still one JSON document on stdout
            sys.stdout.write(
                json.dumps(chrome_trace(meta, spans), sort_keys=True) + "\n"
            )
            return 0

        summary = summarize(spans)
        if args.format == "json":
            out = {"meta": meta, "summary": summary}
            if args.dump:
                out["spans"] = spans
            sys.stdout.write(json.dumps(out, sort_keys=True) + "\n")
            return 0

        print(render_table(summary, meta))
        if args.dump:
            for s in spans:
                print(json.dumps(s, sort_keys=True))
    except BrokenPipeError:
        _quiet_broken_pipe()
    return 0


def build_health_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stmgcn health",
        description="Summarize a health.jsonl numeric-health log "
                    "(see README Numeric health & drift).",
    )
    p.add_argument("path", help="path to a --health-out JSONL file")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="text report or one JSON line on stdout")
    p.add_argument("--dump", action="store_true",
                   help="include raw records (json) / print them (text)")
    return p


def health_main(argv: Optional[List[str]] = None) -> int:
    args = build_health_parser().parse_args(argv)
    try:
        meta, records = load_health(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"health: cannot read log: {e}", file=sys.stderr)
        return 2

    summary = summarize_health(records)
    try:
        if args.format == "json":
            out = {"meta": meta, "summary": summary}
            if args.dump:
                out["records"] = records
            sys.stdout.write(json.dumps(out, sort_keys=True) + "\n")
            return 0

        print(render_health_table(summary, meta))
        if args.dump:
            for r in records:
                print(json.dumps(r, sort_keys=True))
    except BrokenPipeError:
        _quiet_broken_pipe()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
