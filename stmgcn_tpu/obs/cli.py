"""``stmgcn obs`` — dump/summarize an exported JSONL trace.

Text mode renders the per-phase table; ``--format json`` prints exactly
one JSON line on stdout (machine contract, same discipline as the bench
CLIs) with the summary, meta header, and — with ``--dump`` — the raw
spans.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .report import load_trace, render_table, summarize

__all__ = ["build_obs_parser", "main"]


def build_obs_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stmgcn obs",
        description="Summarize a JSONL span trace (see README Observability).",
    )
    p.add_argument("trace", help="path to a --trace-out JSONL file")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="text table or one JSON line on stdout")
    p.add_argument("--dump", action="store_true",
                   help="include raw spans (json) / print them (text)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_obs_parser().parse_args(argv)
    try:
        meta, spans = load_trace(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"obs: cannot read trace: {e}", file=sys.stderr)
        return 2

    summary = summarize(spans)
    try:
        if args.format == "json":
            out = {"meta": meta, "summary": summary}
            if args.dump:
                out["spans"] = spans
            sys.stdout.write(json.dumps(out, sort_keys=True) + "\n")
            return 0

        print(render_table(summary, meta))
        if args.dump:
            for s in spans:
                print(json.dumps(s, sort_keys=True))
    except BrokenPipeError:
        # `stmgcn obs trace | head` closing the pipe early is fine; don't
        # let the teardown flush traceback either
        try:
            sys.stdout.close()
        except BrokenPipeError:
            pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
