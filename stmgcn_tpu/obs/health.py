"""Numeric training health: the ``health.jsonl`` stream and its report.

The training side of the health/drift layer. The superstep scan bodies
(:mod:`stmgcn_tpu.train.step`, ``health=True`` variants) compute the
statistics on device as extra scan ys — global/per-group gradient
norms, update ratio, nonfinite grad/loss counts, per-city loss
attribution on the fleet path — and the trainer downloads them once per
health superstep and hands them here: :class:`HealthWriter` appends the
schema-versioned JSONL stream, :func:`publish_train_health` feeds the
process-wide metrics registry, and :func:`summarize_health` /
:func:`render_health_table` back the ``stmgcn health`` report command.

Same file discipline as the trace JSONL: a ``kind: "meta"`` header line
first, then one JSON object per record, every line stamped with
``schema_version``.
"""

from __future__ import annotations

import json
import math
from typing import Dict, List, Optional, Tuple

__all__ = [
    "HEALTH_SCHEMA_VERSION",
    "HealthWriter",
    "load_health",
    "publish_train_health",
    "render_health_table",
    "summarize_health",
]

HEALTH_SCHEMA_VERSION = 1


class HealthWriter:
    """Append-only ``health.jsonl`` writer (meta header + records).

    Opens lazily on the first record so a health-enabled run that dies
    before its first health superstep leaves no empty file behind.
    """

    def __init__(self, path: str, meta: Optional[dict] = None):
        self.path = path
        self._meta = dict(meta or {})
        self._f = None
        self.records = 0

    def _ensure_open(self) -> None:
        if self._f is None:
            self._f = open(self.path, "a")
            header = {
                "schema_version": HEALTH_SCHEMA_VERSION,
                "kind": "meta",
                **self._meta,
            }
            self._f.write(json.dumps(header) + "\n")

    def write(self, record: dict) -> None:
        self._ensure_open()
        self._f.write(json.dumps(
            {"schema_version": HEALTH_SCHEMA_VERSION, **record}) + "\n")
        self.records += 1

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def publish_train_health(record: dict, registry) -> None:
    """Feed one training health record into the metrics registry.

    Gauges are last-write-wins running state; the nonfinite counts are
    cumulative counters — the signal CI gates on (any nonfinite during
    the smoke train fails the lint gate).
    """
    for key, name in (("loss", "train.health.loss"),
                      ("grad_norm", "train.health.grad_norm"),
                      ("update_ratio", "train.health.update_ratio")):
        if key in record:
            registry.gauge(name).set(record[key])
    for key, name in (("nonfinite_grads", "train.health.nonfinite_grads"),
                      ("nonfinite_loss", "train.health.nonfinite_loss")):
        if record.get(key):
            registry.counter(name).inc(record[key])
    for group, v in (record.get("group_norms") or {}).items():
        registry.gauge("train.health.group_norm",
                       {"group": group}).set(v)
    for city, v in (record.get("city_loss") or {}).items():
        registry.gauge("train.health.city_loss",
                       {"city": str(city)}).set(v)


def load_health(path: str) -> Tuple[Optional[dict], List[dict]]:
    """Parse ``health.jsonl`` → (meta-or-None, records); strict schema,
    same contract as :func:`stmgcn_tpu.obs.report.load_trace`."""
    meta: Optional[dict] = None
    records: List[dict] = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{line_no}: expected JSON object")
            if rec.get("kind") == "meta":
                meta = rec
            else:
                records.append(rec)
    return meta, records


def _agg(values: List[float]) -> dict:
    finite = [v for v in values if isinstance(v, (int, float))
              and math.isfinite(v)]
    if not finite:
        return {"last": None, "mean": None, "max": None}
    return {
        "last": round(finite[-1], 6),
        "mean": round(sum(finite) / len(finite), 6),
        "max": round(max(finite), 6),
    }


def summarize_health(records: List[dict]) -> dict:
    """Aggregate a health stream per phase (``train`` / ``drift``).

    Training records roll up into per-metric last/mean/max plus total
    nonfinite counts and per-group/per-city state; drift records keep
    per-city worst-case z/PSI and name the overall worst city.
    """
    train = [r for r in records if r.get("kind") == "train"]
    drift = [r for r in records if r.get("kind") == "drift"]

    out: dict = {"records": len(records), "train": None, "drift": None}

    if train:
        groups: Dict[str, List[float]] = {}
        cities: Dict[str, List[float]] = {}
        for r in train:
            for g, v in (r.get("group_norms") or {}).items():
                groups.setdefault(g, []).append(v)
            for c, v in (r.get("city_loss") or {}).items():
                cities.setdefault(str(c), []).append(v)
        out["train"] = {
            "count": len(train),
            "last_step": train[-1].get("step"),
            "loss": _agg([r.get("loss") for r in train]),
            "grad_norm": _agg([r.get("grad_norm") for r in train]),
            "update_ratio": _agg([r.get("update_ratio") for r in train]),
            "nonfinite_grads": sum(r.get("nonfinite_grads", 0) for r in train),
            "nonfinite_loss": sum(r.get("nonfinite_loss", 0) for r in train),
            "groups": {g: _agg(vs) for g, vs in sorted(groups.items())},
            "city_loss": {c: _agg(vs) for c, vs in sorted(cities.items())},
        }

    if drift:
        per_city: Dict[Tuple[str, str], dict] = {}
        for r in drift:
            key = (str(r.get("city")), str(r.get("phase")))
            cur = per_city.get(key)
            if cur is None or r.get("z_max", 0.0) > cur.get("z_max", 0.0):
                per_city[key] = r
        worst = max(per_city.values(),
                    key=lambda r: abs(r.get("z_max", 0.0)))
        out["drift"] = {
            "count": len(drift),
            "worst": {
                "city": str(worst.get("city")),
                "phase": worst.get("phase"),
                "z_max": round(worst.get("z_max", 0.0), 4),
                "psi": round(worst.get("psi", 0.0), 6),
                "generation": worst.get("generation"),
            },
            "cities": {
                f"{c}/{p}": {
                    "z_max": round(r.get("z_max", 0.0), 4),
                    "psi": round(r.get("psi", 0.0), 6),
                    "n": r.get("n"),
                    "generation": r.get("generation"),
                }
                for (c, p), r in sorted(per_city.items())
            },
        }
    return out


def render_health_table(summary: dict, meta: Optional[dict] = None) -> str:
    """Fixed-width per-phase health report for terminals."""
    lines: List[str] = []
    if meta:
        lines.append(
            f"health: schema v{meta.get('schema_version', '?')}, "
            f"every_k={meta.get('every_k', '?')}"
        )
    t = summary.get("train")
    if t:
        lines.append(
            f"train: {t['count']} health supersteps, "
            f"last step {t['last_step']}, "
            f"nonfinite grads {t['nonfinite_grads']}, "
            f"nonfinite loss {t['nonfinite_loss']}"
        )
        header = f"{'metric':<28} {'last':>12} {'mean':>12} {'max':>12}"
        lines.append(header)
        lines.append("-" * len(header))

        def row(name: str, a: dict) -> str:
            def fmt(v):
                return f"{v:>12.6g}" if v is not None else f"{'-':>12}"
            return f"{name:<28} {fmt(a['last'])} {fmt(a['mean'])} {fmt(a['max'])}"

        lines.append(row("loss", t["loss"]))
        lines.append(row("grad_norm", t["grad_norm"]))
        lines.append(row("update_ratio", t["update_ratio"]))
        for g, a in t["groups"].items():
            lines.append(row(f"grad_norm[{g}]", a))
        for c, a in t["city_loss"].items():
            lines.append(row(f"city_loss[{c}]", a))
    d = summary.get("drift")
    if d:
        w = d["worst"]
        lines.append(
            f"drift: {d['count']} records; worst city {w['city']} "
            f"({w['phase']}): z_max={w['z_max']}, psi={w['psi']} "
            f"(generation {w['generation']})"
        )
        for key, m in d["cities"].items():
            lines.append(
                f"  {key:<20} z_max={m['z_max']:<10} psi={m['psi']:<10} "
                f"n={m['n']}"
            )
    if not t and not d:
        lines.append("(no health records)")
    return "\n".join(lines)
