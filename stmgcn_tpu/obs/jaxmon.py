"""JAX runtime telemetry via ``jax.monitoring`` listeners.

Counts backend compilations as they happen and exposes a
``jax.recompiles_after_warmup`` gauge: after the caller declares warmup
complete (:func:`mark_warmup_complete`, e.g. at the end of the first
training epoch, once every jitted program has been traced), any further
compile is a *runtime* recompile alarm — the dynamic counterpart of the
static ``recompile-hazard`` lint rule, catching shape/dtype drift the
AST pass cannot see. ``scripts/lint_gate.sh`` fails the gate when a
traced smoke run reports a nonzero value.

Host→device transfer telemetry: jax 0.4.x emits no transfer events on
the CPU/tunneled backends, so upload accounting is done at the
instrumentation sites instead — the trainer's double-buffered ``place``
and the serving upload paths call :func:`record_upload` with the array
byte counts they just moved, giving the measured upload-bytes-per-step
number the window-free path claims.

All counters live in the shared :data:`~stmgcn_tpu.obs.registry.REGISTRY`.
jax is imported inside :func:`install` only — module scope stays
stdlib-only, and installing is idempotent (``jax.monitoring`` has no
per-listener unregister, so a second install must be a no-op).
"""

from __future__ import annotations

from typing import Optional

from .registry import REGISTRY

__all__ = [
    "freeze_recompiles",
    "install",
    "installed",
    "mark_warmup_complete",
    "record_upload",
    "snapshot",
]

#: the duration event jax 0.4.x emits once per backend (XLA) compile
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_INSTALLED = False


def install() -> bool:
    """Register the monitoring listeners (idempotent). Returns True if
    listeners are active after the call, False when the running jax has
    no ``jax.monitoring`` (older/stubbed builds) — callers degrade to
    zero-valued counters rather than failing."""
    global _INSTALLED
    if _INSTALLED:
        return True
    try:
        from jax import monitoring
    except ImportError:
        return False

    compiles = REGISTRY.counter("jax.compilations")
    compile_ms = REGISTRY.counter("jax.compile_ms")
    events = REGISTRY.counter("jax.monitoring_events")

    def _on_duration(event: str, duration: float, **kwargs) -> None:
        if event == _COMPILE_EVENT:
            compiles.inc()
            compile_ms.inc(duration * 1e3)

    def _on_event(event: str, **kwargs) -> None:
        events.inc()

    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)
    _INSTALLED = True
    return True


def installed() -> bool:
    return _INSTALLED


#: recompile count pinned by :func:`freeze_recompiles`; None = live
_FROZEN: Optional[float] = None


def mark_warmup_complete() -> float:
    """Snapshot the compile count as the warmup baseline. Every compile
    after this point shows up in the ``jax.recompiles_after_warmup``
    gauge (refreshed by :func:`snapshot`). Returns the baseline.
    Re-marking re-baselines and unfreezes the gauge."""
    global _FROZEN
    _FROZEN = None
    baseline = REGISTRY.counter("jax.compilations").value
    REGISTRY.gauge("jax.warmup_compilations").set(baseline)
    REGISTRY.gauge("jax.warmup_marked").set(1.0)
    REGISTRY.gauge("jax.recompiles_after_warmup").set(0.0)
    return baseline


def freeze_recompiles() -> float:
    """Pin ``jax.recompiles_after_warmup`` at its current value.

    Called when the warmed steady-state loop *ends* (the trainer calls it
    on entering the test phase): later first-touch compiles — evaluation
    over a split the training loop never gathered from, export tracing —
    are expected new programs, not recompiles of the warmed loop, and
    must not trip the gate. Returns the pinned value; a later
    :func:`mark_warmup_complete` unfreezes."""
    global _FROZEN
    _FROZEN = _refresh_recompiles()
    return _FROZEN


def record_upload(nbytes: int, n: int = 1) -> None:
    """Account a host→device transfer done at an instrumentation site."""
    REGISTRY.counter("jax.upload_bytes").inc(nbytes)
    REGISTRY.counter("jax.uploads").inc(n)


def _refresh_recompiles() -> float:
    if _FROZEN is not None:
        return _FROZEN
    compiles = REGISTRY.counter("jax.compilations").value
    if REGISTRY.gauge("jax.warmup_marked").value:
        baseline = REGISTRY.gauge("jax.warmup_compilations").value
        recompiles = max(0.0, compiles - baseline)
    else:
        recompiles = 0.0
    REGISTRY.gauge("jax.recompiles_after_warmup").set(recompiles)
    return recompiles


def snapshot(steps: Optional[int] = None) -> dict:
    """Current telemetry as a plain dict (bench records, gate checks).

    ``steps`` adds the per-step upload rate when the caller knows how
    many hot-loop steps the counters cover.
    """
    recompiles = _refresh_recompiles()
    out = {
        "installed": _INSTALLED,
        "compilations": int(REGISTRY.counter("jax.compilations").value),
        "compile_ms": round(REGISTRY.counter("jax.compile_ms").value, 3),
        "recompiles_after_warmup": int(recompiles),
        "upload_bytes": int(REGISTRY.counter("jax.upload_bytes").value),
        "uploads": int(REGISTRY.counter("jax.uploads").value),
    }
    if steps:
        out["upload_bytes_per_step"] = round(out["upload_bytes"] / steps, 1)
    return out
