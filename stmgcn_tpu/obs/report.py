"""Load exported traces and summarize them per phase.

A "phase" is a span name; the summary answers *where a millisecond
went*: per-phase count / total / mean and share of the traced wall
window (max span end − min span start). Self-time is what the per-phase
shares are computed from — a parent span's duration minus its children's
— so nested spans (superstep ⊃ upload ⊃ device) don't double-count and
the shares of leaf phases can meaningfully sum toward 100%.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

__all__ = ["chrome_trace", "load_trace", "summarize", "render_table"]


def load_trace(path: str) -> Tuple[Optional[dict], List[dict]]:
    """Parse a JSONL trace file → (meta-or-None, spans). Lines that are
    not valid JSON objects raise — the schema contract is strict."""
    meta: Optional[dict] = None
    spans: List[dict] = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{line_no}: expected JSON object")
            if rec.get("kind") == "meta":
                meta = rec
            else:
                spans.append(rec)
    return meta, spans


def summarize(spans: List[dict]) -> dict:
    """Aggregate spans per phase name.

    Returns ``{"wall_ms", "coverage": <self-time sum / wall>, "phases":
    [{name, count, total_ms, self_ms, mean_ms, pct_wall}, ...]}`` with
    phases sorted by self-time descending. ``pct_wall`` is self-time
    over the wall window, so a fully-instrumented single-thread trace
    sums to ~100 without nested double counting.
    """
    if not spans:
        return {"wall_ms": 0.0, "coverage": 0.0, "phases": []}

    child_ms: Dict[int, float] = {}
    for s in spans:
        parent = s.get("parent", 0)
        if parent:
            child_ms[parent] = child_ms.get(parent, 0.0) + s["dur_ms"]

    t_lo = min(s["ts"] for s in spans)
    t_hi = max(s["ts"] + s["dur_ms"] for s in spans)
    wall_ms = max(t_hi - t_lo, 1e-9)

    phases: Dict[str, dict] = {}
    for s in spans:
        self_ms = max(0.0, s["dur_ms"] - child_ms.get(s.get("id", 0), 0.0))
        p = phases.setdefault(
            s["name"], {"name": s["name"], "count": 0, "total_ms": 0.0,
                        "self_ms": 0.0}
        )
        p["count"] += 1
        p["total_ms"] += s["dur_ms"]
        p["self_ms"] += self_ms

    rows = sorted(phases.values(), key=lambda p: -p["self_ms"])
    for p in rows:
        p["total_ms"] = round(p["total_ms"], 3)
        p["self_ms"] = round(p["self_ms"], 3)
        p["mean_ms"] = round(p["total_ms"] / p["count"], 3)
        p["pct_wall"] = round(100.0 * p["self_ms"] / wall_ms, 1)

    coverage = round(sum(p["self_ms"] for p in rows) / wall_ms, 4)
    return {"wall_ms": round(wall_ms, 3), "coverage": coverage,
            "phases": rows}


def chrome_trace(meta: Optional[dict], spans: List[dict]) -> dict:
    """Convert a span list to the Chrome trace-event JSON format
    (chrome://tracing / Perfetto "load legacy trace").

    Spans become complete ("X") duration events with microsecond
    timestamps. The trace format nests same-track events by time
    containment, so tracks must hold non-overlapping roots: root spans
    (``parent == 0``) are assigned greedily to the first track whose
    previous root already ended, concurrent roots (overlapping time
    ranges — e.g. the checkpoint writer thread under a superstep) open
    new tracks, and children inherit their root's track so each nested
    family renders as one flame.
    """
    by_id = {s.get("id", 0): s for s in spans}

    def root_of(s: dict) -> int:
        seen = set()
        while s.get("parent", 0) and s["parent"] in by_id:
            if s.get("id") in seen:  # defensive: cyclic parent links
                break
            seen.add(s.get("id"))
            s = by_id[s["parent"]]
        return s.get("id", 0)

    roots = sorted(
        (s for s in spans if not (s.get("parent", 0) in by_id)),
        key=lambda s: s["ts"],
    )
    track_end: List[float] = []  # per-track latest root end time
    root_tid: Dict[int, int] = {}
    for r in roots:
        for tid, end in enumerate(track_end):
            if r["ts"] >= end:
                break
        else:
            tid = len(track_end)
            track_end.append(0.0)
        track_end[tid] = r["ts"] + r["dur_ms"]
        root_tid[r.get("id", 0)] = tid

    events = []
    for s in spans:
        ev = {
            "name": s["name"],
            "ph": "X",
            "pid": 0,
            "tid": root_tid.get(root_of(s), 0),
            "ts": round(s["ts"] * 1e3, 1),       # chrome wants microseconds
            "dur": round(s["dur_ms"] * 1e3, 1),
        }
        if s.get("attrs"):
            ev["args"] = s["attrs"]
        events.append(ev)
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        out["otherData"] = {
            k: meta[k] for k in ("schema_version", "capacity", "dropped")
            if k in meta
        }
    return out


def render_table(summary: dict, meta: Optional[dict] = None) -> str:
    """Fixed-width per-phase table for terminals."""
    lines: List[str] = []
    if meta:
        lines.append(
            f"trace: {meta.get('spans', '?')} spans, "
            f"{meta.get('dropped', 0)} dropped "
            f"(ring capacity {meta.get('capacity', '?')}, "
            f"schema v{meta.get('schema_version', '?')})"
        )
    lines.append(
        f"wall window: {summary['wall_ms']:.1f} ms, "
        f"span coverage: {summary['coverage'] * 100:.1f}%"
    )
    header = (f"{'phase':<24} {'count':>7} {'total_ms':>12} "
              f"{'self_ms':>12} {'mean_ms':>10} {'%wall':>7}")
    lines.append(header)
    lines.append("-" * len(header))
    for p in summary["phases"]:
        lines.append(
            f"{p['name']:<24} {p['count']:>7} {p['total_ms']:>12.3f} "
            f"{p['self_ms']:>12.3f} {p['mean_ms']:>10.3f} "
            f"{p['pct_wall']:>7.1f}"
        )
    if not summary["phases"]:
        lines.append("(no spans)")
    return "\n".join(lines)
