"""Multi-layer LSTM as a ``lax.scan`` recurrence.

TPU-native counterpart of the reference's cuDNN-fused ``nn.LSTM``
(``/root/reference/STMGCN.py:21,48``). Designed for XLA rather than
translated:

- the input projection ``x @ Wx + b`` for *all* timesteps is hoisted out of
  the recurrence into one large batched matmul (MXU-friendly — at the
  model's operating point the folded batch is ``B*N`` rows, e.g. 1856 for
  the reference config, SURVEY.md §3.2), leaving only the ``h @ Wh``
  recurrent matmul inside the scan;
- the time loop is a ``lax.scan`` (compiler-friendly, no Python unrolling);
- ``unroll > 1`` asks the scan to unroll that many steps per iteration —
  XLA can then fuse the elementwise gate math across consecutive steps
  (the recurrent matmul chain stays serial either way);
- ``fused_scan=True`` runs ALL layers inside ONE scan over time (the
  shape cuDNN's fused kernel takes): intermediate layers' ``(B, T, H)``
  hidden sequences are never materialized to HBM — only the top layer's
  output sequence is — at the cost of moving layers 1+'s input
  projections inside the step. Numerically identical to the layered path
  (same parameters, same math; equality-tested);
- ``remat=True`` wraps the scan body in ``jax.checkpoint`` so long-horizon
  configs (BASELINE config 5, 24-step) trade recompute for activation
  memory.

Gate math matches torch's LSTM cell definition (i, f, g, o ordering;
sigmoid/tanh) so state semantics are comparable. Parameters use torch's
``U(-1/sqrt(H), 1/sqrt(H))`` init; the two bias vectors torch carries
(``b_ih``, ``b_hh``) are a single fused ``b`` here — identical function
class, one fewer add per step.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = ["StackedLSTM"]


def _uniform_init(scale: float):
    def init(key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

    return init


class StackedLSTM(nn.Module):
    """``num_layers`` stacked LSTMs over a ``(B, T, F)`` sequence.

    Returns ``(outputs, final_states)`` where ``outputs`` is the top layer's
    ``(B, T, H)`` hidden sequence and ``final_states`` is a list of
    ``(h, c)`` pairs per layer. Hidden state starts at zero each call unless
    ``initial_states`` is given (zero-init per forward is the reference's
    behavior, ``STMGCN.py:53-57``).
    """

    hidden_dim: int
    num_layers: int = 1
    #: rematerialize scan steps in the backward pass (XLA schedules). The
    #: pallas backend is *always* rematerializing — its backward kernel
    #: recomputes all gate activations from the saved (h, c) sequences
    #: rather than storing them (ops/pallas_lstm.py) — so ``remat`` is
    #: satisfied by construction there and the flag changes nothing.
    remat: bool = False
    #: scan steps unrolled per iteration (1 = plain scan; 0 = unroll the
    #: whole sequence — the fastest schedule measured on TPU v5e at the
    #: bench operating point, where loop bookkeeping dominates the tiny
    #: per-step recurrent matmul)
    unroll: int = 1
    #: run all layers inside one scan over time (see module docstring)
    fused_scan: bool = False
    #: pack layers >= 1's two per-step matmuls into one K=2H contraction
    #: inside the fused scan (fills the MXU's 128-lane K axis at H=64).
    #: None = pack on TPU only (measured 4% slower on XLA:CPU, where the
    #: per-step operand concat costs more than the split matmuls save);
    #: True/False forces either form — numerics are equal either way, and
    #: the forced-True form is equality-tested on CPU so the TPU-default
    #: path is never dead code under the CPU test suite
    fused_pack: Optional[bool] = None
    #: "xla" runs the scan paths above; "pallas" runs the whole T x L
    #: recurrence as one hand-written TPU kernel pair with VMEM-resident
    #: states and a recomputing backward (ops/pallas_lstm.py). Same
    #: parameters, same math (equality-tested); explicit initial states
    #: fall back to the scan path.
    backend: str = "xla"
    #: with ``backend="pallas"`` on a >1-device mesh: the Mesh to launch
    #: per-shard kernels over (rows sharded on ``pallas_row_axes``, weight
    #: grads psummed — ops/pallas_lstm.py:sharded_fused_lstm). ``None``
    #: launches one global kernel and lets GSPMD place it (single-device
    #: semantics).
    pallas_mesh: Any = None
    pallas_row_axes: tuple = ("dp", "region")
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    def _layer_params(self, layer: int, in_dim: int):
        h_dim = self.hidden_dim
        scale = 1.0 / math.sqrt(h_dim)
        wx = self.param(
            f"wx_{layer}", _uniform_init(scale), (in_dim, 4 * h_dim), self.param_dtype
        )
        wh = self.param(
            f"wh_{layer}", _uniform_init(scale), (h_dim, 4 * h_dim), self.param_dtype
        )
        b = self.param(f"b_{layer}", _uniform_init(scale), (4 * h_dim,), self.param_dtype)
        return wx, wh, b

    @staticmethod
    def _cell(gates, c):
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, c

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        initial_states: Optional[list] = None,
    ) -> tuple[jnp.ndarray, list]:
        if self.backend not in ("xla", "pallas"):
            raise ValueError(f"backend must be xla|pallas, got {self.backend!r}")
        if self.backend == "pallas" and (self.fused_scan or self.unroll != 1):
            # These knobs schedule the XLA scan; silently running the
            # kernel (or, with initial states, the *fused* scan) under
            # them would measure something other than what was configured.
            raise ValueError(
                "fused_scan/unroll are XLA scan schedule knobs and do not "
                "apply to backend='pallas' (the kernel has one schedule); "
                "remat is inherent to the kernel's recomputing backward"
            )
        if self.backend == "pallas" and initial_states is None:
            return self._pallas(x)
        if self.fused_scan:
            return self._fused(x, initial_states)
        batch = x.shape[0]
        h_dim = self.hidden_dim
        final_states = []
        inputs = x
        for layer in range(self.num_layers):
            wx, wh, b = self._layer_params(layer, inputs.shape[-1])
            # wh is deliberately NOT promoted here: it stays master
            # (param) dtype in the scan closure and casts to the compute
            # dtype INSIDE the step body, so the cast's VJP converts each
            # step's cotangent to f32 before the backward scan
            # accumulates it — the recurrent weight-grad accumulator (a
            # backward scan carry) must be f32 under the precision policy
            inputs, wx, b = nn.dtypes.promote_dtype(inputs, wx, b, dtype=self.dtype)
            compute_dtype = wx.dtype

            # Hoisted input projection: one (B, T, 4H) matmul outside the
            # scan. f32 accumulation island (no-op on fp32): under a bf16
            # compute dtype the contraction runs bf16 x bf16 with f32
            # accumulators and x_proj — hence the (h, c) scan carries
            # seeded from its dtype below — stays f32.
            x_proj = jnp.matmul(inputs, wx, preferred_element_type=jnp.float32) + b

            if initial_states is not None:
                h0, c0 = initial_states[layer]
            else:
                h0 = jnp.zeros((batch, h_dim), x_proj.dtype)
                c0 = jnp.zeros((batch, h_dim), x_proj.dtype)

            def step(carry, xt, wh=wh, cdt=compute_dtype):
                h, c = carry
                # recurrent matmul in the compute dtype with f32
                # accumulation; the f32 carry only drops precision at the
                # MXU operand boundary, never in the gate/state arithmetic
                gates = xt + jnp.matmul(
                    h.astype(cdt), wh.astype(cdt),
                    preferred_element_type=jnp.float32,
                )
                h, c = self._cell(gates, c)
                return (h, c), h

            if self.remat:
                step = jax.checkpoint(step)

            (h_t, c_t), hs = jax.lax.scan(
                step,
                (h0, c0),
                x_proj.swapaxes(0, 1),
                unroll=self.unroll if self.unroll >= 1 else x_proj.shape[1],
            )
            inputs = hs.swapaxes(0, 1)  # (B, T, H)
            final_states.append((h_t, c_t))
        return inputs, final_states

    def _collect_params(self, x: jnp.ndarray):
        """All layers' ``(wx, wh, b)`` promoted with ``x`` to compute dtype."""
        params = []
        in_dim = x.shape[-1]
        for layer in range(self.num_layers):
            params.append(self._layer_params(layer, in_dim))
            in_dim = self.hidden_dim
        x, *flat = nn.dtypes.promote_dtype(
            x, *(p for lp in params for p in lp), dtype=self.dtype
        )
        return x, [tuple(flat[3 * i : 3 * i + 3]) for i in range(self.num_layers)]

    def _pallas(self, x: jnp.ndarray):
        """Hand-written fused kernel path (zero initial state only)."""
        from stmgcn_tpu.ops.pallas_lstm import fused_lstm, sharded_fused_lstm

        kernel = (
            sharded_fused_lstm(self.pallas_mesh, tuple(self.pallas_row_axes))
            if self.pallas_mesh is not None
            else fused_lstm
        )
        L, h_dim = self.num_layers, self.hidden_dim
        x, params = self._collect_params(x)
        wx0, _, b0 = params[0]
        x_proj0 = x @ wx0 + b0
        wh_stack = jnp.stack([p[1] for p in params])
        if L > 1:
            wx_stack = jnp.stack([params[layer][0] for layer in range(1, L)])
            b_stack = jnp.stack([params[layer][2] for layer in range(1, L)])
        else:  # never-read placeholder: the kernel operand can't be empty
            wx_stack = jnp.zeros((1, h_dim, 4 * h_dim), x_proj0.dtype)
            b_stack = jnp.zeros((1, 4 * h_dim), x_proj0.dtype)
        hs_top, h_fin, c_fin = kernel(x_proj0, wh_stack, wx_stack, b_stack)
        return hs_top, [(h_fin[layer], c_fin[layer]) for layer in range(L)]

    def _fused(self, x: jnp.ndarray, initial_states: Optional[list]):
        """All layers in one scan; only the top layer's sequence is kept."""
        batch = x.shape[0]
        h_dim = self.hidden_dim
        params = []
        in_dim = x.shape[-1]
        for layer in range(self.num_layers):
            params.append(self._layer_params(layer, in_dim))
            in_dim = h_dim
        # Only the activations promote to the compute dtype: every layer
        # weight consumed INSIDE the scan stays master (param) dtype in
        # the closure and casts at its in-step use site, so each step's
        # weight cotangent converts to f32 before the backward scan
        # accumulates it (same argument as the layered path's wh).
        (x,) = nn.dtypes.promote_dtype(x, dtype=self.dtype)
        cdt = x.dtype

        # Layer 0's input projection is still hoisted; deeper layers consume
        # the previous layer's fresh h inside the step. f32 accumulation
        # island as on the layered path: under bf16 compute the (h, c)
        # carries seeded from x_proj0's dtype stay f32.
        wx0, _, b0 = params[0]
        x_proj0 = (
            jnp.matmul(x, wx0.astype(cdt), preferred_element_type=jnp.float32)
            + b0
        )

        # Layers >= 1 cannot hoist their input projection (it consumes the
        # lower layer's fresh h), so their step does BOTH matmuls — packed
        # into one [inp, h] @ [[wx], [wh]] contraction (K = 2H) so the
        # MXU's 128-lane contraction axis is full at the flagship's H=64
        # where two K=H matmuls would each run it half-empty. Same trick
        # as the Pallas kernel (ops/pallas_lstm.py); weight concat happens
        # at trace time, once. TPU only: on XLA:CPU the per-step operand
        # concat costs more than the split matmuls save (measured 4%
        # slower at the canonical bench point), so other backends keep
        # the two-matmul form — numerics are equal either way (summation
        # order differs at ulp level; pinned by tests/test_lstm_variants).
        pack = (
            self.fused_pack
            if self.fused_pack is not None
            else jax.default_backend() == "tpu"
        )
        wxh = [
            jnp.concatenate([params[layer][0], params[layer][1]], axis=0)
            for layer in range(1, self.num_layers)
        ] if pack else None

        if initial_states is not None:
            states = tuple(tuple(s) for s in initial_states)
        else:
            zero = jnp.zeros((batch, h_dim), x_proj0.dtype)
            states = tuple((zero, zero) for _ in range(self.num_layers))

        def step(carry, xt0):
            new_states = []
            inp = None
            # Every per-step matmul casts BOTH operands (activation and
            # master-dtype weight) to the compute dtype in the body and
            # accumulates in f32, so gate and state arithmetic — and
            # therefore the scan carries, forward and backward — stay f32
            # under a bf16 compute dtype (no-op jaxpr-wise on fp32).
            # Biases stay f32 and add on the f32 accumulator side.
            for layer, (h, c) in enumerate(carry):
                if layer == 0:
                    gates = xt0 + jnp.matmul(
                        h.astype(cdt), params[0][1].astype(cdt),
                        preferred_element_type=jnp.float32,
                    )
                elif pack:
                    gates = (
                        jnp.matmul(
                            jnp.concatenate([inp, h], axis=-1).astype(cdt),
                            wxh[layer - 1].astype(cdt),
                            preferred_element_type=jnp.float32,
                        )
                        + params[layer][2]
                    )
                else:
                    wx, wh, b = params[layer]
                    gates = (
                        jnp.matmul(
                            inp.astype(cdt), wx.astype(cdt),
                            preferred_element_type=jnp.float32,
                        )
                        + b
                        + jnp.matmul(
                            h.astype(cdt), wh.astype(cdt),
                            preferred_element_type=jnp.float32,
                        )
                    )
                h, c = self._cell(gates, c)
                new_states.append((h, c))
                inp = h
            return tuple(new_states), inp  # top layer's h

        if self.remat:
            step = jax.checkpoint(step)

        final, hs_top = jax.lax.scan(
            step,
            states,
            x_proj0.swapaxes(0, 1),
            unroll=self.unroll if self.unroll >= 1 else x_proj0.shape[1],
        )
        return hs_top.swapaxes(0, 1), [tuple(s) for s in final]
