"""Multi-layer LSTM as a ``lax.scan`` recurrence.

TPU-native counterpart of the reference's cuDNN-fused ``nn.LSTM``
(``/root/reference/STMGCN.py:21,48``). Designed for XLA rather than
translated:

- the input projection ``x @ Wx + b`` for *all* timesteps is hoisted out of
  the recurrence into one large batched matmul (MXU-friendly — at the
  model's operating point the folded batch is ``B*N`` rows, e.g. 1856 for
  the reference config, SURVEY.md §3.2), leaving only the ``h @ Wh``
  recurrent matmul inside the scan;
- the time loop is a ``lax.scan`` (compiler-friendly, no Python unrolling);
- ``remat=True`` wraps the scan body in ``jax.checkpoint`` so long-horizon
  configs (BASELINE config 5, 24-step) trade recompute for activation
  memory.

Gate math matches torch's LSTM cell definition (i, f, g, o ordering;
sigmoid/tanh) so state semantics are comparable. Parameters use torch's
``U(-1/sqrt(H), 1/sqrt(H))`` init; the two bias vectors torch carries
(``b_ih``, ``b_hh``) are a single fused ``b`` here — identical function
class, one fewer add per step.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

__all__ = ["StackedLSTM"]


def _uniform_init(scale: float):
    def init(key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

    return init


class StackedLSTM(nn.Module):
    """``num_layers`` stacked LSTMs over a ``(B, T, F)`` sequence.

    Returns ``(outputs, final_states)`` where ``outputs`` is the top layer's
    ``(B, T, H)`` hidden sequence and ``final_states`` is a list of
    ``(h, c)`` pairs per layer. Hidden state starts at zero each call unless
    ``initial_states`` is given (zero-init per forward is the reference's
    behavior, ``STMGCN.py:53-57``).
    """

    hidden_dim: int
    num_layers: int = 1
    remat: bool = False
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        initial_states: Optional[list] = None,
    ) -> tuple[jnp.ndarray, list]:
        batch = x.shape[0]
        h_dim = self.hidden_dim
        scale = 1.0 / math.sqrt(h_dim)
        final_states = []
        inputs = x
        for layer in range(self.num_layers):
            in_dim = inputs.shape[-1]
            wx = self.param(
                f"wx_{layer}", _uniform_init(scale), (in_dim, 4 * h_dim), self.param_dtype
            )
            wh = self.param(
                f"wh_{layer}", _uniform_init(scale), (h_dim, 4 * h_dim), self.param_dtype
            )
            b = self.param(f"b_{layer}", _uniform_init(scale), (4 * h_dim,), self.param_dtype)
            inputs, wx, wh, b = nn.dtypes.promote_dtype(inputs, wx, wh, b, dtype=self.dtype)

            # Hoisted input projection: one (B, T, 4H) matmul outside the scan.
            x_proj = inputs @ wx + b

            if initial_states is not None:
                h0, c0 = initial_states[layer]
            else:
                h0 = jnp.zeros((batch, h_dim), x_proj.dtype)
                c0 = jnp.zeros((batch, h_dim), x_proj.dtype)

            def step(carry, xt, wh=wh):
                h, c = carry
                gates = xt + h @ wh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i = jax.nn.sigmoid(i)
                f = jax.nn.sigmoid(f)
                g = jnp.tanh(g)
                o = jax.nn.sigmoid(o)
                c = f * c + i * g
                h = o * jnp.tanh(c)
                return (h, c), h

            if self.remat:
                step = jax.checkpoint(step)

            (h_t, c_t), hs = jax.lax.scan(step, (h0, c0), x_proj.swapaxes(0, 1))
            inputs = hs.swapaxes(0, 1)  # (B, T, H)
            final_states.append((h_t, c_t))
        return inputs, final_states
