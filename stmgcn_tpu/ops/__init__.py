"""Numeric ops: graph-support builders, graph convolution, recurrence, kernels."""

from stmgcn_tpu.ops.chebconv import (
    ChebGraphConv,
    SparseChebGraphConv,
    TiledChebGraphConv,
)
from stmgcn_tpu.ops.graph import (
    SupportConfig,
    build_supports,
    chebyshev_polynomials,
    chebyshev_supports,
    diffusion_supports,
    localpool_supports,
    max_eigenvalue,
    normalized_laplacian,
    random_walk_normalize,
    rescale_laplacian,
    support_count,
    symmetric_normalize,
)
from stmgcn_tpu.ops.lstm import StackedLSTM
from stmgcn_tpu.ops.tiling import TiledSupports, plan_tiling

__all__ = [
    "ChebGraphConv",
    "SparseChebGraphConv",
    "StackedLSTM",
    "SupportConfig",
    "TiledChebGraphConv",
    "TiledSupports",
    "plan_tiling",
    "build_supports",
    "chebyshev_polynomials",
    "chebyshev_supports",
    "diffusion_supports",
    "localpool_supports",
    "max_eigenvalue",
    "normalized_laplacian",
    "random_walk_normalize",
    "rescale_laplacian",
    "support_count",
    "symmetric_normalize",
]
