"""K-support graph convolution as one fused contraction.

TPU-native counterpart of the reference's dense ``GCN`` module
(``/root/reference/GCN.py:7-46``): where the reference runs a Python loop of
K separate ``einsum('ij,bjp->bip')`` calls and concatenates
(``GCN.py:33-37``), this layer evaluates all K support propagations in a
single ``einsum('kij,bjf->bikf')`` — one batched contraction XLA tiles onto
the MXU — followed by the shared ``(K*F_in, F_out)`` projection.

Parameter layout parity: the weight is a single ``(K*F_in, F_out)`` matrix
(``GCN.py:18``) and the reshape of the ``(B, N, K, F)`` propagated tensor is
k-major, matching ``torch.cat(support_list, dim=-1)`` ordering exactly, so
reference-trained weights map 1:1. Xavier-normal weight init and zero bias
(``GCN.py:17-22``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

__all__ = [
    "BandedChebGraphConv",
    "ChebGraphConv",
    "SparseChebGraphConv",
    "TiledChebGraphConv",
    "accum_dot_general",
    "conv_cls",
    "make_conv",
]


def accum_dot_general(dtype):
    """A ``dot_general`` for ``nn.Dense(dot_general=...)`` pinning f32 MXU
    accumulation under a sub-f32 compute dtype.

    Returns ``None`` (flax's default contraction) when ``dtype`` is
    ``None`` or already >= 32-bit, so fp32 programs keep their exact
    pre-mixed-precision jaxprs; for bf16 the returned contraction runs
    ``bf16 x bf16`` with ``preferred_element_type=f32`` and returns the
    f32 accumulator as-is. Keeping the Dense output f32 means the bias
    add (and any elementwise tail) runs in f32 too — so the *backward*
    bias reduction is an f32 ``reduce_sum``, which the precision lint
    requires. The next matmul's operand cast re-narrows to bf16.
    """
    # static (construction-time) dtype metadata, not a traced value
    if dtype is None or np.dtype(dtype).itemsize >= 4:
        return None

    def _dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
        return jax.lax.dot_general(
            lhs, rhs, dimension_numbers, precision=precision,
            preferred_element_type=jnp.float32,
        )

    return _dot_general


def conv_cls(mode):
    """The graph-conv class for a support representation (one mapping, shared
    by every call site that dispatches on support mode). ``mode`` is
    ``"dense" | "sparse" | "banded" | "tiled"`` (bools accepted for
    back-compat: ``True`` = sparse, ``False`` = dense)."""
    if isinstance(mode, bool):
        mode = "sparse" if mode else "dense"
    classes = {
        "dense": ChebGraphConv,
        "sparse": SparseChebGraphConv,
        "banded": BandedChebGraphConv,
        "tiled": TiledChebGraphConv,
    }
    if mode not in classes:
        raise ValueError(f"support mode must be one of {sorted(classes)}, got {mode!r}")
    return classes[mode]


def make_conv(mode, shard_spec=None, **kwargs):
    """Construct the graph conv for ``mode``; threads the static
    :class:`~stmgcn_tpu.parallel.banded.ShardSpec` only where needed
    (required for banded, optional for sparse — only its mesh-sharded
    support form uses it)."""
    cls = conv_cls(mode)
    if cls is BandedChebGraphConv:
        if shard_spec is None:
            raise ValueError("banded support mode needs a ShardSpec (mesh + axis)")
        kwargs["spec"] = shard_spec
    elif cls is SparseChebGraphConv:
        kwargs["spec"] = shard_spec
    return cls(**kwargs)


def _conv_params(mod, f_in: int):
    """The shared ``(K*F_in, F_out)`` weight + bias (``GCN.py:17-22`` layout)."""
    w = mod.param(
        "W",
        nn.initializers.xavier_normal(),
        (mod.n_supports * f_in, mod.features),
        mod.param_dtype,
    )
    b = (
        mod.param("b", nn.initializers.zeros_init(), (mod.features,), mod.param_dtype)
        if mod.use_bias
        else None
    )
    return w, b


def _project(stacked, w, b, activation):
    """Shared projection/bias/activation tail of both conv variants.

    The matmul accumulates f32 regardless of the compute dtype
    (``preferred_element_type``) and bias/activation ride the f32
    accumulator before one downcast at the end — a no-op chain on the
    fp32 path (jaxpr-identical), the mandatory accumulation island on
    bf16. Adding the bias on the f32 side matters for the *backward*
    pass: the bias gradient is a ``reduce_sum`` of the add's cotangent,
    which this ordering keeps f32 (the precision lint forbids bf16
    reduction accumulators).
    """
    out = jnp.matmul(stacked, w, preferred_element_type=jnp.float32)
    if b is not None:
        out = out + b
    if activation is not None:
        out = activation(out)
    return out.astype(stacked.dtype)


class ChebGraphConv(nn.Module):
    """Graph convolution over a stack of K dense support matrices.

    Call with ``supports`` of shape ``(K, N, N)`` and a signal ``x`` of
    shape ``(B, N, F_in)``; returns ``(B, N, features)``.
    """

    n_supports: int
    features: int
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, supports: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
        if supports.shape[0] != self.n_supports:  # GCN.py:31
            raise ValueError(
                f"expected {self.n_supports} supports, got {supports.shape[0]}"
            )
        batch, n_nodes, f_in = x.shape
        w, b = _conv_params(self, f_in)
        supports, x, w, b = nn.dtypes.promote_dtype(supports, x, w, b, dtype=self.dtype)

        # All K propagations at once; k-major flatten == torch.cat order.
        # f32 accumulation island: bf16 operands contract with f32
        # accumulators (fp32 path: jaxpr-identical no-ops).
        propagated = jnp.einsum(
            "kij,bjf->bikf", supports, x, preferred_element_type=jnp.float32
        ).astype(x.dtype)
        stacked = propagated.reshape(batch, n_nodes, self.n_supports * f_in)
        return _project(stacked, w, b, self.activation)


class SparseChebGraphConv(nn.Module):
    """Graph convolution over K block-sparse supports (Pallas SpMM path).

    Same parameters and math as :class:`ChebGraphConv` (identical param
    names/shapes, so trained weights are interchangeable), but the K
    support propagations run through the block-CSR Pallas kernels in
    :mod:`stmgcn_tpu.ops.spmm` instead of a dense einsum — the memory/FLOP
    win for the large-N configs where dense ``(K, N, N)`` supports are
    mostly zeros.

    Accepted support forms:

    - :class:`~stmgcn_tpu.ops.spmm.BlockSparseStack` — all K propagations
      in ONE fused kernel launch (preferred single-device path);
    - :class:`~stmgcn_tpu.parallel.sparse.ShardedBlockSparse` — per-shard
      row strips over a region mesh (requires ``spec``: the mesh/axis);
    - a K-tuple of :class:`~stmgcn_tpu.ops.spmm.BlockSparse` — legacy
      one-launch-per-support loop.
    """

    n_supports: int
    features: int
    spec: Any = None  # ShardSpec; only needed for ShardedBlockSparse supports
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, supports, x: jnp.ndarray) -> jnp.ndarray:
        from stmgcn_tpu.ops.spmm import BlockSparseStack, spmm, spmm_stack
        from stmgcn_tpu.parallel.sparse import ShardedBlockSparse, sharded_spmm_apply

        k = (
            supports.n_supports
            if isinstance(supports, (BlockSparseStack, ShardedBlockSparse))
            else len(supports)
        )
        if k != self.n_supports:
            raise ValueError(f"expected {self.n_supports} supports, got {k}")
        batch, n_nodes, f_in = x.shape
        w, b = _conv_params(self, f_in)
        x, w, b = nn.dtypes.promote_dtype(x, w, b, dtype=self.dtype)

        if isinstance(supports, ShardedBlockSparse):
            if self.spec is None:
                raise ValueError(
                    "ShardedBlockSparse supports need a ShardSpec (mesh + axis)"
                )
            propagated = sharded_spmm_apply(
                self.spec.mesh, supports, x, self.spec.axis_name
            ).astype(x.dtype)  # (K, B, N, F)
            stacked = propagated.transpose(1, 2, 0, 3).reshape(
                batch, n_nodes, self.n_supports * f_in
            )
            return _project(stacked, w, b, self.activation)

        # (B, N, F) -> (N, B*F): propagate all batch/features per support
        x_mat = x.transpose(1, 0, 2).reshape(n_nodes, batch * f_in)
        if isinstance(supports, BlockSparseStack):
            if supports.data.dtype != x.dtype:
                # sub-f32 compute: block values join the signal's dtype so
                # the kernel's tile matmuls run bf16 x bf16 (its accumulators
                # and out_shape stay f32 — the island is inside the kernel)
                supports = dataclasses.replace(
                    supports,
                    data=supports.data.astype(x.dtype),
                    data_t=supports.data_t.astype(x.dtype),
                )
            propagated = spmm_stack(supports, x_mat).astype(x.dtype)  # one launch
        else:
            if supports and supports[0].data.dtype != x.dtype:
                supports = [
                    dataclasses.replace(
                        bs,
                        data=bs.data.astype(x.dtype),
                        data_t=bs.data_t.astype(x.dtype),
                    )
                    for bs in supports
                ]
            # kernel accumulates fp32; cast back to the compute dtype
            propagated = jnp.stack(
                [spmm(bs, x_mat).astype(x.dtype) for bs in supports], axis=0
            )
        # (K, N, B*F) -> (B, N, K*F), k-major to match the dense layout
        stacked = (
            propagated.reshape(self.n_supports, n_nodes, batch, f_in)
            .transpose(2, 1, 0, 3)
            .reshape(batch, n_nodes, self.n_supports * f_in)
        )
        return _project(stacked, w, b, self.activation)


class BandedChebGraphConv(nn.Module):
    """Graph convolution over region-sharded banded support strips.

    Same parameters and math as :class:`ChebGraphConv` (identical param
    names/shapes — trained weights are interchangeable), but the K support
    propagations run through the explicit halo-exchange plan
    (:func:`stmgcn_tpu.parallel.banded.sharded_banded_apply`): each region
    shard contracts only its strip of the supports and ``ppermute``s
    ``halo`` boundary rows with its ring neighbors, instead of the
    full-node all-gather GSPMD inserts for a dense region-sharded support
    (the contraction the reference loops at ``GCN.py:34-36``).

    Call with a :class:`~stmgcn_tpu.parallel.banded.BandedSupports` and a
    signal ``x`` of shape ``(B, N, F_in)``; ``spec`` carries the mesh and
    region-axis name (static).
    """

    n_supports: int
    features: int
    spec: Any = None  # ShardSpec (mesh + axis_name); static module attr
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, supports, x: jnp.ndarray) -> jnp.ndarray:
        from stmgcn_tpu.parallel.banded import sharded_banded_apply

        if supports.n_supports != self.n_supports:
            raise ValueError(
                f"expected {self.n_supports} supports, got {supports.n_supports}"
            )
        batch, n_nodes, f_in = x.shape
        if n_nodes != supports.n:
            raise ValueError(f"x has {n_nodes} nodes, strips expect {supports.n}")
        w, b = _conv_params(self, f_in)
        x, w, b = nn.dtypes.promote_dtype(x, w, b, dtype=self.dtype)
        propagated = sharded_banded_apply(
            self.spec.mesh, supports.strips, x, supports.halo, self.spec.axis_name
        ).astype(x.dtype)  # strips are fp32; come back to the compute dtype
        # (K, B, N, F) -> (B, N, K*F), k-major to match the dense layout
        stacked = propagated.transpose(1, 2, 0, 3).reshape(
            batch, n_nodes, self.n_supports * f_in
        )
        return _project(stacked, w, b, self.activation)


class TiledChebGraphConv(nn.Module):
    """Graph convolution over reorder/condensed tiled-sparse supports.

    Same parameters and math as :class:`ChebGraphConv` (identical param
    names/shapes — trained weights are interchangeable), consuming one
    branch of an offline :class:`~stmgcn_tpu.ops.tiling.TiledSupports`
    plan (:class:`~stmgcn_tpu.ops.tiling.TiledBranchSupports`). The
    signal is permuted INTO the plan's bandwidth-reduced node order once
    at the boundary, all K propagations run over kept ``(tile, tile)``
    blocks only, and the projected output permutes back out — the
    permutation never touches the contraction itself.

    Two numerically-matching block paths, selected by ``backend``:

    - ``"xla"`` — gathered-tiles: ``jnp.take`` of signal row blocks by
      the block-column index lists + one batched tile matmul with f32
      accumulation (:func:`~stmgcn_tpu.ops.tiling.gathered_tiles_apply`).
      Runs (and is measurable) anywhere, including the CPU host.
    - ``"pallas"`` — the fused block-CSR ``spmm_stack`` kernel from
      :mod:`stmgcn_tpu.ops.spmm`, reused verbatim through
      :meth:`~stmgcn_tpu.ops.tiling.TiledBranchSupports.as_stack`.
    - ``"auto"`` (default) — pallas on a real TPU, xla elsewhere.
    """

    n_supports: int
    features: int
    backend: str = "auto"
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, supports, x: jnp.ndarray) -> jnp.ndarray:
        import jax

        from stmgcn_tpu.ops.spmm import spmm_stack
        from stmgcn_tpu.ops.tiling import TiledBranchSupports, gathered_tiles_apply

        if not isinstance(supports, TiledBranchSupports):
            raise TypeError(
                "tiled mode consumes TiledBranchSupports (one branch of a "
                f"plan_tiling artifact), got {type(supports).__name__}"
            )
        if supports.n_supports != self.n_supports:
            raise ValueError(
                f"expected {self.n_supports} supports, got {supports.n_supports}"
            )
        backend = self.backend
        if backend == "auto":
            backend = "pallas" if jax.default_backend() == "tpu" else "xla"
        if backend not in ("xla", "pallas"):
            raise ValueError(
                f"backend must be auto|xla|pallas, got {self.backend!r}"
            )
        batch, n_nodes, f_in = x.shape
        if n_nodes != supports.n:
            raise ValueError(f"x has {n_nodes} nodes, plan expects {supports.n}")
        w, b = _conv_params(self, f_in)
        x, w, b = nn.dtypes.promote_dtype(x, w, b, dtype=self.dtype)
        if supports.data.dtype != x.dtype:
            # sub-f32 compute: tile values join the signal's dtype so the
            # block contractions (gathered-tiles einsum / Pallas kernel)
            # run bf16 x bf16 against their f32 accumulators
            supports = dataclasses.replace(
                supports,
                data=supports.data.astype(x.dtype),
                data_t=supports.data_t.astype(x.dtype),
            )

        # (B, N, F) -> (N, B*F), then ONE permute into the plan's order
        x_mat = x.transpose(1, 0, 2).reshape(n_nodes, batch * f_in)
        x_mat = jnp.take(x_mat, supports.perm, axis=0)
        if backend == "pallas":
            propagated = spmm_stack(supports.as_stack(), x_mat)
        else:
            propagated = gathered_tiles_apply(supports, x_mat)
        propagated = propagated.astype(x.dtype)  # f32 accumulate -> compute dtype
        # (K, N, B*F) -> (B, N, K*F), k-major to match the dense layout
        stacked = (
            propagated.reshape(self.n_supports, n_nodes, batch, f_in)
            .transpose(2, 1, 0, 3)
            .reshape(batch, n_nodes, self.n_supports * f_in)
        )
        out = _project(stacked, w, b, self.activation)
        # permute the node axis back out AFTER the (node-wise) projection
        return jnp.take(out, supports.inv, axis=1)
