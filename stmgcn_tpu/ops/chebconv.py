"""K-support graph convolution as one fused contraction.

TPU-native counterpart of the reference's dense ``GCN`` module
(``/root/reference/GCN.py:7-46``): where the reference runs a Python loop of
K separate ``einsum('ij,bjp->bip')`` calls and concatenates
(``GCN.py:33-37``), this layer evaluates all K support propagations in a
single ``einsum('kij,bjf->bikf')`` — one batched contraction XLA tiles onto
the MXU — followed by the shared ``(K*F_in, F_out)`` projection.

Parameter layout parity: the weight is a single ``(K*F_in, F_out)`` matrix
(``GCN.py:18``) and the reshape of the ``(B, N, K, F)`` propagated tensor is
k-major, matching ``torch.cat(support_list, dim=-1)`` ordering exactly, so
reference-trained weights map 1:1. Xavier-normal weight init and zero bias
(``GCN.py:17-22``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
from flax import linen as nn

__all__ = ["ChebGraphConv"]


class ChebGraphConv(nn.Module):
    """Graph convolution over a stack of K dense support matrices.

    Call with ``supports`` of shape ``(K, N, N)`` and a signal ``x`` of
    shape ``(B, N, F_in)``; returns ``(B, N, features)``.
    """

    n_supports: int
    features: int
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, supports: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
        if supports.shape[0] != self.n_supports:  # GCN.py:31
            raise ValueError(
                f"expected {self.n_supports} supports, got {supports.shape[0]}"
            )
        batch, n_nodes, f_in = x.shape
        w = self.param(
            "W",
            nn.initializers.xavier_normal(),
            (self.n_supports * f_in, self.features),
            self.param_dtype,
        )
        b = (
            self.param("b", nn.initializers.zeros_init(), (self.features,), self.param_dtype)
            if self.use_bias
            else None
        )
        supports, x, w, b = nn.dtypes.promote_dtype(supports, x, w, b, dtype=self.dtype)

        # All K propagations at once; k-major flatten == torch.cat order.
        propagated = jnp.einsum("kij,bjf->bikf", supports, x)
        stacked = propagated.reshape(batch, n_nodes, self.n_supports * f_in)
        out = stacked @ w
        if b is not None:
            out = out + b
        if self.activation is not None:
            out = self.activation(out)
        return out
