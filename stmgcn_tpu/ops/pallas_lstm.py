"""Fused multi-layer LSTM recurrence as a single Pallas TPU kernel pair.

The shared LSTM is ~93% of the flagship's step FLOPs (BASELINE.md), so it
is the one op worth a hand kernel. TPU-native counterpart of the engine
kernel the reference gets from cuDNN (``/root/reference/STMGCN.py:21,48``
— ``nn.LSTM``'s fused implementation), built the Pallas way rather than
translated:

- the layer-0 input projection for all T steps stays **outside** the
  kernel as one large XLA matmul (MXU-shaped, batched over ``R*T`` rows —
  same hoisting as the scan path, ``ops/lstm.py``);
- one **forward kernel** runs the entire ``T x L`` recurrence for a block
  of rows with every hidden/cell state living in VMEM — no HBM round
  trips between steps or layers (grid over row blocks; T and L are
  static, so the step/layer loops fully unroll into straight-line code);
- one **backward kernel** runs the reverse sweep, *recomputing* gate
  pre-activations from the saved per-step ``h``/``c`` sequences instead
  of storing ``(L, R, T, 4H)`` gate tensors — recompute is MXU-cheap,
  HBM traffic is the scarce resource (the same trade ``jax.checkpoint``
  makes, chosen once here and hand-scheduled);
- weight gradients accumulate across row blocks in revisited output
  blocks (TPU grids execute sequentially, so ``+=`` into a
  constant-index block is race-free).

Zero initial state per call is the reference's semantics
(``STMGCN.py:53-57``); callers that pass explicit initial states use the
scan path instead. Numerics: the kernel computes in float32 regardless of
the storage dtype (``preferred_element_type``), so bf16 inputs get f32
cell arithmetic — at least as accurate as the XLA bf16 scan path it
replaces; equality with the scan path is pinned by
``tests/test_pallas_lstm.py`` in both dtypes, gradients included.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_lstm", "pallas_lstm_available"]

#: rows per grid step — sized so fwd residuals + bwd temporaries of a
#: block stay well inside ~16 MB/core VMEM with pipelining headroom
_BLOCK_R = 128


def pallas_lstm_available() -> bool:
    """True when the current default backend can run the kernel natively."""
    return jax.default_backend() == "tpu"


def _cell_acts(gates_pre):
    """(i, f, g, o) activations from pre-activation gates, f32."""
    i_pre, f_pre, g_pre, o_pre = jnp.split(gates_pre, 4, axis=-1)
    return (
        jax.nn.sigmoid(i_pre),
        jax.nn.sigmoid(f_pre),
        jnp.tanh(g_pre),
        jax.nn.sigmoid(o_pre),
    )


def _fwd_kernel(T, L, xp_ref, wh_ref, wx_ref, b_ref, out_ref, hseq_ref, cseq_ref):
    """Whole T x L recurrence for one row block; states never leave VMEM."""
    br = xp_ref.shape[0]
    h_dim = wh_ref.shape[1]
    f32 = jnp.float32
    h = [jnp.zeros((br, h_dim), f32) for _ in range(L)]
    c = [jnp.zeros((br, h_dim), f32) for _ in range(L)]
    for t in range(T):
        for layer in range(L):
            if layer == 0:
                pre = xp_ref[:, t, :].astype(f32)
            else:
                pre = (
                    jnp.dot(
                        h[layer - 1],
                        wx_ref[layer - 1].astype(f32),
                        preferred_element_type=f32,
                    )
                    + b_ref[layer - 1].astype(f32)
                )
            pre = pre + jnp.dot(
                h[layer], wh_ref[layer].astype(f32), preferred_element_type=f32
            )
            i, f, g, o = _cell_acts(pre)
            c[layer] = f * c[layer] + i * g
            h[layer] = o * jnp.tanh(c[layer])
            hseq_ref[layer, :, t, :] = h[layer].astype(hseq_ref.dtype)
            cseq_ref[layer, :, t, :] = c[layer].astype(cseq_ref.dtype)
        out_ref[:, t, :] = h[L - 1].astype(out_ref.dtype)


def _bwd_kernel(
    T,
    L,
    xp_ref,
    wh_ref,
    wx_ref,
    b_ref,
    hseq_ref,
    cseq_ref,
    gout_ref,
    ghfin_ref,
    gcfin_ref,
    dxp_ref,
    dwh_ref,
    dwx_ref,
    db_ref,
):
    """Reverse sweep for one row block; gate pre-activations recomputed."""
    br = xp_ref.shape[0]
    f32 = jnp.float32

    @pl.when(pl.program_id(0) == 0)
    def _zero_weight_grads():
        dwh_ref[...] = jnp.zeros_like(dwh_ref)
        dwx_ref[...] = jnp.zeros_like(dwx_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dh = [ghfin_ref[layer].astype(f32) for layer in range(L)]
    dc = [gcfin_ref[layer].astype(f32) for layer in range(L)]
    zeros = jnp.zeros((br, wh_ref.shape[1]), f32)
    for t in reversed(range(T)):
        dh[L - 1] = dh[L - 1] + gout_ref[:, t, :].astype(f32)
        for layer in reversed(range(L)):
            h_prev = hseq_ref[layer, :, t - 1, :].astype(f32) if t > 0 else zeros
            c_prev = cseq_ref[layer, :, t - 1, :].astype(f32) if t > 0 else zeros
            c_t = cseq_ref[layer, :, t, :].astype(f32)
            # recompute this step's pre-activations (cheaper than storing)
            if layer == 0:
                pre = xp_ref[:, t, :].astype(f32)
            else:
                below = hseq_ref[layer - 1, :, t, :].astype(f32)
                pre = (
                    jnp.dot(
                        below, wx_ref[layer - 1].astype(f32), preferred_element_type=f32
                    )
                    + b_ref[layer - 1].astype(f32)
                )
            pre = pre + jnp.dot(
                h_prev, wh_ref[layer].astype(f32), preferred_element_type=f32
            )
            i, f, g, o = _cell_acts(pre)
            tc = jnp.tanh(c_t)

            do = dh[layer] * tc
            dct = dc[layer] + dh[layer] * o * (1.0 - tc * tc)
            dgates = jnp.concatenate(
                [
                    dct * g * i * (1.0 - i),  # d i_pre
                    dct * c_prev * f * (1.0 - f),  # d f_pre
                    dct * i * (1.0 - g * g),  # d g_pre
                    do * o * (1.0 - o),  # d o_pre
                ],
                axis=-1,
            )
            dh[layer] = jnp.dot(
                dgates, wh_ref[layer].astype(f32).T, preferred_element_type=f32
            )
            dc[layer] = dct * f
            dwh_ref[layer] += jnp.dot(
                h_prev.T, dgates, preferred_element_type=f32
            ).astype(dwh_ref.dtype)
            if layer == 0:
                dxp_ref[:, t, :] = dgates.astype(dxp_ref.dtype)
            else:
                dh[layer - 1] = dh[layer - 1] + jnp.dot(
                    dgates, wx_ref[layer - 1].astype(f32).T, preferred_element_type=f32
                )
                dwx_ref[layer - 1] += jnp.dot(
                    below.T, dgates, preferred_element_type=f32
                ).astype(dwx_ref.dtype)
                db_ref[layer - 1] += jnp.sum(dgates, axis=0).astype(db_ref.dtype)


def _pad_rows(arr, block):
    r = arr.shape[0]
    pad = (-r) % block
    if pad:
        widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
        arr = jnp.pad(arr, widths)
    return arr, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_lstm(x_proj0, wh_stack, wx_stack, b_stack):
    """Run the fused recurrence; returns ``(hs_top, h_fin, c_fin)``.

    Args:
      x_proj0: ``(R, T, 4H)`` — layer 0's hoisted input projection
        (``x @ wx_0 + b_0``), any float dtype.
      wh_stack: ``(L, H, 4H)`` recurrent weights, all layers.
      wx_stack: ``(max(L-1, 1), H, 4H)`` input weights of layers >= 1
        (ignored garbage row allowed when L == 1 so the operand is never
        zero-sized).
      b_stack: ``(max(L-1, 1), 4H)`` biases of layers >= 1.

    Returns ``hs_top`` ``(R, T, H)`` (top layer's hidden sequence) plus
    per-layer final states ``(L, R, H)`` each, matching
    ``ops.lstm.StackedLSTM``'s return contract.
    """
    out, _ = _fused_fwd(x_proj0, wh_stack, wx_stack, b_stack)
    return out


def _run_fwd(x_proj0, wh_stack, wx_stack, b_stack):
    R, T, four_h = x_proj0.shape
    L, h_dim, _ = wh_stack.shape
    dtype = x_proj0.dtype
    xp, pad = _pad_rows(x_proj0, _BLOCK_R)
    rp = xp.shape[0]
    grid = (rp // _BLOCK_R,)
    kernel = functools.partial(_fwd_kernel, T, L)
    out, hseq, cseq = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_R, T, four_h), lambda i: (i, 0, 0)),
            pl.BlockSpec((L, h_dim, four_h), lambda i: (0, 0, 0)),
            pl.BlockSpec(wx_stack.shape, lambda i: (0, 0, 0)),
            pl.BlockSpec(b_stack.shape, lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_R, T, h_dim), lambda i: (i, 0, 0)),
            pl.BlockSpec((L, _BLOCK_R, T, h_dim), lambda i: (0, i, 0, 0)),
            pl.BlockSpec((L, _BLOCK_R, T, h_dim), lambda i: (0, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, T, h_dim), dtype),
            jax.ShapeDtypeStruct((L, rp, T, h_dim), dtype),
            jax.ShapeDtypeStruct((L, rp, T, h_dim), dtype),
        ],
        interpret=not pallas_lstm_available(),
    )(xp, wh_stack, wx_stack, b_stack)
    return out, hseq, cseq, pad, R


def _fused_fwd(x_proj0, wh_stack, wx_stack, b_stack):
    out, hseq, cseq, pad, R = _run_fwd(x_proj0, wh_stack, wx_stack, b_stack)
    h_fin = hseq[:, :R, -1, :]
    c_fin = cseq[:, :R, -1, :]
    result = (out[:R], h_fin, c_fin)
    residuals = (x_proj0, wh_stack, wx_stack, b_stack, hseq, cseq)
    return result, residuals


def _fused_bwd(residuals, cotangents):
    x_proj0, wh_stack, wx_stack, b_stack, hseq, cseq = residuals
    g_out, g_hfin, g_cfin = cotangents
    R, T, four_h = x_proj0.shape
    L, h_dim, _ = wh_stack.shape
    dtype = x_proj0.dtype

    xp, _ = _pad_rows(x_proj0, _BLOCK_R)
    rp = xp.shape[0]
    gout, _ = _pad_rows(g_out.astype(dtype), _BLOCK_R)
    # final-state cotangents: (L, R, H) -> row-padded, layer-major blocks
    ghfin, _ = _pad_rows(jnp.swapaxes(g_hfin.astype(dtype), 0, 1), _BLOCK_R)
    gcfin, _ = _pad_rows(jnp.swapaxes(g_cfin.astype(dtype), 0, 1), _BLOCK_R)
    ghfin = jnp.swapaxes(ghfin, 0, 1)
    gcfin = jnp.swapaxes(gcfin, 0, 1)
    grid = (rp // _BLOCK_R,)
    kernel = functools.partial(_bwd_kernel, T, L)
    f32 = jnp.float32
    dxp, dwh, dwx, db = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_R, T, four_h), lambda i: (i, 0, 0)),
            pl.BlockSpec((L, h_dim, four_h), lambda i: (0, 0, 0)),
            pl.BlockSpec(wx_stack.shape, lambda i: (0, 0, 0)),
            pl.BlockSpec(b_stack.shape, lambda i: (0, 0)),
            pl.BlockSpec((L, _BLOCK_R, T, h_dim), lambda i: (0, i, 0, 0)),
            pl.BlockSpec((L, _BLOCK_R, T, h_dim), lambda i: (0, i, 0, 0)),
            pl.BlockSpec((_BLOCK_R, T, h_dim), lambda i: (i, 0, 0)),
            pl.BlockSpec((L, _BLOCK_R, h_dim), lambda i: (0, i, 0)),
            pl.BlockSpec((L, _BLOCK_R, h_dim), lambda i: (0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_R, T, four_h), lambda i: (i, 0, 0)),
            # weight grads: every grid step maps to the same block; the
            # sequential TPU grid makes read-modify-write accumulation safe
            pl.BlockSpec((L, h_dim, four_h), lambda i: (0, 0, 0)),
            pl.BlockSpec(wx_stack.shape, lambda i: (0, 0, 0)),
            pl.BlockSpec(b_stack.shape, lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rp, T, four_h), dtype),
            jax.ShapeDtypeStruct(wh_stack.shape, f32),
            jax.ShapeDtypeStruct(wx_stack.shape, f32),
            jax.ShapeDtypeStruct(b_stack.shape, f32),
        ],
        interpret=not pallas_lstm_available(),
    )(xp, wh_stack, wx_stack, b_stack, hseq, cseq, gout, ghfin, gcfin)
    return (
        dxp[:R],
        dwh.astype(wh_stack.dtype),
        dwx.astype(wx_stack.dtype),
        db.astype(b_stack.dtype),
    )


fused_lstm.defvjp(_fused_fwd, _fused_bwd)
