"""Fused multi-layer LSTM recurrence as a single Pallas TPU kernel pair.

The shared LSTM is ~93% of the flagship's step FLOPs (BASELINE.md), so it
is the one op worth a hand kernel. TPU-native counterpart of the engine
kernel the reference gets from cuDNN (``/root/reference/STMGCN.py:21,48``
— ``nn.LSTM``'s fused implementation), built the Pallas way rather than
translated:

- the layer-0 input projection for all T steps stays **outside** the
  kernel as one large XLA matmul (MXU-shaped, batched over ``R*T`` rows —
  same hoisting as the scan path, ``ops/lstm.py``);
- one **forward kernel** runs the entire ``T x L`` recurrence for a block
  of rows with every hidden/cell state living in VMEM — no HBM round
  trips between steps or layers (grid over row blocks; T and L are
  static, so the step/layer loops fully unroll into straight-line code);
- one **backward kernel** runs the reverse sweep, *recomputing* gate
  pre-activations from the saved per-step ``h``/``c`` sequences instead
  of storing ``(L, R, T, 4H)`` gate tensors — recompute is MXU-cheap,
  HBM traffic is the scarce resource (the same trade ``jax.checkpoint``
  makes, chosen once here and hand-scheduled);
- weight gradients accumulate across row blocks in revisited output
  blocks (TPU grids execute sequentially, so ``+=`` into a
  constant-index block is race-free).

Memory layout is **time-major** (``(T, R, ...)`` sequences, ``(T, L, R,
H)`` residuals): every in-kernel ref access then slices only *leading*
axes, so each load/store is a leading-unit-dim reshape of a ``(rows,
feature)`` vector — the one shape cast Mosaic's vector layout inference
supports on all generations. (Row-major ``(R, T, ...)`` layouts put the
sliced axis in the middle and Mosaic rejects the resulting
``(R, 1, F)``-style casts — found the hard way on v5e.) The
batch-major transposes this costs live outside the kernel as cheap XLA
transposes on ``(R, T, H)``-sized tensors.

Zero initial state per call is the reference's semantics
(``STMGCN.py:53-57``); callers that pass explicit initial states use the
scan path instead.

Mesh composition: :func:`sharded_fused_lstm` wraps the kernel pair in
``shard_map`` over the row axis — each device launches the kernel on
its local rows (rows are embarrassingly parallel; the per-shard grid is
the same grid-over-row-blocks, just shorter) and the backward psums
the weight gradients across shards explicitly, so GSPMD never has to
partition the Mosaic custom call itself. Validated for values + grads
against the unsharded kernel on the 8-virtual-device CPU mesh
(interpret lowering) by ``tests/test_pallas_lstm.py``; not yet *timed*
on real multi-chip hardware (this image exposes one chip), so the
multi-chip default remains ``backend="xla"`` and ``pallas`` on a mesh
is opt-in via ``StackedLSTM.pallas_mesh``. Numerics: elementwise cell arithmetic (gates,
tanh/sigmoid, state updates) is float32 regardless of storage dtype, but
matmul *operands* are kept in the storage dtype with f32 accumulation
(``_mm``) — for bf16 storage that means f32-resident states and
cotangents are rounded to bf16 before each MXU contraction, the MXU's
native mode and the same rounding the bf16 scan path applies at every
step. fp32 storage is exact f32 throughout. Agreement with the scan path
is pinned by ``tests/test_pallas_lstm.py`` in both dtypes, gradients
included (fp32 tight, bf16 at bf16-appropriate tolerances).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["fused_lstm", "pallas_lstm_available", "sharded_fused_lstm"]

#: rows per grid step — sized so each kernel's blocks plus
#: double-buffering and straight-line temporaries stay inside the
#: ~16 MB/core scoped VMEM limit. Bigger blocks amortize MXU pipeline
#: fill across the T*L unrolled small matmuls (measured on v5e, bf16 at
#: T=12/L=3 with the round-2 UNPACKED kernel: 256-row fwd blocks were
#: 1.35x faster end-to-end than 128). Round-5 recalibration from real
#: Mosaic AOT evidence (bench_stderr.log, 2026-07-29): the PACKED
#: kernel under vmapped M=3 branches overflows scoped VMEM at the old
#: bases — fp32 fwd at 128 rows allocates 18.04 MB vs the 16 MB limit
#: (the K=2H operand concat + wider wxh block cost ~2 MB the unpacked
#: form didn't carry) — so both bases are halved for headroom
#: (~9 MB at the same point); ``benchmarks/pallas_block_sweep.py``
#: re-raises them per-point on a live chip if the budget allows. The
#: backward kernel carries ~2.5x the forward's live state (residual
#: reads + dxp + recompute temporaries), so it takes half the forward's
#: rows.
def _block_rows(itemsize: int, T: int, L: int) -> tuple[int, int]:
    """(fwd_rows, bwd_rows) for a storage dtype of ``itemsize`` bytes and
    a ``T x L`` recurrence.

    ``STMGCN_PALLAS_FWD_ROWS`` / ``STMGCN_PALLAS_BWD_ROWS`` override the
    derived sizes (tuning knob for on-chip sweeps —
    ``benchmarks/pallas_block_sweep.py``); the fwd/bwd divisibility
    invariant below still applies and is asserted. Any resizing here is
    re-checked statically by ``stmgcn lint``'s Pallas pass
    (``analysis/pallas_check.py``): it re-derives both kernels' BlockSpec
    blocks from these row counts and gates on a VMEM-footprint estimate
    calibrated against the real-Mosaic 18.04 MB OOM below — an override
    that would OOM on chip fails lint on CPU first.

    Every VMEM-resident term scales as ``rows * T * (5 + 2L) * H``
    (``xp``+``out`` blocks plus the two ``(T, L, rows, H)`` residual
    blocks), so the row count derives from the calibration point
    (T=12, L=3: 128 bf16 / 64 fp32 — half the round-2 unpacked-kernel
    values, after real Mosaic AOT showed the packed kernel at the old
    fp32-128 point allocating 18.04 MB vs the 16 MB scoped limit) by
    inverse scaling. Longer sequences (the T=24 longhorizon preset)
    automatically take proportionally narrower blocks instead of
    overflowing scoped VMEM. Rows round down to a power of two and
    floor at the dtype's sublane tile (16 bf16 / 8 fp32).

    Invariant: ``fwd_rows % bwd_rows == 0``. The backward pass re-tiles
    the forward-padded residuals (``hseq``/``cseq`` rows padded to
    ``fwd_rows``) with ``bwd_rows``-sized blocks, which is only correct
    when the forward block is an exact multiple of the backward block.
    """
    import os

    base_fwd = 128 if itemsize <= 2 else 64
    min_rows = 16 if itemsize <= 2 else 8
    scale = (12 * (5 + 2 * 3)) / (T * (5 + 2 * L))
    fwd_rows = base_fwd
    while fwd_rows > min_rows and fwd_rows > base_fwd * scale:
        fwd_rows //= 2
    bwd_rows = max(min_rows, fwd_rows // 2)
    fwd_rows = int(os.environ.get("STMGCN_PALLAS_FWD_ROWS", fwd_rows))
    bwd_rows = int(os.environ.get("STMGCN_PALLAS_BWD_ROWS", bwd_rows))
    if fwd_rows < 1 or bwd_rows < 1:
        raise ValueError(
            "STMGCN_PALLAS_FWD_ROWS/BWD_ROWS must be positive, got "
            f"{fwd_rows}/{bwd_rows}"
        )
    if fwd_rows % bwd_rows:
        # user input now, not derived-by-construction — and violating the
        # invariant makes the backward re-tiling numerically wrong, not
        # slow, so it must survive python -O (no bare assert)
        raise ValueError(
            f"STMGCN_PALLAS_FWD_ROWS ({fwd_rows}) must be a multiple of "
            f"STMGCN_PALLAS_BWD_ROWS ({bwd_rows}): the backward pass "
            "re-tiles the forward-padded residuals"
        )
    return fwd_rows, bwd_rows


def pallas_lstm_available() -> bool:
    """True when the current default backend can run the kernel natively."""
    return jax.default_backend() == "tpu"


def _cell_acts(gates_pre):
    """(i, f, g, o) activations from pre-activation gates, f32."""
    i_pre, f_pre, g_pre, o_pre = jnp.split(gates_pre, 4, axis=-1)
    return (
        jax.nn.sigmoid(i_pre),
        jax.nn.sigmoid(f_pre),
        jnp.tanh(g_pre),
        jax.nn.sigmoid(o_pre),
    )


def _mm(a, w):
    """MXU matmul: operands in storage dtype, f32 accumulation.

    For bf16 storage this is the MXU's native mode (bf16 inputs, f32
    accumulate) — casting operands *up* to f32 first would force multi-pass
    f32 arithmetic at a fraction of the bf16 rate (measured: the
    all-f32-operand version of this kernel was 1.3x slower end-to-end in
    bf16). f32 storage is unchanged: a is already f32.
    """
    return jnp.dot(a.astype(w.dtype), w, preferred_element_type=jnp.float32)


def _fwd_kernel(T, L, xp_ref, wh0_ref, wxh_ref, b_ref, out_ref, hseq_ref, cseq_ref):
    """Whole T x L recurrence for one row block; states never leave VMEM.

    Ref layouts (block shapes): ``xp (T, br, 4H)``, ``wh0 (H, 4H)``
    (layer 0's recurrent weights), ``wxh (max(L-1,1), 2H, 4H)`` (layers
    >= 1: input weights stacked over recurrent weights along the
    contraction axis), ``b`` stacked layer >= 1 biases, ``out
    (T, br, H)``, ``hseq/cseq (T, L, br, H)`` — all sequence refs
    time-major so every access below slices leading axes only.

    MXU shape note: layers >= 1 contract ``[h_below, h_prev] @ wxh`` as
    ONE ``(br, 2H) x (2H, 4H)`` matmul. At the flagship's H=64 that puts
    K=128 on the MXU's 128-lane contraction axis — two separate K=64
    matmuls (the naive ``h_below @ wx + h_prev @ wh``) each run the
    systolic array at half K-occupancy for the same total tile count.
    Layer 0's recurrence is unavoidably K=H (its input term ``xp`` is
    precomputed outside the kernel, where it batches over R*T rows).
    """
    br = xp_ref.shape[1]
    h_dim = wh0_ref.shape[0]
    f32 = jnp.float32
    h = [jnp.zeros((br, h_dim), f32) for _ in range(L)]
    c = [jnp.zeros((br, h_dim), f32) for _ in range(L)]
    for t in range(T):
        for layer in range(L):
            if layer == 0:
                pre = xp_ref[t].astype(f32) + _mm(h[0], wh0_ref[...])
            else:
                hcat = jnp.concatenate([h[layer - 1], h[layer]], axis=-1)
                pre = _mm(hcat, wxh_ref[layer - 1]) + b_ref[
                    layer - 1 : layer
                ].astype(f32)
            i, f, g, o = _cell_acts(pre)
            c[layer] = f * c[layer] + i * g
            h[layer] = o * jnp.tanh(c[layer])
            hseq_ref[t, layer] = h[layer].astype(hseq_ref.dtype)
            cseq_ref[t, layer] = c[layer].astype(cseq_ref.dtype)
        out_ref[t] = h[L - 1].astype(out_ref.dtype)


def _bwd_kernel(
    T,
    L,
    xp_ref,
    wh0_ref,
    wxh_ref,
    b_ref,
    hseq_ref,
    cseq_ref,
    gout_ref,
    ghfin_ref,
    gcfin_ref,
    dxp_ref,
    dwh0_ref,
    dwxh_ref,
    db_ref,
):
    """Reverse sweep for one row block; gate pre-activations recomputed.

    Mirrors the forward's packed layout (see ``_fwd_kernel``): layers
    >= 1 run ONE ``(br, 4H) x (4H, 2H)`` cotangent matmul (full K=4H,
    N=2H=128 at the flagship width) and ONE ``(2H, br) x (br, 4H)``
    weight-gradient matmul per step, where the unpacked form needed two
    of each at half MXU occupancy. The ``(br, 2H)`` products split on
    the lane axis at H — an aligned half-register slice.
    """
    br = xp_ref.shape[1]
    h_dim = wh0_ref.shape[0]
    f32 = jnp.float32

    @pl.when(pl.program_id(0) == 0)
    def _zero_weight_grads():
        dwh0_ref[...] = jnp.zeros_like(dwh0_ref)
        dwxh_ref[...] = jnp.zeros_like(dwxh_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dh = [ghfin_ref[layer].astype(f32) for layer in range(L)]
    dc = [gcfin_ref[layer].astype(f32) for layer in range(L)]
    zeros = jnp.zeros((br, h_dim), f32)
    for t in reversed(range(T)):
        dh[L - 1] = dh[L - 1] + gout_ref[t].astype(f32)
        for layer in reversed(range(L)):
            h_prev = hseq_ref[t - 1, layer].astype(f32) if t > 0 else zeros
            c_prev = cseq_ref[t - 1, layer].astype(f32) if t > 0 else zeros
            c_t = cseq_ref[t, layer].astype(f32)
            # recompute this step's pre-activations (cheaper than storing)
            if layer == 0:
                pre = xp_ref[t].astype(f32) + _mm(h_prev, wh0_ref[...])
            else:
                below = hseq_ref[t, layer - 1].astype(f32)
                hcat = jnp.concatenate([below, h_prev], axis=-1)
                pre = _mm(hcat, wxh_ref[layer - 1]) + b_ref[
                    layer - 1 : layer
                ].astype(f32)
            i, f, g, o = _cell_acts(pre)
            tc = jnp.tanh(c_t)

            do = dh[layer] * tc
            dct = dc[layer] + dh[layer] * o * (1.0 - tc * tc)
            dgates = jnp.concatenate(
                [
                    dct * g * i * (1.0 - i),  # d i_pre
                    dct * c_prev * f * (1.0 - f),  # d f_pre
                    dct * i * (1.0 - g * g),  # d g_pre
                    do * o * (1.0 - o),  # d o_pre
                ],
                axis=-1,
            )
            dc[layer] = dct * f
            if layer == 0:
                dh[0] = _mm(dgates, wh0_ref[...].T)
                dwh0_ref[...] += _mm(
                    h_prev.T.astype(xp_ref.dtype), dgates.astype(xp_ref.dtype)
                ).astype(dwh0_ref.dtype)
                dxp_ref[t] = dgates.astype(dxp_ref.dtype)
            else:
                dcat = _mm(dgates, wxh_ref[layer - 1].T)  # (br, 2H)
                dh[layer - 1] = dh[layer - 1] + dcat[:, :h_dim]
                dh[layer] = dcat[:, h_dim:]
                dwxh_ref[layer - 1] += _mm(
                    hcat.T.astype(xp_ref.dtype), dgates.astype(xp_ref.dtype)
                ).astype(dwxh_ref.dtype)
                db_ref[layer - 1 : layer] += jnp.sum(
                    dgates, axis=0, keepdims=True
                ).astype(db_ref.dtype)


def _pad_rows_axis1(arr, block):
    """Zero-pad axis 1 (the row axis of time-major layouts) to ``block``."""
    r = arr.shape[1]
    pad = (-r) % block
    if pad:
        widths = [(0, 0), (0, pad)] + [(0, 0)] * (arr.ndim - 2)
        arr = jnp.pad(arr, widths)
    return arr, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_lstm(x_proj0, wh_stack, wx_stack, b_stack):
    """Run the fused recurrence; returns ``(hs_top, h_fin, c_fin)``.

    Args:
      x_proj0: ``(R, T, 4H)`` — layer 0's hoisted input projection
        (``x @ wx_0 + b_0``), any float dtype.
      wh_stack: ``(L, H, 4H)`` recurrent weights, all layers.
      wx_stack: ``(max(L-1, 1), H, 4H)`` input weights of layers >= 1
        (ignored garbage row allowed when L == 1 so the operand is never
        zero-sized).
      b_stack: ``(max(L-1, 1), 4H)`` biases of layers >= 1.

    Returns ``hs_top`` ``(R, T, H)`` (top layer's hidden sequence) plus
    per-layer final states ``(L, R, H)`` each, matching
    ``ops.lstm.StackedLSTM``'s return contract.
    """
    out, _ = _fused_fwd(x_proj0, wh_stack, wx_stack, b_stack)
    return out


def _pack_weights(wh_stack, wx_stack):
    """``(wh0, wxh)``: layer 0's recurrent weights alone, layers >= 1's
    input and recurrent weights stacked along the contraction axis
    (``(L-1, 2H, 4H)``; one garbage row when L == 1 so the operand is
    never zero-sized) — the kernel then contracts ``[h_below, h_prev]``
    against one K=2H operand per step."""
    L = wh_stack.shape[0]
    if L > 1:
        wxh = jnp.concatenate([wx_stack[: L - 1], wh_stack[1:]], axis=1)
    else:
        wxh = jnp.concatenate([wx_stack, wx_stack], axis=1)
    return wh_stack[0], wxh


def _run_fwd(x_proj0, wh_stack, wx_stack, b_stack):
    R, T, four_h = x_proj0.shape
    L, h_dim, _ = wh_stack.shape
    dtype = x_proj0.dtype
    block_fwd, _ = _block_rows(jnp.dtype(dtype).itemsize, T, L)
    xp, _ = _pad_rows_axis1(x_proj0.swapaxes(0, 1), block_fwd)  # (T, Rp, 4H)
    rp = xp.shape[1]
    grid = (rp // block_fwd,)
    kernel = functools.partial(_fwd_kernel, T, L)
    wh0, wxh = _pack_weights(wh_stack, wx_stack)
    out, hseq, cseq = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((T, block_fwd, four_h), lambda i: (0, i, 0)),
            pl.BlockSpec((h_dim, four_h), lambda i: (0, 0)),
            pl.BlockSpec(wxh.shape, lambda i: (0, 0, 0)),
            pl.BlockSpec(b_stack.shape, lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((T, block_fwd, h_dim), lambda i: (0, i, 0)),
            pl.BlockSpec((T, L, block_fwd, h_dim), lambda i: (0, 0, i, 0)),
            pl.BlockSpec((T, L, block_fwd, h_dim), lambda i: (0, 0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, rp, h_dim), dtype),
            jax.ShapeDtypeStruct((T, L, rp, h_dim), dtype),
            jax.ShapeDtypeStruct((T, L, rp, h_dim), dtype),
        ],
        interpret=not pallas_lstm_available(),
    )(xp, wh0, wxh, b_stack)
    return out, hseq, cseq, R


def _fused_fwd(x_proj0, wh_stack, wx_stack, b_stack):
    out, hseq, cseq, R = _run_fwd(x_proj0, wh_stack, wx_stack, b_stack)
    h_fin = hseq[-1, :, :R, :]  # (L, R, H)
    c_fin = cseq[-1, :, :R, :]
    result = (out[:, :R].swapaxes(0, 1), h_fin, c_fin)
    residuals = (x_proj0, wh_stack, wx_stack, b_stack, hseq, cseq)
    return result, residuals


def _fused_bwd(residuals, cotangents):
    x_proj0, wh_stack, wx_stack, b_stack, hseq, cseq = residuals
    g_out, g_hfin, g_cfin = cotangents
    R, T, four_h = x_proj0.shape
    L, h_dim, _ = wh_stack.shape
    dtype = x_proj0.dtype

    _, block_bwd = _block_rows(jnp.dtype(dtype).itemsize, T, L)
    xp, _ = _pad_rows_axis1(x_proj0.swapaxes(0, 1), block_bwd)  # (T, Rp, 4H)
    rp = xp.shape[1]
    gout, _ = _pad_rows_axis1(g_out.astype(dtype).swapaxes(0, 1), block_bwd)
    # final-state cotangents: (L, R, H) row-padded on axis 1 already
    ghfin, _ = _pad_rows_axis1(g_hfin.astype(dtype), block_bwd)
    gcfin, _ = _pad_rows_axis1(g_cfin.astype(dtype), block_bwd)
    grid = (rp // block_bwd,)
    kernel = functools.partial(_bwd_kernel, T, L)
    f32 = jnp.float32
    wh0, wxh = _pack_weights(wh_stack, wx_stack)
    dxp, dwh0, dwxh, db = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((T, block_bwd, four_h), lambda i: (0, i, 0)),
            pl.BlockSpec((h_dim, four_h), lambda i: (0, 0)),
            pl.BlockSpec(wxh.shape, lambda i: (0, 0, 0)),
            pl.BlockSpec(b_stack.shape, lambda i: (0, 0)),
            pl.BlockSpec((T, L, block_bwd, h_dim), lambda i: (0, 0, i, 0)),
            pl.BlockSpec((T, L, block_bwd, h_dim), lambda i: (0, 0, i, 0)),
            pl.BlockSpec((T, block_bwd, h_dim), lambda i: (0, i, 0)),
            pl.BlockSpec((L, block_bwd, h_dim), lambda i: (0, i, 0)),
            pl.BlockSpec((L, block_bwd, h_dim), lambda i: (0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((T, block_bwd, four_h), lambda i: (0, i, 0)),
            # weight grads: every grid step maps to the same block; the
            # sequential TPU grid makes read-modify-write accumulation safe
            pl.BlockSpec((h_dim, four_h), lambda i: (0, 0)),
            pl.BlockSpec(wxh.shape, lambda i: (0, 0, 0)),
            pl.BlockSpec(b_stack.shape, lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, rp, four_h), dtype),
            jax.ShapeDtypeStruct((h_dim, four_h), f32),
            jax.ShapeDtypeStruct(wxh.shape, f32),
            jax.ShapeDtypeStruct(b_stack.shape, f32),
        ],
        interpret=not pallas_lstm_available(),
    )(xp, wh0, wxh, b_stack, hseq, cseq, gout, ghfin, gcfin)
    # unpack: wxh rows 0:H are layer l's input weights (dwx), rows H:2H
    # its recurrent weights (dwh); layer 0's recurrent grads stand alone
    L_ = wh_stack.shape[0]
    if L_ > 1:
        dwh = jnp.concatenate([dwh0[None], dwxh[:, h_dim:, :]], axis=0)
        dwx = dwxh[:, :h_dim, :]
    else:
        dwh = dwh0[None]
        dwx = jnp.zeros_like(wx_stack)
    return (
        dxp[:, :R].swapaxes(0, 1),
        dwh.astype(wh_stack.dtype),
        dwx.astype(wx_stack.dtype),
        db.astype(b_stack.dtype),
    )


fused_lstm.defvjp(_fused_fwd, _fused_bwd)


@functools.lru_cache(maxsize=8)
def sharded_fused_lstm(mesh, row_axes: tuple = ("dp", "region")):
    """A :func:`fused_lstm` variant launching per-shard kernels over ``mesh``.

    Rows (axis 0 of ``x_proj0``; ``B*N`` in the model, where batch shards
    over ``dp`` and nodes over ``region``) shard over ``row_axes``;
    weights are replicated. Each device runs the unmodified kernel pair
    on its local row block — rows are embarrassingly parallel through
    the whole ``T x L`` recurrence, so the only cross-device
    communication in the op is the backward's explicit weight-gradient
    ``psum`` (the same all-reduce the dp-sharded scan path's grads pay
    under GSPMD). Wrapping in ``shard_map`` means GSPMD never needs a
    partitioning rule for the Mosaic custom call — the round-3 caveat
    this function retires.

    ``row_axes`` entries absent from the mesh are ignored, so the
    default works on ``(dp,)``-only and ``(dp, region)`` meshes alike.
    The kernel's row-padding happens per shard (each shard pads its
    local rows up to the block size), which is exactly the padding a
    single-device run of the same local shape would do.

    Cached per ``(mesh, row_axes)`` so flax re-traces reuse one
    ``custom_vjp`` instance instead of registering a fresh pair per call.
    """
    from jax.sharding import PartitionSpec as P

    from stmgcn_tpu.utils.platform import shard_map

    axes = tuple(a for a in row_axes if a in mesh.shape)
    if not axes:
        return fused_lstm
    row = P(axes, None, None)  # x_proj0 (R, T, 4H) / hs_top (R, T, H) / dxp
    fin = P(None, axes, None)  # h_fin / c_fin (L, R, H) + their cotangents
    seq = P(None, None, axes, None)  # hseq / cseq residuals (T, L, Rp, H)
    rep = P()
    result_specs = (row, fin, fin)
    resid_specs = (row, rep, rep, rep, seq, seq)

    # check_vma=False on both maps: the pallas_call's out_shape carries no
    # varying-mesh-axes metadata (same reason as parallel/sparse.py)
    fwd_m = shard_map(
        _fused_fwd,
        mesh=mesh,
        in_specs=(row, rep, rep, rep),
        out_specs=(result_specs, resid_specs),
        check_vma=False,
    )

    def _bwd_local(residuals, cotangents):
        dxp, dwh, dwx, db = _fused_bwd(residuals, cotangents)
        # replicated weights: their true cotangent is the sum of every
        # shard's local contribution (the transpose of a broadcast)
        dwh = jax.lax.psum(dwh, axes)
        dwx = jax.lax.psum(dwx, axes)
        db = jax.lax.psum(db, axes)
        return dxp, dwh, dwx, db

    bwd_m = shard_map(
        _bwd_local,
        mesh=mesh,
        in_specs=(resid_specs, result_specs),
        out_specs=(row, rep, rep, rep),
        check_vma=False,
    )

    @jax.custom_vjp
    def sharded(x_proj0, wh_stack, wx_stack, b_stack):
        return fwd_m(x_proj0, wh_stack, wx_stack, b_stack)[0]

    sharded.defvjp(
        lambda *operands: fwd_m(*operands),
        lambda residuals, cotangents: bwd_m(residuals, cotangents),
    )
    return sharded
