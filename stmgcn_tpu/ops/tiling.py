"""Offline tiled-sparse support planning: reorder + condense for the MXU.

Large-N cities (a 10k+-region metro) hit the dense-FLOP ceiling of the
``(M, K, N, N)`` support stack long before the hardware's: a Chebyshev
support of a metro road/grid graph is overwhelmingly zero, but the MXU
only eats dense tiles. The TC-GNN / "sparse GNNs on dense hardware"
recipe (PAPERS.md) fixes the mismatch **offline**:

1. **Reorder** — a bandwidth-reducing node permutation (reverse
   Cuthill-McKee-style BFS over the symmetrized union pattern of all
   M x K supports) clusters each row's neighbors, so nonzeros land in
   few ``(tile, tile)`` blocks instead of being scattered across a row.
2. **Condense** — pack each permuted support's nonzero blocks into a
   uniform block-CSR layout (``ops/spmm.py``'s representation), one
   common block-column count across all M x K supports of the city so
   every kernel operand shape is static.

The result is a :class:`TiledSupports` artifact covering the whole city
in one plan: permutation + inverse, per-support block data/index stacks
(forward and pre-transposed for the backward pass), with
:meth:`TiledSupports.tile_stats` reporting blocks-kept vs
blocks-dense-equivalent — the density ratio that bounds the FLOP win.

Everything here is **numpy on the host** (an offline preprocessing
pass); the online apply lives in
:class:`stmgcn_tpu.ops.chebconv.TiledChebGraphConv`, which permutes the
signal in once, runs either the gathered-tiles XLA path
(:func:`gathered_tiles_apply`) or the fused Pallas ``spmm_stack``
kernel, and permutes the final stack back out.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from stmgcn_tpu.ops.spmm import (
    TILE,
    BlockSparseStack,
    _assemble_blocks,
    _ceil_to,
    _scan_blocks,
)

__all__ = [
    "ShardedTiledBranch",
    "TiledBranchSupports",
    "TiledSupports",
    "gathered_tiles_apply",
    "gathered_tiles_apply_reference",
    "plan_tiling",
    "rcm_permutation",
    "shard_tiled_plan",
    "sharded_gathered_tiles_apply",
]


def rcm_permutation(pattern: np.ndarray) -> np.ndarray:
    """Reverse-Cuthill-McKee-style BFS ordering of a sparsity pattern.

    ``pattern`` is a boolean ``(N, N)`` adjacency (symmetrized inside —
    bandwidth is a property of the symmetric closure). Components are
    seeded from their minimum-degree node and BFS levels visit neighbors
    in ascending-degree order; the final order is reversed (the RCM
    refinement — same bandwidth, better profile). Pure numpy, no scipy.

    Returns ``perm`` (int32): new position ``p`` holds original node
    ``perm[p]``, i.e. ``A_reordered = A[perm][:, perm]``.
    """
    pattern = np.asarray(pattern)
    if pattern.ndim != 2 or pattern.shape[0] != pattern.shape[1]:
        raise ValueError(f"pattern must be square (N, N), got {pattern.shape}")
    sym = (pattern != 0) | (pattern.T != 0)
    np.fill_diagonal(sym, False)
    n = sym.shape[0]
    deg = sym.sum(axis=1)
    order = np.empty(n, dtype=np.int64)
    visited = np.zeros(n, dtype=bool)
    pos = 0
    for start in np.argsort(deg, kind="stable"):
        if visited[start]:
            continue
        visited[start] = True
        order[pos] = start
        head, pos = pos, pos + 1
        while head < pos:
            u = order[head]
            head += 1
            nbrs = np.flatnonzero(sym[u] & ~visited)
            if nbrs.size:
                nbrs = nbrs[np.argsort(deg[nbrs], kind="stable")]
                visited[nbrs] = True
                order[pos : pos + nbrs.size] = nbrs
                pos += nbrs.size
    return order[::-1].astype(np.int32)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TiledBranchSupports:
    """One branch's slice of a :class:`TiledSupports` plan (K supports).

    What the per-branch graph conv consumes: the city permutation plus
    this branch's uniform block-CSR stacks. :meth:`as_stack` views the
    blocks as an :class:`~stmgcn_tpu.ops.spmm.BlockSparseStack` so the
    fused Pallas kernel path is shared verbatim with sparse mode.
    """

    perm: jnp.ndarray  # (N,) int32 — x_reordered = x[perm]
    inv: jnp.ndarray  # (N,) int32 — y = y_reordered[inv]
    data: jnp.ndarray  # (K, R, C, tile, tile) f32
    idx: jnp.ndarray  # (K, R, C) int32
    data_t: jnp.ndarray  # (K, R, C_t, tile, tile) f32
    idx_t: jnp.ndarray  # (K, R, C_t) int32
    n: int
    tile: int

    def tree_flatten(self):
        return (
            self.perm, self.inv, self.data, self.idx, self.data_t, self.idx_t,
        ), (self.n, self.tile)

    @classmethod
    def tree_unflatten(cls, aux, children):
        perm, inv, data, idx, data_t, idx_t = children
        n, tile = aux
        return cls(perm=perm, inv=inv, data=data, idx=idx, data_t=data_t,
                   idx_t=idx_t, n=n, tile=tile)

    @property
    def n_supports(self) -> int:
        return self.data.shape[0]

    def as_stack(self) -> BlockSparseStack:
        """This branch's blocks as the fused-kernel operand (square N x N
        in the *permuted* node order — callers permute the signal)."""
        return BlockSparseStack(
            data=self.data, idx=self.idx, data_t=self.data_t,
            idx_t=self.idx_t, n_rows=self.n, n_cols=self.n, tile=self.tile,
        )


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TiledSupports:
    """One city's tiled-sparse support plan: all M graphs x K supports.

    ``data``/``idx`` carry a leading ``(M, K, ...)`` pair with ONE common
    block-column count across every support (and one for the transposes),
    so per-city plans tree-stack into fleet class operands and the scan
    bodies' per-slot ``jnp.take`` works leaf-wise. Indexing (``plan[m]``)
    yields the branch view the per-branch conv loop consumes, mirroring
    how the sparse M-tuple is consumed.

    Aux data is ``(n, tile)`` only — occupancy accounting is derived on
    demand (:meth:`tile_stats`), never stored, so two cities' plans with
    equal shapes are the *same* pytree structure.
    """

    perm: jnp.ndarray  # (N,) int32
    inv: jnp.ndarray  # (N,) int32
    data: jnp.ndarray  # (M, K, R, C, tile, tile) f32
    idx: jnp.ndarray  # (M, K, R, C) int32
    data_t: jnp.ndarray  # (M, K, R, C_t, tile, tile) f32
    idx_t: jnp.ndarray  # (M, K, R, C_t) int32
    n: int
    tile: int

    def tree_flatten(self):
        return (
            self.perm, self.inv, self.data, self.idx, self.data_t, self.idx_t,
        ), (self.n, self.tile)

    @classmethod
    def tree_unflatten(cls, aux, children):
        perm, inv, data, idx, data_t, idx_t = children
        n, tile = aux
        return cls(perm=perm, inv=inv, data=data, idx=idx, data_t=data_t,
                   idx_t=idx_t, n=n, tile=tile)

    @property
    def m_graphs(self) -> int:
        return self.data.shape[0]

    @property
    def n_supports(self) -> int:
        return self.data.shape[1]

    @property
    def block_rows(self) -> int:
        return self.data.shape[2]

    @property
    def block_cols(self) -> int:
        return self.data.shape[3]

    @property
    def ndim(self) -> int:
        # deliberately NOT 4: every "is this a dense (M, K, N, N) stack"
        # gate in the trainer/serving paths keys off ndim == 4
        return 0

    @property
    def nbytes(self) -> int:
        return (self.data.nbytes + self.idx.nbytes + self.data_t.nbytes
                + self.idx_t.nbytes)

    def __len__(self) -> int:
        # the model's non-dense loop path does len(supports_stack) and
        # supports_stack[m] — same protocol as the sparse M-tuple
        return self.m_graphs

    def __getitem__(self, m: int) -> TiledBranchSupports:
        if not isinstance(m, (int, np.integer)):
            raise TypeError(f"branch index must be an int, got {type(m)!r}")
        return TiledBranchSupports(
            perm=self.perm, inv=self.inv, data=self.data[m], idx=self.idx[m],
            data_t=self.data_t[m], idx_t=self.idx_t[m], n=self.n,
            tile=self.tile,
        )

    def tile_stats(self) -> dict:
        """Occupancy accounting (host-side: reads block values).

        ``blocks_kept`` counts truly-nonzero forward blocks;
        ``blocks_dense_equivalent`` is what a dense padded plan would
        carry (``M * K * R * R``); their ratio is the density that bounds
        the support-apply FLOP win (``flops_ratio`` uses the *stored*
        ``C / R`` — what the kernels actually execute, padding included).
        """
        data = np.asarray(self.data)
        r = self.block_rows
        kept = int(np.any(data != 0.0, axis=(-1, -2)).sum())
        dense_eq = self.m_graphs * self.n_supports * r * r
        return {
            "n": self.n,
            "tile": self.tile,
            "block_rows": r,
            "block_cols": self.block_cols,
            "blocks_kept": kept,
            "blocks_dense_equivalent": dense_eq,
            "density": kept / dense_eq,
            "flops_ratio": self.block_cols / r,
            "nbytes": int(self.nbytes),
            "dense_nbytes": int(
                self.m_graphs * self.n_supports * self.n * self.n * 4
            ),
        }

    def pad_to(self, n_new: int) -> "TiledSupports":
        """Grow to a rung of ``n_new`` nodes (fleet shape classes).

        New nodes are isolated: identity-tail permutation, and zero
        block rows once the rung crosses a tile boundary (index 0 with
        zero data — the same harmless-padding convention as
        ``ops/spmm.py``).
        """
        if n_new < self.n:
            raise ValueError(f"cannot shrink a plan: n={self.n} -> {n_new}")
        if n_new == self.n:
            return self
        r_new = _ceil_to(n_new, self.tile) // self.tile
        grow = r_new - self.block_rows
        perm = jnp.concatenate(
            [self.perm, jnp.arange(self.n, n_new, dtype=jnp.int32)]
        )
        inv = jnp.concatenate(
            [self.inv, jnp.arange(self.n, n_new, dtype=jnp.int32)]
        )

        def pad_r(a):
            widths = [(0, 0)] * a.ndim
            widths[2] = (0, grow)
            return jnp.pad(a, widths)

        return TiledSupports(
            perm=perm, inv=inv,
            data=pad_r(self.data), idx=pad_r(self.idx),
            data_t=pad_r(self.data_t), idx_t=pad_r(self.idx_t),
            n=n_new, tile=self.tile,
        )

    def with_block_cols(self, c: int, c_t: int) -> "TiledSupports":
        """Pad the block-column axes to imposed widths (fleet classes
        stack member plans leaf-wise, which needs one common ``C``)."""
        if c < self.block_cols or c_t < self.data_t.shape[3]:
            raise ValueError(
                f"cannot narrow block columns: ({self.block_cols}, "
                f"{self.data_t.shape[3]}) -> ({c}, {c_t})"
            )

        def pad_c(a, width):
            widths = [(0, 0)] * a.ndim
            widths[3] = (0, width - a.shape[3])
            return jnp.pad(a, widths)

        return TiledSupports(
            perm=self.perm, inv=self.inv,
            data=pad_c(self.data, c), idx=pad_c(self.idx, c),
            data_t=pad_c(self.data_t, c_t), idx_t=pad_c(self.idx_t, c_t),
            n=self.n, tile=self.tile,
        )


def plan_tiling(dense, tile: int = TILE) -> TiledSupports:
    """Plan one city's tiled supports from its dense ``(M, K, N, N)`` stack.

    Offline, numpy-only: RCM-style permutation over the symmetrized union
    pattern of all M x K supports (one ordering for the whole city — the
    signal permutes once, not per branch), then block condensation of
    each permuted support at one common block-column count.
    """
    dense = np.asarray(dense, dtype=np.float32)
    if dense.ndim != 4 or dense.shape[2] != dense.shape[3]:
        raise ValueError(
            f"supports must be dense (M, K, N, N), got {dense.shape}"
        )
    if tile < 1:
        raise ValueError(f"tile must be >= 1, got {tile}")
    m_graphs, k, n, _ = dense.shape
    union = np.any(dense != 0.0, axis=(0, 1))
    perm = rcm_permutation(union)
    inv = np.argsort(perm).astype(np.int32)
    permuted = dense[:, :, perm][:, :, :, perm]

    fwd_scan = [
        [_scan_blocks(permuted[mi, ki], tile) for ki in range(k)]
        for mi in range(m_graphs)
    ]
    bwd_scan = [
        [
            _scan_blocks(np.ascontiguousarray(permuted[mi, ki].T), tile)
            for ki in range(k)
        ]
        for mi in range(m_graphs)
    ]

    def width(scans):
        return max(
            max(int(nz.sum(axis=1).max()), 1)
            for row in scans for _, nz in row
        )

    c_max, c_max_t = width(fwd_scan), width(bwd_scan)

    def assemble(scans, c):
        data = np.stack([
            np.stack([_assemble_blocks(b, nz, c, tile)[0] for b, nz in row])
            for row in scans
        ])
        idx = np.stack([
            np.stack([_assemble_blocks(b, nz, c, tile)[1] for b, nz in row])
            for row in scans
        ])
        return data, idx

    data, idx = assemble(fwd_scan, c_max)
    data_t, idx_t = assemble(bwd_scan, c_max_t)
    return TiledSupports(
        perm=jnp.asarray(perm), inv=jnp.asarray(inv),
        data=jnp.asarray(data), idx=jnp.asarray(idx),
        data_t=jnp.asarray(data_t), idx_t=jnp.asarray(idx_t),
        n=n, tile=tile,
    )


def _gathered_tiles_fwd_call(data, idx, x_mat, n, tile):
    """Gather signal row blocks by ``idx`` + one batched tile contraction.

    ``idx`` entries are in-bounds by construction (block condensation
    emits ``[0, R)`` only), so the gather clips instead of paying
    ``jnp.take``'s negative-index select chain.
    """
    k, r, _ = idx.shape
    n_pad = r * tile
    x_pad = jnp.pad(x_mat, ((0, n_pad - x_mat.shape[0]), (0, 0)))
    x_blocks = x_pad.reshape(r, tile, x_mat.shape[1])
    gathered = jnp.take(x_blocks, idx, axis=0, mode="clip")  # (K, R, C, tile, BF)
    out = jnp.einsum(
        "krcij,krcjf->krif", data, gathered,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(k, n_pad, x_mat.shape[1])[:, :n]


def _gathered_tiles_bwd_call(data_t, idx_t, g, n, tile):
    """Prepared backward: ``dx = sum_k A_k^T @ g_k`` over the offline
    pre-transposed block stacks — the same gather/contract shape as the
    forward, with the k axis folded into the accumulation."""
    k, r, _ = idx_t.shape
    n_pad = r * tile
    g_pad = jnp.pad(g, ((0, 0), (0, n_pad - g.shape[1]), (0, 0)))
    g_blocks = g_pad.reshape(k, r, tile, g.shape[2])
    gathered = jax.vmap(
        lambda blocks, it: jnp.take(blocks, it, axis=0, mode="clip")
    )(g_blocks, idx_t)  # (K, R, C_t, tile, BF)
    dx = jnp.einsum(
        "krcij,krcjf->rif", data_t, gathered,
        preferred_element_type=jnp.float32,
    )
    return dx.reshape(n_pad, g.shape[2])[:n]


def gathered_tiles_apply(branch: TiledBranchSupports, x_mat: jnp.ndarray) -> jnp.ndarray:
    """``out[k] = A_k @ x`` through pure gather + batched matmul XLA ops.

    The off-chip twin of the Pallas ``spmm_stack`` path: ``jnp.take`` of
    the signal's row blocks by the block-column index lists, one batched
    ``(tile, tile) @ (tile, BF)`` contraction per kept block, f32
    accumulation (``preferred_element_type`` mirrors the kernel's MXU
    accumulate). Measurable on the 1-core CPU-fallback host, where
    interpret-mode Pallas is orders of magnitude off. ``x_mat`` is the
    *permuted* ``(N, BF)`` signal; returns ``(K, N, BF)`` f32.

    **Prepared backward** (execution-path-preparing, PAPERS.md): instead
    of the autodiff-derived transpose — a scatter-add of cotangent tiles
    back through the gather — the custom VJP consumes the pre-transposed
    block stacks ``plan_tiling`` already builds (``data_t``/``idx_t``)
    and runs the *same* gathered-tiles SpMM shape over the cotangent:
    ``dx = sum_k A_k^T @ g_k``, offline-prepared layout, no scatter.
    Gradients flow to ``x_mat`` only (supports are offline constants —
    zero support cotangents by design, like :func:`~stmgcn_tpu.ops.spmm
    .spmm_stack`): the VJP closes over the support stacks so ``x`` is
    its sole primal, which keeps the backward jaxpr free of
    materialized zero cotangents for the four structure arrays.
    :func:`gathered_tiles_apply_reference` keeps the plain-autodiff
    body for parity tests.
    """
    data, idx = branch.data, branch.idx
    data_t, idx_t = branch.data_t, branch.idx_t
    n, tile = branch.n, branch.tile
    x_dtype = x_mat.dtype

    @jax.custom_vjp
    def _apply(x):
        return _gathered_tiles_fwd_call(data, idx, x, n, tile)

    def _fwd(x):
        return _gathered_tiles_fwd_call(data, idx, x, n, tile), None

    def _bwd(_res, g):
        # f32-accumulated prepared aggregation -> cotangent back in the
        # primal's dtype (no-op on the f32 path)
        return (_gathered_tiles_bwd_call(data_t, idx_t, g, n, tile).astype(x_dtype),)

    _apply.defvjp(_fwd, _bwd)
    return _apply(x_mat)


def gathered_tiles_apply_reference(
    branch: TiledBranchSupports, x_mat: jnp.ndarray
) -> jnp.ndarray:
    """The same forward with plain autodiff (scatter-add backward) — the
    oracle the prepared backward is parity- and primitive-count-tested
    against (tests/test_mixed_precision.py)."""
    return _gathered_tiles_fwd_call(
        branch.data, branch.idx, x_mat, branch.n, branch.tile
    )


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ShardedTiledBranch:
    """One branch's tiled plan split along the permuted block-row axis.

    The RCM permutation that makes blocks dense also makes them *banded*:
    a permuted support's kept blocks sit within a bounded block distance
    of the diagonal, so splitting the block-row axis into contiguous
    shards needs only a ``halo``-block boundary exchange per shard — the
    tiled analogue of :mod:`stmgcn_tpu.parallel.banded`'s strips, riding
    the same ring :func:`~stmgcn_tpu.parallel.halo.halo_exchange`.

    ``data``/``idx`` lead with the shard axis (placed over ``region``,
    exactly like :class:`~stmgcn_tpu.parallel.sparse.ShardedBlockSparse`);
    ``idx`` is **halo-local**: global block column ``j`` of shard ``s``
    is stored as ``j - s*r_loc + halo``, clamped into the halo-extended
    range for the padded zero-data blocks (index 0 with zero data — the
    clamp lands them on a real block whose contribution is zero).
    ``data_t``/``idx_t`` are the prepared-transpose stacks, sharded the
    same way at their own ``halo_t``.
    """

    data: jnp.ndarray  # (S, K, R_loc, C, tile, tile) f32
    idx: jnp.ndarray  # (S, K, R_loc, C) int32, halo-local
    data_t: jnp.ndarray  # (S, K, R_loc, C_t, tile, tile) f32
    idx_t: jnp.ndarray  # (S, K, R_loc, C_t) int32, halo-local
    halo: int
    halo_t: int
    n: int
    tile: int

    def tree_flatten(self):
        return (self.data, self.idx, self.data_t, self.idx_t), (
            self.halo, self.halo_t, self.n, self.tile,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, idx, data_t, idx_t = children
        halo, halo_t, n, tile = aux
        return cls(data=data, idx=idx, data_t=data_t, idx_t=idx_t,
                   halo=halo, halo_t=halo_t, n=n, tile=tile)

    @property
    def n_shards(self) -> int:
        return self.data.shape[0]

    @property
    def n_supports(self) -> int:
        return self.data.shape[1]

    @property
    def block_rows_local(self) -> int:
        return self.data.shape[2]


def _block_halo(data: np.ndarray, idx: np.ndarray) -> int:
    """Max block distance |column - row| over truly-nonzero blocks —
    the boundary depth a contiguous block-row shard must import. Padded
    zero-data blocks don't count (their index is the harmless 0)."""
    nz = np.any(data != 0.0, axis=(-1, -2))  # (K, R, C)
    rows = np.arange(idx.shape[1], dtype=np.int64)[None, :, None]
    dist = np.abs(idx.astype(np.int64) - rows)
    return int(dist[nz].max(initial=0))


def shard_tiled_plan(
    branch: TiledBranchSupports, n_shards: int
) -> ShardedTiledBranch:
    """Split one branch's tiled plan into ``n_shards`` contiguous
    block-row shards with halo-local column indices (host-side numpy —
    the same offline character as :func:`plan_tiling`).

    Raises when the block rows don't divide ``n_shards`` (pad the plan
    with :meth:`TiledSupports.pad_to` first) or when the plan's block
    bandwidth exceeds a shard's rows (the halo exchange only reaches the
    ring neighbors — re-tile coarser or shard less).
    """
    data = np.asarray(branch.data)
    idx = np.asarray(branch.idx)
    data_t = np.asarray(branch.data_t)
    idx_t = np.asarray(branch.idx_t)
    r = idx.shape[1]
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    if r % n_shards:
        raise ValueError(
            f"{r} block rows not divisible by n_shards={n_shards} — "
            "pad_to a divisible rung first"
        )
    r_loc = r // n_shards
    # halo_exchange needs halo >= 1 and <= r_loc
    halo = max(_block_halo(data, idx), 1)
    halo_t = max(_block_halo(data_t, idx_t), 1)
    over = max(halo, halo_t)
    if over > r_loc:
        raise ValueError(
            f"block bandwidth {over} exceeds the {r_loc} block rows per "
            f"shard at n_shards={n_shards} — the ring halo exchange only "
            "reaches adjacent shards; use fewer shards or a larger tile"
        )

    def split(d, i, h):
        ds = np.stack([d[:, s * r_loc:(s + 1) * r_loc] for s in range(n_shards)])
        loc = np.stack([
            i[:, s * r_loc:(s + 1) * r_loc].astype(np.int64) - s * r_loc + h
            for s in range(n_shards)
        ])
        return ds, np.clip(loc, 0, r_loc + 2 * h - 1).astype(np.int32)

    data_s, idx_s = split(data, idx, halo)
    data_ts, idx_ts = split(data_t, idx_t, halo_t)
    return ShardedTiledBranch(
        data=jnp.asarray(data_s), idx=jnp.asarray(idx_s),
        data_t=jnp.asarray(data_ts), idx_t=jnp.asarray(idx_ts),
        halo=halo, halo_t=halo_t, n=branch.n, tile=branch.tile,
    )


def sharded_gathered_tiles_apply(
    mesh,
    sharded: ShardedTiledBranch,
    x_mat: jnp.ndarray,
    axis_name: str = "region",
) -> jnp.ndarray:
    """:func:`gathered_tiles_apply` with the block rows sharded over
    ``axis_name``: each shard halo-exchanges ``halo`` boundary signal
    blocks with its ring neighbors, gathers by its halo-local indices,
    and contracts its own tiles — no full-node all-gather. ``x_mat`` is
    the *permuted* ``(N, BF)`` signal; returns ``(K, N, BF)`` f32.

    The prepared backward mirrors the forward over the sharded
    pre-transposed stacks (``dx = sum_k A_k^T @ g_k`` at ``halo_t``),
    so the custom VJP keeps the no-scatter property of the single-device
    path on the mesh.
    """
    from stmgcn_tpu.parallel.halo import halo_exchange
    from stmgcn_tpu.utils.platform import shard_map

    data, idx = sharded.data, sharded.idx
    data_t, idx_t = sharded.data_t, sharded.idx_t
    n, tile = sharded.n, sharded.tile
    halo, halo_t = sharded.halo, sharded.halo_t
    r = sharded.n_shards * sharded.block_rows_local
    n_pad = r * tile
    x_dtype = x_mat.dtype
    bf = x_mat.shape[1]

    def local_fwd(d, i, x_blocks):
        # d: (1, K, r_loc, C, t, t); x_blocks: (r_loc, t, BF)
        xb = halo_exchange(x_blocks, halo, axis_name)
        gathered = jnp.take(xb, i[0], axis=0, mode="clip")  # (K, r_loc, C, t, BF)
        return jnp.einsum(
            "krcij,krcjf->krif", d[0], gathered,
            preferred_element_type=jnp.float32,
        )  # (K, r_loc, t, BF)

    def local_bwd(dt, it, g_blocks):
        # g_blocks: (r_loc, K, t, BF) — block rows lead for the exchange
        gb = halo_exchange(g_blocks, halo_t, axis_name)
        gb = gb.transpose(1, 0, 2, 3)  # (K, r_loc + 2h, t, BF)
        gathered = jax.vmap(
            lambda blocks, ii: jnp.take(blocks, ii, axis=0, mode="clip")
        )(gb, it[0])  # (K, r_loc, C_t, t, BF)
        return jnp.einsum(
            "krcij,krcjf->rif", dt[0], gathered,
            preferred_element_type=jnp.float32,
        )  # (r_loc, t, BF)

    fwd_sharded = shard_map(
        local_fwd,
        mesh=mesh,
        in_specs=(
            P(axis_name, None, None, None, None, None),
            P(axis_name, None, None, None),
            P(axis_name, None, None),
        ),
        out_specs=P(None, axis_name, None, None),
        check_vma=False,
    )
    bwd_sharded = shard_map(
        local_bwd,
        mesh=mesh,
        in_specs=(
            P(axis_name, None, None, None, None, None),
            P(axis_name, None, None, None),
            P(axis_name, None, None, None),
        ),
        out_specs=P(axis_name, None, None),
        check_vma=False,
    )

    def fwd_call(x):
        x_pad = jnp.pad(x, ((0, n_pad - x.shape[0]), (0, 0)))
        out = fwd_sharded(data, idx, x_pad.reshape(r, tile, bf))
        return out.reshape(-1, n_pad, bf)[:, :n]

    @jax.custom_vjp
    def _apply(x):
        return fwd_call(x)

    def _fwd(x):
        return fwd_call(x), None

    def _bwd(_res, g):
        g_pad = jnp.pad(g, ((0, 0), (0, n_pad - g.shape[1]), (0, 0)))
        g_blocks = g_pad.reshape(-1, r, tile, bf).transpose(1, 0, 2, 3)
        dx = bwd_sharded(data_t, idx_t, g_blocks).reshape(n_pad, bf)[:n]
        return (dx.astype(x_dtype),)

    _apply.defvjp(_fwd, _bwd)
    return _apply(x_mat)
