"""Block-sparse SpMM as a Pallas TPU kernel.

The framework's hot contraction is ``out = A_k @ x`` over the support
stack (``GCN.py:34-36`` in the reference, the fused einsum in
:mod:`stmgcn_tpu.ops.chebconv` here). Supports are *dense* ``(N, N)``
arrays in the reference — fine at N=58, quadratic waste at the scaled
50x50-grid config (N=2500) where a Chebyshev support of a rook grid has
<1% nonzero blocks (SURVEY.md §2 quirk 8, §7 hard part 1).

This module stores a support as **block-CSR with a uniform block-column
count**: the ``(N, N)`` matrix padded to 128-aligned tiles, only nonzero
``(128, 128)`` blocks kept, every block-row padded to the same number of
block-columns with zero blocks (index 0) so shapes are static. The kernel
walks ``grid = (block_rows, M_tiles, block_cols)`` with the block-column
index list scalar-prefetched (``PrefetchScalarGridSpec``) so the x-tile
DMA for block ``(r, c)`` fetches row-block ``idx[r, c]`` directly from
HBM — compute stays on the MXU via 128x128 ``jnp.dot`` tiles accumulated
in the revisited output block.

Gradient: supports are offline constants (never trained), so the custom
VJP only produces ``dx = A^T @ g``, reusing the kernel with the
pre-transposed block structure; ``None`` cotangents for the structure
arrays.

Off-TPU the kernel runs in Pallas interpret mode (tests), and
:func:`spmm_dense_reference` provides the einsum equivalent for
cross-checking.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu is importable off-TPU too; guard anyway for exotic builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = [
    "BlockSparse",
    "BlockSparseStack",
    "from_dense",
    "spmm",
    "spmm_dense_reference",
    "spmm_stack",
    "stack_from_dense",
]

TILE = 128


def _ceil_to(n: int, t: int) -> int:
    return -(-n // t) * t


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BlockSparse:
    """Uniform block-CSR support matrix plus its pre-transposed structure."""

    data: jnp.ndarray  # (R, C, TILE, TILE) nonzero blocks (zero-padded rows)
    idx: jnp.ndarray  # (R, C) int32 block-column indices
    data_t: jnp.ndarray  # transpose structure, same layout
    idx_t: jnp.ndarray
    n: int  # original (unpadded) dimension
    tile: int

    def tree_flatten(self):
        return (self.data, self.idx, self.data_t, self.idx_t), (self.n, self.tile)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, idx, data_t, idx_t = children
        n, tile = aux
        return cls(data=data, idx=idx, data_t=data_t, idx_t=idx_t, n=n, tile=tile)

    @property
    def block_rows(self) -> int:
        return self.data.shape[0]

    @property
    def block_cols_per_row(self) -> int:
        return self.data.shape[1]

    @property
    def density(self) -> float:
        """Stored fraction of the dense padded matrix (1.0 = no savings)."""
        return self.block_cols_per_row / self.block_rows

    @property
    def nbytes(self) -> int:
        return self.data.nbytes + self.idx.nbytes + self.data_t.nbytes + self.idx_t.nbytes


def _to_blocks(mat: np.ndarray, tile: int):
    """Dense (N, N) -> uniform block-CSR (data, idx) numpy arrays.
    Padding entries keep idx 0 with zero data: harmless accumulation."""
    return _to_blocks_rect(mat, tile)


def from_dense(mat, tile: int = TILE) -> BlockSparse:
    """Build a :class:`BlockSparse` (and its transpose structure) on the host."""
    mat = np.asarray(mat, dtype=np.float32)
    if mat.ndim != 2 or mat.shape[0] != mat.shape[1]:
        raise ValueError(f"support must be square (N, N), got {mat.shape}")
    data, idx = _to_blocks(mat, tile)
    data_t, idx_t = _to_blocks(mat.T, tile)
    return BlockSparse(
        data=jnp.asarray(data),
        idx=jnp.asarray(idx),
        data_t=jnp.asarray(data_t),
        idx_t=jnp.asarray(idx_t),
        n=mat.shape[0],
        tile=tile,
    )


def _spmm_kernel(idx_ref, data_ref, x_ref, out_ref):
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jnp.dot(
        data_ref[0, 0], x_ref[:], preferred_element_type=jnp.float32
    )


def _spmm_call(data, idx, x, n, tile, interpret):
    """Padded kernel invocation: data/idx block-CSR, x (N, M) -> (N, M)."""
    r, c_max = idx.shape
    n_pad = r * tile
    m = x.shape[1]
    tm = min(256, _ceil_to(m, TILE))
    m_pad = _ceil_to(m, tm)
    x_pad = jnp.zeros((n_pad, m_pad), x.dtype).at[: x.shape[0], :m].set(x)
    mb = m_pad // tm

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r, mb, c_max),
        in_specs=[
            pl.BlockSpec((1, 1, tile, tile), lambda i, j, c, idx_ref: (i, c, 0, 0)),
            pl.BlockSpec((tile, tm), lambda i, j, c, idx_ref: (idx_ref[i, c], j)),
        ],
        out_specs=pl.BlockSpec((tile, tm), lambda i, j, c, idx_ref: (i, j)),
    )
    out = pl.pallas_call(
        _spmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, m_pad), jnp.float32),
        interpret=interpret,
    )(idx, data, x_pad)
    return out[:n, :m]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _spmm_vjp(data, idx, data_t, idx_t, x, n, tile, interpret, x_dtype):
    return _spmm_call(data, idx, x, n, tile, interpret)


def _spmm_fwd(data, idx, data_t, idx_t, x, n, tile, interpret, x_dtype):
    return _spmm_call(data, idx, x, n, tile, interpret), (data_t, idx_t)


def _spmm_bwd(n, tile, interpret, x_dtype, res, g):
    data_t, idx_t = res
    dx = _spmm_call(data_t, idx_t, g, n, tile, interpret)
    # the kernel accumulates f32; the cotangent must come back in the
    # primal's dtype (passed statically — a traced dtype-carrier residual
    # would break shard_map's sharding checks) or a bf16 compute path
    # trips dtype checks upstream
    return (None, None, None, None, dx.astype(x_dtype))


_spmm_vjp.defvjp(_spmm_fwd, _spmm_bwd)


def spmm(bs: BlockSparse, x: jnp.ndarray, interpret: Optional[bool] = None) -> jnp.ndarray:
    """``A @ x`` for a block-sparse support; ``x`` is ``(N, M)``.

    ``interpret`` defaults to True off-TPU (CPU tests) and False on TPU.

    .. warning:: Gradients flow only to ``x``. The support's block values
       (``bs.data``/``bs.data_t``) get **zero cotangents by design** —
       supports are offline constants here (built once from adjacency,
       ``GCN.py:50-97``-equivalent). If supports ever become trainable, do
       NOT use this path: it would train silently with zero support
       gradients where the dense einsum path produces real ones. Extend
       ``_spmm_bwd`` with a ``dA = g @ x^T`` block-gather first.
    """
    if x.ndim != 2:
        raise ValueError(f"x must be (N, M), got {x.shape}")
    if x.shape[0] != bs.n:
        raise ValueError(f"x has {x.shape[0]} rows, support expects {bs.n}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _spmm_vjp(
        bs.data, bs.idx, bs.data_t, bs.idx_t, x, bs.n, bs.tile, interpret,
        jnp.dtype(x.dtype).name,
    )


def spmm_dense_reference(mat, x) -> jnp.ndarray:
    """Dense einsum equivalent, for cross-checking the kernel."""
    return jnp.asarray(mat) @ jnp.asarray(x)


# ---------------------------------------------------------------------------
# Fused K-support stack: all K propagations of one branch in ONE Pallas
# launch (the single-support path above launches K kernels from a Python
# loop — K dispatches plus a stack where the dense path is one einsum).
# Rectangular (n_rows, n_cols) structure is supported so a region shard's
# row strip of the supports works through the same kernel.
# ---------------------------------------------------------------------------


def _scan_blocks(mat: np.ndarray, tile: int):
    """Dense (Nr, Nc) -> padded (R, C, tile, tile) block view + (R, C)
    nonzero map (native fast-path scan, numpy fallback)."""
    from stmgcn_tpu import native

    nr, nc = mat.shape
    r, c = _ceil_to(nr, tile) // tile, _ceil_to(nc, tile) // tile
    padded = np.zeros((r * tile, c * tile), dtype=np.float32)
    padded[:nr, :nc] = mat
    blocks = padded.reshape(r, tile, c, tile).transpose(0, 2, 1, 3)
    nonzero = native.nonzero_block_scan_rect(padded, tile)
    if nonzero is None:
        nonzero = np.any(blocks != 0.0, axis=(2, 3))
    return blocks, nonzero


def _assemble_blocks(blocks, nonzero, c_max: int, tile: int):
    """Scanned blocks -> uniform block-CSR (data, idx) at an imposed width."""
    r = blocks.shape[0]
    need = max(int(nonzero.sum(axis=1).max()), 1)
    if need > c_max:
        raise ValueError(f"row needs {need} block-columns > imposed c_max {c_max}")
    data = np.zeros((r, c_max, tile, tile), dtype=np.float32)
    idx = np.zeros((r, c_max), dtype=np.int32)
    for i in range(r):
        cols = np.flatnonzero(nonzero[i])
        data[i, : len(cols)] = blocks[i, cols]
        idx[i, : len(cols)] = cols
    return data, idx


def _to_blocks_rect(mat: np.ndarray, tile: int, c_max: Optional[int] = None):
    """Dense (Nr, Nc) -> uniform block-CSR (data, idx); optionally padded to
    an externally-imposed ``c_max`` (for uniform stacking)."""
    blocks, nonzero = _scan_blocks(mat, tile)
    if c_max is None:
        c_max = max(int(nonzero.sum(axis=1).max()), 1)
    return _assemble_blocks(blocks, nonzero, c_max, tile)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BlockSparseStack:
    """K same-shape supports in uniform block-CSR, plus transposes.

    ``data`` ``(K, R, C, tile, tile)``, ``idx`` ``(K, R, C)``; the
    transpose structure mirrors it for the backward pass. ``n_rows`` /
    ``n_cols`` are the original (unpadded) dimensions.
    """

    data: jnp.ndarray
    idx: jnp.ndarray
    data_t: jnp.ndarray
    idx_t: jnp.ndarray
    n_rows: int
    n_cols: int
    tile: int

    def tree_flatten(self):
        return (self.data, self.idx, self.data_t, self.idx_t), (
            self.n_rows,
            self.n_cols,
            self.tile,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, idx, data_t, idx_t = children
        n_rows, n_cols, tile = aux
        return cls(data=data, idx=idx, data_t=data_t, idx_t=idx_t,
                   n_rows=n_rows, n_cols=n_cols, tile=tile)

    @property
    def n_supports(self) -> int:
        return self.data.shape[0]

    @property
    def density(self) -> float:
        return self.data.shape[2] / self.data_t.shape[1]

    @property
    def nbytes(self) -> int:
        return self.data.nbytes + self.idx.nbytes + self.data_t.nbytes + self.idx_t.nbytes


def stack_from_dense(mats, tile: int = TILE) -> BlockSparseStack:
    """Build a :class:`BlockSparseStack` from dense ``(K, Nr, Nc)`` supports.

    One ``c_max`` across the K supports (max row occupancy) keeps every
    kernel operand shape static.
    """
    mats = np.asarray(mats, dtype=np.float32)
    if mats.ndim != 3:
        raise ValueError(f"supports must be (K, Nr, Nc), got {mats.shape}")
    k = mats.shape[0]
    # one scan per support; c_max from the nonzero maps, assembly once
    fwd_scan = [_scan_blocks(mats[i], tile) for i in range(k)]
    bwd_scan = [_scan_blocks(np.ascontiguousarray(mats[i].T), tile) for i in range(k)]
    c_max = max(max(int(nz.sum(axis=1).max()), 1) for _, nz in fwd_scan)
    c_max_t = max(max(int(nz.sum(axis=1).max()), 1) for _, nz in bwd_scan)
    fwd = [_assemble_blocks(b, nz, c_max, tile) for b, nz in fwd_scan]
    bwd = [_assemble_blocks(b, nz, c_max_t, tile) for b, nz in bwd_scan]
    return BlockSparseStack(
        data=jnp.asarray(np.stack([d for d, _ in fwd])),
        idx=jnp.asarray(np.stack([i for _, i in fwd])),
        data_t=jnp.asarray(np.stack([d for d, _ in bwd])),
        idx_t=jnp.asarray(np.stack([i for _, i in bwd])),
        n_rows=mats.shape[1],
        n_cols=mats.shape[2],
        tile=tile,
    )


def _stack_fwd_kernel(idx_ref, data_ref, x_ref, out_ref):
    c = pl.program_id(3)

    @pl.when(c == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jnp.dot(
        data_ref[0, 0, 0], x_ref[0], preferred_element_type=jnp.float32
    )


def _stack_fwd_call(data, idx, x, n_rows, n_cols, tile, interpret):
    """One launch: ``out[k] = A_k @ x`` for all K supports. ``x``: (Nc, M)."""
    k, r, c_max = idx.shape
    m = x.shape[1]
    tm = min(256, _ceil_to(m, TILE))
    m_pad = _ceil_to(m, tm)
    x_pad = jnp.zeros((_ceil_to(n_cols, tile), m_pad), x.dtype)
    x_pad = x_pad.at[: x.shape[0], :m].set(x)
    mb = m_pad // tm

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k, r, mb, c_max),  # c innermost: out block revisited over c only
        in_specs=[
            pl.BlockSpec((1, 1, 1, tile, tile), lambda ki, i, j, c, idx_ref: (ki, i, c, 0, 0)),
            pl.BlockSpec((1, tile, tm), lambda ki, i, j, c, idx_ref: (0, idx_ref[ki, i, c], j)),
        ],
        out_specs=pl.BlockSpec((1, tile, tm), lambda ki, i, j, c, idx_ref: (ki, i, j)),
    )
    out = pl.pallas_call(
        _stack_fwd_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((k, r * tile, m_pad), jnp.float32),
        interpret=interpret,
    )(idx, data, x_pad[None])
    return out[:, :n_rows, :m]


def _stack_bwd_kernel(idx_t_ref, data_t_ref, g_ref, out_ref):
    ki = pl.program_id(2)
    c = pl.program_id(3)

    @pl.when((ki == 0) & (c == 0))
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jnp.dot(
        data_t_ref[0, 0, 0], g_ref[0], preferred_element_type=jnp.float32
    )


def _stack_bwd_call(data_t, idx_t, g, n_rows, n_cols, tile, interpret):
    """One launch: ``dx = sum_k A_k^T @ g_k``. ``g``: (K, Nr, M)."""
    k, r_t, c_max_t = idx_t.shape
    m = g.shape[2]
    tm = min(256, _ceil_to(m, TILE))
    m_pad = _ceil_to(m, tm)
    g_pad = jnp.zeros((k, _ceil_to(n_rows, tile), m_pad), g.dtype)
    g_pad = g_pad.at[:, : g.shape[1], :m].set(g)
    mb = m_pad // tm

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r_t, mb, k, c_max_t),  # (k, c) innermost: accumulate both into out
        in_specs=[
            pl.BlockSpec((1, 1, 1, tile, tile), lambda i, j, ki, c, idx_ref: (ki, i, c, 0, 0)),
            pl.BlockSpec((1, tile, tm), lambda i, j, ki, c, idx_ref: (ki, idx_ref[ki, i, c], j)),
        ],
        out_specs=pl.BlockSpec((tile, tm), lambda i, j, ki, c, idx_ref: (i, j)),
    )
    out = pl.pallas_call(
        _stack_bwd_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r_t * tile, m_pad), jnp.float32),
        interpret=interpret,
    )(idx_t, data_t, g_pad)
    return out[:n_cols, :m]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _spmm_stack_vjp(data, idx, data_t, idx_t, x, n_rows, n_cols, tile, interpret, x_dtype):
    return _stack_fwd_call(data, idx, x, n_rows, n_cols, tile, interpret)


def _spmm_stack_fwd(data, idx, data_t, idx_t, x, n_rows, n_cols, tile, interpret, x_dtype):
    return _stack_fwd_call(data, idx, x, n_rows, n_cols, tile, interpret), (
        data_t,
        idx_t,
    )


def _spmm_stack_bwd(n_rows, n_cols, tile, interpret, x_dtype, res, g):
    data_t, idx_t = res
    dx = _stack_bwd_call(data_t, idx_t, g, n_rows, n_cols, tile, interpret)
    # f32 kernel accumulation -> cotangent in the primal's dtype (see
    # _spmm_bwd)
    return (None, None, None, None, dx.astype(x_dtype))


_spmm_stack_vjp.defvjp(_spmm_stack_fwd, _spmm_stack_bwd)


def spmm_stack(
    bss: BlockSparseStack, x: jnp.ndarray, interpret: Optional[bool] = None
) -> jnp.ndarray:
    """``out[k] = A_k @ x`` for all K supports in one Pallas launch.

    ``x`` is ``(n_cols, M)``; returns ``(K, n_rows, M)`` in float32.
    Gradients flow to ``x`` only (support cotangents are intentionally
    dropped — see :func:`spmm`'s warning).
    """
    if x.ndim != 2:
        raise ValueError(f"x must be (N, M), got {x.shape}")
    if x.shape[0] != bss.n_cols:
        raise ValueError(f"x has {x.shape[0]} rows, supports expect {bss.n_cols}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _spmm_stack_vjp(
        bss.data, bss.idx, bss.data_t, bss.idx_t, x,
        bss.n_rows, bss.n_cols, bss.tile, interpret, jnp.dtype(x.dtype).name,
    )
