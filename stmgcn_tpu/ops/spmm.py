"""Block-sparse SpMM as a Pallas TPU kernel.

The framework's hot contraction is ``out = A_k @ x`` over the support
stack (``GCN.py:34-36`` in the reference, the fused einsum in
:mod:`stmgcn_tpu.ops.chebconv` here). Supports are *dense* ``(N, N)``
arrays in the reference — fine at N=58, quadratic waste at the scaled
50x50-grid config (N=2500) where a Chebyshev support of a rook grid has
<1% nonzero blocks (SURVEY.md §2 quirk 8, §7 hard part 1).

This module stores a support as **block-CSR with a uniform block-column
count**: the ``(N, N)`` matrix padded to 128-aligned tiles, only nonzero
``(128, 128)`` blocks kept, every block-row padded to the same number of
block-columns with zero blocks (index 0) so shapes are static. The kernel
walks ``grid = (block_rows, M_tiles, block_cols)`` with the block-column
index list scalar-prefetched (``PrefetchScalarGridSpec``) so the x-tile
DMA for block ``(r, c)`` fetches row-block ``idx[r, c]`` directly from
HBM — compute stays on the MXU via 128x128 ``jnp.dot`` tiles accumulated
in the revisited output block.

Gradient: supports are offline constants (never trained), so the custom
VJP only produces ``dx = A^T @ g``, reusing the kernel with the
pre-transposed block structure; ``None`` cotangents for the structure
arrays.

Off-TPU the kernel runs in Pallas interpret mode (tests), and
:func:`spmm_dense_reference` provides the einsum equivalent for
cross-checking.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu is importable off-TPU too; guard anyway for exotic builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["BlockSparse", "from_dense", "spmm", "spmm_dense_reference"]

TILE = 128


def _ceil_to(n: int, t: int) -> int:
    return -(-n // t) * t


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BlockSparse:
    """Uniform block-CSR support matrix plus its pre-transposed structure."""

    data: jnp.ndarray  # (R, C, TILE, TILE) nonzero blocks (zero-padded rows)
    idx: jnp.ndarray  # (R, C) int32 block-column indices
    data_t: jnp.ndarray  # transpose structure, same layout
    idx_t: jnp.ndarray
    n: int  # original (unpadded) dimension
    tile: int

    def tree_flatten(self):
        return (self.data, self.idx, self.data_t, self.idx_t), (self.n, self.tile)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, idx, data_t, idx_t = children
        n, tile = aux
        return cls(data=data, idx=idx, data_t=data_t, idx_t=idx_t, n=n, tile=tile)

    @property
    def block_rows(self) -> int:
        return self.data.shape[0]

    @property
    def block_cols_per_row(self) -> int:
        return self.data.shape[1]

    @property
    def density(self) -> float:
        """Stored fraction of the dense padded matrix (1.0 = no savings)."""
        return self.block_cols_per_row / self.block_rows

    @property
    def nbytes(self) -> int:
        return self.data.nbytes + self.idx.nbytes + self.data_t.nbytes + self.idx_t.nbytes


def _to_blocks(mat: np.ndarray, tile: int):
    """Dense (N, N) -> uniform block-CSR (data, idx) numpy arrays."""
    n_pad = _ceil_to(mat.shape[0], tile)
    padded = np.zeros((n_pad, n_pad), dtype=np.float32)
    padded[: mat.shape[0], : mat.shape[1]] = mat
    r = n_pad // tile
    blocks = padded.reshape(r, tile, r, tile).transpose(0, 2, 1, 3)
    from stmgcn_tpu import native

    nonzero = native.nonzero_block_scan(padded, tile)  # (R, R); None w/o lib
    if nonzero is None:
        nonzero = np.any(blocks != 0.0, axis=(2, 3))
    c_max = max(int(nonzero.sum(axis=1).max()), 1)
    data = np.zeros((r, c_max, tile, tile), dtype=np.float32)
    idx = np.zeros((r, c_max), dtype=np.int32)
    for i in range(r):
        cols = np.flatnonzero(nonzero[i])
        data[i, : len(cols)] = blocks[i, cols]
        idx[i, : len(cols)] = cols
        # padding entries keep idx 0 with zero data: harmless accumulation
    return data, idx


def from_dense(mat, tile: int = TILE) -> BlockSparse:
    """Build a :class:`BlockSparse` (and its transpose structure) on the host."""
    mat = np.asarray(mat, dtype=np.float32)
    if mat.ndim != 2 or mat.shape[0] != mat.shape[1]:
        raise ValueError(f"support must be square (N, N), got {mat.shape}")
    data, idx = _to_blocks(mat, tile)
    data_t, idx_t = _to_blocks(mat.T, tile)
    return BlockSparse(
        data=jnp.asarray(data),
        idx=jnp.asarray(idx),
        data_t=jnp.asarray(data_t),
        idx_t=jnp.asarray(idx_t),
        n=mat.shape[0],
        tile=tile,
    )


def _spmm_kernel(idx_ref, data_ref, x_ref, out_ref):
    c = pl.program_id(2)

    @pl.when(c == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jnp.dot(
        data_ref[0, 0], x_ref[:], preferred_element_type=jnp.float32
    )


def _spmm_call(data, idx, x, n, tile, interpret):
    """Padded kernel invocation: data/idx block-CSR, x (N, M) -> (N, M)."""
    r, c_max = idx.shape
    n_pad = r * tile
    m = x.shape[1]
    tm = min(256, _ceil_to(m, TILE))
    m_pad = _ceil_to(m, tm)
    x_pad = jnp.zeros((n_pad, m_pad), x.dtype).at[: x.shape[0], :m].set(x)
    mb = m_pad // tm

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r, mb, c_max),
        in_specs=[
            pl.BlockSpec((1, 1, tile, tile), lambda i, j, c, idx_ref: (i, c, 0, 0)),
            pl.BlockSpec((tile, tm), lambda i, j, c, idx_ref: (idx_ref[i, c], j)),
        ],
        out_specs=pl.BlockSpec((tile, tm), lambda i, j, c, idx_ref: (i, j)),
    )
    out = pl.pallas_call(
        _spmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, m_pad), jnp.float32),
        interpret=interpret,
    )(idx, data, x_pad)
    return out[:n, :m]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _spmm_vjp(data, idx, data_t, idx_t, x, n, tile, interpret):
    return _spmm_call(data, idx, x, n, tile, interpret)


def _spmm_fwd(data, idx, data_t, idx_t, x, n, tile, interpret):
    return _spmm_call(data, idx, x, n, tile, interpret), (data_t, idx_t)


def _spmm_bwd(n, tile, interpret, res, g):
    data_t, idx_t = res
    dx = _spmm_call(data_t, idx_t, g, n, tile, interpret)
    return (None, None, None, None, dx)


_spmm_vjp.defvjp(_spmm_fwd, _spmm_bwd)


def spmm(bs: BlockSparse, x: jnp.ndarray, interpret: Optional[bool] = None) -> jnp.ndarray:
    """``A @ x`` for a block-sparse support; ``x`` is ``(N, M)``.

    ``interpret`` defaults to True off-TPU (CPU tests) and False on TPU.

    .. warning:: Gradients flow only to ``x``. The support's block values
       (``bs.data``/``bs.data_t``) get **zero cotangents by design** —
       supports are offline constants here (built once from adjacency,
       ``GCN.py:50-97``-equivalent). If supports ever become trainable, do
       NOT use this path: it would train silently with zero support
       gradients where the dense einsum path produces real ones. Extend
       ``_spmm_bwd`` with a ``dA = g @ x^T`` block-gather first.
    """
    if x.ndim != 2:
        raise ValueError(f"x must be (N, M), got {x.shape}")
    if x.shape[0] != bs.n:
        raise ValueError(f"x has {x.shape[0]} rows, support expects {bs.n}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _spmm_vjp(bs.data, bs.idx, bs.data_t, bs.idx_t, x, bs.n, bs.tile, interpret)


def spmm_dense_reference(mat, x) -> jnp.ndarray:
    """Dense einsum equivalent, for cross-checking the kernel."""
    return jnp.asarray(mat) @ jnp.asarray(x)
