"""Offline graph-support construction for spectral / diffusion graph convolution.

TPU-native counterpart of the reference's adjacency preprocessor
(``/root/reference/GCN.py:50-135``, ``Adj_Preprocessor``). This stage runs
once per graph on the host (numpy, float64 internally for eigen-stability) and
produces a dense stack of ``(n_supports, N, N)`` support matrices that are
then placed on device once — the same host-compute/one-upload split as the
reference (``Main.py:48-55``).

Supported kernel families (parity with ``GCN.py:65-92``):

- ``chebyshev``   — Defferrard NIPS'16. ``K+1`` supports: Chebyshev
  polynomials of the rescaled normalized Laplacian.
- ``localpool``   — Kipf ICLR'17. One support: ``I + D^-1/2 A D^-1/2``.
- ``random_walk_diffusion`` — Li ICLR'18 (DCRNN). Diffusion steps on the
  random-walk transition matrix. The reference declares ``2K+1`` supports in
  the model (``STMGCN.py:87-88``) but its preprocessor only emits the
  forward ``K+1`` series because the bidirectional branch is commented out
  (``GCN.py:82-90``) — so diffusion kernels crash the reference's support
  assert (``GCN.py:31``). Here the bidirectional series is implemented and is
  the default, making the declared count and the built count agree
  (documented deviation; ``bidirectional=False`` recovers the forward-only
  ``K+1`` series).

Deviations from the reference, on purpose:

- Isolated nodes (zero degree) produce ``inf`` in the reference's
  ``D^-1/2`` (``GCN.py:109``) and propagate NaN; here the inverse degree is
  zeroed, matching what the reference already does for random-walk
  normalization (``GCN.py:102``).
- ``torch.eig`` (``GCN.py:117``) becomes ``numpy.linalg.eigvalsh`` for
  symmetric Laplacians, general ``eigvals`` otherwise, and a matrix-free
  power iteration above ``POWER_ITERATION_THRESHOLD`` nodes so the scaled
  50x50-grid (N=2500) config never pays a dense O(N^3) eigendecomposition.
  The reference's fall-back to ``lambda_max = 2`` on non-convergence
  (``GCN.py:119-121``) is preserved.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

__all__ = [
    "SupportConfig",
    "build_supports",
    "chebyshev_polynomials",
    "chebyshev_supports",
    "diffusion_supports",
    "localpool_supports",
    "max_eigenvalue",
    "normalized_laplacian",
    "random_walk_normalize",
    "rescale_laplacian",
    "support_count",
    "symmetric_normalize",
]

KERNEL_TYPES = ("chebyshev", "localpool", "random_walk_diffusion")

#: Above this node count, ``max_eigenvalue(method="auto")`` switches from a
#: dense eigendecomposition to power iteration.
POWER_ITERATION_THRESHOLD = 512


def _as_matrix(adj) -> np.ndarray:
    a = np.asarray(adj, dtype=np.float64)
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError(f"adjacency must be a square (N, N) matrix, got {a.shape}")
    return a


def symmetric_normalize(adj) -> np.ndarray:
    """``D^-1/2 A D^-1/2`` (reference: ``GCN.py:107-111``), zeroing isolated rows."""
    a = _as_matrix(adj)
    deg = a.sum(axis=1)
    with np.errstate(divide="ignore"):
        d_inv_sqrt = np.power(deg, -0.5)
    d_inv_sqrt[~np.isfinite(d_inv_sqrt)] = 0.0
    return (a * d_inv_sqrt[:, None]) * d_inv_sqrt[None, :]


def random_walk_normalize(adj) -> np.ndarray:
    """Row-stochastic ``D^-1 A`` (reference: ``GCN.py:99-105``)."""
    a = _as_matrix(adj)
    deg = a.sum(axis=1)
    with np.errstate(divide="ignore"):
        d_inv = np.power(deg, -1.0)
    d_inv[~np.isfinite(d_inv)] = 0.0
    return a * d_inv[:, None]


def normalized_laplacian(adj) -> np.ndarray:
    """``L = I - D^-1/2 A D^-1/2`` (reference: ``GCN.py:73``)."""
    a_norm = symmetric_normalize(adj)
    return np.eye(a_norm.shape[0]) - a_norm


def _power_iteration_lambda_max(mat: np.ndarray) -> float:
    """Largest-magnitude eigenvalue, matrix-free.

    Uses scipy's Lanczos/Arnoldi when available (robust to the
    nearly-degenerate top eigenpairs common in normalized Laplacians of dense
    graphs, where plain power iteration stalls); falls back to plain power
    iteration otherwise.
    """
    if mat.shape[0] > 2:  # ARPACK needs k < N-1; tiny systems go dense anyway
        try:
            from scipy.sparse.linalg import eigs, eigsh

            if np.allclose(mat, mat.T, atol=1e-10):
                return float(eigsh(mat, k=1, which="LA", return_eigenvectors=False)[0])
            return float(eigs(mat, k=1, which="LR", return_eigenvectors=False)[0].real)
        except ImportError:
            pass
    rng = np.random.default_rng(0)
    v = rng.standard_normal(mat.shape[0])
    v /= np.linalg.norm(v)
    lam = 0.0
    w = mat @ v
    for _ in range(5000):
        nw = np.linalg.norm(w)
        if nw == 0.0:
            return 0.0
        v = w / nw
        w = mat @ v
        lam_new = float(v @ w)
        if abs(lam_new - lam) < 1e-9 * max(1.0, abs(lam_new)):
            return lam_new
        lam = lam_new
    return lam


def max_eigenvalue(mat, fallback: float = 2.0, method: str = "auto") -> float:
    """Largest real eigenvalue of ``mat``; ``fallback`` on failure.

    Reference: ``GCN.py:113-121`` (``torch.eig`` real parts, ``lambda_max=2``
    on non-convergence). ``method``: ``"dense"``, ``"power"``, or ``"auto"``
    (power iteration above :data:`POWER_ITERATION_THRESHOLD` nodes).
    """
    m = _as_matrix(mat)
    if method not in ("auto", "dense", "power"):
        raise ValueError(f"unknown method {method!r}")
    if method == "auto":
        method = "power" if m.shape[0] > POWER_ITERATION_THRESHOLD else "dense"
    if m.shape[0] <= 2:
        # Power iteration cannot separate equal-magnitude opposite-sign
        # eigenvalues (e.g. [[0,1],[1,0]]); tiny systems are free to solve
        # densely.
        method = "dense"
    # Broad except on purpose: besides LinAlgError, scipy's ARPACK raises its
    # own no-convergence error type; all failure modes take the reference's
    # lambda_max=2 fallback path (GCN.py:119-121).
    try:
        if method == "power":
            return float(_power_iteration_lambda_max(m))
        if np.allclose(m, m.T, atol=1e-10):
            return float(np.linalg.eigvalsh(m).max())
        return float(np.linalg.eigvals(m).real.max())
    except Exception:
        return float(fallback)


def rescale_laplacian(lap, lambda_max: float | None = None) -> np.ndarray:
    """``2 L / lambda_max - I``, mapping the spectrum into ``[-1, 1]``.

    Reference: ``GCN.py:113-123``. If ``lambda_max`` is None it is computed
    via :func:`max_eigenvalue` (with the same ``lambda_max=2`` fallback).
    """
    lap = _as_matrix(lap)
    if lambda_max is None:
        lambda_max = max_eigenvalue(lap)
    return (2.0 / lambda_max) * lap - np.eye(lap.shape[0])


def chebyshev_polynomials(x, K: int) -> np.ndarray:
    """Stack ``[T_0, ..., T_K]`` of Chebyshev polynomials of ``x``.

    ``T_0 = I``, ``T_1 = x``, ``T_k = 2 x T_{k-1} - T_{k-2}`` — the same
    recursion (including the left-multiplication order) as ``GCN.py:125-135``.
    Returns ``(K+1, N, N)``.
    """
    x = _as_matrix(x)
    if K < 0:
        raise ValueError("K must be >= 0")
    n = x.shape[0]
    out = [np.eye(n)]
    if K >= 1:
        out.append(x)
    for k in range(2, K + 1):
        out.append(2.0 * (x @ out[k - 1]) - out[k - 2])
    return np.stack(out, axis=0)


def chebyshev_supports(adj, K: int, lambda_max: float | None = None) -> np.ndarray:
    """``(K+1, N, N)`` Chebyshev supports of the rescaled normalized Laplacian.

    Reference pipeline: ``GCN.py:66,73-75`` (symmetric normalize -> ``I - A``
    -> eigen-rescale -> Chebyshev recursion -> stack at ``GCN.py:95``).
    """
    lap = normalized_laplacian(adj)
    lap_rescaled = rescale_laplacian(lap, lambda_max=lambda_max)
    return chebyshev_polynomials(lap_rescaled, K)


def localpool_supports(adj) -> np.ndarray:
    """``(1, N, N)`` Kipf local-pooling support ``I + D^-1/2 A D^-1/2``.

    Reference: ``GCN.py:68-70``.
    """
    a_norm = symmetric_normalize(adj)
    return (np.eye(a_norm.shape[0]) + a_norm)[None]


def diffusion_supports(adj, K: int, bidirectional: bool = True) -> np.ndarray:
    """Random-walk diffusion supports (DCRNN).

    Forward series: Chebyshev-style recursion on ``P_fwd^T`` where
    ``P_fwd = D^-1 A`` (reference: ``GCN.py:80-81``). With
    ``bidirectional=True`` (default) the backward series on ``(D'^-1 A^T)^T``
    is appended, dropping its order-0 identity — yielding ``2K+1`` supports,
    the count the reference model declares (``STMGCN.py:88``) but never
    builds because its bidirectional branch is commented out
    (``GCN.py:82-90``).
    """
    a = _as_matrix(adj)
    fwd = chebyshev_polynomials(random_walk_normalize(a).T, K)
    if not bidirectional:
        return fwd
    bwd = chebyshev_polynomials(random_walk_normalize(a.T).T, K)
    return np.concatenate([fwd, bwd[1:]], axis=0)


def support_count(kernel_type: str, K: int, bidirectional: bool = True) -> int:
    """Number of supports a kernel config produces.

    Mirrors the reference's ``ST_MGCN.get_support_K`` (``STMGCN.py:80-91``)
    with the diffusion row made consistent with what is actually built (see
    :func:`diffusion_supports`).
    """
    if kernel_type == "localpool":
        if K != 1:
            raise ValueError("localpool requires K == 1")  # STMGCN.py:83
        return 1
    if kernel_type == "chebyshev":
        return K + 1
    if kernel_type == "random_walk_diffusion":
        return 2 * K + 1 if bidirectional else K + 1
    raise ValueError(f"kernel_type must be one of {KERNEL_TYPES}, got {kernel_type!r}")


def build_supports(
    adj,
    kernel_type: str = "chebyshev",
    K: int = 2,
    *,
    bidirectional: bool = True,
    lambda_max: float | None = None,
    dtype=np.float32,
) -> np.ndarray:
    """Dispatch to the requested support family; returns ``(n_supports, N, N)``.

    Parity with ``Adj_Preprocessor.process`` (``GCN.py:57-97``), with the
    output cast to the on-device dtype (default float32) after float64 host
    computation.
    """
    if kernel_type == "chebyshev":
        out = chebyshev_supports(adj, K, lambda_max=lambda_max)
    elif kernel_type == "localpool":
        # Strict where the reference is split: its preprocessor silently
        # coerces K -> 1 (GCN.py:54) while its model asserts K == 1
        # (STMGCN.py:83). One consistent rule here: reject early.
        if K != 1:
            raise ValueError("localpool requires K == 1")
        out = localpool_supports(adj)
    elif kernel_type == "random_walk_diffusion":
        out = diffusion_supports(adj, K, bidirectional=bidirectional)
    else:
        raise ValueError(f"kernel_type must be one of {KERNEL_TYPES}, got {kernel_type!r}")
    expected = support_count(kernel_type, K, bidirectional)
    assert out.shape[0] == expected, (out.shape, expected)
    return out.astype(dtype)


@dataclasses.dataclass(frozen=True)
class SupportConfig:
    """Static graph-kernel configuration (reference: ``Main.py:15`` dict).

    ``kernel_type`` in {chebyshev, localpool, random_walk_diffusion}; ``K`` is
    the max polynomial order / diffusion step count.
    """

    kernel_type: str = "chebyshev"
    K: int = 2
    bidirectional: bool = True

    def __post_init__(self):
        if self.kernel_type not in KERNEL_TYPES:
            raise ValueError(f"kernel_type must be one of {KERNEL_TYPES}, got {self.kernel_type!r}")
        if self.kernel_type == "localpool" and self.K != 1:
            raise ValueError("localpool requires K == 1")  # STMGCN.py:83
        if self.K < 0:
            raise ValueError("K must be >= 0")

    @property
    def n_supports(self) -> int:
        return support_count(self.kernel_type, self.K, self.bidirectional)

    def build(self, adj, *, lambda_max: float | None = None, dtype=np.float32) -> np.ndarray:
        return build_supports(
            adj,
            self.kernel_type,
            self.K,
            bidirectional=self.bidirectional,
            lambda_max=lambda_max,
            dtype=dtype,
        )

    def build_all(self, adjs: Sequence, *, dtype=np.float32) -> np.ndarray:
        """Build and stack supports for M graphs -> ``(M, n_supports, N, N)``.

        The reference keeps a Python list of per-graph supports
        (``Main.py:48-55``); stacking them lets the model vmap over the M
        branches instead of looping (``STMGCN.py:112-115``).
        """
        return np.stack([self.build(a, dtype=dtype) for a in adjs], axis=0)
