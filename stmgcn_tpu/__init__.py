"""stmgcn-tpu: a TPU-native spatiotemporal multi-graph convolution framework.

A from-scratch JAX/XLA/Pallas/pjit framework with the capabilities of the
PyTorch reference `underdoc-wang/ST-MGCN` (AAAI'19 "Spatiotemporal Multi-Graph
Convolution Network for Ride-Hailing Demand Forecasting"), redesigned
TPU-first:

- ``stmgcn_tpu.ops``      graph-support construction, fused Chebyshev graph
                          convolution, ``lax.scan`` LSTM, Pallas kernels.
- ``stmgcn_tpu.data``     NPZ demand loading, normalization, vectorized
                          serial/daily/weekly windowing, splits, batching.
- ``stmgcn_tpu.models``   contextual-gated LSTM and the ST-MGCN flagship
                          model (M graph branches vmapped, not looped).
- ``stmgcn_tpu.parallel`` device mesh, sharding specs, halo exchange for the
                          partitioned region axis, collective helpers.
- ``stmgcn_tpu.train``    optax optimization, jitted train/eval steps,
                          best-on-validation checkpointing, early stopping,
                          resumable training state.
- ``stmgcn_tpu.cli``      typed configuration presets and the command line
                          entry point.

Layer map and parity citations against the reference live in ``SURVEY.md`` at
the repository root; every public module docstring cites the reference
behavior (``file:line`` under ``/root/reference``) it is equivalent to.
"""

__version__ = "0.1.0"


def __getattr__(name):
    """Lazy top-level API: ``stmgcn_tpu.preset(...)``, ``stmgcn_tpu.Forecaster``
    etc., without importing jax at package-import time."""
    lazy = {
        "ExperimentConfig": "stmgcn_tpu.config",
        "preset": "stmgcn_tpu.config",
        "PRESETS": "stmgcn_tpu.config",
        "build_trainer": "stmgcn_tpu.experiment",
        "run": "stmgcn_tpu.experiment",
        "Forecaster": "stmgcn_tpu.inference",
        "ExportedForecaster": "stmgcn_tpu.export",
        "export_forecaster": "stmgcn_tpu.export",
        "STMGCN": "stmgcn_tpu.models",
        "Trainer": "stmgcn_tpu.train",
    }
    if name in lazy:
        import importlib

        return getattr(importlib.import_module(lazy[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
