#!/usr/bin/env bash
# CI lint gate: stmgcn lint (whole-program + contracts) plus ruff when
# the image ships it, plus a traced smoke-training run that must report
# ZERO JAX recompiles after warmup (the dynamic counterpart of the
# static recompile-hazard rule). Stdout is the contract — EXACTLY one
# JSON line:
#
#   {"gate": "PASS"|"FAIL", "lint": {"exit": N, "errors": N,
#    "warnings": N, "version": N}, "concurrency": {"exit": N,
#    "classes": N|null, "typed_edges": N|null, "findings": N|null},
#    "ruff": {"available": true|false, "exit": N|null},
#    "obs": {"exit": N, "recompiles_after_warmup": N|null,
#    "trace_spans": N|null},
#    "health": {"exit": N, "nonfinite": N|null, "records": N|null,
#    "findings": N|null},
#    "continual": {"exit": N, "promotions": N|null, "rejections": N|null,
#    "nonfinite": N|null},
#    "federation": {"exit": N, "hung": N|null, "cross_generation": N|null,
#    "kills": N|null, "recovered": N|null, "cities": N|null,
#    "findings": N|null},
#    "spmd": {"exit": N, "programs": N|null, "collectives": N|null,
#    "findings": N|null},
#    "spmd_exec": {"exit": N, "program": str|null, "n_devices": N|null,
#    "parity_drift": F|null, "recompiles_after_warmup": N|null},
#    "precision": {"exit": N, "programs": N|null, "bf16_programs": N|null,
#    "sites": N|null, "findings": N|null}}
#
# The "concurrency" section is explicit evidence the static concurrency
# pass (unguarded-attr / lock-order-cycle / condvar-discipline /
# thread-lifecycle) actually ran repo-wide with the class model built:
# a refactor that silently emptied the class database would show
# classes=0 here and fail the gate even with zero findings.
#
# Everything human-readable (full reports, ruff listing) goes to stderr.
# Exit 0 iff the gate is PASS: lint found no unsuppressed errors AND
# ruff (when available) is clean AND the traced smoke run compiled
# nothing after its warmup mark. The stdout shape is pinned by a
# slow-tier test (tests/test_analysis.py::TestLintGateScript).
set -u -o pipefail

cd "$(dirname "$0")/.."

PY=${PYTHON:-python}

lint_json=$("$PY" -m stmgcn_tpu.cli lint --format json 2>>/dev/stderr)
lint_exit=$?
printf '%s\n' "$lint_json" >&2

# Concurrency pass evidence: re-run the four rules standalone and
# report the class-model scale the verdict rests on.
conc_json=$("$PY" - <<'EOF' 2>>/dev/stderr
import json
import os

import stmgcn_tpu
from stmgcn_tpu.analysis.concurrency_check import check_concurrency
from stmgcn_tpu.analysis.program_db import ProgramDB

root = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
db = ProgramDB.from_root(root, package="stmgcn_tpu", type_informed=True)
findings = check_concurrency(db)
for f in findings:
    print(str(f), file=__import__("sys").stderr)
print(json.dumps({
    "classes": len(db.classes),
    "typed_edges": len(db.typed_edges),
    "findings": len(findings),
}))
EOF
)
conc_exit=$?
printf '%s\n' "$conc_json" >&2

ruff_available=false
ruff_exit=null
if command -v ruff >/dev/null 2>&1; then
    ruff_available=true
    ruff check . >&2
    ruff_exit=$?
fi

# Traced smoke run: tiny resident-superstep training with the span
# tracer + jax.monitoring listener armed AND numeric-health telemetry
# on at every_k=1; after warmup (first epoch) every compile is a
# runtime recompile and fails the gate, and any nonfinite grad/loss
# count the health layer saw during the smoke train fails it too. The
# health-overhead config contract (HealthConfig.violations() per
# preset) rides the same interpreter.
obs_json=$(JAX_PLATFORMS=cpu "$PY" - <<'EOF' 2>>/dev/stderr
import json
import os
import tempfile

from stmgcn_tpu.obs import jaxmon
from stmgcn_tpu.obs import trace as obs_trace

obs_trace.configure()
jaxmon.install()

from stmgcn_tpu.analysis.health_check import check_health_overhead
from stmgcn_tpu.config import preset
from stmgcn_tpu.experiment import build_trainer
from stmgcn_tpu.obs.health import load_health
from stmgcn_tpu.obs.registry import REGISTRY

with tempfile.TemporaryDirectory(prefix="stmgcn_gate_") as tmp:
    cfg = preset("smoke")
    cfg.data.rows = 5
    cfg.data.n_timesteps = 24 * 7 * 2 + 60
    cfg.train.epochs = 2
    cfg.train.batch_size = 8
    cfg.train.data_placement = "resident"
    cfg.train.steps_per_superstep = 2
    cfg.train.out_dir = tmp
    cfg.health.enabled = True
    cfg.health.out = os.path.join(tmp, "health.jsonl")
    trainer = build_trainer(cfg, verbose=False)
    trainer.train()
    trainer.flush_checkpoints()
    n_spans = obs_trace.active_tracer().export_jsonl(
        os.path.join(tmp, "trace.jsonl")
    )
    _, health_records = load_health(cfg.health.out)
snap = jaxmon.snapshot()
nonfinite = int(
    REGISTRY.counter("train.health.nonfinite_grads").value
    + REGISTRY.counter("train.health.nonfinite_loss").value
)
print(json.dumps({
    "recompiles_after_warmup": snap["recompiles_after_warmup"],
    "compilations": snap["compilations"],
    "trace_spans": n_spans,
    "health_nonfinite": nonfinite,
    "health_records": len(health_records),
    "health_findings": len(check_health_overhead()),
}))
EOF
)
obs_exit=$?
printf '%s\n' "$obs_json" >&2

# Closed-loop continual drill: live ring ingest + a triggered fine-tune
# + the guarded promotion gate, with one poisoned candidate. The gate
# requires exactly one promotion, exactly one typed rejection, and a
# ZERO-nonfinite health stream on the clean fine-tune — the loop's
# supervision story exercised end-to-end, not asserted from unit tests
# alone.
continual_json=$(JAX_PLATFORMS=cpu "$PY" - <<'EOF' 2>>/dev/stderr
import json
import tempfile

from stmgcn_tpu.train.continual import closed_loop_smoke

with tempfile.TemporaryDirectory(prefix="stmgcn_continual_") as tmp:
    out = closed_loop_smoke(tmp)
print(json.dumps(out))
EOF
)
continual_exit=$?
printf '%s\n' "$continual_json" >&2

# Federation kill-and-recover drill: a short M=2 tier soak over real
# engines runs the full fault schedule (poisoned candidate, replica
# kill, herd spike, hang-on-drain) open-loop. The gate fails on any
# hung caller, any cross-generation response, a kill drill that never
# fired, cities left unserveable after recovery, or federation-config
# contract findings on the shipped presets.
federation_json=$(JAX_PLATFORMS=cpu "$PY" - <<'EOF' 2>>/dev/stderr
import json

from stmgcn_tpu.analysis.federation_check import check_federation_config
from stmgcn_tpu.serving.bench import run_federation_soak, train_throwaway

fc, supports = train_throwaway(rows=3, epochs=1)
rec = run_federation_soak(fc, supports, replicas=2, soak_seconds=0.4,
                          buckets=(1, 2, 4))
print(json.dumps({
    "hung": rec["soak"]["hung_clients"],
    "cross_generation": rec["soak"]["cross_generation"],
    "kills": rec["router"]["kills"],
    "recovered": rec["recovery"]["cities_serveable"],
    "cities": rec["recovery"]["cities_total"],
    "findings": len(check_federation_config()),
}))
EOF
)
federation_exit=$?
printf '%s\n' "$federation_json" >&2

# SPMD contract evidence: the pass must have lowered every probe program
# (zero programs means the probes silently stopped building — the same
# empty-database failure mode the concurrency section guards against)
# and observed a non-trivially-collective-free fleet with zero findings.
spmd_json=$("$PY" - <<'EOF' 2>>/dev/stderr
import json

from stmgcn_tpu.utils.platform import force_host_platform

force_host_platform("cpu", n_devices=8)

from stmgcn_tpu.analysis.spmd_check import spmd_summary

print(json.dumps(spmd_summary()))
EOF
)
spmd_exit=$?
printf '%s\n' "$spmd_json" >&2

# Precision dataflow evidence: the dtype walk must have covered every
# registered contract program (zero programs walked means the registry
# silently emptied) and judged every classified site against the
# declared PrecisionPolicy with zero findings.
precision_json=$("$PY" - <<'EOF' 2>>/dev/stderr
import json

from stmgcn_tpu.utils.platform import force_host_platform

force_host_platform("cpu", n_devices=8)

from stmgcn_tpu.analysis.precision_check import precision_summary

print(json.dumps(precision_summary()))
EOF
)
precision_exit=$?
printf '%s\n' "$precision_json" >&2

# SPMD execution evidence: one short composed superstep actually RUNS on
# the 8-virtual-device substrate — the executed counterpart of the
# static spmd section above (whose findings==0 check covers the same
# composed programs). The dp x branch preset trains against its
# single-device twin; the gate fails on any parity drift (the program is
# bit-exact by contract, tests/test_multichip_exec.py) or any compile
# after the composed trainer's warmup epoch.
spmd_exec_json=$("$PY" - <<'EOF' 2>>/dev/stderr
import json
import tempfile

from stmgcn_tpu.utils.platform import force_host_platform

force_host_platform("cpu", n_devices=8)

import jax
import numpy as np

from stmgcn_tpu.obs import jaxmon

jaxmon.install()

from stmgcn_tpu.parallel.compose import composed_trainer, parity_twin_kind

with tempfile.TemporaryDirectory(prefix="stmgcn_spmd_exec_") as tmp:
    # twin first: the composed trainer's own end-of-first-epoch warmup
    # mark then re-baselines the compile count, so only compiles during
    # the composed program's steady-state epoch can count as recompiles
    twin = composed_trainer(
        "branchpar", twin=parity_twin_kind("branchpar"),
        out_dir=tmp + "/twin",
    )
    h_twin = twin.train()
    composed = composed_trainer("branchpar", out_dir=tmp + "/mesh")
    h_mesh = composed.train()
    drift = max(
        float(np.max(np.abs(
            np.asarray(h_mesh[m]) - np.asarray(h_twin[m])
        )))
        for m in ("train", "validate")
    )
print(json.dumps({
    "program": composed.train_path,
    "n_devices": jax.device_count(),
    "parity_drift": drift,
    "recompiles_after_warmup": jaxmon.snapshot()["recompiles_after_warmup"],
}))
EOF
)
spmd_exec_exit=$?
printf '%s\n' "$spmd_exec_json" >&2

LINT_JSON="$lint_json" LINT_EXIT="$lint_exit" \
CONC_JSON="$conc_json" CONC_EXIT="$conc_exit" \
RUFF_AVAILABLE="$ruff_available" RUFF_EXIT="$ruff_exit" \
OBS_JSON="$obs_json" OBS_EXIT="$obs_exit" \
CONTINUAL_JSON="$continual_json" CONTINUAL_EXIT="$continual_exit" \
FEDERATION_JSON="$federation_json" FEDERATION_EXIT="$federation_exit" \
SPMD_JSON="$spmd_json" SPMD_EXIT="$spmd_exit" \
SPMD_EXEC_JSON="$spmd_exec_json" SPMD_EXEC_EXIT="$spmd_exec_exit" \
PRECISION_JSON="$precision_json" PRECISION_EXIT="$precision_exit" \
"$PY" - <<'EOF'
import json
import os
import sys

try:
    report = json.loads(os.environ["LINT_JSON"])
except ValueError:
    report = {}
lint_exit = int(os.environ["LINT_EXIT"])
ruff_available = os.environ["RUFF_AVAILABLE"] == "true"
ruff_exit = None if os.environ["RUFF_EXIT"] == "null" else int(os.environ["RUFF_EXIT"])
try:
    obs = json.loads(os.environ["OBS_JSON"])
except ValueError:
    obs = {}
obs_exit = int(os.environ["OBS_EXIT"])
recompiles = obs.get("recompiles_after_warmup")
try:
    conc = json.loads(os.environ["CONC_JSON"])
except ValueError:
    conc = {}
conc_exit = int(os.environ["CONC_EXIT"])
try:
    continual = json.loads(os.environ["CONTINUAL_JSON"])
except ValueError:
    continual = {}
continual_exit = int(os.environ["CONTINUAL_EXIT"])
try:
    federation = json.loads(os.environ["FEDERATION_JSON"])
except ValueError:
    federation = {}
federation_exit = int(os.environ["FEDERATION_EXIT"])
try:
    spmd = json.loads(os.environ["SPMD_JSON"])
except ValueError:
    spmd = {}
spmd_exit = int(os.environ["SPMD_EXIT"])
try:
    spmd_exec = json.loads(os.environ["SPMD_EXEC_JSON"])
except ValueError:
    spmd_exec = {}
spmd_exec_exit = int(os.environ["SPMD_EXEC_EXIT"])
try:
    precision = json.loads(os.environ["PRECISION_JSON"])
except ValueError:
    precision = {}
precision_exit = int(os.environ["PRECISION_EXIT"])

ok = lint_exit == 0 and report.get("errors") == 0
# concurrency pass must have run over a real class model and come back
# clean — classes == 0 means the database silently went empty
ok = ok and conc_exit == 0 and conc.get("findings") == 0
ok = ok and (conc.get("classes") or 0) > 0
if ruff_available:
    ok = ok and ruff_exit == 0
ok = ok and obs_exit == 0 and recompiles == 0
# numeric health: the smoke train must have produced records with ZERO
# nonfinite grad/loss counts, and every preset's health config must
# pass the health-overhead contract
ok = ok and obs.get("health_nonfinite") == 0
ok = ok and (obs.get("health_records") or 0) > 0
ok = ok and obs.get("health_findings") == 0
# continual loop: the clean fine-tune promoted (exactly one), the
# poisoned one rejected at the gate (exactly one), zero nonfinite
# observations in the clean health stream
ok = ok and continual_exit == 0
ok = ok and continual.get("promotions") == 1
ok = ok and continual.get("rejections") == 1
ok = ok and continual.get("nonfinite") == 0
# federation drill: no caller hung, no mixed-generation response left
# the router, the scheduled replica kill actually fired, every city is
# serveable again after the drills, and the shipped presets pass the
# federation-config topology contract
ok = ok and federation_exit == 0
ok = ok and federation.get("hung") == 0
ok = ok and federation.get("cross_generation") == 0
ok = ok and federation.get("kills") == 1
ok = ok and federation.get("recovered") is not None
ok = ok and federation.get("recovered") == federation.get("cities")
ok = ok and federation.get("findings") == 0
# spmd contract pass: every probe program lowered (zero programs means
# the probes stopped building) with zero collective-manifest/wire/
# footprint findings
ok = ok and spmd_exit == 0
ok = ok and (spmd.get("programs") or 0) > 0
ok = ok and spmd.get("findings") == 0
# spmd execution smoke: the composed superstep actually ran on 8
# devices as the fused mesh program (not a fallback), bit-identical to
# its single-device twin, with zero compiles after its warmup epoch
ok = ok and spmd_exec_exit == 0
ok = ok and spmd_exec.get("program") == "series_superstep"
ok = ok and spmd_exec.get("n_devices") == 8
ok = ok and spmd_exec.get("parity_drift") == 0.0
ok = ok and spmd_exec.get("recompiles_after_warmup") == 0
# precision dataflow pass: every registered contract program dtype-walked
# (zero programs means the precision certification silently hollowed out)
# with zero policy/accumulator/cast findings — INCLUDING the bf16 twin
# programs (zero bf16 programs means the mixed-precision certification
# dropped out of the registry)
ok = ok and precision_exit == 0
ok = ok and (precision.get("programs") or 0) > 0
ok = ok and (precision.get("bf16_programs") or 0) > 0
ok = ok and precision.get("findings") == 0
print(json.dumps({
    "gate": "PASS" if ok else "FAIL",
    "lint": {
        "exit": lint_exit,
        "errors": report.get("errors"),
        "warnings": report.get("warnings"),
        "version": report.get("version"),
    },
    "concurrency": {
        "exit": conc_exit,
        "classes": conc.get("classes"),
        "typed_edges": conc.get("typed_edges"),
        "findings": conc.get("findings"),
    },
    "ruff": {"available": ruff_available, "exit": ruff_exit},
    "obs": {
        "exit": obs_exit,
        "recompiles_after_warmup": recompiles,
        "trace_spans": obs.get("trace_spans"),
    },
    "health": {
        "exit": obs_exit,
        "nonfinite": obs.get("health_nonfinite"),
        "records": obs.get("health_records"),
        "findings": obs.get("health_findings"),
    },
    "continual": {
        "exit": continual_exit,
        "promotions": continual.get("promotions"),
        "rejections": continual.get("rejections"),
        "nonfinite": continual.get("nonfinite"),
    },
    "federation": {
        "exit": federation_exit,
        "hung": federation.get("hung"),
        "cross_generation": federation.get("cross_generation"),
        "kills": federation.get("kills"),
        "recovered": federation.get("recovered"),
        "cities": federation.get("cities"),
        "findings": federation.get("findings"),
    },
    "spmd": {
        "exit": spmd_exit,
        "programs": spmd.get("programs"),
        "collectives": spmd.get("collectives"),
        "findings": spmd.get("findings"),
    },
    "spmd_exec": {
        "exit": spmd_exec_exit,
        "program": spmd_exec.get("program"),
        "n_devices": spmd_exec.get("n_devices"),
        "parity_drift": spmd_exec.get("parity_drift"),
        "recompiles_after_warmup": spmd_exec.get("recompiles_after_warmup"),
    },
    "precision": {
        "exit": precision_exit,
        "programs": precision.get("programs"),
        "bf16_programs": precision.get("bf16_programs"),
        "sites": precision.get("sites"),
        "findings": precision.get("findings"),
    },
}))
sys.exit(0 if ok else 1)
EOF
