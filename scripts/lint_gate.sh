#!/usr/bin/env bash
# CI lint gate: stmgcn lint (whole-program + contracts) plus ruff when
# the image ships it. Stdout is the contract — EXACTLY one JSON line:
#
#   {"gate": "PASS"|"FAIL", "lint": {"exit": N, "errors": N,
#    "warnings": N, "version": N}, "ruff": {"available": true|false,
#    "exit": N|null}}
#
# Everything human-readable (full reports, ruff listing) goes to stderr.
# Exit 0 iff the gate is PASS: lint found no unsuppressed errors AND
# ruff (when available) is clean. The stdout shape is pinned by a
# slow-tier test (tests/test_analysis.py::TestLintGateScript).
set -u -o pipefail

cd "$(dirname "$0")/.."

PY=${PYTHON:-python}

lint_json=$("$PY" -m stmgcn_tpu.cli lint --format json 2>>/dev/stderr)
lint_exit=$?
printf '%s\n' "$lint_json" >&2

ruff_available=false
ruff_exit=null
if command -v ruff >/dev/null 2>&1; then
    ruff_available=true
    ruff check . >&2
    ruff_exit=$?
fi

LINT_JSON="$lint_json" LINT_EXIT="$lint_exit" \
RUFF_AVAILABLE="$ruff_available" RUFF_EXIT="$ruff_exit" \
"$PY" - <<'EOF'
import json
import os
import sys

try:
    report = json.loads(os.environ["LINT_JSON"])
except ValueError:
    report = {}
lint_exit = int(os.environ["LINT_EXIT"])
ruff_available = os.environ["RUFF_AVAILABLE"] == "true"
ruff_exit = None if os.environ["RUFF_EXIT"] == "null" else int(os.environ["RUFF_EXIT"])

ok = lint_exit == 0 and report.get("errors") == 0
if ruff_available:
    ok = ok and ruff_exit == 0
print(json.dumps({
    "gate": "PASS" if ok else "FAIL",
    "lint": {
        "exit": lint_exit,
        "errors": report.get("errors"),
        "warnings": report.get("warnings"),
        "version": report.get("version"),
    },
    "ruff": {"available": ruff_available, "exit": ruff_exit},
}))
sys.exit(0 if ok else 1)
EOF
