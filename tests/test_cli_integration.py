"""End-to-end CLI integration: the reference's exact entry flow.

The reference is driven as ``Main.py --data data_dict.npz -date ... -cpt
...`` (``Main.py:21-58``): load an NPZ archive, compute calendar splits
from MMDD dates, window with (serial, daily, weekly) lengths, train,
test. C1 (loader), C4 (date splits), and C14 (CLI) are unit-tested
piecewise elsewhere; this file pins their *composition* — a
reference-format archive driven through the real CLI process must
produce the same numbers as the in-process synthetic path that generated
the archive.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.data import synthetic_dataset

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ROWS = 4
# 0101..0114 train / 0115..0121 test over hourly data. The weekly window
# burns one week of history before the first sample, so the archive needs
# burn-in (7d) + train (14d) + test (7d) = 28 days to fit the splits.
N_DAYS = 28
DATES = ["0101", "0114", "0115", "0121"]


@pytest.fixture(scope="module")
def archive(tmp_path_factory):
    """Reference-format ``data_dict.npz`` written from synthetic data."""
    data = synthetic_dataset(rows=ROWS, n_timesteps=24 * N_DAYS, seed=0)
    path = tmp_path_factory.mktemp("npz") / "data_dict.npz"
    np.savez(
        path,
        taxi=data.demand,  # (T, N, C), the reference's demand key
        neighbor_adj=data.adjs["neighbor_adj"],
        trans_adj=data.adjs["trans_adj"],
        semantic_adj=data.adjs["semantic_adj"],
    )
    return str(path)


def _run_cli(args, timeout=900):
    out = subprocess.run(
        [sys.executable, "-m", "stmgcn_tpu.cli", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


@pytest.mark.slow
def test_cli_npz_date_flow_matches_direct_synthetic(archive, tmp_path):
    """``--data data_dict.npz -date ... -cpt ...`` == the in-process run
    on the identical synthetic data (same seed, same recipe)."""
    from stmgcn_tpu.experiment import run

    cli = _run_cli(
        [
            "--data", archive,
            "-date", *DATES,
            "-cpt", "3", "1", "1",
            "--epochs", "2",
            "--batch-size", "16",
            "--platform", "cpu",
            "--out-dir", str(tmp_path / "cli"),
        ]
    )

    cfg = preset("default")
    cfg.data.rows = ROWS
    cfg.data.n_timesteps = 24 * N_DAYS
    cfg.data.dates = tuple(DATES)
    cfg.data.serial_len, cfg.data.daily_len, cfg.data.weekly_len = 3, 1, 1
    cfg.train.epochs = 2
    cfg.train.batch_size = 16
    cfg.train.out_dir = str(tmp_path / "direct")
    direct = run(cfg, verbose=False)

    for mode in ("train", "test"):
        for metric in ("mse", "rmse", "mae", "mape", "pcc"):
            np.testing.assert_allclose(
                cli["results"][mode][metric],
                direct["results"][mode][metric],
                rtol=1e-5,
                err_msg=f"{mode}/{metric} diverged between CLI-npz and direct paths",
            )


@pytest.mark.slow
def test_cli_test_only_reuses_checkpoint(archive, tmp_path):
    """``--test-only`` re-scores the trained checkpoint (Main.py's -test
    path) without retraining — metrics match the training run's report."""
    out_dir = str(tmp_path / "run")
    common = [
        "--data", archive,
        "-date", *DATES,
        "-cpt", "3", "1", "1",
        "--batch-size", "16",
        "--platform", "cpu",
        "--out-dir", out_dir,
    ]
    first = _run_cli([*common, "--epochs", "2"])
    again = _run_cli([*common, "--test-only"])
    for metric in ("rmse", "mae", "pcc"):
        np.testing.assert_allclose(
            first["results"]["test"][metric],
            again["results"]["test"][metric],
            rtol=1e-6,
        )
