"""SPMD contract pass: HLO collective extraction, manifests, and the
three spmd rules (collective-manifest / wire-budget / shard-footprint).

Layers, cheapest first:

- :mod:`stmgcn_tpu.analysis.hlo` parsing/attribution on synthetic HLO
  lines in the exact syntaxes XLA prints on this image (iota replica
  groups with transposes, explicit groups, async ``-start`` tuples,
  ``source_target_pairs``);
- manifest composition (:func:`manifest_for_config`) — pure config;
- a pinned **fire/pass boundary pair per rule** through
  :func:`analyze_program` on hand-built HLO text (no JAX);
- the seeded regression: a real jit-compiled program whose output
  sharding mis-spec forces GSPMD to insert an implicit all-gather, which
  the pass must catch *statically* on the CPU-only host — naming the
  HLO op and the mesh axis — while the corrected twin passes clean;
- slow tier: the whole-tree zero-findings pin over every probe program.
"""

import json

import pytest

from stmgcn_tpu.analysis.hlo import collect_collectives, infer_axes
from stmgcn_tpu.analysis.spmd_check import (
    PROGRAM_SPECS,
    WIRE_BUDGETS,
    analyze_program,
    check_shard_footprints,
    estimate_shard_footprint,
)
from stmgcn_tpu.config import preset
from stmgcn_tpu.parallel.manifest import (
    CollectiveDecl,
    CollectiveManifest,
    manifest_for_config,
)

MESH_2x4 = ((2, 4), ("dp", "region"))
MESH_2x2x2 = ((2, 2, 2), ("dp", "region", "branch"))


class TestInferAxes:
    """Axis attribution from replica_groups / source_target_pairs."""

    def test_iota_groups_vary_trailing_axis(self):
        # [2,4]<=[8]: rows are {0..3},{4..7} — fix dp, vary region
        line = "all-gather(...), replica_groups=[2,4]<=[8]"
        assert infer_axes(line, *MESH_2x4) == "region"

    def test_iota_transpose_varies_leading_axis(self):
        # [4,2]<=[2,4]T(1,0): groups {0,4},{1,5},... — vary dp
        line = "all-reduce(...), replica_groups=[4,2]<=[2,4]T(1,0)"
        assert infer_axes(line, *MESH_2x4) == "dp"

    def test_explicit_groups(self):
        line = "all-reduce(...), replica_groups={{0,4},{1,5},{2,6},{3,7}}"
        assert infer_axes(line, *MESH_2x4) == "dp"

    def test_empty_groups_span_all_axes(self):
        line = "all-reduce(...), replica_groups={}"
        assert infer_axes(line, *MESH_2x4) == "dp+region"

    def test_branch_axis_on_3d_mesh(self):
        # (dp, region, branch) row-major: branch is the fastest axis
        line = "all-reduce(...), replica_groups={{0,1},{2,3},{4,5},{6,7}}"
        assert infer_axes(line, *MESH_2x2x2) == "branch"

    def test_permute_pairs_single_axis(self):
        line = (
            "collective-permute(...), "
            "source_target_pairs={{0,1},{1,2},{2,3},{3,0},{4,5},{5,6},{6,7},{7,4}}"
        )
        assert infer_axes(line, *MESH_2x4) == "region"

    def test_pair_crossing_two_axes_is_unattributable(self):
        line = "collective-permute(...), source_target_pairs={{0,5}}"
        assert infer_axes(line, *MESH_2x4) == "?"

    def test_grouping_matching_no_partition_is_unattributable(self):
        line = "all-reduce(...), replica_groups={{0,3},{1,2},{4,7},{5,6}}"
        assert infer_axes(line, *MESH_2x4) == "?"

    def test_singleton_groups_are_degenerate(self):
        # extent-1 axis partition: no device talks to any other
        line = (
            "all-reduce(...), "
            "replica_groups={{0},{1},{2},{3},{4},{5},{6},{7}}"
        )
        assert infer_axes(line, *MESH_2x4) == ""


class TestCollectCollectives:
    def test_bytes_and_async_pairs_count_once(self):
        hlo = "\n".join([
            "  %all-gather.1 = f32[4,16]{1,0} all-gather(%p0), "
            "replica_groups=[2,4]<=[8], dimensions={1}",
            "  %all-reduce-start.2 = (f32[8,8], f32[8,8], u32[]) "
            "all-reduce-start(%x), replica_groups=[4,2]<=[2,4]T(1,0)",
            "  %all-reduce-done.2 = f32[8,8] all-reduce-done("
            "%all-reduce-start.2)",
        ])
        ops, n_while = collect_collectives(hlo, *MESH_2x4)
        assert n_while == 0
        assert [(o.kind, o.axes, o.out_bytes) for o in ops] == [
            ("all-gather", "region", 4 * 16 * 4),
            # start tuple: scalar u32[] dropped, last nonscalar counted once
            ("all-reduce", "dp", 8 * 8 * 4),
        ]

    def test_degenerate_singleton_ops_are_dropped(self):
        hlo = (
            "  %all-reduce.9 = f32[4]{0} all-reduce(%x), "
            "replica_groups={{0},{1},{2},{3},{4},{5},{6},{7}}"
        )
        ops, _ = collect_collectives(hlo, *MESH_2x4)
        assert ops == []

    def test_while_counted(self):
        hlo = "  %w = (s32[], f32[4]) while(%init), condition=%c, body=%b"
        ops, n_while = collect_collectives(hlo, *MESH_2x4)
        assert ops == [] and n_while == 1


class TestManifestComposition:
    def test_dp_only_train_vs_serve(self):
        cfg = preset("multicity")
        train = manifest_for_config(cfg, program="train")
        assert train.lookup("all-reduce", "dp").required
        # a dp-only mesh serves with zero collectives: empty manifest
        serve = manifest_for_config(cfg, program="serve")
        assert serve.decls == ()

    def test_banded_flips_required_ops(self):
        cfg = preset("scaled")
        dense = manifest_for_config(cfg, program="train", banded=False)
        assert dense.lookup("all-gather", "region").required
        assert dense.lookup("collective-permute", "region") is None
        banded = manifest_for_config(cfg, program="train", banded=True)
        assert banded.lookup("collective-permute", "region").required
        # region gathers still happen in banded programs (backward
        # transposes, pooling) — declared, but no longer plan-defining
        assert banded.lookup("all-gather", "region").required is False

    def test_branch_axis_declares_fusion_psum(self):
        cfg = preset("branchpar")
        m = manifest_for_config(cfg, program="serve")
        assert m.lookup("all-reduce", "branch").required
        assert m.lookup("all-reduce", "dp") is None  # no grads in serve

    def test_to_dict_round_trips_decl_fields(self):
        m = manifest_for_config(preset("bandedbranch"), banded=True)
        d = m.to_dict()
        assert d["program"] == "train"
        kinds = {(x["kind"], x["axes"]) for x in d["decls"]}
        assert ("collective-permute", "region") in kinds
        assert ("all-reduce", "branch") in kinds
        assert all(
            set(x) == {"kind", "axes", "required", "max_count", "reason"}
            for x in d["decls"]
        )


def _m(*decls):
    return CollectiveManifest(program="t", decls=tuple(decls))


_AG_REGION = (
    "  %all-gather.7 = f32[4,16]{1,0} all-gather(%p0), "
    "replica_groups=[2,4]<=[8], dimensions={1}"
)
_PERMUTE = (
    "  %collective-permute.3 = f32[2,2,8]{2,1,0} collective-permute(%x), "
    "source_target_pairs={{0,1},{1,2},{2,3},{3,0},{4,5},{5,6},{6,7},{7,4}}"
)
_AR_DP = (
    "  %all-reduce.5 = f32[64]{0} all-reduce(%g), "
    "replica_groups=[4,2]<=[2,4]T(1,0)"
)


class TestManifestRuleBoundaries:
    """Pinned fire/pass boundary pair for spmd-collective-manifest."""

    def test_undeclared_collective_fires_naming_op_and_axis(self):
        f = analyze_program("p", _AG_REGION, _m(), *MESH_2x4)
        assert [x.rule for x in f] == ["spmd-collective-manifest"]
        assert f[0].severity == "error"
        assert f[0].path == "<contract:spmd:p>"
        assert "%all-gather.7" in f[0].message
        assert "'region'" in f[0].message

    def test_declared_collective_passes(self):
        m = _m(CollectiveDecl("all-gather", "region"))
        assert analyze_program("p", _AG_REGION, m, *MESH_2x4) == []

    def test_required_missing_fires_and_present_passes(self):
        m = _m(CollectiveDecl(
            "collective-permute", "region", required=True, reason="halo"))
        f = analyze_program("p", "", m, *MESH_2x4)
        assert [x.rule for x in f] == ["spmd-collective-manifest"]
        assert "never appears" in f[0].message and "halo" in f[0].message
        assert analyze_program("p", _PERMUTE, m, *MESH_2x4) == []

    def test_max_count_boundary(self):
        m = _m(CollectiveDecl("all-gather", "region", max_count=1))
        one = _AG_REGION
        two = _AG_REGION + "\n" + _AG_REGION.replace(".7", ".8")
        assert analyze_program("p", one, m, *MESH_2x4) == []
        f = analyze_program("p", two, m, *MESH_2x4)
        assert [x.rule for x in f] == ["spmd-collective-manifest"]
        assert "max_count 1" in f[0].message


class TestWireRuleBoundaries:
    """Pinned fire/pass boundary pairs for spmd-wire-budget."""

    _M = _m(
        CollectiveDecl("all-gather", "region"),
        CollectiveDecl("collective-permute", "region"),
        CollectiveDecl("all-reduce", "dp"),
    )

    def test_total_bytes_budget_boundary(self):
        nbytes = 4 * 16 * 4  # _AG_REGION's output
        ok = analyze_program(
            "p", _AG_REGION, self._M, *MESH_2x4, budget=nbytes)
        assert ok == []
        f = analyze_program(
            "p", _AG_REGION, self._M, *MESH_2x4, budget=nbytes - 1)
        assert [x.rule for x in f] == ["spmd-wire-budget"]
        assert "rebaseline" in f[0].message

    def test_halo_permute_bound_boundary(self):
        # permute output 2*2*8*4 = 128 bytes; cap = halo*b*m*f_cap*4
        meta = {"halo": 2, "b_local": 2, "m_local": 1, "f_cap": 8}
        assert analyze_program(
            "p", _PERMUTE, self._M, *MESH_2x4, meta=meta) == []
        tight = dict(meta, f_cap=7)  # cap 112 < 128
        f = analyze_program("p", _PERMUTE, self._M, *MESH_2x4, meta=tight)
        assert [x.rule for x in f] == ["spmd-wire-budget"]
        assert "boundary-rows bound" in f[0].message

    def test_dp_psum_bound_boundary(self):
        # dp all-reduce 256 bytes; cap = 2*param_bytes + 4096
        from stmgcn_tpu.analysis import spmd_check as sc

        slack = sc._PSUM_SLACK_BYTES
        ok = {"param_bytes": (256 - slack + 1) // 2 + 1}
        assert analyze_program(
            "p", _AR_DP, self._M, *MESH_2x4, meta=ok) == []
        over = _AR_DP + "\n" + _AR_DP.replace(".5", ".6").replace(
            "f32[64]", "f32[9999]")
        f = analyze_program(
            "p", over, self._M, *MESH_2x4, meta={"param_bytes": 64})
        assert [x.rule for x in f] == ["spmd-wire-budget"]
        assert "gradient-psum model" in f[0].message


class TestShardFootprint:
    """spmd-shard-footprint: per-device operand math, pure config."""

    def test_every_multi_device_preset_fits(self):
        assert check_shard_footprints() == []

    def test_estimate_scales_down_with_region(self):
        cfg = preset("scaled")
        whole = estimate_shard_footprint(cfg)
        cfg2 = preset("scaled")
        cfg2.mesh.region = 4
        bigger = estimate_shard_footprint(cfg2)
        # dense supports per device: n_local x n_pad — halving region
        # roughly doubles the shard
        assert bigger["supports_bytes"] > 1.5 * whole["supports_bytes"]

    def test_banded_strips_beat_dense_shards(self):
        # scaled: n_local=313, default halo 156 — strips 313 x 625 per
        # support vs dense 313 x 2504: the banded plan's resident win
        cfg = preset("scaled")
        dense = estimate_shard_footprint(cfg)
        cfg2 = preset("scaled")
        cfg2.mesh.region_strategy = "banded"
        banded = estimate_shard_footprint(cfg2)
        assert banded["supports_bytes"] < 0.5 * dense["supports_bytes"]

    def test_fire_pass_boundary_on_budget(self):
        cfg = preset("branchpar")
        total = estimate_shard_footprint(cfg)["total_bytes"]
        assert check_shard_footprints([("b", cfg)], budget_bytes=total) == []
        f = check_shard_footprints([("b", cfg)], budget_bytes=total - 1)
        assert [x.rule for x in f] == ["spmd-shard-footprint"]
        assert f[0].path == "<contract:spmd:b>"
        assert "per-core budget" in f[0].message

    def test_single_device_presets_out_of_scope(self):
        cfg = preset("smoke")
        assert cfg.mesh.n_devices == 1
        # resident-memory owns single-device; even budget 0 stays silent
        assert check_shard_footprints([("s", cfg)], budget_bytes=0) == []


class TestSeededImplicitAllGather:
    """The seeded regression ISSUE 15 names: a program whose output
    sharding mis-spec makes GSPMD insert an implicit all-gather — caught
    statically from the compiled module on the CPU-only host, with the
    HLO op and the mesh axis in the message; the corrected twin passes.
    """

    @pytest.fixture()
    def mesh(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from stmgcn_tpu.parallel import build_mesh

        return build_mesh(dp=8, region=1)

    def _compile(self, mesh, out_spec):
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(
            np.zeros((16, 8), np.float32), NamedSharding(mesh, P("dp"))
        )
        fn = jax.jit(
            lambda a: a * 2.0, out_shardings=NamedSharding(mesh, out_spec)
        )
        return fn.lower(x).compile().as_text()

    def test_seeded_fire_and_corrected_pass(self, mesh):
        from jax.sharding import PartitionSpec as P

        manifest = _m()  # elementwise plan: NO collectives declared
        shape, names = tuple(mesh.devices.shape), tuple(mesh.axis_names)

        # mis-spec: replicated output of a dp-sharded operand — GSPMD
        # must all-gather over dp to satisfy it
        bad = self._compile(mesh, P())
        findings = analyze_program("seeded", bad, manifest, shape, names)
        assert [f.rule for f in findings] == ["spmd-collective-manifest"]
        msg = findings[0].message
        assert "undeclared all-gather" in msg
        assert "'dp'" in msg
        assert "%all-gather" in msg  # names the actual HLO op

        # corrected twin: output keeps the operand's sharding — zero
        # collectives, zero findings
        good = self._compile(mesh, P("dp"))
        assert analyze_program("fixed", good, manifest, shape, names) == []


class TestDeclaredManifestsPureConfig:
    def test_no_jax_needed_and_covers_all_probes(self):
        from stmgcn_tpu.analysis.spmd_check import declared_manifests

        ms = declared_manifests()
        assert set(ms) == set(PROGRAM_SPECS)
        # the dryrun-persisted shape is JSON-serializable as-is
        blob = json.dumps({k: v.to_dict() for k, v in ms.items()})
        assert "collective-permute" in blob

    def test_wire_budgets_cover_all_probes(self):
        assert set(WIRE_BUDGETS) == set(PROGRAM_SPECS)
        assert all(v >= 1024 for v in WIRE_BUDGETS.values())


@pytest.mark.slow
class TestWholeTreePin:
    """The zero-findings / zero-suppressions pin over the real probe
    programs: every multi-device preset's train+serve lowered on the
    virtual mesh, diffed against its manifest, within its wire budget."""

    def test_all_probe_programs_clean(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from stmgcn_tpu.analysis.spmd_check import (
            check_spmd_contracts,
            spmd_summary,
        )

        assert check_spmd_contracts() == []
        summary = spmd_summary()
        assert summary["programs"] == len(PROGRAM_SPECS) == 8
        assert summary["collectives"] > 0
        assert summary["findings"] == 0

    def test_banded_programs_contain_the_halo_permute(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from stmgcn_tpu.analysis.spmd_check import _lower_programs

        reports = _lower_programs()
        for name, (_, _, banded) in PROGRAM_SPECS.items():
            kinds = {(o.kind, o.axes) for o in reports[name].ops}
            if banded:
                assert ("collective-permute", "region") in kinds, name
        # dp training programs sync gradients
        assert ("all-reduce", "dp") in {
            (o.kind, o.axes) for o in reports["multicity/train"].ops
        }


class TestSarifRendering:
    """Satellite a: SARIF 2.1.0 output — one document on stdout."""

    def _findings(self):
        from stmgcn_tpu.analysis.report import Finding

        return [
            Finding(
                rule="spmd-collective-manifest",
                path="<contract:spmd:p>",
                line=0,
                message="undeclared all-gather over 'region'",
            ),
            Finding(
                rule="missing-donate", path="stmgcn_tpu/x.py", line=7,
                message="no donate", col=3, severity="warning",
                suppressed=True,
            ),
        ]

    def test_document_shape(self):
        from stmgcn_tpu.analysis.report import render_sarif

        doc = json.loads(render_sarif(self._findings()))
        assert doc["version"] == "2.1.0"
        assert len(doc["runs"]) == 1
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "stmgcn-lint"
        rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert rules == {"spmd-collective-manifest", "missing-donate"}
        res = run["results"]
        assert len(res) == 2
        by_rule = {r["ruleId"]: r for r in res}
        spmd = by_rule["spmd-collective-manifest"]
        assert spmd["level"] == "error"
        loc = spmd["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "<contract:spmd:p>"
        assert loc["region"]["startLine"] == 1  # SARIF minimum, from line 0
        sup = by_rule["missing-donate"]
        assert sup["level"] == "warning"
        assert sup["suppressions"] == [{"kind": "inSource"}]
        # ruleIndex points into the driver rule table
        for r in res:
            assert run["tool"]["driver"]["rules"][r["ruleIndex"]]["id"] == (
                r["ruleId"]
            )

    def test_cli_stdout_is_one_sarif_document(self):
        """The stdout contract: `stmgcn lint --format sarif` prints
        EXACTLY one JSON document (json.loads of the full stream), even
        when clean."""
        import os
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-m", "stmgcn_tpu.cli", "lint",
             "--format", "sarif", "--no-contracts"],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        doc = json.loads(proc.stdout)  # whole stream parses as ONE doc
        assert doc["version"] == "2.1.0"
        assert doc["runs"][0]["results"] == []
