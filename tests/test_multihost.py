"""Multi-host coordination paths executed with two REAL processes.

Round-3 review finding: the lead-read + broadcast restore
(``train/trainer.py`` ``_load_state``) and the CLI export-status
broadcast only ever ran their ``process_count == 1`` branches in tests.
These tests launch two subprocesses joined into one ``jax.distributed``
job over local gloo collectives (CPU), so the collective code itself
executes — including the error-in-payload design where a lead-side
failure must raise on *every* process rather than leaving peers blocked
in the collective.

Only process 0's ``out_dir`` holds a checkpoint: process 1 can produce
the checkpoint's parameter digest only by receiving the broadcast, so
these assertions genuinely fail if the broadcast logic breaks (verified
by deliberately skipping the non-lead broadcast — both tests then hang
into the timeout/fail).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _multihost_worker import params_digest, worker_config

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_multihost_worker.py")

pytestmark = pytest.mark.slow


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_pair(scenario: str, dirs, extra=(), timeout=420):
    """Launch both workers, wait, and return their outputs."""
    port = _free_port()
    env = dict(os.environ)
    # the pytest process's own platform forcing must not leak its
    # XLA_FLAGS (8 virtual devices) into the workers
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, scenario, str(i), str(port), dirs[i], *extra],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


@pytest.fixture(scope="module")
def trained_lead_dir(tmp_path_factory):
    """A checkpoint in process 0's out_dir only (trained in-process)."""
    from stmgcn_tpu.experiment import build_trainer
    from stmgcn_tpu.train.checkpoint import load_checkpoint

    lead = str(tmp_path_factory.mktemp("mh_lead"))
    trainer = build_trainer(worker_config(lead), verbose=False)
    trainer.train()
    meta, params, _ = load_checkpoint(
        os.path.join(lead, "best.ckpt"), trainer.params, trainer.opt_state
    )
    return lead, meta, params_digest(params)


def test_restore_broadcasts_state_and_error(trained_lead_dir, tmp_path):
    lead, meta, expect_digest = trained_lead_dir
    follower = str(tmp_path / "follower")
    os.makedirs(follower)
    outs = _run_pair("restore", (lead, follower))

    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
    results = {}
    for rc, out, err in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, f"no RESULT line in {out!r}"
        r = json.loads(line[0][len("RESULT "):])
        results[r["proc"]] = r
        # the lead-side read failure must raise identically on this process
        assert "ERRORPATH ok" in out, out

    assert set(results) == {0, 1}
    for r in results.values():
        assert r["epoch"] == meta["epoch"]
        assert r["best_val"] == pytest.approx(meta["best_val"])
        # process 1 has no checkpoint file: matching the trained digest
        # (distinct from the fresh-init digest) proves the broadcast
        assert r["digest"] == expect_digest


def test_cli_export_failure_fails_every_host(trained_lead_dir, tmp_path):
    lead, _, _ = trained_lead_dir
    follower = str(tmp_path / "follower")
    os.makedirs(follower)
    # the export target's parent directory does not exist -> the lead's
    # export fails; the status broadcast must turn that into rc=1 on BOTH
    bad = str(tmp_path / "no_such_dir" / "m.stmgx")
    outs = _run_pair("cli_export", (lead, follower), extra=(bad,))
    for i, (rc, out, err) in enumerate(outs):
        assert "CLIRC 1" in out, (
            f"proc {i} should exit 1 on lead export failure\n"
            f"stdout:{out}\nstderr:{err[-2000:]}"
        )


def test_cli_export_success_on_lead_only(trained_lead_dir, tmp_path):
    lead, _, _ = trained_lead_dir
    follower = str(tmp_path / "follower")
    os.makedirs(follower)
    target = str(tmp_path / "model.stmgx")
    outs = _run_pair("cli_export", (lead, follower), extra=(target,))
    for i, (rc, out, err) in enumerate(outs):
        assert "CLIRC 0" in out, (
            f"proc {i} rc\nstdout:{out}\nstderr:{err[-2000:]}"
        )
    assert os.path.exists(target)
