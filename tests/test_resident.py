"""Device-resident data placement: splits uploaded once, batches gathered
on device by index — must train identically to the streaming path.

The streaming path uploads every batch's arrays (with prefetch overlap);
the resident path ships only a per-batch index vector. Same samples, same
order, same masks => identical losses, histories, and predictions.
"""

import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
from stmgcn_tpu.experiment import build_trainer


def _run(data_placement, rows=5, epochs=2, shuffle=False, **cfg_over):
    cfg = preset("smoke")
    cfg.data.rows = rows
    cfg.data.n_timesteps = 24 * 7 * 2 + 60
    cfg.train.epochs = epochs
    cfg.train.batch_size = 8
    cfg.train.data_placement = data_placement
    cfg.train.shuffle = shuffle
    for k, v in cfg_over.items():
        setattr(cfg.train, k, v)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        cfg.train.out_dir = d
        trainer = build_trainer(cfg, verbose=False)
        assert trainer._resident == (data_placement != "stream")
        history = trainer.train()
        results = trainer.test(modes=("test",), checkpoint=None)
    return history, results


@pytest.mark.parametrize("shuffle", [False, True])
@pytest.mark.slow
def test_resident_matches_stream(shuffle):
    h_res, r_res = _run("resident", shuffle=shuffle)
    h_str, r_str = _run("stream", shuffle=shuffle)
    np.testing.assert_allclose(h_res["train"], h_str["train"], rtol=1e-6)
    np.testing.assert_allclose(h_res["validate"], h_str["validate"], rtol=1e-6)
    assert r_res["test"]["rmse"] == pytest.approx(r_str["test"]["rmse"], rel=1e-6)


def test_auto_is_resident_on_single_device_small_data():
    h_auto, _ = _run("auto")
    h_res, _ = _run("resident")
    np.testing.assert_allclose(h_auto["train"], h_res["train"], rtol=0)


def test_batch_indices_cover_modes_and_padding():
    data = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 50, seed=0)
    ds = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    for mode in ("train", "validate", "test"):
        x_all, y_all = ds.arrays(mode)
        seen = []
        for b in ds.batches(mode, 8, pad_last=True):
            assert b.indices is not None and len(b.indices) == len(b)
            np.testing.assert_array_equal(x_all[b.indices], b.x)
            np.testing.assert_array_equal(y_all[b.indices], b.y)
            seen.extend(b.indices[: b.n_real].tolist())
        assert sorted(seen) == list(range(y_all.shape[0]))


def test_shuffled_indices_match_batch_content():
    data = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 50, seed=0)
    ds = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    x_all, y_all = ds.arrays("train")
    seen = []
    for b in ds.batches("train", 8, shuffle=True, seed=3, epoch=2, pad_last=True):
        np.testing.assert_array_equal(x_all[b.indices], b.x)
        seen.extend(b.indices[: b.n_real].tolist())
    assert sorted(seen) == list(range(y_all.shape[0]))  # a true permutation


def test_index_only_batches_skip_host_copies():
    data = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 50, seed=0)
    ds = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    full = list(ds.batches("train", 8, shuffle=True, seed=1, epoch=4, pad_last=True))
    lean = list(
        ds.batches(
            "train", 8, shuffle=True, seed=1, epoch=4, pad_last=True,
            with_arrays=False,
        )
    )
    assert len(full) == len(lean)
    for f, l in zip(full, lean):
        assert l.x is None and l.y is None
        assert len(l) == len(f) and l.n_real == f.n_real
        np.testing.assert_array_equal(l.indices, f.indices)


def test_resident_rejected_on_mesh():
    """Materialized windows still refuse the mesh; the window-free gather
    (the composed multi-chip fast path) is the supported composition."""
    cfg = preset("multicity")
    cfg.train.data_placement = "resident"
    cfg.train.window_free = False
    with pytest.raises(ValueError, match="window-free"):
        build_trainer(cfg, verbose=False)


def test_resident_on_mesh_composes_window_free():
    cfg = preset("multicity")
    cfg.train.data_placement = "resident"
    trainer = build_trainer(cfg, verbose=False)
    assert trainer._resident is True
    assert trainer._window_free is True


def test_mesh_auto_streams():
    cfg = preset("multicity")
    cfg.train.data_placement = "auto"
    trainer = build_trainer(cfg, verbose=False)
    assert trainer._resident is False
