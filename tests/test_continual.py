"""Closed-loop continual serving: fine-tune daemon, guarded promotion,
torn-write recovery, and the end-to-end smoke drill.

The loop's safety claims are pinned the same way the serving and
resilience suites pin theirs — deterministically, through the fault
plans, never by anecdote:

- **loop-off parity** (tentpole contract): with the daemon disabled, a
  pre-filled ring fine-tuned through :class:`ContinualTrainer` is
  BIT-identical to the existing window-free resident path driven by
  hand — same superstep, same gather, equality not allclose;
- **torn-write** (satellite): a crash between tmp write and rename
  leaves the destination untouched and a ``*.tmp.<pid>`` orphan that
  both ``load_latest_verified`` and the hot-swap watcher ignore;
- **gate**: every typed rejection reason has a test that drives it, and
  a rejected candidate never moves the engine's generation;
- **daemon**: injected fine-tune crashes retry under the restart budget
  and exhaust into ``down`` — with serving untouched either way;
- **smoke**: ``closed_loop_smoke`` (what ``scripts/lint_gate.sh``
  asserts on) runs live ingest + one promotion + one poisoned
  ``nonfinite`` rejection while the engine answers throughout.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from stmgcn_tpu.config import ContinualConfig, ServingConfig, preset
from stmgcn_tpu.data import (
    DemandDataset,
    MinMaxNormalizer,
    SeriesRing,
    WindowSpec,
    synthetic_dataset,
)
from stmgcn_tpu.experiment import build_model
from stmgcn_tpu.inference import Forecaster
from stmgcn_tpu.obs.registry import REGISTRY
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.resilience import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    ServeFaultPlan,
    ServeFaultSpec,
)
from stmgcn_tpu.serving import PromotionGate
from stmgcn_tpu.train import (
    ContinualDaemon,
    ContinualTrainer,
    closed_loop_smoke,
    load_latest_verified,
    make_series_superstep_fns,
    save_checkpoint,
)

SPEC = WindowSpec(3, 0, 0, 24, 1)  # serial-only: burn_in 3, CPU-sized

CCFG = ContinualConfig(
    enabled=True, ring_capacity=64, reorder_window=2,
    finetune_steps=2, finetune_batch=2, max_restarts=2,
    backoff_s=0.001, backoff_max_s=0.002,
    promote_grad_norm_max=1e6, promote_update_ratio_max=100.0,
    promote_eval_margin=0.05,
)

#: a clean fine-tune health summary (what the gate accepts)
CLEAN = {"nonfinite": 0, "grad_norm_max": 1.0, "update_ratio_max": 1e-3,
         "loss_last": 0.5}


class _NS:
    def __init__(self, **kw):
        self.__dict__.update(kw)


@pytest.fixture(scope="module")
def setup():
    cfg = preset("smoke")
    cfg.data.override(rows=2, n_timesteps=64,
                      serial_len=3, daily_len=0, weekly_len=0)
    data = synthetic_dataset(rows=2, n_timesteps=64, seed=0)
    ds = DemandDataset(data, SPEC)
    supports = np.asarray(
        SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(
            ds.adjs.values()
        ),
        np.float32,
    )[: cfg.model.m_graphs]
    model = build_model(cfg, ds.n_feats)
    x0 = jnp.zeros((1, SPEC.seq_len, ds.n_nodes, ds.n_feats), jnp.float32)
    params = model.init(jax.random.key(0), jnp.asarray(supports), x0)
    norm = MinMaxNormalizer.fit(np.asarray(data.demand))
    series = np.asarray(norm.transform(np.asarray(data.demand)), np.float32)
    fc = Forecaster(model, params, norm, cfg,
                    {"input_dim": ds.n_feats, "n_nodes": ds.n_nodes})
    return _NS(cfg=cfg, ds=ds, supports=supports, model=model,
               params=params, series=series, fc=fc)


@pytest.fixture(scope="module")
def engine(setup):
    eng = setup.fc.serving_engine(
        setup.supports,
        config=ServingConfig(buckets=(1, 2), max_batch=2, max_delay_ms=2.0),
    )
    yield eng
    eng.close()


def _ring(setup, rows=64):
    return SeriesRing.from_series(setup.series[:rows], capacity=64,
                                  reorder_window=2)


def _trainer(setup, ring, out_dir, fault_plan=None):
    return ContinualTrainer(
        setup.model, optax.adam(1e-3), setup.supports, ring, SPEC, CCFG,
        str(out_dir), params=setup.params, holdout=2, fault_plan=fault_plan,
    )


def _gate(setup, engine, out_dir, **kw):
    return PromotionGate.from_config(engine, str(out_dir), CCFG, **kw)


def _candidate(setup, dirpath, name="candidate-0000.ckpt", scale=1.0):
    p = jax.tree.map(lambda a: np.asarray(a) * scale, setup.params)
    path = os.path.join(str(dirpath), name)
    save_checkpoint(path, p, None, {"kind": "continual"})
    return path


# -- loop-off parity (tentpole contract) -------------------------------


class TestLoopOffParity:
    def test_prefilled_ring_finetune_bit_identical_to_window_free(
        self, setup, tmp_path
    ):
        """Daemon off + pre-filled ring == the existing window-free path.

        The same committed params fine-tuned (a) through the trainer
        over the ring and (b) by hand through a fresh
        ``make_series_superstep_fns`` over the plain series, with the
        trainer's own block math replicated, must agree BIT-exactly —
        the ring and the continual plumbing add no numerics.
        """
        ring = _ring(setup)
        assert np.array_equal(np.asarray(ring.series()), setup.series)
        trainer = _trainer(setup, ring, tmp_path)
        path, health = trainer.finetune()
        assert health["nonfinite"] == 0
        trainer.commit()

        fns = make_series_superstep_fns(
            setup.model, optax.adam(1e-3), horizon=1, health=True
        )
        targets = SPEC.target_indices(64)[:-2].astype(np.int32)  # holdout=2
        n, s, b = len(targets), CCFG.finetune_steps, CCFG.finetune_batch
        idx = ((np.arange(s * b) + max(0, n - s * b)) % n)
        idx = idx.reshape(s, b).astype(np.int32)
        # stage fresh device copies: the superstep donates its params/
        # opt-state operands, and setup.params must outlive this test
        host = jax.tree.map(np.asarray, setup.params)
        p2, _, losses, _ = fns.train_superstep(
            jax.tree.map(jnp.asarray, host),
            jax.tree.map(jnp.asarray,
                         jax.tree.map(np.asarray,
                                      optax.adam(1e-3).init(setup.params))),
            jnp.asarray(setup.supports),
            jnp.asarray(setup.series),
            jnp.asarray(targets),
            jnp.asarray(SPEC.offsets, jnp.int32),
            jnp.asarray(idx),
            jnp.ones((s, b), jnp.float32),
        )
        got = jax.tree_util.tree_leaves(trainer.params)
        want = jax.tree_util.tree_leaves(jax.tree.map(np.asarray, p2))
        assert len(got) == len(want)
        for a, c in zip(got, want):
            assert np.array_equal(a, c)  # BIT-exact, not allclose
        assert health["loss_last"] == float(np.asarray(losses)[-1])
        assert os.path.exists(path)

    def test_discard_restores_committed_state(self, setup, tmp_path):
        ring = _ring(setup)
        trainer = _trainer(setup, ring, tmp_path)
        before = [np.array(a) for a in jax.tree_util.tree_leaves(trainer.params)]
        trainer.finetune()
        trainer.discard()
        after = jax.tree_util.tree_leaves(trainer.params)
        for a, c in zip(before, after):
            assert np.array_equal(a, c)


# -- torn-write recovery (satellite) -----------------------------------


def _tiny():
    return {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}


class TestTornWrite:
    def test_destination_untouched_and_verified_load_recovers(self, tmp_path):
        path = str(tmp_path / "latest.ckpt")
        save_checkpoint(path, _tiny(), None, {"step": 1})
        plan = FaultPlan(FaultSpec(kind="torn-write", path_glob="latest.ckpt"))
        newer = {"w": np.full((2, 3), 7.0, np.float32)}
        with pytest.raises(InjectedFault):
            save_checkpoint(path, newer, None, {"step": 2}, fault_plan=plan)
        orphans = [p for p in os.listdir(tmp_path) if ".tmp." in p]
        assert orphans, "torn write must leave its partial tmp behind"
        got = load_latest_verified(str(tmp_path), _tiny(), None,
                                   load_opt_state=False)
        assert got is not None
        _, meta, params, _ = got
        assert meta["step"] == 1  # the torn step-2 write never landed
        assert np.array_equal(params["w"], _tiny()["w"])
        # the fault is one-shot: the supervised retry lands cleanly
        save_checkpoint(path, newer, None, {"step": 2}, fault_plan=plan)
        _, meta2, params2, _ = load_latest_verified(
            str(tmp_path), _tiny(), None, load_opt_state=False
        )
        assert meta2["step"] == 2 and np.array_equal(params2["w"], newer["w"])

    def test_watcher_ignores_torn_orphan_then_swaps_clean_write(
        self, setup, engine, tmp_path
    ):
        watcher = engine.watch_checkpoints(str(tmp_path))
        gen0 = engine.generation
        host = jax.tree.map(np.asarray, setup.fc.params)
        plan = FaultPlan(FaultSpec(kind="torn-write", path_glob="latest.ckpt"))
        with pytest.raises(InjectedFault):
            save_checkpoint(str(tmp_path / "latest.ckpt"), host, None,
                            {"step": 1}, fault_plan=plan)
        assert watcher.poll() is False  # orphan tmp is not a checkpoint
        assert engine.generation == gen0
        save_checkpoint(str(tmp_path / "latest.ckpt"), host, None, {"step": 1})
        assert watcher.poll() is True
        assert engine.generation == gen0 + 1


# -- promotion gate ----------------------------------------------------


class TestPromotionGate:
    def test_promote_rotates_and_swaps_through_watcher(
        self, setup, engine, tmp_path
    ):
        gate = _gate(setup, engine, tmp_path)
        gen0 = engine.generation
        d = gate.consider(_candidate(setup, tmp_path), CLEAN)
        assert d.accepted and d.reason == "promoted"
        assert engine.generation == gen0 + 1 == d.generation
        assert os.path.exists(tmp_path / "latest.ckpt")
        d2 = gate.consider(
            _candidate(setup, tmp_path, "candidate-0001.ckpt"), CLEAN
        )
        assert d2.accepted and engine.generation == gen0 + 2
        # the prior live checkpoint rotated aside, not clobbered
        assert os.path.exists(tmp_path / "latest.prev.ckpt")
        assert gate.promotions == 2 and gate.rejections == 0

    @pytest.mark.parametrize("health,reason", [
        ({**CLEAN, "nonfinite": 3}, "nonfinite"),
        ({**CLEAN, "grad_norm_max": float("nan")}, "grad-norm"),
        ({**CLEAN, "grad_norm_max": 1e9}, "grad-norm"),
        ({**CLEAN, "update_ratio_max": 500.0}, "update-ratio"),
    ])
    def test_typed_rejections_quarantine_without_touching_serving(
        self, setup, engine, tmp_path, health, reason
    ):
        gate = _gate(setup, engine, tmp_path)
        before = REGISTRY.counter(
            "continual.rejections", {"reason": reason}
        ).value
        cand = _candidate(setup, tmp_path)
        gen0 = engine.generation
        d = gate.consider(cand, health)
        assert not d.accepted and d.reason == reason
        assert engine.generation == gen0
        assert not os.path.exists(cand)
        assert os.path.exists(f"{cand}.rejected-{reason}")
        assert REGISTRY.counter(
            "continual.rejections", {"reason": reason}
        ).value == before + 1

    def test_corrupt_candidate_rejected(self, setup, engine, tmp_path):
        cand = str(tmp_path / "candidate-0000.ckpt")
        with open(cand, "wb") as f:
            f.write(b"not a checkpoint at all")
        gate = _gate(setup, engine, tmp_path)
        gen0 = engine.generation
        d = gate.consider(cand, CLEAN)
        assert not d.accepted and d.reason == "corrupt"
        assert engine.generation == gen0

    def test_eval_regression_rejected(self, setup, engine, tmp_path):
        calls = []

        def fake_eval(params):  # candidate scored first, then live
            calls.append(1)
            return 5.0 if len(calls) == 1 else 1.0

        gate = _gate(setup, engine, tmp_path, holdout_eval=fake_eval,
                     live_params=setup.params)
        d = gate.consider(_candidate(setup, tmp_path), CLEAN)
        assert not d.accepted and d.reason == "eval-regression"
        assert len(calls) == 2

    def test_injected_gate_crash_becomes_gate_error(
        self, setup, engine, tmp_path
    ):
        gate = _gate(setup, engine, tmp_path)
        cand = _candidate(setup, tmp_path)
        gen0 = engine.generation
        prior = getattr(engine, "_fault_plan", None)
        engine._fault_plan = ServeFaultPlan(
            ServeFaultSpec(kind="promotion-raise", dispatch=0)
        )
        try:
            d = gate.consider(cand, CLEAN)
        finally:
            engine._fault_plan = prior
        assert not d.accepted and d.reason == "gate-error"
        assert os.path.exists(f"{cand}.rejected-gate-error")
        assert engine.generation == gen0


# -- daemon supervision ------------------------------------------------


class _StubEngine:
    def __init__(self, snap=None):
        self._snap = snap

    def drift_snapshot(self):
        return self._snap


class _StubGate:
    def __init__(self, snap=None):
        self._engine = _StubEngine(snap)


class TestDaemon:
    def test_cadence_trigger(self):
        clock = [0.0]
        cfg = ContinualConfig(enabled=True, cadence_s=10.0)
        d = ContinualDaemon(None, _StubGate(), config=cfg,
                            time_fn=lambda: clock[0])
        clock[0] = 5.0
        assert d.should_retrain() is None
        clock[0] = 11.0
        assert d.should_retrain() == "cadence"

    @pytest.mark.parametrize("gauges,want", [
        ({"n": 10, "z_max": 9.0, "psi": 0.1}, "drift"),   # z over 8.0
        ({"n": 10, "z_max": 1.0, "psi": 0.9}, "drift"),   # psi over 0.5
        ({"n": 10, "z_max": 1.0, "psi": 0.1}, None),
    ])
    def test_drift_trigger(self, gauges, want):
        snap = {"schema_version": 1, "generation": 0,
                "cities": {"0": {"commute": gauges}}}
        d = ContinualDaemon(None, _StubGate(snap), config=CCFG)
        assert d.should_retrain() == want

    def test_down_daemon_never_fires(self):
        cfg = ContinualConfig(enabled=True, cadence_s=0.001)
        d = ContinualDaemon(None, _StubGate(), config=cfg)
        d.down = True
        time.sleep(0.002)
        assert d.should_retrain() is None and d.poll() is None

    def test_injected_crash_retried_with_backoff_then_promoted(
        self, setup, engine, tmp_path
    ):
        plan = FaultPlan(FaultSpec(kind="raise", epoch=0, step=0))
        trainer = _trainer(setup, _ring(setup), tmp_path, fault_plan=plan)
        gate = _gate(setup, engine, tmp_path)
        sleeps = []
        daemon = ContinualDaemon(trainer, gate, config=CCFG,
                                 sleep_fn=sleeps.append)
        gen0 = engine.generation
        d = daemon.retrain("cadence")
        assert d is not None and d.accepted
        assert daemon.restarts == 1 and len(sleeps) == 1
        assert 0.001 <= sleeps[0] <= 0.002 * 1.1  # backoff with jitter
        assert engine.generation == gen0 + 1
        assert not daemon.down

    def test_restart_budget_exhausts_into_down_serving_untouched(
        self, setup, engine, tmp_path
    ):
        # one raise per fine-tune ordinal: every attempt dies
        plan = FaultPlan(*[
            FaultSpec(kind="raise", epoch=e, step=0) for e in range(5)
        ])
        cfg = ContinualConfig(
            enabled=True, finetune_steps=2, finetune_batch=2,
            max_restarts=1, backoff_s=0.001, backoff_max_s=0.002,
        )
        trainer = ContinualTrainer(
            setup.model, optax.adam(1e-3), setup.supports, _ring(setup),
            SPEC, cfg, str(tmp_path), params=setup.params, holdout=2,
            fault_plan=plan,
        )
        gate = _gate(setup, engine, tmp_path)
        daemon = ContinualDaemon(trainer, gate, config=cfg,
                                 sleep_fn=lambda s: None)
        gen0 = engine.generation
        assert daemon.retrain("drift") is None
        assert daemon.down
        assert gate.ordinal == 0  # the gate never saw a candidate
        assert engine.generation == gen0
        assert REGISTRY.gauge("continual.daemon_up").value == 0
        assert daemon.poll() is None  # retired, not retried

    def test_torn_candidate_write_retried_through_supervision(
        self, setup, engine, tmp_path
    ):
        plan = FaultPlan(
            FaultSpec(kind="torn-write", path_glob="candidate-*.ckpt")
        )
        trainer = _trainer(setup, _ring(setup), tmp_path, fault_plan=plan)
        gate = _gate(setup, engine, tmp_path)
        daemon = ContinualDaemon(trainer, gate, config=CCFG,
                                 sleep_fn=lambda s: None)
        d = daemon.retrain("cadence")
        assert d is not None and d.accepted
        assert daemon.restarts == 1
        orphans = [p for p in os.listdir(tmp_path / "candidates")
                   if ".tmp." in p]
        assert orphans, "the torn candidate tmp is left for forensics"

    def test_hang_fault_delays_but_completes(self, setup, engine, tmp_path):
        plan = FaultPlan(FaultSpec(kind="hang", hang_ms=20, epoch=0))
        trainer = _trainer(setup, _ring(setup), tmp_path, fault_plan=plan)
        gate = _gate(setup, engine, tmp_path)
        daemon = ContinualDaemon(trainer, gate, config=CCFG)
        t0 = time.perf_counter()
        d = daemon.retrain("cadence")
        assert time.perf_counter() - t0 >= 0.02
        assert d is not None and d.accepted and daemon.restarts == 0

    def test_background_thread_starts_and_stops_bounded(self):
        cfg = ContinualConfig(enabled=True)  # no cadence: never fires
        daemon = ContinualDaemon(None, _StubGate(), config=cfg)
        daemon.start(poll_s=0.01)
        time.sleep(0.05)
        assert daemon.stop() is True
        assert daemon.stop() is True  # idempotent


# -- the end-to-end drill (what lint_gate.sh asserts on) ---------------


class TestClosedLoopSmoke:
    def test_verdict_counts(self, tmp_path):
        out = closed_loop_smoke(str(tmp_path), poison=True, seed=0)
        assert out["promotions"] == 1
        assert out["rejections"] == 1
        assert out["nonfinite"] == 0  # the clean fine-tune's stream
        assert out["rejection_reason"] == "nonfinite"
        assert out["generation"] == 1  # rejection left gen 1 serving
        assert out["rows_ingested"] == 64 and out["ring_len"] == 64
        assert out["predictions"] == 3 and not out["daemon_down"]
        rejected = [
            p for p in os.listdir(tmp_path / "candidates")
            if p.endswith(".rejected-nonfinite")
        ]
        assert len(rejected) == 1
