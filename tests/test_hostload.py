"""Host-load provenance + bench lock (stmgcn_tpu/utils/hostload.py).

The lock is what keeps the measurement machinery from depressing its own
records on this 1-core host (BASELINE.md round 4: concurrent probe
children cost the driver's record 4-20%); the snapshot is what makes a
contended record detectable in-band. Both are pure host-side code — fast
tier."""

import os

from stmgcn_tpu.utils.hostload import BenchLock, host_load_snapshot


def test_snapshot_shape():
    snap = host_load_snapshot()
    assert snap["nproc"] >= 1
    assert snap["loadavg_1m"] is None or snap["loadavg_1m"] >= 0.0
    for proc in snap["competing_python"]:
        assert proc["pid"] != os.getpid()
        assert "python" in proc["cmd"]


def test_snapshot_excludes_self_and_ancestors():
    pids = {p["pid"] for p in host_load_snapshot()["competing_python"]}
    assert os.getpid() not in pids
    assert os.getppid() not in pids


def test_lock_excludes_second_holder(tmp_path):
    path = str(tmp_path / "bench.lock")
    first, second = BenchLock(path), BenchLock(path)
    assert first.acquire(wait_s=1) is True
    # flock is per open-file-description: a second open of the same path
    # contends even within one process — exactly the cross-process case
    assert second.acquire(wait_s=0.2, poll_s=0.05) is False
    rec = second.record()
    assert rec["acquired"] is False and rec["holder_pid"] == os.getpid()
    first.release()
    assert second.acquire(wait_s=1, poll_s=0.05) is True
    assert second.record() == {"acquired": True, "waited_s": second.waited_s}
    second.release()


def test_lock_released_on_context_exit(tmp_path):
    path = str(tmp_path / "bench.lock")
    with BenchLock(path) as held:
        assert held.acquired
    again = BenchLock(path)
    assert again.acquire(wait_s=0.5, poll_s=0.05) is True
    again.release()
