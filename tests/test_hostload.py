"""Host-load provenance + bench lock (stmgcn_tpu/utils/hostload.py).

The lock is what keeps the measurement machinery from depressing its own
records on this 1-core host (BASELINE.md round 4: concurrent probe
children cost the driver's record 4-20%); the snapshot is what makes a
contended record detectable in-band. Both are pure host-side code — fast
tier."""

import os

import pytest

from stmgcn_tpu.utils.hostload import (
    PROBE_MARKER,
    PROBE_SRC,
    BenchLock,
    host_load_snapshot,
    is_contended,
    wait_for_probe_children,
)


def test_snapshot_shape():
    snap = host_load_snapshot()
    assert snap["nproc"] >= 1
    assert snap["loadavg_1m"] is None or snap["loadavg_1m"] >= 0.0
    for proc in snap["competing_python"]:
        assert proc["pid"] != os.getpid()
        assert "python" in proc["cmd"]


def test_snapshot_excludes_self_and_ancestors():
    pids = {p["pid"] for p in host_load_snapshot()["competing_python"]}
    assert os.getpid() not in pids
    assert os.getppid() not in pids


def test_is_contended_detects_either_side():
    quiet = {"competing_python": []}
    busy = {"competing_python": [{"pid": 1, "cmd": "python x.py"}]}
    assert is_contended({"before": quiet, "after": quiet}) is False
    assert is_contended({"before": busy, "after": quiet}) is True
    assert is_contended({"before": quiet, "after": busy}) is True
    assert is_contended({"before": busy, "after": busy}) is True


def test_is_contended_tolerates_missing_fields():
    # records from older schema versions / partial probes must not crash
    assert is_contended({}) is False
    assert is_contended({"before": None, "after": None}) is False
    assert is_contended({"before": {}, "after": {}}) is False
    assert is_contended({"after": {"competing_python": [{"pid": 2}]}}) is True


def test_lock_excludes_second_holder(tmp_path):
    path = str(tmp_path / "bench.lock")
    first, second = BenchLock(path), BenchLock(path)
    assert first.acquire(wait_s=1) is True
    # flock is per open-file-description: a second open of the same path
    # contends even within one process — exactly the cross-process case
    assert second.acquire(wait_s=0.2, poll_s=0.05) is False
    rec = second.record()
    assert rec["acquired"] is False and rec["holder_pid"] == os.getpid()
    first.release()
    assert second.acquire(wait_s=1, poll_s=0.05) is True
    assert second.record() == {"acquired": True, "waited_s": second.waited_s}
    second.release()


def test_lock_released_on_context_exit(tmp_path):
    path = str(tmp_path / "bench.lock")
    with BenchLock(path) as held:
        assert held.acquired
    again = BenchLock(path)
    assert again.acquire(wait_s=0.5, poll_s=0.05) is True
    again.release()


def test_wait_for_probe_children_drains_and_bounds():
    """The drain recognizes probe children by a marker DERIVED from
    PROBE_SRC (so the two cannot drift), waits for a short-lived one,
    and gives up at its budget on a long-lived one."""
    import subprocess
    import sys
    import time

    from stmgcn_tpu.utils.hostload import _competing_python

    assert PROBE_MARKER in PROBE_SRC  # the shared derivation, imported

    def probe_pids():
        # generous cap: the default 16 could hide the fake child behind
        # unrelated python processes on a busy host
        return {
            p["pid"]
            for p in _competing_python(max_procs=256)
            if PROBE_MARKER in p["cmd"]
        }

    def foreign(ours):
        return probe_pids() - {ours}

    def spawn(seconds):
        child = subprocess.Popen(
            [
                sys.executable,
                "-c",
                f"import time\n# {PROBE_MARKER}\ntime.sleep({seconds})",
            ]
        )
        deadline = time.monotonic() + 10  # fork/exec race: wait until seen
        while child.pid not in probe_pids():
            assert time.monotonic() < deadline, "fake probe never visible"
            time.sleep(0.1)
        return child

    short = spawn(3)
    drained = wait_for_probe_children(max_wait_s=30, poll_s=0.5)
    if not drained and foreign(short.pid):
        # a REAL recovery-loop probe started mid-test and legitimately
        # kept the drain waiting — not this test's concern
        short.kill()
        short.wait()
        pytest.skip("live backend probe in flight on this host")
    assert drained is True
    assert short.poll() is not None  # it genuinely waited the child out
    short.wait()

    stuck = spawn(60)
    try:
        assert wait_for_probe_children(max_wait_s=2, poll_s=0.5) is False
    finally:
        stuck.kill()
        stuck.wait()


def _hold_lock(path):
    import time as _time

    lock = BenchLock(path)
    assert lock.acquire(wait_s=5)
    with open(path + ".held", "w") as f:
        f.write("1")
    _time.sleep(30)  # parent kills us long before this expires


def test_lock_excludes_across_processes(tmp_path):
    """The real deployment shape: bench.py in one process, the recovery
    loop in another. Also pins kernel-release-on-death (a killed holder
    must not leave a stale lock). Fork context deliberately: a spawn
    child would re-import this module -> the stmgcn_tpu package -> jax,
    which on this image can dial the wedged axon tunnel and hang.
    Handshake via a sentinel file, not mp.Event — a SIGKILLed holder of
    an Event semaphore wedges multiprocessing's teardown."""
    import multiprocessing as mp
    import time

    ctx = mp.get_context("fork")
    path = str(tmp_path / "bench.lock")
    child = ctx.Process(target=_hold_lock, args=(path,), daemon=True)
    child.start()
    try:
        deadline = time.monotonic() + 20
        while not os.path.exists(path + ".held"):
            assert child.is_alive(), f"child died early, exitcode {child.exitcode}"
            assert time.monotonic() < deadline, "child never acquired"
            time.sleep(0.05)
        mine = BenchLock(path)
        assert mine.acquire(wait_s=0.3, poll_s=0.05) is False
        assert mine.record()["holder_pid"] == child.pid
        # killed holder: the kernel releases the flock with the process
        child.kill()
        child.join(10)
        assert mine.acquire(wait_s=5, poll_s=0.1) is True
        mine.release()
    finally:
        if child.is_alive():
            child.kill()
        child.join(5)
