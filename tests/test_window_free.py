"""Window-free resident data: on-device gather parity and plumbing.

The window-free path keeps ONE normalized ``(T, N, C)`` series resident
per city (plus int32 target/offset vectors) and reconstructs every
microbatch inside the jitted step by pure index copies
(``train/step.py gather_window_batch``) — no window arrays are ever
materialized. Because the gather is index arithmetic with no float math,
parity against the materialized-window oracle is exact equality, not
allclose: per-batch losses, histories, params, and opt-state must match
bit for bit across shuffle on/off, per-step/superstep dispatch, horizon
1 and H>1, padded tail batches, and a SIGTERM mid-epoch resume.
"""

import jax
import numpy as np
import pytest

from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.resilience import FaultPlan, FaultSpec, Preempted
from stmgcn_tpu.train import Trainer

BATCH = 8


def build(out_dir, *, window_free=None, horizon=1, shuffle=False, superstep=1,
          epochs=2, placement="resident", **kw):
    data = synthetic_dataset(rows=5, n_timesteps=24 * 7 * 2 + 60, seed=1)
    dataset = DemandDataset(data, WindowSpec(3, 1, 1, 24, horizon=horizon))
    sup = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                   horizon=horizon, lstm_hidden_dim=8, lstm_num_layers=1,
                   gcn_hidden_dim=8)
    return Trainer(model, dataset, sup, n_epochs=epochs, batch_size=BATCH,
                   shuffle=shuffle, steps_per_superstep=superstep,
                   data_placement=placement, window_free=window_free,
                   out_dir=str(out_dir), verbose=False, **kw)


def same(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


class TestTrainerParity:
    """window_free=True vs the materialized oracle (window_free=False):
    bit-identical histories and final state, with the window-free run
    proving it never built a window array."""

    @pytest.mark.parametrize("shuffle,superstep,horizon", [
        (False, 1, 1),
        (True, 3, 1),
        (False, 3, 4),
        pytest.param(True, 1, 4, marks=pytest.mark.slow),
    ])
    def test_bit_identical_to_materialized(self, tmp_path, shuffle, superstep,
                                           horizon):
        wf = build(tmp_path / "wf", window_free=True, shuffle=shuffle,
                   superstep=superstep, horizon=horizon)
        oracle = build(tmp_path / "mat", window_free=False, shuffle=shuffle,
                       superstep=superstep, horizon=horizon)
        assert wf._window_free and not oracle._window_free

        wf_hist = wf.train()
        oracle_hist = oracle.train()

        # the window-free run must never have materialized windows; the
        # oracle must have (that's what makes it the oracle)
        assert not wf.dataset.materialized
        assert oracle.dataset.materialized

        # coverage precondition: the epoch ends in a padded tail batch
        tail = list(
            wf.dataset.batches("train", BATCH, pad_last=True, with_arrays=False)
        )[-1]
        assert tail.n_real < BATCH

        np.testing.assert_array_equal(wf_hist["train"], oracle_hist["train"])
        np.testing.assert_array_equal(
            wf_hist["validate"], oracle_hist["validate"]
        )
        same(wf.params, oracle.params)
        same(jax.tree.leaves(wf.opt_state), jax.tree.leaves(oracle.opt_state))

    def test_default_is_window_free_when_resident(self, tmp_path):
        tr = build(tmp_path)  # window_free=None, resident placement
        assert tr._resident and tr._window_free

    def test_streaming_placement_refuses_window_free(self, tmp_path):
        with pytest.raises(ValueError, match="resident"):
            build(tmp_path, window_free=True, placement="stream")
        # but auto window-free just degrades with the placement
        tr = build(tmp_path / "s", placement="stream")
        assert not tr._window_free

    def test_hetero_dataset_supports_window_free(self, tmp_path):
        """Heterogeneous datasets delegate the window-free protocol per
        city (data/hetero.py) — once a hard refusal, now the substrate
        the fleet fast path builds on (tests/test_fleet.py pins the
        bit-parity)."""
        from stmgcn_tpu.config import preset
        from stmgcn_tpu.experiment import build_trainer

        cfg = preset("multicity")
        cfg.data.city_rows = (4, 3)
        cfg.data.city_timesteps = (24 * 7 * 2 + 24, 24 * 7 * 2)
        cfg.mesh.dp = 1
        cfg.train.window_free = True
        cfg.train.epochs = 1
        cfg.train.out_dir = str(tmp_path)
        tr = build_trainer(cfg, verbose=False)
        assert tr._window_free and not tr.dataset.materialized
        assert tr.dataset.resident_nbytes < tr.dataset.nbytes


def test_cli_and_config_plumbing():
    from stmgcn_tpu.cli import build_parser, config_from_args

    p = build_parser()
    assert config_from_args(p.parse_args([])).train.window_free is None
    wf = config_from_args(p.parse_args(["--window-free"]))
    assert wf.train.window_free is True
    mat = config_from_args(p.parse_args(["--no-window-free"]))
    assert mat.train.window_free is False


class TestWindowFreeResume:
    """Mid-epoch SIGTERM on the window-free path: resume must end
    bit-identical to the uninterrupted window-free run (same drill as
    test_resilience.TestResumeParity, on the new data path)."""

    @pytest.mark.parametrize("shuffle,superstep", [
        (False, 1),
        pytest.param(True, 3, marks=pytest.mark.slow),
    ])
    def test_sigterm_resume_bit_exact(self, tmp_path, shuffle, superstep):
        ref = build(tmp_path / "ref", window_free=True, shuffle=shuffle,
                    superstep=superstep)
        ref_hist = ref.train()

        plan = FaultPlan(FaultSpec("sigterm", epoch=2, step=4))
        faulted = build(tmp_path / "run", window_free=True, fault_plan=plan,
                        shuffle=shuffle, superstep=superstep)
        with pytest.raises(Preempted, match="--resume auto"):
            faulted.train()

        resumed = build(tmp_path / "run", window_free=True, shuffle=shuffle,
                        superstep=superstep)
        meta = resumed.restore_auto()
        assert meta is not None
        assert meta["epoch"] == 2 and meta["batch_in_epoch"] > 0
        hist = resumed.train()

        assert resumed._window_free and not resumed.dataset.materialized
        same(ref.params, resumed.params)
        same(jax.tree.leaves(ref.opt_state), jax.tree.leaves(resumed.opt_state))
        assert hist["train"][-1] == ref_hist["train"][-1]
        assert hist["validate"][-1] == ref_hist["validate"][-1]


class TestWindowFreeData:
    """Dataset-level contracts behind the trainer path: gather-index
    parity with the materialized arrays, laziness, and the footprint."""

    @pytest.mark.parametrize("n_cities,horizon", [(1, 1), (2, 3)])
    def test_mode_targets_reconstruct_arrays(self, n_cities, horizon):
        datas = [
            synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 40, seed=c)
            for c in range(n_cities)
        ]
        ds = DemandDataset(
            datas if n_cities > 1 else datas[0],
            WindowSpec(4, 1, 1, 24, horizon=horizon),
        )
        stack = ds.series_stack()
        offsets = ds.window.offsets
        for mode in ("train", "validate", "test"):
            x, y = ds.arrays(mode)
            tgt = ds.mode_targets(mode)
            np.testing.assert_array_equal(x, stack[tgt[:, None] + offsets])
            if horizon == 1:
                np.testing.assert_array_equal(y, stack[tgt])
            else:
                np.testing.assert_array_equal(
                    y, stack[tgt[:, None] + np.arange(horizon)]
                )
            for c in range(n_cities):
                xc, yc = ds.city_arrays(mode, c)
                tc = ds.mode_targets(mode, city=c)
                np.testing.assert_array_equal(
                    xc, ds.series(c)[tc[:, None] + offsets]
                )

    def test_index_batches_never_materialize(self):
        data = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 40, seed=0)
        ds = DemandDataset(data, WindowSpec(4, 1, 1, 24))
        batches = list(ds.batches("train", BATCH, pad_last=True,
                                  with_arrays=False))
        assert batches and not ds.materialized
        assert all(b.x is None for b in batches)
        ds.arrays("train")  # the materialized path still works on demand
        assert ds.materialized

    def test_resident_footprint_math(self):
        data = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 40, seed=0)
        ds = DemandDataset(data, WindowSpec(10, 1, 1, 24))
        # the window-free footprint is the acceptance-level >=4x smaller,
        # and the analytic nbytes equals the real materialized bytes
        assert ds.nbytes >= 4 * ds.resident_nbytes
        ds.materialize()
        real = sum(a.nbytes for a in ds._xs) + sum(a.nbytes for a in ds._ys)
        assert ds.nbytes == real
