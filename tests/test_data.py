"""Data pipeline tests (SURVEY.md §4: C1-C6 windowing/normalization/split parity)."""

import numpy as np
import pytest

from stmgcn_tpu.data import (
    Batch,
    DemandDataset,
    MinMaxNormalizer,
    StdNormalizer,
    WindowSpec,
    date_splits,
    grid_adjacency,
    load_npz,
    sliding_windows,
    synthetic_dataset,
    synthetic_demand,
)
from stmgcn_tpu.data.splits import fraction_splits


def loop_windows(data, s, d, w, day_steps):
    """Straightforward per-timestep loop implementing the pinned reference
    semantics (SURVEY.md §2 C3/C5: burn-in, skip strides d*day_steps and
    w*day_steps*7, oldest-first periodic order, [weekly|daily|serial] concat).
    Used as the oracle for the vectorized gather."""
    serial, daily, weekly, ys = [], [], [], []
    # corrected burn-in: covers the deepest periodic lag p_len**2 * period
    # (the reference's own start_idx under-covers for p_len >= 2 and wraps)
    start = max(s, d * d * day_steps, w * w * day_steps * 7)
    for i in range(start, len(data)):
        serial.append(data[i - s : i])
        daily.append(np.array([data[i - d * day_steps * k] for k in range(1, d + 1)][::-1]))
        weekly.append(np.array([data[i - w * day_steps * 7 * k] for k in range(1, w + 1)][::-1]))
        ys.append(data[i])
    parts = [np.array(weekly), np.array(daily), np.array(serial)]
    parts = [p for p in parts if p.ndim != 2]  # Data_Container.py:84 empty-seq test
    return np.concatenate(parts, axis=1), np.array(ys)


class TestWindowing:
    @pytest.mark.parametrize(
        "s,d,w,day_steps",
        [(3, 1, 1, 24), (2, 2, 1, 24), (3, 0, 0, 24), (0, 1, 0, 24),
         (0, 0, 2, 4), (5, 2, 2, 4), (1, 1, 1, 4)],
    )
    def test_matches_loop_oracle(self, s, d, w, day_steps):
        spec = WindowSpec(s, d, w, day_steps)
        T = spec.burn_in + 50
        data = np.random.default_rng(0).standard_normal((T, 6, 2)).astype(np.float32)
        x, y = sliding_windows(data, spec)
        x_ref, y_ref = loop_windows(data, s, d, w, day_steps)
        assert x.shape == (T - spec.burn_in, spec.seq_len, 6, 2)
        np.testing.assert_array_equal(x, x_ref)
        np.testing.assert_array_equal(y, y_ref)

    def test_burn_in_and_seq_len(self):
        spec = WindowSpec(3, 1, 1, 24)  # the reference default (-cpt 3 1 1)
        assert spec.seq_len == 5
        assert spec.burn_in == 168

    def test_too_short_series_raises(self):
        with pytest.raises(ValueError, match="burn_in"):
            sliding_windows(np.zeros((168, 4, 1)), WindowSpec(3, 1, 1, 24))

    def test_all_empty_raises(self):
        with pytest.raises(ValueError):
            WindowSpec(0, 0, 0, 24)

    def test_negative_length_raises(self):
        with pytest.raises(ValueError):
            WindowSpec(-1, 1, 1, 24)


class TestNormalize:
    def test_minmax_range_and_roundtrip(self):
        x = np.random.default_rng(1).gamma(2.0, 20.0, size=(100, 5, 1))
        norm = MinMaxNormalizer.fit(x)
        z = norm.transform(x)
        assert z.min() == pytest.approx(-1.0) and z.max() == pytest.approx(1.0)
        np.testing.assert_allclose(norm.inverse(z), x, rtol=1e-12)

    def test_std_roundtrip(self):
        x = np.random.default_rng(2).standard_normal((50, 3))
        norm = StdNormalizer.fit(x)
        z = norm.transform(x)
        assert abs(z.mean()) < 1e-12 and z.std() == pytest.approx(1.0)
        np.testing.assert_allclose(norm.inverse(z), x, atol=1e-12)

    def test_serialization_roundtrip(self):
        from stmgcn_tpu.data.normalize import normalizer_from_dict

        norm = MinMaxNormalizer(minimum=-3.0, maximum=7.0)
        assert normalizer_from_dict(norm.to_dict()) == norm


class TestSplits:
    def test_reference_default_dates(self):
        # Main.py defaults: -date 0101 0630 0701 0731, dt=1, val_ratio=0.2
        spec = date_splits(["0101", "0630", "0701", "0731"], day_timesteps=24,
                           val_ratio=0.2, year=2017, burn_in=168)
        # 181 train days * 24 = 4344; val = int(4344*0.2) = 868; train = 3476
        assert spec.mode_len == {"train": 3476, "validate": 868, "test": 744}
        assert spec.start_idx == 0  # clamped: 0101 starts inside the burn-in
        assert spec.range_for("train") == (0, 3476)
        assert spec.range_for("validate") == (3476, 4344)
        assert spec.range_for("test") == (4344, 5088)

    def test_unit_bug_fix_mid_year_start(self):
        # Reference would index sample arrays with the *day* index 14
        # (SURVEY.md §2 quirk 3); correct is 14*24 - burn_in timesteps.
        spec = date_splits(["0115", "0131", "0201", "0207"], day_timesteps=24,
                           burn_in=168)
        assert spec.start_idx == 14 * 24 - 168

    def test_bounds_check(self):
        with pytest.raises(ValueError, match="only"):
            date_splits(["0101", "0630", "0701", "0731"], day_timesteps=24,
                        burn_in=168, n_samples=100)

    def test_descending_dates_raise(self):
        with pytest.raises(ValueError, match="ascending"):
            date_splits(["0630", "0101", "0701", "0731"], burn_in=168)

    def test_fraction_splits(self):
        spec = fraction_splits(100, train=0.7, validate=0.1)
        assert spec.mode_len == {"train": 70, "validate": 10, "test": 20}
        with pytest.raises(ValueError):
            fraction_splits(100, train=0.9, validate=0.2)


class TestLoader:
    def test_roundtrip_and_key_gating(self, tmp_path):
        ds = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2)
        path = tmp_path / "data_dict.npz"
        np.savez(path, taxi=ds.demand, **ds.adjs)
        for m in (1, 2, 3):
            loaded = load_npz(str(path), m_graphs=m)
            assert loaded.n_graphs == m
            assert list(loaded.adjs)[:1] == ["neighbor_adj"]
        np.testing.assert_array_equal(loaded.demand, ds.demand)

    def test_2d_demand_expanded(self, tmp_path):
        path = tmp_path / "d.npz"
        np.savez(path, taxi=np.zeros((10, 4)), neighbor_adj=np.eye(4))
        assert load_npz(str(path), m_graphs=1).demand.shape == (10, 4, 1)

    def test_missing_demand_key(self, tmp_path):
        path = tmp_path / "d.npz"
        np.savez(path, other=np.zeros((10, 4)))
        with pytest.raises(KeyError):
            load_npz(str(path), m_graphs=1)

    def test_too_few_adjs(self, tmp_path):
        path = tmp_path / "d.npz"
        np.savez(path, taxi=np.zeros((10, 4, 1)), neighbor_adj=np.eye(4))
        with pytest.raises(ValueError, match="adjacency"):
            load_npz(str(path), m_graphs=3)

    def test_adj_shape_mismatch(self, tmp_path):
        path = tmp_path / "d.npz"
        np.savez(path, taxi=np.zeros((10, 4, 1)), neighbor_adj=np.eye(5))
        with pytest.raises(ValueError, match="shape"):
            load_npz(str(path), m_graphs=1)

    def test_custom_adj_keys_after_canonical(self, tmp_path):
        path = tmp_path / "d.npz"
        np.savez(path, taxi=np.zeros((10, 4, 1)), neighbor_adj=np.eye(4),
                 road_adj=np.eye(4))
        loaded = load_npz(str(path), m_graphs=2)
        assert list(loaded.adjs) == ["neighbor_adj", "road_adj"]


class TestSynthetic:
    def test_shapes_and_nonnegativity(self):
        ds = synthetic_dataset(rows=5, n_timesteps=24 * 7 * 2)
        assert ds.demand.shape == (24 * 7 * 2, 25, 1)
        assert (ds.demand >= 0).all()
        assert ds.n_graphs == 3
        for a in ds.adj_list():
            assert a.shape == (25, 25)
            np.testing.assert_array_equal(a, a.T)
            assert np.diag(a).sum() == 0

    def test_grid_adjacency_degree(self):
        adj = grid_adjacency(3)
        # corner degree 2, edge 3, center 4
        deg = adj.sum(1)
        assert sorted(deg.tolist()) == [2, 2, 2, 2, 3, 3, 3, 3, 4]


class TestPipeline:
    def make(self, **kw):
        ds = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=3)
        return DemandDataset(ds, WindowSpec(3, 1, 1, 24), **kw)

    def test_split_views_and_denormalize(self):
        raw = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=3)
        dd = DemandDataset(raw, WindowSpec(3, 1, 1, 24))
        x, y = dd.arrays("train")
        assert x.shape[1:] == (5, 9, 1)
        # denormalized targets reproduce the raw demand exactly
        np.testing.assert_allclose(
            dd.denormalize(y), raw.demand[168 : 168 + len(y)], rtol=1e-5, atol=1e-4
        )

    def test_normalize_kind_selection(self):
        raw = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=3)
        std = DemandDataset(raw, WindowSpec(3, 1, 1, 24), normalize="std")
        from stmgcn_tpu.data.normalize import StdNormalizer

        assert isinstance(std.normalizer, StdNormalizer)
        _, y = std.arrays("train")
        np.testing.assert_allclose(
            std.denormalize(y), raw.demand[168 : 168 + len(y)], rtol=1e-4, atol=1e-3
        )
        none = DemandDataset(raw, WindowSpec(3, 1, 1, 24), normalize="none")
        assert none.normalizer is None
        _, y_raw = none.arrays("train")
        np.testing.assert_allclose(y_raw, raw.demand[168 : 168 + len(y_raw)], rtol=1e-6)
        # bool back-compat + bad kind fails loudly
        assert DemandDataset(raw, WindowSpec(3, 1, 1, 24), normalize=False).normalizer is None
        with np.testing.assert_raises(ValueError):
            DemandDataset(raw, WindowSpec(3, 1, 1, 24), normalize="zscore")

    def test_normalize_config_reaches_dataset(self):
        from stmgcn_tpu.config import preset
        from stmgcn_tpu.data.normalize import StdNormalizer
        from stmgcn_tpu.experiment import build_dataset

        cfg = preset("smoke")
        cfg.data.n_timesteps = 24 * 7 * 2
        cfg.data.normalize = "std"
        assert isinstance(build_dataset(cfg).normalizer, StdNormalizer)

    def test_percity_graphs_batching(self):
        # cities with differing graphs: accepted, batches never mix cities,
        # every split sees every city (VERDICT round-1 missing #5)
        a = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=3)
        b = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=7)
        assert not np.array_equal(a.adjs["semantic_adj"], b.adjs["semantic_adj"])
        dd = DemandDataset([a, b], WindowSpec(3, 1, 1, 24))
        assert not dd.shared_graphs
        for mode in ("train", "validate", "test"):
            batches = list(dd.batches(mode, 16, pad_last=True))
            assert {bt.city for bt in batches} == {0, 1}
            assert len(batches) == dd.num_batches(mode, 16)
            assert sum(bt.n_real for bt in batches) == dd.mode_size(mode)
        # per-city slices come from the right city
        x0, _ = dd.city_arrays("train", 0)
        first = next(iter(dd.batches("train", 16)))
        np.testing.assert_array_equal(first.x, x0[:16])

    def test_percity_mismatched_graph_keys_raise(self):
        a = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=3, m_graphs=3)
        b = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=4, m_graphs=2)
        with np.testing.assert_raises(ValueError):
            DemandDataset([a, b], WindowSpec(3, 1, 1, 24))

    def test_shared_graph_cities_detected(self):
        a = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=3)
        b = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 3, seed=9)
        b.adjs = a.adjs
        dd = DemandDataset([a, b], WindowSpec(3, 1, 1, 24))
        assert dd.shared_graphs
        assert all(bt.city == 0 for bt in dd.batches("train", 32))

    def test_batch_iteration_counts(self):
        dd = self.make()
        n = dd.split.mode_len["train"]
        batches = list(dd.batches("train", 32))
        assert len(batches) == -(-n // 32) == dd.num_batches("train", 32)
        assert sum(b.n_real for b in batches) == n
        assert all(isinstance(b, Batch) for b in batches)

    def test_pad_last_static_shapes(self):
        dd = self.make()
        batches = list(dd.batches("validate", 32, pad_last=True))
        assert all(len(b) == 32 for b in batches)
        assert batches[-1].n_real == (dd.split.mode_len["validate"] % 32 or 32)

    def test_drop_last(self):
        dd = self.make()
        n = dd.split.mode_len["train"]
        batches = list(dd.batches("train", 32, drop_last=True))
        assert len(batches) == n // 32
        assert all(len(b) == 32 for b in batches)

    def test_shuffle_deterministic_per_epoch(self):
        dd = self.make()
        a = [b.y for b in dd.batches("train", 16, shuffle=True, seed=7, epoch=1)]
        b = [b.y for b in dd.batches("train", 16, shuffle=True, seed=7, epoch=1)]
        c = [b.y for b in dd.batches("train", 16, shuffle=True, seed=7, epoch=2)]
        np.testing.assert_array_equal(np.concatenate(a), np.concatenate(b))
        assert not np.array_equal(np.concatenate(a), np.concatenate(c))

    def test_batch_xy_alignment_under_shuffle(self):
        dd = self.make()
        x_all, y_all = dd.arrays("train")
        for b in dd.batches("train", 16, shuffle=True, seed=1):
            for bx, by in zip(b.x, b.y):
                # each y must be the sample following its own x window
                matches = np.where((y_all == by).all(axis=(1, 2)))[0]
                assert any((x_all[m] == bx).all() for m in matches)
            break
