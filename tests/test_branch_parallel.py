"""Branch model parallelism: the M graph branches sharded over a mesh axis.

The branches are independent until the sum fusion (the reference runs
them *sequentially*, STMGCN.py:112-115); with the vmapped stacked layout
their params and supports shard over a ``branch`` mesh axis and GSPMD
turns the fusion into one psum — the expert-parallel analogue for this
model family. Contract: identical losses/trajectories vs single device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import MeshConfig, preset
from stmgcn_tpu.experiment import build_trainer, route_supports, build_dataset
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.parallel import MeshPlacement, build_mesh, mesh_from_config
from stmgcn_tpu.train import make_optimizer, make_step_fns


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()[:8]


def problem(M=2, N=16, B=8, T=5):
    rng = np.random.default_rng(0)
    sup = (rng.standard_normal((M, 3, N, N)) * 0.2).astype(np.float32)
    x = rng.standard_normal((B, T, N, 1)).astype(np.float32)
    y = (rng.standard_normal((B, N, 1)) * 0.1).astype(np.float32)
    model = STMGCN(m_graphs=M, n_supports=3, seq_len=T, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=2, gcn_hidden_dim=8)
    return model, sup, x, y


class TestMesh3Axis:
    def test_branch_axis_only_when_needed(self, eight_devices):
        assert build_mesh(dp=2, region=2).shape == {"dp": 2, "region": 2}
        m = build_mesh(dp=2, region=2, branch=2)
        assert m.shape == {"dp": 2, "region": 2, "branch": 2}
        assert mesh_from_config(MeshConfig(dp=2, branch=2)).shape == {
            "dp": 2, "region": 1, "branch": 2}

    def test_divisibility(self, eight_devices):
        pl = MeshPlacement(build_mesh(dp=1, region=1, branch=2))
        pl.check_divisibility(8, 16, m_graphs=2)
        with pytest.raises(ValueError, match="m_graphs"):
            pl.check_divisibility(8, 16, m_graphs=3)


class TestBranchParallelParity:
    @pytest.mark.parametrize("dp,region,branch", [(4, 1, 2), (2, 2, 2), (1, 1, 2)])
    @pytest.mark.slow
    def test_training_trajectory_matches_single_device(
        self, eight_devices, dp, region, branch
    ):
        model, sup, x, y = problem()
        fns = make_step_fns(model, make_optimizer(1e-2, 1e-4), "mse")
        mask = np.ones(x.shape[0], np.float32)

        params, opt = fns.init(jax.random.key(0), jnp.asarray(sup), jnp.asarray(x))
        single = []
        p, o = params, opt
        for _ in range(3):
            p, o, loss = fns.train_step(p, o, jnp.asarray(sup), jnp.asarray(x),
                                        jnp.asarray(y), jnp.asarray(mask))
            single.append(float(loss))

        pl = MeshPlacement(build_mesh(dp=dp, region=region, branch=branch))
        fns2 = make_step_fns(model, make_optimizer(1e-2, 1e-4), "mse")
        pm, om = fns2.init(jax.random.key(0), jnp.asarray(sup), jnp.asarray(x))
        pm, om = pl.put(pm, "state"), pl.put(om, "state")
        sup_m, x_m = pl.put(sup, "supports"), pl.put(x, "x")
        y_m, mask_m = pl.put(y, "y"), pl.put(mask, "mask")
        mesh_losses = []
        for _ in range(3):
            pm, om, loss = fns2.train_step(pm, om, sup_m, x_m, y_m, mask_m)
            mesh_losses.append(float(loss))
        np.testing.assert_allclose(mesh_losses, single, rtol=1e-5)
        # stacked branch params genuinely shard over the branch axis
        wh = pm["params"]["branches"]["cg_lstm"]["lstm"]["wh_0"]
        assert wh.sharding.spec[0] == "branch"

    def test_trainer_end_to_end_on_branch_mesh(self, eight_devices, tmp_path):
        cfg = preset("multicity")
        cfg.data.override(rows=4, n_cities=1, n_timesteps=24 * 7 * 2 + 24)
        cfg.model.m_graphs = 3
        cfg.train.epochs = 1
        cfg.train.batch_size = 16
        cfg.train.out_dir = str(tmp_path)
        cfg.mesh.dp, cfg.mesh.region, cfg.mesh.branch = 2, 1, 3  # 6 devices
        trainer = build_trainer(cfg, verbose=False)
        hist = trainer.train()
        assert np.isfinite(hist["train"][0])
        assert np.isfinite(trainer.test(modes=("test",))["test"]["rmse"])


class TestBranchGuards:
    def test_branch_composes_with_sparse_and_banded(self):
        from stmgcn_tpu.parallel import ShardedBlockSparse

        cfg = preset("smoke")
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.mesh.dp, cfg.mesh.branch = 1, 1  # keep n_devices small for build
        cfg.mesh.branch = 2
        # sparse x branch: stacks regardless of graph structure (block-CSR
        # handles arbitrary sparsity; round 5, tests/test_branch_banded.py)
        cfg.model.m_graphs = 2  # grid + random transport links
        cfg.model.sparse = True
        ds2 = build_dataset(cfg)
        sup, modes = route_supports(cfg, ds2)
        assert modes == ("sparse", "sparse")
        assert isinstance(sup, ShardedBlockSparse) and sup.branch_stacked
        # banded x branch needs every branch within the halo budget: the
        # transport graph (bandwidth ~N) disqualifies, so 'auto' falls
        # back to the all-dense GSPMD branch plan instead of erroring
        cfg.model.sparse = False
        cfg.mesh.region = 2
        cfg.mesh.region_strategy = "auto"
        _, modes = route_supports(cfg, ds2)
        assert modes is None  # GSPMD fallback, not an error
        # ... and 'banded' demands every branch qualify
        cfg.mesh.region_strategy = "banded"
        with pytest.raises(ValueError, match="every branch banded"):
            route_supports(cfg, ds2)
        # smoke's own single neighborhood graph IS banded: it stacks
        cfg.model.m_graphs = 1
        cfg.mesh.halo = None
        ds1 = build_dataset(cfg)
        sup, modes = route_supports(cfg, ds1)
        assert set(modes) == {"banded"} and sup.branch_stacked
