"""Composed multi-chip execution: the mesh presets run the REAL programs.

PR 20's tentpole: the fused window-free / fleet superstep programs the
trainer dispatches are now the same programs the mesh presets shard
(``parallel/compose.py``) and the same programs ``analysis/spmd_check``
certifies. These tests pin the execution half of that contract on the
8-virtual-device substrate (``tests/conftest.py`` forces it):

- **Parity**: each preset's composed trainer vs its twin
  (``parity_twin_kind``): dense presets against a true single-device
  build of the identical config, banded presets against the per-step
  loop on the same mesh. The homogeneous supersteps (``branchpar``,
  ``scaled``, ``bandedbranch``) are **bit-exact** over the full loss
  history — the in-scan gradient psum and the banded halo plan reorder
  nothing on these shapes. The ``multicity`` fleet program's per-class
  psum DOES reassociate the dp-sharded gradient sum, so its pin is
  allclose at f32 reduction-order resolution (~1e-7 observed), not
  bitwise — recorded honestly rather than papered over.
- **Sharded tiled apply**: ``ops/tiling.shard_tiled_plan`` +
  ``sharded_gathered_tiles_apply`` against the single-device
  gathered-tiles oracle, forward and prepared backward, bit-exact (the
  halo exchange moves whole blocks; no cross-shard reductions exist).
- **Resume drill**: SIGTERM mid-epoch on the sharded superstep path,
  reusing the PR 3 machinery — resume must end bit-identical to the
  uninterrupted sharded run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.parallel.compose import (
    COMPOSED_PRESETS,
    composed_config,
    composed_trainer,
    parity_twin_kind,
)
from stmgcn_tpu.resilience import FaultPlan, FaultSpec, Preempted

#: f32 reduction-order resolution for the fleet psum reassociation
FLEET_RTOL = 2e-5

#: presets whose composed program is bit-exact against its twin
BITEXACT = ("branchpar", "scaled", "bandedbranch")


def same(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


def close(a, b, rtol=1e-3, atol=1e-5):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x, np.float32), np.asarray(y, np.float32),
            rtol=rtol, atol=atol,
        ),
        a,
        b,
    )


class TestComposedParity:
    @pytest.mark.parametrize("name", list(COMPOSED_PRESETS))
    def test_composed_vs_twin(self, tmp_path, name):
        composed = composed_trainer(name, out_dir=str(tmp_path / "mesh"))
        twin = composed_trainer(
            name, twin=parity_twin_kind(name), out_dir=str(tmp_path / "twin")
        )
        # the composed side must actually be the fused mesh program —
        # a silent fallback to per-step would pass parity vacuously
        assert composed._meshy
        assert composed.train_path in ("series_superstep", "fleet_superstep")
        assert composed._window_free and not composed.dataset.materialized

        h_mesh = composed.train()
        h_twin = twin.train()

        mesh_tr = np.asarray(h_mesh["train"])
        twin_tr = np.asarray(h_twin["train"])
        if name in BITEXACT:
            np.testing.assert_array_equal(mesh_tr, twin_tr)
            np.testing.assert_array_equal(
                np.asarray(h_mesh["validate"]), np.asarray(h_twin["validate"])
            )
            # final params: the last update's psum isn't reflected in any
            # recorded loss, and its reassociation can move single
            # elements by ~1 ulp — allclose, histories stay bitwise
            close(composed.params, twin.params)
        else:  # multicity fleet: dp-psum reassociation, allclose not bitwise
            np.testing.assert_allclose(mesh_tr, twin_tr, rtol=FLEET_RTOL)
            np.testing.assert_allclose(
                np.asarray(h_mesh["validate"]),
                np.asarray(h_twin["validate"]),
                rtol=FLEET_RTOL,
            )

    def test_dp_branch_bf16_bit_exact(self, tmp_path):
        """The bf16 superstep twin composes identically: mixed-precision
        islands keep the psum in f32, so the dp x branch program stays
        bit-exact against its single-device build."""
        from stmgcn_tpu.config import MeshConfig
        from stmgcn_tpu.experiment import build_trainer

        cfg = composed_config("branchpar")
        cfg.model.dtype = "bfloat16"
        cfg.train.out_dir = str(tmp_path / "mesh")
        composed = build_trainer(cfg, verbose=False)
        assert composed.train_path == "series_superstep"

        single = composed_config("branchpar")
        single.model.dtype = "bfloat16"
        single.mesh = MeshConfig()
        single.train.out_dir = str(tmp_path / "twin")
        twin = build_trainer(single, verbose=False)

        h_mesh = composed.train()
        h_twin = twin.train()
        np.testing.assert_array_equal(
            np.asarray(h_mesh["train"]), np.asarray(h_twin["train"])
        )
        close(composed.params, twin.params)

    def test_composed_program_names_engage(self):
        """The audited program is the dispatched program: every preset's
        composed_program() returns the fused superstep the trainer's
        train_path names."""
        for name in COMPOSED_PRESETS:
            tr = composed_trainer(name)
            pname, _, _ = tr.composed_program()
            assert pname == tr.train_path


class TestShardedTiled:
    """Tiled (tile, tile) block stacks split along the banded permutation
    (the 'composing tiled plans with meshes' follow-on PR 13 left open)."""

    def _plan(self, n=128, tile=8, k=2, band=5, seed=0):
        from stmgcn_tpu.ops.tiling import plan_tiling

        rng = np.random.default_rng(seed)
        dense = np.zeros((1, k, n, n), np.float32)
        for kk in range(k):
            a = np.zeros((n, n), np.float32)
            for d in range(1, band + 1):
                off = (rng.random(n - d) < 0.6).astype(np.float32)
                a += np.diag(off * rng.normal(size=n - d), d)
                a += np.diag(off * rng.normal(size=n - d), -d)
            np.fill_diagonal(a, rng.normal(size=n))
            dense[0, kk] = a
        return plan_tiling(dense, tile=tile)

    def test_bit_exact_fwd_and_prepared_bwd(self):
        from stmgcn_tpu.ops.tiling import (
            gathered_tiles_apply,
            gathered_tiles_apply_reference,
            shard_tiled_plan,
            sharded_gathered_tiles_apply,
        )
        from stmgcn_tpu.parallel import build_mesh

        plan = self._plan()
        branch = plan[0]
        sharded = shard_tiled_plan(branch, 8)
        assert sharded.n_shards == 8
        mesh = build_mesh(dp=1, region=8)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((plan.n, 4)).astype(np.float32))

        ref = gathered_tiles_apply_reference(branch, x)
        out = sharded_gathered_tiles_apply(mesh, sharded, x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

        g = jnp.asarray(
            rng.standard_normal(np.asarray(ref).shape).astype(np.float32)
        )
        _, vjp_ref = jax.vjp(lambda v: gathered_tiles_apply(branch, v), x)
        _, vjp_sh = jax.vjp(
            lambda v: sharded_gathered_tiles_apply(mesh, sharded, v), x
        )
        (dx_ref,) = vjp_ref(g)
        (dx_sh,) = vjp_sh(g)
        np.testing.assert_array_equal(np.asarray(dx_sh), np.asarray(dx_ref))

    def test_indivisible_rows_raise(self):
        from stmgcn_tpu.ops.tiling import shard_tiled_plan

        plan = self._plan(n=96)  # 12 block rows
        with pytest.raises(ValueError, match="pad_to a divisible rung"):
            shard_tiled_plan(plan[0], 8)
        # pad_to the next divisible rung and the split goes through
        padded = plan.pad_to(128)
        sharded = shard_tiled_plan(padded[0], 8)
        assert sharded.block_rows_local == 2

    def test_bandwidth_over_shard_raises(self):
        from stmgcn_tpu.ops.tiling import shard_tiled_plan

        plan = self._plan(n=128, band=24)  # block halo > r_loc at 8 shards
        with pytest.raises(ValueError, match="block bandwidth"):
            shard_tiled_plan(plan[0], 8)


class TestShardedResume:
    """Mid-epoch SIGTERM on the sharded superstep path (PR 3 machinery):
    resume must end bit-identical to the uninterrupted sharded run."""

    def test_sigterm_resume_bit_exact(self, tmp_path):
        ref = composed_trainer("branchpar", out_dir=str(tmp_path / "ref"))
        ref_hist = ref.train()

        plan = FaultPlan(FaultSpec("sigterm", epoch=2, step=4))
        faulted = composed_trainer(
            "branchpar", out_dir=str(tmp_path / "run"), fault_plan=plan
        )
        assert faulted._meshy
        with pytest.raises(Preempted, match="--resume auto"):
            faulted.train()

        resumed = composed_trainer("branchpar", out_dir=str(tmp_path / "run"))
        meta = resumed.restore_auto()
        assert meta is not None
        assert meta["epoch"] == 2 and meta["batch_in_epoch"] > 0
        hist = resumed.train()

        assert resumed.train_path == "series_superstep"
        same(ref.params, resumed.params)
        same(jax.tree.leaves(ref.opt_state), jax.tree.leaves(resumed.opt_state))
        assert hist["train"][-1] == ref_hist["train"][-1]
        assert hist["validate"][-1] == ref_hist["validate"][-1]
