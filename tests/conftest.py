"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform *before* jax is imported so
distributed/sharding tests run without TPU hardware — the standard JAX trick
(`--xla_force_host_platform_device_count`) substituting for the multi-device
fixtures the reference never had (SURVEY.md §4).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Make the repo importable without installation.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# The axon TPU plugin in this image ignores the JAX_PLATFORMS env var; the
# config flag does stick. Must run before any backend initialization.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
