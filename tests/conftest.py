"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform *before* any backend
initialization so distributed/sharding tests run without TPU hardware —
the standard JAX trick (``--xla_force_host_platform_device_count``)
substituting for the multi-device fixtures the reference never had
(SURVEY.md §4). The axon-plugin platform gotcha lives in one place:
``stmgcn_tpu/utils/platform.py``.
"""

import os
import sys

# Make the repo importable without installation.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from stmgcn_tpu.utils import force_host_platform  # noqa: E402

force_host_platform("cpu", n_devices=8)
