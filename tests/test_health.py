"""Numeric health & drift telemetry: bit-parity, cadence, attribution,
sketches, checkpoint baselines, serving drift lifecycle.

The health layer's core claim is "free when off, bit-identical when on":
the health-instrumented step/superstep variants run the SAME shared raw
train step and only *read* statistics off grads/updates the step already
computed, so params/opt-state/losses must match the plain path bit for
bit — exact equality, not allclose — on the per-step, fused-superstep,
and fleet paths alike. The "free when off" half is a jaxpr pin: the
plain ``train_series_superstep`` primitive count must not move when the
health variant exists alongside it (``jax.make_jaxpr`` does no DCE, so
any leak of health math into the plain program shows up as a count
change).

The serving side is numpy-only (drift sketches ride ``serve_predict``,
which never traces): Welford moments vs the two-pass numpy oracle,
drift z/PSI firing on a shifted stream and staying silent for cities
without a baseline, the ``health_baseline`` blob round-tripping through
checkpoint meta, and the DriftMonitor resetting atomically with
``swap_params`` so gauges never mix param generations.
"""

import json
import math

import jax
import numpy as np
import pytest

from stmgcn_tpu.config import ServingConfig, preset
from stmgcn_tpu.data import (
    DemandDataset,
    HeteroCityDataset,
    MinMaxNormalizer,
    WindowSpec,
    synthetic_dataset,
)
from stmgcn_tpu.experiment import build_model
from stmgcn_tpu.inference import Forecaster
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.obs.drift import (
    DriftMonitor,
    MomentSketch,
    baseline_from_samples,
    drift_metrics,
    psi,
)
from stmgcn_tpu.obs.health import (
    HEALTH_SCHEMA_VERSION,
    HealthWriter,
    load_health,
    publish_train_health,
    render_health_table,
    summarize_health,
)
from stmgcn_tpu.obs.registry import MetricsRegistry
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.serving import ServingEngine
from stmgcn_tpu.train import CitySupports, Trainer
from stmgcn_tpu.train.checkpoint import load_checkpoint

BATCH = 8
CITY_DIMS = ((3, 3), (2, 4), (2, 2))


def build(out_dir, *, superstep=1, epochs=2, placement="resident", **kw):
    data = synthetic_dataset(rows=5, n_timesteps=24 * 7 * 2 + 60, seed=1)
    dataset = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    sup = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
    return Trainer(model, dataset, sup, n_epochs=epochs, batch_size=BATCH,
                   steps_per_superstep=superstep, data_placement=placement,
                   out_dir=str(out_dir), verbose=False, **kw)


def build_fleet(out_dir, *, superstep=2, epochs=2, **kw):
    datas = [
        synthetic_dataset(rows=r, cols=c, n_timesteps=24 * 7 * 2 + 12 * i,
                          seed=i + 1)
        for i, (r, c) in enumerate(CITY_DIMS)
    ]
    dataset = HeteroCityDataset(datas, WindowSpec(3, 1, 1, 24))
    sup = CitySupports(
        SupportConfig("chebyshev", 2).build_all(d.adjs.values())
        for d in datas
    )
    model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                   horizon=1, lstm_hidden_dim=8, lstm_num_layers=1,
                   gcn_hidden_dim=8)
    return Trainer(model, dataset, sup, n_epochs=epochs, batch_size=BATCH,
                   steps_per_superstep=superstep, out_dir=str(out_dir),
                   verbose=False, **kw)


def same(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


def train_and_load(trainer):
    hist = trainer.train()
    trainer.flush_checkpoints()
    return hist


def build_smoke_trainer(out_dir, **health_kw):
    """Experiment-level trainer (checkpoint meta carries config/derived,
    so Forecaster.from_checkpoint can rebuild the model)."""
    from stmgcn_tpu.experiment import build_trainer

    cfg = preset("smoke")
    cfg.data.rows = 5
    cfg.data.n_timesteps = 24 * 7 * 2 + 60
    cfg.train.epochs = 1
    cfg.train.batch_size = BATCH
    cfg.train.data_placement = "resident"
    cfg.train.steps_per_superstep = 2
    cfg.train.out_dir = str(out_dir)
    for k, v in health_kw.items():
        setattr(cfg.health, k, v)
    return build_trainer(cfg, verbose=False), cfg


# -- moment sketch vs the numpy oracle ---------------------------------


class TestMomentSketch:
    def test_welford_batched_merge_matches_numpy(self):
        """Chunked streaming updates reproduce the two-pass mean/std of
        the concatenation — the property that makes the sketch a valid
        stand-in for retaining raw samples."""
        rng = np.random.default_rng(0)
        chunks = [rng.normal(3.0, 2.0, (n, 3)) for n in (1, 17, 256, 40)]
        sk = MomentSketch(3, bins=16)
        for c in chunks:
            assert sk.update(c) == c.shape[0]
        allv = np.concatenate(chunks)
        np.testing.assert_allclose(sk.mean, allv.mean(axis=0), rtol=1e-12)
        np.testing.assert_allclose(sk.std(), allv.std(axis=0, ddof=1),
                                   rtol=1e-10)
        assert sk.n == allv.shape[0]
        # no norm: histogram counts stay zero, probs degrade to uniform
        assert sk.counts.sum() == 0
        np.testing.assert_allclose(sk.probs(), np.full(16, 1 / 16))

    def test_normed_histogram_probs_sum_to_one(self):
        rng = np.random.default_rng(1)
        sk = MomentSketch(2, bins=8, norm=(np.zeros(2), np.ones(2)))
        sk.update(rng.normal(0, 1, (500, 2)))
        assert sk.counts.sum() == 1000  # pooled over channels
        np.testing.assert_allclose(sk.probs().sum(), 1.0)

    def test_baseline_blob_schema(self):
        blob = baseline_from_samples(
            np.random.default_rng(2).normal(5, 3, (400, 2)), bins=16)
        assert set(blob) == {"n", "mean", "std", "hist"}
        assert blob["n"] == 400 and len(blob["mean"]) == 2
        assert len(blob["hist"]) == 16
        np.testing.assert_allclose(sum(blob["hist"]), 1.0)
        json.dumps(blob)  # must be JSON-able as stored in checkpoint meta

    def test_psi_and_drift_metrics(self):
        base = np.full(8, 1 / 8)
        assert psi(base, base) == pytest.approx(0.0, abs=1e-12)
        shifted = np.array([0.5, 0.3, 0.1, 0.1, 0, 0, 0, 0])
        assert psi(base, shifted) > 0.25
        # empty sketch: drift is defined as zero, not NaN
        blob = baseline_from_samples(np.ones((10, 1)), bins=8)
        assert drift_metrics(blob, MomentSketch(1, bins=8)) == {
            "n": 0, "z_max": 0.0, "psi": 0.0}


class TestDriftMonitor:
    @staticmethod
    def _baseline(bins=16, seed=0):
        rng = np.random.default_rng(seed)
        return {
            "schema_version": 1, "bins": bins,
            "input": {"0": baseline_from_samples(
                rng.normal(10.0, 2.0, (4000, 1)), bins=bins)},
        }

    def test_fires_on_shifted_city_silent_on_held_out(self):
        mon = DriftMonitor(self._baseline())
        rng = np.random.default_rng(3)
        # same-distribution traffic: PSI stays under the stable rule of
        # thumb; shifted traffic blows past both gates
        mon.observe_input(0, rng.normal(10.0, 2.0, (2000, 1)))
        calm = mon.snapshot()["cities"]["0"]["input"]
        assert calm["n"] == 2000 and calm["psi"] < 0.1

        hot = DriftMonitor(self._baseline())
        hot.observe_input(0, rng.normal(26.0, 2.0, (2000, 1)))
        # a held-out city with no baseline is silently ignored — nothing
        # to compare against, and it must NOT pollute the snapshot
        hot.observe_input(1, rng.normal(99.0, 1.0, (50, 1)))
        snap = hot.snapshot()
        m = snap["cities"]["0"]["input"]
        assert m["z_max"] > 10 and m["psi"] > 0.25
        assert "1" not in snap["cities"]

    def test_reset_drops_sketches_and_bumps_generation(self):
        reg = MetricsRegistry()
        mon = DriftMonitor(self._baseline(), registry=reg)
        mon.observe_input(0, np.full((100, 1), 30.0))
        assert mon.snapshot()["cities"]["0"]["input"]["n"] == 100
        labels = {"city": "0", "phase": "input", "generation": "0"}
        assert reg.gauge("serving.drift.n", labels).value == 100

        mon.reset(1)
        snap = mon.snapshot()
        assert snap["generation"] == 1 and snap["cities"] == {}
        assert reg.gauge("serving.drift.generation").value == 1
        # fresh traffic after the reset accumulates under the new label
        mon.observe_input(0, np.full((7, 1), 10.0))
        labels_g1 = {"city": "0", "phase": "input", "generation": "1"}
        assert reg.gauge("serving.drift.n", labels_g1).value == 7

    def test_reset_with_new_baseline_swaps_comparison(self):
        mon = DriftMonitor(self._baseline())
        new = {"bins": 8, "input": {"0": baseline_from_samples(
            np.random.default_rng(4).normal(50.0, 1.0, (1000, 1)), bins=8)}}
        mon.reset(1, baseline=new)
        assert mon.bins == 8
        mon.observe_input(0, np.random.default_rng(5).normal(
            50.0, 1.0, (500, 1)))
        assert mon.snapshot()["cities"]["0"]["input"]["psi"] < 0.1


# -- health.jsonl writer / report --------------------------------------


class TestHealthStream:
    def test_writer_lazy_open_and_roundtrip(self, tmp_path):
        path = tmp_path / "health.jsonl"
        w = HealthWriter(str(path), {"every_k": 2, "groups": ["a"]})
        assert not path.exists()  # lazy: no record, no file
        w.write({"kind": "train", "step": 1, "loss": 0.5})
        w.close()
        meta, records = load_health(str(path))
        assert meta["schema_version"] == HEALTH_SCHEMA_VERSION
        assert meta["every_k"] == 2 and meta["groups"] == ["a"]
        assert records == [{"schema_version": HEALTH_SCHEMA_VERSION,
                            "kind": "train", "step": 1, "loss": 0.5}]

    def test_load_rejects_non_object_lines(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text('[1, 2]\n')
        with pytest.raises(ValueError, match="expected JSON object"):
            load_health(str(p))

    def test_publish_counters_only_inc_when_nonzero(self):
        reg = MetricsRegistry()
        publish_train_health({"loss": 1.0, "grad_norm": 2.0,
                              "nonfinite_grads": 0,
                              "group_norms": {"lstm": 0.5}}, reg)
        assert reg.gauge("train.health.loss").value == 1.0
        assert reg.gauge("train.health.group_norm",
                         {"group": "lstm"}).value == 0.5
        assert reg.counter("train.health.nonfinite_grads").value == 0
        publish_train_health({"nonfinite_grads": 3, "nonfinite_loss": 1}, reg)
        assert reg.counter("train.health.nonfinite_grads").value == 3
        assert reg.counter("train.health.nonfinite_loss").value == 1

    def test_summary_and_table_cover_train_and_drift(self):
        records = [
            {"kind": "train", "step": 2, "loss": 0.5, "grad_norm": 1.0,
             "update_ratio": 1e-3, "nonfinite_grads": 0, "nonfinite_loss": 0,
             "group_norms": {"lstm": 0.7}, "city_loss": {"0": 0.4}},
            {"kind": "train", "step": 4, "loss": 0.25, "grad_norm": 2.0,
             "update_ratio": 2e-3, "nonfinite_grads": 1, "nonfinite_loss": 0,
             "group_norms": {"lstm": 0.9}, "city_loss": {"0": 0.2}},
            {"kind": "drift", "city": "0", "phase": "input", "z_max": 12.5,
             "psi": 0.4, "n": 100, "generation": 1},
        ]
        s = summarize_health(records)
        assert s["records"] == 3
        assert s["train"]["count"] == 2 and s["train"]["last_step"] == 4
        assert s["train"]["loss"] == {"last": 0.25, "mean": 0.375, "max": 0.5}
        assert s["train"]["nonfinite_grads"] == 1
        assert s["train"]["groups"]["lstm"]["max"] == 0.9
        assert s["drift"]["worst"]["city"] == "0"
        assert s["drift"]["worst"]["z_max"] == 12.5
        text = render_health_table(s, {"schema_version": 1, "every_k": 1})
        assert "grad_norm[lstm]" in text and "city_loss[0]" in text
        assert "worst city 0" in text
        assert render_health_table(summarize_health([])) == \
            "(no health records)"


# -- trainer bit-parity: health on == health off -----------------------


class TestTrainerParity:
    """health=True must not move a single bit of params/opt-state/history
    on any dispatch path — the stats are a pure readout."""

    def _check(self, on, off):
        h_on, h_off = train_and_load(on), train_and_load(off)
        np.testing.assert_array_equal(h_on["train"], h_off["train"])
        np.testing.assert_array_equal(h_on["validate"], h_off["validate"])
        same(on.params, off.params)
        same(jax.tree.leaves(on.opt_state), jax.tree.leaves(off.opt_state))

    def test_per_step_path(self, tmp_path):
        out = tmp_path / "h.jsonl"
        on = build(tmp_path / "on", placement="stream",
                   health=True, health_out=str(out))
        off = build(tmp_path / "off", placement="stream")
        self._check(on, off)
        meta, records = load_health(str(out))
        assert meta["every_k"] == 1 and len(records) > 0
        assert all(r["nonfinite_grads"] == 0 and r["nonfinite_loss"] == 0
                   for r in records)
        assert set(records[0]["group_norms"]) == set(meta["groups"])

    def test_fused_superstep_path(self, tmp_path):
        out = tmp_path / "h.jsonl"
        on = build(tmp_path / "on", superstep=3,
                   health=True, health_out=str(out))
        off = build(tmp_path / "off", superstep=3)
        self._check(on, off)
        _, records = load_health(str(out))
        # fused blocks download per-step stats: steps per record > 1
        assert any(r["steps"] > 1 for r in records)
        assert all(math.isfinite(r["grad_norm"]) and
                   math.isfinite(r["update_ratio"]) for r in records)

    def test_fleet_path(self, tmp_path):
        out = tmp_path / "h.jsonl"
        on = build_fleet(tmp_path / "on", health=True, health_out=str(out))
        off = build_fleet(tmp_path / "off")
        self._check(on, off)
        _, records = load_health(str(out))
        fleet_recs = [r for r in records if "city_loss" in r]
        assert fleet_recs, "fleet blocks must attribute loss per city"
        cities = {c for r in fleet_recs for c in r["city_loss"]}
        assert cities <= {"0", "1", "2"} and len(cities) >= 2


class TestCadence:
    def test_every_k_halves_the_stream(self, tmp_path):
        outs = {}
        for k in (1, 2):
            out = tmp_path / f"h{k}.jsonl"
            tr = build(tmp_path / f"t{k}", placement="stream", epochs=2,
                       health=True, health_every_k=k, health_out=str(out))
            train_and_load(tr)
            outs[k] = load_health(str(out))
        meta1, recs1 = outs[1]
        meta2, recs2 = outs[2]
        assert meta2["every_k"] == 2
        # the cadence counter ticks once per dispatch unit, firing on
        # counter % k == 0 — exactly ceil(n/2) of the k=1 stream
        assert len(recs2) == (len(recs1) + 1) // 2
        # same data, same seed: the due steps' records agree on the step
        steps1 = [r["step"] for r in recs1]
        assert [r["step"] for r in recs2] == steps1[::2]

    def test_every_k_validated(self, tmp_path):
        with pytest.raises(ValueError, match="health_every_k"):
            build(tmp_path, health=True, health_every_k=0)
        with pytest.raises(ValueError, match="health_sketch_size"):
            build(tmp_path, health=True, health_sketch_size=0)


class TestCityLossAttribution:
    def test_fleet_city_loss_sums_to_step_losses_bit_exact(
            self, tmp_path, monkeypatch):
        """The (S, n_members) one-hot scatter row-sums to the scan's loss
        vector EXACTLY (one-hot rows are exact 0/1 floats), and the
        emitted dict's total matches the block's summed loss."""
        captured = []
        orig = Trainer._health_emit

        def spy(self, stats, losses, *, cities=None):
            captured.append(
                (jax.device_get(stats), jax.device_get(losses), cities))
            return orig(self, stats, losses, cities=cities)

        monkeypatch.setattr(Trainer, "_health_emit", spy)
        tr = build_fleet(tmp_path, epochs=1, health=True,
                         health_out=str(tmp_path / "h.jsonl"))
        train_and_load(tr)

        fleet_calls = [(s, l, c) for s, l, c in captured
                       if "city_loss" in s]
        assert fleet_calls
        for stats, losses, cities in fleet_calls:
            cl = np.asarray(stats["city_loss"])  # (S, n_members)
            losses = np.atleast_1d(np.asarray(losses))
            assert cl.shape[0] == losses.shape[0]
            np.testing.assert_array_equal(cl.sum(axis=1), losses)
            # one-hot: each step charges exactly its own slot
            assert ((cl != 0).sum(axis=1) <= 1).all()
            assert cities is not None and cl.shape[1] <= len(CITY_DIMS)

        _, records = load_health(str(tmp_path / "h.jsonl"))
        for r in records:
            if "city_loss" in r:
                total = sum(r["city_loss"].values())
                assert math.isfinite(total) and total >= 0


# -- checkpoint baseline round-trip ------------------------------------


class TestCheckpointBaseline:
    def test_baseline_persisted_and_reloaded(self, tmp_path):
        tr, _ = build_smoke_trainer(tmp_path, enabled=True, sketch_size=16,
                                    out=str(tmp_path / "h"))
        train_and_load(tr)
        meta, _, _ = load_checkpoint(tr.best_path, load_opt_state=False)
        hb = meta["health_baseline"]
        assert hb["schema_version"] == 1 and hb["bins"] == 16
        assert set(hb["input"]) == {"0"} and set(hb["prediction"]) == {"0"}
        for phase in ("input", "prediction"):
            blob = hb[phase]["0"]
            assert len(blob["hist"]) == 16
            np.testing.assert_allclose(sum(blob["hist"]), 1.0)
        # the prediction-phase baseline is on the raw demand scale, the
        # input phase on the normalized scale — they must differ
        assert hb["input"]["0"]["mean"] != hb["prediction"]["0"]["mean"]

        fc = Forecaster.from_checkpoint(tr.best_path)
        assert fc.health_baseline == hb

    def test_fleet_baseline_covers_every_city(self, tmp_path):
        tr = build_fleet(tmp_path, epochs=1, health=True,
                         health_out=str(tmp_path / "h"))
        train_and_load(tr)
        meta, _, _ = load_checkpoint(tr.best_path, load_opt_state=False)
        hb = meta["health_baseline"]
        assert set(hb["input"]) == {"0", "1", "2"}

    def test_checkpoint_without_baseline_still_loads(self, tmp_path):
        # health off entirely, and health on with baseline capture off:
        # both write meta without the key, and readers must not care
        off, _ = build_smoke_trainer(tmp_path / "off")
        train_and_load(off)
        meta, _, _ = load_checkpoint(off.best_path, load_opt_state=False)
        assert "health_baseline" not in meta
        assert Forecaster.from_checkpoint(off.best_path).health_baseline \
            is None

        nob = build(tmp_path / "nob", epochs=1, health=True,
                    health_baseline=False, health_out=str(tmp_path / "h"))
        train_and_load(nob)
        meta, _, _ = load_checkpoint(nob.best_path, load_opt_state=False)
        assert "health_baseline" not in meta


# -- serving drift lifecycle -------------------------------------------


class TestServingDriftLifecycle:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = preset("smoke")
        cfg.data.rows = 3
        data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 40, seed=0)
        ds = DemandDataset(data, WindowSpec(3, 1, 1, 24))
        supports = np.asarray(
            SupportConfig(cfg.model.kernel_type, cfg.model.K)
            .build_all(ds.adjs.values()), np.float32,
        )[: cfg.model.m_graphs]
        model = build_model(cfg, ds.n_feats)
        x = np.zeros((2, cfg.data.seq_len, ds.n_nodes, ds.n_feats),
                     np.float32)
        params = model.init(jax.random.key(0), np.asarray(supports), x)
        norm = MinMaxNormalizer.fit(np.asarray(data.demand))
        fc = Forecaster(model, params, norm, cfg,
                        {"input_dim": ds.n_feats, "n_nodes": ds.n_nodes})
        return fc, supports, ds

    def _hist(self, fc, ds, b, lo=0.0, hi=50.0, seed=1):
        rng = np.random.default_rng(seed)
        return rng.uniform(lo, hi, (b, fc.seq_len, ds.n_nodes, ds.n_feats)
                           ).astype(np.float32)

    def test_observe_swap_reset(self, setup):
        fc, supports, ds = setup
        eng = ServingEngine.from_forecaster(
            fc, supports,
            config=ServingConfig(buckets=(1, 2, 4), max_batch=4,
                                 max_delay_ms=5.0))
        try:
            assert eng.drift_snapshot() is None  # no monitor yet
            cal = self._hist(fc, ds, 4)
            baseline = {
                "schema_version": 1, "bins": 16,
                "input": {"0": baseline_from_samples(
                    fc.normalizer.transform(cal).reshape(-1, ds.n_feats),
                    bins=16)},
                "prediction": {"0": baseline_from_samples(
                    np.asarray(fc.predict(supports, cal)
                               ).reshape(-1, ds.n_feats), bins=16)},
            }
            eng.enable_drift(baseline, city=0)

            # in-distribution traffic observes at BOTH boundaries
            eng.predict_direct(self._hist(fc, ds, 4, seed=2))
            snap = eng.drift_snapshot()
            assert snap["generation"] == 0
            assert set(snap["cities"]["0"]) == {"input", "prediction"}
            n0 = snap["cities"]["0"]["input"]["n"]
            assert n0 > 0

            # shifted traffic moves the gauges on the SAME generation
            eng.predict_direct(self._hist(fc, ds, 4, lo=300, hi=400, seed=3))
            hot = eng.drift_snapshot()["cities"]["0"]["input"]
            assert hot["n"] > n0 and hot["z_max"] > 10

            # hot-swap: generation bumps, live sketches drop atomically
            gen = eng.swap_params(fc.params)
            snap = eng.drift_snapshot()
            assert gen == 1 and snap["generation"] == 1
            assert snap["cities"] == {}

            # post-swap traffic accumulates fresh under the new generation
            eng.predict_direct(self._hist(fc, ds, 2, seed=4))
            snap = eng.drift_snapshot()
            assert snap["cities"]["0"]["input"]["n"] > 0
        finally:
            eng.close()

    def test_swap_with_new_baseline(self, setup):
        fc, supports, ds = setup
        eng = ServingEngine.from_forecaster(
            fc, supports,
            config=ServingConfig(buckets=(1, 2), max_batch=2,
                                 max_delay_ms=5.0))
        try:
            eng.enable_drift({"bins": 8, "input": {"0": baseline_from_samples(
                np.ones((10, ds.n_feats)), bins=8)}})
            new_base = {"bins": 4, "input": {"0": baseline_from_samples(
                np.zeros((10, ds.n_feats)), bins=4)}}
            eng.swap_params(fc.params, health_baseline=new_base)
            assert eng.drift.bins == 4
        finally:
            eng.close()

    def test_from_checkpoint_autowires_drift(self, tmp_path):
        """A health+drift-configured checkpoint wires the monitor up at
        engine construction without any enable_drift call."""
        tr, cfg = build_smoke_trainer(tmp_path, enabled=True, drift=True,
                                      out=str(tmp_path / "h"))
        train_and_load(tr)
        fc = Forecaster.from_checkpoint(tr.best_path)
        assert fc.health_baseline is not None
        assert fc.config.health.drift
        sup = SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(
            tr.dataset.adjs.values())
        eng = ServingEngine.from_forecaster(
            fc, np.asarray(sup, np.float32)[: cfg.model.m_graphs],
            config=ServingConfig(buckets=(1, 2), max_batch=2,
                                 max_delay_ms=5.0))
        try:
            assert eng.drift is not None
            assert eng.drift.bins == fc.health_baseline["bins"]
        finally:
            eng.close()


# -- the free-when-off jaxpr pin ---------------------------------------


class TestFreeWhenOff:
    def test_plain_series_superstep_program_unchanged(self):
        """The health variant existing must cost the plain program
        NOTHING: jax.make_jaxpr does no DCE, so the pinned primitive
        count of the health-off window-free superstep is proof the plain
        path's jaxpr is byte-for-byte the pre-health program. If this
        moves, rerun `stmgcn lint --rebaseline` ONLY after confirming the
        change is deliberate."""
        from stmgcn_tpu.analysis.jaxpr_check import (
            PRIMITIVE_BUDGETS,
            count_primitives,
            _trace_step_jaxprs,
        )

        jaxprs = _trace_step_jaxprs("smoke")
        plain = count_primitives(jaxprs["train_series_superstep"])
        health = count_primitives(jaxprs["train_series_superstep_health"])
        assert plain == 455  # the pre-health measurement, exactly
        # the health program is a registered contract of its own
        assert "train_series_superstep_health" in PRIMITIVE_BUDGETS
        assert plain < health <= PRIMITIVE_BUDGETS[
            "train_series_superstep_health"]
