"""End-to-end coverage of the localpool and diffusion kernel families.

The reference only ever runs chebyshev (its diffusion path crashes on the
support-count assert, SURVEY.md §2 quirk 2); here all three families must
train end-to-end.
"""

import numpy as np
import pytest

from stmgcn_tpu.cli import build_parser, config_from_args
from stmgcn_tpu.config import preset
from stmgcn_tpu.experiment import build_supports, build_trainer


def tiny(cfg):
    cfg.data.rows = 4
    cfg.data.n_timesteps = 24 * 7 * 2 + 48
    cfg.train.epochs = 1
    cfg.train.batch_size = 16
    return cfg


@pytest.mark.parametrize(
    "kernel,K,n_supports",
    [("localpool", 1, 1), ("chebyshev", 2, 3), ("random_walk_diffusion", 2, 5)],
)
def test_kernel_family_trains_end_to_end(tmp_path, kernel, K, n_supports):
    cfg = tiny(preset("smoke"))
    cfg.model.kernel_type = kernel
    cfg.model.K = K
    cfg.train.out_dir = str(tmp_path)
    assert cfg.model.n_supports == n_supports
    trainer = build_trainer(cfg, verbose=False)
    assert trainer.supports.shape[1] == n_supports
    hist = trainer.train()
    assert np.isfinite(hist["train"][0])


@pytest.mark.parametrize("kernel,K", [("localpool", 1), ("random_walk_diffusion", 1)])
@pytest.mark.parametrize("mode", ["sparse", "banded-mesh"])
def test_kernel_family_composes_with_modes(tmp_path, kernel, K, mode):
    """Non-default kernel families run through the sparse block-CSR path
    and the banded mesh routing, not just the dense chebyshev default."""
    import jax

    cfg = tiny(preset("smoke"))
    cfg.model.kernel_type = kernel
    cfg.model.K = K
    cfg.model.m_graphs = 1  # smoke preset: neighbor grid only (banded-able)
    cfg.train.out_dir = str(tmp_path)
    if mode == "sparse":
        cfg.model.sparse = True
    else:
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        cfg.mesh.dp, cfg.mesh.region = 4, 2
        cfg.mesh.region_strategy = "auto"
        cfg.mesh.halo = 8  # rook-grid bandwidth: K hops x cols=4
    trainer = build_trainer(cfg, verbose=False)
    if mode == "banded-mesh":
        assert trainer.model.branch_modes() == ("banded",)
    hist = trainer.train()
    assert np.isfinite(hist["train"][0])


def test_forward_only_diffusion_supports():
    cfg = tiny(preset("smoke"))
    cfg.model.kernel_type = "random_walk_diffusion"
    cfg.model.K = 2
    cfg.model.bidirectional = False
    assert cfg.model.n_supports == 3
    from stmgcn_tpu.experiment import build_dataset

    ds = build_dataset(cfg)
    assert build_supports(cfg, ds).shape[1] == 3


def test_cli_val_ratio_override():
    args = build_parser().parse_args(["--preset", "smoke", "--val-ratio", "0.3"])
    cfg = config_from_args(args)
    # the original 0.7 train block splits 0.49/0.21; test share untouched
    assert cfg.data.val_ratio == 0.3
    assert cfg.data.val_frac == pytest.approx(0.21)
    assert cfg.data.train_frac == pytest.approx(0.49)
    # large ratios stay valid on the fraction path (crashes before the fix)
    args = build_parser().parse_args(["--preset", "smoke", "--val-ratio", "0.45"])
    cfg = config_from_args(args)
    from stmgcn_tpu.data.splits import fraction_splits

    s = fraction_splits(1000, train=cfg.data.train_frac, validate=cfg.data.val_frac)
    assert s.mode_len["train"] + s.mode_len["validate"] == pytest.approx(700, abs=2)


def test_top_level_api_exports():
    import stmgcn_tpu

    assert callable(stmgcn_tpu.preset)
    assert stmgcn_tpu.preset("smoke").name == "smoke"
    assert stmgcn_tpu.Forecaster.__name__ == "Forecaster"
    with pytest.raises(AttributeError):
        stmgcn_tpu.nonexistent_thing
