"""The fused Pallas LSTM matches the lax.scan path — outputs, final
states, and gradients — on the same parameter tree.

On CPU the kernel runs in interpreter mode (same program, no Mosaic), so
these tests pin the math; on-chip timing lives in the bench.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.ops.lstm import StackedLSTM


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.normal(size=(16, 12, 3)).astype(np.float32))


@pytest.mark.parametrize("layers", [1, 2, 3])
def test_pallas_matches_scan(data, layers):
    base = StackedLSTM(hidden_dim=8, num_layers=layers)
    params = base.init(jax.random.key(0), data)
    want_out, want_fin = base.apply(params, data)

    pallas = StackedLSTM(hidden_dim=8, num_layers=layers, backend="pallas")
    got_out, got_fin = pallas.apply(params, data)  # identical param tree
    np.testing.assert_allclose(
        np.asarray(got_out), np.asarray(want_out), rtol=1e-5, atol=1e-6
    )
    for (gh, gc), (wh, wc) in zip(got_fin, want_fin):
        np.testing.assert_allclose(np.asarray(gh), np.asarray(wh), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gc), np.asarray(wc), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_pallas_gradients_match_scan(data):
    base = StackedLSTM(hidden_dim=8, num_layers=3)
    pallas = StackedLSTM(hidden_dim=8, num_layers=3, backend="pallas")
    params = base.init(jax.random.key(1), data)

    def loss(model, p, x):
        out, finals = model.apply(p, x)
        # touch final states too, so their cotangents are exercised
        extra = sum(jnp.mean(h) + jnp.mean(c) for h, c in finals)
        return jnp.mean(out[:, -1, :] ** 2) + 0.1 * extra

    g_base = jax.grad(lambda p: loss(base, p, data))(params)
    g_pallas = jax.grad(lambda p: loss(pallas, p, data))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6
        ),
        g_pallas,
        g_base,
    )


@pytest.mark.slow
def test_pallas_input_gradient_matches(data):
    base = StackedLSTM(hidden_dim=8, num_layers=2)
    pallas = StackedLSTM(hidden_dim=8, num_layers=2, backend="pallas")
    params = base.init(jax.random.key(2), data)

    gx_base = jax.grad(lambda x: jnp.sum(base.apply(params, x)[0] ** 2))(data)
    gx_pallas = jax.grad(lambda x: jnp.sum(pallas.apply(params, x)[0] ** 2))(data)
    np.testing.assert_allclose(
        np.asarray(gx_pallas), np.asarray(gx_base), rtol=2e-4, atol=2e-6
    )


def test_pallas_row_padding(data):
    """Row counts not divisible by the kernel block are padded internally."""
    x = data[:5]  # 5 rows << block size
    base = StackedLSTM(hidden_dim=8, num_layers=2)
    pallas = StackedLSTM(hidden_dim=8, num_layers=2, backend="pallas")
    params = base.init(jax.random.key(3), x)
    np.testing.assert_allclose(
        np.asarray(pallas.apply(params, x)[0]),
        np.asarray(base.apply(params, x)[0]),
        rtol=1e-5,
        atol=1e-6,
    )


def test_pallas_under_vmap(data):
    """Branch-vmapped models run the kernel under vmap (stacked params)."""
    base = StackedLSTM(hidden_dim=8, num_layers=2)
    pallas = StackedLSTM(hidden_dim=8, num_layers=2, backend="pallas")
    keys = [jax.random.key(i) for i in range(3)]
    stacked = jax.vmap(lambda k: base.init(k, data))(jnp.stack(keys))

    want = jax.vmap(lambda p: base.apply(p, data)[0])(stacked)
    got = jax.vmap(lambda p: pallas.apply(p, data)[0])(stacked)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_flagship_model_with_pallas_backend():
    """Full branch-vmapped ST-MGCN trains one step on the kernel path."""
    from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
    from stmgcn_tpu.models import STMGCN
    from stmgcn_tpu.ops import SupportConfig
    from stmgcn_tpu.train import make_optimizer, make_step_fns

    data_ = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 40, seed=0)
    ds = DemandDataset(data_, WindowSpec(3, 1, 1, 24))
    supports = jnp.asarray(SupportConfig("chebyshev", 1).build_all(ds.adjs.values()))
    kwargs = dict(
        m_graphs=3, n_supports=2, seq_len=5, input_dim=ds.n_feats,
        lstm_hidden_dim=8, lstm_num_layers=2, gcn_hidden_dim=8,
    )
    batch = next(ds.batches("train", 4, pad_last=True))
    x, y = jnp.asarray(batch.x), jnp.asarray(batch.y)
    mask = jnp.ones(4, jnp.float32)

    base = STMGCN(**kwargs)
    pallas = STMGCN(**kwargs, lstm_backend="pallas")
    params = base.init(jax.random.key(0), supports, x)
    np.testing.assert_allclose(
        np.asarray(pallas.apply(params, supports, x)),
        np.asarray(base.apply(params, supports, x)),
        rtol=1e-5,
        atol=1e-6,
    )
    # one training step end-to-end on the kernel path
    fns = make_step_fns(pallas, make_optimizer(2e-3, 1e-4), "mse")
    p0, opt0 = fns.init(jax.random.key(0), supports, x)
    _, _, loss_pallas = fns.train_step(p0, opt0, supports, x, y, mask)
    fns_b = make_step_fns(base, make_optimizer(2e-3, 1e-4), "mse")
    pb, optb = fns_b.init(jax.random.key(0), supports, x)
    _, _, loss_base = fns_b.train_step(pb, optb, supports, x, y, mask)
    assert float(loss_pallas) == pytest.approx(float(loss_base), rel=1e-5)


@pytest.mark.slow
def test_pallas_bf16(data):
    base = StackedLSTM(hidden_dim=8, num_layers=3, dtype=jnp.bfloat16)
    pallas = StackedLSTM(
        hidden_dim=8, num_layers=3, backend="pallas", dtype=jnp.bfloat16
    )
    params = base.init(jax.random.key(4), data)
    want, _ = base.apply(params, data)
    got, _ = pallas.apply(params, data)
    # kernel keeps cell elementwise math in f32 (at least as accurate as
    # the bf16 scan); compare loosely in bf16 range
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=0.05, atol=0.05
    )


@pytest.mark.slow
def test_pallas_bf16_gradients(data):
    """bf16 backward path: the kernel rounds f32 cotangents/activations to
    bf16 before each MXU contraction (``_mm``) — new rounding that exists
    only in bf16, so it gets its own gradient pin at bf16 tolerances
    (~3 decimal digits, accumulated over T=12 steps x 3 layers)."""
    base = StackedLSTM(hidden_dim=8, num_layers=3, dtype=jnp.bfloat16)
    pallas = StackedLSTM(
        hidden_dim=8, num_layers=3, backend="pallas", dtype=jnp.bfloat16
    )
    params = base.init(jax.random.key(5), data)

    def loss(model, p, x):
        out, finals = model.apply(p, x)
        extra = sum(jnp.mean(h) + jnp.mean(c) for h, c in finals)
        return jnp.mean(out[:, -1, :].astype(jnp.float32) ** 2) + 0.1 * extra.astype(
            jnp.float32
        )

    g_base = jax.grad(lambda p: loss(base, p, data))(params)
    g_pallas = jax.grad(lambda p: loss(pallas, p, data))(params)
    for path, a in jax.tree_util.tree_flatten_with_path(g_pallas)[0]:
        b = g_base
        for k in path:
            b = b[k.key]
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        # relative to the leaf's scale: bf16 has ~2-3 significant digits
        scale = max(np.abs(b).max(), 1e-3)
        np.testing.assert_allclose(a, b, atol=0.06 * scale, err_msg=str(path))
        # and the gradient must genuinely point the same way, not just be
        # small: cosine similarity over the leaf
        denom = np.linalg.norm(a) * np.linalg.norm(b)
        if denom > 1e-12:
            assert (a * b).sum() / denom > 0.99, path


@pytest.mark.slow
class TestShardedKernel:
    """sharded_fused_lstm: per-shard kernel launch over a mesh matches the
    single-launch kernel and the scan path — values and gradients. The
    round-4 caveat (GSPMD can't partition the Mosaic custom call) is
    retired by never asking GSPMD to: shard_map splits rows, the kernel
    runs per shard, the backward psums weight grads explicitly."""

    def _mesh(self, dp, region):
        from stmgcn_tpu.parallel import build_mesh

        return build_mesh(dp=dp, region=region)

    @pytest.mark.parametrize("dp,region", [(8, 1), (4, 2)])
    def test_values_and_grads_match_unsharded(self, dp, region):
        from stmgcn_tpu.ops.pallas_lstm import fused_lstm, sharded_fused_lstm

        mesh = self._mesh(dp, region)
        rng = np.random.default_rng(7)
        R, T, L, H = 16, 4, 2, 8
        xp = jnp.asarray(rng.normal(size=(R, T, 4 * H)).astype(np.float32))
        wh = jnp.asarray(rng.normal(size=(L, H, 4 * H)).astype(np.float32)) * 0.2
        wx = jnp.asarray(rng.normal(size=(L - 1, H, 4 * H)).astype(np.float32)) * 0.2
        b = jnp.asarray(rng.normal(size=(L - 1, 4 * H)).astype(np.float32)) * 0.2
        sharded = sharded_fused_lstm(mesh, ("dp", "region"))

        def total(fn, args):
            hs, h_fin, c_fin = fn(*args)
            return jnp.sum(hs**2) + jnp.sum(h_fin) + jnp.sum(c_fin)

        args = (xp, wh, wx, b)
        v_ref, g_ref = jax.value_and_grad(lambda a: total(fused_lstm, a))(args)
        v_sh, g_sh = jax.value_and_grad(lambda a: total(sharded, a))(args)
        np.testing.assert_allclose(float(v_sh), float(v_ref), rtol=1e-5)
        jax.tree.map(
            lambda a, r: np.testing.assert_allclose(
                np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-6
            ),
            g_sh,
            g_ref,
        )

    def test_model_on_mesh_matches_scan(self):
        """Full branch-vmapped ST-MGCN with the sharded kernel on a
        (dp=4, region=2) mesh: forward and one training-step loss match
        the XLA scan path on the same mesh."""
        from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
        from stmgcn_tpu.models import STMGCN
        from stmgcn_tpu.ops import SupportConfig
        from stmgcn_tpu.parallel import MeshPlacement
        from stmgcn_tpu.train import make_optimizer, make_step_fns

        mesh = self._mesh(4, 2)
        placement = MeshPlacement(mesh)
        data_ = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 40, seed=0)
        ds = DemandDataset(data_, WindowSpec(3, 1, 1, 24))
        supports = placement.put(
            jnp.asarray(SupportConfig("chebyshev", 1).build_all(ds.adjs.values())),
            "supports",
        )
        kwargs = dict(
            m_graphs=3, n_supports=2, seq_len=5, input_dim=ds.n_feats,
            lstm_hidden_dim=8, lstm_num_layers=2, gcn_hidden_dim=8,
        )
        batch = next(ds.batches("train", 8, pad_last=True))
        x = placement.put(jnp.asarray(batch.x), "x")
        y = placement.put(jnp.asarray(batch.y), "y")
        mask = placement.put(jnp.ones(8, jnp.float32), "mask")

        base = STMGCN(**kwargs)
        sharded = STMGCN(**kwargs, lstm_backend="pallas", lstm_pallas_mesh=mesh)
        params = placement.put(base.init(jax.random.key(0), supports, x), "state")
        np.testing.assert_allclose(
            np.asarray(sharded.apply(params, supports, x)),
            np.asarray(base.apply(params, supports, x)),
            rtol=1e-5,
            atol=1e-5,
        )
        fns = make_step_fns(sharded, make_optimizer(2e-3, 1e-4), "mse")
        fns_b = make_step_fns(base, make_optimizer(2e-3, 1e-4), "mse")
        p0, opt0 = fns.init(jax.random.key(0), supports, x)
        _, _, loss_sh = fns.train_step(p0, opt0, supports, x, y, mask)
        pb, optb = fns_b.init(jax.random.key(0), supports, x)
        _, _, loss_base = fns_b.train_step(pb, optb, supports, x, y, mask)
        assert float(loss_sh) == pytest.approx(float(loss_base), rel=1e-5)


class TestBlockSizing:
    """VMEM-derived block rows scale inversely with the T*L recurrence."""

    def test_calibration_point_unchanged(self):
        from stmgcn_tpu.ops.pallas_lstm import _block_rows

        # round-5 bases: half the round-2 unpacked values — real Mosaic
        # AOT showed the packed kernel OOM scoped VMEM at fp32-128
        # (18.04 MB vs 16 MB; bench_stderr.log 2026-07-29)
        assert _block_rows(2, 12, 3) == (128, 64)
        assert _block_rows(4, 12, 3) == (64, 32)

    def test_longhorizon_halves_blocks(self):
        from stmgcn_tpu.ops.pallas_lstm import _block_rows

        # T=24 doubles every VMEM-resident term: rows halve, no overflow
        assert _block_rows(2, 24, 3) == (64, 32)
        assert _block_rows(4, 24, 3) == (32, 16)

    def test_floors_at_sublane_tile(self):
        from stmgcn_tpu.ops.pallas_lstm import _block_rows

        fwd16, bwd16 = _block_rows(2, 500, 8)
        fwd8, bwd8 = _block_rows(4, 500, 8)
        assert fwd16 >= 16 and bwd16 >= 16 and fwd16 % bwd16 == 0
        assert fwd8 >= 8 and bwd8 >= 8 and fwd8 % bwd8 == 0


@pytest.mark.slow
def test_pallas_matches_scan_at_longhorizon_t24():
    """T=24, L=3 (the longhorizon preset's recurrence shape): the
    auto-narrowed blocks keep kernel math identical to the scan path."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(8, 24, 3)).astype(np.float32))
    base = StackedLSTM(hidden_dim=8, num_layers=3)
    pallas = StackedLSTM(hidden_dim=8, num_layers=3, backend="pallas")
    params = base.init(jax.random.key(0), x)
    want_out, want_fin = base.apply(params, x)
    got_out, got_fin = pallas.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(got_out), np.asarray(want_out), rtol=1e-5, atol=1e-6
    )

    def loss(model, p):
        out, _ = model.apply(p, x)
        return jnp.mean(out ** 2)

    g_base = jax.grad(lambda p: loss(base, p))(params)
    g_pallas = jax.grad(lambda p: loss(pallas, p))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6
        ),
        g_pallas,
        g_base,
    )
