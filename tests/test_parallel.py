"""Distributed tests on a virtual 8-device CPU mesh (SURVEY.md §4).

Sharded-vs-single-device numerical equality is the correctness contract:
the same params/batches must produce the same losses and parameter
trajectories whether run on one device or sharded over (dp, region).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stmgcn_tpu.config import preset
from stmgcn_tpu.utils.platform import shard_map
from stmgcn_tpu.experiment import build_trainer
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.parallel import MeshPlacement, build_mesh, halo_exchange, mesh_from_config
from stmgcn_tpu.train import make_optimizer, make_step_fns


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()[:8]


def setup_problem(N=16, B=16, M=2, T=5, seed=0):
    rng = np.random.default_rng(seed)
    sup = (rng.standard_normal((M, 3, N, N)) * 0.2).astype(np.float32)
    x = rng.standard_normal((B, T, N, 1)).astype(np.float32)
    y = (rng.standard_normal((B, N, 1)) * 0.1).astype(np.float32)
    model = STMGCN(m_graphs=M, n_supports=3, seq_len=T, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=2, gcn_hidden_dim=8)
    return model, sup, x, y


class TestMesh:
    def test_build_mesh_shape(self, eight_devices):
        mesh = build_mesh(dp=4, region=2)
        assert mesh.shape == {"dp": 4, "region": 2}

    def test_too_few_devices(self):
        with pytest.raises(ValueError, match="devices"):
            build_mesh(dp=64, region=2)

    def test_mesh_from_config_single_is_none(self):
        from stmgcn_tpu.config import MeshConfig

        assert mesh_from_config(MeshConfig(dp=1, region=1)) is None


class TestShardedEquivalence:
    @pytest.mark.parametrize("dp,region", [(8, 1), (1, 8), (4, 2)])
    def test_forward_matches_single_device(self, eight_devices, dp, region):
        model, sup, x, y = setup_problem()
        params = model.init(jax.random.key(0), jnp.asarray(sup), jnp.asarray(x))
        single = np.asarray(jax.jit(model.apply)(params, jnp.asarray(sup), jnp.asarray(x)))

        pl = MeshPlacement(build_mesh(dp=dp, region=region))
        out = jax.jit(model.apply)(
            pl.put(params, "state"), pl.put(sup, "supports"), pl.put(x, "x")
        )
        np.testing.assert_allclose(np.asarray(out), single, rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("dp,region", [(8, 1), (4, 2)])
    @pytest.mark.slow
    def test_train_trajectory_matches_single_device(self, eight_devices, dp, region):
        model, sup, x, y = setup_problem()
        fns = make_step_fns(model, make_optimizer(1e-2, 1e-4), "mse")
        mask = np.ones(x.shape[0], np.float32)

        params_s, opt_s = fns.init(jax.random.key(0), jnp.asarray(sup), jnp.asarray(x))
        ref_params = params_s
        losses_single = []
        for _ in range(3):
            ref_params, opt_s, loss = fns.train_step(
                ref_params, opt_s, jnp.asarray(sup), jnp.asarray(x),
                jnp.asarray(y), jnp.asarray(mask),
            )
            losses_single.append(float(loss))

        pl = MeshPlacement(build_mesh(dp=dp, region=region))
        fns2 = make_step_fns(model, make_optimizer(1e-2, 1e-4), "mse")
        params_m, opt_m = fns2.init(jax.random.key(0), jnp.asarray(sup), jnp.asarray(x))
        params_m = pl.put(params_m, "state")
        opt_m = pl.put(opt_m, "state")
        sup_m, x_m = pl.put(sup, "supports"), pl.put(x, "x")
        y_m, mask_m = pl.put(y, "y"), pl.put(mask, "mask")
        losses_mesh = []
        for _ in range(3):
            params_m, opt_m, loss = fns2.train_step(params_m, opt_m, sup_m, x_m, y_m, mask_m)
            losses_mesh.append(float(loss))

        np.testing.assert_allclose(losses_mesh, losses_single, rtol=1e-5)
        # atol covers near-zero weights where cross-replica reduction
        # order (vs the single-device sum) leaves O(1e-5) drift after the
        # optimizer amplifies it over 3 steps; rtol still pins the rest
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=2e-5
            ),
            params_m, ref_params,
        )

    @pytest.mark.slow
    def test_gradient_allreduce_semantics(self, eight_devices):
        """dp-sharded batch loss == mean over the full batch, so grads agree."""
        model, sup, x, y = setup_problem(B=8)
        fns = make_step_fns(model, make_optimizer(1e-3), "mse")
        params, _ = fns.init(jax.random.key(1), jnp.asarray(sup), jnp.asarray(x))
        loss_single, _ = fns.eval_step(
            params, jnp.asarray(sup), jnp.asarray(x), jnp.asarray(y),
            jnp.ones(8),
        )
        pl = MeshPlacement(build_mesh(dp=8, region=1))
        loss_mesh, _ = fns.eval_step(
            pl.put(params, "state"), pl.put(sup, "supports"), pl.put(x, "x"),
            pl.put(y, "y"), pl.put(np.ones(8, np.float32), "mask"),
        )
        np.testing.assert_allclose(float(loss_mesh), float(loss_single), rtol=1e-6)


class TestPlacement:
    def test_divisibility_checks(self, eight_devices):
        pl = MeshPlacement(build_mesh(dp=4, region=2))
        pl.check_divisibility(batch_size=16, n_nodes=16)
        with pytest.raises(ValueError, match="batch_size"):
            pl.check_divisibility(batch_size=6, n_nodes=16)
        with pytest.raises(ValueError, match="n_nodes"):
            pl.check_divisibility(batch_size=16, n_nodes=9)

    def test_unknown_kind_raises(self, eight_devices):
        pl = MeshPlacement(build_mesh(dp=8, region=1))
        with pytest.raises(ValueError, match="kind"):
            pl.put(np.ones(8), "gradients")

    def test_sharding_layout(self, eight_devices):
        pl = MeshPlacement(build_mesh(dp=2, region=4))
        x = pl.put(np.zeros((8, 5, 16, 1), np.float32), "x")
        # 8 shards, each (4, 5, 4, 1)
        assert len(x.addressable_shards) == 8
        assert x.addressable_shards[0].data.shape == (4, 5, 4, 1)


class TestHaloExchange:
    def test_matches_unsharded_neighborhood(self, eight_devices):
        mesh = build_mesh(dp=1, region=8)
        n, h = 64, 2
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)

        def padded(local):
            return halo_exchange(local, halo=h, axis_name="region")

        out = jax.jit(
            shard_map(padded, mesh=mesh, in_specs=P("region", None),
                      out_specs=P("region", None))
        )(x)
        out = np.asarray(out)  # (8 * (8 + 2h), 3)
        per = n // 8
        blocks = out.reshape(8, per + 2 * h, 3)
        for i in range(8):
            lo, hi = i * per, (i + 1) * per
            want_left = x[lo - h : lo] if i > 0 else np.zeros((h, 3))
            want_right = x[hi : hi + h] if i < 7 else np.zeros((h, 3))
            np.testing.assert_array_equal(blocks[i, :h], want_left)
            np.testing.assert_array_equal(blocks[i, h : h + per], x[lo:hi])
            np.testing.assert_array_equal(blocks[i, h + per :], want_right)

    def test_banded_spmv_via_halo(self, eight_devices):
        """Banded A @ x computed shard-locally with halos == dense result."""
        mesh = build_mesh(dp=1, region=8)
        n, w = 64, 2  # bandwidth w: A[i,j] = 0 for |i-j| > w
        rng = np.random.default_rng(0)
        a = rng.standard_normal((n, n)).astype(np.float32)
        a[np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > w] = 0.0
        x = rng.standard_normal((n, 4)).astype(np.float32)
        want = a @ x

        per = n // 8

        def local_spmv(a_rows, x_local):
            # a_rows: this shard's (per, n) rows; x_local: (per, 4)
            xp = halo_exchange(x_local, halo=w, axis_name="region")  # (per+2w, 4)
            i = jax.lax.axis_index("region")
            # columns this shard's rows can touch: [i*per - w, (i+1)*per + w)
            cols = jax.lax.dynamic_slice_in_dim(
                jnp.pad(a_rows, ((0, 0), (w, w))), i * per, per + 2 * w, axis=1
            )
            return cols @ xp

        got = jax.jit(
            shard_map(local_spmv, mesh=mesh,
                      in_specs=(P("region", None), P("region", None)),
                      out_specs=P("region", None))
        )(a, x)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)

    def test_halo_validation(self, eight_devices):
        mesh = build_mesh(dp=1, region=8)
        with pytest.raises(ValueError, match="halo"):
            jax.jit(
                shard_map(lambda v: halo_exchange(v, halo=0, axis_name="region"),
                          mesh=mesh, in_specs=P("region"), out_specs=P("region"))
            )(np.zeros(16, np.float32))


class TestEndToEndShardedTrainer:
    @pytest.mark.slow
    def test_multicity_preset_trains_on_mesh(self, eight_devices, tmp_path):
        """Heterogeneous pair on the dp=8 mesh: batch axis shards, node
        axes stay whole, per-city shapes each get their own compiled step."""
        cfg = preset("multicity")
        cfg.data.city_rows = (4, 3)  # dp=8 divides batch 64; region=1
        cfg.data.city_timesteps = (24 * 7 * 2 + 24, 24 * 7 * 2)
        cfg.train.epochs = 1
        cfg.train.out_dir = str(tmp_path)
        trainer = build_trainer(cfg, verbose=False)
        assert isinstance(trainer.placement, MeshPlacement)
        hist = trainer.train()
        assert np.isfinite(hist["train"][0])
        res = trainer.test(modes=("test",))
        assert np.isfinite(res["test"]["rmse"])
