"""Property-based tests (hypothesis) for windowing and normalization."""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from stmgcn_tpu.data import MinMaxNormalizer, WindowSpec, sliding_windows


@st.composite
def window_specs(draw):
    day_steps = draw(st.sampled_from([2, 4, 24]))
    s = draw(st.integers(0, 6))
    d = draw(st.integers(0, 2))
    w = draw(st.integers(0, 1))
    h = draw(st.integers(1, 3))
    if s + d + w == 0:
        s = 1
    return WindowSpec(s, d, w, day_steps, horizon=h)


@settings(max_examples=40, deadline=None)
@given(spec=window_specs(), extra=st.integers(5, 40), seed=st.integers(0, 10))
def test_windowing_invariants(spec, extra, seed):
    """Every sample's components point at the documented absolute lags."""
    T = spec.burn_in + spec.horizon - 1 + extra
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((T, 3, 1)).astype(np.float32)
    x, y = sliding_windows(data, spec)

    assert x.shape == (spec.n_samples(T), spec.seq_len, 3, 1)
    offsets = spec.offsets
    # all offsets point into the past; components may legitimately overlap
    # (e.g. a short day makes the daily lag coincide with a serial slot —
    # reference semantics keep the duplicate, Data_Container.py:82-86)
    assert (offsets < 0).all()
    # each component is internally increasing (oldest-first)
    for comp in (offsets[: spec.weekly_len],
                 offsets[spec.weekly_len : spec.weekly_len + spec.daily_len],
                 offsets[spec.weekly_len + spec.daily_len :]):
        if len(comp) > 1:
            assert (np.diff(comp) > 0).all()
    # burn-in always covers the deepest lag: no wraparound possible
    assert spec.burn_in >= -offsets.min()

    # spot-check three samples against direct indexing
    for i in (0, len(y) // 2, len(y) - 1):
        t = spec.burn_in + i
        np.testing.assert_array_equal(x[i], data[t + offsets])
        want_y = data[t] if spec.horizon == 1 else data[t : t + spec.horizon]
        np.testing.assert_array_equal(y[i], want_y)


@settings(max_examples=40, deadline=None)
@given(
    lo=st.floats(-1e5, 1e5, allow_nan=False),
    span=st.floats(1e-3, 1e6, allow_nan=False),
    seed=st.integers(0, 10),
)
def test_minmax_roundtrip_property(lo, span, seed):
    rng = np.random.default_rng(seed)
    x = lo + span * rng.random((20, 4)).astype(np.float64)
    norm = MinMaxNormalizer.fit(x)
    z = norm.transform(x)
    assert z.min() >= -1.0 - 1e-9 and z.max() <= 1.0 + 1e-9
    np.testing.assert_allclose(norm.inverse(z), x, rtol=1e-9, atol=abs(lo) * 1e-9 + 1e-9)


@settings(max_examples=12, deadline=None)
@given(
    k=st.integers(1, 4),
    nr=st.integers(1, 200),
    nc=st.integers(1, 200),
    m=st.integers(1, 40),
    density=st.floats(0.0, 0.4),
    seed=st.integers(0, 2**31 - 1),
)
def test_spmm_stack_matches_dense_property(k, nr, nc, m, density, seed):
    """Fused-K block-CSR kernel == dense einsum for arbitrary rectangular
    shapes (tile-unaligned), sparsity patterns, empty rows/supports."""
    import jax.numpy as jnp

    from stmgcn_tpu.ops.spmm import spmm_stack, stack_from_dense

    rng = np.random.default_rng(seed)
    mats = rng.standard_normal((k, nr, nc)).astype(np.float32)
    mats[rng.random((k, nr, nc)) > density] = 0.0  # can zero everything
    x = rng.standard_normal((nc, m)).astype(np.float32)

    got = np.asarray(spmm_stack(stack_from_dense(mats), jnp.asarray(x)))
    want = np.einsum("kij,jm->kim", mats, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
