"""Region-sharded banded convolution vs dense reference (8 virtual devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.data import grid_adjacency
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.parallel import (
    bandwidth,
    build_mesh,
    sharded_banded_apply,
    strip_decompose,
)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return build_mesh(dp=1, region=8)


class TestBandwidth:
    def test_grid_supports_band(self):
        # rook grid: adjacency band = cols; chebyshev K doubles reach per order
        adj = grid_adjacency(8)  # N=64, band 8
        assert bandwidth(adj) == 8
        sups = SupportConfig("chebyshev", 2).build(adj)
        assert bandwidth(sups[2]) <= 16
        assert bandwidth(np.zeros((4, 4))) == 0


class TestStripDecompose:
    def test_validation(self):
        sups = np.eye(64)[None]
        with pytest.raises(ValueError, match="divisible"):
            strip_decompose(sups, 7, 4)
        wide = np.zeros((1, 64, 64), np.float32)
        wide[0, 0, 63] = 1.0
        with pytest.raises(ValueError, match="bandwidth"):
            strip_decompose(wide, 8, 4)
        with pytest.raises(ValueError, match="exceeds shard size"):
            strip_decompose(sups, 8, 9)

    def test_strip_contents(self):
        rng = np.random.default_rng(0)
        mat = rng.standard_normal((16, 16)).astype(np.float32)
        mat[np.abs(np.subtract.outer(np.arange(16), np.arange(16))) > 2] = 0
        strips = strip_decompose(mat[None], 4, 2)
        assert strips.shape == (4, 1, 4, 8)
        # shard 1 rows 4..7, columns 2..9
        np.testing.assert_array_equal(strips[1, 0], mat[4:8, 2:10])
        # boundary shard 0 zero-pads the left halo
        assert (strips[0, 0, :, :2] == 0).all()


class TestShardedBandedApply:
    def test_matches_dense_on_grid_chebyshev(self, mesh):
        # 16x16 grid over 8 shards: n_local=32, K=1 chebyshev band 16 = halo
        adj = grid_adjacency(16)
        sups = SupportConfig("chebyshev", 1).build(adj)
        halo = 16
        strips = strip_decompose(sups, 8, halo)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 256, 3)).astype(np.float32)

        got = sharded_banded_apply(mesh, strips, x, halo)
        want = jnp.einsum("kij,bjf->kbif", jnp.asarray(sups), jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_under_jit_and_grad(self, mesh):
        adj = grid_adjacency(16)
        sups = SupportConfig("chebyshev", 1).build(adj)
        strips = strip_decompose(sups, 8, 16)
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((2, 256, 2)).astype(np.float32)
        )

        @jax.jit
        def loss(x):
            return jnp.mean(sharded_banded_apply(mesh, strips, x, 16) ** 2)

        val, grad = jax.value_and_grad(loss)(x)
        assert np.isfinite(float(val))
        # gradient must match the dense formulation's
        dense = jnp.asarray(sups)

        @jax.jit
        def loss_dense(x):
            return jnp.mean(jnp.einsum("kij,bjf->kbif", dense, x) ** 2)

        grad_dense = jax.grad(loss_dense)(x)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(grad_dense),
                                   rtol=2e-4, atol=2e-5)
