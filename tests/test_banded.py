"""Region-sharded banded convolution vs dense reference (8 virtual devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.data import grid_adjacency
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.parallel import (
    BandedSpec,
    MeshPlacement,
    banded_decompose,
    bandwidth,
    build_mesh,
    sharded_banded_apply,
    strip_decompose,
)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return build_mesh(dp=1, region=8)


class TestBandwidth:
    def test_grid_supports_band(self):
        # rook grid: adjacency band = cols; chebyshev K doubles reach per order
        adj = grid_adjacency(8)  # N=64, band 8
        assert bandwidth(adj) == 8
        sups = SupportConfig("chebyshev", 2).build(adj)
        assert bandwidth(sups[2]) <= 16
        assert bandwidth(np.zeros((4, 4))) == 0


class TestStripDecompose:
    def test_validation(self):
        sups = np.eye(64)[None]
        with pytest.raises(ValueError, match="divisible"):
            strip_decompose(sups, 7, 4)
        wide = np.zeros((1, 64, 64), np.float32)
        wide[0, 0, 63] = 1.0
        with pytest.raises(ValueError, match="bandwidth"):
            strip_decompose(wide, 8, 4)
        with pytest.raises(ValueError, match="exceeds shard size"):
            strip_decompose(sups, 8, 9)

    def test_strip_contents(self):
        rng = np.random.default_rng(0)
        mat = rng.standard_normal((16, 16)).astype(np.float32)
        mat[np.abs(np.subtract.outer(np.arange(16), np.arange(16))) > 2] = 0
        strips = strip_decompose(mat[None], 4, 2)
        assert strips.shape == (4, 1, 4, 8)
        # shard 1 rows 4..7, columns 2..9
        np.testing.assert_array_equal(strips[1, 0], mat[4:8, 2:10])
        # boundary shard 0 zero-pads the left halo
        assert (strips[0, 0, :, :2] == 0).all()


class TestShardedBandedApply:
    def test_matches_dense_on_grid_chebyshev(self, mesh):
        # 16x16 grid over 8 shards: n_local=32, K=1 chebyshev band 16 = halo
        adj = grid_adjacency(16)
        sups = SupportConfig("chebyshev", 1).build(adj)
        halo = 16
        strips = strip_decompose(sups, 8, halo)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 256, 3)).astype(np.float32)

        got = sharded_banded_apply(mesh, strips, x, halo)
        want = jnp.einsum("kij,bjf->kbif", jnp.asarray(sups), jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_under_jit_and_grad(self, mesh):
        adj = grid_adjacency(16)
        sups = SupportConfig("chebyshev", 1).build(adj)
        strips = strip_decompose(sups, 8, 16)
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((2, 256, 2)).astype(np.float32)
        )

        @jax.jit
        def loss(x):
            return jnp.mean(sharded_banded_apply(mesh, strips, x, 16) ** 2)

        val, grad = jax.value_and_grad(loss)(x)
        assert np.isfinite(float(val))
        # gradient must match the dense formulation's
        dense = jnp.asarray(sups)

        @jax.jit
        def loss_dense(x):
            return jnp.mean(jnp.einsum("kij,bjf->kbif", dense, x) ** 2)

        grad_dense = jax.grad(loss_dense)(x)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(grad_dense),
                                   rtol=2e-4, atol=2e-5)


def _banded_supports(N, K, w, seed=0):
    rng = np.random.default_rng(seed)
    sup = (rng.standard_normal((K, N, N)) * 0.2).astype(np.float32)
    dist = np.abs(np.subtract.outer(np.arange(N), np.arange(N)))
    sup[:, dist > w] = 0.0
    return sup


class TestBandedConvLayer:
    """BandedChebGraphConv == ChebGraphConv with the *same* parameters."""

    def test_parity_and_param_interchange(self, mesh):
        from stmgcn_tpu.ops.chebconv import BandedChebGraphConv, ChebGraphConv

        N, B, F, K, w = 64, 4, 3, 3, 2
        sup = _banded_supports(N, K, w)
        x = np.random.default_rng(1).standard_normal((B, N, F)).astype(np.float32)
        bsup = banded_decompose(sup, 8)
        assert bsup.halo == w

        dense = ChebGraphConv(n_supports=K, features=5)
        banded = BandedChebGraphConv(n_supports=K, features=5, spec=BandedSpec(mesh))
        params = dense.init(jax.random.key(0), jnp.asarray(sup), jnp.asarray(x))
        want = dense.apply(params, jnp.asarray(sup), jnp.asarray(x))
        got = jax.jit(banded.apply)(params, bsup, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_validation(self, mesh):
        from stmgcn_tpu.ops.chebconv import BandedChebGraphConv, make_conv

        bsup = banded_decompose(_banded_supports(64, 3, 2), 8)
        conv = BandedChebGraphConv(n_supports=2, features=4, spec=BandedSpec(mesh))
        x = jnp.zeros((2, 64, 3))
        with pytest.raises(ValueError, match="supports"):
            conv.init(jax.random.key(0), bsup, x)
        with pytest.raises(ValueError, match="ShardSpec"):
            make_conv("banded", n_supports=3, features=4)


class TestMixedModeModel:
    """Flagship with per-branch ('banded', 'dense') routing == all-dense."""

    def test_forward_parity_same_params(self, mesh):
        from stmgcn_tpu.models import STMGCN

        N, B, T, K, w = 64, 8, 5, 3, 3
        sup0 = _banded_supports(N, K, w, seed=3)
        sup1 = (np.random.default_rng(4).standard_normal((K, N, N)) * 0.2).astype(
            np.float32
        )  # full-bandwidth branch stays dense
        x = np.random.default_rng(5).standard_normal((B, T, N, 1)).astype(np.float32)

        kw = dict(m_graphs=2, n_supports=K, seq_len=T, input_dim=1,
                  lstm_hidden_dim=8, lstm_num_layers=2, gcn_hidden_dim=8)
        ref = STMGCN(**kw, vmap_branches=False)
        mixed = STMGCN(**kw, support_modes=("banded", "dense"),
                       shard_spec=BandedSpec(mesh))
        dense_stack = jnp.asarray(np.stack([sup0, sup1]))
        params = ref.init(jax.random.key(0), dense_stack, jnp.asarray(x))
        want = ref.apply(params, dense_stack, jnp.asarray(x))

        routed = (banded_decompose(sup0, 8), jnp.asarray(sup1))
        got = jax.jit(mixed.apply)(params, routed, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_mode_validation(self):
        from stmgcn_tpu.models import STMGCN

        with pytest.raises(ValueError, match="not both"):
            STMGCN(m_graphs=2, n_supports=3, seq_len=5, input_dim=1,
                   sparse=True, support_modes=("dense", "dense")).branch_modes()
        with pytest.raises(ValueError, match="entries"):
            STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                   support_modes=("dense",)).branch_modes()

    def test_dense_sequence_with_wrong_branch_count_raises(self):
        # an M-sequence of dense supports must still satisfy the M check
        from stmgcn_tpu.models import STMGCN

        model = STMGCN(m_graphs=3, n_supports=2, seq_len=5, input_dim=1,
                       lstm_hidden_dim=4, lstm_num_layers=1, gcn_hidden_dim=4)
        sups = tuple(np.zeros((2, 8, 8), np.float32) for _ in range(2))
        x = jnp.zeros((2, 5, 8, 1))
        with pytest.raises(ValueError, match="supports_stack"):
            model.init(jax.random.key(0), sups, x)


class TestRouting:
    def _cfg(self, region=4, strategy="auto", halo=None, rows=16):
        from stmgcn_tpu.config import preset

        cfg = preset("scaled")
        cfg.data.rows = rows
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.model.dtype = "float32"
        cfg.train.batch_size = 16
        cfg.mesh.dp, cfg.mesh.region = 8 // region, region
        cfg.mesh.region_strategy = strategy
        cfg.mesh.halo = halo
        return cfg

    def test_auto_routes_grid_banded_rest_dense(self, mesh):
        from stmgcn_tpu.experiment import build_dataset, route_supports
        from stmgcn_tpu.parallel import BandedSupports

        cfg = self._cfg(halo=48)  # cheb-K3 on a 16-col grid: bandwidth 48
        ds = build_dataset(cfg)
        sup, modes = route_supports(cfg, ds)
        assert modes[0] == "banded"  # neighbor grid branch
        assert isinstance(sup[0], BandedSupports)
        assert "dense" in modes[1:]  # random transport links are not banded

    def test_gspmd_strategy_is_noop(self):
        from stmgcn_tpu.experiment import build_dataset, route_supports

        cfg = self._cfg(strategy="gspmd")
        ds = build_dataset(cfg)
        sup, modes = route_supports(cfg, ds)
        assert modes is None and sup.ndim == 4

    def test_banded_strategy_rejects_wide_graphs(self):
        from stmgcn_tpu.experiment import build_dataset, route_supports

        cfg = self._cfg(strategy="banded", halo=48)
        ds = build_dataset(cfg)
        with pytest.raises(ValueError, match="bandwidth"):
            route_supports(cfg, ds)

    @pytest.mark.slow
    def test_end_to_end_banded_training_matches_dense(self, mesh, tmp_path):
        """Banded-routed training reproduces dense-routed losses exactly.

        Both runs use the loop param layout (strategy active), identical
        init streams; only the support representation/communication plan
        differs — halo=0 forces every branch dense, halo=48 puts the grid
        branch on the explicit halo-exchange plan. (A vmapped GSPMD run is
        *not* loss-comparable: the stacked layout draws different init
        RNGs — the documented layout caveat.)
        """
        from stmgcn_tpu.experiment import build_trainer

        losses, modes = {}, {}
        for label, halo in (("dense", 0), ("banded", 48)):
            cfg = self._cfg(strategy="auto", halo=halo)
            cfg.train.epochs = 1
            cfg.train.out_dir = str(tmp_path / label)
            trainer = build_trainer(cfg, verbose=False)
            modes[label] = trainer.model.branch_modes()
            losses[label] = trainer.train()
        assert modes["dense"] == ("dense",) * 3
        assert modes["banded"][0] == "banded"
        np.testing.assert_allclose(
            losses["banded"]["validate"], losses["dense"]["validate"], rtol=1e-5
        )
        np.testing.assert_allclose(
            losses["banded"]["train"], losses["dense"]["train"], rtol=1e-5
        )

    @pytest.mark.slow
    def test_banded_checkpoint_serves_single_device(self, mesh, tmp_path):
        """A banded-trained checkpoint rebuilds on one device via Forecaster
        (loop param layout is config-determined; supports passed dense)."""
        from stmgcn_tpu.experiment import build_dataset, build_supports, build_trainer
        from stmgcn_tpu.inference import Forecaster

        cfg = self._cfg(strategy="auto", halo=48)
        cfg.train.epochs = 1
        cfg.train.out_dir = str(tmp_path)
        trainer = build_trainer(cfg, verbose=False)
        assert "banded" in trainer.model.branch_modes()
        trainer.train()

        fc = Forecaster.from_checkpoint(str(tmp_path / "best.ckpt"))
        ds = build_dataset(cfg)
        dense_sup = build_supports(cfg, ds)
        hist = ds.arrays("test")[0][:2]
        pred = fc.predict(dense_sup, ds.denormalize(hist))
        assert pred.shape == (2, ds.n_nodes, ds.n_feats)
        assert np.isfinite(pred).all()

    def test_placement_puts_routed_supports(self, mesh):
        pl = MeshPlacement(build_mesh(dp=1, region=8))
        bsup = banded_decompose(_banded_supports(64, 2, 2), 8)
        dense = np.zeros((2, 64, 64), np.float32)
        placed = pl.put((bsup, dense), "supports")
        assert placed[0].strips.sharding.spec == ("region", None, None, None)
        assert placed[1].shape == (2, 64, 64)
