"""Preemption-safety tests: fault injection, the verified checkpoint
chain, exact mid-epoch resume, and the divergence guard.

The acceptance drills mirror a preemptible-TPU job's life: SIGTERM lands
mid-epoch (injected deterministically by a :class:`FaultPlan`), the
emergency checkpoint is written, a fresh process ``--resume auto``-s and
must end **bit-identical** to a run that was never interrupted; corrupt
checkpoint bytes must never load silently (fallback + quarantine); a
poisoned batch must trip the divergence guard, roll back, and leave the
run bit-identical to one that never saw the batch.
"""

import json
import os
import struct

import jax
import numpy as np
import pytest

from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.resilience import (
    DivergenceError,
    DivergenceGuard,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    Preempted,
)
from stmgcn_tpu.train import (
    CorruptCheckpointError,
    Trainer,
    load_checkpoint,
    load_latest_verified,
    save_checkpoint,
    verify_checkpoint,
)
from stmgcn_tpu.train import checkpoint as ckpt_mod


def build(out_dir, fault_plan=None, shuffle=False, superstep=1, epochs=2, **kw):
    data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 60, seed=1)
    dataset = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    sup = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
    return Trainer(model, dataset, sup, n_epochs=epochs, batch_size=16,
                   shuffle=shuffle, steps_per_superstep=superstep,
                   data_placement="resident", out_dir=str(out_dir),
                   fault_plan=fault_plan, verbose=False, **kw)


def same(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


def _toy_state():
    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    opt_state = {"m": np.linspace(0.0, 1.0, 4, dtype=np.float32)}
    return params, opt_state


class TestCheckpointFormat:
    def test_v2_roundtrip_and_verify(self, tmp_path):
        params, opt_state = _toy_state()
        path = str(tmp_path / "c.ckpt")
        save_checkpoint(path, params, opt_state, {"epoch": 3})
        meta = verify_checkpoint(path)
        assert meta["epoch"] == 3
        meta2, params_l, opt_l = load_checkpoint(path)
        assert meta2["epoch"] == 3
        same(params, params_l)
        same(opt_state, opt_l)

    def test_v1_files_still_load(self, tmp_path):
        """Pre-chain checkpoints (no CRCs) written by older runs load."""
        from flax import serialization

        params, opt_state = _toy_state()
        blobs = [
            json.dumps({"epoch": 7}).encode("utf-8"),
            serialization.to_bytes(params),
            serialization.to_bytes(opt_state),
        ]
        data = ckpt_mod._MAGIC_V1 + b"".join(
            struct.pack("<Q", len(b)) + b for b in blobs
        )
        path = tmp_path / "old.ckpt"
        path.write_bytes(data)
        meta, params_l, opt_l = load_checkpoint(str(path))
        assert meta["epoch"] == 7
        same(params, params_l)
        same(opt_state, opt_l)

    def test_truncation_detected_at_any_cut(self, tmp_path):
        params, opt_state = _toy_state()
        good = str(tmp_path / "good.ckpt")
        save_checkpoint(good, params, opt_state, {"epoch": 1})
        data = open(good, "rb").read()
        cut_path = tmp_path / "cut.ckpt"
        for cut in (3, 6, 10, 20, len(data) // 2, len(data) - 1):
            cut_path.write_bytes(data[:cut])
            with pytest.raises(ValueError):  # CorruptCheckpointError or magic
                load_checkpoint(str(cut_path))
            with pytest.raises(ValueError):
                verify_checkpoint(str(cut_path))

    def test_bitflip_detected_by_crc(self, tmp_path):
        params, opt_state = _toy_state()
        path = str(tmp_path / "c.ckpt")
        save_checkpoint(path, params, opt_state, {"epoch": 1})
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0x01
        open(path, "wb").write(bytes(data))
        with pytest.raises(CorruptCheckpointError, match="CRC"):
            verify_checkpoint(path)

    def test_trailing_garbage_detected(self, tmp_path):
        params, opt_state = _toy_state()
        path = str(tmp_path / "c.ckpt")
        save_checkpoint(path, params, opt_state, {"epoch": 1})
        with open(path, "ab") as f:
            f.write(b"extra")
        with pytest.raises(CorruptCheckpointError, match="trailing"):
            verify_checkpoint(path)


class TestVerifiedChain:
    def test_empty_dir_returns_none(self, tmp_path):
        params, opt_state = _toy_state()
        assert load_latest_verified(str(tmp_path), params, opt_state) is None

    def test_fallback_and_quarantine(self, tmp_path):
        params, opt_state = _toy_state()
        save_checkpoint(str(tmp_path / "latest.prev.ckpt"), params, opt_state,
                        {"epoch": 1})
        latest = str(tmp_path / "latest.ckpt")
        save_checkpoint(latest, params, opt_state, {"epoch": 2})
        data = bytearray(open(latest, "rb").read())
        data[len(data) // 2] ^= 0x01
        open(latest, "wb").write(bytes(data))

        path, meta, params_l, opt_l = load_latest_verified(
            str(tmp_path), params, opt_state
        )
        assert os.path.basename(path) == "latest.prev.ckpt"
        assert meta["epoch"] == 1
        same(params, params_l)
        assert not os.path.exists(latest)  # quarantined, never silently loaded
        assert os.path.exists(latest + ".corrupt")

    def test_best_snapshots_newest_epoch_first(self, tmp_path):
        params, opt_state = _toy_state()
        for name, epoch in (("best.ckpt", 2), ("best_e3.ckpt", 3),
                            ("best_e5.ckpt", 5)):
            save_checkpoint(str(tmp_path / name), params, opt_state,
                            {"epoch": epoch})
        path, meta, _, _ = load_latest_verified(str(tmp_path), params, opt_state)
        assert os.path.basename(path) == "best_e5.ckpt"
        assert meta["epoch"] == 5


class TestFaultPlanUnit:
    def test_spec_validation(self):
        with pytest.raises(ValueError, match="kind"):
            FaultSpec("explode")
        with pytest.raises(ValueError, match="step ordinal"):
            FaultSpec("poison")
        with pytest.raises(ValueError, match="keep_fraction"):
            FaultSpec("truncate-write", keep_fraction=1.5)

    def test_empty_plan_is_inert(self):
        plan = FaultPlan()
        assert not plan.active
        plan.before_step(1, 0)
        assert plan.mutate_write("latest.ckpt", b"abc") == b"abc"
        assert plan.poison_value(1, 0) is None
        assert not plan.should_drop(1, 0)

    def test_raise_fires_once(self):
        plan = FaultPlan(FaultSpec("raise", epoch=1, step=2))
        plan.before_step(1, 0)  # no match
        with pytest.raises(InjectedFault, match="epoch 1, step 2"):
            plan.before_step(1, 2)
        plan.before_step(1, 2)  # one-shot: re-running the ordinal is clean

    def test_poison_and_drop_are_pure_matches(self):
        plan = FaultPlan(
            FaultSpec("poison", epoch=1, step=3), FaultSpec("drop", epoch=1, step=4)
        )
        for _ in range(2):  # rollback re-runs must re-fire
            assert np.isnan(plan.poison_value(1, 3))
            assert plan.should_drop(1, 4)
        assert plan.poison_value(2, 3) is None
        assert plan.any_drop(1, 3, 6)
        assert not plan.any_drop(1, 5, 8)

    def test_write_faults_count_matching_writes(self):
        plan = FaultPlan(
            FaultSpec("truncate-write", path_glob="latest.ckpt", write_index=1)
        )
        data = bytes(range(100))
        assert plan.mutate_write("/out/latest.ckpt", data) == data  # index 0
        assert plan.mutate_write("/out/best.ckpt", data) == data  # glob miss
        assert plan.mutate_write("/out/latest.ckpt", data) == data[:50]
        assert plan.mutate_write("/out/latest.ckpt", data) == data  # one-shot

        plan = FaultPlan(FaultSpec("corrupt-write", flip_byte=7))
        out = plan.mutate_write("x.ckpt", data)
        assert len(out) == len(data) and out[7] == data[7] ^ 0x01


class TestResumeParity:
    """Interrupted-resume acceptance: SIGTERM mid-epoch, restart with
    ``--resume auto``, final state bit-identical to the uninterrupted run
    — across shuffle on/off and the per-step/superstep paths."""

    @pytest.mark.parametrize("shuffle,superstep", [
        (False, 1),
        pytest.param(True, 1, marks=pytest.mark.slow),
        pytest.param(False, 3, marks=pytest.mark.slow),
        (True, 3),
    ])
    def test_sigterm_resume_bit_exact(self, tmp_path, shuffle, superstep):
        ref = build(tmp_path / "ref", shuffle=shuffle, superstep=superstep)
        ref_hist = ref.train()

        plan = FaultPlan(FaultSpec("sigterm", epoch=2, step=4))
        faulted = build(tmp_path / "run", fault_plan=plan, shuffle=shuffle,
                        superstep=superstep)
        with pytest.raises(Preempted, match="--resume auto"):
            faulted.train()

        resumed = build(tmp_path / "run", shuffle=shuffle, superstep=superstep)
        meta = resumed.restore_auto()
        assert meta is not None
        assert meta["epoch"] == 2 and meta["batch_in_epoch"] > 0
        hist = resumed.train()

        same(ref.params, resumed.params)
        same(ref.opt_state, resumed.opt_state)
        # epoch 2's train loss is recomputed from the persisted partial
        # per-batch losses — it must match the uninterrupted run's exactly
        assert hist["train"][-1] == ref_hist["train"][-1]
        assert hist["validate"][-1] == ref_hist["validate"][-1]

    def test_restore_auto_fresh_start(self, tmp_path):
        tr = build(tmp_path)
        assert tr.restore_auto() is None  # --resume auto starts fresh

    def test_bare_restore_raises_when_nothing_resumable(self, tmp_path):
        tr = build(tmp_path)
        with pytest.raises(FileNotFoundError, match="no verified checkpoint"):
            tr.restore()

    def test_raise_fault_with_step_cadence_resumes(self, tmp_path):
        """A hard crash between epoch boundaries loses no steps when
        ``checkpoint_every_steps`` keeps latest.ckpt fresh."""
        ref = build(tmp_path / "ref")
        ref.train()

        plan = FaultPlan(FaultSpec("raise", epoch=2, step=3))
        faulted = build(tmp_path / "run", fault_plan=plan,
                        checkpoint_every_steps=1)
        with pytest.raises(InjectedFault):
            faulted.train()
        faulted.flush_checkpoints()

        # the emergency-free crash still left a verified mid-epoch cursor
        meta = verify_checkpoint(str(tmp_path / "run" / "latest.ckpt"))
        assert meta["epoch"] == 2  # the in-progress epoch being resumed
        assert meta["batch_in_epoch"] == 3 and meta["global_step"] > 0
        assert meta["shuffle"] is False
        partial = meta["partial"]
        assert len(partial["losses"]) == len(partial["counts"]) == 3

        resumed = build(tmp_path / "run", checkpoint_every_steps=1)
        assert resumed.restore_auto() is not None
        resumed.train()
        same(ref.params, resumed.params)
        same(ref.opt_state, resumed.opt_state)


class TestCorruptionDrill:
    @pytest.mark.parametrize("kind", [
        "corrupt-write",
        pytest.param("truncate-write", marks=pytest.mark.slow),
    ])
    def test_corrupted_latest_falls_back_and_quarantines(self, tmp_path, kind):
        """Bit rot / short write on the newest checkpoint: the restart must
        fall back to the rotated previous latest, quarantining the bad file
        — never silently loading it."""
        plan = FaultPlan(FaultSpec(kind, path_glob="latest.ckpt", write_index=1))
        tr = build(tmp_path, fault_plan=plan)
        tr.train()  # epoch 2's latest write lands corrupted

        restarted = build(tmp_path)
        meta = restarted.restore_auto()
        assert meta is not None and meta["epoch"] == 1  # latest.prev (epoch 1)
        assert os.path.exists(tmp_path / "latest.ckpt.corrupt")
        assert not os.path.exists(tmp_path / "latest.ckpt")


class TestDivergenceGuard:
    def test_guard_unit(self):
        with pytest.raises(ValueError, match="action"):
            DivergenceGuard(action="explode")
        with pytest.raises(ValueError, match="patience"):
            DivergenceGuard(patience=0)
        with pytest.raises(ValueError, match="lr_cut"):
            DivergenceGuard(lr_cut=1.5)
        g = DivergenceGuard(patience=2)
        g.trip(float("nan"), 1, 0)
        g.ok()  # a finite step resets the consecutive counter
        g.trip(float("inf"), 1, 2)
        with pytest.raises(DivergenceError, match="--checkify nan"):
            g.trip(float("nan"), 1, 3)
        assert g.total == 3

    @pytest.mark.parametrize("superstep", [1, 3])
    def test_poisoned_batch_skip_matches_drop(self, tmp_path, superstep):
        """Acceptance drill: a NaN-poisoned batch trips the guard, rolls
        back, and the completed run is bit-identical to one that never saw
        the batch (a drop fault at the same ordinal)."""
        poisoned = build(
            tmp_path / "poisoned",
            fault_plan=FaultPlan(FaultSpec("poison", epoch=2, step=3)),
            superstep=superstep, divergence_guard=True,
        )
        poisoned.train()
        assert poisoned._guard.total == 1

        control = build(
            tmp_path / "control",
            fault_plan=FaultPlan(FaultSpec("drop", epoch=2, step=3)),
            superstep=superstep,
        )
        control.train()
        same(control.params, poisoned.params)
        same(control.opt_state, poisoned.opt_state)

    def test_persistent_divergence_aborts_with_hint(self, tmp_path):
        plan = FaultPlan(
            FaultSpec("poison", epoch=1, step=1),
            FaultSpec("poison", epoch=1, step=2),
            FaultSpec("poison", epoch=1, step=3),
        )
        tr = build(tmp_path, fault_plan=plan, divergence_guard=True,
                   divergence_patience=3)
        with pytest.raises(DivergenceError, match="--checkify nan"):
            tr.train()

    def test_deferred_batches_survive_midepoch_resume(self, tmp_path):
        """A SIGTERM landing between a guard defer and its end-of-epoch
        retry must not lose the deferred batch: its ordinal is persisted
        in the mid-epoch checkpoint, re-materialized on resume from the
        epoch's deterministic batch order, and retried in the same slot —
        the resumed run ends bit-identical to an uninterrupted one."""
        ref = build(
            tmp_path / "ref",
            fault_plan=FaultPlan(FaultSpec("poison", epoch=1, step=1)),
            divergence_guard=True, divergence_action="defer",
        )
        ref.train()
        assert ref._guard.total == 1

        plan = FaultPlan(
            FaultSpec("poison", epoch=1, step=1),
            FaultSpec("sigterm", epoch=1, step=3),
        )
        faulted = build(tmp_path / "run", fault_plan=plan,
                        divergence_guard=True, divergence_action="defer")
        with pytest.raises(Preempted):
            faulted.train()
        meta = verify_checkpoint(faulted.latest_path)
        assert meta["epoch"] == 1 and meta["batch_in_epoch"] > 0
        assert meta["deferred"] == [1]  # the pending retry, by ordinal

        resumed = build(tmp_path / "run", divergence_guard=True,
                        divergence_action="defer")
        assert resumed.restore_auto() is not None
        resumed.train()
        same(ref.params, resumed.params)
        same(ref.opt_state, resumed.opt_state)

    def test_lr_cut_applied_and_persisted(self, tmp_path):
        tr = build(
            tmp_path,
            fault_plan=FaultPlan(FaultSpec("poison", epoch=1, step=2)),
            divergence_guard=True, divergence_lr_cut=0.5,
        )
        tr.train()
        assert tr._lr_scale == 0.5
        meta = verify_checkpoint(tr.latest_path)
        assert meta["lr_scale"] == 0.5  # survives a resume


class TestAsyncWriterFailure:
    def test_failure_surfaces_then_writer_recovers(self, tmp_path):
        tr = build(tmp_path / "out", epochs=1)
        tr._write(str(tmp_path / "no_such_dir" / "x.ckpt"), b"data")
        with pytest.raises(RuntimeError, match="background checkpoint") as exc:
            tr.flush_checkpoints()
        assert isinstance(exc.value.__cause__, FileNotFoundError)
        # the worker survives the failed job: later saves land and verify
        tr._save(tr.latest_path)
        tr.flush_checkpoints()
        assert verify_checkpoint(tr.latest_path)["epoch"] == 0


class TestCLIFlags:
    def test_resume_modes(self):
        from stmgcn_tpu.cli import build_parser

        p = build_parser()
        assert p.parse_args([]).resume is None
        assert p.parse_args(["--resume"]).resume == "strict"
        assert p.parse_args(["--resume", "auto"]).resume == "auto"
        with pytest.raises(SystemExit):
            p.parse_args(["--resume", "bogus"])

    def test_resilience_flags_reach_config(self):
        from stmgcn_tpu.cli import build_parser, config_from_args

        args = build_parser().parse_args([
            "--divergence-guard", "--divergence-action", "defer",
            "--divergence-patience", "5", "--divergence-lr-cut", "0.5",
            "--checkpoint-every-steps", "25",
        ])
        cfg = config_from_args(args)
        assert cfg.train.divergence_guard is True
        assert cfg.train.divergence_action == "defer"
        assert cfg.train.divergence_patience == 5
        assert cfg.train.divergence_lr_cut == 0.5
        assert cfg.train.checkpoint_every_steps == 25

        cfg = config_from_args(build_parser().parse_args([]))
        assert cfg.train.divergence_guard is False
        assert cfg.train.checkpoint_every_steps == 0
