"""The precision dataflow pass (analysis.dtype_flow + precision_check).

Three layers, mirroring the analysis suite's structure: (1) every rule
fires on a seeded known-bad fixture and stays quiet on its known-good
twin — the bf16 scan carry vs the f32 twin is the canonical pair; (2)
the policy/census machinery round-trips (PrecisionPolicy.violations(),
rebaseline against a temp copy, coverage holes); (3) the shipped
all-fp32 tree is pinned clean: every registered contract program walks,
zero findings, zero suppressions.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from stmgcn_tpu.analysis.dtype_flow import flow_program, program_flows
from stmgcn_tpu.analysis.precision_check import (
    PRECISION_BASELINES,
    check_flow,
    check_precision,
    precision_summary,
)
from stmgcn_tpu.config import PrecisionPolicy


def _flow(fn, *avals, name="fixture"):
    return flow_program(name, jax.make_jaxpr(fn)(*avals))


def _rules(findings):
    return {f.rule for f in findings}


XS = jax.ShapeDtypeStruct((8,), jnp.float32)


class TestAccumDtypeRule:
    def _scan_sum(self, carry_dtype):
        def fn(xs):
            def body(c, x):
                return c + x.astype(carry_dtype), c

            return jax.lax.scan(body, jnp.zeros((), carry_dtype), xs)

        return fn

    def test_bf16_scan_carry_fires_naming_the_carry(self):
        flow = _flow(self._scan_sum(jnp.bfloat16), XS, name="bf16_accum")
        findings = check_flow(flow, PrecisionPolicy())
        assert _rules(findings) == {"accum-dtype"}
        [f] = findings
        # the finding names the exact scan-carry eqn, not just the program
        carry = next(s for s in flow.sites if s.role == "scan_carry")
        assert f"eqn #{carry.eqn_index} (scan) carry[0]" in f.message
        assert "bfloat16" in f.message
        assert "reduction_f32_roles" in f.message
        assert f.path == "<contract:precision:bf16_accum>"

    def test_f32_twin_passes(self):
        flow = _flow(self._scan_sum(jnp.float32), XS, name="f32_twin")
        assert check_flow(flow, PrecisionPolicy()) == []
        # same program shape: the twin really does have the same carry
        assert any(s.role == "scan_carry" for s in flow.sites)

    def test_bf16_cumsum_fires_inside_sub_jaxpr(self):
        # jnp.cumsum keeps the narrow dtype AND hides the cumsum eqn in
        # a pjit sub-jaxpr — the recursive walk still classifies it
        flow = _flow(
            lambda xs: jnp.cumsum(xs.astype(jnp.bfloat16)), XS, name="csum"
        )
        assert _rules(check_flow(flow, PrecisionPolicy())) == {"accum-dtype"}

    def test_jnp_sum_of_bf16_upcasts_and_passes(self):
        flow = _flow(
            lambda xs: jnp.sum(xs.astype(jnp.bfloat16)), XS, name="rsum_ok"
        )
        assert check_flow(flow, PrecisionPolicy()) == []

    def test_bf16_max_is_order_statistic_not_accumulation(self):
        flow = _flow(
            lambda xs: jnp.max(xs.astype(jnp.bfloat16)), XS, name="rmax"
        )
        assert check_flow(flow, PrecisionPolicy()) == []


class TestImplicitCastRule:
    def test_unwhitelisted_cast_fires(self):
        policy = PrecisionPolicy(cast_whitelist=())
        flow = _flow(lambda x: x.astype(jnp.bfloat16) * 1, XS, name="cast")
        findings = check_flow(flow, policy)
        assert _rules(findings) == {"implicit-cast"}
        assert "float32->bfloat16" in findings[0].message
        assert "cast_whitelist" in findings[0].message

    def test_whitelisted_cast_passes(self):
        flow = _flow(lambda x: x.astype(jnp.bfloat16) * 1, XS, name="cast")
        assert check_flow(flow, PrecisionPolicy()) == []

    def test_f64_cast_belongs_to_fp64_promotion(self):
        """Promotions to f64 are fp64-promotion's finding (jaxpr_check),
        never double-reported as implicit-cast."""
        jax.config.update("jax_enable_x64", True)
        try:
            flow = _flow(
                lambda x: x.astype(jnp.float64), XS, name="to64"
            )
        finally:
            jax.config.update("jax_enable_x64", False)
        policy = PrecisionPolicy(cast_whitelist=())
        assert _rules(check_flow(flow, policy)) <= {"precision-policy"}
        assert "implicit-cast" not in _rules(check_flow(flow, policy))
        assert any(e["kind"] == "convert" for e in flow.fp64_events)


class TestPrecisionPolicyRule:
    def test_bf16_dot_outside_role_allowance_fires(self):
        policy = PrecisionPolicy(
            role_dtypes={"dot_general": ("float32",)},
            cast_whitelist=(("float32", "bfloat16"),),
        )
        a = jax.ShapeDtypeStruct((4, 4), jnp.float32)
        flow = _flow(
            lambda m: jnp.matmul(
                m.astype(jnp.bfloat16), m.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            ),
            a, name="bf16_dot",
        )
        findings = check_flow(flow, policy)
        assert _rules(findings) == {"precision-policy"}
        assert any(
            "role_dtypes['dot_general']" in f.message for f in findings
        )

    def test_bf16_dot_passes_default_policy(self):
        """The default policy pre-approves the bf16 migration's compute
        dtype for dot-general operands — but only with an explicit f32
        accumulator (``preferred_element_type``); a plain bf16 matmul
        (bf16-out accumulator) stays an accum-dtype finding."""
        a = jax.ShapeDtypeStruct((4, 4), jnp.float32)
        flow = _flow(
            lambda m: jnp.matmul(
                m.astype(jnp.bfloat16), m.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            ),
            a, name="bf16_dot",
        )
        assert check_flow(flow, PrecisionPolicy()) == []
        naked = _flow(
            lambda m: m.astype(jnp.bfloat16) @ m.astype(jnp.bfloat16),
            a, name="bf16_dot_naked",
        )
        assert _rules(check_flow(naked, PrecisionPolicy())) == {"accum-dtype"}

    def test_master_param_boundary(self):
        def step(p, x):
            return p - 0.1 * x.astype(p.dtype), jnp.sum(x)

        p16 = jax.ShapeDtypeStruct((8,), jnp.bfloat16)
        closed = jax.make_jaxpr(step)(p16, XS)
        flow = flow_program(
            "halfmaster", closed,
            in_labels=("param", "window"), out_labels=("param", "loss"),
        )
        findings = check_flow(flow, PrecisionPolicy())
        assert any(
            "master_param_dtype" in f.message and "param[0]" in f.message
            for f in findings
        )


class TestProvenanceChains:
    def test_chain_names_input_label_and_cast_steps(self):
        def fn(w, x):
            return jnp.sum(w.astype(jnp.bfloat16) * x.astype(jnp.bfloat16))

        closed = jax.make_jaxpr(fn)(XS, XS)
        flow = flow_program(
            "prov", closed, in_labels=("param", "window")
        )
        cast = next(s for s in flow.sites if s.role == "cast")
        assert cast.provenance[0] == "input:param[0]"
        assert cast.provenance[-1] == "cast:float32->bfloat16"
        rendered = cast.describe()
        assert "input:param[0] -> cast:float32->bfloat16" in rendered
        assert f"eqn #{cast.eqn_index}" in rendered

    def test_label_arity_mismatch_raises(self):
        closed = jax.make_jaxpr(lambda x: x)(XS)
        with pytest.raises(ValueError, match="in_labels"):
            flow_program("bad", closed, in_labels=("a", "b"))


class TestPolicyViolations:
    def test_default_policy_is_self_consistent(self):
        assert PrecisionPolicy().violations() == []

    def test_sub_f32_master_fires(self):
        v = PrecisionPolicy(master_param_dtype="bfloat16").violations()
        assert any("master_param_dtype" in msg for msg in v)

    def test_unknown_role_fires(self):
        v = PrecisionPolicy(role_dtypes={"warp_drive": ("float32",)})
        assert any("warp_drive" in msg for msg in v.violations())

    def test_reduction_allowance_contradiction_fires(self):
        v = PrecisionPolicy(
            role_dtypes={"scan_carry": ("bfloat16",)},
        ).violations()
        assert any("reduction_f32_roles" in msg for msg in v)

    def test_f64_whitelist_contradicts_fp64_rule(self):
        v = PrecisionPolicy(
            cast_whitelist=(("float32", "float64"),)
        ).violations()
        assert any("float64" in msg for msg in v)

    def test_violations_become_findings(self):
        policy = PrecisionPolicy(master_param_dtype="float8")
        findings = check_precision("smoke", policy=policy, flows={})
        assert any(
            f.rule == "precision-policy" and "PrecisionPolicy" in f.message
            for f in findings
        )

    def test_json_round_trip_keeps_tuples(self):
        policy = PrecisionPolicy()
        thawed = PrecisionPolicy(
            **json.loads(json.dumps(dataclasses_asdict(policy)))
        )
        assert thawed.violations() == []
        assert thawed.cast_whitelist == policy.cast_whitelist


def dataclasses_asdict(policy):
    import dataclasses

    return dataclasses.asdict(policy)


class TestCoverageAndCensus:
    def test_missing_program_is_a_coverage_finding(self):
        flows = dict(program_flows("smoke"))
        flows.pop("train_step")
        findings = check_precision("smoke", flows=flows)
        assert any(
            f.rule == "precision-policy"
            and "train_step" in f.message
            and "coverage hole" in f.message
            for f in findings
        )

    def test_census_drift_is_a_finding(self):
        flow = program_flows("smoke")["train_step"]
        from stmgcn_tpu.analysis.precision_check import _census_findings

        baseline = json.loads(json.dumps(PRECISION_BASELINES["train_step"]))
        assert _census_findings("train_step", flow.census, baseline) == []
        baseline["bytes"].pop("float32")
        drift = _census_findings("train_step", flow.census, baseline)
        assert any("drifted" in f.message for f in drift)
        missing = _census_findings("train_step", flow.census, None)
        assert any("--rebaseline" in f.message for f in missing)

    def test_rebaseline_round_trips_against_copy(self, tmp_path):
        import stmgcn_tpu.analysis.precision_check as pc

        target = tmp_path / "precision_check_copy.py"
        target.write_text(open(pc.__file__).read())
        before = json.loads(json.dumps(PRECISION_BASELINES))
        try:
            result = pc.rebaseline_precision(path=str(target))
            assert result["path"] == str(target)
            line = next(
                l for l in target.read_text().splitlines()
                if l.startswith("PRECISION_BASELINES = ")
            )
            ns = {}
            exec(line, ns)
            assert ns["PRECISION_BASELINES"] == result["census"]
            # in-memory baselines updated so later checks see them
            assert pc.PRECISION_BASELINES == result["census"]
        finally:
            pc.PRECISION_BASELINES.clear()
            pc.PRECISION_BASELINES.update(before)

    def test_missing_literal_raises(self, tmp_path):
        import stmgcn_tpu.analysis.precision_check as pc

        target = tmp_path / "no_literal.py"
        target.write_text("x = 1\n")
        before = json.loads(json.dumps(PRECISION_BASELINES))
        try:
            with pytest.raises(RuntimeError, match="PRECISION_BASELINES"):
                pc.rebaseline_precision(path=str(target))
        finally:
            pc.PRECISION_BASELINES.clear()
            pc.PRECISION_BASELINES.update(before)


class TestShippedTreeIsClean:
    """The tier-1 pin: today's all-fp32 tree pre-certifies clean."""

    def test_every_registered_program_walks_with_zero_findings(self):
        from stmgcn_tpu.analysis.jaxpr_check import PRIMITIVE_BUDGETS

        flows = program_flows("smoke")
        assert set(flows) == set(PRIMITIVE_BUDGETS)
        assert check_precision("smoke", flows=flows) == []

    def test_summary_shape_for_the_gate(self):
        summary = precision_summary("smoke")
        assert summary["programs"] == len(program_flows("smoke"))
        assert summary["sites"] > 0
        assert summary["findings"] == 0

    def test_zero_suppressions_in_package_source(self):
        """The precision rules hold with no `# stmgcn: ignore` escape
        hatches anywhere in the shipped package."""
        import os
        import re

        import stmgcn_tpu

        root = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
        pat = re.compile(
            r"stmgcn:\s*ignore\[(precision-policy|accum-dtype|implicit-cast)"
        )
        for dirpath, _, names in os.walk(root):
            for n in names:
                if n.endswith(".py"):
                    with open(os.path.join(dirpath, n)) as f:
                        assert not pat.search(f.read()), (dirpath, n)

    def test_fp64_scan_shares_the_walk(self):
        """jaxpr_check's fp64-promotion now consumes the dtype walk's
        structured events — same walk, byte-identical message format."""
        from stmgcn_tpu.analysis.jaxpr_check import _check_one

        jax.config.update("jax_enable_x64", True)
        try:
            closed = jax.make_jaxpr(
                lambda x: x.astype(jnp.float64) * 2
            )(XS)
        finally:
            jax.config.update("jax_enable_x64", False)
        flow = flow_program("fx", closed)
        via_flow = _check_one("fx", closed, 1, 100, fp64_events=flow.fp64_events)
        direct = _check_one("fx", closed, 1, 100)
        assert [str(f) for f in via_flow] == [str(f) for f in direct]
        assert any(f.rule == "fp64-promotion" for f in via_flow)


class TestSarifRuleMetadata:
    def test_every_rule_has_nonempty_descriptions(self):
        """The SARIF satellite: every finding-producing rule ships both
        a shortDescription and a fullDescription, never empty."""
        from stmgcn_tpu.analysis.report import Finding, render_sarif
        from stmgcn_tpu.analysis.rules import RULES

        findings = [
            Finding(rule=rid, path="x.py", line=1, message="m")
            for rid in RULES
        ]
        doc = json.loads(render_sarif(findings))
        rules = doc["runs"][0]["tool"]["driver"]["rules"]
        assert len(rules) == len(RULES)
        for rule in rules:
            assert rule["shortDescription"]["text"].strip()
            assert rule["fullDescription"]["text"].strip()

    def test_new_rules_registered_with_long_descriptions(self):
        from stmgcn_tpu.analysis.rules import RULES

        for rid in ("precision-policy", "accum-dtype", "implicit-cast"):
            assert rid in RULES
            assert RULES[rid].severity == "error"
            assert len(RULES[rid].description) > len(RULES[rid].summary)
