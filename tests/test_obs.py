"""Unified runtime observability (stmgcn_tpu/obs/).

Pins the PR's contracts: the span tracer's bounded ring + nesting +
JSONL schema, the process-wide metrics registry and its two exporters,
the ``jax.monitoring`` compile telemetry (warmup mark / freeze), the
``stmgcn obs`` CLI's one-JSON-line stdout contract, the bounded
reservoirs that replaced ``serving/metrics.py``'s unbounded lists, the
``EngineStats.device_ms_estimate`` cold-start fallback chain, and —
the expensive claim — bit-identical training results with tracing on.

The module-global tracer is process state: every test that calls
``obs_trace.configure`` must disable it again (the autouse fixture
below enforces this), or later tests in the same process would run
instrumented.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from stmgcn_tpu.obs import jaxmon
from stmgcn_tpu.obs import trace as obs_trace
from stmgcn_tpu.obs.cli import main as obs_main
from stmgcn_tpu.obs.registry import (
    REGISTRY,
    MetricsRegistry,
    Reservoir,
)
from stmgcn_tpu.obs.cli import health_main
from stmgcn_tpu.obs.report import (
    chrome_trace,
    load_trace,
    render_table,
    summarize,
)
from stmgcn_tpu.obs.trace import SCHEMA_VERSION, Tracer


@pytest.fixture(autouse=True)
def _tracing_off_after():
    yield
    obs_trace.configure(enable=False)


# -- tracer ------------------------------------------------------------


class TestTracer:
    def test_nesting_parent_depth(self):
        trc = Tracer()
        with trc.span("outer"):
            with trc.span("inner", step=3):
                pass
        outer = next(s for s in trc.spans() if s["name"] == "outer")
        inner = next(s for s in trc.spans() if s["name"] == "inner")
        assert inner["parent"] == outer["id"] and inner["depth"] == 1
        assert outer["parent"] == 0 and outer["depth"] == 0
        assert inner["attrs"] == {"step": 3}

    def test_record_span_inherits_open_nesting(self):
        import time

        trc = Tracer()
        with trc.span("outer") as sp:
            t0 = time.perf_counter()
            trc.record_span("retro", t0, t0 + 0.001)
            sp.end()
        retro = next(s for s in trc.spans() if s["name"] == "retro")
        outer = next(s for s in trc.spans() if s["name"] == "outer")
        assert retro["parent"] == outer["id"]

    def test_ring_is_bounded_and_counts_drops(self):
        trc = Tracer(capacity=8)
        for i in range(20):
            trc.record_span(f"s{i}", 0.0, 0.001)
        assert len(trc.spans()) == 8
        assert trc.dropped == 12
        # the ring keeps the most RECENT window
        assert trc.spans()[-1]["name"] == "s19"

    def test_end_is_idempotent(self):
        trc = Tracer()
        sp = trc.span("once")
        sp.end()
        sp.end()
        assert len(trc.spans()) == 1

    def test_unbalanced_close_unwinds_stack(self):
        trc = Tracer()
        outer = trc.span("outer")
        trc.span("abandoned")  # never closed (exception path analogue)
        outer.end()
        nxt = trc.span("after")
        assert nxt.parent == 0 and nxt.depth == 0
        nxt.end()

    def test_capacity_must_be_positive(self):
        with pytest.raises(ValueError):
            Tracer(capacity=0)

    def test_export_jsonl_schema(self, tmp_path):
        trc = Tracer(capacity=16)
        with trc.span("a"):
            with trc.span("b"):
                pass
        path = str(tmp_path / "t.jsonl")
        n = trc.export_jsonl(path)
        assert n == 2
        lines = open(path).read().splitlines()
        assert len(lines) == 3  # meta header + one object per span
        objs = [json.loads(line) for line in lines]  # every line is JSON
        meta, spans = objs[0], objs[1:]
        assert meta["kind"] == "meta"
        assert meta["schema_version"] == SCHEMA_VERSION
        assert meta["capacity"] == 16 and meta["spans"] == 2
        for s in spans:
            assert s["schema_version"] == SCHEMA_VERSION
            for key in ("id", "parent", "depth", "name", "ts", "dur_ms"):
                assert key in s

    def test_disabled_path_allocates_nothing(self):
        obs_trace.configure(enable=False)
        assert obs_trace.active_tracer() is None
        assert obs_trace.enabled() is False
        # the casual-path span() hands back ONE shared no-op object — the
        # zero-allocation contract the superstep hot loop relies on
        assert obs_trace.span("x") is obs_trace.span("y")
        with obs_trace.span("z") as sp:
            sp.fence(None)  # no-ops, never imports jax

    def test_module_switch_roundtrip(self):
        trc = obs_trace.configure(capacity=32)
        assert obs_trace.active_tracer() is trc and obs_trace.enabled()
        with obs_trace.span("on"):
            pass
        assert trc.spans()[0]["name"] == "on"
        obs_trace.configure(enable=False)
        assert obs_trace.active_tracer() is None


# -- report / summarize ------------------------------------------------


class TestReport:
    def _trace(self, tmp_path):
        import time

        trc = Tracer()
        with trc.span("epoch") as sp:
            t0 = time.perf_counter()
            time.sleep(0.02)
            trc.record_span("step", t0, time.perf_counter())
            sp.end()
        path = str(tmp_path / "t.jsonl")
        trc.export_jsonl(path)
        return path

    def test_summarize_self_time_subtracts_children(self, tmp_path):
        meta, spans = load_trace(self._trace(tmp_path))
        assert meta["kind"] == "meta"
        summary = summarize(spans)
        phases = {p["name"]: p for p in summary["phases"]}
        # a leaf keeps its full duration as self time ...
        assert phases["step"]["self_ms"] == phases["step"]["total_ms"]
        # ... and the child's duration comes out of the parent's
        assert phases["epoch"]["self_ms"] == pytest.approx(
            phases["epoch"]["total_ms"] - phases["step"]["total_ms"],
            abs=0.005,
        )
        assert 0.0 < summary["coverage"] <= 1.01
        assert "wall_ms" in summary

    def test_render_table_mentions_every_phase(self, tmp_path):
        meta, spans = load_trace(self._trace(tmp_path))
        table = render_table(summarize(spans), meta)
        assert "epoch" in table and "step" in table
        assert "coverage" in table

    def test_load_trace_rejects_garbage(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text("not json\n")
        with pytest.raises((ValueError, json.JSONDecodeError)):
            load_trace(str(bad))


# -- metrics registry --------------------------------------------------


class TestRegistry:
    def test_get_or_create_identity(self):
        reg = MetricsRegistry()
        c1 = reg.counter("x", {"a": "1"})
        c2 = reg.counter("x", {"a": "1"})
        assert c1 is c2
        assert reg.counter("x", {"a": "2"}) is not c1
        c1.inc()
        c1.inc(2.5)
        assert c1.value == 3.5

    def test_kind_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_to_json_shapes(self):
        reg = MetricsRegistry()
        reg.counter("c").inc(3)
        reg.gauge("g").set(1.5)
        reg.histogram("h").extend([1.0, 2.0, 3.0])
        snap = reg.to_json()
        assert snap["c"] == 3  # whole floats render as ints
        assert snap["g"] == 1.5
        assert snap["h"]["count"] == 3 and snap["h"]["p50"] == 2.0
        # labeled metrics render name{k=v}
        reg.counter("c", {"engine": "0"}).inc()
        assert reg.to_json()["c{engine=0}"] == 1

    def test_to_prometheus_exposition(self):
        reg = MetricsRegistry()
        reg.counter("serving.shed", {"reason": "overloaded"}).inc(4)
        reg.histogram("latency-ms").add(7.0)
        text = reg.to_prometheus()
        assert "# TYPE serving_shed counter" in text
        assert 'serving_shed{reason="overloaded"} 4.0' in text
        assert "# TYPE latency_ms summary" in text
        assert 'latency_ms{quantile="0.5"} 7.0' in text
        assert "latency_ms_count 1" in text
        assert text.endswith("\n")

    def test_reset_keeps_registrations_alive(self):
        reg = MetricsRegistry()
        c = reg.counter("x")
        c.inc(5)
        reg.reset()
        assert c.value == 0.0
        assert reg.counter("x") is c  # held references stay live

    def test_dumps_is_one_json_doc(self):
        reg = MetricsRegistry()
        reg.counter("a").inc()
        assert json.loads(reg.dumps()) == {"a": 1}


class TestReservoir:
    def test_bounded_retention_keeps_recent(self):
        r = Reservoir(capacity=4)
        r.extend(range(10))
        assert r.samples() == [6, 7, 8, 9]
        assert r.count == 10  # all-time count survives eviction
        assert r.total == sum(range(10))

    def test_percentile_shape_matches_serving_metrics(self):
        r = Reservoir(capacity=16)
        assert r.percentiles() == {
            "p50": None, "p95": None, "p99": None, "mean": None,
        }
        r.extend([1.0, 2.0, 3.0, 4.0])
        from stmgcn_tpu.serving.metrics import percentiles

        assert r.percentiles() == percentiles([1.0, 2.0, 3.0, 4.0])

    def test_capacity_must_be_positive(self):
        with pytest.raises(ValueError):
            Reservoir(capacity=0)

    def test_mean_default_when_empty(self):
        assert Reservoir(capacity=2).mean(default=9.5) == 9.5


# -- jax monitoring ----------------------------------------------------


class TestJaxMonitoring:
    def test_install_idempotent_and_counts_compiles(self):
        import jax
        import jax.numpy as jnp

        assert jaxmon.install() is True
        assert jaxmon.install() is True  # second call must not re-register
        assert jaxmon.installed()
        before = REGISTRY.counter("jax.compilations").value

        @jax.jit
        def f(x):
            return x * 2 + 1

        f(jnp.arange(7)).block_until_ready()
        assert REGISTRY.counter("jax.compilations").value > before

    def test_warmup_mark_and_recompile_gauge(self):
        import jax
        import jax.numpy as jnp

        assert jaxmon.install() is True
        jaxmon.mark_warmup_complete()
        assert jaxmon.snapshot()["recompiles_after_warmup"] == 0

        @jax.jit
        def g(x):
            return x - 3

        g(jnp.arange(11)).block_until_ready()  # a compile after the mark
        snap = jaxmon.snapshot()
        assert snap["recompiles_after_warmup"] >= 1

        # freeze pins the reading; later compiles stay invisible
        frozen = jaxmon.freeze_recompiles()
        g(jnp.arange(13).astype(jnp.float32)).block_until_ready()
        assert jaxmon.snapshot()["recompiles_after_warmup"] == int(frozen)
        # re-marking unfreezes and re-baselines
        jaxmon.mark_warmup_complete()
        assert jaxmon.snapshot()["recompiles_after_warmup"] == 0

    def test_record_upload_and_per_step_rate(self):
        before = REGISTRY.counter("jax.upload_bytes").value
        jaxmon.record_upload(1000)
        jaxmon.record_upload(1000)
        snap = jaxmon.snapshot(steps=2)
        assert snap["upload_bytes"] - int(before) == 2000
        assert "upload_bytes_per_step" in snap


# -- stmgcn obs CLI ----------------------------------------------------


class TestObsCli:
    def _trace(self, tmp_path):
        trc = Tracer()
        with trc.span("phase"):
            trc.record_span("work", 0.0, 1.0)
        path = str(tmp_path / "t.jsonl")
        trc.export_jsonl(path)
        return path

    def test_json_format_is_one_line(self, tmp_path, capsys):
        rc = obs_main([self._trace(tmp_path), "--format", "json"])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.count("\n") == 1 and out.endswith("\n")
        doc = json.loads(out)
        assert doc["meta"]["kind"] == "meta"
        assert {"wall_ms", "coverage", "phases"} <= set(doc["summary"])
        assert "spans" not in doc  # only with --dump

    def test_json_dump_includes_spans(self, tmp_path, capsys):
        rc = obs_main([self._trace(tmp_path), "--format", "json", "--dump"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0 and len(doc["spans"]) == 2

    def test_text_renders_table(self, tmp_path, capsys):
        rc = obs_main([self._trace(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0 and "phase" in out and "coverage" in out

    def test_missing_file_exits_2(self, tmp_path, capsys):
        rc = obs_main([str(tmp_path / "nope.jsonl")])
        err = capsys.readouterr().err
        assert rc == 2 and "cannot read" in err

    def test_chrome_format_is_one_trace_document(self, tmp_path, capsys):
        """--format chrome emits ONE ``chrome://tracing`` /
        ui.perfetto.dev JSON document on stdout and nothing else."""
        rc = obs_main([self._trace(tmp_path), "--format", "chrome"])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.count("\n") == 1 and out.endswith("\n")
        doc = json.loads(out)
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        assert len(events) == 2
        assert all(e["ph"] == "X" for e in events)
        assert {"schema_version", "capacity", "dropped"} <= set(
            doc["otherData"])

    def test_chrome_trace_track_assignment_and_units(self):
        """Trace-event ts/dur are MICROSECONDS (span fields are ms);
        overlapping roots land on distinct derived tracks, children
        inherit their root's track, sequential roots reuse track 0."""
        spans = [
            {"id": 1, "parent": 0, "name": "a", "ts": 0.0, "dur_ms": 5.0},
            {"id": 2, "parent": 1, "name": "a.child", "ts": 1.0,
             "dur_ms": 2.0, "attrs": {"k": 1}},
            {"id": 3, "parent": 0, "name": "b", "ts": 2.0, "dur_ms": 2.0},
            {"id": 4, "parent": 0, "name": "c", "ts": 6.0, "dur_ms": 1.0},
        ]
        doc = chrome_trace({"schema_version": 1, "capacity": 8,
                            "dropped": 0}, spans)
        by_name = {e["name"]: e for e in doc["traceEvents"]}
        assert by_name["a"]["ts"] == 0.0 and by_name["a"]["dur"] == 5000.0
        assert by_name["a"]["tid"] == 0
        assert by_name["a.child"]["tid"] == 0  # child rides its root
        assert by_name["a.child"]["args"] == {"k": 1}
        assert by_name["b"]["tid"] == 1  # overlaps a -> new track
        assert by_name["c"]["tid"] == 0  # a ended -> track 0 free again

    def test_obs_package_is_lean(self):
        """Importing stmgcn_tpu.obs must not pull jax (serving/export
        import it at module scope; their leanness contracts inherit)."""
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import sys; import stmgcn_tpu.obs; "
                "print('JAX' if any(m == 'jax' or m.startswith('jax.') "
                "for m in sys.modules) else 'LEAN')",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.stdout.strip() == "LEAN", out.stderr


class TestHealthCli:
    """``stmgcn health PATH``: same stdout contract family as obs —
    text renders the fixed-width report, --format json is EXACTLY one
    machine-parseable line, unreadable input exits 2."""

    def _health(self, tmp_path):
        from stmgcn_tpu.obs.health import HealthWriter

        path = str(tmp_path / "health.jsonl")
        w = HealthWriter(path, {"every_k": 1, "groups": ["lstm"]})
        w.write({"kind": "train", "epoch": 0, "step": 2, "steps": 2,
                 "loss": 0.5, "grad_norm": 1.25, "update_ratio": 1e-3,
                 "nonfinite_grads": 0, "nonfinite_loss": 0,
                 "group_norms": {"lstm": 0.7}})
        w.write({"kind": "drift", "city": "0", "phase": "input",
                 "z_max": 3.0, "psi": 0.02, "n": 64, "generation": 0})
        w.close()
        return path

    def test_json_format_is_one_line(self, tmp_path, capsys):
        rc = health_main([self._health(tmp_path), "--format", "json"])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.count("\n") == 1 and out.endswith("\n")
        doc = json.loads(out)
        assert doc["meta"]["every_k"] == 1
        assert doc["summary"]["train"]["count"] == 1
        assert doc["summary"]["drift"]["worst"]["city"] == "0"
        assert "records" not in doc  # only with --dump

    def test_json_dump_includes_records(self, tmp_path, capsys):
        rc = health_main([self._health(tmp_path), "--format", "json",
                          "--dump"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0 and len(doc["records"]) == 2

    def test_text_renders_report(self, tmp_path, capsys):
        rc = health_main([self._health(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "grad_norm[lstm]" in out and "drift:" in out

    def test_missing_file_exits_2(self, tmp_path, capsys):
        rc = health_main([str(tmp_path / "nope.jsonl")])
        err = capsys.readouterr().err
        assert rc == 2 and "cannot read" in err


# -- EngineStats: bounded reservoirs + cold-start fallback --------------


class TestEngineStats:
    def test_reservoir_bounds_memory(self):
        from stmgcn_tpu.serving.metrics import EngineStats

        stats = EngineStats(reservoir=8)
        for i in range(100):
            stats.record_dispatch(4, 4, [float(i)], float(i))
        snap = stats.snapshot()
        bucket = snap["buckets"]["4"]
        assert bucket["dispatches"] == 100  # all-time totals survive
        # but the retained window is the last 8 samples: p50 of 92..99
        assert bucket["device_ms"]["p50"] == 95.5
        assert snap["totals"]["dispatches"] == 100

    def test_device_ms_estimate_fallback_chain(self):
        from stmgcn_tpu.serving.metrics import EngineStats

        stats = EngineStats()
        # 1. stone cold: no rung has samples -> the caller's default
        assert stats.device_ms_estimate(4, default=7.5) == 7.5
        # 2. rung miss, other rungs warm -> global mean
        stats.record_dispatch(16, 16, [1.0], 10.0)
        stats.record_dispatch(16, 16, [1.0], 20.0)
        assert stats.device_ms_estimate(4, default=7.5) == 15.0
        # 3. rung warm -> that rung's own mean wins
        stats.record_dispatch(4, 4, [1.0], 2.0)
        assert stats.device_ms_estimate(4, default=7.5) == 2.0

    def test_snapshot_totals_come_from_registry(self):
        from stmgcn_tpu.serving.metrics import EngineStats

        stats = EngineStats()
        stats.record_dispatch(4, 3, [1.0, 1.0, 1.0], 5.0)
        engine_label = stats._labels["engine"]
        assert (
            REGISTRY.counter("serving.rows", {"engine": engine_label}).value
            == 3.0
        )
        assert stats.snapshot()["totals"]["rows"] == 3

    def test_shed_counts_registry_backed(self):
        from stmgcn_tpu.serving.metrics import EngineStats

        stats = EngineStats()
        stats.record_shed("overloaded")
        stats.record_shed("overloaded")
        stats.record_shed("degraded")
        assert stats.shed_counts() == {"overloaded": 2, "degraded": 1}
        assert stats.snapshot()["totals"]["shed"] == {
            "overloaded": 2, "degraded": 1,
        }


# -- tracing-on bit parity ---------------------------------------------


def _train_tiny(trace: bool, tmp_path, steps_per_superstep=2):
    from stmgcn_tpu.config import preset
    from stmgcn_tpu.experiment import build_trainer

    trc = obs_trace.configure(enable=trace)
    try:
        cfg = preset("smoke")
        cfg.data.rows = 5
        cfg.data.n_timesteps = 24 * 7 * 2 + 60
        cfg.train.epochs = 2
        cfg.train.batch_size = 8
        cfg.train.data_placement = "resident"
        cfg.train.steps_per_superstep = steps_per_superstep
        cfg.train.out_dir = str(tmp_path / ("traced" if trace else "plain"))
        trainer = build_trainer(cfg, verbose=False)
        history = trainer.train()
        return trainer.params, history, trc
    finally:
        obs_trace.configure(enable=False)


class TestTracedParity:
    def test_tracing_is_bit_invisible_to_training(self, tmp_path):
        """The PR's core safety claim: spans + fences change WHEN the
        host observes device results, never the results themselves —
        and the traced superstep run emits every expected phase."""
        import jax

        params_plain, hist_plain, _ = _train_tiny(False, tmp_path)
        params_traced, hist_traced, trc = _train_tiny(True, tmp_path)
        jax.tree.map(
            np.testing.assert_array_equal, params_plain, params_traced
        )
        assert hist_plain == hist_traced

        names = {s["name"] for s in trc.spans()}
        assert {
            "train.host_pack", "train.upload", "train.superstep",
            "train.epoch", "train.train_epoch", "train.eval_epoch",
            "train.checkpoint", "event.train_start", "event.train_end",
        } <= names


# -- slow tier: end-to-end CLI trace contracts --------------------------


@pytest.mark.slow
class TestTraceCliContract:
    def test_traced_run_schema_and_obs_cli_stdout(self, tmp_path):
        """The JSONL schema contract on a REAL `--trace-out` training run
        (one JSON object per line, schema_version everywhere, spans nest)
        plus the one-JSON-line stdout contract of `stmgcn obs --format
        json` over that trace."""
        trace_path = str(tmp_path / "trace.jsonl")
        run = subprocess.run(
            [
                sys.executable, "-m", "stmgcn_tpu.cli",
                "--preset", "smoke",
                "--rows", "5", "--timesteps", str(24 * 7 * 2 + 60),
                "--epochs", "2", "--batch-size", "8",
                "--data-placement", "resident",
                "--steps-per-superstep", "2",
                "--out-dir", str(tmp_path / "out"),
                "--trace-out", trace_path,
            ],
            capture_output=True,
            text=True,
            timeout=560,
        )
        assert run.returncode == 0, run.stderr[-2000:]

        lines = open(trace_path).read().splitlines()
        assert len(lines) >= 2
        objs = [json.loads(line) for line in lines]  # one object per line
        meta, spans = objs[0], objs[1:]
        assert meta["kind"] == "meta"
        assert meta["schema_version"] == SCHEMA_VERSION
        assert meta["spans"] == len(spans)
        ids = set()
        for s in spans:
            assert s["schema_version"] == SCHEMA_VERSION
            assert s["dur_ms"] >= 0.0
            ids.add(s["id"])
        for s in spans:  # nesting: every parent is a recorded span (or root)
            assert s["parent"] == 0 or s["parent"] in ids
            if s["parent"] in ids:
                assert s["depth"] >= 1

        # span durations must account for >= 90% of the wall window
        summary = summarize(spans)
        assert summary["coverage"] >= 0.90, summary

        obs = subprocess.run(
            [
                sys.executable, "-m", "stmgcn_tpu.cli",
                "obs", trace_path, "--format", "json",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert obs.returncode == 0, obs.stderr
        assert obs.stdout.count("\n") == 1  # EXACTLY one JSON line
        doc = json.loads(obs.stdout)
        assert doc["meta"]["spans"] == len(spans)
        assert doc["summary"]["coverage"] >= 0.90


# -- continual-loop metrics (ingest ring / fine-tune / promotion gate) --


class TestContinualLoopMetrics:
    """Registry-label contracts for the closed continual loop.

    Every stage of the loop reports through the same process-wide
    registry the serving engine uses, so ``stmgcn obs`` and the
    Prometheus exposition see it with no extra wiring: per-city
    ``ingest.*`` counters + the ``ring.occupancy`` gauge from the
    device-resident ring, ``continual.retrains`` from the fine-tune
    trainer, ``continual.promotions`` / ``continual.rejections{reason}``
    from the gate, and the ``promotion.gate_ms`` latency reservoir.
    """

    def test_ingest_counters_and_occupancy_gauge_city_labeled(self):
        from stmgcn_tpu.data import SeriesRing

        reg = MetricsRegistry()
        ring = SeriesRing(8, 2, 1, reorder_window=2, city=3, registry=reg)
        row = np.zeros((2, 1), np.float32)
        ring.ingest(0, row)
        ring.ingest(2, row)          # gap: ts 1 forward-filled
        ring.ingest(2, row)          # duplicate redelivery
        ring.ingest(4, row)          # gap: ts 3 forward-filled
        ring.ingest(3, row)          # late, inside the reorder window
        ring.ingest(5, np.full((2, 1), np.nan, np.float32))  # quarantined

        labels = {"city": "3"}
        assert reg.counter("ingest.rows", labels).value == ring.rows
        assert reg.counter("ingest.gaps", labels).value == ring.gaps == 2
        assert reg.counter("ingest.out_of_order", labels).value == 1
        assert reg.counter("ingest.duplicates", labels).value == 1
        assert reg.counter("ingest.nonfinite", labels).value == 1
        # occupancy is a fill fraction, not a row count
        assert reg.gauge("ring.occupancy", labels).value == \
            len(ring) / ring.capacity
        # both exporters surface the labeled series
        assert 'ingest_rows{city="3"}' in reg.to_prometheus()
        assert 'ring.occupancy{city=3}' in reg.to_json()

    def _gate(self, reg, tmp_path):
        import types

        from stmgcn_tpu.serving.promotion import PromotionGate

        class _Eng:  # the gate's engine surface, minus the serving stack
            generation = 0
            _params_template = None
            _fault_plan = None

            def watch_checkpoints(self, out_dir):
                return types.SimpleNamespace(poll=lambda: True)

        return PromotionGate(_Eng(), str(tmp_path), registry=reg)

    def test_gate_counters_and_latency_reservoir(self, tmp_path):
        from stmgcn_tpu.train.checkpoint import save_checkpoint

        reg = MetricsRegistry()
        gate = self._gate(reg, tmp_path)
        good = str(tmp_path / "candidate-0000.ckpt")
        save_checkpoint(good, {"w": np.ones((2,), np.float32)}, None, {})
        clean = {"nonfinite": 0, "grad_norm_max": 1.0,
                 "update_ratio_max": 1e-3}
        assert gate.consider(good, clean).accepted
        # promotion rotated `good` away — the reject drill needs its own
        bad = str(tmp_path / "candidate-0001.ckpt")
        save_checkpoint(bad, {"w": np.ones((2,), np.float32)}, None, {})
        assert not gate.consider(bad, {**clean, "nonfinite": 2}).accepted

        assert reg.counter("continual.promotions").value == 1
        assert reg.counter(
            "continual.rejections", {"reason": "nonfinite"}
        ).value == 1
        h = reg.histogram("promotion.gate_ms")
        assert h.count == 2 and all(v >= 0.0 for v in h.samples())
        text = reg.to_prometheus()
        assert 'continual_rejections{reason="nonfinite"} 1.0' in text
        assert "# TYPE promotion_gate_ms summary" in text
        assert "promotion_gate_ms_count 2" in text

    def test_retrains_counter_and_daemon_up_gauge(self, tmp_path):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import optax

        from stmgcn_tpu.config import ContinualConfig
        from stmgcn_tpu.data import SeriesRing, WindowSpec
        from stmgcn_tpu.train import ContinualDaemon, ContinualTrainer

        class _Tiny(nn.Module):
            @nn.compact
            def __call__(self, supports, x, n_real=None):
                return nn.Dense(x.shape[-1])(x.mean(axis=1))

        reg = MetricsRegistry()
        spec = WindowSpec(2, 0, 0, 4, 1)
        rng = np.random.default_rng(0)
        series = rng.uniform(0, 1, (10, 2, 1)).astype(np.float32)
        ring = SeriesRing.from_series(series, capacity=16, reorder_window=2,
                                      registry=reg)
        model = _Tiny()
        supports = np.zeros((1, 1, 2, 2), np.float32)
        params = model.init(
            jax.random.key(0), jnp.asarray(supports),
            jnp.zeros((1, 2, 2, 1), jnp.float32),
        )
        cfg = ContinualConfig(enabled=True, finetune_steps=1,
                              finetune_batch=2)
        trainer = ContinualTrainer(
            model, optax.sgd(1e-2), supports, ring, spec, cfg,
            str(tmp_path), params=params, holdout=2, registry=reg,
        )
        trainer.finetune()
        assert reg.counter("continual.retrains").value == 1
        assert "continual_retrains 1.0" in reg.to_prometheus()

        class _StubGate:
            class _engine:
                @staticmethod
                def drift_snapshot():
                    return None

        daemon = ContinualDaemon(trainer, _StubGate(), config=cfg,
                                 registry=reg)
        assert reg.gauge("continual.daemon_up").value == 1
