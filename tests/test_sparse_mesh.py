"""Sparse supports composed with the (dp, region) mesh (8 virtual devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.parallel import (
    MeshPlacement,
    ShardSpec,
    ShardedBlockSparse,
    build_mesh,
    sharded_from_dense,
    sharded_spmm_apply,
)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return build_mesh(dp=2, region=4)


def make_supports(K=3, N=256, w=30, seed=0):
    rng = np.random.default_rng(seed)
    mats = rng.standard_normal((K, N, N)).astype(np.float32)
    dist = np.abs(np.subtract.outer(np.arange(N), np.arange(N)))
    mats[:, dist > w] = 0.0
    return mats


class TestShardedSpmmApply:
    def test_matches_dense(self, mesh):
        mats = make_supports()
        x = np.random.default_rng(1).standard_normal((8, 256, 5)).astype(np.float32)
        ssp = sharded_from_dense(mats, 4)
        got = jax.jit(lambda xx: sharded_spmm_apply(mesh, ssp, xx))(jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(got), np.einsum("kij,bjf->kbif", mats, x), rtol=1e-4, atol=1e-4
        )

    @pytest.mark.slow
    def test_gradient_matches_dense(self, mesh):
        mats = make_supports()
        x = np.random.default_rng(2).standard_normal((4, 256, 3)).astype(np.float32)
        c = np.random.default_rng(3).standard_normal((3, 4, 256, 3)).astype(np.float32)
        ssp = sharded_from_dense(mats, 4)
        g = jax.grad(
            lambda xx: jnp.sum(sharded_spmm_apply(mesh, ssp, xx) * jnp.asarray(c))
        )(jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(g), np.einsum("kij,kbif->bjf", mats, c), rtol=1e-3, atol=1e-4
        )

    def test_validation(self):
        with pytest.raises(ValueError, match="divisible"):
            sharded_from_dense(make_supports(N=250), 4)
        with pytest.raises(ValueError, match="\\(K, N, N\\)"):
            sharded_from_dense(np.zeros((2, 8, 16), np.float32), 2)

    def test_strip_memory_fraction(self, mesh):
        # the point of sharded sparsity: ONE shard's strip storage is far
        # below the full dense stack every device would otherwise hold
        mats = make_supports(N=512, w=16)
        ssp = sharded_from_dense(mats, 4)
        per_shard = ssp.nbytes / ssp.n_shards
        assert per_shard < mats.nbytes / 2


class TestSparseMeshModel:
    def test_conv_layer_parity_with_dense_params(self, mesh):
        from stmgcn_tpu.ops.chebconv import ChebGraphConv, SparseChebGraphConv

        mats = make_supports()
        x = jnp.asarray(
            np.random.default_rng(4).standard_normal((8, 256, 6)).astype(np.float32)
        )
        dense = ChebGraphConv(n_supports=3, features=8)
        params = dense.init(jax.random.key(0), jnp.asarray(mats), x)
        want = dense.apply(params, jnp.asarray(mats), x)

        sharded = SparseChebGraphConv(n_supports=3, features=8, spec=ShardSpec(mesh))
        got = jax.jit(sharded.apply)(params, sharded_from_dense(mats, 4), x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_spec_required(self, mesh):
        from stmgcn_tpu.ops.chebconv import SparseChebGraphConv

        conv = SparseChebGraphConv(n_supports=3, features=4)
        ssp = sharded_from_dense(make_supports(), 4)
        with pytest.raises(ValueError, match="ShardSpec"):
            conv.init(jax.random.key(0), ssp, jnp.zeros((2, 256, 3)))


class TestSparseMeshTrainer:
    def _cfg(self, tmp_path, sparse, mesh_on=True):
        from stmgcn_tpu.config import preset

        cfg = preset("scaled")
        cfg.data.rows = 16
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.model.dtype = "float32"
        cfg.model.sparse = sparse
        cfg.train.epochs = 1
        cfg.train.batch_size = 16
        cfg.train.out_dir = str(tmp_path / ("mesh" if mesh_on else "single"))
        if mesh_on:
            cfg.mesh.dp, cfg.mesh.region = 2, 4
        else:
            cfg.mesh.dp = cfg.mesh.region = 1
            cfg.mesh.region_strategy = "gspmd"
        return cfg

    @pytest.mark.slow
    def test_sparse_mesh_training_matches_single_device(self, mesh, tmp_path):
        """VERDICT round-1 missing #4: sparse trains on the mesh with
        sharded-vs-single parity (identical loss trajectory)."""
        from stmgcn_tpu.experiment import build_trainer, route_supports, build_dataset

        cfg = self._cfg(tmp_path, sparse=True, mesh_on=True)
        sup, modes = route_supports(cfg, build_dataset(cfg))
        assert modes == ("sparse",) * 3
        assert all(isinstance(s, ShardedBlockSparse) for s in sup)

        mesh_losses = build_trainer(cfg, verbose=False).train()
        single = build_trainer(
            self._cfg(tmp_path, sparse=True, mesh_on=False), verbose=False
        ).train()
        np.testing.assert_allclose(
            mesh_losses["validate"], single["validate"], rtol=1e-5
        )

    def test_single_device_blockcsr_rejected_on_mesh(self, mesh):
        from stmgcn_tpu.ops.spmm import stack_from_dense
        from stmgcn_tpu.train.trainer import _contains_blocksparse

        bss = stack_from_dense(make_supports())
        assert _contains_blocksparse((bss,))
        assert not _contains_blocksparse((sharded_from_dense(make_supports(), 4),))

    def test_placement_puts_sharded_sparse(self, mesh):
        pl = MeshPlacement(mesh)
        ssp = sharded_from_dense(make_supports(), 4)
        placed = pl.put((ssp,), "supports")[0]
        assert placed.data.sharding.spec[0] == "region"
        assert placed.idx_t.sharding.spec[0] == "region"
        assert placed.n == ssp.n
