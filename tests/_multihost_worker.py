"""Worker for the 2-process multi-host tests (run as a subprocess).

Not a pytest module (underscore prefix): ``tests/test_multihost.py``
launches two copies of this script, each joining a real
``jax.distributed`` job over local gloo collectives, to execute the
code paths that only exist when ``jax.process_count() > 1``:

- ``Trainer._load_state``'s lead-read + broadcast restore (and its
  error-in-payload path, where a lead-side failure must raise on every
  process instead of leaving peers blocked in the collective),
- the CLI export-status broadcast (every host exits nonzero when the
  lead's export fails).

Each process gets its own ``out_dir`` and only process 0's contains a
checkpoint — a non-lead process can therefore produce the checkpoint's
parameter digest only by actually receiving the broadcast.

Usage: python _multihost_worker.py <scenario> <proc_id> <port> <out_dir>
       [export_path]
Scenarios: restore | cli_export
"""

import hashlib
import json
import os
import sys


def params_digest(params) -> str:
    """Order-stable sha256 over every array leaf in the params pytree."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for path, leaf in sorted(
        jax.tree_util.tree_flatten_with_path(params)[0], key=lambda kv: str(kv[0])
    ):
        h.update(str(path).encode())
        h.update(np.ascontiguousarray(np.asarray(leaf, np.float32)).tobytes())
    return h.hexdigest()


def worker_config(out_dir: str):
    """The tiny training config shared by the parent test and both workers
    (shapes must match for the broadcast state to be restorable)."""
    from stmgcn_tpu.config import preset

    cfg = preset("smoke")
    cfg.data.rows = 4
    cfg.data.n_timesteps = 24 * 7 * 2 + 24
    cfg.train.epochs = 2
    cfg.train.out_dir = out_dir
    return cfg


def _init(proc_id: int, port: str) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from stmgcn_tpu.utils import force_host_platform

    force_host_platform("cpu")
    import jax

    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        f"localhost:{port}", num_processes=2, process_id=proc_id
    )


def scenario_restore(proc_id: int, out_dir: str) -> None:
    import jax

    from stmgcn_tpu.experiment import build_trainer

    trainer = build_trainer(worker_config(out_dir), verbose=False)
    assert jax.process_count() == 2, "distributed init did not take"
    meta = trainer.restore(os.path.join(out_dir, "best.ckpt"))
    print(
        "RESULT "
        + json.dumps(
            {
                "proc": proc_id,
                "epoch": meta["epoch"],
                "best_val": meta["best_val"],
                "digest": params_digest(trainer.params),
            }
        ),
        flush=True,
    )

    # Error-in-payload: the lead fails to read (no such file) and every
    # process must raise together — a hang here means the lead bailed
    # before the collective and left the peer blocked in it.
    try:
        trainer.restore(os.path.join(out_dir, "missing.ckpt"))
        print("ERRORPATH missing-raise", flush=True)
    except RuntimeError as e:
        ok = "lead process failed to load" in str(e)
        print(f"ERRORPATH {'ok' if ok else f'wrong-message: {e}'}", flush=True)


def scenario_cli_export(proc_id: int, out_dir: str, export_path: str) -> None:
    from stmgcn_tpu.cli import main

    cfg = worker_config(out_dir)
    rc = main(
        [
            "--preset", "smoke",
            "--rows", str(cfg.data.rows),
            "--timesteps", str(cfg.data.n_timesteps),
            "--epochs", str(cfg.train.epochs),
            "--out-dir", out_dir,
            "--test-only",
            "--export", export_path,
        ]
    )
    print(f"CLIRC {rc}", flush=True)


def main_() -> None:
    scenario, proc_id, port, out_dir = sys.argv[1:5]
    _init(int(proc_id), port)
    if scenario == "restore":
        scenario_restore(int(proc_id), out_dir)
    elif scenario == "cli_export":
        scenario_cli_export(int(proc_id), out_dir, sys.argv[5])
    else:
        raise SystemExit(f"unknown scenario {scenario}")


if __name__ == "__main__":
    main_()
