"""Federation-tier contracts: hash ring, global budget, scatter/gather,
lifecycle, and the tier promotion gate.

Engine-free like the admission units — the router's contracts (ring
determinism, minimal-movement re-shard, per-city typed partial-failure
outcomes, single-generation gathers, bounded drains) are routing-layer
properties, so fake replicas pin them fast and deterministically; the
real M-replica engines are exercised by the slow-tier soak contract
test at the bottom, which runs ``serve-bench --soak --federation`` as a
subprocess and asserts the one-JSON-line stdout record the lint gate
and README numbers come from.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from stmgcn_tpu.config import FederationConfig, ServingConfig
from stmgcn_tpu.resilience import FederationFaultPlan, FederationFaultSpec
from stmgcn_tpu.serving import (
    AdmissionController,
    CityOutcome,
    FederationRouter,
    GlobalBudget,
    HashRing,
    Overloaded,
    ReplicaUnavailable,
    ShedError,
    TierPromotionGate,
    ring_hash,
)
from stmgcn_tpu.serving.metrics import EngineStats


# ---------------------------------------------------------------------------
# fakes: the router only needs predict/close/generation/drift_snapshot


class FakeWatcher:
    """Stands in for CheckpointWatcher in tier-gate tests: poll() applies
    'the new checkpoint' by bumping its engine's generation."""

    def __init__(self, engine, fail=False):
        self._engine = engine
        self.fail = fail
        self.polls = 0
        self.stopped = False

    def poll(self):
        self.polls += 1
        if self.fail:
            return False
        self._engine.generation += 1
        return True

    def stop(self, timeout_s=None):
        self.stopped = True
        return True


class FakeEngine:
    """A replica double: serves any city, typed-raises on demand, and
    carries the generation/watcher surface the router + tier gate use."""

    def __init__(self, *, shed_cities=(), delay_s=0.0, watcher_fails=False):
        self.generation = 0
        self.shed_cities = set(shed_cities)
        self.delay_s = delay_s
        self.watcher_fails = watcher_fails
        self.closed = False
        self.calls = []
        self._params_template = None
        self._watcher = None

    def predict(self, history, *, city, with_generation=False):
        if self.delay_s:
            time.sleep(self.delay_s)
        if city in self.shed_cities:
            raise Overloaded(f"fake shed for city {city}")
        self.calls.append(city)
        out = np.full((1, 2), float(city), np.float32)
        return (out, self.generation) if with_generation else out

    def drift_snapshot(self):
        return {"cities": {"0": {"input": {"z_max": 0.5 + self.generation,
                                           "psi": 0.1}}}}

    def watch_checkpoints(self, out_dir, **kwargs):
        self._watcher = FakeWatcher(self, fail=self.watcher_fails)
        return self._watcher

    def close(self):
        self.closed = True


def make_router(n_replicas=3, n_cities=9, *, spares=0, fault_plan=None,
                engine_factory=FakeEngine, budget=None):
    engines = [engine_factory() for _ in range(n_replicas)]
    spare_engines = [engine_factory() for _ in range(spares)]
    cfg = FederationConfig(enabled=True, replicas=n_replicas, spares=spares)
    router = FederationRouter(
        engines, range(n_cities), config=cfg, spare_engines=spare_engines,
        global_budget=budget, fault_plan=fault_plan,
    )
    return router, engines, spare_engines


HIST = np.zeros((1, 3), np.float32)


# ---------------------------------------------------------------------------


class TestHashRing:
    def test_ring_hash_is_process_salt_free(self):
        # Python's builtin hash() is salted per process; the ring hash
        # must not be — replica layouts have to agree across runs/hosts
        assert ring_hash("city:0") == ring_hash("city:0")
        assert ring_hash("city:0") != ring_hash("city:1")
        # pinned: a changed hash silently re-shards every deployment
        assert ring_hash("replica:0#0") == 0xC92D06DA2EFA9FE3

    def test_owner_deterministic_and_total(self):
        ring = HashRing([0, 1, 2], vnodes=64)
        a = ring.assignment(range(50))
        b = HashRing([2, 1, 0], vnodes=64).assignment(range(50))
        assert a == b  # membership order must not matter
        assert set(a) == set(range(50))
        assert set(a.values()) <= {0, 1, 2}

    def test_removal_moves_only_the_removed_replicas_cities(self):
        cities = range(64)
        before = HashRing([0, 1, 2], vnodes=64).assignment(cities)
        after = HashRing([0, 2], vnodes=64).assignment(cities)
        for c in cities:
            if before[c] != 1:
                # consistent hashing's whole point: survivors keep theirs
                assert after[c] == before[c]
            else:
                assert after[c] in (0, 2)

    def test_addition_only_steals(self):
        cities = range(64)
        before = HashRing([0, 1], vnodes=64).assignment(cities)
        after = HashRing([0, 1, 2], vnodes=64).assignment(cities)
        for c in cities:
            assert after[c] == before[c] or after[c] == 2

    def test_validation(self):
        with pytest.raises(ValueError):
            HashRing([], vnodes=4)
        with pytest.raises(ValueError):
            HashRing([0], vnodes=0)

    def test_imbalance_zero_for_single_replica(self):
        assert HashRing([7], vnodes=4).imbalance(range(10)) == 0.0
        assert HashRing([0, 1], vnodes=64).imbalance([]) == 0.0


class TestGlobalBudget:
    def test_draw_release_refuse(self):
        b = GlobalBudget(10)
        assert b.try_draw(6) and b.try_draw(4)
        assert not b.try_draw(1)
        b.release(4)
        assert b.try_draw(3)
        snap = b.snapshot()
        assert snap == {"total_rows": 10, "outstanding": 9, "peak": 10,
                        "refused": 1}

    def test_double_release_cannot_manufacture_budget(self):
        b = GlobalBudget(4)
        assert b.try_draw(4)
        b.release(4)
        b.release(4)  # double pay-back: clamped, not banked
        assert b.snapshot()["outstanding"] == 0
        assert b.try_draw(4)
        assert not b.try_draw(1)

    def test_validation(self):
        with pytest.raises(ValueError):
            GlobalBudget(0)

    def test_concurrent_accounting_is_exact(self):
        b = GlobalBudget(8)
        held = []
        lock = threading.Lock()
        refused = [0]

        def worker():
            for _ in range(200):
                if b.try_draw(1):
                    with lock:
                        held.append(1)
                    b.release(1)
                    with lock:
                        held.pop()
                else:
                    with lock:
                        refused[0] += 1

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert not any(t.is_alive() for t in threads)
        snap = b.snapshot()
        assert snap["outstanding"] == 0  # everything paid back
        assert snap["peak"] <= 8  # the invariant the budget exists for

    def test_admission_sheds_tier_overloaded_after_local_checks(self):
        cfg = ServingConfig(buckets=(1, 4), max_batch=4,
                            queue_bound_rows=100)
        stats = EngineStats()
        budget = GlobalBudget(4)
        ctl = AdmissionController(cfg, stats, (1, 4), global_budget=budget)
        ctl.admit(4, 0)  # locally fine, draws the whole tier budget
        with pytest.raises(Overloaded, match="tier-wide"):
            ctl.admit(1, 4)
        assert stats.shed_counts().get("tier-overloaded") == 1
        # a locally-shed request must never have drawn tier budget
        with pytest.raises(Overloaded, match="queue holds"):
            ctl.admit(200, 0)
        assert budget.snapshot()["outstanding"] == 4
        ctl.release_rows(4)
        assert budget.snapshot()["outstanding"] == 0


class TestFederationRouter:
    def test_predict_routes_to_ring_owner(self):
        router, engines, _ = make_router()
        try:
            for c in range(9):
                out = router.predict(HIST, city=c)
                assert float(out[0, 0]) == float(c)
                rid = router.replica_for(c)
                assert c in engines[rid].calls
        finally:
            router.close()

    def test_predict_unknown_city_raises(self):
        router, _, _ = make_router(n_cities=4)
        try:
            with pytest.raises(ValueError, match="city must be one of"):
                router.predict(HIST, city=99)
        finally:
            router.close()

    def test_predict_many_single_generation_all_ok(self):
        router, _, _ = make_router()
        try:
            outcomes = router.predict_many({c: HIST for c in range(9)})
            assert set(outcomes) == set(range(9))
            assert all(o.ok for o in outcomes.values())
            assert {o.generation for o in outcomes.values()} == {0}
        finally:
            router.close()

    def test_partial_failure_is_typed_per_city(self):
        # one replica sheds its cities: those cities come back with their
        # own typed error; sibling cities are unaffected — and the caller
        # is never handed an exception or a hang, only outcomes
        router, engines, _ = make_router(n_replicas=3, n_cities=12)
        try:
            victim = router.replica_for(0)
            engines[victim].shed_cities = set(range(12))
            outcomes = router.predict_many({c: HIST for c in range(12)})
            for c, o in outcomes.items():
                if router.replica_for(c) == victim:
                    assert not o.ok
                    assert isinstance(o.error, Overloaded)
                    assert o.replica == victim
                else:
                    assert o.ok
        finally:
            router.close()

    def test_kill_heals_ring_and_keeps_every_city_served(self):
        router, engines, _ = make_router(n_replicas=3, n_cities=12)
        try:
            before = router.assignment()
            victim = before[0]
            owned = [c for c, r in before.items() if r == victim]
            router.kill(victim)
            after = router.assignment()
            assert victim not in after.values()
            # minimal movement: only the dead replica's cities moved
            for c, r in before.items():
                if r != victim:
                    assert after[c] == r
            assert router.cities_moved == len(owned)
            for c in range(12):
                assert router.predict(HIST, city=c) is not None
            deadline = time.monotonic() + 5.0
            while not engines[victim].closed and time.monotonic() < deadline:
                time.sleep(0.01)  # the reaper closes off the scatter path
            assert engines[victim].closed
        finally:
            router.close()

    def test_fault_plan_kill_at_scatter_never_hangs_a_caller(self):
        plan = FederationFaultPlan(
            FederationFaultSpec(kind="replica-kill", replica=0, dispatch=0)
        )
        router, engines, _ = make_router(n_replicas=3, n_cities=12,
                                         fault_plan=plan)
        try:
            outcomes = router.predict_many({c: HIST for c in range(12)})
            assert set(outcomes) == set(range(12))
            # every city answered or failed typed — none missing, none hung
            for o in outcomes.values():
                assert o.ok or isinstance(o.error, ShedError)
            assert router.kills == 1
            assert 0 not in router.assignment().values()
            # the plan is one-shot: the next scatter kills nobody
            router.predict_many({0: HIST})
            assert router.kills == 1
        finally:
            router.close()

    def test_generation_split_never_yields_mixed_success(self):
        router, engines, _ = make_router(n_replicas=2, n_cities=8)
        try:
            laggard = router.replica_for(0)
            for i, e in enumerate(engines):
                if i != laggard:
                    e.generation = 1  # the tier cut over; one replica lags
            outcomes = router.predict_many({c: HIST for c in range(8)})
            ok_gens = {o.generation for o in outcomes.values() if o.ok}
            assert len(ok_gens) == 1  # the tier contract
            for c, o in outcomes.items():
                if router.replica_for(c) == laggard:
                    assert not o.ok
                    assert isinstance(o.error, ReplicaUnavailable)
            assert router.generation_retries > 0
        finally:
            router.close()

    def test_drain_flushes_and_reassigns(self):
        plan = FederationFaultPlan(
            FederationFaultSpec(kind="hang-on-drain", replica=1, hang_ms=30.0)
        )
        router, engines, _ = make_router(n_replicas=3, n_cities=12,
                                         fault_plan=plan)
        try:
            owned = [c for c, r in router.assignment().items() if r == 1]
            t0 = time.perf_counter()
            report = router.drain(1)
            elapsed_s = time.perf_counter() - t0
            assert report["flushed"] is True
            assert report["moved_cities"] == len(owned)
            assert report["watcher_wedged"] is False
            # the injected 30 ms hang is *bounded* by the drain window
            assert elapsed_s < router.config.drain_timeout_s + 1.0
            assert 1 not in router.assignment().values()
            for c in range(12):
                router.predict(HIST, city=c)
        finally:
            router.close()

    def test_promote_spare_joins_ring_with_bounded_handover(self):
        router, engines, spares = make_router(n_replicas=2, n_cities=8,
                                              spares=1)
        try:
            spare_rid = 2
            with pytest.raises(ValueError, match="not a spare"):
                router.promote_spare(0)
            report = router.promote_spare(spare_rid)
            assert report["promoted"] == spare_rid
            assert report["handover_flushed"] is True
            assert spare_rid in router.assignment().values()
            # addition only steals: no city moved between the survivors
            assert report["moved_cities"] == sum(
                1 for r in router.assignment().values() if r == spare_rid
            )
        finally:
            router.close()

    def test_concurrent_scatters_account_globally(self):
        budget = GlobalBudget(1000)
        router, engines, _ = make_router(n_replicas=3, n_cities=9,
                                         budget=budget)
        try:
            errs = []

            def caller():
                try:
                    outs = router.predict_many({c: HIST for c in range(9)})
                    assert all(o.ok for o in outs.values())
                except Exception as e:  # surfaced below, not swallowed
                    errs.append(e)

            threads = [threading.Thread(target=caller) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
            assert not any(t.is_alive() for t in threads)
            assert errs == []
            assert router.health()["scatters"] == 8
        finally:
            router.close()

    def test_drift_rollup_labels_replicas_and_takes_fleet_max(self):
        router, engines, _ = make_router(n_replicas=2, n_cities=4)
        try:
            engines[1].generation = 2  # fake drift scales with generation
            roll = router.drift_rollup()
            assert set(roll["replicas"]) == {"0", "1"}
            assert roll["fleet"]["z_max"] == max(
                v["z_max"] for v in roll["replicas"].values()
            )
        finally:
            router.close()

    def test_close_is_idempotent_and_closes_all(self):
        router, engines, spares = make_router(n_replicas=2, n_cities=4,
                                              spares=1)
        router.close()
        router.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not all(
            e.closed for e in engines + spares
        ):
            time.sleep(0.01)
        assert all(e.closed for e in engines + spares)


class TestFederationFaultPlan:
    def test_spec_validation(self):
        with pytest.raises(ValueError, match="kind"):
            FederationFaultSpec(kind="nope")
        with pytest.raises(ValueError, match="replica-kill"):
            FederationFaultSpec(kind="replica-kill", replica=1)
        with pytest.raises(ValueError, match="hang_ms"):
            FederationFaultSpec(kind="hang-on-drain", replica=1)
        with pytest.raises(ValueError, match="herd-spike"):
            FederationFaultSpec(kind="herd-spike", city=0, dispatch=1)

    def test_empty_plan_short_circuits(self):
        plan = FederationFaultPlan()
        assert not plan.active
        assert plan.kill_at_scatter(0) is None
        assert plan.herd_burst(0) == []
        assert plan.poison_candidate("/nonexistent/candidate-0.ckpt") is False

    def test_herd_burst_is_one_shot(self):
        plan = FederationFaultPlan(
            FederationFaultSpec(kind="herd-spike", city=3, dispatch=5,
                                burst=10)
        )
        assert plan.herd_burst(4) == []
        assert plan.herd_burst(5) == [(3, 10)]
        assert plan.herd_burst(5) == []

    def test_poison_flips_one_byte_once(self, tmp_path):
        p = tmp_path / "candidate-0.ckpt"
        p.write_bytes(b"abcdef")
        plan = FederationFaultPlan(
            FederationFaultSpec(kind="poisoned-candidate")
        )
        assert plan.poison_candidate(str(p)) is True
        assert p.read_bytes() != b"abcdef"
        assert plan.poison_candidate(str(p)) is False  # one-shot
        other = tmp_path / "best.ckpt"
        other.write_bytes(b"abcdef")
        plan2 = FederationFaultPlan(
            FederationFaultSpec(kind="poisoned-candidate")
        )
        assert plan2.poison_candidate(str(other)) is False  # glob mismatch


class TestTierPromotionGate:
    """Quarantine-once / cutover-everywhere, on fake watchers + real
    candidate files (the integrity check reads real bytes)."""

    def _gate(self, tmp_path, n_replicas=3, watcher_fails_on=(),
              fault_plan=None):
        engines = [
            FakeEngine(watcher_fails=(i in watcher_fails_on))
            for i in range(n_replicas)
        ]
        cfg = FederationConfig(enabled=True, replicas=n_replicas)
        router = FederationRouter(
            engines, range(2 * n_replicas), config=cfg, fault_plan=fault_plan,
        )
        gate = TierPromotionGate(router, str(tmp_path / "watch"))
        return gate, router, engines

    def _candidate(self, tmp_path, name="candidate-0.ckpt"):
        from stmgcn_tpu.train.checkpoint import save_checkpoint

        path = str(tmp_path / name)
        save_checkpoint(path, {"w": np.ones((2,), np.float32)}, {}, {})
        return path

    CLEAN = {"nonfinite": 0, "grad_norm_max": 1.0, "update_ratio_max": 0.01}

    def test_promotion_cuts_over_every_replica_once(self, tmp_path):
        gate, router, engines = self._gate(tmp_path)
        try:
            path = self._candidate(tmp_path)
            decision = gate.consider(path, self.CLEAN)
            assert decision.accepted and decision.reason == "promoted"
            assert [e.generation for e in engines] == [1, 1, 1]
            assert [w.polls for w in gate.watchers.values()] == [1, 1, 1]
            assert decision.checks["tier"]["swapped"] == [0, 1, 2]
            assert os.path.exists(os.path.join(gate.out_dir, "latest.ckpt"))
        finally:
            router.close()

    def test_poisoned_candidate_quarantined_once_not_m_times(self, tmp_path):
        plan = FederationFaultPlan(
            FederationFaultSpec(kind="poisoned-candidate")
        )
        gate, router, engines = self._gate(tmp_path, fault_plan=plan)
        try:
            path = self._candidate(tmp_path)
            decision = gate.consider(path, self.CLEAN)
            assert not decision.accepted
            assert decision.reason == "corrupt"
            # ONE quarantine for the tier: one rename, one count, and no
            # replica ever saw the candidate
            assert gate.rejections == 1
            assert decision.path.endswith(".rejected-corrupt")
            assert not os.path.exists(path)
            assert [e.generation for e in engines] == [0, 0, 0]
            assert [w.polls for w in gate.watchers.values()] == [0, 0, 0]
        finally:
            router.close()

    def test_failed_cutover_detaches_replica_from_ring(self, tmp_path):
        gate, router, engines = self._gate(tmp_path, watcher_fails_on={1})
        try:
            path = self._candidate(tmp_path)
            decision = gate.consider(path, self.CLEAN)
            assert decision.accepted
            assert decision.checks["tier"]["failed"] == [1]
            assert gate.detached == [1]
            # the laggard left the ring: the active set stays generation-
            # consistent and its cities re-homed to cut-over replicas
            assert 1 not in router.assignment().values()
            gens = {
                e.generation for i, e in enumerate(engines) if i != 1
            }
            assert gens == {1}
        finally:
            router.close()


class TestFederationConfigViolations:
    """Boundary pins live in tests/test_analysis.py with the other
    contract rules; here only the dataclass plumbing the router uses."""

    def test_router_rejects_invalid_config(self):
        cfg = FederationConfig(enabled=True, replicas=2,
                               drain_timeout_s=1.0, handover_timeout_s=9.0)
        with pytest.raises(ValueError, match="invalid federation config"):
            FederationRouter([FakeEngine(), FakeEngine()], range(4),
                             config=cfg)


# ---------------------------------------------------------------------------
# slow tier: the real M-replica soak through the CLI, one JSON line out


CLEAN_ENV = {
    k: v for k, v in os.environ.items() if not k.startswith("STMGCN_")
}


@pytest.mark.slow
class TestFederationSoakContract:
    def test_serve_bench_federation_record_contract(self, tmp_path):
        env = dict(
            CLEAN_ENV, JAX_PLATFORMS="cpu",
            STMGCN_BENCH_LOCK_PATH=str(tmp_path / "bench.lock"),
        )
        proc = subprocess.run(
            [
                sys.executable, "-m", "stmgcn_tpu.cli", "serve-bench",
                "--rows", "3", "--batch", "4", "--buckets", "1,2,4",
                "--clients", "4", "--per-client", "4", "--iters", "5",
                "--warmup", "1", "--no-fleet", "--soak",
                "--soak-seconds", "1.0", "--soak-overload", "2.0",
                "--federation", "3",
            ],
            capture_output=True, text=True, timeout=1200, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-4000:]
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, f"stdout must be ONE json line: {lines}"
        record = json.loads(lines[0])

        fed = record["federation"]
        assert fed["config"]["replicas"] == 3
        assert fed["config"]["cities"] >= fed["config"]["replicas"]
        assert fed["config_findings"] == []

        # the never-hang / never-mix tier contract, under real load
        soak = fed["soak"]
        assert soak["hung_clients"] == 0
        assert soak["cross_generation"] == 0
        assert soak["outcomes"]["ok"] > 0

        # capacity is measured, not asserted: the record must carry the
        # provenance to judge it (core count, host contention)
        assert fed["capacity"]["tier_rps"] > 0
        assert fed["capacity"]["n_cores"] >= 1
        assert isinstance(fed["contended"], bool)

        drills = fed["drills"]
        assert drills["tier_rejection"]["reason"] == "corrupt"
        assert drills["tier_rejection"]["rejections_counted"] == 1
        assert drills["tier_rejection"]["generations_untouched"] is True
        assert drills["replica_kill"]["kills"] == 1
        assert drills["replica_kill"]["cities_moved"] >= 1
        assert drills["herd"]["extra_ok"] + drills["herd"]["extra_shed"] > 0
        assert drills["drain"]["flushed"] is True
        assert drills["drain"]["watcher_wedged"] is False
        assert drills["reshard_promote"]["handover_flushed"] is True
        assert drills["reshard_promote"]["burst_cross_generation"] == 0

        promo = fed["promotion"]
        assert promo["mid_soak"]["accepted"] is True
        gens = set(promo["generations_after"].values())
        assert gens == {1}  # every live replica on the promoted generation

        rec = fed["recovery"]
        assert rec["cities_serveable"] == rec["cities_total"]
        assert fed["budget"]["outstanding"] == 0
