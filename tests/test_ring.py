"""Device-resident ingest ring tests: wraparound, numpy-oracle bit
parity through the superstep gather, anomaly handling (gap /
out-of-order / duplicate / nonfinite / stale reject), mid-ingest
SIGTERM consistency, and the zero-recompiles-after-warmup property of
the jitted ingest program.
"""

import signal

import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.data import (
    SeriesRing,
    StaleObservationError,
    WindowSpec,
    ingest_stream,
)
from stmgcn_tpu.obs.registry import MetricsRegistry
from stmgcn_tpu.resilience import IngestFaultPlan, IngestFaultSpec
from stmgcn_tpu.train.step import gather_window_batch


def _series(T, N=4, C=2, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(T, N, C)).astype(np.float32)


class OracleRing:
    """Pure-host mirror of the documented ingest semantics, kept as a
    growing list (no wraparound mechanics at all) — the ring's
    :meth:`series` must equal the oracle's tail bit-for-bit."""

    def __init__(self, start_ts, reorder_window):
        self.start_ts = start_ts
        self.reorder_window = reorder_window
        self.rows: list[np.ndarray] = []

    def ingest(self, ts, row):
        row = np.asarray(row, np.float32)
        nxt = self.start_ts + len(self.rows)
        if not np.isfinite(row).all():
            if ts >= nxt:
                fill = self.rows[-1] if self.rows else np.zeros_like(row)
                self.rows.extend([fill] * (ts + 1 - nxt))
            return
        if ts >= nxt:
            fill = self.rows[-1] if self.rows else np.zeros_like(row)
            self.rows.extend([fill] * (ts - nxt))
            self.rows.append(row)
        elif nxt - ts <= self.reorder_window:
            self.rows[ts - self.start_ts] = row

    def tail(self, n):
        return np.stack(self.rows[-n:])


class TestRingBasics:
    def test_wraparound_at_exact_capacity(self):
        full = _series(12)
        ring = SeriesRing(12, 4, 2, start_ts=0, registry=MetricsRegistry())
        for t in range(12):
            ring.ingest(t, full[t])
        assert len(ring) == 12
        np.testing.assert_array_equal(np.asarray(ring.series()), full)
        # one more row wraps: slot 0 is overwritten, view shifts by one
        extra = _series(1, seed=9)[0]
        ring.ingest(12, extra)
        assert len(ring) == 12 and ring.origin_ts == 1
        expect = np.concatenate([full[1:], extra[None]])
        np.testing.assert_array_equal(np.asarray(ring.series()), expect)

    def test_from_series_parity_and_tail(self):
        full = _series(20)
        reg = MetricsRegistry()
        ring = SeriesRing.from_series(full, start_ts=7, registry=reg)
        np.testing.assert_array_equal(np.asarray(ring.series()), full)
        assert ring.origin_ts == 7 and ring.next_ts == 27
        small = SeriesRing.from_series(full, start_ts=7, capacity=6,
                                       registry=MetricsRegistry())
        np.testing.assert_array_equal(np.asarray(small.series()), full[-6:])
        # a pre-filled ring keeps ingesting exactly like a live one
        more = _series(3, seed=5)
        for i in range(3):
            small.ingest(27 + i, more[i])
        np.testing.assert_array_equal(
            np.asarray(small.series()),
            np.concatenate([full, more])[-6:],
        )

    def test_series_last_k_and_occupancy(self):
        full = _series(10)
        reg = MetricsRegistry()
        ring = SeriesRing(16, 4, 2, start_ts=0, registry=reg)
        for t in range(10):
            ring.ingest(t, full[t])
        np.testing.assert_array_equal(np.asarray(ring.series(last=4)), full[-4:])
        assert reg.gauge("ring.occupancy", {"city": "0"}).value == 10 / 16
        assert reg.counter("ingest.rows", {"city": "0"}).value == 10


class TestOracleParity:
    def test_messy_feed_matches_oracle_and_gather(self):
        """A feed with gaps, bounded reordering, duplicates, and a
        nonfinite row must land bit-identical to the host oracle, and
        the superstep gather over the ring must equal the same gather
        over the oracle series."""
        full = _series(60, seed=3)
        cap, win = 24, 3
        ring = SeriesRing(cap, 4, 2, start_ts=0, reorder_window=win,
                          registry=MetricsRegistry())
        oracle = OracleRing(0, win)
        events = []
        t = 0
        while t < 60:
            if t == 10:          # gap: skip two timestamps
                t += 2
            if t == 20:          # swap within the reorder window
                events += [(21, full[21]), (20, full[20])]
                t = 22
                continue
            if t == 30:          # duplicate delivery
                events += [(30, full[30]), (30, full[30])]
                t = 31
                continue
            if t == 40:          # nonfinite observation
                bad = full[40].copy()
                bad[0, 0] = np.inf
                events.append((40, bad))
                t = 41
                continue
            events.append((t, full[t]))
            t += 1
        for ts, row in events:
            ring.ingest(ts, row)
            oracle.ingest(ts, row)
        got = np.asarray(ring.series())
        np.testing.assert_array_equal(got, oracle.tail(cap))
        # gaps: two skipped timestamps at t=10, plus the slot the
        # out-of-order pair forward-filled before its late half arrived
        assert ring.gaps == 3 and ring.out_of_order == 1
        assert ring.duplicates == 1 and ring.nonfinite == 1

        spec = WindowSpec(serial_len=3, daily_len=1, weekly_len=0,
                          day_timesteps=4, horizon=1)
        targets = ring.target_indices(spec)
        offsets = jnp.asarray(spec.offsets)
        idx = jnp.arange(targets.shape[0])
        x, y = gather_window_batch(ring.series(), jnp.asarray(targets),
                                   offsets, idx)
        ref = oracle.tail(cap)
        np.testing.assert_array_equal(
            np.asarray(x), ref[targets[:, None] + spec.offsets[None, :]])
        np.testing.assert_array_equal(np.asarray(y), ref[targets])

    def test_gap_forward_fill_is_deterministic(self):
        full = _series(16, seed=4)
        feeds = []
        for _ in range(2):
            ring = SeriesRing(16, 4, 2, start_ts=0,
                              registry=MetricsRegistry())
            for ts in (0, 1, 5, 6, 11):
                ring.ingest(ts, full[ts])
            feeds.append(np.asarray(ring.series()))
        np.testing.assert_array_equal(feeds[0], feeds[1])
        # fills repeat the last real row, bit-exactly
        np.testing.assert_array_equal(feeds[0][2], full[1])
        np.testing.assert_array_equal(feeds[0][4], full[1])
        np.testing.assert_array_equal(feeds[0][7], full[6])

    def test_gap_larger_than_capacity(self):
        full = _series(4)
        ring = SeriesRing(4, 4, 2, start_ts=0, reorder_window=2,
                          registry=MetricsRegistry())
        ring.ingest(0, full[0])
        ring.ingest(100, full[1])  # 99 missing rows, only 4 slots resident
        assert len(ring) == 4 and ring.next_ts == 101
        got = np.asarray(ring.series())
        np.testing.assert_array_equal(got[:3], np.broadcast_to(full[0], (3, 4, 2)))
        np.testing.assert_array_equal(got[3], full[1])
        assert ring.gaps == 99


class TestAnomalies:
    def test_timestamp_regression_rejected(self):
        full = _series(10)
        ring = SeriesRing(8, 4, 2, start_ts=0, reorder_window=2,
                          registry=MetricsRegistry())
        for t in range(8):
            ring.ingest(t, full[t])
        with pytest.raises(StaleObservationError):
            ring.ingest(3, full[3])  # 5 behind, window is 2
        with pytest.raises(StaleObservationError):
            ring.ingest(-1, full[0])  # before the ring's first timestamp
        # the reject changed nothing
        np.testing.assert_array_equal(np.asarray(ring.series()), full[:8])

    def test_nonfinite_quarantined_and_counted(self):
        full = _series(6)
        reg = MetricsRegistry()
        ring = SeriesRing(8, 4, 2, start_ts=0, registry=reg)
        ring.ingest(0, full[0])
        bad = full[1].copy()
        bad[1, 0] = np.nan
        assert ring.ingest(1, bad) == "nonfinite"
        ring.ingest(2, full[2])
        got = np.asarray(ring.series())
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got[1], full[0])  # forward-filled
        assert ring.quarantined == [(1, "nonfinite")]
        assert reg.counter("ingest.nonfinite", {"city": "0"}).value == 1

    def test_ingest_stream_counts_rejects(self):
        full = _series(10)
        ring = SeriesRing(8, 4, 2, start_ts=0, reorder_window=1,
                          registry=MetricsRegistry())
        rows = [(t, full[t]) for t in range(8)] + [(2, full[2])]
        summary = ingest_stream(ring, rows)
        assert summary == {"fed": 9, "accepted": 8, "rejected": 1}


class TestSigterm:
    def test_mid_ingest_sigterm_leaves_ring_consistent(self):
        """SIGTERM delivered mid-stream (by the ingest fault plan) must
        leave every committed row fully written and the bookkeeping
        matching the device state — and the feed must be resumable to a
        state bit-identical to an uninterrupted one."""

        class _Term(Exception):
            pass

        def _handler(signum, frame):
            raise _Term

        full = _series(12)
        ring = SeriesRing(8, 4, 2, start_ts=0, registry=MetricsRegistry())
        plan = IngestFaultPlan([IngestFaultSpec(kind="sigterm", row=5)])
        rows = [(t, full[t]) for t in range(12)]
        old = signal.signal(signal.SIGTERM, _handler)
        try:
            with pytest.raises(_Term):
                ingest_stream(ring, rows, plan)
        finally:
            signal.signal(signal.SIGTERM, old)
        # rows 0-4 committed; row 5 (in flight) is not visible anywhere
        assert ring.count == 5 and len(ring) == 5
        np.testing.assert_array_equal(np.asarray(ring.series()), full[:5])
        # resuming the feed converges to the uninterrupted result
        ingest_stream(ring, rows[5:], plan)
        np.testing.assert_array_equal(np.asarray(ring.series()), full[-8:])


class TestZeroRecompiles:
    def test_ingest_adds_zero_compiles_after_warmup(self):
        from stmgcn_tpu.obs import jaxmon

        if not jaxmon.install():
            pytest.skip("jax.monitoring unavailable")
        full = _series(20, seed=8)
        ring = SeriesRing(6, 4, 2, start_ts=0, reorder_window=2,
                          registry=MetricsRegistry())
        ring.ingest(0, full[0])   # warmup: traces the ingest program
        ring.series()             # and the (unwrapped) view slice
        compiles = jaxmon.REGISTRY.counter("jax.compilations")
        baseline = compiles.value
        for t in range(1, 15):    # wraps the ring twice over
            ring.ingest(t, full[t])
        ring.ingest(13, full[13])  # late path reuses the same program
        assert compiles.value == baseline
