"""Model parity tests (SURVEY.md §4): flax modules vs a plain-numpy oracle.

The oracle below independently implements the paper equations (eqs. 6-9,
K-support convolution, stacked LSTM cell) with explicit loops, consuming the
*same* parameter values extracted from the flax param tree — so any
disagreement is a math bug, not an init difference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.models import CGLSTM, STMGCN
from stmgcn_tpu.ops.chebconv import ChebGraphConv
from stmgcn_tpu.ops.lstm import StackedLSTM


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def oracle_chebconv(supports, x, w, b, relu=True):
    """K-loop + concat, the reference's op order (GCN.py:33-42)."""
    parts = [np.einsum("ij,bjf->bif", supports[k], x) for k in range(supports.shape[0])]
    out = np.concatenate(parts, axis=-1) @ w
    if b is not None:
        out = out + b
    return np.maximum(out, 0.0) if relu else out


def oracle_lstm(x, layer_params):
    """Per-timestep loop; gates split (i, f, g, o) like torch's cell."""
    for wx, wh, b in layer_params:
        B, T, _ = x.shape
        H = wh.shape[0]
        h = np.zeros((B, H))
        c = np.zeros((B, H))
        outs = []
        for t in range(T):
            gates = x[:, t] @ wx + h @ wh + b
            i, f, g, o = np.split(gates, 4, axis=-1)
            c = sigmoid(f) * c + sigmoid(i) * np.tanh(g)
            h = sigmoid(o) * np.tanh(c)
            outs.append(h)
        x = np.stack(outs, axis=1)
    return x


def oracle_branch(supports, obs, p):
    """CG_LSTM + GCN for one graph: eqs. 6-9 then shared LSTM then gconv."""
    B, T, N, C = obs.shape
    x_nt = obs.sum(-1).transpose(0, 2, 1)  # (B, N, T)
    gate = p["cg_lstm"]["gate"]
    g = oracle_chebconv(supports, x_nt, gate["temporal_gconv"]["W"], gate["temporal_gconv"]["b"])
    z = (x_nt + g).mean(axis=1)  # eqs. 6-7
    fc_k, fc_b = gate["gate_fc"]["kernel"], gate["gate_fc"]["bias"]
    s = sigmoid(np.maximum(z @ fc_k + fc_b, 0.0) @ fc_k + fc_b)  # eq. 8, shared fc
    ow = obs * s[:, :, None, None]  # eq. 9
    folded = ow.transpose(0, 2, 1, 3).reshape(B * N, T, C)
    lstm = p["cg_lstm"]["lstm"]
    n_layers = sum(1 for k in lstm if k.startswith("wx_"))
    layers = [(lstm[f"wx_{i}"], lstm[f"wh_{i}"], lstm[f"b_{i}"]) for i in range(n_layers)]
    h = oracle_lstm(folded, layers)[:, -1].reshape(B, N, -1)
    return oracle_chebconv(supports, h, p["gcn"]["W"], p["gcn"]["b"])


def oracle_stmgcn(supports_stack, obs, params):
    br = params["params"]["branches"]
    m_graphs = supports_stack.shape[0]
    fused = sum(
        oracle_branch(supports_stack[m], obs, jax.tree.map(lambda a: np.asarray(a[m]), br))
        for m in range(m_graphs)
    )
    head = params["params"]["head"]
    return fused @ head["kernel"] + head["bias"]


def random_supports(rng, K, N):
    s = rng.standard_normal((K, N, N)).astype(np.float32) * 0.2
    return s


class TestChebConv:
    def test_matches_oracle(self):
        rng = np.random.default_rng(0)
        sup = jnp.asarray(random_supports(rng, 3, 7))
        x = jnp.asarray(rng.standard_normal((4, 7, 6)).astype(np.float32))
        layer = ChebGraphConv(n_supports=3, features=5)
        params = layer.init(jax.random.key(0), sup, x)
        got = layer.apply(params, sup, x)
        want = oracle_chebconv(
            np.asarray(sup), np.asarray(x),
            np.asarray(params["params"]["W"]), np.asarray(params["params"]["b"]),
        )
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)

    def test_support_count_mismatch_raises(self):
        rng = np.random.default_rng(1)
        sup = jnp.asarray(random_supports(rng, 2, 5))
        x = jnp.zeros((2, 5, 3))
        layer = ChebGraphConv(n_supports=3, features=4)
        with pytest.raises(ValueError, match="supports"):
            layer.init(jax.random.key(0), sup, x)

    def test_no_bias_no_activation(self):
        rng = np.random.default_rng(2)
        sup = jnp.asarray(random_supports(rng, 2, 5))
        x = jnp.asarray(rng.standard_normal((3, 5, 4)).astype(np.float32))
        layer = ChebGraphConv(n_supports=2, features=4, use_bias=False, activation=None)
        params = layer.init(jax.random.key(0), sup, x)
        assert "b" not in params["params"]
        got = layer.apply(params, sup, x)
        want = oracle_chebconv(np.asarray(sup), np.asarray(x),
                               np.asarray(params["params"]["W"]), None, relu=False)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)
        assert (np.asarray(got) < 0).any()  # really no relu


class TestStackedLSTM:
    def test_matches_oracle(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((6, 9, 4)).astype(np.float32))
        lstm = StackedLSTM(hidden_dim=8, num_layers=3)
        params = lstm.init(jax.random.key(1), x)
        got, states = lstm.apply(params, x)
        p = params["params"]
        layers = [(np.asarray(p[f"wx_{i}"]), np.asarray(p[f"wh_{i}"]), np.asarray(p[f"b_{i}"]))
                  for i in range(3)]
        want = oracle_lstm(np.asarray(x), layers)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)
        assert len(states) == 3
        np.testing.assert_allclose(np.asarray(got[:, -1]), np.asarray(states[-1][0]),
                                   rtol=1e-6)

    def test_remat_equals_no_remat(self):
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.standard_normal((3, 24, 5)).astype(np.float32))
        base = StackedLSTM(hidden_dim=8, num_layers=2)
        params = base.init(jax.random.key(2), x)
        out_a, _ = jax.jit(base.apply)(params, x)
        rem = StackedLSTM(hidden_dim=8, num_layers=2, remat=True)
        out_b, _ = jax.jit(rem.apply)(params, x)
        np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), rtol=1e-6)

    def test_initial_state_threading(self):
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((2, 6, 3)).astype(np.float32))
        lstm = StackedLSTM(hidden_dim=4, num_layers=2)
        params = lstm.init(jax.random.key(3), x)
        # running [0:3] then [3:6] with threaded state == running [0:6]
        _, st = lstm.apply(params, x[:, :3])
        out_b, _ = lstm.apply(params, x[:, 3:], initial_states=st)
        out_full, _ = lstm.apply(params, x)
        np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_full[:, 3:]),
                                   rtol=1e-5, atol=1e-6)


class TestSTMGCN:
    def build(self, shared=True, M=3, K=3, N=9, T=5, C=1, B=4, seed=0):
        rng = np.random.default_rng(seed)
        sup = jnp.asarray(np.stack([random_supports(rng, K, N) for _ in range(M)]))
        x = jnp.asarray(rng.standard_normal((B, T, N, C)).astype(np.float32))
        model = STMGCN(m_graphs=M, n_supports=K, seq_len=T, input_dim=C,
                       lstm_hidden_dim=16, lstm_num_layers=2, gcn_hidden_dim=8,
                       shared_gate_fc=shared)
        params = model.init(jax.random.key(seed), sup, x)
        return model, params, sup, x

    def test_matches_oracle_end_to_end(self):
        model, params, sup, x = self.build()
        got = jax.jit(model.apply)(params, sup, x)
        want = oracle_stmgcn(np.asarray(sup), np.asarray(x),
                             jax.tree.map(np.asarray, params))
        assert got.shape == (4, 9, 1)
        np.testing.assert_allclose(np.asarray(got), want, rtol=5e-4, atol=1e-5)

    def test_branch_params_stacked_on_m_axis(self):
        _, params, _, _ = self.build(M=3)
        leaves = jax.tree.leaves(params["params"]["branches"])
        assert all(leaf.shape[0] == 3 for leaf in leaves)

    def test_unshared_gate_has_second_fc(self):
        _, params, _, _ = self.build(shared=False)
        gate = params["params"]["branches"]["cg_lstm"]["gate"]
        assert "gate_fc2" in gate

    def test_shared_vs_unshared_outputs_differ(self):
        model_s, params_s, sup, x = self.build(shared=True)
        model_u = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                         lstm_hidden_dim=16, lstm_num_layers=2, gcn_hidden_dim=8,
                         shared_gate_fc=False)
        params_u = model_u.init(jax.random.key(0), sup, x)
        assert not np.allclose(np.asarray(model_s.apply(params_s, sup, x)),
                               np.asarray(model_u.apply(params_u, sup, x)))

    def test_wrong_m_raises(self):
        model, params, sup, x = self.build(M=3)
        with pytest.raises(ValueError, match="supports_stack"):
            model.apply(params, sup[:2], x)

    def test_bfloat16_compute(self):
        rng = np.random.default_rng(7)
        sup = jnp.asarray(np.stack([random_supports(rng, 3, 6) for _ in range(2)]))
        x = jnp.asarray(rng.standard_normal((2, 5, 6, 1)).astype(np.float32))
        model = STMGCN(m_graphs=2, n_supports=3, seq_len=5, input_dim=1,
                       lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8,
                       dtype=jnp.bfloat16)
        params = model.init(jax.random.key(0), sup, x)
        out = model.apply(params, sup, x)
        assert out.dtype == jnp.bfloat16
        # params stay full precision
        assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(params))

    def test_grad_flows_everywhere(self):
        model, params, sup, x = self.build(M=2, B=2)
        def loss(p):
            return jnp.mean(model.apply(p, sup, x) ** 2)
        grads = jax.grad(loss)(params)
        norms = [float(jnp.abs(g).max()) for g in jax.tree.leaves(grads)]
        assert all(n > 0 for n in norms), "some parameter got zero gradient"
