"""Tiled-sparse Chebyshev supports: plan, conv parity, routing, serving.

The tiled path (``ops/tiling.py`` + ``TiledChebGraphConv``) is an
offline reorder/condense of the dense ``(M, K, N, N)`` support stack
into MXU-shaped ``(tile, tile)`` blocks. Its correctness contract is
the dense path: one shared RCM-style permutation must round-trip
exactly, the condensed blocks must reconstruct the permuted supports
bit-for-bit, and the online apply (gathered-tiles XLA or the Pallas
``spmm_stack`` kernel) must match ``ChebGraphConv`` on the same params
— forward and gradient — across K in {2, 3} and M = 3 branch graphs.
Above the ops layer, the experiment/trainer/serving wiring routes
``model.tiled`` configs end to end: loop-layout params, fleet shape
classes over tiled cities, and bit-identical tiled serving engines.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import MeshConfig, ServingConfig, preset
from stmgcn_tpu.data import grid_adjacency
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.ops.chebconv import ChebGraphConv, TiledChebGraphConv
from stmgcn_tpu.ops.tiling import (
    TiledBranchSupports,
    TiledSupports,
    gathered_tiles_apply,
    plan_tiling,
    rcm_permutation,
)

M, TILE = 3, 8


def scrambled_supports(side=8, m_graphs=M, order=2, seed=0, noise=0.0):
    """Dense Chebyshev supports over M scrambled-grid graphs.

    The node scramble destroys the grid's natural banded ordering — the
    case RCM exists for. Condensation fixtures stay noise-free: even a
    handful of uniform-random long-range edges wreck any bandwidth-
    reducing order once the 2-hop Chebyshev supports square them (real
    metro graphs are locally structured, not uniform-random). Parity
    fixtures pass ``noise`` > 0 — the math must hold on any pattern.
    """
    rng = np.random.default_rng(seed)
    n = side * side
    shuffle = rng.permutation(n)
    adjs = []
    for m in range(m_graphs):
        a = grid_adjacency(side)
        extra = (rng.random((n, n)) < noise).astype(np.float32)
        a = np.maximum(a, np.maximum(extra, extra.T))
        np.fill_diagonal(a, 0)
        adjs.append(a[shuffle][:, shuffle])
    return SupportConfig("chebyshev", order).build_all(adjs)  # (M, order+1, N, N)


def reconstruct(plan: TiledSupports) -> np.ndarray:
    """Scatter a plan's blocks back to the dense *permuted* stack."""
    t, r = plan.tile, plan.block_rows
    n_pad = r * t
    data = np.asarray(plan.data)
    idx = np.asarray(plan.idx)
    out = np.zeros((plan.m_graphs, plan.n_supports, n_pad, n_pad), np.float32)
    for mi in range(plan.m_graphs):
        for ki in range(plan.n_supports):
            for ri in range(r):
                for ci in range(idx.shape[3]):
                    col = idx[mi, ki, ri, ci]
                    out[mi, ki, ri * t:(ri + 1) * t, col * t:(col + 1) * t] += (
                        data[mi, ki, ri, ci]
                    )
    return out[:, :, :plan.n, :plan.n]


class TestPlanTiling:
    def test_rcm_round_trip_identity(self):
        dense = scrambled_supports()
        perm = rcm_permutation(np.any(dense != 0.0, axis=(0, 1)))
        n = dense.shape[-1]
        assert sorted(perm.tolist()) == list(range(n))  # a true permutation
        inv = np.argsort(perm)
        x = np.random.default_rng(1).standard_normal(n)
        np.testing.assert_array_equal(x[perm][inv], x)

    def test_blocks_reconstruct_permuted_dense_exactly(self):
        dense = scrambled_supports(noise=0.01)
        plan = plan_tiling(dense, tile=TILE)
        perm = np.asarray(plan.perm)
        permuted = dense[:, :, perm][:, :, :, perm]
        np.testing.assert_array_equal(reconstruct(plan), permuted)

    def test_rcm_condenses_a_scrambled_grid(self):
        dense = scrambled_supports(side=12)  # N=144: room to condense
        stats = plan_tiling(dense, tile=TILE).tile_stats()
        # identity-ordered: a scrambled grid's nonzeros land nearly
        # everywhere; after RCM they cluster into a strict minority of
        # the dense block grid
        assert stats["blocks_kept"] < stats["blocks_dense_equivalent"]
        assert stats["density"] < 0.8
        assert 0 < stats["flops_ratio"] < 1
        assert stats["nbytes"] < stats["dense_nbytes"]

    def test_plan_validation(self):
        with pytest.raises(ValueError, match="dense"):
            plan_tiling(np.zeros((2, 3, 4)), tile=TILE)
        with pytest.raises(ValueError, match="tile"):
            plan_tiling(scrambled_supports(), tile=0)
        plan = plan_tiling(scrambled_supports(), tile=TILE)
        with pytest.raises(ValueError, match="shrink"):
            plan.pad_to(plan.n - 1)
        with pytest.raises(ValueError, match="narrow"):
            plan.with_block_cols(0, 0)
        with pytest.raises(TypeError, match="int"):
            plan[0:1]

    def test_pad_to_keeps_new_nodes_isolated(self):
        dense = scrambled_supports()
        plan = plan_tiling(dense, tile=TILE)
        rung = plan.n + TILE + 3  # crosses a tile boundary
        padded = plan.pad_to(rung)
        assert padded.n == rung and len(np.asarray(padded.perm)) == rung
        # the padded rows/cols of the reconstruction are exactly zero and
        # the original permuted stack is untouched
        rec = reconstruct(padded)
        np.testing.assert_array_equal(rec[:, :, :plan.n, :plan.n],
                                      reconstruct(plan))
        assert not rec[:, :, plan.n:, :].any()
        assert not rec[:, :, :, plan.n:].any()


class TestTiledConvParity:
    @pytest.mark.parametrize("order", [1, 2])  # K = order + 1 in {2, 3}
    def test_forward_and_grad_match_dense(self, order):
        dense = scrambled_supports(order=order, noise=0.01)
        plan = plan_tiling(dense, tile=TILE)
        n = dense.shape[-1]
        k = order + 1
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((4, n, 2)).astype(np.float32)
        )
        ref = ChebGraphConv(n_supports=k, features=5)
        tiled = TiledChebGraphConv(n_supports=k, features=5, backend="xla")
        params = ref.init(jax.random.key(0), jnp.asarray(dense[0]), x)
        assert jax.tree.structure(params) == jax.tree.structure(
            tiled.init(jax.random.key(0), plan[0], x)
        )  # shared (K*F_in, F_out) layout — params are interchangeable
        for m in range(M):
            want = ref.apply(params, jnp.asarray(dense[m]), x)
            got = tiled.apply(params, plan[m], x)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-5)

        def loss_ref(xx, sup):
            return (ref.apply(params, sup, xx) ** 2).sum()

        def loss_tiled(xx, branch):
            return (tiled.apply(params, branch, xx) ** 2).sum()

        g_ref = jax.grad(loss_ref)(x, jnp.asarray(dense[1]))
        g_tiled = jax.grad(loss_tiled)(x, plan[1])
        np.testing.assert_allclose(np.asarray(g_tiled), np.asarray(g_ref),
                                   rtol=2e-4, atol=2e-4)

    def test_pallas_interpret_backend_matches_xla(self):
        dense = scrambled_supports(side=4, order=1)
        plan = plan_tiling(dense, tile=4)
        n = dense.shape[-1]
        x = jnp.asarray(
            np.random.default_rng(3).standard_normal((2, n, 1)).astype(np.float32)
        )
        xla = TiledChebGraphConv(n_supports=2, features=3, backend="xla")
        pal = TiledChebGraphConv(n_supports=2, features=3, backend="pallas")
        params = xla.init(jax.random.key(0), plan[0], x)
        np.testing.assert_allclose(
            np.asarray(pal.apply(params, plan[0], x)),
            np.asarray(xla.apply(params, plan[0], x)),
            rtol=1e-5, atol=1e-5,
        )

    def test_gathered_tiles_apply_matches_matmul(self):
        dense = scrambled_supports(order=2, noise=0.01)
        plan = plan_tiling(dense, tile=TILE)
        n = dense.shape[-1]
        x = np.random.default_rng(4).standard_normal((n, 6)).astype(np.float32)
        perm = np.asarray(plan.perm)
        for m in range(M):
            got = np.asarray(gathered_tiles_apply(plan[m], jnp.asarray(x[perm])))
            permuted = dense[m][:, perm][:, :, perm]
            want = np.einsum("kij,jf->kif", permuted, x[perm])
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def tiled_cfg(out_dir=None, **model_kw):
    cfg = preset("smoke")
    cfg.model.tiled = True
    cfg.model.tile_size = TILE
    for k, v in model_kw.items():
        setattr(cfg.model, k, v)
    cfg.train.epochs = 1
    if out_dir is not None:
        cfg.train.out_dir = str(out_dir)
    return cfg


class TestTiledRouting:
    def test_route_supports_returns_tiled_modes(self):
        from stmgcn_tpu.experiment import build_dataset, route_supports

        cfg = tiled_cfg()
        sup, modes = route_supports(cfg, build_dataset(cfg))
        assert modes == ("tiled",) * cfg.model.m_graphs
        assert isinstance(sup, TiledSupports)
        assert isinstance(sup[0], TiledBranchSupports)

    def test_build_model_derives_loop_layout(self):
        from stmgcn_tpu.experiment import build_dataset, build_model, route_supports

        cfg = tiled_cfg()
        ds = build_dataset(cfg)
        sup, _ = route_supports(cfg, ds)
        model = build_model(cfg, ds.n_feats)  # no explicit modes: config-derived
        assert model.branch_modes() == ("tiled",) * cfg.model.m_graphs
        x = jnp.zeros((2, cfg.data.seq_len, ds.n_nodes, ds.n_feats), jnp.float32)
        params = model.init(jax.random.key(0), sup, x)
        assert "branch_0" in params["params"] and "branches" not in params["params"]

    def test_tiled_plus_sparse_rejected(self):
        from stmgcn_tpu.experiment import build_dataset, build_supports

        cfg = tiled_cfg(sparse=True)
        with pytest.raises(ValueError, match="mutually exclusive"):
            build_supports(cfg, build_dataset(cfg))

    def test_tiled_plus_mesh_rejected(self):
        from stmgcn_tpu.experiment import build_dataset, route_supports

        cfg = tiled_cfg()
        cfg.mesh = MeshConfig(dp=2)
        with pytest.raises(ValueError, match="mesh"):
            route_supports(cfg, build_dataset(cfg))

    def test_waste_budget_enforced(self):
        from stmgcn_tpu.experiment import build_dataset, build_supports

        cfg = tiled_cfg()
        cfg.model.tile_waste_budget = 1e-9
        with pytest.raises(ValueError, match="tile_waste_budget"):
            build_supports(cfg, build_dataset(cfg))

    def test_smoke_preset_trains_tiled_end_to_end(self, tmp_path):
        from stmgcn_tpu.experiment import build_trainer

        cfg = tiled_cfg(tmp_path)
        trainer = build_trainer(cfg, verbose=False)
        hist = trainer.train()
        assert np.isfinite(hist["train"][0])


class TestTiledFleetAndServing:
    @pytest.fixture(scope="class")
    def fleet_run(self, tmp_path_factory):
        """One hetero tiled training run shared by the serving assertions."""
        from stmgcn_tpu.experiment import build_dataset, build_supports, build_trainer
        from stmgcn_tpu.inference import Forecaster

        out = tmp_path_factory.mktemp("tiled_fleet")
        cfg = preset("multicity")
        cfg.mesh = MeshConfig()
        cfg.data.city_rows = (5, 4)
        cfg.data.cols = 5
        cfg.data.city_timesteps = None
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.model.tiled = True
        cfg.model.tile_size = TILE
        cfg.train.epochs = 1
        cfg.train.steps_per_superstep = 4
        cfg.train.fleet = True
        cfg.train.out_dir = str(out)
        trainer = build_trainer(cfg, verbose=False)
        trainer.train()
        fc = Forecaster.from_checkpoint(str(out / "best.ckpt"))
        plans = build_supports(cfg, build_dataset(cfg))
        return cfg, trainer, fc, plans

    def test_fleet_superstep_engages_on_tiled_cities(self, fleet_run):
        _, trainer, _, plans = fleet_run
        assert trainer.train_path == "fleet_superstep"
        assert all(isinstance(p, TiledSupports) for p in plans.per_city)

    def test_fleet_engine_private_exact_fit_classes(self, fleet_run):
        from stmgcn_tpu.serving import FleetServingEngine

        _, _, fc, plans = fleet_run
        scfg = ServingConfig(buckets=(4,), max_batch=4)
        with FleetServingEngine.from_forecaster(fc, plans, config=scfg) as eng:
            # tiled cities never rung-share: one exact-fit class each
            assert sorted(eng._groups) == sorted(
                (p.n, (c,)) for c, p in enumerate(plans.per_city)
            )
            for c, plan in enumerate(plans.per_city):
                hist = np.random.default_rng(c).standard_normal(
                    (2, fc.seq_len, plan.n, fc.derived["input_dim"])
                ).astype(np.float32)
                want = fc.predict(plan, hist, city=c)
                got = eng.predict_direct(hist, city=c)
                np.testing.assert_array_equal(got, want)  # bit parity
            gen0 = eng.generation
            assert eng.swap_params(fc.params) == gen0 + 1  # fleet-wide swap

    def test_serving_engine_tiled_city(self, fleet_run):
        from stmgcn_tpu.serving import ServingEngine

        _, _, fc, plans = fleet_run
        plan = plans.per_city[0]
        scfg = ServingConfig(buckets=(4,), max_batch=4)
        with ServingEngine.from_forecaster(fc, plan, config=scfg, city=0) as eng:
            hist = np.random.default_rng(9).standard_normal(
                (3, fc.seq_len, plan.n, fc.derived["input_dim"])
            ).astype(np.float32)
            want = fc.predict(plan, hist, city=0)
            np.testing.assert_array_equal(eng.predict_direct(hist), want)
            pre = eng.predict_direct(hist)
            eng.swap_params(fc.params)  # same params — output unchanged
            np.testing.assert_array_equal(eng.predict_direct(hist), pre)


class TestFootprint:
    def test_tiled_apply_never_materializes_dense_supports(self):
        """Laziness pin: no intermediate in the tiled conv's jaxpr is
        anywhere near the dense N^2 support stack a (K, N, N) apply
        would carry."""
        side = 16  # N = 256, two tile rows at tile=128
        dense = scrambled_supports(side=side, m_graphs=1, order=2)
        plan = plan_tiling(dense, tile=128)
        n = dense.shape[-1]
        x = jnp.zeros((1, n, 1), jnp.float32)
        conv = TiledChebGraphConv(n_supports=3, features=4, backend="xla")
        params = conv.init(jax.random.key(0), plan[0], x)

        avals = []

        def walk(jaxpr):
            for eqn in jaxpr.eqns:
                avals.extend(v.aval for v in eqn.outvars)
                for sub in jax.core.jaxprs_in_params(eqn.params):
                    walk(sub)

        walk(jax.make_jaxpr(
            lambda b, xx: conv.apply(params, b, xx)
        )(plan[0], x).jaxpr)
        biggest = max(int(np.prod(a.shape)) for a in avals if hasattr(a, "shape"))
        # the largest tiled intermediate is the gathered block tensor
        # (K * R * C * tile * BF) — far under the (K, N, N) dense stack
        assert biggest < 3 * n * n

    def test_plan_is_smaller_than_dense_for_structured_graphs(self):
        # tile must track sqrt(N)-ish bandwidth: at tile=64 on N=256 the
        # forward+transpose blocks outweigh dense — 16 wins handily
        dense = scrambled_supports(side=16, m_graphs=1, order=2)
        plan = plan_tiling(dense, tile=16)
        stats = plan.tile_stats()
        assert stats["nbytes"] < stats["dense_nbytes"]
