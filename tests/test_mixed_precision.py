"""Mixed-precision bf16 training: twin drills, prepared backward,
master-param checkpoints, SR determinism, and config plumbing.

The bf16 mode's contract is layered: (1) the *twin drill* — the bf16
superstep twin of the fp32 program trains with health instrumentation
on, every loss inside a pinned band of the fp32 run's, zero nonfinite
grads or losses, and the optimizer-visible params stay f32 masters
throughout; (2) the tiled Chebyshev apply's *prepared backward* (a
custom VJP running the offline pre-transposed gathered-tiles SpMM over
the cotangent) is parity-tested against both plain autodiff and the
dense oracle, with a strictly smaller, scatter-free backward jaxpr; (3)
checkpoints are precision-invariant — f32 masters in the same v2
format, restore-compatible across ``--precision``, exact mid-epoch
resume at bf16; (4) stochastic rounding is a pure function of
``sr_seed``; (5) ``--precision`` rides the CLI -> ExperimentConfig ->
json round trip, and the fp32 default traces programs containing no
bf16 dtype at all (bit-identity with the pre-mixed-precision release is
pinned structurally by the unchanged fp32 ``PRIMITIVE_BUDGETS`` and
``PRECISION_BASELINES``).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.cli import build_parser, config_from_args
from stmgcn_tpu.config import ExperimentConfig, TrainConfig, preset
from stmgcn_tpu.data import DemandDataset, WindowSpec, grid_adjacency, synthetic_dataset
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.ops.tiling import (
    gathered_tiles_apply,
    gathered_tiles_apply_reference,
    plan_tiling,
)
from stmgcn_tpu.resilience import FaultPlan, FaultSpec, InjectedFault
from stmgcn_tpu.train import (
    Trainer,
    make_optimizer,
    make_step_fns,
    make_superstep_fns,
    verify_checkpoint,
)
from stmgcn_tpu.train.step import PRECISIONS, _health_stats


def same(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


def _leaf_dtypes(tree):
    return {str(leaf.dtype) for leaf in jax.tree.leaves(tree)}


# ---------------------------------------------------------------------------
# shared unit fixture: the test_superstep.py shapes, pool large enough for
# a 6-step block so the twin drill sees several optimizer steps


def _drill_fixture():
    rng = np.random.default_rng(0)
    m, n, t, b, s, pool = 2, 9, 5, 4, 6, 12
    sup = jnp.asarray(rng.standard_normal((m, 3, n, n)).astype(np.float32) * 0.2)
    model = STMGCN(m_graphs=m, n_supports=3, seq_len=t, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
    x_all = jnp.asarray(rng.standard_normal((pool, t, n, 1)).astype(np.float32))
    y_all = jnp.asarray(
        rng.standard_normal((pool, n, 1)).astype(np.float32) * 0.1
    )
    opt = make_optimizer(1e-3, 1e-4)
    fns = make_step_fns(model, opt, "mse")
    params, opt_state = fns.init(jax.random.key(0), sup, x_all[:b])
    idx = jnp.asarray(rng.integers(0, pool, size=(s, b)).astype(np.int32))
    mask = jnp.ones((s, b), jnp.float32)
    return model, opt, sup, x_all, y_all, params, opt_state, idx, mask


class TestTwinDrill:
    """bf16 superstep vs its fp32 twin, health instrumentation on."""

    def test_bf16_superstep_drill_pinned_band_zero_nonfinite(self):
        model, opt, sup, x_all, y_all, params, opt_state, idx, mask = (
            _drill_fixture()
        )
        runs = {}
        for p in PRECISIONS:
            sfns = make_superstep_fns(model, opt, "mse", health=True,
                                      precision=p)
            # both paths donate (params, opt_state): hand each its own copy
            pp = jax.tree.map(jnp.copy, params)
            ss = jax.tree.map(jnp.copy, opt_state)
            pp, ss, losses, stats = sfns.train_superstep(
                pp, ss, sup, x_all, y_all, idx, mask
            )
            runs[p] = (np.asarray(losses), stats, pp)

        losses32, stats32, _ = runs["fp32"]
        losses16, stats16, params16 = runs["bf16"]
        # zero nonfinite anywhere in the bf16 drill — grads and losses
        for stats in (stats32, stats16):
            assert int(np.sum(np.asarray(stats["nonfinite_grads"]))) == 0
            assert int(np.sum(np.asarray(stats["nonfinite_loss"]))) == 0
        assert np.isfinite(losses16).all()
        # the pinned band: bf16 per-step losses track fp32 to well under
        # a loss-unit of drift at these shapes (measured ~6e-6; the band
        # leaves headroom for BLAS variation without admitting a broken
        # accumulation island, which drifts orders of magnitude further)
        np.testing.assert_allclose(losses16, losses32, rtol=0, atol=1e-3)
        assert np.abs(losses16 - losses32).max() < 1e-3
        # the optimizer-visible state never leaves f32: masters, not shadows
        assert _leaf_dtypes(params16) == {"float32"}
        # grad-norm health math is f32 even when grads originate bf16-side
        assert stats16["grad_norm"].dtype == jnp.float32

    def test_precision_validation(self):
        model, opt, *_ = _drill_fixture()
        with pytest.raises(ValueError, match="precision"):
            make_step_fns(model, opt, "mse", precision="fp16")
        assert PRECISIONS == ("fp32", "bf16")


class TestHealthStatsBf16:
    """The _health_stats fix: norm math in f32 on bf16 grad trees."""

    def test_grad_norm_f32_on_bf16_grads(self):
        # 1 + 2^-7 is exactly representable in bf16 (7 mantissa bits),
        # so the fixture loses nothing entering the tree; the norm and
        # update_ratio must come back as f32 scalars matching the
        # float64 reference far inside bf16's ~4e-3 resolution
        v = 1.0 + 2.0 ** -7
        big = jnp.full((1024,), v, jnp.bfloat16)
        grads = {"params": {"lstm": {"w": big}}}
        params = {"params": {"lstm": {"w": jnp.ones((1024,), jnp.bfloat16)}}}
        stats = _health_stats(params, grads, grads, jnp.float32(0.5))
        assert stats["grad_norm"].dtype == jnp.float32
        assert stats["update_ratio"].dtype == jnp.float32
        assert stats["group_norms"].dtype == jnp.float32
        want = float(np.sqrt(np.sum(np.full(1024, v, np.float64) ** 2)))
        np.testing.assert_allclose(float(stats["grad_norm"]), want, rtol=1e-5)
        np.testing.assert_allclose(
            float(stats["update_ratio"]), want / 32.0, rtol=1e-5
        )
        # nonfinite counting stays on the RAW leaves: a genuinely inf
        # bf16 grad is counted, a merely-large finite one is not
        assert int(stats["nonfinite_grads"]) == 0
        grads_inf = {"params": {"lstm": {"w": big.at[0].set(jnp.inf)}}}
        stats = _health_stats(params, grads_inf, grads, jnp.float32(0.5))
        assert int(stats["nonfinite_grads"]) == 1


# ---------------------------------------------------------------------------
# prepared backward


def _tiled_fixture(tile=8):
    rng = np.random.default_rng(0)
    side, m_graphs = 8, 3
    n = side * side
    shuffle = rng.permutation(n)
    adjs = []
    for _ in range(m_graphs):
        a = grid_adjacency(side)
        extra = (rng.random((n, n)) < 0.01).astype(np.float32)
        a = np.maximum(a, np.maximum(extra, extra.T))
        np.fill_diagonal(a, 0)
        adjs.append(a[shuffle][:, shuffle])
    dense = SupportConfig("chebyshev", 2).build_all(adjs)
    return dense, plan_tiling(dense, tile=tile)


def _count_primitives(closed):
    """Total eqn count, recursing through pjit/scan/custom-vjp bodies."""
    total = 0

    def walk(jaxpr):
        nonlocal total
        for eqn in jaxpr.eqns:
            total += 1
            for p in eqn.params.values():
                subs = p if isinstance(p, (list, tuple)) else (p,)
                for q in subs:
                    sub = getattr(q, "jaxpr", None)
                    if sub is not None:
                        walk(getattr(sub, "jaxpr", sub))

    walk(closed.jaxpr)
    return total


class TestPreparedBackward:
    def test_vjp_parity_tiled_and_dense(self):
        dense, plan = _tiled_fixture()
        n = dense.shape[-1]
        x = jnp.asarray(
            np.random.default_rng(4).standard_normal((n, 6)).astype(np.float32)
        )
        perm = np.asarray(plan.perm)
        for m in range(dense.shape[0]):
            br = plan[m]
            g_prep = jax.grad(
                lambda xx: (gathered_tiles_apply(br, xx) ** 2).sum()
            )(x)
            g_auto = jax.grad(
                lambda xx: (gathered_tiles_apply_reference(br, xx) ** 2).sum()
            )(x)
            # dense oracle on the same permuted coordinates
            permuted = jnp.asarray(dense[m][:, perm][:, :, perm])
            g_dense = jax.grad(
                lambda xx: (
                    jnp.einsum("kij,jf->kif", permuted, xx) ** 2
                ).sum()
            )(x)
            np.testing.assert_allclose(np.asarray(g_prep), np.asarray(g_auto),
                                       rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(np.asarray(g_prep), np.asarray(g_dense),
                                       rtol=1e-4, atol=1e-4)

    def test_backward_jaxpr_smaller_and_scatter_free(self):
        _, plan = _tiled_fixture()
        br = plan[0]
        x = jax.ShapeDtypeStruct((br.n, 6), jnp.float32)
        prep = jax.make_jaxpr(
            jax.grad(lambda xx: (gathered_tiles_apply(br, xx) ** 2).sum())
        )(x)
        auto = jax.make_jaxpr(
            jax.grad(
                lambda xx: (gathered_tiles_apply_reference(br, xx) ** 2).sum()
            )
        )(x)
        n_prep, n_auto = _count_primitives(prep), _count_primitives(auto)
        # strictly below autodiff, and pinned: regressions that re-grow
        # the backward (a scatter sneaking back in, a lost fusion) move
        # this number
        assert n_prep < n_auto
        assert n_prep == 24
        # the autodiff transpose scatters cotangent tiles back through
        # the gather; the prepared backward is a second gathered SpMM
        assert "scatter" in str(auto.jaxpr)
        assert "scatter" not in str(prep.jaxpr)

    def test_prepared_backward_under_bf16_inputs_accumulates_f32(self):
        _, plan = _tiled_fixture()
        br = plan[0]
        x16 = jnp.asarray(
            np.random.default_rng(5)
            .standard_normal((br.n, 6))
            .astype(np.float32)
        ).astype(jnp.bfloat16)
        g = jax.grad(
            lambda xx: (gathered_tiles_apply(br, xx) ** 2)
            .sum(dtype=jnp.float32)
        )(x16)
        # cotangent returns in the primal's dtype, accumulated f32 inside
        assert g.dtype == jnp.bfloat16
        assert bool(jnp.isfinite(g.astype(jnp.float32)).all())


# ---------------------------------------------------------------------------
# checkpoints: f32 masters, precision-invariant format, mid-epoch resume


def _build_trainer(out_dir, precision="fp32", epochs=2, **kw):
    data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 60, seed=1)
    dataset = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    sup = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
    return Trainer(model, dataset, sup, n_epochs=epochs, batch_size=16,
                   data_placement="resident", out_dir=str(out_dir),
                   precision=precision, verbose=False, **kw)


class TestMasterCheckpoints:
    def test_bf16_checkpoint_roundtrip_f32_masters(self, tmp_path):
        tr = _build_trainer(tmp_path / "run", precision="bf16", epochs=1)
        tr.train()
        tr.flush_checkpoints()
        meta = verify_checkpoint(str(tmp_path / "run" / "latest.ckpt"))
        assert meta["precision"] == "bf16"
        fresh = _build_trainer(tmp_path / "run", precision="bf16", epochs=1)
        restored = fresh.restore()
        assert restored["precision"] == "bf16"
        # the payload is the f32 masters — bit for bit, no bf16 leaves
        assert _leaf_dtypes(fresh.params) == {"float32"}
        same(fresh.params, tr.params)
        same(jax.tree.leaves(fresh.opt_state), jax.tree.leaves(tr.opt_state))

    def test_restore_compatible_across_precisions(self, tmp_path):
        """fp32 checkpoints load into bf16 trainers and vice versa —
        precision is provenance in meta, never a format change."""
        tr32 = _build_trainer(tmp_path / "a", precision="fp32", epochs=1)
        tr32.train()
        tr32.flush_checkpoints()
        tr16 = _build_trainer(tmp_path / "a", precision="bf16", epochs=1)
        meta = tr16.restore()
        assert meta["precision"] == "fp32"  # the *writer's* provenance
        same(tr16.params, tr32.params)

    @pytest.mark.slow
    def test_mid_epoch_resume_bit_exact_at_bf16(self, tmp_path):
        """The resilience drill at bf16: crash mid-epoch with a step-
        cadence checkpoint, resume, end bit-identical to uninterrupted."""
        ref = _build_trainer(tmp_path / "ref", precision="bf16")
        ref.train()

        plan = FaultPlan(FaultSpec("raise", epoch=2, step=3))
        faulted = _build_trainer(tmp_path / "run", precision="bf16",
                                 fault_plan=plan, checkpoint_every_steps=1)
        with pytest.raises(InjectedFault):
            faulted.train()
        faulted.flush_checkpoints()
        meta = verify_checkpoint(str(tmp_path / "run" / "latest.ckpt"))
        assert meta["precision"] == "bf16"
        assert meta["epoch"] == 2 and meta["batch_in_epoch"] == 3

        resumed = _build_trainer(tmp_path / "run", precision="bf16",
                                 checkpoint_every_steps=1)
        assert resumed.restore_auto() is not None
        resumed.train()
        same(ref.params, resumed.params)
        same(jax.tree.leaves(ref.opt_state),
             jax.tree.leaves(resumed.opt_state))

    def test_trainer_validation(self, tmp_path):
        with pytest.raises(ValueError, match="precision"):
            _build_trainer(tmp_path, precision="fp16")
        with pytest.raises(ValueError, match="sr_seed"):
            _build_trainer(tmp_path, precision="fp32", sr_seed=7)


class TestStochasticRounding:
    def test_sr_deterministic_per_seed(self):
        model, opt, sup, x_all, y_all, params, opt_state, idx, mask = (
            _drill_fixture()
        )
        b = idx.shape[1]
        x, y = x_all[:b], y_all[:b]
        m1 = jnp.ones((b,), jnp.float32)

        def run(seed):
            fns = make_step_fns(model, opt, "mse", precision="bf16",
                                sr_seed=seed)
            pp = jax.tree.map(jnp.copy, params)
            ss = jax.tree.map(jnp.copy, opt_state)
            pp, ss, loss = fns.train_step(pp, ss, sup, x, y, m1)
            return pp, float(loss)

        p_a, l_a = run(7)
        p_b, l_b = run(7)
        p_c, l_c = run(11)
        same(p_a, p_b)
        assert l_a == l_b
        # a different seed draws different rounding noise
        assert l_a != l_c
        leaves_a, leaves_c = jax.tree.leaves(p_a), jax.tree.leaves(p_c)
        assert any(
            not np.array_equal(np.asarray(x1), np.asarray(x2))
            for x1, x2 in zip(leaves_a, leaves_c)
        )
        # SR perturbs the cast, not the scale: still finite, still close
        assert abs(l_a - l_c) < 1e-2
        assert _leaf_dtypes(p_a) == {"float32"}

    def test_sr_requires_bf16(self):
        model, opt, *_ = _drill_fixture()
        # fp32 + sr_seed is inert at the factory level (sr applies only
        # to the bf16 cast); the *trainer* rejects it loudly instead —
        # TestMasterCheckpoints.test_trainer_validation pins that.
        fns = make_step_fns(model, opt, "mse", precision="fp32", sr_seed=3)
        assert fns.train_step is not None


# ---------------------------------------------------------------------------
# config plumbing: CLI -> ExperimentConfig -> json round trip (tier 1)


class TestPrecisionConfigPlumbing:
    def test_cli_round_trip(self):
        args = build_parser().parse_args(
            ["--preset", "smoke", "--precision", "bf16", "--sr-seed", "7"]
        )
        cfg = config_from_args(args)
        assert cfg.train.precision == "bf16"
        assert cfg.train.sr_seed == 7
        thawed = ExperimentConfig.from_dict(
            json.loads(json.dumps(cfg.to_dict()))
        )
        assert thawed.train.precision == "bf16"
        assert thawed.train.sr_seed == 7

    def test_fp32_default_everywhere(self):
        assert TrainConfig().precision == "fp32"
        assert TrainConfig().sr_seed is None
        args = build_parser().parse_args(["--preset", "smoke"])
        cfg = config_from_args(args)
        assert cfg.train.precision == "fp32" and cfg.train.sr_seed is None
        thawed = ExperimentConfig.from_dict(
            json.loads(json.dumps(preset("smoke").to_dict()))
        )
        assert thawed.train.precision == "fp32"

    def test_fp32_programs_contain_no_bf16(self):
        """The structural half of the bit-identity claim: every fp32
        contract program's dtype census is bf16-free (the byte-level
        half is the unchanged fp32 PRIMITIVE_BUDGETS / baselines, pinned
        by test_analysis / test_precision)."""
        from stmgcn_tpu.analysis.dtype_flow import program_flows
        from stmgcn_tpu.analysis.precision_check import precision_summary

        flows = program_flows("smoke")
        bf16_twins = {n for n in flows if n.endswith("_bf16")}
        assert len(bf16_twins) == 4
        for name, flow in flows.items():
            kinds = set(flow.census["bytes"]) | set(flow.census["flops"])
            if name in bf16_twins:
                assert "bfloat16" in kinds
            else:
                assert "bfloat16" not in kinds, name
        assert precision_summary("smoke")["bf16_programs"] == 4
