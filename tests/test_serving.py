"""ServingEngine contracts: padding parity, micro-batching, artifacts.

Train-free like tests/test_export.py — a freshly-initialized flagship
plus a fitted normalizer pins everything that matters: AOT bucket
programs, BIT-exact padding parity against ``Forecaster.predict`` (the
forward is row-independent and the normalizer elementwise, so padded
rows must never perturb real rows — equality, not allclose), the
micro-batcher's dispatch policy, and the per-shape program cache that
fixes the ``ExportedForecaster.predict`` batch-scaling bug.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import ServingConfig, preset
from stmgcn_tpu.data import (
    DemandDataset,
    MinMaxNormalizer,
    WindowSpec,
    synthetic_dataset,
)
from stmgcn_tpu.experiment import build_model
from stmgcn_tpu.export import ExportedForecaster, export_forecaster
from stmgcn_tpu.inference import Forecaster
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.serving import EngineStats, MicroBatcher, ServingEngine

LADDER = ServingConfig(buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0)


@pytest.fixture(scope="module")
def setup():
    cfg = preset("smoke")
    cfg.data.rows = 3
    data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 40, seed=0)
    ds = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    supports = np.asarray(
        SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(ds.adjs.values()),
        np.float32,
    )[: cfg.model.m_graphs]
    model = build_model(cfg, ds.n_feats)
    x = jnp.zeros((2, cfg.data.seq_len, ds.n_nodes, ds.n_feats), jnp.float32)
    params = model.init(jax.random.key(0), jnp.asarray(supports), x)
    norm = MinMaxNormalizer.fit(np.asarray(data.demand))
    fc = Forecaster(
        model, params, norm, cfg, {"input_dim": ds.n_feats, "n_nodes": ds.n_nodes}
    )
    return fc, supports, ds


@pytest.fixture(scope="module")
def engine(setup):
    fc, supports, _ = setup
    eng = fc.serving_engine(supports, config=LADDER)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def artifact(setup, tmp_path_factory):
    fc, supports, _ = setup
    path = str(tmp_path_factory.mktemp("serving") / "model.stmgx")
    export_forecaster(fc, path, platforms=("cpu",))
    return path


def _hist(fc, ds, b, seed=1):
    rng = np.random.default_rng(seed)
    return rng.uniform(0, 50, (b, fc.seq_len, ds.n_nodes, ds.n_feats)).astype(
        np.float32
    )


# -- padding parity (tentpole contract) --------------------------------


@pytest.mark.parametrize("b", [1, 2, 3, 4])
def test_padding_parity_bit_exact(setup, engine, b):
    """Engine results across bucket boundaries (exact fits at 1/2/4,
    padded at 3) are BIT-identical to the unpadded live predictor."""
    fc, supports, ds = setup
    hist = _hist(fc, ds, b)
    ref = fc.predict(supports, hist)
    np.testing.assert_array_equal(engine.predict_direct(hist), ref)
    np.testing.assert_array_equal(engine.predict(hist), ref)


def test_oversized_batch_splits_across_buckets(setup, engine):
    """A request above the top rung is chunked, never rejected."""
    fc, supports, ds = setup
    hist = _hist(fc, ds, 7)  # cap is 4 -> chunks of 4 + 3
    ref = fc.predict(supports, hist)
    np.testing.assert_array_equal(engine.predict(hist), ref)
    np.testing.assert_array_equal(engine.predict_direct(hist), ref)


def test_prenormalized_input_parity(setup, engine):
    fc, supports, ds = setup
    hist = _hist(fc, ds, 3)
    ref = fc.predict(supports, hist)
    np.testing.assert_array_equal(
        engine.predict(fc.normalizer.transform(hist), normalized=True), ref
    )
    np.testing.assert_array_equal(
        engine.predict_direct(fc.normalizer.transform(hist), normalized=True), ref
    )


def test_engine_validates_history_and_close(setup):
    fc, supports, ds = setup
    eng = ServingEngine.from_forecaster(fc, supports, config=LADDER)
    with pytest.raises(ValueError, match="history must be"):
        eng.predict(np.ones((2, 99, ds.n_nodes, ds.n_feats), np.float32))
    eng.close()
    eng.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        eng.predict(_hist(fc, ds, 1))


def test_engine_rejects_bad_ladder(setup):
    fc, supports, _ = setup
    bad = ServingConfig(buckets=(4, 2, 1), max_batch=4)
    with pytest.raises(ValueError, match="invalid serving config"):
        ServingEngine.from_forecaster(fc, supports, config=bad)


def test_engine_stats_split_queue_vs_device(setup, engine):
    fc, _, ds = setup
    engine.stats.reset()
    engine.predict_direct(_hist(fc, ds, 3))
    snap = engine.stats.snapshot()
    assert snap["totals"]["requests"] == 1
    (bucket,) = snap["buckets"]
    assert bucket == "4"  # smallest covering rung for 3 rows
    stats = snap["buckets"][bucket]
    assert stats["pad_waste"] == pytest.approx(0.25)
    assert stats["device_ms"]["p50"] > 0
    assert stats["queue_wait_ms"]["p50"] == 0.0  # direct path never queues


# -- exported-artifact path --------------------------------------------


def test_engine_from_artifact_parity(setup, artifact):
    fc, supports, ds = setup
    with ServingEngine.from_artifact(artifact, supports, config=LADDER) as eng:
        for b in (1, 3, 4):
            hist = _hist(fc, ds, b)
            np.testing.assert_allclose(
                eng.predict(hist), fc.predict(supports, hist),
                rtol=1e-5, atol=1e-4,
            )


def test_exported_predict_routes_through_engine(setup, artifact):
    """Once wrapped, the artifact's own predict serves from the bucket
    ladder (same results, telemetry visible in the engine stats)."""
    fc, supports, ds = setup
    ex = ExportedForecaster.load(artifact)
    hist = _hist(fc, ds, 2)
    before = ex.predict(supports, hist)
    with ServingEngine.from_artifact(ex, supports, config=LADDER) as eng:
        eng.stats.reset()
        np.testing.assert_array_equal(ex.predict(supports, hist), before)
        assert eng.stats.snapshot()["totals"]["requests"] == 1
        with pytest.raises(ValueError, match="pinned"):
            ex.predict(supports * 2.0, hist)


def test_exported_per_shape_program_cache(setup, artifact):
    """The batch-scaling bug fix: repeat shapes reuse one compiled
    program instead of re-tracing through jit every call."""
    fc, supports, ds = setup
    ex = ExportedForecaster.load(artifact)
    h2 = _hist(fc, ds, 2)
    first = ex.predict(supports, h2)
    np.testing.assert_array_equal(ex.predict(supports, h2), first)
    assert len(ex._programs) == 1
    ex.predict(supports, _hist(fc, ds, 5))
    assert len(ex._programs) == 2


# -- micro-batcher unit tests (no JAX involved) ------------------------


def _rows(v, n=1):
    return np.full((n, 3), v, np.float32)


def test_microbatcher_coalesces_concurrent_requests():
    dispatched = []

    def dispatch(payload, bucket, segments):
        dispatched.append((payload.shape[0], bucket, segments))
        time.sleep(0.03)  # slow device: arrivals pile up behind it
        return payload * 2.0

    stats = EngineStats()
    mb = MicroBatcher(dispatch, (1, 2, 4), max_delay_ms=50.0, stats=stats)
    barrier = threading.Barrier(4)
    results = {}

    def client(i):
        barrier.wait()
        results[i] = mb.submit(_rows(float(i)))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mb.close()
    for i in range(4):
        np.testing.assert_array_equal(results[i], _rows(float(i)) * 2.0)
    snap = stats.snapshot()
    assert snap["totals"]["requests"] == 4
    assert snap["totals"]["dispatches"] <= 3  # coalesced, not 4 singles


def test_microbatcher_top_rung_dispatches_without_delay():
    """A request that saturates the top rung must not wait out the
    deadline — and an exact-fit payload is passed through zero-copy."""
    seen = []
    mb = MicroBatcher(
        lambda p, b, s: (seen.append(p), p)[1],
        (1, 2, 4),
        max_delay_ms=5000.0,
        stats=EngineStats(),
    )
    rows = _rows(7.0, n=4)
    t0 = time.perf_counter()
    out = mb.submit(rows)
    elapsed = time.perf_counter() - t0
    mb.close()
    assert elapsed < 2.0  # nowhere near the 5 s deadline
    assert seen[0] is rows  # exact fit: the caller's array itself
    np.testing.assert_array_equal(out, rows)


def test_microbatcher_deadline_fires_for_lone_request():
    stats = EngineStats()
    mb = MicroBatcher(
        lambda p, b, s: p + 1.0, (1, 2, 4), max_delay_ms=40.0, stats=stats
    )
    t0 = time.perf_counter()
    out = mb.submit(_rows(1.0, n=2))  # 2 rows < cap 4: waits for company
    elapsed = time.perf_counter() - t0
    mb.close()
    np.testing.assert_array_equal(out, _rows(1.0, n=2) + 1.0)
    assert 0.03 <= elapsed < 2.0  # released by the deadline, not saturation
    assert stats.snapshot()["buckets"]["2"]["dispatches"] == 1


def test_microbatcher_oversized_submit_rejected():
    mb = MicroBatcher(lambda p, b, s: p, (1, 2), max_delay_ms=1.0,
                      stats=EngineStats())
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        mb.submit(_rows(0.0, n=3))
    mb.close()


def test_microbatcher_dispatch_error_released_to_caller():
    def dispatch(payload, bucket, segments):
        raise RuntimeError("device fell over")

    mb = MicroBatcher(dispatch, (1, 2), max_delay_ms=1.0, stats=EngineStats())
    with pytest.raises(RuntimeError, match="device fell over"):
        mb.submit(_rows(0.0))
    # the worker survives a dying dispatch — next request still served
    with pytest.raises(RuntimeError, match="device fell over"):
        mb.submit(_rows(1.0))
    mb.close()
