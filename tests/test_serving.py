"""ServingEngine contracts: padding parity, micro-batching, artifacts,
typed overload sheds, atomic hot-swap, and the serving fault harness.

Train-free like tests/test_export.py — a freshly-initialized flagship
plus a fitted normalizer pins everything that matters: AOT bucket
programs, BIT-exact padding parity against ``Forecaster.predict`` (the
forward is row-independent and the normalizer elementwise, so padded
rows must never perturb real rows — equality, not allclose), the
micro-batcher's dispatch policy, and the per-shape program cache that
fixes the ``ExportedForecaster.predict`` batch-scaling bug. The
robustness sections drive every failure path deterministically through
:class:`~stmgcn_tpu.resilience.ServeFaultPlan` — never by anecdote.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import ServingConfig, preset
from stmgcn_tpu.data import (
    DemandDataset,
    MinMaxNormalizer,
    WindowSpec,
    synthetic_dataset,
)
from stmgcn_tpu.experiment import build_model
from stmgcn_tpu.export import ExportedForecaster, export_forecaster
from stmgcn_tpu.inference import Forecaster
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.resilience import ServeFaultPlan, ServeFaultSpec
from stmgcn_tpu.serving import (
    AdmissionController,
    BatcherWedged,
    DeadlineExceeded,
    DispatchError,
    EngineStats,
    MicroBatcher,
    Overloaded,
    ServingEngine,
    ShedError,
)
from stmgcn_tpu.train.checkpoint import save_checkpoint

LADDER = ServingConfig(buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0)


@pytest.fixture(scope="module")
def setup():
    cfg = preset("smoke")
    cfg.data.rows = 3
    data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 40, seed=0)
    ds = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    supports = np.asarray(
        SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(ds.adjs.values()),
        np.float32,
    )[: cfg.model.m_graphs]
    model = build_model(cfg, ds.n_feats)
    x = jnp.zeros((2, cfg.data.seq_len, ds.n_nodes, ds.n_feats), jnp.float32)
    params = model.init(jax.random.key(0), jnp.asarray(supports), x)
    norm = MinMaxNormalizer.fit(np.asarray(data.demand))
    fc = Forecaster(
        model, params, norm, cfg, {"input_dim": ds.n_feats, "n_nodes": ds.n_nodes}
    )
    return fc, supports, ds


@pytest.fixture(scope="module")
def engine(setup):
    fc, supports, _ = setup
    eng = fc.serving_engine(supports, config=LADDER)
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def artifact(setup, tmp_path_factory):
    fc, supports, _ = setup
    path = str(tmp_path_factory.mktemp("serving") / "model.stmgx")
    export_forecaster(fc, path, platforms=("cpu",))
    return path


def _hist(fc, ds, b, seed=1):
    rng = np.random.default_rng(seed)
    return rng.uniform(0, 50, (b, fc.seq_len, ds.n_nodes, ds.n_feats)).astype(
        np.float32
    )


# -- padding parity (tentpole contract) --------------------------------


@pytest.mark.parametrize("b", [1, 2, 3, 4])
def test_padding_parity_bit_exact(setup, engine, b):
    """Engine results across bucket boundaries (exact fits at 1/2/4,
    padded at 3) are BIT-identical to the unpadded live predictor."""
    fc, supports, ds = setup
    hist = _hist(fc, ds, b)
    ref = fc.predict(supports, hist)
    np.testing.assert_array_equal(engine.predict_direct(hist), ref)
    np.testing.assert_array_equal(engine.predict(hist), ref)


def test_oversized_batch_splits_across_buckets(setup, engine):
    """A request above the top rung is chunked, never rejected."""
    fc, supports, ds = setup
    hist = _hist(fc, ds, 7)  # cap is 4 -> chunks of 4 + 3
    ref = fc.predict(supports, hist)
    np.testing.assert_array_equal(engine.predict(hist), ref)
    np.testing.assert_array_equal(engine.predict_direct(hist), ref)


def test_prenormalized_input_parity(setup, engine):
    fc, supports, ds = setup
    hist = _hist(fc, ds, 3)
    ref = fc.predict(supports, hist)
    np.testing.assert_array_equal(
        engine.predict(fc.normalizer.transform(hist), normalized=True), ref
    )
    np.testing.assert_array_equal(
        engine.predict_direct(fc.normalizer.transform(hist), normalized=True), ref
    )


def test_engine_validates_history_and_close(setup):
    fc, supports, ds = setup
    eng = ServingEngine.from_forecaster(fc, supports, config=LADDER)
    with pytest.raises(ValueError, match="history must be"):
        eng.predict(np.ones((2, 99, ds.n_nodes, ds.n_feats), np.float32))
    eng.close()
    eng.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        eng.predict(_hist(fc, ds, 1))


def test_engine_rejects_bad_ladder(setup):
    fc, supports, _ = setup
    bad = ServingConfig(buckets=(4, 2, 1), max_batch=4)
    with pytest.raises(ValueError, match="invalid serving config"):
        ServingEngine.from_forecaster(fc, supports, config=bad)


def test_engine_stats_split_queue_vs_device(setup, engine):
    fc, _, ds = setup
    engine.stats.reset()
    engine.predict_direct(_hist(fc, ds, 3))
    snap = engine.stats.snapshot()
    assert snap["totals"]["requests"] == 1
    (bucket,) = snap["buckets"]
    assert bucket == "4"  # smallest covering rung for 3 rows
    stats = snap["buckets"][bucket]
    assert stats["pad_waste"] == pytest.approx(0.25)
    assert stats["device_ms"]["p50"] > 0
    assert stats["queue_wait_ms"]["p50"] == 0.0  # direct path never queues


# -- exported-artifact path --------------------------------------------


def test_engine_from_artifact_parity(setup, artifact):
    fc, supports, ds = setup
    with ServingEngine.from_artifact(artifact, supports, config=LADDER) as eng:
        for b in (1, 3, 4):
            hist = _hist(fc, ds, b)
            np.testing.assert_allclose(
                eng.predict(hist), fc.predict(supports, hist),
                rtol=1e-5, atol=1e-4,
            )


def test_exported_predict_routes_through_engine(setup, artifact):
    """Once wrapped, the artifact's own predict serves from the bucket
    ladder (same results, telemetry visible in the engine stats)."""
    fc, supports, ds = setup
    ex = ExportedForecaster.load(artifact)
    hist = _hist(fc, ds, 2)
    before = ex.predict(supports, hist)
    with ServingEngine.from_artifact(ex, supports, config=LADDER) as eng:
        eng.stats.reset()
        np.testing.assert_array_equal(ex.predict(supports, hist), before)
        assert eng.stats.snapshot()["totals"]["requests"] == 1
        with pytest.raises(ValueError, match="pinned"):
            ex.predict(supports * 2.0, hist)


def test_exported_per_shape_program_cache(setup, artifact):
    """The batch-scaling bug fix: repeat shapes reuse one compiled
    program instead of re-tracing through jit every call."""
    fc, supports, ds = setup
    ex = ExportedForecaster.load(artifact)
    h2 = _hist(fc, ds, 2)
    first = ex.predict(supports, h2)
    np.testing.assert_array_equal(ex.predict(supports, h2), first)
    assert len(ex._programs) == 1
    ex.predict(supports, _hist(fc, ds, 5))
    assert len(ex._programs) == 2


# -- micro-batcher unit tests (no JAX involved) ------------------------


def _rows(v, n=1):
    return np.full((n, 3), v, np.float32)


def test_microbatcher_coalesces_concurrent_requests():
    dispatched = []

    def dispatch(payload, bucket, segments):
        dispatched.append((payload.shape[0], bucket, segments))
        time.sleep(0.03)  # slow device: arrivals pile up behind it
        return payload * 2.0

    stats = EngineStats()
    mb = MicroBatcher(dispatch, (1, 2, 4), max_delay_ms=50.0, stats=stats)
    barrier = threading.Barrier(4)
    results = {}

    def client(i):
        barrier.wait()
        results[i] = mb.submit(_rows(float(i)))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mb.close()
    for i in range(4):
        np.testing.assert_array_equal(results[i], _rows(float(i)) * 2.0)
    snap = stats.snapshot()
    assert snap["totals"]["requests"] == 4
    assert snap["totals"]["dispatches"] <= 3  # coalesced, not 4 singles


def test_microbatcher_top_rung_dispatches_without_delay():
    """A request that saturates the top rung must not wait out the
    deadline — and an exact-fit payload is passed through zero-copy."""
    seen = []
    mb = MicroBatcher(
        lambda p, b, s: (seen.append(p), p)[1],
        (1, 2, 4),
        max_delay_ms=5000.0,
        stats=EngineStats(),
    )
    rows = _rows(7.0, n=4)
    t0 = time.perf_counter()
    out = mb.submit(rows)
    elapsed = time.perf_counter() - t0
    mb.close()
    assert elapsed < 2.0  # nowhere near the 5 s deadline
    assert seen[0] is rows  # exact fit: the caller's array itself
    np.testing.assert_array_equal(out, rows)


def test_microbatcher_deadline_fires_for_lone_request():
    stats = EngineStats()
    mb = MicroBatcher(
        lambda p, b, s: p + 1.0, (1, 2, 4), max_delay_ms=40.0, stats=stats
    )
    t0 = time.perf_counter()
    out = mb.submit(_rows(1.0, n=2))  # 2 rows < cap 4: waits for company
    elapsed = time.perf_counter() - t0
    mb.close()
    np.testing.assert_array_equal(out, _rows(1.0, n=2) + 1.0)
    assert 0.03 <= elapsed < 2.0  # released by the deadline, not saturation
    assert stats.snapshot()["buckets"]["2"]["dispatches"] == 1


def test_microbatcher_oversized_submit_rejected():
    mb = MicroBatcher(lambda p, b, s: p, (1, 2), max_delay_ms=1.0,
                      stats=EngineStats())
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        mb.submit(_rows(0.0, n=3))
    mb.close()


def test_microbatcher_dispatch_error_released_to_caller():
    def dispatch(payload, bucket, segments):
        raise RuntimeError("device fell over")

    mb = MicroBatcher(dispatch, (1, 2), max_delay_ms=1.0, stats=EngineStats())
    with pytest.raises(RuntimeError, match="device fell over"):
        mb.submit(_rows(0.0))
    # the worker survives a dying dispatch — next request still served
    with pytest.raises(RuntimeError, match="device fell over"):
        mb.submit(_rows(1.0))
    mb.close()


# -- typed failure contract --------------------------------------------


def test_dispatch_error_reaches_every_coalesced_waiter():
    """Each waiter of a dead coalesced dispatch gets its OWN typed
    DispatchError carrying the batch context, with the device error as
    ``__cause__`` — not a shared bare exception."""
    def dispatch(payload, bucket, segments):
        time.sleep(0.05)  # keep the worker busy so later arrivals coalesce
        raise RuntimeError("device fell over")

    mb = MicroBatcher(dispatch, (1, 2, 4), max_delay_ms=30.0,
                      stats=EngineStats())
    errors = {}

    def client(i, n):
        try:
            mb.submit(_rows(float(i), n=n))
        except Exception as e:  # noqa: BLE001 — capturing for assertions
            errors[i] = e

    first = threading.Thread(target=client, args=(0, 4))  # saturates: dispatch 0
    first.start()
    time.sleep(0.02)  # worker now inside dispatch 0; these three queue up
    rest = [threading.Thread(target=client, args=(i, 1)) for i in (1, 2, 3)]
    for t in rest:
        t.start()
    for t in [first] + rest:
        t.join(timeout=30)
    mb.close()
    assert sorted(errors) == [0, 1, 2, 3]
    assert len({id(e) for e in errors.values()}) == 4  # own instance each
    for e in errors.values():
        assert isinstance(e, DispatchError)
        assert isinstance(e.__cause__, RuntimeError)
        assert "device fell over" in str(e)
        assert e.bucket == 4
    assert errors[0].requests == 1 and errors[0].rows == 4
    # clients 1-3 coalesced behind the busy worker into one dispatch
    assert errors[1].requests == 3 and errors[1].rows == 3


def test_submit_after_close_raises_immediately():
    mb = MicroBatcher(lambda p, b, s: p, (1, 2), max_delay_ms=1.0,
                      stats=EngineStats())
    mb.close()
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit(_rows(0.0))
    assert time.perf_counter() - t0 < 1.0  # fail-fast, no queue wait


def test_batcher_death_releases_waiters_and_fails_fast():
    """An injected worker death (BaseException at dispatch entry) wedges
    the batcher: the in-flight waiter is released with BatcherWedged and
    later submits raise it immediately instead of blocking forever."""
    plan = ServeFaultPlan(ServeFaultSpec(kind="batcher-die", dispatch=0))
    mb = MicroBatcher(lambda p, b, s: p, (1, 2, 4), max_delay_ms=5.0,
                      stats=EngineStats(), fault_plan=plan)
    with pytest.raises(BatcherWedged) as exc:
        mb.submit(_rows(0.0, n=4))
    assert exc.value.__cause__ is not None  # the BatcherKilled fault
    for _ in range(200):  # the worker protector marks death asynchronously
        if mb.wedged:
            break
        time.sleep(0.01)
    assert mb.wedged
    t0 = time.perf_counter()
    with pytest.raises(BatcherWedged):
        mb.submit(_rows(1.0))
    assert time.perf_counter() - t0 < 1.0
    mb.close()


def test_engine_survives_batcher_death_inline(setup):
    """A wedged batcher degrades ``predict`` to the inline path — the
    caller whose dispatch died is still served, as is everyone after."""
    fc, supports, ds = setup
    plan = ServeFaultPlan(ServeFaultSpec(kind="batcher-die", dispatch=0))
    eng = ServingEngine.from_forecaster(fc, supports, config=LADDER,
                                        fault_plan=plan)
    try:
        hist = _hist(fc, ds, 2)
        ref = fc.predict(supports, hist)
        np.testing.assert_array_equal(eng.predict(hist), ref)
        np.testing.assert_array_equal(eng.predict(hist), ref)
        assert eng._batcher.wedged
    finally:
        eng.close()


# -- SLO admission control ---------------------------------------------


def _slo_config(**kw):
    base = dict(buckets=(1, 2, 4), max_batch=4, max_delay_ms=1.0)
    base.update(kw)
    return ServingConfig(**base)


def test_admission_controller_typed_sheds():
    cfg = _slo_config(deadline_ms=10.0, queue_bound_rows=8)
    assert cfg.violations() == []
    stats = EngineStats()
    adm = AdmissionController(cfg, stats, (1, 2, 4))
    # cold stats: the wait floor is the coalescing delay itself
    assert adm.estimated_wait_ms(8) == pytest.approx(2 * 1.0)
    adm.admit(4, 0)
    adm.admit(4, 4)  # fills the bound exactly: admitted
    with pytest.raises(Overloaded, match="bound"):
        adm.admit(1, 8)
    # teach the wait model: 6 ms per top-rung dispatch measured
    stats.record_dispatch(4, 4, [0.0], 6.0)
    assert adm.estimated_wait_ms(8) == pytest.approx(12.0)
    unbounded = AdmissionController(
        _slo_config(deadline_ms=10.0, queue_bound_rows=0), stats, (1, 2, 4)
    )
    unbounded.admit(1, 7)  # one dispatch ahead: 6 ms fits the deadline
    with pytest.raises(DeadlineExceeded, match="estimated queue wait"):
        unbounded.admit(1, 8)  # two ahead: 12 ms cannot
    assert stats.snapshot()["totals"]["shed"] == {
        "overloaded": 1, "deadline": 1
    }


def test_queued_deadline_expiry_shed_at_dispatch_boundary():
    """A request admitted with time to spare but stalled behind a slow
    dispatch is shed when its deadline expires — never served late."""
    cfg = _slo_config(deadline_ms=50.0, queue_bound_rows=0)
    stats = EngineStats()
    adm = AdmissionController(cfg, stats, (1, 2, 4))

    def dispatch(payload, bucket, segments):
        time.sleep(0.3)  # stall: the queued request's 50 ms expire behind it
        return payload

    mb = MicroBatcher(dispatch, (1, 2, 4), max_delay_ms=1.0, stats=stats,
                      admission=adm)
    outcome = {}

    def blocked():
        try:
            outcome["result"] = mb.submit(_rows(1.0))
        except ShedError as e:
            outcome["error"] = e

    head = threading.Thread(target=lambda: mb.submit(_rows(0.0, n=4)))
    head.start()  # saturates -> dispatch 0 starts, worker stalls 300 ms
    time.sleep(0.05)
    tail = threading.Thread(target=blocked)
    tail.start()  # queued at ~t+50ms with a 50 ms deadline
    head.join(timeout=30)
    tail.join(timeout=30)
    mb.close()
    assert "result" not in outcome
    assert isinstance(outcome["error"], DeadlineExceeded)
    assert "expired in queue" in str(outcome["error"])
    assert stats.snapshot()["totals"]["shed"] == {"deadline": 1}


def test_engine_sheds_overloaded_at_queue_bound(setup):
    """With the worker stalled and the queue at its row bound, the next
    arrival is shed with Overloaded at submit time — deterministically,
    via the fault plan. Every admitted caller is still served exactly."""
    fc, supports, ds = setup
    cfg = _slo_config(deadline_ms=5000.0, queue_bound_rows=4)
    plan = ServeFaultPlan(ServeFaultSpec(kind="dispatch-slow", slow_ms=400.0))
    eng = fc.serving_engine(supports, config=cfg, fault_plan=plan)
    try:
        h4, h1 = _hist(fc, ds, 4), _hist(fc, ds, 1)
        ref4, ref1 = fc.predict(supports, h4), fc.predict(supports, h1)
        results = {}

        def client(key, hist):
            results[key] = eng.predict(hist)

        head = threading.Thread(target=client, args=("head", h4))
        head.start()  # saturates -> slow dispatch, worker busy 400 ms
        time.sleep(0.1)
        queued = [
            threading.Thread(target=client, args=(i, h1)) for i in range(4)
        ]
        for t in queued:
            t.start()  # fill the queue to exactly the 4-row bound
        time.sleep(0.1)
        with pytest.raises(Overloaded):
            eng.predict(h1)  # bound full, worker stalled: typed shed
        for t in [head] + queued:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in [head] + queued)  # nobody hangs
        np.testing.assert_array_equal(results["head"], ref4)
        for i in range(4):
            np.testing.assert_array_equal(results[i], ref1)
        assert eng.stats.snapshot()["totals"]["shed"]["overloaded"] == 1
    finally:
        eng.close()


def test_degrade_policy_serves_shed_requests_inline(setup):
    """shed_policy="degrade": an arrival the queue would shed is served
    inline at degrade_rung instead — same bits, counted as degraded."""
    fc, supports, ds = setup
    cfg = _slo_config(deadline_ms=5000.0, queue_bound_rows=4,
                      shed_policy="degrade", degrade_rung=1)
    plan = ServeFaultPlan(ServeFaultSpec(kind="dispatch-slow", slow_ms=400.0))
    eng = fc.serving_engine(supports, config=cfg, fault_plan=plan)
    try:
        h4, h1 = _hist(fc, ds, 4), _hist(fc, ds, 1)
        ref1 = fc.predict(supports, h1)
        results = {}

        def client(key, hist):
            results[key] = eng.predict(hist)

        head = threading.Thread(target=client, args=("head", h4))
        head.start()
        time.sleep(0.1)
        queued = [
            threading.Thread(target=client, args=(i, h1)) for i in range(4)
        ]
        for t in queued:
            t.start()
        time.sleep(0.1)
        t0 = time.perf_counter()
        out = eng.predict(h1)  # shed -> served inline while worker stalls
        assert time.perf_counter() - t0 < 0.3  # did NOT wait out the queue
        np.testing.assert_array_equal(out, ref1)
        for t in [head] + queued:
            t.join(timeout=30)
        shed = eng.stats.snapshot()["totals"]["shed"]
        assert shed["degraded"] == 1 and shed["overloaded"] == 1
    finally:
        eng.close()


def test_engine_rejects_bad_slo_config(setup):
    fc, supports, _ = setup
    bad = _slo_config(max_delay_ms=5.0, deadline_ms=5.0)  # at the floor
    with pytest.raises(ValueError, match="invalid serving config"):
        ServingEngine.from_forecaster(fc, supports, config=bad)


# -- atomic param hot-swap ---------------------------------------------


def _scaled_forecaster(fc, factor):
    params = jax.tree.map(lambda a: a * factor, fc.params)
    return params, Forecaster(
        fc.model, params, fc.normalizer, fc.config, fc.derived,
        getattr(fc, "normalizers", None),
    )


def test_swap_params_atomicity_under_concurrent_load(setup):
    """Hammer: concurrent clients predict across three live swaps; every
    response must be BIT-identical to the reference predictor of the
    generation it reports — a mixed-generation result can match neither."""
    fc, supports, ds = setup
    eng = fc.serving_engine(supports, config=LADDER)
    try:
        hist = _hist(fc, ds, 2)
        params_by_gen, expected = {0: fc.params}, {}
        expected[0] = fc.predict(supports, hist)
        for g in (1, 2, 3):
            params_by_gen[g], fcg = _scaled_forecaster(fc, 1.0 + 0.01 * g)
            expected[g] = fcg.predict(supports, hist)
        assert not np.array_equal(expected[0], expected[1])  # teeth
        mismatches, failures = [], []
        stop = threading.Event()

        def client():
            try:
                while not stop.is_set():
                    out, gen = eng.predict(hist, with_generation=True)
                    if not np.array_equal(out, expected[gen]):
                        mismatches.append(gen)
            except Exception as e:  # noqa: BLE001 — surfaced below
                failures.append(e)

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        for g in (1, 2, 3):
            time.sleep(0.05)
            assert eng.swap_params(params_by_gen[g]) == g
        time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not failures
        assert not mismatches
        assert eng.generation == 3
    finally:
        eng.close()


def test_swap_params_rejects_leaf_mismatch(setup):
    fc, supports, _ = setup
    eng = fc.serving_engine(supports, config=LADDER)
    try:
        bad = jax.tree.map(lambda a: a.astype(jnp.float16), fc.params)
        with pytest.raises(ValueError, match="swap_params"):
            eng.swap_params(bad)
        assert eng.generation == 0  # rejected swap leaves params live
    finally:
        eng.close()


def test_from_artifact_cannot_swap(setup, artifact, tmp_path):
    fc, supports, _ = setup
    with ServingEngine.from_artifact(artifact, supports, config=LADDER) as eng:
        with pytest.raises(RuntimeError, match="from_artifact"):
            eng.swap_params(fc.params)
        with pytest.raises(RuntimeError, match="cannot hot-swap"):
            eng.watch_checkpoints(str(tmp_path))


def test_checkpoint_watcher_quarantines_then_recovers(setup, tmp_path):
    """Mid-watch bit rot (injected at rest by the fault plan): the
    watcher quarantines the corrupt checkpoint and keeps serving the old
    params; the next clean write swaps in normally."""
    fc, supports, ds = setup
    plan = ServeFaultPlan(
        ServeFaultSpec(kind="corrupt-checkpoint", path_glob="latest.ckpt")
    )
    eng = fc.serving_engine(supports, config=LADDER, fault_plan=plan)
    try:
        hist = _hist(fc, ds, 2)
        ref0 = fc.predict(supports, hist)
        new_params, fc_new = _scaled_forecaster(fc, 1.001)
        ref1 = fc_new.predict(supports, hist)
        watcher = eng.watch_checkpoints(str(tmp_path))
        assert watcher.poll() is False  # nothing there yet
        ckpt = str(tmp_path / "latest.ckpt")
        save_checkpoint(ckpt, new_params, new_params, {"epoch": 1})
        assert watcher.poll() is False  # corrupted at rest -> quarantined
        assert watcher.rejected == 1 and watcher.swaps == 0
        assert os.path.exists(ckpt + ".corrupt")
        assert eng.generation == 0
        np.testing.assert_array_equal(eng.predict(hist), ref0)  # old params
        time.sleep(0.01)  # strictly newer mtime than the corrupted scan
        save_checkpoint(ckpt, new_params, new_params, {"epoch": 1})
        assert watcher.poll() is True  # one-shot fault spent: clean swap
        assert watcher.swaps == 1 and watcher.last_path == ckpt
        assert eng.generation == 1
        out, gen = eng.predict(hist, with_generation=True)
        assert gen == 1
        np.testing.assert_array_equal(out, ref1)
    finally:
        eng.close()


def test_watcher_close_during_inflight_poll_does_not_deadlock(setup, tmp_path):
    """The lifecycle contract the thread-lifecycle lint rule assumes:
    stop()/close() join the watcher thread with a *bounded* timeout, so
    a poll wedged in slow checkpoint IO cannot hang shutdown."""
    fc, supports, _ = setup
    eng = fc.serving_engine(supports, config=LADDER)
    try:
        watcher = eng.watch_checkpoints(str(tmp_path), poll_s=0.01)
        entered = threading.Event()
        release = threading.Event()

        def wedged_poll():
            entered.set()
            release.wait(timeout=30)
            return False

        watcher.poll = wedged_poll  # next loop iteration blocks in "IO"
        assert entered.wait(timeout=10)  # a poll is now in flight
        t0 = time.monotonic()
        assert watcher.stop(timeout_s=0.2) is False  # wedged, but bounded
        assert time.monotonic() - t0 < 5.0  # returned promptly, no deadlock
        release.set()  # the wedged IO finally completes
        assert watcher._thread is not None
        watcher._thread.join(timeout=10)
        assert not watcher._thread.is_alive()  # stop event ends the loop
        assert watcher.stop(timeout_s=0.2) is True  # idempotent once dead
    finally:
        eng.close()  # close hook after stop(): still clean, no hang
