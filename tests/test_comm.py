"""Communication accounting: HLO collective stats + banded-vs-GSPMD volume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from stmgcn_tpu.parallel import banded_decompose, build_mesh, sharded_banded_apply
from stmgcn_tpu.utils import collective_stats, step_comm_report


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return build_mesh(dp=1, region=8)


class TestCollectiveStats:
    def test_parses_shapes_and_ops(self):
        hlo = """
  %all-gather.3 = f32[8,256,3]{2,1,0} all-gather(%p0), replica_groups={}
  %collective-permute.1 = bf16[16,8]{1,0} collective-permute(%p1)
  %x = f32[4]{0} add(%a, %b)
"""
        stats = collective_stats(hlo)
        assert stats["all-gather"] == {"count": 1, "bytes": 8 * 256 * 3 * 4}
        assert stats["collective-permute"] == {"count": 1, "bytes": 16 * 8 * 2}
        assert stats["all-reduce"]["count"] == 0
        assert stats["total_bytes"] == 8 * 256 * 3 * 4 + 16 * 8 * 2

    def test_empty(self):
        assert collective_stats("")["total_bytes"] == 0

    def test_async_pairs_count_once_result_bytes_only(self):
        # TPU HLO splits collectives into -start/-done pairs; the start's
        # tuple is (operands..., result) possibly followed by scalar u32[]
        # context elements (the historical collective-permute-start form) —
        # wire volume is the result element only.
        hlo = """
  %ags = (f32[1,8]{1,0}, f32[4,8]{1,0}) all-gather-start(%p0)
  %agd = f32[4,8]{1,0} all-gather-done(%ags)
  %cps = (f32[2,3]{1,0}, f32[2,3]{1,0}, u32[], u32[]) collective-permute-start(%p1)
  %cpd = f32[2,3]{1,0} collective-permute-done(%cps)
"""
        stats = collective_stats(hlo)
        assert stats["all-gather"] == {"count": 1, "bytes": 4 * 8 * 4}
        assert stats["collective-permute"] == {"count": 1, "bytes": 2 * 3 * 4}


class TestBandedCommVolume:
    """The banded halo plan moves N/(2*halo)x fewer bytes than GSPMD."""

    def test_banded_beats_gspmd_allgather(self, mesh):
        rng = np.random.default_rng(0)
        N, B, F, K, w = 256, 8, 16, 3, 16
        sup = (rng.standard_normal((K, N, N)) * 0.2).astype(np.float32)
        dist = np.abs(np.subtract.outer(np.arange(N), np.arange(N)))
        sup[:, dist > w] = 0.0
        x = rng.standard_normal((B, N, F)).astype(np.float32)
        bsup = banded_decompose(sup, 8)

        x_s = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(None, "region", None)))
        sup_s = jax.device_put(
            jnp.asarray(sup), NamedSharding(mesh, P(None, "region", None))
        )
        strips_s = jax.device_put(
            bsup.strips, NamedSharding(mesh, P("region", None, None, None))
        )

        gspmd = step_comm_report(lambda s, xx: jnp.einsum("kij,bjf->kbif", s, xx),
                                 sup_s, x_s)
        banded = step_comm_report(
            lambda st, xx: sharded_banded_apply(mesh, st, xx, bsup.halo), strips_s, x_s
        )
        # GSPMD all-gathers the full node axis of the signal: B*N*F floats.
        assert gspmd["all-gather"]["count"] >= 1
        assert gspmd["all-gather"]["bytes"] >= B * N * F * 4
        # The halo plan permutes only 2*halo boundary rows, no all-gather.
        assert banded["all-gather"]["count"] == 0
        assert banded["collective-permute"]["count"] == 2
        assert banded["total_bytes"] == 2 * bsup.halo * B * F * 4
        # the headline: ~N/(2*halo) = 8x less wire volume
        assert banded["total_bytes"] * 4 < gspmd["total_bytes"]


def test_while_loop_detected_and_rejected():
    """Static counts don't multiply through loops — step_comm_report must
    refuse a loopy program unless told to accept lower bounds."""
    import jax
    from jax import lax

    from stmgcn_tpu.utils.comm import collective_stats, step_comm_report

    def loopy(x):
        return lax.while_loop(lambda v: v.sum() < 100.0, lambda v: v + 1.0, x)

    compiled = jax.jit(loopy).lower(jnp.ones((4, 4))).compile()
    stats = collective_stats(compiled.as_text())
    assert stats["while_count"] >= 1

    with pytest.raises(ValueError, match="while-loop"):
        step_comm_report(loopy, jnp.ones((4, 4)))
    assert step_comm_report(loopy, jnp.ones((4, 4)), allow_loops=True)[
        "while_count"
    ] >= 1


def test_loop_free_program_reports_zero_whiles():
    from stmgcn_tpu.utils.comm import step_comm_report

    stats = step_comm_report(lambda x: x @ x, jnp.ones((8, 8)))
    assert stats["while_count"] == 0


def test_while_loop_with_tuple_carry_detected():
    """Real loops (scan/fori with multi-array carries) print tuple result
    shapes — '%while.0 = (f32[..], f32[..]) while(' — which the detector
    must count too."""
    import jax
    from jax import lax

    from stmgcn_tpu.utils.comm import collective_stats

    def loopy(x, y):
        def body(c):
            a, b = c
            return a + 1.0, b * 0.5

        return lax.while_loop(lambda c: c[0].sum() < 100.0, body, (x, y))

    compiled = jax.jit(loopy).lower(jnp.ones((4, 4)), jnp.ones((2,))).compile()
    assert collective_stats(compiled.as_text())["while_count"] >= 1
