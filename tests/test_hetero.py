"""Heterogeneous multi-city: per-city shapes, normalizers, splits, metrics.

The reference is single-city (``Data_Container.py:8-29``); BASELINE
config 4's bar is a real city pair differing in region count, span, and
demand scale. The key parity property: the pairing machinery must not
change any single city's math — a city trained alone matches its
trajectory inside the pair (exactly, for the epoch prefix its batches
occupy; city order is deterministic and city 0 streams first).
"""

import dataclasses

import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.data import DemandDataset, HeteroCityDataset, WindowSpec, synthetic_dataset
from stmgcn_tpu.data.splits import fraction_splits
from stmgcn_tpu.experiment import build_dataset, build_trainer


def _pair_cfg(tmp_path, epochs=2):
    cfg = preset("multicity")
    cfg.data.city_rows = (4, 3)
    cfg.data.city_timesteps = (24 * 7 * 2 + 24, 24 * 7 * 2)
    cfg.mesh.dp = 1
    cfg.train.epochs = epochs
    cfg.train.out_dir = str(tmp_path)
    return cfg


def _solo_cfg(tmp_path, epochs=2):
    cfg = preset("multicity")
    cfg.data.n_cities = 1
    cfg.data.city_rows = None
    cfg.data.city_timesteps = None
    cfg.data.rows = 4
    cfg.data.n_timesteps = 24 * 7 * 2 + 24
    cfg.mesh.dp = 1
    cfg.train.epochs = epochs
    cfg.train.out_dir = str(tmp_path)
    return cfg


class TestHeteroDataset:
    def test_per_city_shapes_normalizers_splits(self, tmp_path):
        ds = build_dataset(_pair_cfg(tmp_path))
        assert ds.heterogeneous and not ds.shared_graphs
        assert ds.city_n_nodes == [16, 9]
        # per-city normalizers fitted on each city alone
        n0, n1 = ds.normalizers
        assert n0.to_dict() != n1.to_dict()
        assert ds.normalizer is None
        # per-city splits over each city's own sample count
        sizes = [c.mode_size("train") for c in ds.cities]
        assert ds.mode_size("train") == sum(sizes) and sizes[0] != sizes[1]
        x0, _ = ds.city_arrays("train", 0)
        x1, _ = ds.city_arrays("train", 1)
        assert x0.shape[2] == 16 and x1.shape[2] == 9

    def test_batches_never_mix_cities_and_tag_city(self, tmp_path):
        ds = build_dataset(_pair_cfg(tmp_path))
        seen = set()
        for b in ds.batches("train", 16, pad_last=True):
            seen.add(b.city)
            expect_n = ds.city_n_nodes[b.city]
            assert b.x.shape[2] == expect_n and b.x.shape[0] == 16
        assert seen == {0, 1}

    def test_validations(self, tmp_path):
        window = WindowSpec(3, 1, 1, 24)
        a = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 24, seed=0)
        b = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2, seed=1)
        ds = HeteroCityDataset([a, b], window)
        with pytest.raises(ValueError, match="city_arrays"):
            ds.arrays("train")
        with pytest.raises(ValueError, match="city="):
            ds.denormalize(np.zeros(3))
        with pytest.raises(ValueError, match="city_n_nodes"):
            ds.n_nodes
        with pytest.raises(ValueError, match="per city"):
            HeteroCityDataset([a, b], window, splits=[None])
        # channel-count mismatch is structural (sizes the LSTM input)
        bad = dataclasses.replace(b, demand=np.repeat(b.demand, 2, axis=-1))
        with pytest.raises(ValueError, match="channel count"):
            HeteroCityDataset([a, bad], window)

    def test_shared_graphs_rejects_differing_region_counts(self, tmp_path):
        cfg = _pair_cfg(tmp_path)  # city_rows (4, 3): N=16 vs N=9
        cfg.data.shared_graphs = True
        with pytest.raises(ValueError, match="region count"):
            build_dataset(cfg)

    def test_shared_graphs_allows_same_n_different_span(self, tmp_path):
        """Equal region counts with differing series lengths may share a
        graph stack (N matches; the hetero pipeline handles per-city T)."""
        cfg = _pair_cfg(tmp_path)
        cfg.data.city_rows = (4, 4)
        cfg.data.city_timesteps = (504, 360)
        cfg.data.shared_graphs = True
        ds = build_dataset(cfg)
        assert ds.heterogeneous and ds.city_n_nodes == [16, 16]

    def test_same_shape_cities_opt_into_hetero(self, tmp_path):
        cfg = _pair_cfg(tmp_path)
        cfg.data.city_rows = None
        cfg.data.city_timesteps = None
        cfg.data.rows = 3
        cfg.data.n_timesteps = 24 * 7 * 2 + 24
        assert not getattr(build_dataset(cfg), "heterogeneous", False)
        cfg.data.hetero = True  # forces per-city normalizers on twins
        ds = build_dataset(cfg)
        assert ds.heterogeneous
        assert ds.normalizers[0].to_dict() != ds.normalizers[1].to_dict()


class TestHeteroParity:
    def test_single_city_hetero_matches_homogeneous_trajectory(self, tmp_path):
        """The hetero container with one city IS the single-city pipeline."""
        data = synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 24, seed=0)
        window = WindowSpec(3, 1, 1, 24)
        split = fraction_splits(window.n_samples(data.demand.shape[0]))
        homo = DemandDataset(data, window, split)
        het = HeteroCityDataset([data], window, [split])
        assert het.mode_size("train") == homo.mode_size("train")
        hb = list(het.batches("train", 16, pad_last=True))
        mb = list(homo.batches("train", 16, pad_last=True))
        assert len(hb) == len(mb)
        for h, m in zip(hb, mb):
            np.testing.assert_array_equal(h.x, m.x)
            np.testing.assert_array_equal(h.y, m.y)
            assert h.n_real == m.n_real
        np.testing.assert_array_equal(
            het.denormalize(hb[0].y, city=0), homo.denormalize(mb[0].y)
        )

    @pytest.mark.slow
    def test_city0_trains_identically_alone_and_inside_pair(self, tmp_path):
        """City 0's training prefix inside the pair == the city alone.

        Cities stream in order, so the first epoch's city-0 batches (and
        the parameter updates they produce) must be bit-compatible with a
        single-city run: same data (same synthetic seed), same init (all
        parameters are region-count-agnostic), same steps.
        """
        import jax

        solo = build_trainer(_solo_cfg(tmp_path / "solo"), verbose=False)
        pair = build_trainer(_pair_cfg(tmp_path / "pair"), verbose=False)

        # identical initial parameters: same seed, N-agnostic shapes
        jax.tree.map(np.testing.assert_array_equal, solo.params, pair.params)

        def city0_losses(tr, n_steps=3):
            params, opt = tr.params, tr.opt_state
            losses = []
            for batch, (x, y, mask) in tr._placed_batches("train"):
                if batch.city != 0 or len(losses) >= n_steps:
                    break
                params, opt, loss = tr.step_fns.train_step(
                    params, opt, tr._supports_for(batch), x, y, mask
                )
                losses.append(float(loss))
            return losses, params

        solo_losses, solo_params = city0_losses(solo)
        pair_losses, pair_params = city0_losses(pair)
        assert len(solo_losses) == 3
        np.testing.assert_allclose(solo_losses, pair_losses, rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
            solo_params,
            pair_params,
        )


class TestHeteroTraining:
    @pytest.mark.slow
    def test_pair_trains_with_per_city_metrics(self, tmp_path):
        tr = build_trainer(_pair_cfg(tmp_path), verbose=False)
        hist = tr.train()
        assert np.isfinite(hist["train"]).all()
        res = tr.test(modes=("test",))
        per_city = res["test"]["per_city"]
        assert set(per_city) == {"city0", "city1"}
        for rep in per_city.values():
            assert np.isfinite(rep["rmse"]) and np.isfinite(rep["pcc"])
        # checkpoint meta carries one normalizer per city
        meta = tr._meta()
        assert len(meta["normalizers"]) == 2
        assert meta["normalizers"][0] != meta["normalizers"][1]
        assert meta["derived"]["n_nodes"] == [16, 9]

    @pytest.mark.slow
    def test_hetero_branch_mesh_trains(self, tmp_path):
        """Hetero cities x branch model parallelism: the M vmapped
        branches shard over the branch axis while each city keeps its own
        shapes/supports (dense GSPMD; no node padding needed)."""
        import jax

        if len(jax.devices()) < 6:
            pytest.skip("needs 6 virtual devices")
        cfg = _pair_cfg(tmp_path, epochs=1)
        cfg.mesh.dp, cfg.mesh.branch = 2, 3
        tr = build_trainer(cfg, verbose=False)
        hist = tr.train()
        assert np.isfinite(hist["train"][0])
        res = tr.test(modes=("test",))["test"]
        assert np.isfinite(res["rmse"])
        assert set(res["per_city"]) == {"city0", "city1"}

    def test_hetero_rejects_scalar_node_pad(self, tmp_path):
        from stmgcn_tpu.train import Trainer

        ds = build_dataset(_pair_cfg(tmp_path))
        with pytest.raises(ValueError, match="node_pad"):
            Trainer(None, ds, None, node_pad=2, out_dir=str(tmp_path))

    @pytest.mark.slow
    def test_hetero_region_mesh_matches_single_device(self, tmp_path):
        """Hetero x region sharding composes via per-city node padding:
        city shapes (16, 9) on a region=2 mesh pad independently
        (16 -> 16, 9 -> 10) and the loss trajectory matches an unsharded
        run exactly — padded rows are masked out of loss AND gate pooling
        (per-city n_real_nodes step functions)."""
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 virtual devices")
        cfg = _pair_cfg(tmp_path / "mesh", epochs=2)
        cfg.mesh.dp, cfg.mesh.region = 1, 2
        mesh_tr = build_trainer(cfg, verbose=False)
        assert mesh_tr._node_pads == (0, 1)  # 16 % 2 == 0; 9 -> 10
        assert mesh_tr._city_n_real == (None, 9)
        mesh_hist = mesh_tr.train()

        single = _pair_cfg(tmp_path / "single", epochs=2)
        single_tr = build_trainer(single, verbose=False)
        single_hist = single_tr.train()
        np.testing.assert_allclose(
            mesh_hist["train"], single_hist["train"], rtol=1e-5
        )
        np.testing.assert_allclose(
            mesh_hist["validate"], single_hist["validate"], rtol=1e-5
        )
        res = mesh_tr.test(modes=("test",))["test"]
        ref = single_tr.test(modes=("test",))["test"]
        for k in ("rmse", "mae", "pcc"):
            np.testing.assert_allclose(res[k], ref[k], rtol=1e-4)
            np.testing.assert_allclose(
                [res["per_city"][c][k] for c in sorted(res["per_city"])],
                [ref["per_city"][c][k] for c in sorted(ref["per_city"])],
                rtol=1e-4,
            )


class TestHeteroServing:
    @pytest.mark.slow
    def test_forecaster_serves_each_city_from_hetero_checkpoint(self, tmp_path):
        """A hetero-trained checkpoint serves both cities: per-city
        normalizer + region count selected with predict(city=...)."""
        from stmgcn_tpu.inference import Forecaster
        from stmgcn_tpu.experiment import build_supports

        cfg = _pair_cfg(tmp_path, epochs=1)
        tr = build_trainer(cfg, verbose=False)
        tr.train()
        fc = Forecaster.from_checkpoint(tr.best_path)
        assert fc.normalizers is not None and len(fc.normalizers) == 2

        ds = build_dataset(cfg)
        sup = build_supports(cfg, ds)
        for city, n in enumerate(ds.city_n_nodes):
            hist = np.random.default_rng(city).uniform(
                0, 40, (2, fc.seq_len, n, ds.n_feats)
            ).astype(np.float32)
            out = fc.predict(np.asarray(sup.for_city(city)), hist, city=city)
            assert out.shape == (2, n, ds.n_feats) and np.isfinite(out).all()
        # omitting city= on a multi-normalizer checkpoint is an error, not
        # a silent city-0 default (hetero twins can share N, so no shape
        # check could catch the wrong normalizer)
        with pytest.raises(ValueError, match="pass city="):
            fc.predict(
                np.asarray(sup.for_city(0)),
                np.zeros((2, fc.seq_len, ds.city_n_nodes[0], ds.n_feats), np.float32),
            )
        # wrong city => shape validation catches the mismatch
        with pytest.raises(ValueError):
            fc.predict(
                np.asarray(sup.for_city(0)),
                np.zeros((2, fc.seq_len, ds.city_n_nodes[0], ds.n_feats), np.float32),
                city=1,
            )

    @pytest.mark.slow
    def test_hetero_export_per_city(self, tmp_path):
        """export_forecaster bakes one city per artifact; city= required."""
        from stmgcn_tpu.experiment import build_supports
        from stmgcn_tpu.export import ExportedForecaster, export_forecaster
        from stmgcn_tpu.inference import Forecaster

        cfg = _pair_cfg(tmp_path, epochs=1)
        tr = build_trainer(cfg, verbose=False)
        tr.train()
        fc = Forecaster.from_checkpoint(tr.best_path)
        with pytest.raises(ValueError, match="pass city="):
            export_forecaster(fc, str(tmp_path / "x.stmgx"), platforms=("cpu",))

        ds = build_dataset(cfg)
        sup = build_supports(cfg, ds)
        for c, n in enumerate(ds.city_n_nodes):
            path = str(tmp_path / f"model.city{c}.stmgx")
            export_forecaster(fc, path, platforms=("cpu",), city=c)
            loaded = ExportedForecaster.load(path)
            hist = np.random.default_rng(c).uniform(
                0, 40, (2, fc.seq_len, n, ds.n_feats)
            ).astype(np.float32)
            np.testing.assert_allclose(
                loaded.predict(np.asarray(sup.for_city(c)), hist),
                fc.predict(np.asarray(sup.for_city(c)), hist, city=c),
                rtol=1e-5,
                atol=1e-4,
            )
