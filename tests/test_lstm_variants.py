"""LSTM scan-scheduling variants are numerically identical to the default.

``unroll`` and ``fused_scan`` are pure XLA scheduling levers (the bench
compares their step time on hardware); here the contract is equality with
the layered scan on the SAME parameters, including gradients and remat.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.ops.lstm import StackedLSTM


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.normal(size=(16, 12, 3)).astype(np.float32))


def _out(model, params, x):
    outputs, finals = model.apply(params, x)
    return outputs, finals


@pytest.mark.parametrize("variant", [
    dict(unroll=3), dict(unroll=12), dict(fused_scan=True),
    dict(fused_scan=True, unroll=4), dict(fused_scan=True, remat=True),
    dict(unroll=0), dict(fused_scan=True, unroll=0),  # 0 = full unroll
    # the TPU-default packed K=2H contraction, forced on so the CPU
    # suite executes it (off-TPU it would otherwise be dead code)
    dict(fused_scan=True, fused_pack=True),
    dict(fused_scan=True, fused_pack=True, unroll=0, remat=True),
])
def test_variant_matches_default(data, variant):
    base = StackedLSTM(hidden_dim=8, num_layers=3)
    params = base.init(jax.random.key(0), data)
    want_out, want_fin = _out(base, params, data)

    alt = StackedLSTM(hidden_dim=8, num_layers=3, **variant)
    got_out, got_fin = _out(alt, params, data)  # identical param tree
    np.testing.assert_allclose(np.asarray(got_out), np.asarray(want_out),
                               rtol=1e-5, atol=1e-6)
    for (gh, gc), (wh, wc) in zip(got_fin, want_fin):
        np.testing.assert_allclose(np.asarray(gh), np.asarray(wh), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gc), np.asarray(wc), rtol=1e-5, atol=1e-6)


def test_fused_gradients_match_default(data):
    base = StackedLSTM(hidden_dim=8, num_layers=3)
    fused = StackedLSTM(hidden_dim=8, num_layers=3, fused_scan=True)
    params = base.init(jax.random.key(1), data)

    def loss(model, p):
        out, _ = model.apply(p, data)
        return jnp.mean(out[:, -1, :] ** 2)

    g_base = jax.grad(lambda p: loss(base, p))(params)
    g_fused = jax.grad(lambda p: loss(fused, p))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        ),
        g_fused, g_base,
    )


def test_fused_respects_initial_states(data):
    rng = np.random.default_rng(2)
    states = [
        (jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32)),
         jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32)))
        for _ in range(2)
    ]
    base = StackedLSTM(hidden_dim=8, num_layers=2)
    fused = StackedLSTM(hidden_dim=8, num_layers=2, fused_scan=True)
    params = base.init(jax.random.key(3), data)
    want, _ = base.apply(params, data, initial_states=states)
    got, _ = fused.apply(params, data, initial_states=states)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


def test_flagship_with_fused_lstm_matches(data):
    from stmgcn_tpu.models import STMGCN

    rng = np.random.default_rng(4)
    sup = jnp.asarray((rng.normal(size=(2, 3, 16, 16)) * 0.2).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(4, 5, 16, 1)).astype(np.float32))
    kw = dict(m_graphs=2, n_supports=3, seq_len=5, input_dim=1,
              lstm_hidden_dim=8, lstm_num_layers=2, gcn_hidden_dim=8)
    base = STMGCN(**kw)
    fast = STMGCN(**kw, lstm_fused_scan=True, lstm_unroll=5)
    params = base.init(jax.random.key(0), sup, x)
    np.testing.assert_allclose(
        np.asarray(fast.apply(params, sup, x)),
        np.asarray(base.apply(params, sup, x)),
        rtol=1e-5, atol=1e-6,
    )


def test_pallas_backend_rejects_scan_schedule_knobs():
    """fused_scan/unroll schedule the XLA scan; combining them with the
    pallas kernel must raise, not silently measure something else."""
    x = jnp.zeros((2, 4, 8), jnp.float32)
    for kwargs in ({"fused_scan": True}, {"unroll": 0}, {"unroll": 4}):
        m = StackedLSTM(hidden_dim=8, num_layers=1, backend="pallas", **kwargs)
        with pytest.raises(ValueError, match="schedule knobs"):
            m.init(jax.random.key(0), x)
