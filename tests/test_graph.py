"""Unit tests for graph-support construction (SURVEY.md §4: C7 closed-form checks)."""

import numpy as np
import pytest

from stmgcn_tpu.ops import graph


def path3():
    # 0 - 1 - 2 path graph, degrees [1, 2, 1]
    return np.array([[0, 1, 0], [1, 0, 1], [0, 1, 0]], dtype=np.float64)


class TestNormalizations:
    def test_symmetric_normalize_path3_closed_form(self):
        got = graph.symmetric_normalize(path3())
        s = 1.0 / np.sqrt(2.0)
        want = np.array([[0, s, 0], [s, 0, s], [0, s, 0]])
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_symmetric_normalize_isolated_node_is_finite(self):
        a = np.zeros((3, 3))
        a[0, 1] = a[1, 0] = 1.0  # node 2 isolated
        got = graph.symmetric_normalize(a)
        assert np.isfinite(got).all()
        assert (got[2] == 0).all() and (got[:, 2] == 0).all()

    def test_random_walk_rows_sum_to_one(self):
        rng = np.random.default_rng(0)
        a = rng.random((6, 6))
        np.fill_diagonal(a, 0)
        got = graph.random_walk_normalize(a)
        np.testing.assert_allclose(got.sum(axis=1), np.ones(6), atol=1e-12)

    def test_laplacian_psd_spectrum(self):
        lap = graph.normalized_laplacian(path3())
        eig = np.linalg.eigvalsh(lap)
        assert eig.min() >= -1e-10
        assert eig.max() <= 2.0 + 1e-10


class TestEigenRescale:
    def test_rescaled_spectrum_in_unit_interval(self):
        rng = np.random.default_rng(1)
        a = rng.random((12, 12))
        a = (a + a.T) / 2
        np.fill_diagonal(a, 0)
        lap = graph.normalized_laplacian(a)
        eig = np.linalg.eigvalsh(graph.rescale_laplacian(lap))
        assert eig.max() <= 1.0 + 1e-8
        assert eig.min() >= -1.0 - 1e-8

    def test_power_iteration_matches_dense(self):
        rng = np.random.default_rng(2)
        a = rng.random((40, 40))
        a = (a + a.T) / 2
        np.fill_diagonal(a, 0)
        lap = graph.normalized_laplacian(a)
        dense = graph.max_eigenvalue(lap, method="dense")
        power = graph.max_eigenvalue(lap, method="power")
        np.testing.assert_allclose(power, dense, rtol=1e-5)

    def test_fallback_lambda_max(self, monkeypatch):
        # Reference semantics: non-convergent eig -> lambda_max = 2 (GCN.py:119-121)
        def boom(*a, **k):
            raise np.linalg.LinAlgError("no convergence")

        monkeypatch.setattr(np.linalg, "eigvalsh", boom)
        monkeypatch.setattr(np.linalg, "eigvals", boom)
        lam = graph.max_eigenvalue(graph.normalized_laplacian(path3()), method="dense")
        assert lam == 2.0


class TestChebyshev:
    def test_polynomial_recursion(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((5, 5))
        t = graph.chebyshev_polynomials(x, K=3)
        assert t.shape == (4, 5, 5)
        np.testing.assert_allclose(t[0], np.eye(5))
        np.testing.assert_allclose(t[1], x)
        np.testing.assert_allclose(t[2], 2 * x @ t[1] - t[0])
        np.testing.assert_allclose(t[3], 2 * x @ t[2] - t[1])

    def test_chebyshev_supports_shape_and_t0(self):
        sup = graph.chebyshev_supports(path3(), K=2)
        assert sup.shape == (3, 3, 3)
        np.testing.assert_allclose(sup[0], np.eye(3))

    def test_scalar_chebyshev_identity(self):
        # On a 1x1 "graph" the supports are literal Chebyshev values T_k(x).
        x = np.array([[0.3]])
        t = graph.chebyshev_polynomials(x, K=4)
        vals = t[:, 0, 0]
        want = [1.0, 0.3, 2 * 0.3 ** 2 - 1, np.cos(3 * np.arccos(0.3)), np.cos(4 * np.arccos(0.3))]
        np.testing.assert_allclose(vals, want, atol=1e-12)


class TestKernelFamilies:
    def test_localpool_is_identity_plus_norm(self):
        sup = graph.localpool_supports(path3())
        np.testing.assert_allclose(sup[0], np.eye(3) + graph.symmetric_normalize(path3()))

    def test_diffusion_counts(self):
        a = path3()
        assert graph.diffusion_supports(a, K=2, bidirectional=False).shape[0] == 3
        assert graph.diffusion_supports(a, K=2, bidirectional=True).shape[0] == 5

    def test_diffusion_symmetric_graph_fwd_bwd_agree(self):
        a = path3()
        sup = graph.diffusion_supports(a, K=2, bidirectional=True)
        np.testing.assert_allclose(sup[1], sup[3], atol=1e-12)
        np.testing.assert_allclose(sup[2], sup[4], atol=1e-12)

    def test_support_count_table(self):
        # Mirrors reference ST_MGCN.get_support_K (STMGCN.py:80-91)
        assert graph.support_count("chebyshev", 2) == 3
        assert graph.support_count("localpool", 1) == 1
        assert graph.support_count("random_walk_diffusion", 2) == 5
        assert graph.support_count("random_walk_diffusion", 2, bidirectional=False) == 3
        with pytest.raises(ValueError):
            graph.support_count("localpool", 2)
        with pytest.raises(ValueError):
            graph.support_count("nope", 1)


class TestSupportConfig:
    def test_build_all_stacks_m_graphs(self):
        cfg = graph.SupportConfig("chebyshev", K=2)
        assert cfg.n_supports == 3
        rng = np.random.default_rng(4)
        adjs = []
        for _ in range(3):
            a = rng.random((7, 7))
            a = (a + a.T) / 2
            np.fill_diagonal(a, 0)
            adjs.append(a)
        stacked = cfg.build_all(adjs)
        assert stacked.shape == (3, 3, 7, 7)
        assert stacked.dtype == np.float32

    def test_invalid_kernel_type_raises(self):
        with pytest.raises(ValueError):
            graph.SupportConfig("invalid")
