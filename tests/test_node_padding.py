"""Node-axis padding for region meshes that do not divide N.

BASELINE config 3 is a 50x50 grid (N=2500) sharded over region=8 — 2500 %
8 != 0, so the node axis carries zero-padded isolated rows. The contract:
the padded model is numerically identical to the unpadded one at real
nodes (supports built at true N then zero-padded — padding the adjacency
would change the Laplacian spectrum; gate pooling excludes padded rows;
the (B, N) loss mask excludes them from optimization and metrics).
"""

import dataclasses

import jax
import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.experiment import (
    build_dataset,
    build_model,
    build_supports,
    build_trainer,
    node_pad_target,
    route_supports,
)
from stmgcn_tpu.train import Trainer


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()[:8]


def _cfg(rows=5, region=8, strategy="auto", sparse=False):
    cfg = preset("scaled")
    cfg.data.rows = rows
    cfg.data.n_timesteps = 24 * 7 * 2 + 48
    cfg.model.dtype = "float32"
    cfg.model.K = 2
    cfg.model.sparse = sparse
    cfg.train.epochs = 2
    cfg.train.batch_size = 16
    cfg.mesh.dp, cfg.mesh.region = 1, region
    cfg.mesh.region_strategy = strategy
    return cfg


class TestPadTarget:
    def test_target_math(self):
        cfg = _cfg()
        assert node_pad_target(cfg, 25) == 32
        assert node_pad_target(cfg, 2500) == 2504
        assert node_pad_target(cfg, 32) is None  # divisible
        cfg.mesh.dp = cfg.mesh.region = 1
        assert node_pad_target(cfg, 25) is None  # no mesh

    def test_supports_padded_rows_are_zero(self):
        cfg = _cfg()
        ds = build_dataset(cfg)  # N=25 -> padded 32
        sup, modes = route_supports(cfg, ds)
        # routed per-branch entries: dense arrays padded; banded strips
        # decompose from the padded stack
        for m, entry in enumerate(sup):
            if modes[m] == "dense":
                assert entry.shape[-1] == 32
                assert np.all(np.asarray(entry)[:, 25:, :] == 0)
                assert np.all(np.asarray(entry)[:, :, 25:] == 0)

    def test_supports_real_rows_unchanged_by_padding(self):
        # padding must NOT alter supports at real nodes (spectrum preserved:
        # supports are built at true N, then zero-padded)
        cfg = _cfg(strategy="gspmd")
        ds = build_dataset(cfg)
        padded = build_supports(cfg, ds)
        cfg1 = _cfg(strategy="gspmd")
        cfg1.mesh.dp = cfg1.mesh.region = 1
        unpadded = build_supports(cfg1, build_dataset(cfg1))
        np.testing.assert_array_equal(np.asarray(padded)[..., :25, :25],
                                      np.asarray(unpadded))


class TestPaddedTrainingParity:
    @pytest.mark.slow
    def test_padded_mesh_matches_unpadded_single_device(self, eight_devices, tmp_path):
        """The headline contract: identical loss trajectory (and the scaled
        preset's literal region=8 config becomes trainable at any N)."""
        cfg = _cfg()
        cfg.train.out_dir = str(tmp_path / "mesh")
        trainer = build_trainer(cfg, verbose=False)
        assert trainer.node_pad == 7  # 25 -> 32
        hist = trainer.train()

        cfg1 = _cfg(strategy="gspmd")
        cfg1.mesh.dp = cfg1.mesh.region = 1
        ds = build_dataset(cfg1)
        model = dataclasses.replace(
            build_model(cfg1, ds.n_feats), vmap_branches=False
        )  # same loop param layout/init stream as the strategy-active run
        single = Trainer(
            model, ds, build_supports(cfg1, ds),
            lr=cfg1.train.lr, weight_decay=cfg1.train.weight_decay,
            n_epochs=2, batch_size=16, out_dir=str(tmp_path / "single"),
            verbose=False,
        )
        hist1 = single.train()
        np.testing.assert_allclose(hist["validate"], hist1["validate"], rtol=2e-5)
        np.testing.assert_allclose(hist["train"], hist1["train"], rtol=2e-5)

        # denormalized metrics at true N match too: padded node rows were
        # trimmed from the predictions before scoring
        res = trainer.test(modes=("test",))
        res1 = single.test(modes=("test",))
        for metric in ("mse", "rmse", "mae", "mape", "pcc"):
            np.testing.assert_allclose(
                res["test"][metric], res1["test"][metric], rtol=1e-4
            )

    @pytest.mark.slow
    def test_padded_sparse_mesh_trains(self, eight_devices, tmp_path):
        cfg = _cfg(sparse=True, strategy="gspmd")
        cfg.train.out_dir = str(tmp_path)
        trainer = build_trainer(cfg, verbose=False)
        assert trainer.node_pad == 7
        hist = trainer.train()
        assert np.isfinite(hist["train"]).all()
